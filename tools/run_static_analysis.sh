#!/bin/sh
# Single entry point for the mxlint static-analysis suite (ISSUE 4/7/8):
#   1. the seven analyzers (C-ABI / JAX hazards / native concurrency /
#      Python concurrency / compiled-program graphs / serving wire
#      protocol / asyncio event-loop hazards, plus the envlint
#      env-var doc-drift rider) — fails on any NEW
#      violation vs baseline/pragmas.  DEFAULT SCOPE: --changed-only
#      (files changed vs the merge-base + working tree; graphlint
#      re-traces only programs whose recorded trace closure changed),
#      so iteration costs seconds; pass --all for the full tier-1
#      sweep (what tests/test_static_analysis.py always runs).  Other
#      flags pass through (section headers go to stderr so the
#      analyzer's stdout stays clean) — but for pure-JSON CI output
#      call `python -m tools.analysis --format json` directly: this
#      wrapper also runs the sanitizer smoke, whose pytest output
#      follows on stdout.
#   2. sanitizer smoke, delegated to tests/test_native_sanitize.py so
#      the sanitizer matrix (flags, env, binaries, toolchain probe,
#      skip reasons) lives in exactly one place — the test module
#      skips with a visible reason when the toolchain lacks make, a
#      C++ compiler, or sanitizer support.
# Wired into tools/run_slow_tier.sh (with --all); tier-1 coverage
# lives in tests/test_static_analysis.py.
set -e
cd "$(dirname "$0")/.."

# pull --all out of the positional params, keeping the rest intact as
# "$@" so pass-through args survive word splitting (paths with spaces)
SCOPE="--changed-only"
n=$#
i=0
while [ "$i" -lt "$n" ]; do
    arg=$1
    shift
    if [ "$arg" = "--all" ]; then
        SCOPE="--all"
    else
        set -- "$@" "$arg"
    fi
    i=$((i + 1))
done

if [ "$SCOPE" = "--changed-only" ]; then
    # the sharding-readiness audit (docs/sharding_readiness.md) is a
    # rendered view of the engine's declared shardings vs the megatron
    # rules — regenerate it whenever serving/ or models/ changed so
    # the tier-1 pin (test_sharding_audit_checked_in_and_current)
    # never trips on a stale table during iteration.  Full runs and
    # CI leave the committed file authoritative.
    # tools/analysis/ is included: the table's rendering/derivation
    # lives in graphlint.py, so an audit-code edit also stales it
    CHANGED_ALL=$( (git diff --name-only HEAD; \
                git ls-files -o --exclude-standard) 2>/dev/null \
               || true)
    # mxnet_tpu/parallel and mxnet_tpu/kvstore joined in round 19: the
    # train half of the audit derives from the FSDP rule table
    # (parallel/fsdp.py), the ZeRO composition (parallel/mesh.py), and
    # the ICI-allreduce KVStore rides the same train paths
    CHANGED=$(printf '%s\n' "$CHANGED_ALL" \
               | grep -E '^(mxnet_tpu/(serving|models|parallel|kvstore)|tools/analysis)/' \
               || true)
    if [ -n "$CHANGED" ]; then
        echo "== regenerating docs/sharding_readiness.md (serving/" \
             "or models/ changed) ==" >&2
        python -m tools.analysis --write-sharding-audit >&2
    fi
    # the wire-protocol audit (docs/protocol.md) is protolint's
    # rendered model of serving/'s send sites + dispatch arms — same
    # staleness story, different trigger set (serving/, the
    # parallel/dist.py wire, or the analyzer itself)
    CHANGED_PROTO=$(printf '%s\n' "$CHANGED_ALL" \
               | grep -E '^(mxnet_tpu/serving/|mxnet_tpu/parallel/dist\.py|tools/analysis/)' \
               || true)
    if [ -n "$CHANGED_PROTO" ]; then
        echo "== regenerating docs/protocol.md (serving/," \
             "parallel/dist.py, or tools/analysis/ changed) ==" >&2
        python -m tools.analysis --write-protocol-audit >&2
    fi
fi

echo "== mxlint analyzers ($SCOPE) ==" >&2
python -m tools.analysis --baseline tools/analysis/baseline.json \
    $SCOPE "$@"

echo "== sanitizer smoke (tests/test_native_sanitize.py) ==" >&2
python -m pytest tests/test_native_sanitize.py -q -p no:cacheprovider \
    -k "test_all_combined" -rs
echo "== static analysis: OK ==" >&2
