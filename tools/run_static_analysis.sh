#!/bin/sh
# Single entry point for the mxlint static-analysis suite (ISSUE 4/7):
#   1. the four analyzers (C-ABI / JAX hazards / native concurrency /
#      Python concurrency) — pure parsing, fails on any NEW violation
#      vs baseline/pragmas.  DEFAULT SCOPE: --changed-only (files
#      changed vs the merge-base + working tree), so iteration costs
#      seconds; pass --all for the full tier-1 sweep (what
#      tests/test_static_analysis.py always runs).
#   2. sanitizer smoke, delegated to tests/test_native_sanitize.py so
#      the sanitizer matrix (flags, env, binaries, toolchain probe,
#      skip reasons) lives in exactly one place — the test module
#      skips with a visible reason when the toolchain lacks make, a
#      C++ compiler, or sanitizer support.
# Wired into tools/run_slow_tier.sh (with --all); tier-1 coverage
# lives in tests/test_static_analysis.py.
set -e
cd "$(dirname "$0")/.."

SCOPE="--changed-only"
for arg in "$@"; do
    [ "$arg" = "--all" ] && SCOPE="--all"
done

echo "== mxlint analyzers ($SCOPE) =="
python -m tools.analysis --baseline tools/analysis/baseline.json $SCOPE

echo "== sanitizer smoke (tests/test_native_sanitize.py) =="
python -m pytest tests/test_native_sanitize.py -q -p no:cacheprovider \
    -k "test_all_combined" -rs
echo "== static analysis: OK =="
