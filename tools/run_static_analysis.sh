#!/bin/sh
# Single entry point for the mxlint static-analysis suite (ISSUE 4):
#   1. the three analyzers (C-ABI / JAX hazards / native concurrency)
#      — pure parsing, fails on any NEW violation vs baseline/pragmas;
#   2. sanitizer smoke, delegated to tests/test_native_sanitize.py so
#      the sanitizer matrix (flags, env, binaries, toolchain probe,
#      skip reasons) lives in exactly one place — the test module
#      skips with a visible reason when the toolchain lacks make, a
#      C++ compiler, or sanitizer support.
# Wired into tools/run_slow_tier.sh; tier-1 coverage lives in
# tests/test_static_analysis.py.
set -e
cd "$(dirname "$0")/.."

echo "== mxlint analyzers =="
python -m tools.analysis --baseline tools/analysis/baseline.json

echo "== sanitizer smoke (tests/test_native_sanitize.py) =="
python -m pytest tests/test_native_sanitize.py -q -p no:cacheprovider \
    -k "test_all_combined" -rs
echo "== static analysis: OK =="
