#!/bin/sh
# Run the slow tier in bounded chunks (each <5 min on a 1-vCPU
# host) so the whole tier is verifiable inside standard command
# timeouts.  Usage: tools/run_slow_tier.sh [extra pytest args]
set -e
cd "$(dirname "$0")/.."
sh tools/run_static_analysis.sh --all
for g in a b c d e f g h i j k l m n o; do
    echo "== slow group $g =="
    python -m pytest tests/ -q -m "slow_$g" -p no:cacheprovider "$@"
done
