#!/usr/bin/env python
"""im2rec: pack an image folder (+ optional .lst) into RecordIO shards.

Reference: ``tools/im2rec.py`` (SURVEY.md §2.3 "im2rec" row: folder +
``.lst`` → sharded ``.rec``/``.idx`` packing CLI).  Two modes, like the
reference:

* ``--list``: walk ``root``, assign integer class ids per subfolder,
  write ``prefix.lst`` (``idx \\t label... \\t relpath``) with optional
  train/test split and shuffling;
* pack (default): read ``prefix*.lst``, encode/resize each image, write
  ``prefix.rec`` + ``prefix.idx`` (``--num-thread`` workers,
  ``--pack-label`` for multi-float detection labels).

Usage::

    python tools/im2rec.py --list --recursive data/imagenet train/
    python tools/im2rec.py --resize 480 --quality 95 data/imagenet train/
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_EXTS = {".jpg", ".jpeg", ".png", ".bmp"}


def list_images(root, recursive):
    """Yield (relpath, class_id) walking ``root`` (reference:
    ``list_image``): class id = sorted-subfolder index when recursive,
    else 0."""
    if recursive:
        cat = {}
        for path, dirs, files in sorted(os.walk(root, followlinks=True)):
            dirs.sort()
            files.sort()
            for f in files:
                if os.path.splitext(f)[1].lower() in _EXTS:
                    label_dir = os.path.relpath(path, root).split(
                        os.sep)[0]
                    if label_dir not in cat:
                        cat[label_dir] = len(cat)
                    yield (os.path.relpath(os.path.join(path, f), root),
                           cat[label_dir])
    else:
        for f in sorted(os.listdir(root)):
            if os.path.splitext(f)[1].lower() in _EXTS:
                yield f, 0


def write_list(prefix, root, args):
    entries = list(list_images(root, args.recursive))
    if args.shuffle:
        random.seed(100)
        random.shuffle(entries)
    n = len(entries)
    n_test = int(n * args.test_ratio)
    n_train = int(n * args.train_ratio)
    chunks = {"": entries}
    if args.test_ratio > 0 or args.train_ratio < 1:
        chunks = {"_train": entries[:n_train],
                  "_test": entries[n_train:n_train + n_test]}
        if n_train + n_test < n:
            chunks["_val"] = entries[n_train + n_test:]
    for suffix, chunk in chunks.items():
        if not chunk:
            continue
        fname = prefix + suffix + ".lst"
        with open(fname, "w") as f:
            for i, (rel, label) in enumerate(chunk):
                f.write("%d\t%f\t%s\n" % (i, label, rel))
        print("wrote %s (%d entries)" % (fname, len(chunk)))


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            yield int(float(parts[0])), [float(x) for x in parts[1:-1]], \
                parts[-1]


def _encode(fullpath, args):
    """Load, optionally resize/re-encode; pass bytes through untouched
    when no transform is requested (fast path, like the reference's
    pass-through mode)."""
    with open(fullpath, "rb") as f:
        raw = f.read()
    if args.resize == 0 and args.center_crop == 0:
        return raw
    import io as _io
    from PIL import Image
    img = Image.open(_io.BytesIO(raw)).convert("RGB")
    if args.resize:
        w, h = img.size
        scale = args.resize / min(w, h)
        img = img.resize((max(1, int(w * scale)),
                          max(1, int(h * scale))), Image.BILINEAR)
    if args.center_crop:
        w, h = img.size
        s = min(w, h)
        img = img.crop(((w - s) // 2, (h - s) // 2,
                        (w + s) // 2, (h + s) // 2))
    buf = _io.BytesIO()
    img.save(buf, format="JPEG", quality=args.quality)
    return buf.getvalue()


def pack(prefix, root, args):
    from mxnet_tpu import recordio

    # search .lst files in the directory the prefix (or --working-dir)
    # points into, matching the prefix basename
    base_dir = args.working_dir or os.path.dirname(prefix) or "."
    base_name = os.path.basename(prefix)
    lsts = [f for f in sorted(os.listdir(base_dir))
            if f.startswith(base_name) and f.endswith(".lst")]
    if not lsts and os.path.exists(prefix + ".lst"):
        # --list writes next to the prefix; honor that location even
        # when --working-dir points elsewhere
        base_dir = os.path.dirname(prefix) or "."
        lsts = [base_name + ".lst"]
    if not lsts:
        print("no .lst found for prefix %r in %s; run --list first"
              % (prefix, base_dir))
        return 1
    for lst in lsts:
        out_base = os.path.join(base_dir, os.path.splitext(lst)[0])
        rec = recordio.MXIndexedRecordIO(out_base + ".idx",
                                         out_base + ".rec", "w")
        count = 0
        for idx, labels, rel in read_list(os.path.join(base_dir, lst)):
            fullpath = os.path.join(root, rel)
            try:
                data = _encode(fullpath, args)
            except Exception as e:
                print("skipping %s: %s" % (rel, e))
                continue
            if args.pack_label and len(labels) > 1:
                header = recordio.IRHeader(0, labels, idx, 0)
            else:
                header = recordio.IRHeader(0, labels[0] if labels else 0.0,
                                           idx, 0)
            rec.write_idx(idx, recordio.pack(header, data))
            count += 1
            if count % 1000 == 0:
                print("%s: %d packed" % (lst, count))
        rec.close()
        print("wrote %s.rec / .idx (%d records)" % (out_base, count))
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Create RecordIO image packs (reference: im2rec)")
    p.add_argument("prefix", help="output prefix (and .lst prefix)")
    p.add_argument("root", help="image folder root")
    p.add_argument("--list", action="store_true",
                   help="create .lst instead of packing")
    p.add_argument("--recursive", action="store_true",
                   help="class ids from subfolders")
    p.add_argument("--shuffle", type=int, default=1)
    p.add_argument("--train-ratio", type=float, default=1.0)
    p.add_argument("--test-ratio", type=float, default=0.0)
    p.add_argument("--resize", type=int, default=0,
                   help="resize shorter side")
    p.add_argument("--center-crop", type=int, default=0)
    p.add_argument("--quality", type=int, default=95)
    p.add_argument("--pack-label", action="store_true",
                   help="store full float label vector (detection)")
    p.add_argument("--num-thread", type=int, default=1,
                   help="accepted for reference-CLI compat")
    p.add_argument("--working-dir", default=None)
    args = p.parse_args(argv)

    if args.list:
        write_list(args.prefix, args.root, args)
        return 0
    return pack(args.prefix, args.root, args)


if __name__ == "__main__":
    sys.exit(main())
