"""CPU-vs-TPU operator consistency sweep on real hardware.

The §4.2 second-backend oracle (reference:
``tests/python/gpu/test_operator_gpu.py`` imports the whole CPU suite +
``check_consistency``), run as a standalone CLI because the pytest tier
pins itself to the 8-device virtual CPU mesh:

    python tools/check_tpu_consistency.py            # needs the chip
    python tools/check_tpu_consistency.py --family nn

Each case runs forward AND input gradients on cpu(0) and tpu(0) and
cross-compares within per-dtype tolerance.  128 cases spanning every
op family (round-2 verdict item #4).  Exit code 0 = all pass.
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# every case here calls each op ~once per context — the eager-jit cache
# would pay a per-op XLA compile for a single use (docs/perf.md "Eager
# dispatch"); the retracing path is faster for one-shot sweeps
os.environ.setdefault("MXNET_EAGER_JIT", "0")

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import check_consistency

RNG = np.random.RandomState(0)


def rand(*shape, scale=1.0, lo=None, hi=None):
    if lo is not None:
        return RNG.uniform(lo, hi, shape).astype("float32")
    return (RNG.randn(*shape) * scale).astype("float32")


def build_cases():
    cases = []

    # --- elementwise unary (one case each; positive-domain where needed)
    UNARY = ["relu", "sigmoid", "tanh", "erf", "softsign", "mish",
             "log_sigmoid", "hard_sigmoid", "sin", "cos", "tan",
             "arcsin", "arccos", "arctan", "sinh", "cosh", "arcsinh",
             "arctanh", "exp", "expm1", "square", "cbrt", "negative",
             "abs", "sign", "floor", "ceil", "round", "rint", "trunc",
             "fix", "logical_not", "degrees", "radians"]
    POS_UNARY = ["log", "log10", "log2", "log1p", "sqrt", "rsqrt",
                 "rcbrt", "reciprocal", "gamma", "gammaln", "digamma"]
    for name in UNARY:
        dom = dict(lo=-0.7, hi=0.7) if name in (
            "arcsin", "arccos", "arctanh") else {}
        cases.append(("u_" + name,
                      (lambda n: lambda x: getattr(nd, n)(x))(name),
                      [rand(4, 6, **dom) if dom else rand(4, 6)]))
    for name in POS_UNARY:
        cases.append(("u_" + name,
                      (lambda n: lambda x: getattr(nd, n)(x))(name),
                      [rand(4, 6, lo=0.4, hi=1.6)]))

    # --- binary / broadcast
    BINARY = ["broadcast_add", "broadcast_sub", "broadcast_mul",
              "broadcast_maximum", "broadcast_minimum",
              "broadcast_hypot", "broadcast_power", "arctan2",
              "elemwise_add", "elemwise_mul", "maximum", "minimum"]
    for name in BINARY:
        shapes = [(3, 4), (3, 1)] if name.startswith("broadcast") \
            else [(3, 4), (3, 4)]
        pos = name in ("broadcast_power",)
        cases.append(("b_" + name,
                      (lambda n: lambda a, b: getattr(nd, n)(a, b))(
                          name),
                      [rand(*shapes[0], lo=0.4, hi=1.6) if pos
                       else rand(*shapes[0]),
                       rand(*shapes[1], lo=0.4, hi=1.6) if pos
                       else rand(*shapes[1])]))
    cases.append(("b_broadcast_div", lambda a, b: nd.broadcast_div(a, b),
                  [rand(3, 4), rand(1, 4, lo=0.5, hi=1.5)]))

    # --- reductions / argsort family
    cases += [
        ("r_sum", lambda x: nd.sum(x, axis=1), [rand(4, 6)]),
        ("r_mean_keep", lambda x: nd.mean(x, axis=0, keepdims=True),
         [rand(4, 6)]),
        ("r_prod", lambda x: nd.prod(x, axis=1),
         [rand(3, 4, lo=0.5, hi=1.5)]),
        ("r_max", lambda x: nd.max(x, axis=1), [rand(4, 6, scale=2)]),
        ("r_min", lambda x: nd.min(x, axis=0), [rand(4, 6, scale=2)]),
        ("r_norm", lambda x: nd.norm(x, axis=1), [rand(4, 6)]),
        ("r_nansum", lambda x: nd.nansum(x, axis=1), [rand(4, 6)]),
        ("r_moments", lambda x: nd.moments(x, axes=(0,))[0],
         [rand(4, 6)]),
        ("r_cumsum", lambda x: nd.cumsum(x, axis=1), [rand(4, 6)]),
        ("r_logsumexp_path",
         lambda x: nd.log(nd.sum(nd.exp(x), axis=-1)), [rand(4, 6)]),
        ("r_softmax", lambda x: nd.softmax(x), [rand(4, 7)]),
        ("r_log_softmax", lambda x: nd.log_softmax(x), [rand(4, 7)]),
        ("r_softmin", lambda x: nd.softmin(x), [rand(4, 7)]),
        ("r_topk_val", lambda x: nd.topk(x, k=3, ret_typ="value",
                                         axis=-1), [rand(5, 12)]),
        ("r_sort", lambda x: nd.sort(x, axis=-1), [rand(5, 12)]),
    ]

    # --- shape / indexing
    cases += [
        ("s_transpose", lambda x: nd.transpose(x, axes=(1, 0, 2)),
         [rand(2, 3, 4)]),
        ("s_reshape", lambda x: nd.reshape(x, shape=(6, 4)),
         [rand(2, 3, 4)]),
        ("s_slice", lambda x: nd.slice(x, begin=(0, 1), end=(3, 4)),
         [rand(3, 4)]),
        ("s_slice_axis", lambda x: nd.slice_axis(x, axis=1, begin=1,
                                                 end=3), [rand(3, 4)]),
        ("s_flip", lambda x: nd.flip(x, axis=1), [rand(3, 4)]),
        ("s_tile", lambda x: nd.tile(x, reps=(2, 2)), [rand(2, 3)]),
        ("s_repeat", lambda x: nd.repeat(x, repeats=2, axis=0),
         [rand(2, 3)]),
        ("s_pad", lambda x: nd.pad(x, mode="constant",
                                   pad_width=(0, 0, 0, 0, 1, 1, 1, 1)),
         [rand(1, 1, 3, 3)]),
        ("s_expand_swap",
         lambda x: nd.swapaxes(nd.expand_dims(x, axis=0), 0, 1),
         [rand(3, 4)]),
        ("s_depth_to_space", lambda x: nd.depth_to_space(x,
                                                         block_size=2),
         [rand(1, 4, 3, 3)]),
        ("s_one_hot_path", lambda x: nd.dot(
            nd.one_hot(nd.argmax(x, axis=1), depth=4), x),
         [rand(4, 4)]),
        ("s_take", lambda x: nd.take(
            x, nd.array(np.array([0., 2.]), ctx=x.context)),
         [rand(4, 5)]),
        ("s_gather_nd", lambda x: nd.gather_nd(
            x, nd.array(np.array([[0, 1], [1, 2]], "int32"),
                        ctx=x.context)), [rand(3, 4)]),
        ("s_where", lambda x, y: nd.where(
            nd.array((np.arange(12).reshape(3, 4) % 2)
                     .astype("float32"), ctx=x.context), x, y),
         [rand(3, 4), rand(3, 4)]),
        ("s_concat", lambda a, b: nd.Concat(a, b, dim=1),
         [rand(3, 2), rand(3, 3)]),
        ("s_stack", lambda a, b: nd.stack(a, b, axis=1),
         [rand(3, 4), rand(3, 4)]),
        ("s_split_sq",
         lambda x: nd.split(x, num_outputs=2, axis=1)[0], [rand(4, 6)]),
        ("s_clip", lambda x: nd.clip(x, a_min=-0.5, a_max=0.5),
         [rand(3, 4, scale=2)]),
    ]

    # --- nn
    cases += [
        ("nn_dense_gelu", lambda x, w: nd.LeakyReLU(
            nd.FullyConnected(x, w, num_hidden=32, no_bias=True),
            act_type="gelu"), [rand(8, 16), rand(32, 16)]),
        ("nn_conv_bn_relu", lambda x, w: nd.relu(
            nd.Convolution(x, w, kernel=(3, 3), pad=(1, 1),
                           num_filter=8, no_bias=True)),
         [rand(2, 4, 12, 12), rand(8, 4, 3, 3)]),
        ("nn_conv_stride", lambda x, w: nd.Convolution(
            x, w, kernel=(3, 3), stride=(2, 2), num_filter=4,
            no_bias=True), [rand(1, 3, 9, 9), rand(4, 3, 3, 3)]),
        ("nn_deconv", lambda x, w: nd.Deconvolution(
            x, w, kernel=(2, 2), stride=(2, 2), num_filter=4,
            no_bias=True), [rand(1, 3, 4, 4), rand(3, 4, 2, 2)]),
        ("nn_depthwise", lambda x, w: nd.Convolution(
            x, w, kernel=(3, 3), pad=(1, 1), num_filter=4, num_group=4,
            no_bias=True), [rand(1, 4, 6, 6), rand(4, 1, 3, 3)]),
        ("nn_pool_max", lambda x: nd.Pooling(
            x, kernel=(2, 2), stride=(2, 2), pool_type="max"),
         [rand(2, 3, 8, 8)]),
        ("nn_pool_avg_incl", lambda x: nd.Pooling(
            x, kernel=(3, 3), stride=(2, 2), pool_type="avg"),
         [rand(2, 3, 9, 9)]),
        ("nn_pool_global", lambda x: nd.Pooling(
            x, kernel=(1, 1), global_pool=True, pool_type="avg"),
         [rand(2, 3, 5, 5)]),
        ("nn_layernorm", lambda x, g, b: nd.LayerNorm(x, g, b),
         [rand(4, 24), np.ones(24, "float32"),
          np.zeros(24, "float32")]),
        ("nn_groupnorm", lambda x, g, b: nd.GroupNorm(
            x, g, b, num_groups=2),
         [rand(2, 4, 5, 5), np.ones(4, "float32"),
          np.zeros(4, "float32")]),
        ("nn_instancenorm", lambda x, g, b: nd.InstanceNorm(x, g, b),
         [rand(2, 3, 5, 5), np.ones(3, "float32"),
          np.zeros(3, "float32")]),
        ("nn_l2norm", lambda x: nd.L2Normalization(x), [rand(4, 8)]),
        ("nn_lrn", lambda x: nd.LRN(x, nsize=3), [rand(1, 5, 4, 4)]),
        ("nn_embed", lambda w: nd.Embedding(
            nd.array(np.array([[1, 3], [0, 2]], "float32"),
                     ctx=w.context), w, input_dim=8, output_dim=5),
         [rand(8, 5)]),
        ("nn_smooth_l1", lambda x: nd.smooth_l1(x, scalar=1.0),
         [rand(4, 5, scale=2)]),
        ("nn_seq_mask", lambda x: nd.SequenceMask(
            x, nd.array(np.array([2., 3.]), ctx=x.context),
            use_sequence_length=True), [rand(4, 2, 3)]),
        ("nn_dense_bias", lambda x, w, b: nd.FullyConnected(
            x, w, b, num_hidden=6), [rand(3, 5), rand(6, 5), rand(6)]),
        ("nn_prelu", lambda x, a: nd.LeakyReLU(
            x, a, act_type="prelu"), [rand(3, 4), rand(4, lo=0.1,
                                                       hi=0.3)]),
    ]

    # --- linalg
    def spd(n=4):
        m = RNG.randn(n, n).astype("float32")
        return m @ m.T + n * np.eye(n, dtype="float32")

    tril = np.tril(RNG.uniform(0.5, 1.5, (4, 4))).astype("float32")
    cases += [
        ("la_dot", lambda a, b: nd.dot(a, b),
         [rand(4, 5), rand(5, 6)]),
        ("la_dot_t", lambda a, b: nd.dot(a, b, transpose_a=True),
         [rand(5, 4), rand(5, 6)]),
        ("la_batch_dot_t", lambda a, b: nd.batch_dot(a, b,
                                                     transpose_b=True),
         [rand(3, 5, 7), rand(3, 6, 7)]),
        ("la_gemm2", lambda a, b: nd.linalg_gemm2(a, b),
         [rand(3, 4), rand(4, 5)]),
        ("la_potrf", lambda a: nd.linalg_potrf(a), [spd()]),
        ("la_trmm", lambda b: nd.linalg_trmm(
            nd.array(tril, ctx=b.context), b), [rand(4, 4)]),
        ("la_sumlogdiag", lambda a: nd.linalg_sumlogdiag(a),
         [spd()]),
        ("la_det", lambda a: nd.linalg_det(a), [spd()]),
        ("la_syrk", lambda a: nd.linalg_syrk(a), [rand(3, 4)]),
        ("la_diag", lambda x: nd.diag(x), [rand(4, 4)]),
    ]

    # --- vision / detection
    cases += [
        ("v_roialign", lambda x: nd.contrib.ROIAlign(
            x, nd.array(np.array([[0, 1.0, 1.0, 7.0, 7.0]], "float32"),
                        ctx=x.context),
            pooled_size=(2, 2), spatial_scale=1.0),
         [rand(1, 3, 10, 10)]),
        ("v_bilinear_resize", lambda x: nd.contrib.BilinearResize2D(
            x, height=6, width=6), [rand(1, 2, 4, 4)]),
        ("v_adaptive_pool", lambda x: nd.contrib.AdaptiveAvgPooling2D(
            x, output_size=(2, 2)), [rand(1, 2, 6, 6)]),
        ("v_deform_conv", lambda x, w: nd.DeformableConvolution(
            x, nd.array(np.full((1, 8, 4, 4), 0.3, "float32"),
                        ctx=x.context), w,
            nd.array(np.zeros(3, "float32"), ctx=x.context),
            kernel=(2, 2), num_filter=3),
         [rand(1, 2, 5, 5), rand(3, 2, 2, 2)]),
        # grid drawn ONCE here: a lambda that consumes RNG per call
        # would hand each context a different grid
        ("v_grid_sample",
         (lambda grid: lambda x: nd.BilinearSampler(
             x, nd.array(grid, ctx=x.context)))(
                 RNG.uniform(-0.8, 0.8, (1, 2, 4, 4))
                 .astype("float32")),
         [rand(1, 2, 5, 5)]),
        ("v_interleaved_qk",
         lambda q: nd.contrib.interleaved_matmul_selfatt_qk(q, heads=2),
         [rand(4, 2, 2 * 3 * 8)]),
    ]

    # --- fused optimizer-style composites (fwd only via grad=False is
    # not supported by check_consistency; use differentiable proxies)
    cases += [
        ("o_adam_math", lambda w, g, m, v: w - 0.01 * (
            (0.9 * m + 0.1 * g) / (nd.sqrt(0.999 * v + 0.001 *
                                           nd.square(g)) + 1e-8)),
         [rand(6), rand(6), rand(6), rand(6, lo=0.1, hi=0.5)]),
        ("o_lars_math", lambda w, g: w * nd.norm(w) /
         (nd.norm(g) + 1e-6), [rand(8), rand(8)]),
        ("o_clip_global", lambda g1, g2: g1 * nd.minimum(
            nd.ones((1,), ctx=g1.context),
            1.0 / nd.sqrt(nd.sum(nd.square(g1)) +
                          nd.sum(nd.square(g2)) + 1e-12)),
         [rand(5), rand(7)]),
    ]
    return cases


def build_sweep_cases():
    """Auto-generate consistency cases from the registry sweep's own
    case builders (round-3 verdict #6): every op the CPU sweep
    grad/fwd-checks gets a cpu-vs-tpu comparison with the same inputs
    and attrs, so the hard families (conv/pool/norm/linalg/quantized/
    reduce) are sampled exactly as broadly as the sweep itself."""
    import json
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tests"))
    import test_registry_sweep as sweep

    record_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs", "op_sweep_record.json")
    with open(record_path) as f:
        rec = json.load(f)["ops"]

    def first_out(out):
        return out[0] if isinstance(out, (tuple, list)) else out

    cases = []
    dropped = []
    # cross-backend comparison is ILL-POSED for these (documented, not
    # silent): eigen/singular vectors are sign- and degenerate-order-
    # indeterminate between backends; the CPU sweep's reconstruction-
    # style checks cover their correctness instead
    SIGN_AMBIGUOUS = {"_linalg_syevd": "eigenvector sign/order is "
                                       "backend-indeterminate",
                      "_np_linalg_svd": "singular-vector signs are "
                                        "backend-indeterminate"}
    for name in sorted(rec):
        r = rec[name]
        if r.get("status") != "pass":
            continue
        if name in SIGN_AMBIGUOUS:
            dropped.append((name, SIGN_AMBIGUOUS[name]))
            continue
        grad = r.get("mode") == "grad"
        try:
            if name in sweep.SPECS:
                mode, builder = sweep.SPECS[name]
                if mode == "gradf":
                    # gradf builders close over ctx-PINNED constant
                    # NDArrays — running the closure on the tpu context
                    # mixes committed devices; these ops are covered by
                    # the hand-written per-family cases instead
                    dropped.append((name, "gradf closure (ctx-pinned "
                                          "constants)"))
                    continue
                else:
                    nd_inputs, kwargs = builder()
                    fn = (lambda _n, _k: lambda *xs: first_out(
                        sweep.call(_n, *xs, **_k)))(name, kwargs)
            else:
                nd_inputs = sweep._auto_case(name)
                if nd_inputs is None:
                    dropped.append((name, "no auto pattern"))
                    continue
                fn = (lambda _n: lambda *xs: first_out(
                    sweep.call(_n, *xs)))(name)
        except Exception as e:  # noqa: BLE001 — builder broke
            dropped.append((name, "builder: %s" % str(e)[:80]))
            continue
        inputs = [x.asnumpy() if hasattr(x, "asnumpy") else
                  np.asarray(x) for x in nd_inputs]
        cases.append(("sw_" + name, fn, inputs, grad))
    if dropped:
        print("sweep cases dropped (%d):" % len(dropped))
        for n, why in dropped:
            print("  drop %s: %s" % (n, why))
    return cases


def _write_record(path, n_cases, record, failed, errored):
    """Incremental per-case record (the sweep takes hours through the
    tunnel; a partial record beats none if the run is cut short)."""
    if not path:
        return
    import json
    done = len(record)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"summary": {"cases": n_cases, "completed": done,
                               "pass": done - len(failed) - len(errored),
                               "fail": len(failed),
                               "harness_error": len(errored)},
                   "cases": record}, f, indent=1, sort_keys=True)
    os.replace(tmp, path)      # atomic: a cut-short run keeps the last
                               # complete record instead of a torn file


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--family", default=None,
                    help="prefix filter (u_, b_, r_, s_, nn_, la_, v_, "
                         "o_, sw_)")
    ap.add_argument("--max-cases", type=int, default=0)
    ap.add_argument("--no-sweep", action="store_true",
                    help="only the hand-written cases (round-2 set)")
    ap.add_argument("--record", default=None,
                    help="write the per-case JSON record here")
    ap.add_argument("--start", type=int, default=0,
                    help="skip the first N cases (resume after a "
                         "tunnel wedge; see tools/run_tpu_oracle.sh)")
    args = ap.parse_args()

    if mx.num_tpus() == 0:
        print("SKIP: no TPU visible")
        return 0
    cases = [(n, f, i, True) for (n, f, i) in build_cases()]
    if not args.no_sweep:
        cases += build_sweep_cases()
    if args.family:
        prefixes = tuple(args.family.split(","))
        cases = [c for c in cases if c[0].startswith(prefixes)]
    if args.max_cases:
        cases = cases[:args.max_cases]
    total_cases = len(cases)
    if args.start:
        cases = cases[args.start:]

    failed = []
    errored = []
    record = {}
    if args.record and args.start and os.path.exists(args.record):
        # resuming: keep the previous chunks' results
        import json
        try:
            with open(args.record) as f:
                record = json.load(f).get("cases", {})
            failed = [k for k, v in record.items()
                      if v.get("status") == "FAIL"]
            errored = [k for k, v in record.items()
                       if v.get("status") == "error"]
        except Exception:
            record = {}
    consecutive_backend_errors = 0
    for case_i, (name, fn, inputs, grad) in enumerate(cases):
        try:
            # rtol 2e-3: TPU evaluates transcendentals (log/exp
            # family, gammaln, ...) with its own polynomial
            # approximations — observed cpu-vs-tpu forward deltas are
            # ~1.5e-4 relative and composed-transcendental GRADIENTS
            # (mish) reach ~1.3e-3 — the same reason the reference's
            # check_consistency grants GPU contexts looser f32
            # tolerances than CPU
            check_consistency(fn, inputs, grad=grad, rtol=2e-3,
                              atol=1e-5)
            record[name] = {"status": "pass",
                            "mode": "grad" if grad else "fwd"}
            consecutive_backend_errors = 0
            print("ok  %s" % name, flush=True)
        except AssertionError as e:
            consecutive_backend_errors = 0
            failed.append(name)
            record[name] = {"status": "FAIL", "error": str(e)[:200]}
            print("FAIL %s: %s" % (name, str(e)[:200]), flush=True)
        except Exception as e:  # noqa: BLE001 — classify below
            if "TPU backend error" in str(e):
                # the PjRt client is likely wedged — every later
                # dispatch in this process would fail too.  Tolerate ONE
                # (transient tunnel hiccup), then stop at the SECOND and
                # let the wrapper restart a fresh process from the FIRST
                # errored case (the wedge began there; its record entry
                # is dropped so it gets a clean retry)
                consecutive_backend_errors += 1
                if consecutive_backend_errors == 1:
                    first_backend_err = (args.start + case_i, name)
                    record[name] = {"status": "error",
                                    "error": str(e)[:200]}
                    errored.append(name)
                    print("err %s: %s" % (name, str(e)[:120]),
                          flush=True)
                    continue
                idx, first_name = first_backend_err
                record.pop(first_name, None)
                if first_name in errored:
                    errored.remove(first_name)
                print("TUNNEL WEDGED at case %d (%s); resume with "
                      "--start %d" % (idx, first_name, idx), flush=True)
                _write_record(args.record, total_cases, record,
                              failed, errored)
                return 3
            consecutive_backend_errors = 0
            # harness limitation (int-typed inputs the f32 harness
            # can't re-place, etc.) ONLY if the same case also fails
            # on the CPU-only context — a TPU-side-only crash is a
            # real inconsistency and must fail the gate
            from mxnet_tpu.context import cpu as _cpu
            try:
                check_consistency(fn, inputs, ctx_list=[_cpu()],
                                  grad=grad, rtol=2e-3, atol=1e-5)
                cpu_ok = True
            except Exception:
                cpu_ok = False
            if cpu_ok:
                failed.append(name)
                record[name] = {"status": "FAIL",
                                "error": "tpu-only crash: %s"
                                         % str(e)[:200]}
                print("FAIL %s (tpu-only): %s"
                      % (name, str(e)[:150]), flush=True)
            else:
                errored.append(name)
                record[name] = {"status": "error",
                                "error": str(e)[:200]}
                print("err %s: %s" % (name, str(e)[:120]), flush=True)
        if args.record and len(record) % 25 == 0:
            _write_record(args.record, total_cases, record, failed,
                          errored)
    # end-of-run retry of backend-errored cases: the client is healthy
    # here (later cases ran), so a REPEATED "TPU backend error" on a
    # case whose CPU run passes is a genuine TPU-only crash, not a
    # tunnel hiccup — reclassify it as FAIL
    from mxnet_tpu.context import cpu as _cpu
    for name, fn, inputs, grad in cases:
        if record.get(name, {}).get("status") != "error":
            continue
        if "TPU backend error" not in record[name].get("error", ""):
            continue
        try:
            check_consistency(fn, inputs, grad=grad, rtol=2e-3,
                              atol=1e-5)
            errored.remove(name)
            record[name] = {"status": "pass",
                            "mode": "grad" if grad else "fwd",
                            "note": "passed on end-of-run retry "
                                    "(transient tunnel error)"}
            print("ok  %s (retry)" % name, flush=True)
        except Exception as e2:  # noqa: BLE001
            try:
                check_consistency(fn, inputs, ctx_list=[_cpu()],
                                  grad=grad, rtol=2e-3, atol=1e-5)
                cpu_ok = True
            except Exception:
                cpu_ok = False
            if cpu_ok:
                errored.remove(name)
                failed.append(name)
                record[name] = {"status": "FAIL",
                                "error": "tpu-only crash (repeated): %s"
                                         % str(e2)[:160]}
                print("FAIL %s (tpu-only, repeated)" % name, flush=True)

    n_pass = len(record) - len(failed) - len(errored)
    print("%d/%d consistent (%d FAIL, %d harness-errored)"
          % (n_pass, len(record), len(failed), len(errored)))
    _write_record(args.record, total_cases, record, failed, errored)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
