"""CPU-vs-TPU operator consistency sweep on real hardware.

The §4.2 second-backend oracle (reference:
``tests/python/gpu/test_operator_gpu.py`` imports the whole CPU suite +
``check_consistency``), run as a standalone CLI because the pytest tier
pins itself to the 8-device virtual CPU mesh:

    python tools/check_tpu_consistency.py            # needs the chip

Each case runs forward AND input gradients on cpu(0) and tpu(0) and
cross-compares within per-dtype tolerance.  Exit code 0 = all pass.
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import check_consistency


def rand(*shape, scale=1.0, rng=np.random):
    return (rng.randn(*shape) * scale).astype("float32")


def main():
    if mx.num_tpus() == 0:
        print("SKIP: no TPU visible")
        return 0
    rng = np.random.RandomState(0)

    cases = [
        ("dense_gelu", lambda x, w: nd.LeakyReLU(
            nd.FullyConnected(x, w, num_hidden=32, no_bias=True),
            act_type="gelu"),
         [rand(8, 16, rng=rng), rand(32, 16, rng=rng)]),
        ("conv_bn_relu", lambda x, w: nd.relu(
            nd.Convolution(x, w, kernel=(3, 3), pad=(1, 1),
                           num_filter=8, no_bias=True)),
         [rand(2, 4, 12, 12, rng=rng), rand(8, 4, 3, 3, rng=rng)]),
        ("softmax_ce", lambda x: nd.log_softmax(x, axis=-1),
         [rand(6, 10, rng=rng)]),
        ("layernorm", lambda x, g, b: nd.LayerNorm(x, g, b),
         [rand(4, 24, rng=rng), np.ones(24, "float32"),
          np.zeros(24, "float32")]),
        ("batch_dot_t", lambda a, b: nd.batch_dot(a, b,
                                                  transpose_b=True),
         [rand(3, 5, 7, rng=rng), rand(3, 6, 7, rng=rng)]),
        ("pool_max", lambda x: nd.Pooling(x, kernel=(2, 2),
                                          stride=(2, 2),
                                          pool_type="max"),
         [rand(2, 3, 8, 8, rng=rng)]),
        ("reduce_stats", lambda x: nd.sqrt(nd.mean(nd.square(x),
                                                   axis=(1, 2))),
         [rand(4, 9, 9, rng=rng)]),
        ("topk_pick", lambda x: nd.topk(x, k=3, ret_typ="value",
                                        axis=-1),
         [rand(5, 12, rng=rng)]),
        # constants created inside fn must live on the op's context —
        # mixed-context eager ops raise, matching reference semantics
        ("roialign", lambda x: nd.contrib.ROIAlign(
            x, nd.array(np.array([[0, 1.0, 1.0, 7.0, 7.0]], "float32"),
                        ctx=x.context),
            pooled_size=(2, 2), spatial_scale=1.0),
         [rand(1, 3, 10, 10, rng=rng)]),
        ("take_embed", lambda w: nd.Embedding(
            nd.array(np.array([[1, 3], [0, 2]], "float32"),
                     ctx=w.context), w, input_dim=8, output_dim=5),
         [rand(8, 5, rng=rng)]),
    ]

    failed = []
    for name, fn, inputs in cases:
        try:
            check_consistency(fn, inputs)
            print("ok  %s" % name)
        except Exception as e:  # noqa: BLE001 — report and continue
            failed.append(name)
            print("FAIL %s: %s" % (name, str(e)[:200]))
    print("%d/%d consistent" % (len(cases) - len(failed), len(cases)))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
