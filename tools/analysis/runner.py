"""mxlint entry point — run all three analyzers against the live repo.

Usage (from the repo root)::

    python -m tools.analysis                 # human-readable, exit 1 on
                                             # new violations
    python -m tools.analysis --json          # machine-readable report
    python -m tools.analysis --write-baseline  # accept current findings

Tier-1 wiring: ``tests/test_static_analysis.py`` calls :func:`run_all`
directly; ``tools/run_static_analysis.sh`` is the CLI wrapper that also
smokes the sanitizer builds.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List

from . import abi, jaxlint, native_lint
from .findings import Finding, load_baseline, split_new

__all__ = ["REPO_ROOT", "run_all", "main"]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(
    __file__)), "baseline.json")

HEADER = "native/include/mxnet_tpu/c_api.h"
BINDINGS = "mxnet_tpu/native.py"


def run_all(root: str = None, baseline_path: str = None) -> Dict:
    """Run every analyzer; returns ``{"findings": [...],
    "new": [...], "baselined": [...]}`` (Finding objects)."""
    root = root or REPO_ROOT
    findings: List[Finding] = []
    findings += abi.check(os.path.join(root, HEADER),
                          os.path.join(root, BINDINGS),
                          HEADER, BINDINGS)
    findings += jaxlint.run(root)
    findings += native_lint.run(root)
    baseline = load_baseline(baseline_path or DEFAULT_BASELINE)
    new, old = split_new(findings, baseline)
    return {"findings": findings, "new": new, "baselined": old}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="mxlint", description="repo static-analysis suite "
        "(C-ABI / JAX hazards / native concurrency)")
    ap.add_argument("--root", default=REPO_ROOT)
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept every current finding into the "
                         "baseline (review the diff!)")
    args = ap.parse_args(argv)

    report = run_all(args.root, args.baseline)
    if args.write_baseline:
        entries = [{"rule": f.rule, "path": f.path, "symbol": f.symbol,
                    "reason": "accepted by --write-baseline"}
                   for f in report["findings"]]
        with open(args.baseline, "w") as f:
            json.dump({"version": 1, "allow": entries}, f, indent=2,
                      sort_keys=True)
            f.write("\n")
        print("mxlint: baselined %d finding(s) -> %s"
              % (len(entries), args.baseline))
        return 0

    if args.json:
        print(json.dumps({
            "new": [vars(f) for f in report["new"]],
            "baselined": [vars(f) for f in report["baselined"]],
        }, indent=2))
    else:
        for f in report["new"]:
            print("NEW  %s" % f)
        for f in report["baselined"]:
            print("old  %s" % f)
        print("mxlint: %d new violation(s), %d baselined"
              % (len(report["new"]), len(report["baselined"])))
    return 1 if report["new"] else 0


if __name__ == "__main__":
    sys.exit(main())
