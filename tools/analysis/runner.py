"""mxlint entry point — run all four analyzers against the live repo.

Usage (from the repo root)::

    python -m tools.analysis                 # human-readable, exit 1 on
                                             # new violations
    python -m tools.analysis --changed-only  # only files changed vs the
                                             # merge-base (seconds, the
                                             # iteration default in
                                             # tools/run_static_analysis.sh)
    python -m tools.analysis --all           # full run (tier-1 scope)
    python -m tools.analysis --json          # machine-readable report
    python -m tools.analysis --write-baseline  # accept current findings

Tier-1 wiring: ``tests/test_static_analysis.py`` calls :func:`run_all`
directly (always full scope); ``tools/run_static_analysis.sh`` is the
CLI wrapper that also smokes the sanitizer builds.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Dict, List, Optional, Set

from . import abi, jaxlint, native_lint, pylocklint
from .findings import Finding, load_baseline, split_new

__all__ = ["REPO_ROOT", "changed_files", "run_all", "main"]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(
    __file__)), "baseline.json")

HEADER = "native/include/mxnet_tpu/c_api.h"
BINDINGS = "mxnet_tpu/native.py"


def _git(root: str, *args: str) -> Optional[str]:
    try:
        out = subprocess.run(["git"] + list(args), cwd=root,
                             capture_output=True, text=True, timeout=30)
    except Exception:
        return None
    return out.stdout if out.returncode == 0 else None


def changed_files(root: str) -> Optional[Set[str]]:
    """Repo-relative paths changed vs the merge-base (committed on this
    branch since the base, staged, unstaged, and untracked).  None when
    git is unavailable — the caller falls back to a full run."""
    base = None
    for ref in ("origin/main", "origin/master", "main", "master"):
        mb = _git(root, "merge-base", "HEAD", ref)
        if mb is not None:
            base = mb.strip()
            break
    out: Set[str] = set()
    probes = [("diff", "--name-only", "HEAD")]
    if base:
        probes.append(("diff", "--name-only", base, "HEAD"))
    for probe in probes:
        got = _git(root, *probe)
        if got is None:
            return None
        out.update(p.strip() for p in got.splitlines() if p.strip())
    untracked = _git(root, "ls-files", "-o", "--exclude-standard")
    if untracked is not None:
        out.update(p.strip() for p in untracked.splitlines()
                   if p.strip())
    return out


def run_all(root: str = None, baseline_path: str = None,
            changed_only: bool = False) -> Dict:
    """Run every analyzer; returns ``{"findings": [...],
    "new": [...], "baselined": [...]}`` (Finding objects).

    ``changed_only`` restricts reporting to files changed vs the
    merge-base (plus the working tree) so iteration costs seconds; the
    cross-module passes still parse their whole scope, so a change in
    one module that breaks an invariant ANCHORED in another is only
    guaranteed to surface on a full run — which is why tier-1 always
    runs full scope."""
    root = root or REPO_ROOT
    # changed_files() returning None (git unavailable) degrades to a
    # full run — `only is None` means unscoped everywhere below
    only = changed_files(root) if changed_only else None
    findings: List[Finding] = []
    if only is None or HEADER in only or BINDINGS in only:
        findings += abi.check(os.path.join(root, HEADER),
                              os.path.join(root, BINDINGS),
                              HEADER, BINDINGS)
    findings += jaxlint.run(root, only=only)
    findings += native_lint.run(root, only=only)
    findings += pylocklint.run(root, only=only)
    baseline = load_baseline(baseline_path or DEFAULT_BASELINE)
    new, old = split_new(findings, baseline)
    return {"findings": findings, "new": new, "baselined": old,
            "changed": sorted(only) if only is not None else None}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="mxlint", description="repo static-analysis suite "
        "(C-ABI / JAX hazards / native + Python concurrency)")
    ap.add_argument("--root", default=REPO_ROOT)
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--changed-only", action="store_true",
                    help="report only files changed vs the merge-base "
                         "(iteration mode — seconds, not the full "
                         "sweep)")
    ap.add_argument("--all", action="store_true",
                    help="full scope (the tier-1 default; overrides "
                         "--changed-only)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept every current finding into the "
                         "baseline (review the diff!)")
    args = ap.parse_args(argv)

    # --write-baseline must see the FULL finding set: writing from a
    # changed-only scope would silently drop baseline entries for
    # every unchanged file
    report = run_all(args.root, args.baseline,
                     changed_only=args.changed_only and not args.all
                     and not args.write_baseline)
    if report.get("changed") is not None and not args.json:
        print("mxlint: --changed-only over %d changed file(s)"
              % len(report["changed"]))
    if args.write_baseline:
        entries = [{"rule": f.rule, "path": f.path, "symbol": f.symbol,
                    "reason": "accepted by --write-baseline"}
                   for f in report["findings"]]
        with open(args.baseline, "w") as f:
            json.dump({"version": 1, "allow": entries}, f, indent=2,
                      sort_keys=True)
            f.write("\n")
        print("mxlint: baselined %d finding(s) -> %s"
              % (len(entries), args.baseline))
        return 0

    if args.json:
        print(json.dumps({
            "new": [vars(f) for f in report["new"]],
            "baselined": [vars(f) for f in report["baselined"]],
        }, indent=2))
    else:
        for f in report["new"]:
            print("NEW  %s" % f)
        for f in report["baselined"]:
            print("old  %s" % f)
        print("mxlint: %d new violation(s), %d baselined"
              % (len(report["new"]), len(report["baselined"])))
    return 1 if report["new"] else 0


if __name__ == "__main__":
    sys.exit(main())
