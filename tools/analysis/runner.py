"""mxlint entry point — run all seven analyzers against the live repo.

Usage (from the repo root)::

    python -m tools.analysis                 # human-readable, exit 1 on
                                             # new violations
    python -m tools.analysis --changed-only  # only files changed vs the
                                             # merge-base (seconds, the
                                             # iteration default in
                                             # tools/run_static_analysis.sh)
    python -m tools.analysis --all           # full run (tier-1 scope)
    python -m tools.analysis --format json   # machine-readable findings
                                             # (stable schema: rule, file,
                                             # line, message, fingerprint)
    python -m tools.analysis --write-baseline    # accept current findings
    python -m tools.analysis --update-budgets    # re-record graphlint's
                                                 # HBM manifest (never
                                                 # relaxes a budget)
    python -m tools.analysis --write-sharding-audit  # regenerate
                                                 # docs/sharding_readiness.md
    python -m tools.analysis --write-protocol-audit  # regenerate
                                                 # docs/protocol.md

Tier-1 wiring: ``tests/test_static_analysis.py`` calls :func:`run_all`
directly (always full scope); ``tools/run_static_analysis.sh`` is the
CLI wrapper that also smokes the sanitizer builds.
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
from typing import Dict, List, Optional, Set

from . import (abi, asynclint, envlint, graphlint, jaxlint,
               native_lint, protolint, pylocklint)
from .findings import Finding, load_baseline, split_new

__all__ = ["REPO_ROOT", "changed_files", "run_all", "fingerprint",
           "findings_json", "main"]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(
    __file__)), "baseline.json")

HEADER = "native/include/mxnet_tpu/c_api.h"
BINDINGS = "mxnet_tpu/native.py"


def _git(root: str, *args: str) -> Optional[str]:
    try:
        out = subprocess.run(["git"] + list(args), cwd=root,
                             capture_output=True, text=True, timeout=30)
    except Exception:
        return None
    return out.stdout if out.returncode == 0 else None


def changed_files(root: str) -> Optional[Set[str]]:
    """Repo-relative paths changed vs the merge-base (committed on this
    branch since the base, staged, unstaged, and untracked).  None when
    git is unavailable — the caller falls back to a full run."""
    base = None
    for ref in ("origin/main", "origin/master", "main", "master"):
        mb = _git(root, "merge-base", "HEAD", ref)
        if mb is not None:
            base = mb.strip()
            break
    out: Set[str] = set()
    probes = [("diff", "--name-only", "HEAD")]
    if base:
        probes.append(("diff", "--name-only", base, "HEAD"))
    for probe in probes:
        got = _git(root, *probe)
        if got is None:
            return None
        out.update(p.strip() for p in got.splitlines() if p.strip())
    untracked = _git(root, "ls-files", "-o", "--exclude-standard")
    if untracked is not None:
        out.update(p.strip() for p in untracked.splitlines()
                   if p.strip())
    return out


def run_all(root: str = None, baseline_path: str = None,
            changed_only: bool = False) -> Dict:
    """Run every analyzer; returns ``{"findings": [...],
    "new": [...], "baselined": [...]}`` (Finding objects).

    ``changed_only`` restricts reporting to files changed vs the
    merge-base (plus the working tree) so iteration costs seconds; the
    cross-module passes still parse their whole scope, so a change in
    one module that breaks an invariant ANCHORED in another is only
    guaranteed to surface on a full run — which is why tier-1 always
    runs full scope.  graphlint scopes by *trace closure* instead: a
    program re-traces when any file its last recorded trace touched
    changed (see ``graphlint._needs_trace``)."""
    root = root or REPO_ROOT
    # changed_files() returning None (git unavailable) degrades to a
    # full run — `only is None` means unscoped everywhere below
    only = changed_files(root) if changed_only else None
    findings: List[Finding] = []
    if only is None or HEADER in only or BINDINGS in only:
        findings += abi.check(os.path.join(root, HEADER),
                              os.path.join(root, BINDINGS),
                              HEADER, BINDINGS)
    findings += jaxlint.run(root, only=only)
    findings += native_lint.run(root, only=only)
    findings += pylocklint.run(root, only=only)
    findings += graphlint.run(root, only=only)
    findings += protolint.run(root, only=only)
    findings += asynclint.run(root, only=only)
    findings += envlint.run(root, only=only)
    baseline = load_baseline(baseline_path or DEFAULT_BASELINE)
    new, old = split_new(findings, baseline)
    return {"findings": findings, "new": new, "baselined": old,
            "changed": sorted(only) if only is not None else None}


def fingerprint(f: Finding) -> str:
    """Stable finding id for CI annotation — sha1 of the
    line-independent baseline key, so unrelated edits do not churn
    annotations."""
    return hashlib.sha1(f.key.encode()).hexdigest()[:12]


def findings_json(report: Dict) -> Dict:
    """The ``--format json`` schema (stable; CI consumes it):
    ``{"version": 1, "findings": [{rule, file, line, message,
    fingerprint, analyzer, symbol, status}], "new": N,
    "baselined": M}``."""
    out = []
    for status, fs in (("new", report["new"]),
                       ("baselined", report["baselined"])):
        for f in fs:
            out.append({"rule": f.rule, "file": f.path, "line": f.line,
                        "message": f.message,
                        "fingerprint": fingerprint(f),
                        "analyzer": f.analyzer, "symbol": f.symbol,
                        "status": status})
    return {"version": 1, "findings": out,
            "new": len(report["new"]),
            "baselined": len(report["baselined"])}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="mxlint", description="repo static-analysis suite "
        "(C-ABI / JAX hazards / native + Python concurrency / "
        "compiled-program graphs / serving wire protocol / asyncio "
        "event-loop hazards + env-var doc drift)")
    ap.add_argument("--root", default=REPO_ROOT)
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--json", action="store_true",
                    help="alias for --format json")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text",
                    help="output format; json is the stable "
                         "machine-readable schema (rule, file, line, "
                         "message, fingerprint) for CI annotation")
    ap.add_argument("--changed-only", action="store_true",
                    help="report only files changed vs the merge-base "
                         "(iteration mode — seconds, not the full "
                         "sweep); graphlint re-traces only programs "
                         "whose recorded trace closure changed")
    ap.add_argument("--all", action="store_true",
                    help="full scope (the tier-1 default; overrides "
                         "--changed-only)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept every current finding into the "
                         "baseline (review the diff!)")
    ap.add_argument("--update-budgets", action="store_true",
                    help="re-record graphlint's per-program peak-live "
                         "bytes + trace closures in hbm_budgets.json "
                         "(ALWAYS full scope; never relaxes a budget)")
    ap.add_argument("--write-sharding-audit", action="store_true",
                    help="regenerate the sharding-readiness audit "
                         "table (docs/sharding_readiness.md)")
    ap.add_argument("--write-protocol-audit", action="store_true",
                    help="regenerate the serving wire-protocol audit "
                         "table (docs/protocol.md)")
    args = ap.parse_args(argv)
    fmt = "json" if args.json else args.format

    if args.write_protocol_audit:
        # pure AST (no import of the checkout), so --root is honored
        path = os.path.join(args.root, protolint.AUDIT_PATH)
        with open(path, "w") as f:
            f.write(protolint.protocol_audit_md(args.root))
        print("protolint: wrote %s" % path)
        return 0

    if args.update_budgets or args.write_sharding_audit:
        # graphlint traces the IMPORTED checkout — a foreign --root
        # would write this checkout's measurements into the other
        # tree's manifest paths (or vice versa); refuse the mix
        if os.path.realpath(args.root) != os.path.realpath(REPO_ROOT):
            print("mxlint: --update-budgets/--write-sharding-audit "
                  "audit the imported checkout (%s) and do not honor "
                  "--root; run them from the target checkout"
                  % REPO_ROOT, file=sys.stderr)
            return 2

    if args.update_budgets:
        data = graphlint.update_budgets(args.root)
        for name, e in sorted(data["programs"].items()):
            print("graphlint: %-24s peak=%d budget=%d"
                  % (name, e["peak_bytes"], e["budget_bytes"]))
        print("graphlint: wrote %s" % graphlint.BUDGETS_PATH)
        return 0
    if args.write_sharding_audit:
        path = os.path.join(args.root, graphlint.AUDIT_PATH)
        with open(path, "w") as f:
            f.write(graphlint.sharding_audit_md(args.root))
        print("graphlint: wrote %s" % path)
        return 0

    # --write-baseline must see the FULL finding set: writing from a
    # changed-only scope would silently drop baseline entries for
    # every unchanged file
    report = run_all(args.root, args.baseline,
                     changed_only=args.changed_only and not args.all
                     and not args.write_baseline)
    if report.get("changed") is not None and fmt != "json":
        print("mxlint: --changed-only over %d changed file(s)"
              % len(report["changed"]))
    if args.write_baseline:
        entries = [{"rule": f.rule, "path": f.path, "symbol": f.symbol,
                    "reason": "accepted by --write-baseline"}
                   for f in report["findings"]]
        with open(args.baseline, "w") as f:
            json.dump({"version": 1, "allow": entries}, f, indent=2,
                      sort_keys=True)
            f.write("\n")
        print("mxlint: baselined %d finding(s) -> %s"
              % (len(entries), args.baseline))
        return 0

    if fmt == "json":
        print(json.dumps(findings_json(report), indent=2))
    else:
        for f in report["new"]:
            print("NEW  %s" % f)
        for f in report["baselined"]:
            print("old  %s" % f)
        print("mxlint: %d new violation(s), %d baselined"
              % (len(report["new"]), len(report["baselined"])))
    return 1 if report["new"] else 0


if __name__ == "__main__":
    sys.exit(main())
