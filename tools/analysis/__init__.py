"""mxlint — the repo-native static-analysis suite (ISSUE 4 + 7 + 8).

Seven analyzers, each a module here, all runnable as tier-1 tests
(``tests/test_static_analysis.py``) and as a CLI
(``python -m tools.analysis``, ``--changed-only`` for the seconds-fast
iteration scope, ``--format json`` for CI annotation):

* :mod:`.abi` — C-ABI consistency between ``c_api.h``, the ctypes
  ``_PROTOTYPES`` table, and every call site in ``mxnet_tpu/native.py``;
* :mod:`.jaxlint` — JAX hot-loop hazards (implicit host syncs, retrace
  churn, trace-clock mixing, unsynced benchmark clocks);
* :mod:`.native_lint` — locking discipline over ``native/src/*.cc``
  (lock order, guarded fields, condvar predicates), backstopped by the
  ``make tsan`` / ``make asan`` stress harness;
* :mod:`.pylocklint` — Python concurrency over ``mxnet_tpu/serving``,
  ``obs`` and ``io`` (inferred guarded-by, cross-module lock-order
  cycles, cv protocol, blocking-under-lock, PrefixCache refcount
  balance), backstopped by the :mod:`.interleave` explorer;
* :mod:`.graphlint` — jaxpr-level audit of the hot COMPILED programs
  (serving step, COW page copy, GPT generate/speculative, the train
  steps, the Pallas paged-attention wrapper): donation verified
  against the lowering, peak-live-bytes vs the committed
  ``hbm_budgets.json`` manifest, bf16/int8→f32 dtype drift, host
  callbacks in hot programs, plus the report-mode sharding-readiness
  audit (``docs/sharding_readiness.md``);
* :mod:`.protolint` — wire-protocol & process-lifecycle audit of the
  disaggregated serving cluster (``mxnet_tpu/serving/`` over the
  ``parallel/dist.py`` raw frames): per-role send-site ↔ dispatch-arm
  agreement, meta-key schema drift between processes, the incarnation
  gen fence as a checked invariant, request/reply pairing on every
  exit edge, and Process/Connection/Listener lifecycle (the
  ``py-ref-leak`` exit-edge machinery generalized to OS resources),
  plus the checked-in protocol audit (``docs/protocol.md``);
* :mod:`.asynclint` — asyncio event-loop hazards in the HTTP/SSE
  front door (``mxnet_tpu/serving`` + ``obs``): a call-graph model of
  every ``async def`` with the thread↔loop boundary made explicit
  (executor hops and ``call_soon_threadsafe`` terminate taint) —
  blocking primitives reachable from coroutines, dropped coroutines
  and lost task exceptions, loop-owned state mutated from engine
  threads, StreamWriter close()+wait_closed() on every exit edge, and
  threading locks held across awaits.

Riding along, :mod:`.envlint`: every literal ``MXNET_*`` env read in
``mxnet_tpu/`` must have a row in ``docs/env_vars.md``
(``env-doc-drift``).

The dynamic half of ISSUE 7 lives in :mod:`.interleave`: a loom-lite
deterministic scheduler that serializes the serving cluster's threads
and explores seeded interleavings (``tests/test_interleave.py``).

Rule catalog, pragma syntax and baseline workflow:
``docs/static_analysis.md``.
"""
from .findings import Finding  # noqa: F401
from .runner import main, run_all  # noqa: F401
