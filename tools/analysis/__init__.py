"""mxlint — the repo-native static-analysis suite (ISSUE 4 tentpole).

Three analyzers, each a module here, all runnable as tier-1 tests
(``tests/test_static_analysis.py``) and as a CLI
(``python -m tools.analysis``):

* :mod:`.abi` — C-ABI consistency between ``c_api.h``, the ctypes
  ``_PROTOTYPES`` table, and every call site in ``mxnet_tpu/native.py``;
* :mod:`.jaxlint` — JAX hot-loop hazards (implicit host syncs, retrace
  churn, trace-clock mixing);
* :mod:`.native_lint` — locking discipline over ``native/src/*.cc``
  (lock order, guarded fields, condvar predicates), backstopped by the
  ``make tsan`` / ``make asan`` stress harness.

Rule catalog, pragma syntax and baseline workflow:
``docs/static_analysis.md``.
"""
from .findings import Finding  # noqa: F401
from .runner import main, run_all  # noqa: F401
