"""Env-var documentation drift check (ISSUE 19 satellite).

``docs/env_vars.md`` is the operator's contract: every runtime switch
the tree actually reads must have a row there.  The table has been
kept current by hand through 24 rounds; this rider makes the drift
machine-checked the same way graphlint pins the sharding audit.

One rule, ``env-doc-drift``: every read of a literal ``MXNET_*`` key
in ``mxnet_tpu/`` — ``os.environ.get("K")``, ``os.environ["K"]``,
``"K" in os.environ``, ``os.environ.setdefault("K", ...)``, or a call
to one of the repo's env helpers (``env_flag`` / ``env_int`` in
``base.py``, the ``_env_default`` / ``_env_int`` module-local clones)
with a literal first argument — must appear in a backticked
``MXNET_*`` token somewhere in ``docs/env_vars.md``.  Dynamic key
construction (prefix + name) is invisible to the AST scan and out of
scope; docstring mentions of a key are not reads (the scan is
AST-based precisely so prose can't satisfy — or trip — the rule).

The reverse direction (documented-but-never-read) is deliberately not
a rule: keys read by tools/ and tests/ (``MXNET_SERVE_PREFILL``,
``MXNET_TEST_SEED``) legitimately live in the table.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding, apply_pragmas

PACKAGES = ["mxnet_tpu"]
DOC = "docs/env_vars.md"

TRIGGER_PREFIXES = ("mxnet_tpu/", "tools/analysis/")
TRIGGER_FILES = (DOC,)

_ENV_HELPERS = {"env_flag", "env_int", "_env_default", "_env_int"}
_KEY_RE = re.compile(r"`(MXNET_[A-Z0-9_]+)`")


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _is_environ(node: ast.AST) -> bool:
    return _dotted(node).endswith("environ")


def _literal_key(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value.startswith("MXNET_"):
        return node.value
    return None


def _reads(tree: ast.AST) -> List[Tuple[str, int]]:
    """Every (key, line) a module reads with a literal MXNET_* key."""
    out: List[Tuple[str, int]] = []
    for n in ast.walk(tree):
        if isinstance(n, ast.Call):
            f = n.func
            if isinstance(f, ast.Attribute) and \
                    f.attr in ("get", "setdefault", "pop") and \
                    _is_environ(f.value) and n.args:
                k = _literal_key(n.args[0])
                if k:
                    out.append((k, n.lineno))
            name = f.attr if isinstance(f, ast.Attribute) else \
                (f.id if isinstance(f, ast.Name) else None)
            if name in _ENV_HELPERS and n.args:
                k = _literal_key(n.args[0])
                if k:
                    out.append((k, n.lineno))
        elif isinstance(n, ast.Subscript) and _is_environ(n.value):
            k = _literal_key(n.slice)
            if k:
                out.append((k, n.lineno))
        elif isinstance(n, ast.Compare) and \
                any(isinstance(op, (ast.In, ast.NotIn))
                    for op in n.ops) and \
                any(_is_environ(c) for c in n.comparators):
            k = _literal_key(n.left)
            if k:
                out.append((k, n.lineno))
    return out


def documented_keys(doc_text: str) -> Set[str]:
    """The backticked ``MXNET_*`` tokens in docs/env_vars.md."""
    return set(_KEY_RE.findall(doc_text))


def analyze(modules: Dict[str, str],
            documented: Set[str]) -> List[Finding]:
    out: List[Finding] = []
    for rel in sorted(modules):
        source = modules[rel]
        try:
            tree = ast.parse(source, rel)
        except SyntaxError:
            continue
        fs = []
        for key, line in _reads(tree):
            if key in documented:
                continue
            fs.append(Finding(
                "env", "env-doc-drift", rel, line, key,
                "%s is read here but has no row in %s — every "
                "runtime switch must be documented for the operator "
                "(add the row: variable, default, effect)"
                % (key, DOC)))
        out.extend(apply_pragmas(fs, source))
    return sorted(out, key=lambda f: (f.path, f.line, f.symbol))


def lint_source(source: str, rel_path: str,
                documented: Set[str]) -> List[Finding]:
    """Single-module entry (the drift test drives this directly)."""
    return analyze({rel_path: source}, documented)


def _load_modules(root: str) -> Dict[str, str]:
    modules: Dict[str, str] = {}
    for pkg in PACKAGES:
        top = os.path.join(root, pkg)
        for dirpath, _dirnames, filenames in os.walk(top):
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                full = os.path.join(dirpath, name)
                rel = os.path.relpath(full, root).replace(os.sep, "/")
                with open(full) as f:
                    modules[rel] = f.read()
    return modules


def triggered(only: Optional[Set[str]]) -> bool:
    if only is None:
        return True
    return any(p in TRIGGER_FILES
               or p.startswith(TRIGGER_PREFIXES) for p in only)


def run(root: str, only: Optional[Set[str]] = None) -> List[Finding]:
    """Check every mxnet_tpu/ env read against docs/env_vars.md."""
    if not triggered(only):
        return []
    doc_path = os.path.join(root, DOC)
    documented: Set[str] = set()
    if os.path.exists(doc_path):
        with open(doc_path) as f:
            documented = documented_keys(f.read())
    findings = analyze(_load_modules(root), documented)
    if only is not None:
        findings = [f for f in findings if f.path in only]
    return findings
