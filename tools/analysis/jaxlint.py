"""JAX hazard linter (mxlint analyzer 2 of 3) — Python ``ast`` based.

Rules
-----
``host-sync``  In a designated hot-loop region, a device→host
    materialization of a value produced by a compiled step function:
    ``np.asarray``/``np.array`` on a *device-tainted* expression,
    ``.item()`` / ``.tolist()`` / ``.block_until_ready()`` on one,
    ``float()``/``int()``/``bool()`` of one, or ``jax.device_get`` of
    one.  Taint is a simple intra-region dataflow: results of calls to
    compiled-step callables (terminal name matching ``*step_fn``, a
    name bound from ``jax.jit(...)``, or a function defined under
    ``@jax.jit``) are tainted; round 21 adds two sources for the
    overlap split — calls to ``*_dispatch`` (the dispatch helper
    returns the step program's output un-materialized) and the
    ``DEVICE_PARAMS`` registry (a hot-region function that RECEIVES a
    step result as a parameter, like the overlap ``_drain``, declares
    it there).  Taint propagates through subscripts, attributes,
    arithmetic, and tuple unpacking; a flagged materialization (e.g.
    ``x = np.asarray(x)``) clears it — the sync happened there,
    downstream host math is free.  ``jnp.asarray`` (host→device) is
    deliberately NOT a sync.

``retrace``  Retrace/recompile churn: (a) ``jax.jit(...)`` called
    inside a ``for``/``while`` body — the compile cache is keyed on
    the function object, so a fresh closure per iteration recompiles
    every time (the repo idiom is a module-level keyed cache, see
    ``models/gpt.py``); (b) a known-jitted callable invoked with a
    bare Python numeric literal or a ``list``/``dict``/``set`` display
    as an argument — scalars belong in the cache key / static args,
    not the traced signature.

``clock-mix``  In modules on the profiler's shared trace clock
    (``time.perf_counter`` — obs/, serving/, profiler, serve_bench),
    a call to ``time.time``/``time.monotonic``/``time.clock`` or
    ``datetime.*.now`` — mixing clocks skews every span it touches.

``bench-no-sync``  (round 12) In benchmark modules, a timed region —
    opened by ``t0 = time.perf_counter()``, closed by any other
    ``perf_counter()`` read — containing a call to a recognized
    jitted/step callable whose result is never synced
    (``block_until_ready`` / ``jax.device_get`` / ``np.asarray`` /
    ``float()``/``.item()``) before the closing read.  That clock
    measures DISPATCH, not execution — the hazard class that bit
    ``serve_bench._fixed_batch`` in round 9.  Dispatch-timing on
    purpose?  Pragma it with the justification.

Suppression: ``# mxlint: allow(<rule>)`` on the line or the comment
block directly above (see ``findings.py``).
"""
from __future__ import annotations

import ast
import fnmatch
import os
import re
from typing import List, Optional, Set, Tuple

from .findings import Finding, apply_pragmas

__all__ = ["HOT_REGIONS", "CLOCK_MODULES", "lint_source", "run"]

# (repo-relative glob, qualname regex) — the designated hot-loop regions
HOT_REGIONS: List[Tuple[str, str]] = [
    # round 11: the speculation plan/draft path runs once per engine
    # step on the host — it must stay pure host work (no device syncs
    # beyond step()'s one pragma'd token read-back).
    # round 21: the overlap split — plan build (planner thread AND
    # inline cold path), dispatch, deferred drain/commit, and the
    # planner kick all run once per step; a stray sync in any of them
    # un-hides exactly the host latency the pipeline exists to hide
    ("mxnet_tpu/serving/engine.py",
     r"(?:.*\.)?(step|_step_serial|_step_overlap|_take_plan|_drain"
     r"|_maybe_plan_ahead|_build_plan|_dispatch|_commit"
     r"|_plan_speculation)$"),
    # round 10: the cluster router loop (per-replica worker + routing
    # + completion) and the prefix-cache match/insert/evict paths run
    # once per step / per admission — no host syncs may sneak in.
    # round 12 widens both: the watchdog/failover path (a host sync
    # inside _fail_replica stalls EVERY waiter under the cluster lock)
    # and the eviction/COW leaf (_drop runs inside the allocator's
    # pressure callback, mid-admission)
    # round 17: the round-16 autoscaler actuation paths protolint's
    # call-graph walks also cover — add_worker/drain_worker and the
    # late-join handshake helper run while the cluster serves; a host
    # sync or in-loop jit there stalls scale actuation behind device
    # work exactly like a stall in the failover path would
    ("mxnet_tpu/serving/cluster.py",
     r"(?:.*\.)?(_worker|_pump_inbox|_complete|_route_locked"
     r"|_monitor_loop|_fail_replica|drain_replica"
     r"|add_worker|drain_worker|_handshake_one)$"),
    ("mxnet_tpu/serving/prefix_cache.py",
     r"(?:.*\.)?(match|insert_chain|evict|_drop)$"),
    # round 15: the disaggregated page export/install paths run per
    # transfer on the worker main loop — the ONE device round-trip
    # each (gather→host, host→scatter) is the transfer itself; any
    # additional sync, in-loop jit, or clock mix here stalls the
    # prefill→decode pipeline per page frame
    ("mxnet_tpu/serving/paged_kv.py",
     r"(?:.*\.)?(export_pages|install_pages)$"),
    ("mxnet_tpu/serving/page_streamer.py", r".*"),
    # round 18: the KV-tiering hot paths — spill runs inside the
    # allocator's pressure callback (mid-admission, mid-step phase A),
    # warm restore + swap-in run inside match()/_admit on the serving
    # thread; the ONE device round-trip each (export gather / install
    # scatter) IS the tier transfer — any additional sync, in-loop
    # jit, or clock mix here prices every pressure event and every
    # preemption resume
    ("mxnet_tpu/serving/tier_store.py", r".*"),
    ("mxnet_tpu/serving/prefix_cache.py",
     r"(?:.*\.)?(_spill_entry|_restore_run|_spilled_run|spill"
     r"|probe_depth|spilled_content)$"),
    ("mxnet_tpu/serving/engine.py",
     r"(?:.*\.)?(_preempt_victim|_swap_in)$"),
    # round 12: the metrics-registry mutation path — instrument
    # creation and reset run under the registry lock; a device sync or
    # in-loop jit there blocks every scrape and engine step behind it
    ("mxnet_tpu/obs/metrics.py",
     r"(?:.*\.)?(_get|counter|gauge|histogram|reset|reset_values)$"),
    # round 11: the host-side drafters feed the step builder — same
    # once-per-step budget as the engine scheduler
    ("mxnet_tpu/serving/drafters.py", r".*"),
    # round 11: the paged-attention kernel call path (builder + entry
    # point) is traced inside the step program — a stray host sync or
    # an in-loop jit here retraces/stalls every serving step
    ("mxnet_tpu/kernels/paged_attention.py", r".*"),
    ("mxnet_tpu/models/gpt.py", r"generate(?:_speculative)?$"),
    ("benchmark/serve_bench.py", r".*"),
    ("benchmark/spec_decode_probe.py", r".*"),
    # round 16: the autoscaler control loop ticks continuously next
    # to the serving threads (a host sync or in-loop jit there stalls
    # every scaling decision behind device work), the chaos driver's
    # poll/apply path runs inside the replay's timed loop, and the
    # trace generator feeds seeded workloads whose timing sections
    # must stay pure host work (bench-no-sync applies to the
    # benchmark/ module as usual)
    ("mxnet_tpu/serving/autoscaler.py", r".*"),
    ("mxnet_tpu/serving/chaos.py", r".*"),
    ("benchmark/traffic_trace.py", r".*"),
    # round 19: the training scale-out hot paths — the ICI-allreduce
    # KVStore's push/bucketing runs once per gradient sync (an in-loop
    # jit or stray host sync there serializes every training step
    # behind the collective), and the FSDP rule-table/composition
    # helpers are traced inside the sharded train step
    ("mxnet_tpu/kvstore/ici.py", r".*"),
    ("mxnet_tpu/parallel/fsdp.py", r".*"),
    # round 20: the HTTP front door's streaming/cancel paths run on
    # the asyncio event loop thread right next to the serving threads
    # — ONE loop serves every open connection, so a device sync, an
    # in-loop jit, or a clock mix in the SSE pump or the disconnect→
    # cancel path stalls every stream at once (the per-request
    # cluster work rides the executor, never the loop)
    ("mxnet_tpu/serving/http_frontend.py",
     r"(?:.*\.)?(_stream_sse|_respond_json|_run_request"
     r"|_cancel_disconnected|_serve_conn|_conn_loop"
     r"|_handle_generate|_handle_statusz|_handle_trace)$"),
    ("benchmark/http_bench.py", r".*"),
    # round 22: the zero-copy put transport and its cluster data-plane
    # callers run per page frame between the prefill and decode engine
    # loops — segment write/mmap-read and the caps/put framing must
    # stay pure host work (the device hand-off is the install scatter,
    # already covered via paged_kv.install_pages), and the peer-fetch
    # / stream / fetch-serve methods that choose the transport sit on
    # the worker main loop where a stray sync stalls decode admission
    ("mxnet_tpu/serving/transport.py", r".*"),
    ("mxnet_tpu/serving/cluster.py",
     r"(?:.*\.)?(_send_pages_frame|_serve_fetches|_stream_pages"
     r"|_fetch_remote|_peer_handler|_peer_conn)$"),
    # round 23: the flight recorder's emit path runs at every wire
    # send/recv, page install, and step boundary in BOTH router and
    # worker processes, and the span-ship/merge paths ride the worker
    # stats tick and the router recv loop — a device sync, in-loop
    # jit, or clock mix in any of them prices every hot-path event
    # (the recorder's mmap store must stay pure host work)
    ("mxnet_tpu/obs/flight.py", r".*"),
    ("mxnet_tpu/obs/trace.py", r".*"),
    ("mxnet_tpu/serving/cluster.py",
     r"(?:.*\.)?(_on_spans|_on_clock|_clock_ping|_maybe_send_stats"
     r"|_commit_tokens_locked|_slo_locked)$"),
]

# modules whose timestamps must stay on the shared perf_counter clock
CLOCK_MODULES: List[str] = [
    "mxnet_tpu/obs/*.py",
    "mxnet_tpu/serving/*.py",
    "mxnet_tpu/profiler.py",
    "benchmark/serve_bench.py",
    "benchmark/http_bench.py",
]

# modules whose perf_counter regions must sync their jitted work
# (bench-no-sync — every benchmark driver times compiled programs)
BENCH_MODULES: List[str] = [
    "benchmark/*.py",
]

STEP_FN_RE = re.compile(r".*step_fn$")
# round 21: the overlap split routes the raw step-program output
# through ``_dispatch`` (it stages inputs and returns the jitted call's
# result WITHOUT materializing) — in hot regions a call to it is a
# device result exactly like a *step_fn call.  Kept separate from
# STEP_FN_RE so the bench linter's jit-call heuristic is unchanged.
DEVICE_OUT_RE = re.compile(r".*(?:step_fn|_dispatch)$")
# hot-region functions that RECEIVE a step-program result as a
# parameter (the overlap ``_drain`` gets step N's sampled tokens while
# step N+1 executes): (repo-relative glob, qualname regex, params) —
# the named parameters are seeded device-tainted before linting
DEVICE_PARAMS: List[Tuple[str, str, Tuple[str, ...]]] = [
    ("mxnet_tpu/serving/engine.py", r"(?:.*\.)?_drain$", ("tok",)),
]
_NP_ALIASES = {"np", "numpy", "onp"}
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_WRONG_CLOCKS = {("time", "time"), ("time", "monotonic"),
                 ("time", "clock")}


def _terminal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _is_jax_jit(call: ast.Call) -> bool:
    return _dotted(call.func) in ("jax.jit", "jit")


class _RegionLinter(ast.NodeVisitor):
    """Lints one hot region (a function def and everything nested)."""

    def __init__(self, path: str, findings: List[Finding]):
        self.path = path
        self.findings = findings
        self.tainted: Set[str] = set()
        self.jitted: Set[str] = set()
        self.loop_depth = 0

    # -- helpers ------------------------------------------------------
    def _add(self, rule: str, node: ast.AST, symbol: str, msg: str):
        self.findings.append(Finding(
            "jax", rule, self.path, node.lineno, symbol, msg))

    def _expr_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Call):
            t = _terminal(node.func)
            if t and (DEVICE_OUT_RE.match(t) or t in self.jitted):
                return True
            return any(self._expr_tainted(a) for a in node.args)
        for child in ast.iter_child_nodes(node):
            if self._expr_tainted(child):
                return True
        return False

    def _is_step_call(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        t = _terminal(node.func)
        return bool(t and (DEVICE_OUT_RE.match(t) or t in self.jitted))

    # -- taint bookkeeping --------------------------------------------
    def visit_FunctionDef(self, node):
        for dec in node.decorator_list:
            if _dotted(dec) in ("jax.jit", "jit") or (
                    isinstance(dec, ast.Call) and _is_jax_jit(dec)):
                self.jitted.add(node.name)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node):
        self.generic_visit(node)  # flag RHS syncs before retargeting
        value_tainted = (self._is_step_call(node.value)
                         or self._expr_tainted(node.value))
        # a HOST materialization on the RHS *clears* taint: np.asarray
        # (np alias only — jnp.asarray stays on device and must keep
        # the taint), .item()/.tolist(), jax.device_get.  The sync
        # happened there; its result is host memory.
        if isinstance(node.value, ast.Call):
            func = node.value.func
            if isinstance(func, ast.Attribute):
                base = func.value
                is_np_call = (func.attr in ("asarray", "array")
                              and isinstance(base, ast.Name)
                              and base.id in _NP_ALIASES)
                # NOT block_until_ready: it returns the same device
                # array — a later float()/np.asarray is still a copy
                if is_np_call or func.attr in ("item", "tolist",
                                               "device_get"):
                    value_tainted = False
            if _is_jax_jit(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.jitted.add(tgt.id)
        names: List[str] = []
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                names.append(tgt.id)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                names.extend(e.id for e in tgt.elts
                             if isinstance(e, ast.Name))
        for name in names:
            if value_tainted:
                self.tainted.add(name)
            else:
                self.tainted.discard(name)

    # -- loops (for the jit-in-loop rule) -----------------------------
    def visit_For(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_While = visit_For
    visit_AsyncFor = visit_For

    # -- the rules ----------------------------------------------------
    def visit_Call(self, node):
        self.generic_visit(node)
        func = node.func
        dotted = _dotted(func)

        # retrace (a): jax.jit built inside a loop
        if _is_jax_jit(node) and self.loop_depth > 0:
            self._add("retrace", node, dotted or "jax.jit",
                      "jax.jit(...) inside a loop recompiles every "
                      "iteration — build once and cache (gpt.py idiom)")

        # retrace (b): jitted callable fed literals/containers
        if self._is_step_call(node):
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Constant) and isinstance(
                        arg.value, (int, float)) and not isinstance(
                        arg.value, bool):
                    self._add("retrace", node, _terminal(func) or "?",
                              "Python scalar literal in a jitted call "
                              "signature — mark static or fold into "
                              "the cache key")
                    break
                if isinstance(arg, (ast.List, ast.Dict, ast.Set)):
                    self._add("retrace", node, _terminal(func) or "?",
                              "container display in a jitted call "
                              "signature — structure changes retrace")
                    break

        # host-sync
        if isinstance(func, ast.Attribute):
            base = func.value
            if (func.attr in ("asarray", "array")
                    and isinstance(base, ast.Name)
                    and base.id in _NP_ALIASES
                    and any(self._expr_tainted(a) for a in node.args)):
                self._add("host-sync", node, "%s.%s" % (base.id,
                                                        func.attr),
                          "implicit device sync: numpy materialization "
                          "of a step-program result in a hot loop")
            elif func.attr in _SYNC_METHODS and self._expr_tainted(base):
                self._add("host-sync", node, "." + func.attr,
                          "device sync on a step-program result in a "
                          "hot loop")
            elif dotted.endswith("device_get") and any(
                    self._expr_tainted(a) for a in node.args):
                self._add("host-sync", node, dotted,
                          "jax.device_get of a step-program result in "
                          "a hot loop")
        elif isinstance(func, ast.Name) and func.id in ("float", "int",
                                                        "bool"):
            if any(self._expr_tainted(a) for a in node.args):
                self._add("host-sync", node, func.id,
                          "%s() of a step-program result forces a "
                          "device sync in a hot loop" % func.id)


class _ClockLinter(ast.NodeVisitor):
    def __init__(self, path: str, findings: List[Finding]):
        self.path = path
        self.findings = findings

    def visit_Call(self, node):
        self.generic_visit(node)
        dotted = _dotted(node.func)
        parts = tuple(dotted.rsplit(".", 2)[-2:])
        if parts in _WRONG_CLOCKS:
            self.findings.append(Finding(
                "jax", "clock-mix", self.path, node.lineno, dotted,
                "wrong clock on a trace-clock module — use "
                "time.perf_counter (profiler.now_us) so spans "
                "interleave in one dump"))
        elif dotted.endswith(".now") and "datetime" in dotted:
            self.findings.append(Finding(
                "jax", "clock-mix", self.path, node.lineno, dotted,
                "wall-clock datetime in a trace-clock module — use "
                "time.perf_counter"))


class _BenchSyncLinter:
    """bench-no-sync: linear scan of each function for timed regions
    whose jitted work is never synced before the closing clock read.

    Recognized jitted callables: names bound from ``jax.jit(...)``
    anywhere in the module, ``@jax.jit`` defs, and ``*step_fn`` names
    (the same vocabulary as the taint linter).  Unknown callables
    (``eng.step()``, host loops) never flag — the rule is deliberately
    precise rather than complete."""

    _SYNCS = {"block_until_ready", "device_get", "item", "tolist"}

    def __init__(self, path: str, findings: List[Finding]):
        self.path = path
        self.findings = findings
        self.jitted: Set[str] = set()
        self.sync_helpers: Set[str] = set()

    def collect_jitted(self, tree: ast.Module):
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _dotted(dec) in ("jax.jit", "jit") or (
                            isinstance(dec, ast.Call)
                            and _is_jax_jit(dec)):
                        self.jitted.add(node.name)
                        break
                else:
                    # a plain function whose body syncs (the repo's
                    # hard_sync-style helpers) is itself a sync
                    if any(isinstance(n, ast.Call) and self._is_sync(n)
                           for n in ast.walk(node)):
                        self.sync_helpers.add(node.name)
            elif isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call) and _is_jax_jit(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.jitted.add(tgt.id)

    def _is_clock(self, call: ast.Call) -> bool:
        d = _dotted(call.func)
        # any wall-clock read opens/closes a timed region — clock-mix
        # separately polices WHICH clock trace-clock modules may use
        return d.endswith("perf_counter") or d in ("time.time",
                                                   "time.monotonic")

    def _is_sync(self, call: ast.Call) -> bool:
        func = call.func
        if isinstance(func, ast.Attribute):
            if func.attr in self._SYNCS:
                return True
            if func.attr in ("asarray", "array") and isinstance(
                    func.value, ast.Name) and \
                    func.value.id in _NP_ALIASES:
                return True
        return isinstance(func, ast.Name) and (
            func.id in ("float", "int")
            or func.id in self.sync_helpers)

    def _is_jit_call(self, call: ast.Call) -> bool:
        t = _terminal(call.func)
        if not t:
            return False
        if STEP_FN_RE.match(t):
            return True
        # only BARE names match the jitted set: `eng.run()` must not
        # alias an unrelated local `@jax.jit def run` (the engine
        # drain loop syncs internally every step)
        return isinstance(call.func, ast.Name) and t in self.jitted

    def lint_function(self, fn):
        self.timing_open = False
        self.unsynced = None
        self._walk(fn.body)

    def _walk(self, stmts):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            self._stmt(stmt)
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if sub:
                    self._walk(sub)
            for h in getattr(stmt, "handlers", ()):
                self._walk(h.body)

    def _stmt(self, stmt):
        # calls of THIS statement only (compound bodies walk
        # separately), outermost-first in source order
        sub = {id(s) for attr in ("body", "orelse", "finalbody")
               for s in getattr(stmt, attr, ()) or ()}
        sub |= {id(s) for h in getattr(stmt, "handlers", ())
                for s in h.body}
        calls = [n for n in ast.walk(stmt)
                 if isinstance(n, ast.Call) and id(n) not in sub]
        calls.sort(key=lambda c: (c.lineno, c.col_offset))
        consumed: Set[int] = set()
        opener = (isinstance(stmt, ast.Assign)
                  and isinstance(stmt.value, ast.Call)
                  and self._is_clock(stmt.value))
        for call in calls:
            if id(call) in consumed:
                continue
            if self._is_clock(call):
                if self.timing_open and self.unsynced is not None:
                    # ANY later clock read closes the region — a bare
                    # `t1 = perf_counter()` assignment both closes the
                    # old region and opens the next one
                    self.findings.append(Finding(
                        "jax", "bench-no-sync", self.path,
                        call.lineno, "perf_counter",
                        "timed region closes without syncing the "
                        "jitted call at line %d — this clock measures "
                        "dispatch, not execution (block_until_ready "
                        "the result; round-9 _fixed_batch hazard)"
                        % self.unsynced))
                    self.unsynced = None
                if opener and call is stmt.value:
                    self.timing_open = True
                    self.unsynced = None
            elif self._is_sync(call):
                self.unsynced = None
                for inner in ast.walk(call):
                    if isinstance(inner, ast.Call) and inner is not \
                            call:
                        consumed.add(id(inner))
            elif self._is_jit_call(call) and self.timing_open:
                self.unsynced = call.lineno


def _qualname_functions(tree: ast.Module):
    """Yield (qualname, FunctionDef) for every function, with class
    nesting reflected (``Class.method``)."""
    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                yield prefix + child.name, child
                # nested defs are linted as part of their region root
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, prefix + child.name + ".")
    yield from walk(tree, "")


def lint_source(source: str, rel_path: str,
                region_re: Optional[str] = None,
                clock: Optional[bool] = None,
                bench: Optional[bool] = None) -> List[Finding]:
    """Lint one module.  ``region_re``/``clock``/``bench`` override
    the repo config (fixture tests drive this directly)."""
    tree = ast.parse(source, rel_path)
    findings: List[Finding] = []

    patterns = []
    if region_re is not None:
        patterns.append(re.compile(region_re))
    else:
        patterns.extend(re.compile(rx) for glob, rx in HOT_REGIONS
                        if fnmatch.fnmatch(rel_path, glob))
    if patterns:
        for qualname, fn in _qualname_functions(tree):
            if any(p.match(qualname) for p in patterns):
                linter = _RegionLinter(rel_path, findings)
                for glob, rx, pnames in DEVICE_PARAMS:
                    if fnmatch.fnmatch(rel_path, glob) and \
                            re.match(rx, qualname):
                        linter.tainted.update(pnames)
                linter.visit(fn)

    if clock is None:
        clock = any(fnmatch.fnmatch(rel_path, g) for g in CLOCK_MODULES)
    if clock:
        _ClockLinter(rel_path, findings).visit(tree)

    if bench is None:
        bench = any(fnmatch.fnmatch(rel_path, g) for g in BENCH_MODULES)
    if bench:
        linter = _BenchSyncLinter(rel_path, findings)
        linter.collect_jitted(tree)
        for _, fn in _qualname_functions(tree):
            linter.lint_function(fn)

    return apply_pragmas(findings, source)


def run(root: str, only: Optional[Set[str]] = None) -> List[Finding]:
    """Lint every configured module under ``root``.  ``only``: optional
    set of repo-relative paths (--changed-only)."""
    rels = {glob for glob, _ in HOT_REGIONS} | set(CLOCK_MODULES) \
        | set(BENCH_MODULES)
    seen: Set[str] = set()
    findings: List[Finding] = []
    for pattern in sorted(rels):
        dirname = os.path.dirname(pattern)
        full_dir = os.path.join(root, dirname)
        if not os.path.isdir(full_dir):
            continue
        for name in sorted(os.listdir(full_dir)):
            rel = os.path.join(dirname, name)
            if not fnmatch.fnmatch(rel, pattern) or rel in seen:
                continue
            if only is not None and rel not in only:
                continue
            seen.add(rel)
            with open(os.path.join(root, rel)) as f:
                findings.extend(lint_source(f.read(), rel))
    return findings
