"""graphlint (mxlint analyzer 5) — jaxpr-level audit of the repo's hot
compiled programs.

Analyzers 1–4 check *source*; nothing checked the *compiled programs*
the perf story rides on.  Donation of the paged KV pools, bf16/int8
dtype discipline in the attention paths, and per-program HBM footprints
were enforced only by convention — one refactor that silently drops
``donate_argnums`` doubles serving HBM and no test notices.  graphlint
closes that hole: a **registry** of the repo's hot compiled programs
(:func:`live_programs` — serving step in both kernels, the COW page
copy, GPT ``generate`` and the speculative block, the transformer /
GPT train steps, the Pallas paged-attention wrapper) is traced via
``jax.make_jaxpr`` / ``jax.eval_shape`` on checked-in abstract shapes
(tiny configs, declared right next to each builder — no weights ever
materialize, no program ever compiles or runs), and jaxpr-walk rules
audit the result.

Rules
-----
``graph-donation``  Every arg a :class:`ProgramSpec` declares donated
    must actually be donated AND be in-place-updatable: the lowering
    must carry ``tf.aliasing_output`` on each of its flattened leaves
    (jax only aliases a donated buffer that is shape/dtype-matched to
    an output).  A refactor that drops ``donate_argnums`` — or breaks
    the output match so donation silently stops applying — is a
    finding.

``graph-hbm-budget``  Peak live bytes from a linear-scan live-range
    estimator over the jaxpr (:func:`peak_live_bytes`: inputs live
    from entry to last use, each equation allocates its outputs, a
    value dies after its last consumer; nested jaxprs — pjit / scan /
    while / cond / remat — contribute their own internal peak at their
    program point; ``pallas_call`` bodies are VMEM scratch and are not
    recursed into).  The estimate is compared against the committed
    manifest ``tools/analysis/hbm_budgets.json``: exceeding a
    program's ``budget_bytes``, or growing >10% over its recorded
    ``peak_bytes``, is a finding.  ``--update-budgets`` re-records
    measurements but NEVER relaxes a budget (the perf-gate semantics:
    widening takes a hand edit with justification in review).  The
    numbers are estimates on the registry's tiny abstract shapes — a
    trajectory gate, not a chip measurement.

``graph-dtype-drift``  In a program whose ``dtype_region`` is declared
    (the bf16-compute / int8-KV serving and decode programs), every
    ``convert_element_type`` from bf16/int8 **to f32** must land on a
    declared accumulation point: ``f32_allow`` maps allowed last-dim
    sizes to labels (layer-norm statistics over ``d_model``, the
    f32 logits over ``vocab``, the KV-quantization accumulation over
    ``head_dim``, softmax statistics over the sequence dim).  An
    undeclared upcast — e.g. a refactor that casts the KV pool or a
    gathered page view to f32, materializing a double-width copy every
    step — is a finding, anchored at the offending source line.
    Scalar (rank-0) converts are always allowed; downcasts are not
    policed (they are the intended compute direction).  Known
    boundary: the allowance is a last-dim filter, so an upcast that
    SHARES an accumulation point's last dim — e.g. an f32 copy of the
    (T, d_model) residual stream, indistinguishable by aval from the
    layer-norm statistics upcast and feeding the same mixed consumer
    sets — passes; the rule's target class is the KV/pool/page-view
    upcasts, whose last dims (2·dh, 2, page dims) are distinct from
    every declared point.

``graph-host-sync``  Hot programs must stay host-free: any callback /
    infeed / outfeed / debug-print primitive in the jaxpr (at any
    nesting depth) is a finding — a host round-trip inside the serving
    step or a train step serializes the device on the host every
    iteration.

``graph-sharding-readiness``  (round 14, tensor-parallel serving) The
    engine's DECLARED step-program shardings (``serving/engine.py
    step_input_specs`` — what ``ServingEngine(tp=N)`` lowers through)
    must cover every input: params matching the megatron rules
    (``models/transformer.py param_specs``; int8 ``{"q","s"}`` leaves
    verified against graphlint's own independent derivation of the
    float rule), pools sharding exactly the heads axis over ``tp``,
    host-built rows replicated.  UNCOVERED count must be 0 and covered
    rows must MATCH — a drifted declaration (silent per-step reshard /
    gather) or a new unsharded input fails tier-1.
    :func:`sharding_audit_md` renders the same table into the
    checked-in ``docs/sharding_readiness.md`` (pre-round-14 this was
    the report-mode ROADMAP-1 work-list; now it is a verified
    contract).  The sharded step's registry entry
    (``serving_step_tp``) additionally records per-device (÷tp)
    expected peaks next to its ``hbm_budgets.json`` row.

Scope / suppression: findings go through the shared pragma + baseline
machinery (``findings.py``).  ``--changed-only`` re-traces a program
only when a file in its *recorded trace closure* (the source files its
jaxpr's tracebacks touched on the last ``--update-budgets``, stored in
the manifest) changed; ``--all``, ``--write-baseline`` and
``--update-budgets`` always trace everything.
"""
from __future__ import annotations

import dataclasses
import json
import math
import os
import re
import sys
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from .findings import Finding, apply_pragmas

__all__ = ["ProgramSpec", "spec", "live_programs", "peak_live_bytes",
           "check_program", "run", "update_budgets", "load_budgets",
           "sharding_audit_md", "BUDGETS_PATH", "AUDIT_PATH",
           "GROWTH", "HEADROOM"]

BUDGETS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "hbm_budgets.json")
AUDIT_PATH = "docs/sharding_readiness.md"

# graphlint audits the IMPORTED mxnet_tpu checkout — the one this file
# lives in — whatever --root the caller passes (imports do not follow
# root).  Trace closures are always resolved against this root so a
# foreign --root cannot wipe the recorded closures; runner.main()
# rejects foreign roots for the graphlint write modes outright.
OWN_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

GROWTH = 0.10       # >10% live-bytes growth vs the manifest = finding
HEADROOM = 1.15     # initial budget = ceil(peak * HEADROOM)

# kernel bodies are VMEM-scratch programs (their f32 online-softmax
# accumulators are the declared-by-design accumulation points) — never
# recursed into by any rule
_SKIP_SUBJAXPR = {"pallas_call"}

_CALLBACK_RE = re.compile(r"callback|infeed|outfeed|debug_print")


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    """One registered hot program.

    ``build()`` returns ``(fn, args)``: ``fn`` the LIVE callable from
    the repo module (so a refactor there is what gets audited) and
    ``args`` a tuple of abstract ``ShapeDtypeStruct`` pytrees — the
    checked-in shapes.  ``donate`` lists the positional args the repo
    declares donated (``fn`` must be jitted for the check to run).
    ``dtype_region`` ("bf16"/"int8") turns on drift checking with the
    ``f32_allow`` {last_dim: label} accumulation points.  ``hot``
    enforces host-sync-freedom.  ``path``/``line`` anchor registry-
    level findings (captured at :func:`spec` call sites)."""
    name: str
    build: Callable[[], Tuple[Any, tuple]]
    donate: Tuple[int, ...] = ()
    dtype_region: Optional[str] = None
    f32_allow: Any = None          # {last_dim: label}
    hot: bool = True
    path: str = ""
    line: int = 0
    # files that shape the program WITHOUT leaving traceback frames in
    # the jaxpr (e.g. sharding-spec construction at jit time — the FSDP
    # rule table); merged into the recorded closure so --changed-only
    # re-traces on their edits too (round 19)
    extra_closure: Tuple[str, ...] = ()


def spec(name, build, *, donate=(), dtype_region=None, f32_allow=None,
         hot=True, extra_closure=()):
    """Register a program, anchoring findings at the caller's line."""
    frame = sys._getframe(1)
    return ProgramSpec(name=name, build=build, donate=tuple(donate),
                       dtype_region=dtype_region,
                       f32_allow=dict(f32_allow or {}), hot=hot,
                       path=frame.f_code.co_filename,
                       line=frame.f_lineno,
                       extra_closure=tuple(extra_closure))


# ---------------------------------------------------------------------------
# the live registry — the repo's hot compiled programs, on the
# checked-in abstract shapes below (tiny configs: tracing is abstract,
# nothing allocates or compiles)
# ---------------------------------------------------------------------------

# serving-step registry shapes (the paper's serving config: bf16
# compute, weight-only-int8 params, int8-KV pages, one draft row)
_SLOTS, _PAGE, _CHUNK, _SPEC_K = 2, 4, 4, 1
_GEN_B, _GEN_P, _GEN_NEW = 1, 8, 8
# tensor-parallel serving step (round 14): tp degree of the sharded
# registry entry, and the ÷tp columns of the per-device expected-peak
# manifest rows (both must divide gpt_tiny's 4 heads)
_TP = 2
_PER_DEVICE_TPS = (2, 4)
# FSDP BERT train step (round 19): dp degree of the sharded train
# registry entries, and the dp size the train-audit's shape-aware
# derivation divides against (8 = the virtual tier-1 mesh; it must
# exceed bert_tiny's type_vocab_size=2 so the derivation is forced off
# type_emb's dim 0, the case the regex table also special-cases)
_TRAIN_DP = 2
_AUDIT_DP_SIZE = 8


def _gpt_cfg():
    from mxnet_tpu.models import gpt as G
    return G.gpt_tiny(dtype="bfloat16")


def _serve_geometry(cfg):
    pps = -(-cfg.max_len // _PAGE)
    n_rows = _SLOTS * (1 + _SPEC_K) + _CHUNK
    num_pages = _SLOTS * pps + 1
    return pps, n_rows, num_pages


def _abstract_pools(cfg, num_pages):
    import jax
    import jax.numpy as jnp
    H = cfg.n_heads
    dh = cfg.d_model // H
    return [{"kv": jax.ShapeDtypeStruct((num_pages, _PAGE, H, 2 * dh),
                                        jnp.int8),
             # round-22 tile-shaped scale planes (serving/paged_kv.py)
             "s": jax.ShapeDtypeStruct((num_pages, 2, _PAGE, H),
                                       jnp.float32)}
            for _ in range(cfg.n_layers)]


def _abstract_qparams(cfg):
    import jax
    from mxnet_tpu.models import gpt as G
    return jax.eval_shape(lambda: G.quantize_decode_params(
        G.init_params(jax.random.PRNGKey(0), cfg)))


def _serving_step_args(cfg):
    import jax
    import jax.numpy as jnp
    pps, n_rows, num_pages = _serve_geometry(cfg)
    i32 = jnp.int32
    return (_abstract_qparams(cfg), _abstract_pools(cfg, num_pages),
            jax.ShapeDtypeStruct((n_rows,), i32),
            jax.ShapeDtypeStruct((n_rows,), i32),
            jax.ShapeDtypeStruct((n_rows,), i32),
            jax.ShapeDtypeStruct((n_rows,), jnp.bool_),
            jax.ShapeDtypeStruct((_SLOTS + 1, pps), i32),
            jax.ShapeDtypeStruct((_SLOTS, 1 + _SPEC_K), i32))


def _build_serving_step(kernel):
    from mxnet_tpu.serving.engine import _make_step
    cfg = _gpt_cfg()
    pps, n_rows, _ = _serve_geometry(cfg)
    fn = _make_step(cfg, _SLOTS, n_rows, pps, _PAGE, True,
                    kernel=kernel, n_sample=1 + _SPEC_K)
    return fn, _serving_step_args(cfg)


def build_serving_step_xla():
    return _build_serving_step("xla")


def build_serving_step_pallas():
    return _build_serving_step("pallas")


def build_serving_step_overlap():
    """The latency-hiding step variant (round 21): the SAME live
    ``_make_step`` builder with ``overlap=True`` — two extra inputs
    (the previous step's device-resident ``(S, n_sample)`` argmax
    matrix and the per-row ``tok_src`` selector) and one gather +
    ``where`` at the top of the graph.  Donation of the pools must
    survive the wrapper (the overlap engine runs EVERY step through
    this program, fenced steps included), and its peak is gated
    against its own manifest row — the selector must cost rows, not
    a second resident pool."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.serving.engine import _make_step
    cfg = _gpt_cfg()
    pps, n_rows, _ = _serve_geometry(cfg)
    fn = _make_step(cfg, _SLOTS, n_rows, pps, _PAGE, True,
                    kernel="xla", n_sample=1 + _SPEC_K, overlap=True)
    args = _serving_step_args(cfg) + (
        jax.ShapeDtypeStruct((_SLOTS, 1 + _SPEC_K), jnp.int32),
        jax.ShapeDtypeStruct((n_rows,), jnp.int32))
    return fn, args


def _registry_mesh():
    """The tp mesh the sharded registry entry traces over — the same
    virtual CPU mesh the tier-1 conftest and the MULTICHIP dry-runs
    force (the CLI entry, ``tools/analysis/__main__.py``, requests it
    before jax's backend initializes; library imports deliberately do
    not mutate topology)."""
    import jax
    from mxnet_tpu.parallel.mesh import serving_mesh
    if len(jax.devices()) < _TP:
        raise RuntimeError(
            "graphlint: the serving_step_tp registry entry needs a "
            "%d-device mesh but only %d device(s) are visible — jax "
            "initialized before tools.analysis could request the "
            "virtual CPU mesh; run via `python -m tools.analysis` or "
            "set XLA_FLAGS=--xla_force_host_platform_device_count=8"
            % (_TP, len(jax.devices())))
    return serving_mesh(_TP)


def build_serving_step_tp():
    """The tensor-parallel serving step: the SAME live ``_make_step``
    builder, lowered through a tp=``_TP`` mesh with the engine's
    declared shardings (megatron params, heads-sharded pools,
    replicated host rows).  Donation of the sharded pools must survive
    the lowering — the ``graph-donation`` gate runs on this entry like
    any other."""
    from mxnet_tpu.serving.engine import _make_step
    cfg = _gpt_cfg()
    pps, n_rows, _ = _serve_geometry(cfg)
    args = _serving_step_args(cfg)
    fn = _make_step(cfg, _SLOTS, n_rows, pps, _PAGE, True,
                    kernel="xla", n_sample=1 + _SPEC_K,
                    mesh=_registry_mesh(), params=args[0])
    return fn, args


def build_serving_step_pallas_tp():
    """Round 22: the PALLAS serving step lowered through the tp mesh
    — ``paged_attention`` shard_map'ed so each device walks its 1/tp
    heads slice of the heads-sharded pool (attention collective-free
    per head; the wo psum stays outside the kernel).  Donation of the
    sharded pools must survive BOTH the shard_map and the pallas_call
    inside it, and the per-device peak divides like the XLA tp
    entry's."""
    from mxnet_tpu.serving.engine import _make_step
    cfg = _gpt_cfg()
    pps, n_rows, _ = _serve_geometry(cfg)
    args = _serving_step_args(cfg)
    fn = _make_step(cfg, _SLOTS, n_rows, pps, _PAGE, True,
                    kernel="pallas", n_sample=1 + _SPEC_K,
                    mesh=_registry_mesh(), params=args[0])
    return fn, args


def build_serving_page_install_put():
    """The put-transport install (round 22): page content that
    arrived as zero-copy ``/dev/shm`` views rides a ``device_put``
    into the SAME donated whole-page scatter the socket path runs —
    one program for both transports is the bit-identity argument.
    Registered separately so the zero-copy path's donation is gated
    on its own: a regression that copies the pools here would erase
    exactly the bytes the put saved."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.serving.paged_kv import _make_install
    cfg = _gpt_cfg()
    _, _, num_pages = _serve_geometry(cfg)
    H = cfg.n_heads
    dh = cfg.d_model // H
    b = 4
    base = _make_install(cfg, True, b)
    fn = jax.jit(
        lambda pools, ids, content: base(
            pools, ids, jax.tree_util.tree_map(jnp.asarray, content)),
        donate_argnums=(0,))
    content = [{"kv": jax.ShapeDtypeStruct((b, _PAGE, H, 2 * dh),
                                           jnp.int8),
                "s": jax.ShapeDtypeStruct((b, 2, _PAGE, H),
                                          jnp.float32)}
               for _ in range(cfg.n_layers)]
    return fn, (_abstract_pools(cfg, num_pages),
                jax.ShapeDtypeStruct((b,), jnp.int32), content)


def build_serving_page_install():
    """The disaggregated page-install scatter (round 15): received
    page content lands in the donated pools in place — same
    in-place-update contract as the step program, so its donation and
    HBM peak are gated like the step's (``serving/paged_kv.py
    _make_install``; bucket 4 pages, int8-KV layout)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.serving.paged_kv import _make_install
    cfg = _gpt_cfg()
    _, _, num_pages = _serve_geometry(cfg)
    H = cfg.n_heads
    dh = cfg.d_model // H
    b = 4
    fn = _make_install(cfg, True, b)
    content = [{"kv": jax.ShapeDtypeStruct((b, _PAGE, H, 2 * dh),
                                           jnp.int8),
                "s": jax.ShapeDtypeStruct((b, 2, _PAGE, H),
                                          jnp.float32)}
               for _ in range(cfg.n_layers)]
    return fn, (_abstract_pools(cfg, num_pages),
                jax.ShapeDtypeStruct((b,), jnp.int32), content)


def build_tier_page_restore():
    """The KV-tiering single-page install (round 18): a host-tier
    spill/restore/swap moves pages one (or a small power-of-two run)
    at a time through the SAME donated scatter family as the
    round-15 transfer path, but at bucket 1 — the shape every
    pressure spill's restore and every swap-in resume compiles.  Its
    donation must alias the pools in place (a copy here would double
    the pool bytes at every preemption resume) and its peak is
    budget-gated like the step's."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.serving.paged_kv import _make_install
    cfg = _gpt_cfg()
    _, _, num_pages = _serve_geometry(cfg)
    H = cfg.n_heads
    dh = cfg.d_model // H
    b = 1
    fn = _make_install(cfg, True, b)
    content = [{"kv": jax.ShapeDtypeStruct((b, _PAGE, H, 2 * dh),
                                           jnp.int8),
                "s": jax.ShapeDtypeStruct((b, 2, _PAGE, H),
                                          jnp.float32)}
               for _ in range(cfg.n_layers)]
    return fn, (_abstract_pools(cfg, num_pages),
                jax.ShapeDtypeStruct((b,), jnp.int32), content)


def build_cow_page_copy():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.serving.engine import _make_copy
    cfg = _gpt_cfg()
    _, _, num_pages = _serve_geometry(cfg)
    fn = _make_copy(cfg, True)
    scalar = jax.ShapeDtypeStruct((), jnp.int32)
    return fn, (_abstract_pools(cfg, num_pages), scalar, scalar)


def build_gpt_generate():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt as G
    cfg = _gpt_cfg()
    params = jax.eval_shape(
        lambda: G.init_params(jax.random.PRNGKey(0), cfg))
    key = jax.eval_shape(lambda: jax.random.PRNGKey(0))

    def gen(params, prompt, rng):
        return G.generate(params, cfg, prompt, _GEN_NEW, rng=rng,
                          kv_int8=True)
    return gen, (params,
                 jax.ShapeDtypeStruct((_GEN_B, _GEN_P), jnp.int32), key)


def build_gpt_spec_block():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt as G
    cfg = _gpt_cfg()
    params = jax.eval_shape(
        lambda: G.init_params(jax.random.PRNGKey(0), cfg))
    key = jax.eval_shape(lambda: jax.random.PRNGKey(0))

    def gen(params, prompt, rng):
        return G.generate_speculative(params, cfg, prompt, _GEN_NEW,
                                      K=2, rng=rng, kv_int8=True)
    return gen, (params,
                 jax.ShapeDtypeStruct((_GEN_B, _GEN_P), jnp.int32), key)


def _train_batch(with_labels):
    import jax
    import jax.numpy as jnp
    batch = {"tokens": jax.ShapeDtypeStruct((2, 16), jnp.int32)}
    if with_labels:
        batch["labels"] = jax.ShapeDtypeStruct((2, 16), jnp.int32)
    return batch


def _train_mesh():
    """The dp mesh the FSDP train registry entries lower through —
    same virtual-CPU-mesh contract as :func:`_registry_mesh`."""
    import jax
    from mxnet_tpu.parallel.mesh import make_mesh
    if len(jax.devices()) < _TRAIN_DP:
        raise RuntimeError(
            "graphlint: the bert_train_step_fsdp registry entries need "
            "a %d-device mesh but only %d device(s) are visible — run "
            "via `python -m tools.analysis` or set XLA_FLAGS="
            "--xla_force_host_platform_device_count=8"
            % (_TRAIN_DP, len(jax.devices())))
    return make_mesh({"dp": _TRAIN_DP},
                     devices=list(jax.devices())[:_TRAIN_DP])


def _bert_fsdp_cfg(param_dtype):
    from mxnet_tpu.models import transformer as T
    return T.bert_tiny(use_flash=False, remat=False, dropout=0.0,
                       dtype=("float32" if param_dtype == "float32"
                              else "bfloat16"),
                       param_dtype=param_dtype)


def _build_bert_train_fsdp(param_dtype):
    """The FSDP BERT pretrain step (round 19, ROADMAP 5): the live
    ``make_train_step(fsdp=True)`` builder lowered through a
    dp=``_TRAIN_DP`` mesh with params + optimizer moments sharded by
    the ``parallel/fsdp.py`` rule table.  Donation of the (params,
    opt_state) tuple must survive the sharded lowering — the state is
    updated in place every step, and a dropped donation doubles
    resident training HBM exactly like the serving-pool case.  The
    abstract state is built from the same ``init_params`` /
    ``optax.adamw().init`` pair the live ``init_state`` materializes
    (eval_shape only; the adamw state STRUCTURE does not depend on
    hyperparameters)."""
    import jax
    import optax
    from mxnet_tpu.models import transformer as T
    cfg = _bert_fsdp_cfg(param_dtype)
    _, step = T.make_train_step(cfg, mesh=_train_mesh(), fsdp=True)
    params = jax.eval_shape(
        lambda: T.init_params(jax.random.PRNGKey(0), cfg))
    opt = jax.eval_shape(optax.adamw(1e-4).init, params)
    key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    return step, ((params, opt), _train_batch(True), key)


def build_bert_train_step_fsdp():
    return _build_bert_train_fsdp("float32")


def build_bert_train_step_fsdp_bf16():
    return _build_bert_train_fsdp("bfloat16")


def build_bert_train_step_fsdp_bucketed():
    """The bucketed-overlap FSDP step (round 21): the live
    ``make_train_step(fsdp=True, bucket_overlap=True)`` — backward
    runs as a manual ``lax.scan`` over layers with each layer's
    reduce-scatter carried INSIDE the scan body, so the collective
    overlaps the next layer's grad math instead of fusing into one
    tail allreduce.  Donation of (params, opt_state) must survive the
    scan-carried lowering, and its peak is gated against its own
    manifest row — the scan carry must not duplicate the grad
    accumulator."""
    import jax
    import optax
    from mxnet_tpu.models import transformer as T
    cfg = _bert_fsdp_cfg("float32")
    _, step = T.make_train_step(cfg, mesh=_train_mesh(), fsdp=True,
                                bucket_overlap=True)
    params = jax.eval_shape(
        lambda: T.init_params(jax.random.PRNGKey(0), cfg))
    opt = jax.eval_shape(optax.adamw(1e-4).init, params)
    key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    return step, ((params, opt), _train_batch(True), key)


def build_transformer_train_step():
    import jax
    from mxnet_tpu.models import transformer as T
    init_state, step = T.make_train_step(T.bert_tiny())
    key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    state = jax.eval_shape(init_state, key)
    return step, (state, _train_batch(True), key)


def build_gpt_train_step():
    import jax
    from mxnet_tpu.models import gpt as G
    init_state, step = G.make_train_step(G.gpt_tiny())
    key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    state = jax.eval_shape(init_state, key)
    return step, (state, _train_batch(False), key)


def build_paged_attention_kernel():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.kernels.paged_attention import paged_attention
    cfg = _gpt_cfg()
    H = cfg.n_heads
    dh = cfg.d_model // H
    pps, n_rows, num_pages = _serve_geometry(cfg)

    def attend(q, kv, s, bt, pos):
        return paged_attention(q, kv, s, bt, pos, page_size=_PAGE)
    fn = jax.jit(attend)
    return fn, (jax.ShapeDtypeStruct((n_rows, H, dh), jnp.bfloat16),
                jax.ShapeDtypeStruct((num_pages, _PAGE, H, 2 * dh),
                                     jnp.int8),
                jax.ShapeDtypeStruct((num_pages, _PAGE, H, 2),
                                     jnp.float32),
                jax.ShapeDtypeStruct((n_rows, pps), jnp.int32),
                jax.ShapeDtypeStruct((n_rows,), jnp.int32))


def live_programs() -> List[ProgramSpec]:
    """The audited registry.  Declared accumulation points
    (``f32_allow`` last dims, gpt_tiny geometry): 64 = ``d_model``
    (layer-norm statistics), 1024 = ``vocab`` (f32 logits), 16 =
    ``head_dim`` (KV-quantization accumulation — ``models/gpt.py
    _kv_quantize`` upcasts k/v once and computes scale + grid in f32),
    8 = the prompt/sequence dim (softmax statistics on the prefill's
    jnp attention reference)."""
    cfg = _gpt_cfg()
    dh = cfg.d_model // cfg.n_heads
    acc = {cfg.d_model: "ln-stats", cfg.vocab_size: "logits",
           dh: "quant-acc"}
    gen_acc = dict(acc)
    gen_acc[_GEN_P] = "softmax-stats"
    return [
        spec("serving_step", build_serving_step_xla, donate=(1,),
             dtype_region="int8", f32_allow=acc),
        spec("serving_step_pallas", build_serving_step_pallas,
             donate=(1,), dtype_region="int8", f32_allow=acc),
        # round 21: the overlap (tok_src) step variant — every step
        # of an overlap engine runs through it, so its donation and
        # budget are gated exactly like the serial program's
        spec("serving_step_overlap", build_serving_step_overlap,
             donate=(1,), dtype_region="int8", f32_allow=acc),
        spec("serving_step_tp", build_serving_step_tp, donate=(1,),
             dtype_region="int8", f32_allow=acc),
        # round 22: the mesh-lowered PALLAS step — the chip-ready
        # data path; donation through shard_map + pallas_call gated
        # like the XLA tp entry, per-device peak recorded ÷tp
        spec("serving_step_pallas_tp2", build_serving_step_pallas_tp,
             donate=(1,), dtype_region="int8", f32_allow=acc,
             extra_closure=("mxnet_tpu/parallel/mesh.py",)),
        spec("cow_page_copy", build_cow_page_copy, donate=(0,),
             dtype_region="int8", f32_allow={}),
        spec("serving_page_install", build_serving_page_install,
             donate=(0,), dtype_region="int8", f32_allow={}),
        # round 22: the same install scatter as the put transport
        # drives it (device_put of mapped segment views)
        spec("serving_page_install_put",
             build_serving_page_install_put,
             donate=(0,), dtype_region="int8", f32_allow={},
             extra_closure=("mxnet_tpu/serving/transport.py",
                            "mxnet_tpu/serving/page_streamer.py")),
        spec("tier_page_restore", build_tier_page_restore,
             donate=(0,), dtype_region="int8", f32_allow={}),
        spec("gpt_generate", build_gpt_generate,
             dtype_region="int8", f32_allow=gen_acc),
        spec("gpt_spec_block", build_gpt_spec_block,
             dtype_region="int8", f32_allow=gen_acc),
        spec("paged_attention_kernel", build_paged_attention_kernel,
             dtype_region="int8", f32_allow={}),
        # train steps deliberately carry no dtype_region: the AMP
        # master-weight pattern (bf16 compute, f32 params/optimizer)
        # upcasts at every param boundary by design
        spec("transformer_train_step", build_transformer_train_step,
             donate=(0,)),
        spec("gpt_train_step", build_gpt_train_step),
        # round 19 (ROADMAP 5): the FSDP BERT pretrain step, lowered
        # through the dp mesh with rule-table-sharded params + moments
        # — donation of (params, opt_state) gated like the serving
        # pools', f32 and bf16-param variants
        spec("bert_train_step_fsdp", build_bert_train_step_fsdp,
             donate=(0,),
             extra_closure=("mxnet_tpu/parallel/fsdp.py",
                            "mxnet_tpu/parallel/mesh.py")),
        spec("bert_train_step_fsdp_bf16",
             build_bert_train_step_fsdp_bf16, donate=(0,),
             extra_closure=("mxnet_tpu/parallel/fsdp.py",
                            "mxnet_tpu/parallel/mesh.py")),
        # round 21: the layer-bucketed reduce-scatter-overlap step —
        # scan-carried collectives; donation gated like the fused one
        spec("bert_train_step_fsdp_bucketed",
             build_bert_train_step_fsdp_bucketed, donate=(0,),
             extra_closure=("mxnet_tpu/parallel/fsdp.py",
                            "mxnet_tpu/parallel/mesh.py")),
    ]


# ---------------------------------------------------------------------------
# jaxpr plumbing
# ---------------------------------------------------------------------------

def _sub_jaxprs(eqn):
    """Yield nested (Closed)Jaxprs of an equation — pjit / scan /
    while / cond / remat / custom_* bodies; ``pallas_call`` is
    deliberately opaque (VMEM-scratch kernel internals)."""
    from jax import core
    if eqn.primitive.name in _SKIP_SUBJAXPR:
        return
    for v in eqn.params.values():
        if isinstance(v, core.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, core.Jaxpr):
            yield v
        elif isinstance(v, (list, tuple)):
            for x in v:
                if isinstance(x, core.ClosedJaxpr):
                    yield x.jaxpr
                elif isinstance(x, core.Jaxpr):
                    yield x


def _walk_eqns(jaxpr):
    """Depth-first over every equation at every nesting level."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from _walk_eqns(sub)


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * dtype.itemsize


def peak_live_bytes(jaxpr) -> int:
    """Linear-scan live-range estimate of a jaxpr's peak live bytes.

    Inputs/consts are live from entry to their last use, each equation
    allocates its outputs, and a value dies after its last consumer
    (program outputs live to the end).  An equation with nested
    jaxprs contributes the nested peak *beyond its own operands* at
    that program point (for ``cond``/``while``/``scan`` that is the
    worst branch / one iteration — per-iteration temporaries do not
    accumulate).  Donation is not modeled: a donated buffer counts on
    both sides of its update for the one equation where old and new
    overlap, which XLA aliases away — a deliberate, deterministic
    overestimate.  The point is the trajectory, not the absolute
    number."""
    from jax import core
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    n = len(jaxpr.eqns)
    last: Dict[Any, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if isinstance(v, core.Var):
                last[v] = i
    for v in jaxpr.outvars:
        if isinstance(v, core.Var):
            last[v] = n
    live = 0
    seen: Set[Any] = set()
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        if v not in seen:
            seen.add(v)
            live += _aval_bytes(v.aval)
    peak = live
    for i, eqn in enumerate(jaxpr.eqns):
        inner = 0
        for sub in _sub_jaxprs(eqn):
            operand = sum(_aval_bytes(v.aval) for v in sub.invars)
            inner = max(inner, max(0, peak_live_bytes(sub) - operand))
        alloc = 0
        for v in eqn.outvars:
            if not isinstance(v, core.DropVar):
                alloc += _aval_bytes(v.aval)
        live += alloc
        peak = max(peak, live + inner)
        freed = 0
        dead: Set[Any] = set()
        for v in eqn.invars:
            if isinstance(v, core.Var) and v not in dead \
                    and last.get(v) == i:
                dead.add(v)
                freed += _aval_bytes(v.aval)
        for v in eqn.outvars:
            if not isinstance(v, core.DropVar) and v not in last:
                freed += _aval_bytes(v.aval)   # produced, never read
        live -= freed
    return peak


def _repo_frame(eqn, root) -> Optional[Tuple[str, int]]:
    """Innermost traceback frame inside the repo, as (relpath, line)."""
    tb = eqn.source_info.traceback
    if tb is None:
        return None
    root = os.path.abspath(root) + os.sep
    for f in tb.frames:
        name = f.file_name
        if name.startswith(root) and "site-packages" not in name:
            return os.path.relpath(name, root[:-1]), f.line_num
    return None


def _trace_closure(jaxpr, root) -> Set[str]:
    """Repo-relative LIBRARY files the trace touched (the program's
    recorded trace closure, for ``--changed-only`` scoping).  Only
    ``mxnet_tpu/`` files qualify: traceback frames also carry the
    driver stack (the CLI runner, a test file, whatever invoked the
    trace), which would make the closure depend on who ran the update.
    Changes under ``tools/analysis`` always re-trace everything via
    :func:`_needs_trace`, so the infra needs no closure entry."""
    root = OWN_ROOT          # the traced modules live HERE (imports
    root_abs = os.path.abspath(root) + os.sep   # ignore --root)
    files: Set[str] = set()
    for eqn in _walk_eqns(getattr(jaxpr, "jaxpr", jaxpr)):
        tb = eqn.source_info.traceback
        if tb is None:
            continue
        for f in tb.frames:
            name = f.file_name
            if name.startswith(root_abs) and "site-packages" not in name:
                rel = os.path.relpath(name, root)
                if rel.startswith("mxnet_tpu" + os.sep):
                    files.add(rel)
    return files


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------

def _rel(path, root) -> str:
    path = os.path.abspath(path)
    root = os.path.abspath(root)
    try:
        rel = os.path.relpath(path, root)
    except ValueError:
        return path
    return path if rel.startswith("..") else rel


def _check_donation(sp, fn, args, jaxpr, root, findings):
    import jax
    from collections import Counter
    if not sp.donate:
        return
    low = fn.lower(*args)
    info_args, _ = low.args_info
    n_aliased = low.as_text().count("tf.aliasing_output")
    # output avals come from the jaxpr check_program already traced —
    # no third abstract trace
    out_count = Counter((tuple(a.shape), str(a.dtype))
                        for a in jaxpr.out_avals)
    n_before = len(findings)
    for argnum in sp.donate:
        infos = jax.tree_util.tree_leaves(info_args[argnum])
        dropped = [i for i in infos if not i.donated]
        if dropped:
            findings.append(Finding(
                "graph", "graph-donation", _rel(sp.path, root),
                sp.line, "%s.arg%d" % (sp.name, argnum),
                "declared donated arg %d is NOT donated (%d/%d leaves "
                "undonated) — donate_argnums dropped?  Serving HBM "
                "doubles when the pools stop updating in place"
                % (argnum, len(dropped), len(infos))))
            continue
        unmatched = [i for i in infos
                     if out_count[(tuple(i.shape),
                                   str(i.dtype))] == 0]
        if unmatched:
            findings.append(Finding(
                "graph", "graph-donation", _rel(sp.path, root),
                sp.line, "%s.arg%d" % (sp.name, argnum),
                "declared donated arg %d is not in-place-updatable: "
                "%d/%d leaves have no shape/dtype-matched output, so "
                "donation silently stops applying"
                % (argnum, len(unmatched), len(infos))))
            continue
    # aliasing backstop: expected count spans EVERY donated leaf in
    # the lowering (not just registry-declared args) — otherwise an
    # alias newly established on some other donated arg could mask a
    # lost alias on a declared one
    expect_alias = sum(
        1 for arg in info_args
        for i in jax.tree_util.tree_leaves(arg)
        if i.donated and out_count[(tuple(i.shape),
                                    str(i.dtype))] > 0)
    if len(findings) == n_before and n_aliased < expect_alias:
        findings.append(Finding(
            "graph", "graph-donation", _rel(sp.path, root), sp.line,
            sp.name,
            "donation declared and output-matched but the lowering "
            "established only %d/%d input-output aliases — an unused "
            "donated input or an aliasing regression"
            % (n_aliased, expect_alias)))


def _check_budget(sp, jaxpr, budgets, root, findings) -> int:
    peak = peak_live_bytes(jaxpr)
    entry = (budgets or {}).get("programs", {}).get(sp.name)
    sym = sp.name
    if entry is None:
        findings.append(Finding(
            "graph", "graph-hbm-budget", _rel(sp.path, root), sp.line,
            sym, "no hbm_budgets.json entry (peak-live estimate %d "
            "bytes) — run python -m tools.analysis --update-budgets"
            % peak))
    elif peak > entry["budget_bytes"]:
        findings.append(Finding(
            "graph", "graph-hbm-budget", _rel(sp.path, root), sp.line,
            sym, "peak live bytes %d exceed the committed budget %d "
            "(manifest peak %d) — shrink the program or justify a "
            "hand-edited budget" % (peak, entry["budget_bytes"],
                                    entry["peak_bytes"])))
    elif peak > int(entry["peak_bytes"] * (1 + GROWTH)):
        findings.append(Finding(
            "graph", "graph-hbm-budget", _rel(sp.path, root), sp.line,
            sym, "peak live bytes %d grew >%d%% over the manifest's %d "
            "— re-record with --update-budgets if intended"
            % (peak, int(GROWTH * 100), entry["peak_bytes"])))
    return peak


def _check_dtype_drift(sp, jaxpr, root, findings):
    if sp.dtype_region is None:
        return
    allow = sp.f32_allow or {}
    for eqn in _walk_eqns(getattr(jaxpr, "jaxpr", jaxpr)):
        if eqn.primitive.name != "convert_element_type":
            continue
        src = eqn.invars[0].aval
        dst = eqn.outvars[0].aval
        if str(getattr(dst, "dtype", "")) != "float32":
            continue
        if str(getattr(src, "dtype", "")) not in ("bfloat16", "int8"):
            continue
        shape = src.shape
        if len(shape) == 0 or shape[-1] in allow:
            continue
        loc = _repo_frame(eqn, root) or (_rel(sp.path, root), sp.line)
        findings.append(Finding(
            "graph", "graph-dtype-drift", loc[0], loc[1],
            "%s:%s->f32:last=%d" % (sp.name, src.dtype, shape[-1]),
            "undeclared f32 upcast of a %s %s tensor inside the %s "
            "region of %s (declared accumulation last-dims: %s) — pin "
            "the accumulation dtype or declare the point in the "
            "registry" % (src.dtype, "x".join(map(str, shape)),
                          sp.dtype_region, sp.name,
                          sorted(allow) or "none")))


def _check_host_sync(sp, jaxpr, root, findings):
    if not sp.hot:
        return
    for eqn in _walk_eqns(getattr(jaxpr, "jaxpr", jaxpr)):
        name = eqn.primitive.name
        if _CALLBACK_RE.search(name):
            loc = _repo_frame(eqn, root) or (_rel(sp.path, root),
                                             sp.line)
            findings.append(Finding(
                "graph", "graph-host-sync", loc[0], loc[1],
                "%s:%s" % (sp.name, name),
                "host primitive `%s` inside hot program %s — a host "
                "round-trip per step serializes the device on the "
                "host" % (name, sp.name)))


def check_program(sp: ProgramSpec, root: str,
                  budgets: Optional[Dict] = None) -> List[Finding]:
    """Trace one registered program and run every rule over it."""
    import jax
    fn, args = sp.build()
    jaxpr = jax.make_jaxpr(fn)(*args)
    findings: List[Finding] = []
    _check_donation(sp, fn, args, jaxpr, root, findings)
    _check_budget(sp, jaxpr, budgets, root, findings)
    _check_dtype_drift(sp, jaxpr, root, findings)
    _check_host_sync(sp, jaxpr, root, findings)
    return findings


# ---------------------------------------------------------------------------
# manifest + runner entry points
# ---------------------------------------------------------------------------

def _per_device_expected_peaks(sp, peak: int) -> Optional[Dict]:
    """Per-device (÷tp) expected peaks for the serving step programs,
    recorded next to their manifest entries (round 14).

    The estimator discounts the INPUTS the engine declares tp-sharded
    (heads-sharded pools + megatron-sharded params, from
    ``step_input_specs``): per_device(tp) = peak − sharded_bytes +
    ceil(sharded_bytes / tp).  Intermediates are conservatively left
    replicated (GSPMD shards most of them too, and the XLA gather
    path's merged (T·H) view does re-gather heads), so the number is
    an upper-bound trajectory gate like ``peak_bytes`` itself — the
    point it pins is that the DOMINANT resident state (pools +
    weights) divides by tp.

    Recorded for every mesh-lowerable step entry — round 22 made the
    Pallas step one of them (``paged_attention`` shard_maps over the
    mesh, each device walking its 1/tp heads slice), so its manifest
    row carries ÷tp numbers like the XLA entries'."""
    if sp.name not in ("serving_step", "serving_step_tp",
                       "serving_step_pallas_tp2"):
        return None
    import jax
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.serving import engine as E
    cfg = _gpt_cfg()
    args = _serving_step_args(cfg)
    declared = E.step_input_specs(args[0], cfg, kv_int8=True)
    leaves = jax.tree_util.tree_leaves(args)
    specs = jax.tree_util.tree_leaves(
        declared, is_leaf=lambda x: isinstance(x, P))
    assert len(leaves) == len(specs)
    sharded = sum(_aval_bytes(leaf)
                  for leaf, spec in zip(leaves, specs)
                  if "tp" in tuple(spec))
    return {"tp%d" % tp: int(peak - sharded + math.ceil(sharded / tp))
            for tp in _PER_DEVICE_TPS}


def load_budgets(path: str = None) -> Dict:
    path = path or BUDGETS_PATH
    if not os.path.exists(path):
        return {"version": 1, "programs": {}}
    with open(path) as f:
        return json.load(f)


def _needs_trace(sp, budgets, only: Set[str]) -> bool:
    """--changed-only: a program re-traces when any file in its
    recorded trace closure changed (no recorded closure, or an
    analysis-infra change, always re-traces)."""
    if any(p.startswith("tools/analysis") for p in only):
        return True
    entry = (budgets or {}).get("programs", {}).get(sp.name)
    closure = (entry or {}).get("closure")
    if not closure:
        return True
    # extra_closure unions at READ time only — the stored closure
    # stays a pure trace record (one mechanism, not two)
    return bool((set(closure) | set(sp.extra_closure)) & only)


def run(root: str, only: Optional[Set[str]] = None,
        budgets_path: Optional[str] = None,
        specs: Optional[List[ProgramSpec]] = None,
        budgets: Optional[Dict] = None) -> List[Finding]:
    """Audit every registered program; pragma-filtered findings."""
    if budgets is None:
        budgets = load_budgets(budgets_path)
    if specs is None:
        specs = live_programs()
    findings: List[Finding] = []
    for sp in specs:
        if only is not None and not _needs_trace(sp, budgets, only):
            continue
        findings.extend(check_program(sp, root, budgets))
    # the sharding-readiness rule scopes with the serving step: it
    # re-audits whenever the step program would re-trace (engine /
    # model / analysis-infra changes), or always on a full run
    step_sp = [sp for sp in specs if sp.name == "serving_step"]
    if step_sp and (only is None
                    or _needs_trace(step_sp[0], budgets, only)):
        findings.extend(sharding_readiness_findings(root))
    # the train half (round 19) scopes with the FSDP train step the
    # same way — transformer / parallel.fsdp / analysis-infra changes
    train_sp = [sp for sp in specs if sp.name == "bert_train_step_fsdp"]
    if train_sp and (only is None
                     or _needs_trace(train_sp[0], budgets, only)):
        findings.extend(train_sharding_readiness_findings(root))
    by_path: Dict[str, List[Finding]] = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)
    out: List[Finding] = []
    for path, fs in sorted(by_path.items()):
        full = os.path.join(root, path)
        if os.path.exists(full):
            with open(full) as fh:
                fs = apply_pragmas(fs, fh.read())
        out.extend(fs)
    return out


def update_budgets(root: str, path: Optional[str] = None,
                   specs: Optional[List[ProgramSpec]] = None) -> Dict:
    """Re-measure every program (ALWAYS full scope) and rewrite the
    manifest.  ``peak_bytes`` and the trace closure re-record;
    ``budget_bytes`` only ever ratchets DOWN (min of the old budget
    and ceil(peak * HEADROOM)) — the perf-gate never-relax rule.  A
    program whose peak now exceeds its committed budget stays a
    finding until the budget is hand-edited with justification."""
    import jax
    path = path or BUDGETS_PATH
    old = load_budgets(path).get("programs", {})
    programs: Dict[str, Dict] = {}
    for sp in (specs if specs is not None else live_programs()):
        fn, args = sp.build()
        jaxpr = jax.make_jaxpr(fn)(*args)
        peak = peak_live_bytes(jaxpr)
        cand = int(math.ceil(peak * HEADROOM))
        prev = old.get(sp.name)
        budget = cand if prev is None else min(prev["budget_bytes"],
                                               cand)
        programs[sp.name] = {
            "peak_bytes": peak,
            "budget_bytes": budget,
            "closure": sorted(_trace_closure(jaxpr, root)),
        }
        per_dev = _per_device_expected_peaks(sp, peak)
        if per_dev is not None:
            programs[sp.name]["per_device_expected_peak_bytes"] = \
                per_dev
    data = {"version": 1, "programs": programs}
    with open(path, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    return data


# ---------------------------------------------------------------------------
# sharding-readiness audit (report mode)
# ---------------------------------------------------------------------------

def _partition_rules(cfg):
    """Megatron param rules as {tree-path: spec-string}, from
    ``models/transformer.py param_shardings`` over a mesh built by
    ``parallel/mesh.py`` (tp axis present; size irrelevant for the
    rule table)."""
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.parallel.mesh import make_mesh
    import jax
    # dp absorbs whatever devices the host exposes (tier-1 runs with
    # a virtual 8-device CPU mesh); only the axis NAMES matter here
    mesh = make_mesh({"dp": -1, "tp": 1})
    shardings = T.param_shardings(cfg, mesh)
    rules: Dict[str, str] = {}
    for path, ns in jax.tree_util.tree_flatten_with_path(shardings)[0]:
        rules[jax.tree_util.keystr(path)] = "P%s" % (tuple(ns.spec),)
    return rules


def _agg_path(keystr_path: str) -> str:
    """Collapse per-layer indices so the table lists each rule once."""
    return re.sub(r"\[(\d+)\]", "[*]", keystr_path)


def _spec_str(p) -> str:
    return "P%s" % (tuple(p),)


def _derived_spec_strs(rule: str, leaf_key: str) -> Dict[str, str]:
    """Expected declared specs for an int8 ``{"q","s"}`` pair whose
    float weight carries ``rule`` (a ``_spec_str``): ``q`` inherits
    the 2-D rule; the 1-D scale ``s`` takes the rule entry of the dim
    it indexes — per-ROW for the embedding table (``q_rows``), per-
    COLUMN for everything else (``q_cols``).  This is graphlint's OWN
    derivation, independent of ``models/gpt.py decode_param_specs`` —
    the audit verifies the engine's declaration against it."""
    entries = [e.strip() for e in rule[2:-1].rstrip(",").split(",")]
    entries += ["None"] * (2 - len(entries))
    pick = entries[0] if leaf_key.startswith("['tok_emb']") \
        else entries[1]
    return {"q": rule, "s": "P(%s,)" % pick}


_AUDIT_INPUT_NAMES = ["params", "pools", "tokens", "row_slot",
                      "row_pos", "row_live", "bt", "slot_rows"]


def _sharding_rows(cfg):
    """Audit core: every step-program input leaf with its ENGINE-
    DECLARED spec (``serving/engine.py step_input_specs``) verified
    against the megatron rule table.  Returns (rows, counts) where
    counts = {covered, derived, uncovered, mismatched}; a MISMATCH or
    UNCOVERED row is a ``graph-sharding-readiness`` finding."""
    import jax
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.serving import engine as E

    rules = _partition_rules(cfg)
    args = _serving_step_args(cfg)
    declared = E.step_input_specs(args[0], cfg, kv_int8=True)
    # per-pool-key heads axis: kv (pages, page_size, H, 2*dh) shards
    # axis 2; the round-22 tile-shaped scale planes (pages, 2,
    # page_size, H) shard axis 3 — graphlint derives the expectation
    # from the pool layout itself, independent of the engine's table
    heads_axis_by_key = {"kv": 2, "s": 3}

    rows: List[Tuple[str, str, str, int, str]] = []
    counts = {"covered": 0, "derived": 0, "uncovered": 0,
              "mismatched": 0}
    seen: Set[Tuple[str, str]] = set()
    is_p = lambda x: isinstance(x, P)       # noqa: E731
    for name, arg, dec in zip(_AUDIT_INPUT_NAMES, args, declared):
        leaves = jax.tree_util.tree_flatten_with_path(arg)[0]
        specs = jax.tree_util.tree_flatten_with_path(
            dec, is_leaf=is_p)[0]
        if len(leaves) != len(specs):
            raise RuntimeError(
                "graphlint: declared sharding tree for %r does not "
                "match the program input tree (%d leaves vs %d "
                "specs)" % (name, len(leaves), len(specs)))
        for (path, leaf), (spath, spec) in zip(leaves, specs):
            ks = jax.tree_util.keystr(path)
            if jax.tree_util.keystr(spath) != ks:
                raise RuntimeError(
                    "graphlint: declared spec path %s != input leaf "
                    "path %s under %r"
                    % (jax.tree_util.keystr(spath), ks, name))
            agg = name + _agg_path(ks)
            shape = "x".join(map(str, leaf.shape)) or "scalar"
            if (agg, shape) in seen:
                continue
            seen.add((agg, shape))
            nbytes = _aval_bytes(leaf)
            decs = _spec_str(spec)
            if name == "params":
                expect, how = None, None
                if ks in rules:
                    expect, how = rules[ks], "covered"
                else:
                    m = re.match(r"(.*)\['([qs])'\]$", ks)
                    if m and m.group(1) in rules:
                        expect = _derived_spec_strs(
                            rules[m.group(1)], m.group(1))[m.group(2)]
                        how = "derived(%s)" % m.group(2)
                if expect is None:
                    status = "UNCOVERED — no megatron rule for the " \
                        "declared %s" % decs
                    counts["uncovered"] += 1
                elif decs != expect:
                    status = "MISMATCH — engine declares %s, rule " \
                        "says %s" % (decs, expect)
                    counts["mismatched"] += 1
                elif how == "covered":
                    status = "covered: %s" % decs
                    counts["covered"] += 1
                else:
                    status = "%s: %s from %s" % (how, decs,
                                                 rules[m.group(1)])
                    counts["derived"] += 1
            elif name == "pools":
                entries = tuple(spec)
                m = re.search(r"\['(kv|s)'\]$", ks)
                heads_axis = heads_axis_by_key[m.group(1)] if m else 2
                ok = (len(entries) > heads_axis
                      and entries[heads_axis] == "tp"
                      and all(e is None for i, e in enumerate(entries)
                              if i != heads_axis))
                if ok:
                    status = ("covered: %s — engine-declared, pages "
                              "shard the HEADS axis; block tables / "
                              "free lists / prefix trie stay "
                              "host-side" % decs)
                    counts["covered"] += 1
                else:
                    status = ("MISMATCH — pools must shard exactly "
                              "the heads axis over tp, engine "
                              "declares %s" % decs)
                    counts["mismatched"] += 1
            else:
                if tuple(spec) == ():
                    status = ("covered: P() — engine-declared, "
                              "replicated host-built row/table input")
                    counts["covered"] += 1
                else:
                    status = ("MISMATCH — host-built inputs must "
                              "replicate, engine declares %s" % decs)
                    counts["mismatched"] += 1
            rows.append((agg, shape, str(leaf.dtype), nbytes, status))
    return rows, counts


def sharding_readiness_findings(root: str) -> List[Finding]:
    """The ``graph-sharding-readiness`` rule (round 14): the engine's
    declared step-program shardings (``step_input_specs``) must cover
    EVERY input — params matching the megatron rules (int8 q/s
    derived), pools heads-sharded, host rows replicated.  UNCOVERED
    count must be 0 and covered rows must MATCH; the checked-in
    ``docs/sharding_readiness.md`` renders the same table."""
    import inspect
    from mxnet_tpu.serving import engine as E
    try:
        line = inspect.getsourcelines(E.step_input_specs)[1]
    except (OSError, TypeError):
        line = 1
    path = "mxnet_tpu/serving/engine.py"
    findings: List[Finding] = []
    _, counts = _sharding_rows(_gpt_cfg())
    if counts["uncovered"]:
        findings.append(Finding(
            "graph", "graph-sharding-readiness", path, line,
            "step_input_specs.uncovered",
            "%d serving-step input group(s) have no declared/derivable"
            " sharding — the step program cannot lower through the "
            "mesh for them (see docs/sharding_readiness.md)"
            % counts["uncovered"]))
    if counts["mismatched"]:
        findings.append(Finding(
            "graph", "graph-sharding-readiness", path, line,
            "step_input_specs.mismatch",
            "%d serving-step input group(s) declare shardings that "
            "contradict the megatron rule table / pool layout — "
            "params would silently reshard (or gather) every step"
            % counts["mismatched"]))
    return findings


# ---------------------------------------------------------------------------
# train-step sharding audit (round 19 — the ROADMAP-5 closing criterion)
# ---------------------------------------------------------------------------

def _train_fsdp_derivation(cfg):
    """graphlint's OWN shape-aware derivation of the FSDP composition,
    independent of the ``parallel/fsdp.py`` regex rule table the
    declaration binds: for every param leaf, ``dp`` lands on the FIRST
    dim the megatron rule (``models/transformer.py param_specs``)
    leaves free whose size divides the audit dp degree; a leaf with no
    free divisible dim composes ``dp`` as a sub-axis of its smallest
    already-sharded dim (tp partitions first, dp subdivides the
    shard).  Two independent routes to the same table — a rule-table
    edit that silently changes a param's placement is a MISMATCH."""
    import jax
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.models import transformer as T

    base = T.param_specs(cfg, tp="tp")
    shapes = jax.eval_shape(
        lambda: T.init_params(jax.random.PRNGKey(0), cfg))
    is_p = lambda x: isinstance(x, P)       # noqa: E731
    base_leaves, treedef = jax.tree_util.tree_flatten(base, is_leaf=is_p)
    shape_leaves = jax.tree_util.tree_leaves(shapes)
    assert len(base_leaves) == len(shape_leaves)
    out = []
    for spec_, leaf in zip(base_leaves, shape_leaves):
        ndim = len(leaf.shape)
        entries = list(spec_)[:ndim]
        entries += [None] * (ndim - len(entries))
        for i in range(ndim):
            if entries[i] is None \
                    and leaf.shape[i] % _AUDIT_DP_SIZE == 0:
                entries[i] = "dp"
                break
        else:
            for i in range(ndim):
                if entries[i] is not None \
                        and leaf.shape[i] % _AUDIT_DP_SIZE == 0:
                    cur = entries[i]
                    entries[i] = (cur + ("dp",)
                                  if isinstance(cur, tuple)
                                  else (cur, "dp"))
                    break
        out.append(P(*entries))
    return jax.tree_util.tree_unflatten(treedef, out)


def _train_sharding_rows(cfg):
    """Audit core for the train step: every declared input spec
    (``models/transformer.py train_step_input_specs`` — what
    ``make_train_step(fsdp=True)`` lowers through) verified against
    the independent derivation; batch rows must shard exactly the
    batch dim over dp, the rng replicates, and the declared OUTPUT
    param specs must equal the input ones (the donation / no-reshard
    contract).  Returns (rows, counts)."""
    import jax
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.models import transformer as T

    counts = {"covered": 0, "mismatched": 0, "uncovered": 0}
    rows: List[Tuple[str, str, str, int, str]] = []
    try:
        declared, batch_specs, rng_spec = T.train_step_input_specs(
            cfg, tp="tp")
    except Exception as e:                  # rule-table gap
        counts["uncovered"] += 1
        rows.append(("params", "-", "-", 0,
                     "UNCOVERED — train_step_input_specs failed: %s"
                     % e))
        return rows, counts
    derived = _train_fsdp_derivation(cfg)
    shapes = jax.eval_shape(
        lambda: T.init_params(jax.random.PRNGKey(0), cfg))
    is_p = lambda x: isinstance(x, P)       # noqa: E731
    dec_leaves = jax.tree_util.tree_flatten_with_path(
        declared, is_leaf=is_p)[0]
    der_leaves = jax.tree_util.tree_flatten_with_path(
        derived, is_leaf=is_p)[0]
    shp_leaves = jax.tree_util.tree_flatten_with_path(shapes)[0]
    seen: Set[Tuple[str, str]] = set()
    for (dpath, dec), (_, der), (_, leaf) in zip(dec_leaves, der_leaves,
                                                 shp_leaves):
        agg = "params" + _agg_path(jax.tree_util.keystr(dpath))
        shape = "x".join(map(str, leaf.shape)) or "scalar"
        if (agg, shape) in seen:
            continue
        seen.add((agg, shape))
        decs, ders = _spec_str(dec), _spec_str(der)
        if decs == ders:
            status = ("covered: %s — rule table and shape-aware "
                      "derivation agree" % decs)
            counts["covered"] += 1
        else:
            status = ("MISMATCH — declared %s, derivation says %s"
                      % (decs, ders))
            counts["mismatched"] += 1
        rows.append((agg, shape, str(leaf.dtype), _aval_bytes(leaf),
                     status))
    for name, spec_ in sorted(batch_specs.items()):
        entries = tuple(spec_)
        ok = (len(entries) >= 1 and entries[0] == "dp"
              and all(e is None for e in entries[1:]))
        if ok:
            status = "covered: %s — batch dim sharded over dp" \
                % _spec_str(spec_)
            counts["covered"] += 1
        else:
            status = ("MISMATCH — batch inputs must shard exactly the "
                      "batch dim over dp, declared %s"
                      % _spec_str(spec_))
            counts["mismatched"] += 1
        rows.append(("batch['%s']" % name, "B x T", "-", 0, status))
    if tuple(rng_spec) == ():
        rows.append(("rng", "key", "-", 0,
                     "covered: P() — replicated step key"))
        counts["covered"] += 1
    else:
        rows.append(("rng", "key", "-", 0,
                     "MISMATCH — the step rng must replicate, "
                     "declared %s" % _spec_str(rng_spec)))
        counts["mismatched"] += 1
    out_p, out_loss = T.train_step_output_specs(cfg, tp="tp")
    out_ok = (jax.tree_util.tree_structure(
                  out_p, is_leaf=is_p) == jax.tree_util.tree_structure(
                  declared, is_leaf=is_p)
              and all(_spec_str(a) == _spec_str(b) for (_, a), (_, b)
                      in zip(jax.tree_util.tree_flatten_with_path(
                                 out_p, is_leaf=is_p)[0],
                             dec_leaves))
              and tuple(out_loss) == ())
    if out_ok:
        rows.append(("out: (params', loss)", "-", "-", 0,
                     "covered: params keep the input placement "
                     "(donation contract), loss replicates"))
        counts["covered"] += 1
    else:
        rows.append(("out: (params', loss)", "-", "-", 0,
                     "MISMATCH — output params must keep EXACTLY the "
                     "input placement (a drifted out spec forces a "
                     "reshard every step and breaks donation)"))
        counts["mismatched"] += 1
    return rows, counts


def _train_audit_cfg():
    from mxnet_tpu.models import transformer as T
    return T.bert_tiny(use_flash=False, remat=False, dropout=0.0)


def train_sharding_readiness_findings(root: str) -> List[Finding]:
    """The train half of ``graph-sharding-readiness`` (round 19): the
    FSDP train step's DECLARED in/out specs must cover every param
    (regex rule table agreeing with the shape-aware derivation), shard
    the batch over dp, replicate the rng, and keep the output params
    on the input placement."""
    import inspect
    from mxnet_tpu.models import transformer as T
    try:
        line = inspect.getsourcelines(T.train_step_input_specs)[1]
    except (OSError, TypeError):
        line = 1
    path = "mxnet_tpu/models/transformer.py"
    findings: List[Finding] = []
    _, counts = _train_sharding_rows(_train_audit_cfg())
    if counts["uncovered"]:
        findings.append(Finding(
            "graph", "graph-sharding-readiness", path, line,
            "train_step_input_specs.uncovered",
            "%d train-step input group(s) have no declared/derivable "
            "sharding — the FSDP step cannot lower through the mesh "
            "for them (see docs/sharding_readiness.md)"
            % counts["uncovered"]))
    if counts["mismatched"]:
        findings.append(Finding(
            "graph", "graph-sharding-readiness", path, line,
            "train_step_input_specs.mismatch",
            "%d train-step input/output group(s) declare shardings "
            "that contradict the FSDP composition of the megatron "
            "rule table — params would silently reshard (or gather "
            "full-size) every step" % counts["mismatched"]))
    return findings


def sharding_audit_md(root: str) -> str:
    """The ServingEngine step-program input audit: every input leaf
    with its engine-declared sharding, verified against the megatron
    rules."""
    rows, counts = _sharding_rows(_gpt_cfg())
    lines = [
        "# Sharding readiness — ServingEngine step program",
        "",
        "Report-mode output of graphlint's sharding-readiness audit: "
        "for every",
        "input of the serving step program (registry shapes: gpt_tiny, "
        "%d slots," % _SLOTS,
        "page_size %d, spec_K %d, int8 weights + int8-KV), the "
        "ENGINE'S DECLARED" % (_PAGE, _SPEC_K),
        "sharding (`serving/engine.py step_input_specs` — what "
        "`ServingEngine(tp=N)`",
        "lowers the step through) verified against the megatron "
        "partition rules",
        "(`models/transformer.py param_shardings` over a "
        "`parallel/mesh.py` mesh).",
        "Round 13 this table was the ROADMAP-1 work-list (8 UNCOVERED "
        "groups:",
        "pools + host row vectors); round 14 landed tensor-parallel "
        "serving and",
        "the audit now VERIFIES the engine's declarations — UNCOVERED "
        "or",
        "MISMATCH rows fail tier-1 via the `graph-sharding-readiness` "
        "rule.",
        "",
        "Regenerate: `python -m tools.analysis "
        "--write-sharding-audit`",
        "(`tests/test_static_analysis.py` pins this file current; "
        "`tools/run_static_analysis.sh --changed-only` regenerates it "
        "when",
        "serving/ or models/ change).",
        "",
        "| input | shape | dtype | bytes | partition rule |",
        "|---|---|---|---|---|",
    ]
    for agg, shape, dtype, nbytes, status in rows:
        lines.append("| `%s` | %s | %s | %d | %s |"
                     % (agg, shape, dtype, nbytes, status))
    lines += [
        "",
        "**Summary:** %d covered, %d derived (int8 q/s from the float "
        "rule)," % (counts["covered"], counts["derived"]),
        "UNCOVERED count: %d, mismatched: %d.  Params follow the "
        "megatron rules" % (counts["uncovered"], counts["mismatched"]),
        "(weights tp-sharded, norms/biases-on-unsharded-dims "
        "replicated), the",
        "paged KV pools shard the heads axis over tp (each device "
        "holds 1/tp of",
        "every page), and the host-built row/table int32 vectors "
        "replicate.",
        "Per-device expected peaks for the sharded step live in",
        "`tools/analysis/hbm_budgets.json` "
        "(`per_device_expected_peak_bytes`).",
        "",
    ]
    t_rows, t_counts = _train_sharding_rows(_train_audit_cfg())
    lines += [
        "# Sharding readiness — FSDP BERT train step (round 19)",
        "",
        "The train half of the audit (the ROADMAP-5 closing "
        "criterion): for every",
        "input of the FSDP pretrain step "
        "(`models/transformer.py make_train_step(fsdp=True)`,",
        "bert_tiny shapes, dp composed with tp), the DECLARED "
        "shardings",
        "(`train_step_input_specs` / `train_step_output_specs`) "
        "verified against",
        "graphlint's own shape-aware derivation from the megatron "
        "table — dp on the",
        "first free dim that divides dp=%d, sub-axis composition "
        "when none is free." % _AUDIT_DP_SIZE,
        "The `parallel/fsdp.py` regex rule table and this derivation "
        "are independent",
        "routes; MISMATCH or UNCOVERED rows fail tier-1 via "
        "`graph-sharding-readiness`.",
        "",
        "| input | shape | dtype | bytes | partition rule |",
        "|---|---|---|---|---|",
    ]
    for agg, shape, dtype, nbytes, status in t_rows:
        lines.append("| `%s` | %s | %s | %d | %s |"
                     % (agg, shape, dtype, nbytes, status))
    lines += [
        "",
        "**Summary:** %d covered, UNCOVERED count: %d, mismatched: "
        "%d.  Params and" % (t_counts["covered"],
                             t_counts["uncovered"],
                             t_counts["mismatched"]),
        "param-shaped optimizer moments hold exactly 1/dp per device "
        "(asserted against",
        "live `addressable_shards` in `tests/test_train_scale.py`); "
        "the batch shards its",
        "leading dim over dp; updated params keep the input placement "
        "(donation).",
        "",
    ]
    return "\n".join(lines)
