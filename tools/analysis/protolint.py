"""Wire-protocol & process-lifecycle analyzer (mxlint analyzer 6 —
ISSUE 12 tentpole).

Round 15 made the serving stack a multi-process distributed system:
router, prefill and decode workers exchange ~20 stringly-typed message
kinds (``conn.send("kind", {...}, bufs)`` over the ``parallel/dist.py``
raw-frame wire), dispatched by hand-written ``elif kind ==`` chains and
fenced against zombie incarnations by per-handler gen checks.  Nothing
machine-checked that the two processes agree on the protocol: a kind
nobody handles is a silent drop, a meta key one side stopped sending is
a runtime ``KeyError`` mid-serve, a handler that forgets the gen fence
re-admits a zombie incarnation, and a dropped request/reply pairing is
a distributed stall.  This pass AST-models the per-role protocol and
checks those agreements statically, the way the C-ABI pass checks the
header against the ctypes table.

The protocol model
------------------
Endpoints are the classes in :data:`ROLES` (``DisaggServingCluster`` =
the router process, ``_DisaggWorker`` = a worker process — prefill and
decode share one dispatch, and the peer fetch server is the same
class's data plane).  A **send site** is either a literal-kind
``X.send("kind", meta, bufs)`` call or the deferred-send tuple idiom
``(conn_expr, ("kind", meta, bufs))`` (what ``_dispatch_locked``
returns for ``_do_sends`` to perform outside the lock).  The send's
**target** role is ``router`` when the receiver expression mentions a
role name (``self.router.send``), else ``worker`` (the router only
ever talks to workers; worker→worker is the peer data plane).  A
**dispatch arm** is any comparison of the handler's kind variable
(a parameter named ``kind``, a name unpacked at position 0 of a
``.recv()`` result, or ``got[0]``) against a string literal — the
``elif kind ==`` chains, the handshake guards
(``if got[0] != "ready": raise``), and conditional-expression tests
all count.  Kinds starting with ``_`` are in-process synthetic
(``_wake``/``_lost`` ride the worker inbox, never the wire) and are
excluded from the model.

Rules
-----
``proto-unhandled-kind``  A kind is sent to a role with no dispatch
    arm anywhere in that role — the frame would be silently dropped
    (or, in a handshake window, kill the connection).  Fires at the
    send site.

``proto-unknown-kind``  A dispatch arm for a kind no peer ever sends —
    dead protocol surface that drifts out of date unnoticed.  Fires at
    the arm.

``proto-meta-schema``  Every meta key a handler reads via ``meta["k"]``
    or defaultless ``meta.get("k")`` — directly in the arm, through
    same-class calls the arm passes the meta dict into, or through the
    queue hand-off idiom (``self.q.put((meta, ...))`` →
    ``self.q.get()``) — must be present at every send site of that
    kind whose meta resolves to a dict literal.  Schema drift between
    processes is today a runtime KeyError mid-serve.  Fires at the
    drifted send site, once per missing key.

``proto-gen-fence``  A handler for any kind whose send sites carry an
    incarnation gen (a ``gen``-named meta key, a value read off a
    ``.gen`` field, or the ``srid`` convention — ``srid`` is
    ``(rid, gen)`` by protocol contract) must contain a gen-fence
    comparison (an operand derived from the meta's gen/srid, or
    naming a ``gen`` field) and must not mutate state before it.  The
    PR-10 zombie fence becomes a checked invariant, not a convention.

``proto-reply-pairing``  Request/reply kinds — inferred by name:
    ``fetch``/``fetch_reply`` (K → ``K_reply``) and
    ``stats_req``/``stats`` (``K_req`` → K), both sides must exist in
    the model — must reach a reply send **on every exit edge of the
    replying function, exception edges included**: an early return or
    an unprotected may-raise call before the reply attempt is a
    distributed stall (the requester waits out its full timeout for a
    reply that will never come).  A reply send inside ``try/except``
    counts as the attempt — a dead peer excuses the reply, a local
    exception does not.  The obligation follows the queue hand-off
    (the fetch arm enqueues; ``_serve_fetches`` owes the reply from
    the dequeue on).

``py-resource-lifecycle``  pylocklint's ``py-ref-leak`` exit-edge
    machinery, generalized to OS resources: a ``Connection`` /
    ``Listener`` / ``Process`` / socket / non-daemon ``Thread`` bound
    to a local name must, on every exit path including exception
    edges, be settled — closed/joined/terminated, stored into owned
    state, returned, or handed to another call (ownership transfer).
    Threads constructed ``daemon=True`` are exempt (the repo's
    watchdog/recv threads are self-reaping by design); Processes are
    NOT — a pid needs reaping however the process exits.  Also:
    ``X.terminate()`` with no later ``X.join()`` in the same function
    leaves a zombie pid for the router's lifetime.

Approximations (documented, in the pylocklint tradition):

* Meta dicts are tracked only while they stay the dispatch variable —
  a meta stored into a request record and read back later
  (``st["meta"]["decode"]``) is invisible to the schema rule; the
  audit table documents the full send-side schema regardless.
* Send sites whose meta is not a dict literal (directly or via a
  single same-function ``meta = {...}`` assignment, ``dict(k=v)``
  also resolves) are skipped by the schema rule, never guessed.
* A ``try`` protects its body's exception edges when it has a handler
  that does not just re-raise; handler bodies are not themselves
  walked for the obligation.
* Calls resolve through ``self`` and unique module-level names only —
  ambiguous names contribute no edge.

The audit (``--write-protocol-audit`` → ``docs/protocol.md``) renders
the whole model as a per-kind table — sender→receiver roles, send
site(s), handler site(s), meta schema, bufs layout, gen fence — and is
pinned current by tier-1 exactly like ``docs/sharding_readiness.md``.

Scoping: the protocol lives in ``mxnet_tpu/serving/`` over the
``parallel/dist.py`` wire; ``--changed-only`` re-analyzes only when
serving/, ``parallel/dist.py``, or ``tools/analysis/`` change (and
then reports findings in changed files, like pylocklint — tier-1
always runs full scope).
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding, apply_pragmas

__all__ = ["ROLES", "PACKAGES", "AUDIT_PATH", "analyze", "lint_source",
           "run", "build_model", "protocol_audit_md"]

# repo-relative package roots holding the protocol endpoints
PACKAGES = ["mxnet_tpu/serving"]

# --changed-only trigger set: prefixes + exact files
TRIGGER_PREFIXES = ("mxnet_tpu/serving/", "tools/analysis/")
TRIGGER_FILES = ("mxnet_tpu/parallel/dist.py",)

AUDIT_PATH = "docs/protocol.md"

# The declared topology (the registry idiom graphlint also uses):
# endpoint class -> role.  Fixtures pass their own mapping.
ROLES: Dict[str, str] = {"DisaggServingCluster": "router",
                         "_DisaggWorker": "worker"}

# resource constructors the lifecycle rule tracks (terminal call name)
RESOURCE_CTORS = {"Connection", "Listener", "Process", "Thread",
                  "connect", "create_connection", "socket"}
# settle methods on a tracked resource name
_SETTLE_METHODS = {"close", "join", "terminate", "kill", "shutdown",
                   "release"}

# calls treated as non-raising by the exit-edge walkers
_SAFE_NAME_CALLS = {"len", "min", "max", "int", "float", "bool",
                    "str", "repr", "list", "tuple", "set", "dict",
                    "sorted", "enumerate", "zip", "abs", "range",
                    "isinstance", "id", "getattr", "hasattr", "sum",
                    "any", "all", "print", "type"}
_SAFE_ATTR_CALLS = {"get", "append", "appendleft", "pop", "popleft",
                    "discard", "add", "items", "values", "keys",
                    "update", "extend", "clear", "perf_counter",
                    "release", "copy", "setdefault", "put",
                    "put_nowait", "set", "is_set", "getpid"}


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _may_raise(stmt: ast.AST) -> Optional[int]:
    """Line of the first call in ``stmt`` that can raise (whitelisted
    builtins and obviously-safe methods excluded)."""
    for n in ast.walk(stmt):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        if isinstance(f, ast.Name) and f.id in _SAFE_NAME_CALLS:
            continue
        if isinstance(f, ast.Attribute) and f.attr in _SAFE_ATTR_CALLS:
            continue
        return n.lineno
    return None


def _try_protects(stmt: ast.Try) -> bool:
    """A try protects its body's exception edges when it has a handler
    that does not just re-raise (the handler redirects the edge and
    execution continues after the try).  A bare try/finally does NOT
    protect — the exception propagates past the finally."""
    for h in stmt.handlers:
        if not (len(h.body) == 1 and isinstance(h.body[0], ast.Raise)
                and h.body[0].exc is None):
            return True
    return False


# ---------------------------------------------------------------------------
# model records
# ---------------------------------------------------------------------------
class SendSite:
    __slots__ = ("kind", "mod", "line", "cls", "role", "target",
                 "keys", "carries_gen", "bufs", "fnqual")

    def __init__(self, kind, mod, line, cls, role, target, keys,
                 carries_gen, bufs, fnqual):
        self.kind = kind
        self.mod = mod
        self.line = line
        self.cls = cls
        self.role = role            # sender role
        self.target = target        # receiver role
        self.keys = keys            # frozenset | None (unresolvable)
        self.carries_gen = carries_gen
        self.bufs = bufs            # short source descriptor
        self.fnqual = fnqual


class Arm:
    __slots__ = ("kind", "mod", "line", "cls", "role", "fnqual",
                 "span", "required", "optional", "has_fence",
                 "fence_line", "early_mut_line", "reach")

    def __init__(self, kind, mod, line, cls, role, fnqual, span):
        self.kind = kind
        self.mod = mod
        self.line = line
        self.cls = cls
        self.role = role
        self.fnqual = fnqual
        self.span = span            # (lo, hi) line range of the arm
        self.required: Set[str] = set()
        self.optional: Set[str] = set()
        self.has_fence = False
        self.fence_line: Optional[int] = None
        self.early_mut_line: Optional[int] = None
        self.reach: Set[str] = set()   # reachable same-class fn quals


class _Fn:
    __slots__ = ("qual", "mod", "cls", "name", "node", "role")

    def __init__(self, qual, mod, cls, name, node, role):
        self.qual = qual
        self.mod = mod
        self.cls = cls
        self.name = name
        self.node = node
        self.role = role


class _Module:
    def __init__(self, rel: str, source: str):
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, rel)


# ---------------------------------------------------------------------------
# per-function protocol scan
# ---------------------------------------------------------------------------
class _FnScan:
    """Everything protolint needs from one function body: kind tests,
    meta reads, gen-fence compares, mutations, meta-passing calls,
    queue puts, and send sites."""

    def __init__(self, prog: "_Program", fn: _Fn,
                 extra_meta: Tuple[str, ...] = (),
                 extra_gen: Tuple[str, ...] = ()):
        self.prog = prog
        self.fn = fn
        node = fn.node
        args = node.args
        params = [a.arg for a in args.posonlyargs + args.args
                  + args.kwonlyargs]
        self.kind_vars: Set[str] = {p for p in params if p == "kind"}
        self.meta_vars: Set[str] = {p for p in params if p == "meta"}
        self.meta_vars.update(extra_meta)
        self.recv_vars: Set[str] = set()
        # seeded gen-derived names (callee params bound from gen reads)
        self.gen_vars: Set[str] = set(extra_gen)
        # collected events, all (line, ...) in source order
        self.reads: List[Tuple[int, str, bool]] = []   # line, key, req
        self.fences: List[int] = []
        self.mutations: List[int] = []
        self.kind_tests: List[Tuple[int, str, str, ast.AST]] = []
        # meta-passing call edges: (line, callee qual, param name)
        self.meta_calls: List[Tuple[int, str, Optional[str]]] = []
        # plain same-class call edges: (line, callee qual)
        self.calls: List[Tuple[int, str]] = []
        # queue puts of the meta var: (line, queue attr, position)
        self.qputs: List[Tuple[int, str, int]] = []
        self._collect_vars()
        self._collect()

    # -- variable discovery -------------------------------------------
    def _collect_vars(self):
        """recv-result names and (kind, meta) unpack targets."""
        for n in ast.walk(self.fn.node):
            if not isinstance(n, ast.Assign):
                continue
            v = n.value
            is_recv = isinstance(v, ast.Call) and isinstance(
                v.func, ast.Attribute) and v.func.attr == "recv"
            for tgt in n.targets:
                if is_recv and isinstance(tgt, ast.Name):
                    self.recv_vars.add(tgt.id)
        # unpacks of recv vars: kind, meta, bufs = got
        for n in ast.walk(self.fn.node):
            if not isinstance(n, ast.Assign):
                continue
            v = n.value
            src_is_recv = (
                isinstance(v, ast.Name) and v.id in self.recv_vars
            ) or (isinstance(v, ast.Call)
                  and isinstance(v.func, ast.Attribute)
                  and v.func.attr == "recv")
            if not src_is_recv:
                continue
            for tgt in n.targets:
                if isinstance(tgt, ast.Tuple) and len(tgt.elts) >= 2:
                    e0, e1 = tgt.elts[0], tgt.elts[1]
                    if isinstance(e0, ast.Name) and e0.id != "_":
                        self.kind_vars.add(e0.id)
                    if isinstance(e1, ast.Name) and e1.id != "_":
                        self.meta_vars.add(e1.id)

    def _is_kind_expr(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.kind_vars
        if isinstance(node, ast.Subscript) and isinstance(
                node.value, ast.Name) and \
                node.value.id in self.recv_vars:
            s = node.slice
            return isinstance(s, ast.Constant) and s.value == 0
        return False

    def _is_meta_expr(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.meta_vars
        if isinstance(node, ast.Subscript) and isinstance(
                node.value, ast.Name) and \
                node.value.id in self.recv_vars:
            s = node.slice
            return isinstance(s, ast.Constant) and s.value == 1
        return False

    # -- event collection ---------------------------------------------
    def _meta_read(self, node: ast.AST) -> Optional[Tuple[str, bool]]:
        """(key, required) when ``node`` reads a meta key."""
        if isinstance(node, ast.Subscript) and \
                self._is_meta_expr(node.value):
            k = _str_const(node.slice)
            if k is not None:
                return k, True
        if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) and node.func.attr == "get" \
                and self._is_meta_expr(node.func.value) and node.args:
            k = _str_const(node.args[0])
            if k is not None:
                return k, len(node.args) < 2
        return None

    def _expr_gen_derived(self, expr: ast.AST) -> bool:
        """Does ``expr`` carry incarnation-gen information?  A meta
        read of a gen/srid key, a name previously derived from one, or
        anything naming a ``gen`` field."""
        for n in ast.walk(expr):
            r = self._meta_read(n)
            if r is not None and ("gen" in r[0] or r[0] == "srid"):
                return True
            if isinstance(n, ast.Name) and n.id in self.gen_vars:
                return True
            if isinstance(n, ast.Name) and "gen" in n.id:
                return True
            if isinstance(n, ast.Attribute) and "gen" in n.attr:
                return True
            if isinstance(n, ast.Subscript):
                k = _str_const(n.slice)
                if k is not None and "gen" in k:
                    return True
        return False

    def _resolve_call(self, call: ast.Call) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Attribute) and isinstance(
                f.value, ast.Name) and f.value.id == "self" \
                and self.fn.cls:
            qual = "%s::%s.%s" % (self.fn.mod, self.fn.cls, f.attr)
            if qual in self.prog.fns:
                return qual
        elif isinstance(f, ast.Name):
            quals = self.prog.by_name.get(f.id, [])
            if len(quals) == 1:
                return quals[0]
        return None

    def _collect(self):
        fn = self.fn
        for node in ast.walk(fn.node):
            line = getattr(node, "lineno", 0)
            r = self._meta_read(node)
            if r is not None:
                self.reads.append((line, r[0], r[1]))
            if isinstance(node, ast.Compare):
                self._on_compare(node)
            if isinstance(node, ast.Assign):
                # gen-derived propagation: key = tuple(meta["srid"])
                if self._expr_gen_derived(node.value):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            self.gen_vars.add(tgt.id)
                for tgt in node.targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        self.mutations.append(line)
            elif isinstance(node, (ast.AugAssign, ast.Delete)):
                tgts = node.targets if isinstance(node, ast.Delete) \
                    else [node.target]
                for tgt in tgts:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        self.mutations.append(line)
            elif isinstance(node, ast.Expr) and isinstance(
                    node.value, ast.Call):
                f = node.value.func
                if isinstance(f, ast.Attribute):
                    root = f.value
                    while isinstance(root, (ast.Attribute,
                                            ast.Subscript)):
                        root = root.value
                    if isinstance(root, ast.Name) and \
                            root.id == "self" and \
                            f.attr not in ("send", "recv", "close"):
                        self.mutations.append(line)
            if isinstance(node, ast.Call):
                qual = self._resolve_call(node)
                if qual is not None and qual != fn.qual:
                    self.calls.append((line, qual))
                    pname = self._meta_param_for(node, qual)
                    if pname is not None:
                        self.meta_calls.append((line, qual, pname))
                self._on_qput(node, line)

    def _on_compare(self, node: ast.Compare):
        line = node.lineno
        left = node.left
        op = node.ops[0]
        comp = node.comparators[0]
        if self._is_kind_expr(left) and isinstance(
                op, (ast.Eq, ast.NotEq, ast.In, ast.NotIn)):
            lits: List[str] = []
            k = _str_const(comp)
            if k is not None:
                lits.append(k)
            elif isinstance(comp, (ast.Tuple, ast.List)):
                lits.extend(s for s in map(_str_const, comp.elts)
                            if s is not None)
            kindop = "eq" if isinstance(op, (ast.Eq, ast.In)) \
                else "ne"
            for k in lits:
                self.kind_tests.append((line, k, kindop, node))
        # gen fence: any compare with a gen-derived operand
        if any(self._expr_gen_derived(side)
               for side in [node.left] + list(node.comparators)):
            self.fences.append(line)

    def _meta_param_for(self, call: ast.Call,
                        qual: str) -> Optional[str]:
        """When the call passes the dispatch meta dict itself, return
        the callee parameter name it binds to."""
        callee = self.prog.fns[qual].node
        cargs = callee.args
        names = [a.arg for a in cargs.posonlyargs + cargs.args]
        if names and names[0] == "self":
            names = names[1:]
        for i, a in enumerate(call.args):
            if self._is_meta_expr(a) and i < len(names):
                return names[i]
        for kw in call.keywords:
            if kw.arg and self._is_meta_expr(kw.value):
                return kw.arg
        return None

    def _on_qput(self, call: ast.Call, line: int):
        f = call.func
        if not (isinstance(f, ast.Attribute)
                and f.attr in ("put", "put_nowait")
                and isinstance(f.value, ast.Attribute)
                and isinstance(f.value.value, ast.Name)
                and f.value.value.id == "self"):
            return
        if not call.args or not isinstance(call.args[0], ast.Tuple):
            return
        for i, e in enumerate(call.args[0].elts):
            if self._is_meta_expr(e):
                self.qputs.append((line, f.value.attr, i))
                return


# ---------------------------------------------------------------------------
# whole-model construction
# ---------------------------------------------------------------------------
class _Program:
    def __init__(self, modules: Dict[str, str],
                 roles: Optional[Dict[str, str]] = None):
        self.roles = dict(ROLES if roles is None else roles)
        self.role_names = set(self.roles.values())
        self.modules = {rel: _Module(rel, src)
                        for rel, src in sorted(modules.items())}
        self.fns: Dict[str, _Fn] = {}
        self.by_name: Dict[str, List[str]] = {}
        self._collect_fns()
        self.scans: Dict[Tuple[str, Tuple[str, ...]], _FnScan] = {}
        self.sends: List[SendSite] = []
        self.arms: List[Arm] = []
        self.findings: List[Finding] = []
        self._collect_sends()
        self._collect_arms()

    # ------------------------------------------------------ helpers --
    def _collect_fns(self):
        for mod in self.modules.values():
            def walk(node, cls, outer):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        if outer is not None:
                            continue      # nested defs ride the parent
                        qual = "%s::%s%s" % (
                            mod.rel, cls + "." if cls else "",
                            child.name)
                        self.fns[qual] = _Fn(
                            qual, mod.rel, cls, child.name, child,
                            self.roles.get(cls))
                        self.by_name.setdefault(child.name,
                                                []).append(qual)
                        walk(child, cls, qual)
                    elif isinstance(child, ast.ClassDef):
                        walk(child, child.name, outer)
                    else:
                        walk(child, cls, outer)
            walk(mod.tree, None, None)

    def scan(self, qual: str,
             extra_meta: Tuple[str, ...] = ()) -> _FnScan:
        key = (qual, tuple(sorted(extra_meta)))
        if key not in self.scans:
            self.scans[key] = _FnScan(self, self.fns[qual],
                                      extra_meta)
        return self.scans[key]

    def _add(self, rule, mod, line, symbol, msg):
        self.findings.append(Finding("proto", rule, mod, line,
                                     symbol, msg))

    def _target_of(self, role: str, recv_expr: ast.AST) -> str:
        d = _dotted(recv_expr).lower()
        for r in sorted(self.role_names):
            if r in d:
                return r
        # default topology: everything else is a worker-side conn
        # (the router only talks to workers; worker↔worker is the
        # peer data plane)
        return "worker" if "worker" in self.role_names else role

    # ---------------------------------------------------- send sites --
    def _resolve_meta_keys(self, expr: Optional[ast.AST],
                           fnnode: ast.AST,
                           line: int) -> Tuple[Optional[frozenset],
                                               bool]:
        """(keys, carries_gen) for a send's meta expression; keys is
        None when unresolvable."""
        if expr is None or (isinstance(expr, ast.Constant)
                            and expr.value is None):
            return frozenset(), False
        if isinstance(expr, ast.Name):
            # nearest preceding `name = {...}` in the same function
            best = None
            for n in ast.walk(fnnode):
                if isinstance(n, ast.Assign) and n.lineno < line:
                    for tgt in n.targets:
                        if isinstance(tgt, ast.Name) and \
                                tgt.id == expr.id:
                            if best is None or n.lineno > best.lineno:
                                best = n
            if best is not None:
                return self._resolve_meta_keys(best.value, fnnode,
                                               line)
            return None, False
        if isinstance(expr, ast.Dict):
            keys: Set[str] = set()
            gen = False
            for k, v in zip(expr.keys, expr.values):
                ks = _str_const(k) if k is not None else None
                if ks is None:
                    return None, self._values_gen(expr)
                keys.add(ks)
                if "gen" in ks or ks == "srid":
                    gen = True
                if not gen and self._values_gen(v):
                    gen = True
            return frozenset(keys), gen
        if isinstance(expr, ast.Call) and isinstance(
                expr.func, ast.Name) and expr.func.id == "dict" \
                and not expr.args:
            keys = {kw.arg for kw in expr.keywords if kw.arg}
            gen = any("gen" in k or k == "srid" for k in keys) or \
                any(self._values_gen(kw.value)
                    for kw in expr.keywords)
            return frozenset(keys), gen
        return None, self._values_gen(expr)

    @staticmethod
    def _values_gen(expr: ast.AST) -> bool:
        for n in ast.walk(expr):
            if isinstance(n, ast.Attribute) and "gen" in n.attr:
                return True
            if isinstance(n, ast.Subscript):
                k = _str_const(n.slice)
                if k is not None and "gen" in k:
                    return True
        return False

    @staticmethod
    def _bufs_desc(expr: Optional[ast.AST]) -> str:
        if expr is None:
            return "—"
        if isinstance(expr, (ast.List, ast.Tuple)):
            return "—" if not expr.elts else str(len(expr.elts))
        if isinstance(expr, ast.Name):
            return expr.id
        return _dotted(expr) or "expr"

    def _collect_sends(self):
        for qual, fn in sorted(self.fns.items()):
            if fn.role is None:
                continue
            for node in ast.walk(fn.node):
                kind = recv = meta = bufs = None
                line = getattr(node, "lineno", 0)
                if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) and \
                        node.func.attr == "send" and node.args:
                    kind = _str_const(node.args[0])
                    recv = node.func.value
                    meta = node.args[1] if len(node.args) > 1 else None
                    bufs = node.args[2] if len(node.args) > 2 else None
                elif isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) and \
                        node.func.attr == "_send_pages_frame" and \
                        len(node.args) >= 3:
                    # round 22: the put-or-socket page-frame wrapper —
                    # semantically `args[0].send(args[1], args[2],
                    # args[3])`, with the transport choosing between
                    # inline bufs and a shm-segment `put` meta key
                    kind = _str_const(node.args[1])
                    recv = node.args[0]
                    meta = node.args[2]
                    bufs = node.args[3] if len(node.args) > 3 else None
                elif isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) and \
                        node.func.attr == "send_caps":
                    # round 22: the data-plane capability handshake —
                    # `conn.send("caps", {"put": put_capability()})`
                    # wrapped in transport.Connection.send_caps
                    kind = "caps"
                    recv = node.func.value
                    meta = ast.Dict(keys=[ast.Constant("put")],
                                    values=[ast.Constant(None)])
                elif isinstance(node, ast.Tuple) and \
                        len(node.elts) == 2 and \
                        isinstance(node.elts[1], ast.Tuple) and \
                        2 <= len(node.elts[1].elts) <= 3 and \
                        _str_const(node.elts[1].elts[0]) is not None:
                    inner = node.elts[1].elts
                    kind = _str_const(inner[0])
                    recv = node.elts[0]
                    meta = inner[1]
                    bufs = inner[2] if len(inner) == 3 else None
                if kind is None or kind.startswith("_"):
                    continue
                keys, gen = self._resolve_meta_keys(meta, fn.node,
                                                    line)
                self.sends.append(SendSite(
                    kind, fn.mod, line, fn.cls, fn.role,
                    self._target_of(fn.role, recv), keys, gen,
                    self._bufs_desc(bufs), qual))

    # -------------------------------------------------------- arms ---
    def _collect_arms(self):
        for qual, fn in sorted(self.fns.items()):
            if fn.role is None:
                continue
            scan = self.scan(qual)
            if not scan.kind_tests:
                continue
            tests = sorted(scan.kind_tests, key=lambda t: t[0])
            test_lines = sorted({t[0] for t in tests})
            fn_end = fn.node.end_lineno
            for line, kind, op, node in tests:
                if op == "eq":
                    span = self._eq_span(fn.node, node, line)
                else:
                    later = [tl for tl in test_lines if tl > line]
                    span = (line, (later[0] - 1) if later else fn_end)
                arm = Arm(kind, fn.mod, line, fn.cls, fn.role, qual,
                          span)
                self._fill_arm(arm, scan)
                self.arms.append(arm)

    def _eq_span(self, fnnode, cmpnode,
                 line) -> Tuple[int, int]:
        """Line span covered by an equality arm: the If/IfExp body
        whose test contains the compare, plus the test itself."""
        hit = None
        for n in ast.walk(fnnode):
            if isinstance(n, (ast.If, ast.IfExp)):
                if any(sub is cmpnode for sub in ast.walk(n.test)):
                    hit = n
        if isinstance(hit, ast.If):
            return (hit.lineno, hit.body[-1].end_lineno)
        if isinstance(hit, ast.IfExp):
            return (hit.body.lineno, hit.body.end_lineno)
        return (line, line)

    def _fill_arm(self, arm: Arm, scan: _FnScan):
        lo, hi = arm.span
        for line, key, req in scan.reads:
            if lo <= line <= hi:
                (arm.required if req else arm.optional).add(key)
        fence_lines = [ln for ln in scan.fences if lo <= ln <= hi]
        # transitive: calls inside the span that receive the meta (or
        # gen-derived args) contribute reads and fences; the queue
        # hand-off contributes its consumer
        reach_fences: List[int] = []
        seen: Set[str] = set()

        def absorb(qual: str, extra_meta: Tuple[str, ...],
                   via_line: int, depth: int):
            if qual in seen or depth > 4:
                return
            seen.add(qual)
            arm.reach.add(qual)
            sub = self.scan(qual, extra_meta)
            for _, key, req in sub.reads:
                (arm.required if req else arm.optional).add(key)
            if sub.fences:
                reach_fences.append(via_line)
            for line2, q2, pname in sub.meta_calls:
                absorb(q2, (pname,) if pname else (), via_line,
                       depth + 1)

        for line, qual, pname in scan.meta_calls:
            if lo <= line <= hi:
                absorb(qual, (pname,) if pname else (), line, 1)
        # plain same-class calls: reply sends may live one or two
        # hops down (`stats_req` → _send_stats) without the meta
        # dict traveling along
        for line, qual in scan.calls:
            if lo <= line <= hi:
                arm.reach.add(qual)
                for _, q2 in self.scan(qual).calls:
                    arm.reach.add(q2)
        # calls passing gen-derived expressions (e.g. the abort arm's
        # self._abort(meta["rid"], meta["below_gen"])): bind the
        # callee params receiving them as gen-derived seeds
        for line, qual in scan.calls:
            if not (lo <= line <= hi) or qual in seen:
                continue
            callnodes = [n for n in ast.walk(scan.fn.node)
                         if isinstance(n, ast.Call)
                         and getattr(n, "lineno", 0) == line]
            for cn in callnodes:
                if scan._resolve_call(cn) != qual:
                    continue
                callee = self.fns[qual].node
                cargs = callee.args
                names = [a.arg for a in cargs.posonlyargs + cargs.args]
                if names and names[0] == "self":
                    names = names[1:]
                genp = tuple(
                    names[i] for i, a in enumerate(cn.args)
                    if i < len(names) and scan._expr_gen_derived(a))
                if genp:
                    probe = _FnScan(self, self.fns[qual],
                                    extra_gen=genp)
                    if probe.fences:
                        reach_fences.append(line)
                    arm.reach.add(qual)
        # queue hand-off consumers
        for line, attr, pos in scan.qputs:
            if not (lo <= line <= hi):
                continue
            for cqual, cextra in self._queue_consumers(
                    scan.fn, attr, pos):
                absorb(cqual, cextra, line, 1)
        all_fences = sorted(fence_lines + reach_fences)
        if all_fences:
            arm.has_fence = True
            arm.fence_line = all_fences[0]
            muts = [ln for ln in scan.mutations
                    if lo <= ln <= hi and ln < arm.fence_line]
            if muts:
                arm.early_mut_line = muts[0]

    def _queue_consumers(self, fn: _Fn, attr: str,
                         pos: int) -> List[Tuple[str,
                                                 Tuple[str, ...]]]:
        """Same-class functions that dequeue ``self.<attr>`` — the
        unpack target at ``pos`` becomes their meta variable."""
        out = []
        for qual, other in self.fns.items():
            if other.cls != fn.cls or other.mod != fn.mod:
                continue
            for n in ast.walk(other.node):
                if isinstance(n, ast.Assign) and isinstance(
                        n.value, ast.Call) and isinstance(
                        n.value.func, ast.Attribute) and \
                        n.value.func.attr in ("get", "get_nowait"):
                    qv = n.value.func.value
                    if isinstance(qv, ast.Attribute) and \
                            qv.attr == attr and isinstance(
                            qv.value, ast.Name) and \
                            qv.value.id == "self":
                        tgt = n.targets[0]
                        if isinstance(tgt, ast.Tuple) and \
                                pos < len(tgt.elts) and isinstance(
                                tgt.elts[pos], ast.Name):
                            out.append((qual,
                                        (tgt.elts[pos].id,)))
        return out


# ---------------------------------------------------------------------------
# reply-pairing exit-edge walker
# ---------------------------------------------------------------------------
def _contains_reply_send(stmt: ast.AST, reply: str) -> bool:
    for n in ast.walk(stmt):
        if isinstance(n, ast.Call) and isinstance(
                n.func, ast.Attribute) and n.func.attr == "send" \
                and n.args and _str_const(n.args[0]) == reply:
            return True
        # transport-selecting wrapper: the kind rides in arg 1
        # (`self._send_pages_frame(conn, "fetch_reply", meta, bufs)`)
        if isinstance(n, ast.Call) and isinstance(
                n.func, ast.Attribute) and \
                n.func.attr == "_send_pages_frame" and \
                len(n.args) >= 2 and _str_const(n.args[1]) == reply:
            return True
        if isinstance(n, ast.Tuple) and len(n.elts) >= 2 and \
                _str_const(n.elts[0]) == reply and isinstance(
                n.elts[1], (ast.Dict, ast.Name)):
            return True
    return False


class _ReplyWalker:
    """Every path from the obligation start must reach a reply-send
    attempt — early exits and unprotected may-raise calls before it
    are dropped replies (ref-leak-style forward walk)."""

    def __init__(self, prog: _Program, mod: str, kind: str,
                 reply: str):
        self.prog = prog
        self.mod = mod
        self.kind = kind
        self.reply = reply
        self.reported = False

    def _add(self, line, msg):
        if self.reported:
            return
        self.reported = True
        self.prog._add("proto-reply-pairing", self.mod, line,
                       self.kind, msg)

    def track(self, stmts, protected: bool) -> bool:
        for stmt in stmts:
            # settle-by-containment applies to LEAF statements only:
            # a compound statement holding the send in one branch
            # must still have its other branches walked (an
            # `if ok: send_reply()` / `else: return` must not pass)
            if not isinstance(stmt, (ast.If, ast.Try, ast.For,
                                     ast.While, ast.With)) and \
                    _contains_reply_send(stmt, self.reply):
                return True
            if isinstance(stmt, ast.Try):
                prot = protected or _try_protects(stmt)
                if self.track(stmt.body, prot):
                    return True
                continue
            if isinstance(stmt, ast.If):
                t = self.track(stmt.body, protected)
                e = self.track(stmt.orelse, protected)
                if t and (stmt.orelse and e):
                    return True
                continue
            if isinstance(stmt, (ast.For, ast.While)):
                if self.track(stmt.body, protected):
                    return True
                continue
            if isinstance(stmt, ast.With):
                if self.track(stmt.body, protected):
                    return True
                continue
            if isinstance(stmt, (ast.Return, ast.Continue, ast.Break,
                                 ast.Raise)):
                self._add(stmt.lineno,
                          "handler exit before sending %r — the "
                          "%r requester waits out its timeout for a "
                          "reply that will never come"
                          % (self.reply, self.kind))
                return True
            if not protected:
                line = _may_raise(stmt)
                if line is not None:
                    self._add(line,
                              "call may raise before the %r reply is "
                              "attempted — the exception edge drops "
                              "the reply to %r (wrap it so the reply "
                              "still goes out, even empty)"
                              % (self.reply, self.kind))
                    return True
        return False


def _reply_pass(prog: _Program):
    sent_kinds = {s.kind for s in prog.sends}
    for arm in prog.arms:
        if arm.kind.startswith("_"):
            continue
        reply = None
        if arm.kind + "_reply" in sent_kinds:
            reply = arm.kind + "_reply"
        elif arm.kind.endswith("_req") and arm.kind[:-4] in sent_kinds:
            reply = arm.kind[:-4]
        if reply is None:
            continue
        walker = _ReplyWalker(prog, arm.mod, arm.kind, reply)
        armfn = prog.fns[arm.fnqual]
        lo, hi = arm.span
        stmts = _span_stmts(armfn.node, lo, hi)
        # the LAST arm of an elif chain fits its whole If inside the
        # span — unwrap to the matched body so branch analysis runs
        # (the test-false path owes no reply: the kind didn't match)
        while len(stmts) == 1 and isinstance(stmts[0], ast.If) and \
                any(_str_const(c) == arm.kind
                    for n in ast.walk(stmts[0].test)
                    if isinstance(n, ast.Compare)
                    for c in n.comparators):
            stmts = stmts[0].body
        if any(_contains_reply_send(s, reply) for s in stmts):
            if not walker.track(stmts, False):
                walker._add(hi, "no %r reply on the fall-through "
                            "path of the %r arm" % (reply, arm.kind))
            continue
        # the reply lives in a reachable function (direct call or the
        # queue hand-off): walk that function from its obligation
        # start
        target = None
        for qual in sorted(arm.reach):
            fnode = prog.fns[qual].node
            if any(_contains_reply_send(s, reply)
                   for s in ast.walk(fnode)
                   if isinstance(s, ast.stmt)):
                target = qual
                break
        if target is None:
            walker._add(arm.line,
                        "the %r arm never reaches a %r reply send — "
                        "the request/reply pairing is broken"
                        % (arm.kind, reply))
            continue
        fnode = prog.fns[target].node
        start = _dequeue_region(fnode)
        if start is None:
            start = fnode.body
        if not walker.track(start, False):
            walker._add(fnode.end_lineno,
                        "no %r reply on the fall-through path of %s"
                        % (reply, prog.fns[target].name))


def _span_stmts(fnnode, lo, hi) -> List[ast.stmt]:
    """Top-most statements fully inside the line span."""
    out = []

    def walk(stmts):
        for s in stmts:
            if s.lineno >= lo and s.end_lineno <= hi:
                out.append(s)
            else:
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(s, attr, None)
                    if sub:
                        walk(sub)
                for h in getattr(s, "handlers", ()):
                    walk(h.body)
    walk(fnnode.body)
    return out


def _is_dequeue_call(n: ast.AST) -> bool:
    """A queue dequeue: ``self.<q>.get_nowait()`` or a no-positional
    ``self.<q>.get(timeout=...)`` (dict ``.get(k)`` always has a
    positional arg, so it never matches)."""
    return (isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and isinstance(n.func.value, ast.Attribute)
            and (n.func.attr == "get_nowait"
                 or (n.func.attr == "get" and not n.args)))


def _dequeue_region(fnnode) -> Optional[List[ast.stmt]]:
    """Statements following the queue-dequeue statement in its block —
    the reply obligation's start for the hand-off idiom.  The dequeue
    may sit inside a try (the ``except queue.Empty: return`` idiom);
    the obligation then continues with the try's block siblings."""
    def find(stmts):
        for i, s in enumerate(stmts):
            subs = [getattr(s, a, None)
                    for a in ("body", "orelse", "finalbody")]
            subs = [b for b in subs if b]
            subs.extend(h.body for h in getattr(s, "handlers", ()))
            inner = None
            for b in subs:
                inner = find(b)
                if inner is not None:
                    break
            if inner is not None:
                return inner if inner else stmts[i + 1:]
            nested = {id(n) for b in subs for st in b
                      for n in ast.walk(st)}
            if any(_is_dequeue_call(n) and id(n) not in nested
                   for n in ast.walk(s)):
                return stmts[i + 1:]
        return None
    return find(fnnode.body)


# ---------------------------------------------------------------------------
# resource-lifecycle pass (py-ref-leak machinery, generalized)
# ---------------------------------------------------------------------------
def _ctor_name(call: ast.Call) -> Optional[str]:
    f = call.func
    t = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    return t if t in RESOURCE_CTORS else None


def _is_daemon_thread(call: ast.Call, ctor: str) -> bool:
    if ctor != "Thread":
        return False
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is True:
            return True
    return False


def _name_in(node: ast.AST, name: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == name
               for n in ast.walk(node))


def _settles_resource(stmt: ast.AST, name: str) -> bool:
    for n in ast.walk(stmt):
        if isinstance(n, ast.Call) and isinstance(
                n.func, ast.Attribute) and \
                n.func.attr in _SETTLE_METHODS and isinstance(
                n.func.value, ast.Name) and n.func.value.id == name:
            return True
    return False


def _escapes_resource(stmt: ast.AST, name: str) -> bool:
    if isinstance(stmt, ast.Return) and stmt.value is not None \
            and _name_in(stmt.value, name):
        return True
    if isinstance(stmt, ast.Assign) and _name_in(stmt.value, name):
        for tgt in stmt.targets:
            if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                return True
    # handed to another call (Thread(args=(conn,)), q.put((conn,..)),
    # Connection(sock), handler(conn) ...): ownership transfers
    for n in ast.walk(stmt):
        if isinstance(n, ast.Call):
            args = list(n.args) + [kw.value for kw in n.keywords]
            recv_is_self = isinstance(n.func, ast.Attribute) and \
                isinstance(n.func.value, ast.Name) and \
                n.func.value.id == name
            if not recv_is_self and any(_name_in(a, name)
                                        for a in args):
                return True
    return False


class _ResourceScanner:
    def __init__(self, prog: _Program, fn: _Fn):
        self.prog = prog
        self.fn = fn
        self._reported = False            # per-tracked-resource flag

    def _add(self, line, name, msg):
        self._reported = True
        self.prog._add("py-resource-lifecycle", self.fn.mod, line,
                       name, msg)

    def scan(self):
        self._scan_block(self.fn.node.body, [])
        self._terminate_reap()

    def _acquire(self, stmt) -> Optional[Tuple[str, str]]:
        if not isinstance(stmt, ast.Assign):
            return None
        v = stmt.value
        if not isinstance(v, ast.Call):
            return None
        ctor = _ctor_name(v)
        if ctor is None or _is_daemon_thread(v, ctor):
            return None
        tgt = stmt.targets[0]
        if isinstance(tgt, ast.Name):
            return tgt.id, ctor
        return None

    def _scan_block(self, body, conts):
        """``conts``: the continuation blocks execution falls into
        after this block ends (innermost first) — a resource acquired
        inside an ``if`` may legitimately settle after it."""
        for i, stmt in enumerate(body):
            got = self._acquire(stmt)
            if got is not None:
                name, ctor = got
                self._reported = False
                settled = self._track(body[i + 1:], name, ctor,
                                      stmt.lineno, protected=False)
                for cont in conts:
                    if settled:
                        break
                    settled = self._track(cont, name, ctor,
                                          stmt.lineno,
                                          protected=False)
                if not settled and not self._reported:
                    # clean fall-through off the function end is an
                    # exit path too
                    self._add(stmt.lineno, name,
                              "function exit leaks the %s bound to "
                              "%r (never closed/joined, stored, or "
                              "returned on the fall-through path)"
                              % (ctor, name))
                # keep scanning for further acquisitions after it
            sub_conts = [body[i + 1:]] + conts
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if sub:
                    self._scan_block(sub, sub_conts)
            for h in getattr(stmt, "handlers", ()):
                self._scan_block(h.body, sub_conts)

    def _try_settles(self, stmt: ast.Try, name: str) -> bool:
        return any(_settles_resource(s, name) or
                   _escapes_resource(s, name)
                   for h in stmt.handlers for s in h.body) or \
            any(_settles_resource(s, name) for s in stmt.finalbody)

    def _track(self, stmts, name, ctor, acq_line,
               protected) -> bool:
        for stmt in stmts:
            if _settles_resource(stmt, name) or \
                    _escapes_resource(stmt, name):
                return True
            if isinstance(stmt, ast.Try):
                prot = protected or self._try_settles(stmt, name)
                if self._track(stmt.body, name, ctor, acq_line,
                               prot):
                    return True
                continue
            if isinstance(stmt, ast.If):
                t = self._track(stmt.body, name, ctor, acq_line,
                                protected)
                e = self._track(stmt.orelse, name, ctor, acq_line,
                                protected)
                if t and (stmt.orelse and e):
                    return True
                continue
            if isinstance(stmt, (ast.For, ast.While, ast.With)):
                if self._track(stmt.body, name, ctor, acq_line,
                               protected):
                    return True
                continue
            if isinstance(stmt, (ast.Return, ast.Continue, ast.Break,
                                 ast.Raise)):
                self._add(stmt.lineno, name,
                          "exit leaks the %s bound to %r at line %d "
                          "(neither closed/joined nor stored/"
                          "returned on this path)"
                          % (ctor, name, acq_line))
                return True
            if not protected:
                line = _may_raise(stmt)
                if line is not None:
                    self._add(line, name,
                              "call may raise between the %s "
                              "construction at line %d and its "
                              "close/escape — the exception edge "
                              "leaks it" % (ctor, acq_line))
                    return True
        return False

    def _terminate_reap(self):
        """``X.terminate()`` with no later ``X.join()`` in the same
        function leaves a zombie pid."""
        terms: List[Tuple[int, str]] = []
        joins: List[Tuple[int, str]] = []
        for n in ast.walk(self.fn.node):
            if isinstance(n, ast.Call) and isinstance(
                    n.func, ast.Attribute):
                if n.func.attr == "terminate":
                    terms.append((n.lineno, _dotted(n.func.value)))
                elif n.func.attr == "join":
                    joins.append((n.lineno, _dotted(n.func.value)))
        for line, who in terms:
            if not any(jl > line and jw == who for jl, jw in joins):
                self._add(line, "terminate",
                          "%s.terminate() is never followed by "
                          "%s.join() in this function — a SIGTERMed "
                          "process stays a zombie pid until the "
                          "parent exits" % (who, who))


# ---------------------------------------------------------------------------
# rule passes over the model
# ---------------------------------------------------------------------------
def _protocol_pass(prog: _Program):
    handled: Dict[Tuple[str, str], List[Arm]] = {}
    for arm in prog.arms:
        handled.setdefault((arm.role, arm.kind), []).append(arm)
    sent: Dict[Tuple[str, str], List[SendSite]] = {}
    for s in prog.sends:
        sent.setdefault((s.target, s.kind), []).append(s)

    # unhandled kinds: fire at every send site of the (target, kind)
    for (target, kind), sites in sorted(sent.items()):
        if (target, kind) in handled:
            continue
        for s in sites:
            prog._add("proto-unhandled-kind", s.mod, s.line, kind,
                      "%r is sent to the %s role but no %s class "
                      "has a dispatch arm for it — the frame is "
                      "silently dropped" % (kind, target, target))

    # unknown kinds: an arm nobody sends to
    for (role, kind), arms in sorted(handled.items()):
        if kind.startswith("_") or (role, kind) in sent:
            continue
        for arm in arms:
            prog._add("proto-unknown-kind", arm.mod, arm.line, kind,
                      "dispatch arm for %r but no peer ever sends it "
                      "to the %s role — dead protocol surface"
                      % (kind, role))

    # meta schema: union required keys per (role, kind); check sites
    for (role, kind), arms in sorted(handled.items()):
        required: Set[str] = set()
        for arm in arms:
            required |= arm.required
        if not required:
            continue
        for s in sent.get((role, kind), []):
            if s.keys is None:
                continue                  # unresolvable: never guess
            for key in sorted(required - s.keys):
                prog._add(
                    "proto-meta-schema", s.mod, s.line, kind,
                    "send site omits meta[%r], which the %s handler "
                    "reads without a default — schema drift between "
                    "processes is a runtime KeyError mid-serve"
                    % (key, role))

    # gen fence: kinds whose sends carry gen need fenced handlers
    gen_kinds = {(s.target, s.kind) for s in prog.sends
                 if s.carries_gen}
    for arm in prog.arms:
        if (arm.role, arm.kind) not in gen_kinds:
            continue
        if not arm.has_fence:
            prog._add(
                "proto-gen-fence", arm.mod, arm.line, arm.kind,
                "handler for gen-carrying %r never compares the "
                "incarnation gen — a zombie worker's late frame "
                "lands in a resubmitted request (the PR-10 fence is "
                "a checked invariant, not a convention)" % arm.kind)
        elif arm.early_mut_line is not None:
            prog._add(
                "proto-gen-fence", arm.mod, arm.early_mut_line,
                arm.kind,
                "handler for gen-carrying %r mutates state before "
                "the gen fence at line %d — the fence must come "
                "first" % (arm.kind, arm.fence_line))


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------
def build_model(modules: Dict[str, str],
                roles: Optional[Dict[str, str]] = None) -> _Program:
    return _Program(modules, roles)


def analyze(modules: Dict[str, str],
            roles: Optional[Dict[str, str]] = None) -> List[Finding]:
    """Analyze ``{rel_path: source}`` as one protocol; findings are
    pragma-filtered per module."""
    prog = build_model(modules, roles)
    _protocol_pass(prog)
    _reply_pass(prog)
    for qual in sorted(prog.fns):
        _ResourceScanner(prog, prog.fns[qual]).scan()
    out: List[Finding] = []
    for rel, mod in prog.modules.items():
        fs = [f for f in prog.findings if f.path == rel]
        out.extend(apply_pragmas(fs, mod.source))
    return sorted(out, key=lambda f: (f.path, f.line, f.rule))


def lint_source(source: str, rel_path: str,
                roles: Optional[Dict[str, str]] = None
                ) -> List[Finding]:
    """Single-module entry (fixtures drive this directly)."""
    return analyze({rel_path: source}, roles)


def _load_modules(root: str) -> Dict[str, str]:
    modules: Dict[str, str] = {}
    for pkg in PACKAGES:
        d = os.path.join(root, pkg)
        if not os.path.isdir(d):
            continue
        for name in sorted(os.listdir(d)):
            if not name.endswith(".py"):
                continue
            rel = "%s/%s" % (pkg, name)
            with open(os.path.join(root, rel)) as f:
                modules[rel] = f.read()
    return modules


def triggered(only: Optional[Set[str]]) -> bool:
    """Does the change set intersect the protocol's trigger scope?"""
    if only is None:
        return True
    return any(p in TRIGGER_FILES
               or p.startswith(TRIGGER_PREFIXES) for p in only)


def run(root: str, only: Optional[Set[str]] = None) -> List[Finding]:
    """Lint the live protocol.  ``only`` (--changed-only): the whole
    analysis is skipped unless serving/, ``parallel/dist.py``, or
    ``tools/analysis/`` changed; when it runs, reporting is restricted
    to changed files (pylocklint's convention — tier-1 always runs
    full scope)."""
    if not triggered(only):
        return []
    findings = analyze(_load_modules(root))
    if only is not None:
        findings = [f for f in findings if f.path in only]
    return findings


# ---------------------------------------------------------------------------
# protocol audit (docs/protocol.md)
# ---------------------------------------------------------------------------
def protocol_audit_md(root: str) -> str:
    """Render the wire-protocol model as the checked-in audit table
    (pinned current by tier-1, like docs/sharding_readiness.md)."""
    prog = build_model(_load_modules(root))
    handled: Dict[Tuple[str, str], List[Arm]] = {}
    for arm in prog.arms:
        handled.setdefault((arm.role, arm.kind), []).append(arm)
    by_kind: Dict[str, List[SendSite]] = {}
    for s in prog.sends:
        by_kind.setdefault(s.kind, []).append(s)
    for (role, kind), arms in handled.items():
        by_kind.setdefault(kind, [])

    def site(mod, line):
        return "%s:%d" % (os.path.basename(mod), line)

    def fnq(qual):
        return qual.split("::", 1)[1]

    lines = [
        "# Wire protocol — disaggregated serving cluster",
        "",
        "Generated by protolint (`python -m tools.analysis "
        "--write-protocol-audit`) from",
        "the AST protocol model over `mxnet_tpu/serving/` — the "
        "router ↔ worker control",
        "plane and the worker ↔ worker data plane riding "
        "`parallel/dist.py` raw frames",
        "through `serving/transport.py`.  Checked in and pinned "
        "current by tier-1",
        "(`tests/test_static_analysis.py`) exactly like "
        "`docs/sharding_readiness.md`;",
        "`tools/run_static_analysis.sh --changed-only` regenerates "
        "it when serving/,",
        "`parallel/dist.py`, or `tools/analysis/` change.",
        "",
        "Meta schema = the union of keys set at every send site "
        "(protolint's",
        "`proto-meta-schema` verifies each handler-read key is "
        "present at each site).",
        "Gen fence: `yes` = every handler compares the incarnation "
        "gen before mutating",
        "state (`proto-gen-fence`); `—` = the kind carries no gen.  "
        "`srid` is the",
        "`(rid, gen)` pair by protocol contract.  In-process "
        "synthetic kinds",
        "(`_wake`, `_lost`) never travel the wire and are excluded.",
        "",
        "| kind | route | sent from | handled at | meta schema | "
        "bufs | gen fence |",
        "|---|---|---|---|---|---|---|",
    ]
    for kind in sorted(by_kind):
        if kind.startswith("_"):
            continue                      # in-process synthetic
        sites = by_kind[kind]
        routes = sorted({"%s → %s" % (s.role, s.target)
                         for s in sites})
        senders = sorted({site(s.mod, s.line) for s in sites})
        targets = sorted({s.target for s in sites})
        arms = []
        for t in targets:
            arms.extend(handled.get((t, kind), []))
        if not sites:           # arm with no sender (should not ship)
            for (role, k), al in handled.items():
                if k == kind:
                    arms.extend(a for a in al if a not in arms)
        handlers = sorted({"`%s` (%s)" % (fnq(a.fnqual),
                                          site(a.mod, a.line))
                           for a in arms}) or ["**UNCOVERED**"]
        keysets = [s.keys for s in sites if s.keys is not None]
        allkeys: Set[str] = set().union(*keysets) if keysets else set()
        everykeys = set.intersection(*map(set, keysets)) \
            if keysets else set()
        schema = ", ".join(
            "`%s`" % k if k in everykeys else "`%s`?" % k
            for k in sorted(allkeys)) or "—"
        bufs = "/".join(sorted({s.bufs for s in sites})) or "—"
        carries = any(s.carries_gen for s in sites)
        if not carries:
            fence = "—"
        elif arms and all(a.has_fence and a.early_mut_line is None
                          for a in arms):
            fence = "yes"
        else:
            fence = "NO"
        lines.append("| `%s` | %s | %s | %s | %s | %s | %s |" % (
            kind, "; ".join(routes) or "?",
            "; ".join(senders) or "—",
            "; ".join(handlers), schema, bufs, fence))
    lines += [
        "",
        "Reply pairings (checked on every exit edge, exception edges "
        "included, by",
        "`proto-reply-pairing`): `fetch` → `fetch_reply` (the peer "
        "fetch server replies",
        "even when serving the fetch fails — the requester degrades "
        "to a cold prefill",
        "instead of waiting out its timeout), `stats_req` → `stats` "
        "(`_send_stats`",
        "replies unconditionally; the periodic rate limit lives in "
        "`_maybe_send_stats`),",
        "`clock_req` → `clock` (the router's handshake ping-pong "
        "clock probe: the",
        "worker echoes `t0` with its own `t_worker` immediately, so "
        "the router's",
        "min-RTT filter can estimate each worker's perf_counter "
        "offset for the",
        "merged-trace clock reconciliation, round 23).",
        "",
        "Distributed tracing (round 23): every request-bearing kind "
        "(`submit`,",
        "`pages`/`handoff`, `fetch`/`fetch_reply`, `cancel`) carries "
        "the edge-minted",
        "`trace_id` in its meta; workers stamp their spans with it "
        "and ship drained",
        "span batches router-ward as the fire-and-forget `spans` "
        "kind on the stats",
        "tick (NOT inside `_send_stats` — the `stats_req` reply path "
        "stays call-free).",
        "",
        "Zero-copy page puts (round 22): `caps` is the FIRST frame "
        "both directions on",
        "every worker ↔ worker data-plane connection and advertises "
        "the `put_pages`",
        "capability (`transport.put_capability`).  When both ends "
        "advertise it for the",
        "same host+segment dir, `pages` and `fetch_reply` bufs ride "
        "a `/dev/shm`",
        "segment named in the meta `put` key instead of socket "
        "frames — the receiver",
        "mmaps and unlinks the segment at open, so on-disk segments "
        "≈ frames in",
        "flight, and `transport.put_sweep(pid)` reclaims a killed "
        "sender's leftovers.",
        "Everything above the transport (kinds, meta schema, gen "
        "fences, reply",
        "pairings, stale-frame drops) is bit-identical across the "
        "two paths;",
        "`MXNET_SERVE_TRANSPORT=socket` forces the frame path.",
        "",
    ]
    return "\n".join(lines)
