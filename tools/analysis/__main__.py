import os
import sys

# graphlint's tensor-parallel serving-step registry entry traces a
# program jitted over a 2-device mesh; the CLI requests the virtual
# CPU mesh (the same mechanism the tests' conftest and the MULTICHIP
# dry-runs use) BEFORE jax's backend initializes.  Deliberately HERE
# and not in the package __init__: importing the library must not
# mutate process-global topology for hosts that never trace the tp
# program (the tp spec builder raises a clear error if devices are
# short at trace time).
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()

from .runner import main  # noqa: E402

sys.exit(main())
