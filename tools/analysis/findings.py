"""mxlint core: structured findings, inline pragmas, and the baseline.

Every analyzer in ``tools/analysis`` emits :class:`Finding` records.  A
finding is suppressed either by an inline pragma at (or in the comment
block directly above) the offending line::

    # mxlint: allow(host-sync) -- justification          (Python)
    // mxlint: allow(lock-order) -- justification        (C/C++)

or by an entry in the checked-in baseline (``tools/analysis/baseline.json``)
keyed on ``rule:path:symbol`` — deliberately *line-independent* so
unrelated edits do not churn the baseline.  Pragmas are the preferred
mechanism (auditable at the call site); the baseline exists for
pre-existing accepted debt.  Anything not suppressed is a NEW violation
and fails ``tests/test_static_analysis.py``.
"""
from __future__ import annotations

import dataclasses
import json
import re
from typing import Dict, Iterable, List, Set, Tuple

__all__ = ["Finding", "parse_pragmas", "is_allowed", "apply_pragmas",
           "load_baseline", "split_new"]

PRAGMA_RE = re.compile(
    r"(?:#|//)\s*mxlint:\s*(allow|requires)\(([^)]*)\)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""
    analyzer: str        # "abi" | "jax" | "native"
    rule: str            # e.g. "host-sync", "abi-argtypes", "lock-order"
    path: str            # repo-relative path
    line: int            # 1-based; 0 when the finding is file/symbol level
    symbol: str          # function / field / MX symbol the rule fired on
    message: str

    @property
    def key(self) -> str:
        """Baseline key — line-independent on purpose."""
        return "%s:%s:%s" % (self.rule, self.path, self.symbol)

    def __str__(self) -> str:
        loc = "%s:%d" % (self.path, self.line) if self.line else self.path
        return "%s: [%s/%s] %s — %s" % (loc, self.analyzer, self.rule,
                                        self.symbol, self.message)


def parse_pragmas(source: str) -> Dict[int, Set[str]]:
    """Map 1-based line number -> set of rule names allowed on that line
    (``requires`` pragmas are analyzer-specific and handled separately)."""
    out: Dict[int, Set[str]] = {}
    for i, text in enumerate(source.splitlines(), 1):
        for kind, rules in PRAGMA_RE.findall(text):
            if kind != "allow":
                continue
            out.setdefault(i, set()).update(
                r.strip() for r in rules.split(",") if r.strip())
    return out


def _comment_only(text: str) -> bool:
    s = text.strip()
    return not s or s.startswith("#") or s.startswith("//")


def is_allowed(source_lines: List[str], pragmas: Dict[int, Set[str]],
               line: int, rule: str) -> bool:
    """A pragma suppresses ``rule`` at ``line`` when it sits on the line
    itself or anywhere in the contiguous comment block directly above."""
    def hit(ln: int) -> bool:
        rules = pragmas.get(ln)
        return bool(rules) and (rule in rules or "*" in rules)

    if hit(line):
        return True
    ln = line - 1
    while ln >= 1 and _comment_only(source_lines[ln - 1]):
        if hit(ln):
            return True
        ln -= 1
    return False


def apply_pragmas(findings: Iterable[Finding],
                  source: str) -> List[Finding]:
    """Drop findings suppressed by inline pragmas in ``source``."""
    lines = source.splitlines()
    pragmas = parse_pragmas(source)
    return [f for f in findings
            if not (f.line and is_allowed(lines, pragmas, f.line, f.rule))]


def load_baseline(path: str) -> Set[str]:
    """Baseline format: ``{"version": 1, "allow": [{"rule":..,
    "path":.., "symbol":.., "reason":..}, ...]}``; entries may also be
    raw ``rule:path:symbol`` strings."""
    with open(path) as f:
        data = json.load(f)
    keys: Set[str] = set()
    for entry in data.get("allow", []):
        if isinstance(entry, str):
            keys.add(entry)
        else:
            keys.add("%s:%s:%s" % (entry["rule"], entry["path"],
                                   entry["symbol"]))
    return keys


def split_new(findings: Iterable[Finding],
              baseline: Set[str]) -> Tuple[List[Finding], List[Finding]]:
    """Partition into (new, baselined)."""
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        (old if f.key in baseline else new).append(f)
    return new, old
