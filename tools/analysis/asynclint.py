"""asyncio event-loop hazard analyzer (mxlint analyzer 7 — ISSUE 19
tentpole).

The HTTP/SSE front door (``mxnet_tpu/serving/http_frontend.py``) is
~970 lines of hand-rolled asyncio: coroutines, executor hops for the
sync cluster RPCs, and ``call_soon_threadsafe`` bridges carrying
engine-thread events onto the loop.  pylocklint audits the *thread*
side of that code and protolint the wire, but nothing machine-checked
the event-loop contracts themselves — a blocking call in a coroutine
stalls every connection at once, a dropped task swallows its
exception forever, and a plain ``put_nowait`` from the engine thread
corrupts loop-owned state.  This pass builds an AST + cross-module
call-graph model of every ``async def`` in ``mxnet_tpu/serving`` and
``mxnet_tpu/obs`` with the thread↔loop boundary made explicit:
function references passed to ``run_in_executor`` / ``_in_executor``
/ ``Thread(target=)`` / ``attach_stream`` run on executor or engine
threads (coroutine taint TERMINATES there, thread-context taint
STARTS there); references passed to ``call_soon`` /
``call_soon_threadsafe`` / ``add_done_callback`` run on the loop.

Rules
-----
``async-blocking-call``  A blocking primitive — ``time.sleep``,
    ``queue.Queue`` get/put, ``.acquire()`` on a ``threading``
    lock, ``.result()`` on a ``concurrent.futures`` future (a name
    assigned from ``X.submit(...)`` or the direct
    ``submit(...).result()`` chain), socket recv/send/connect,
    ``open()``, or a sync cluster RPC (``*.cluster.submit(...)``) —
    reached directly or transitively from a coroutine without a
    ``run_in_executor`` hop.  One such call stalls the whole loop:
    every open connection, every SSE stream.  Intended-sync sites
    take a pragma with justification.

``async-unawaited-coroutine``  A call that resolves to an ``async
    def`` used as a bare expression statement — the coroutine object
    is created and dropped, its body never runs, and Python's
    "coroutine was never awaited" warning fires (at best) long after
    the bug.  Await it, gather it, or wrap it in a task.

``async-task-exception``  A ``create_task``/``ensure_future`` result
    that is neither stored-and-settled (awaited, ``.cancel()``-ed, or
    given ``add_done_callback``) on every exit edge — exception edges
    included — nor escaped (returned / stored into an attribute,
    subscript, or container / passed on).  A garbage-collected task's
    exception is silently lost; a bare ``ensure_future(...)``
    expression statement is the degenerate case.

``async-threadsafe-boundary``  Code reachable from a non-loop thread
    (an executor hop target, a ``Thread(target=)``, an engine
    ``attach_stream`` callback) mutating loop-owned state —
    ``put_nowait`` on an ``asyncio.Queue``, ``.set()`` on an
    ``asyncio.Event``, or ``loop.call_soon`` — without going through
    ``call_soon_threadsafe``.  asyncio's structures are not
    thread-safe; the engine→SSE bridge is the live instance (it
    passes ``q.put_nowait`` as a *reference* to
    ``call_soon_threadsafe``, which is the clean shape).

``async-writer-lifecycle``  An ``asyncio.StreamWriter`` — the
    ``open_connection`` result or the writer parameter of the
    ``start_server`` callback — must reach ``close()`` **and**
    ``await wait_closed()``, or escape (returned / stored into owned
    state), on EVERY exit edge including exceptions.  ``close()``
    alone only schedules the close: the connection-reset path never
    drains, and under load the half-closed transports pile up.  This
    generalizes protolint's ``py-resource-lifecycle`` exit-edge walk
    to async defs (``try/finally`` settling covers the try's edges;
    a ``try`` with a real handler protects its body).  Passing the
    writer to a helper is a borrow, not a settle — the obligation
    stays with the originator.

``async-lock-across-await``  A held ``threading.Lock``/``RLock``
    (``with lock:`` containing an ``await``) spanning an await point
    inside a coroutine: the loop can interleave another coroutine
    that blocks on the same lock — deadlocking the loop thread
    against itself, which no watchdog can preempt.

Approximations (documented, in the pylocklint tradition):

* Receivers are typed by constructor assignment (locals, enclosing
  defs, and ``self.X = ctor()`` class attributes); untyped receivers
  never flag — ``.get()`` on a dict is not ``.get()`` on a
  ``queue.Queue``, and ``.result()`` on an already-done asyncio task
  is not a blocking future wait.  Precise, not complete.
* Lambda bodies are not walked: a lambda handed to ``_in_executor``
  runs on the executor by construction, and classifying every other
  lambda's eventual calling context is guesswork.
* Calls resolve through ``self``, enclosing defs, module level, and
  unique bare names only — ambiguous names contribute no edge.
* A coroutine *called* from a thread-context function is a dropped
  coroutine, not thread-executed code; thread taint does not
  propagate into async defs.

Scoping: ``--changed-only`` re-analyzes when serving/, obs/, or
``tools/analysis/`` change (tier-1 always runs full scope), reporting
restricted to changed files like pylocklint.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding, apply_pragmas

PACKAGES = ["mxnet_tpu/serving", "mxnet_tpu/obs"]

# --changed-only trigger scope (tools/analysis/ included: an analyzer
# edit must re-run its own analysis)
TRIGGER_PREFIXES = ("mxnet_tpu/serving/", "mxnet_tpu/obs/",
                    "tools/analysis/")

# receiver types by constructor (dotted name of the ctor call)
_CTOR_TYPES = {
    "queue.Queue": "thread_queue",
    "queue.LifoQueue": "thread_queue",
    "queue.PriorityQueue": "thread_queue",
    "queue.SimpleQueue": "thread_queue",
    "asyncio.Queue": "aio_queue",
    "threading.Event": "thread_event",
    "asyncio.Event": "aio_event",
    "threading.Lock": "thread_lock",
    "threading.RLock": "thread_lock",
    "asyncio.Lock": "aio_lock",
    "socket.socket": "socket",
    "socket.create_connection": "socket",
}

# function-reference args to these calls run on a non-loop thread
# (executor pool, engine thread) — coroutine taint terminates, thread
# taint starts
_THREAD_REGISTRARS = {"run_in_executor", "_in_executor", "submit",
                      "Thread", "attach_stream", "start_new_thread"}
# ...and to these they run ON the loop (no threadsafe marshalling
# needed; not an executor hop either)
_LOOP_REGISTRARS = {"call_soon", "call_soon_threadsafe", "call_later",
                    "call_at", "add_done_callback"}

# calls that cannot raise for exit-edge purposes (protolint's
# whitelist + the asyncio lifecycle calls themselves)
_SAFE_NAME_CALLS = {"len", "min", "max", "int", "float", "bool",
                    "str", "repr", "list", "tuple", "set", "dict",
                    "sorted", "enumerate", "zip", "abs", "range",
                    "isinstance", "id", "getattr", "hasattr", "sum",
                    "any", "all", "print", "type", "next"}
_SAFE_ATTR_CALLS = {"get", "append", "appendleft", "pop", "popleft",
                    "discard", "add", "items", "values", "keys",
                    "update", "extend", "clear", "perf_counter",
                    "release", "copy", "setdefault", "put",
                    "put_nowait", "set", "is_set", "getpid", "close",
                    "cancel", "done", "cancelled", "get_extra_info",
                    "is_closing", "set_result", "inc", "observe",
                    "record", "debug", "info", "warning", "error"}


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _mentions(node: ast.AST, name: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == name
               for n in ast.walk(node))


def _may_raise(stmt: ast.AST) -> Optional[int]:
    """Line of the first call in ``stmt`` that can raise."""
    for n in ast.walk(stmt):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        if isinstance(f, ast.Name) and f.id in _SAFE_NAME_CALLS:
            continue
        if isinstance(f, ast.Attribute) and f.attr in _SAFE_ATTR_CALLS:
            continue
        return n.lineno
    return None


def _try_protects(stmt: ast.Try) -> bool:
    """A try with a handler that does not just re-raise redirects its
    body's exception edges — execution continues after the try."""
    for h in stmt.handlers:
        if not (len(h.body) == 1 and isinstance(h.body[0], ast.Raise)
                and h.body[0].exc is None):
            return True
    return False


_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _own_nodes(fnnode: ast.AST) -> List[ast.AST]:
    """Every AST node executed as part of THIS function's body —
    nested defs and lambdas excluded (their bodies run later, in a
    context of their own)."""
    out: List[ast.AST] = []

    def walk(n):
        for c in ast.iter_child_nodes(n):
            if isinstance(c, _DEFS + (ast.Lambda,)):
                continue
            out.append(c)
            walk(c)
    walk(fnnode)
    return out


def _is_task_ctor(call: ast.Call) -> bool:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr in ("create_task", "ensure_future")
    if isinstance(call.func, ast.Name):
        return call.func.id in ("create_task", "ensure_future")
    return False


# ---------------------------------------------------------------------------
# program model
# ---------------------------------------------------------------------------
class _Fn:
    __slots__ = ("qual", "mod", "cls", "name", "node", "parent",
                 "is_async", "locals", "edges", "coro", "thread",
                 "loop_cb", "server_cb")

    def __init__(self, qual, mod, cls, name, node, parent):
        self.qual = qual
        self.mod = mod
        self.cls = cls                  # enclosing class name or None
        self.name = name
        self.node = node
        self.parent = parent            # enclosing fn qual or None
        self.is_async = isinstance(node, ast.AsyncFunctionDef)
        self.locals: Dict[str, str] = {}   # name -> receiver type
        self.edges: Set[str] = set()       # direct synchronous calls
        self.coro = self.is_async          # runs on the loop, awaited
        self.thread = False                # reachable from a thread
        self.loop_cb = False               # scheduled ON the loop
        self.server_cb = False             # asyncio.start_server cb

    @property
    def display(self) -> str:
        return self.qual.split("::", 1)[1]


class _Module:
    def __init__(self, rel: str, source: str):
        self.rel = rel
        self.source = source
        self.tree = ast.parse(source, rel)


class _Program:
    def __init__(self, modules: Dict[str, str]):
        self.modules = {rel: _Module(rel, src)
                        for rel, src in sorted(modules.items())}
        self.fns: Dict[str, _Fn] = {}
        self.by_name: Dict[str, List[str]] = {}
        # (mod, cls) -> {attr: receiver type} from self.X = ctor()
        self.cls_types: Dict[Tuple[str, str], Dict[str, str]] = {}
        self.findings: List[Finding] = []
        for mod in self.modules.values():
            self._collect(mod)
        for fn in self.fns.values():
            self._type_locals(fn)
        for fn in list(self.fns.values()):
            self._scan_calls(fn)
        self._propagate()

    def _add(self, rule, mod, line, symbol, msg):
        self.findings.append(Finding("async", rule, mod, line,
                                     symbol, msg))

    # -- collection --------------------------------------------------
    def _collect(self, mod: _Module):
        def add(node, cls, parent):
            if parent:
                qual = "%s.%s" % (parent, node.name)
            else:
                qual = "%s::%s%s" % (mod.rel, cls + "." if cls else "",
                                     node.name)
            fn = _Fn(qual, mod.rel, cls, node.name, node, parent)
            self.fns[qual] = fn
            self.by_name.setdefault(node.name, []).append(qual)
            for child in node.body:
                walk_stmt(child, cls, qual)

        def walk_stmt(node, cls, parent):
            if isinstance(node, _DEFS):
                add(node, cls, parent)
            elif isinstance(node, ast.ClassDef):
                for child in node.body:
                    walk_stmt(child, node.name, None)
            else:
                for attr in ("body", "orelse", "finalbody"):
                    for child in getattr(node, attr, ()):
                        walk_stmt(child, cls, parent)
                for h in getattr(node, "handlers", ()):
                    for child in h.body:
                        walk_stmt(child, cls, parent)

        for node in mod.tree.body:
            walk_stmt(node, None, None)

    # -- receiver typing ---------------------------------------------
    def _value_type(self, v: ast.AST) -> Optional[str]:
        if not isinstance(v, ast.Call):
            return None
        ctor = _dotted(v.func)
        if ctor in _CTOR_TYPES:
            return _CTOR_TYPES[ctor]
        if isinstance(v.func, ast.Attribute):
            a = v.func.attr
            if a == "submit":
                return "cfuture"        # concurrent.futures future
            if a in ("run_in_executor", "_in_executor"):
                return "aio_future"     # awaitable — not blocking
            if a in ("create_task", "ensure_future"):
                return "task"
        return None

    def _type_locals(self, fn: _Fn):
        for n in _own_nodes(fn.node):
            if not isinstance(n, ast.Assign):
                continue
            t = self._value_type(n.value)
            if t is None:
                continue
            for tgt in n.targets:
                if isinstance(tgt, ast.Name):
                    fn.locals[tgt.id] = t
                elif (isinstance(tgt, ast.Attribute)
                      and isinstance(tgt.value, ast.Name)
                      and tgt.value.id == "self" and fn.cls):
                    self.cls_types.setdefault(
                        (fn.mod, fn.cls), {})[tgt.attr] = t

    def recv_type(self, fn: _Fn, node: ast.AST) -> Optional[str]:
        """Type of a receiver expression: fn locals, enclosing defs,
        then ``self.X`` class attributes.  None = unknown (no rule
        fires on it)."""
        if isinstance(node, ast.Name):
            cur: Optional[_Fn] = fn
            while cur is not None:
                if node.id in cur.locals:
                    return cur.locals[node.id]
                cur = self.fns.get(cur.parent) if cur.parent else None
            return None
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            cls = self._cls_of(fn)
            if cls:
                return self.cls_types.get((fn.mod, cls),
                                          {}).get(node.attr)
        return None

    def _cls_of(self, fn: _Fn) -> Optional[str]:
        cur: Optional[_Fn] = fn
        while cur is not None:
            if cur.cls:
                return cur.cls
            cur = self.fns.get(cur.parent) if cur.parent else None
        return None

    # -- call resolution ---------------------------------------------
    def resolve(self, fn: _Fn, func: ast.AST) -> Optional[str]:
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self"):
            cls = self._cls_of(fn)
            if cls:
                qual = "%s::%s.%s" % (fn.mod, cls, func.attr)
                if qual in self.fns:
                    return qual
            return None
        if isinstance(func, ast.Name):
            cur: Optional[_Fn] = fn
            while cur is not None:       # enclosing nested defs
                qual = "%s.%s" % (cur.qual, func.id)
                if qual in self.fns:
                    return qual
                cur = self.fns.get(cur.parent) if cur.parent else None
            qual = "%s::%s" % (fn.mod, func.id)
            if qual in self.fns:
                return qual
            cands = self.by_name.get(func.id, [])
            if len(cands) == 1:          # unique bare name only
                return cands[0]
        return None

    def _ref_targets(self, fn: _Fn, args) -> List[str]:
        """Function references among call ARGS (not called here —
        registered to run elsewhere)."""
        out = []
        for a in args:
            if isinstance(a, (ast.Name, ast.Attribute)):
                qual = self.resolve(fn, a)
                if qual is not None:
                    out.append(qual)
        return out

    def _scan_calls(self, fn: _Fn):
        for n in _own_nodes(fn.node):
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            callee_name = f.attr if isinstance(f, ast.Attribute) \
                else (f.id if isinstance(f, ast.Name) else None)
            args = list(n.args) + [k.value for k in n.keywords]
            if callee_name in _THREAD_REGISTRARS:
                for qual in self._ref_targets(fn, args):
                    tgt = self.fns[qual]
                    if not tgt.is_async:  # coroutines aren't run by
                        tgt.thread = True  # the thread, see docstring
                continue                  # hop: no synchronous edge
            if callee_name in _LOOP_REGISTRARS:
                for qual in self._ref_targets(fn, args):
                    self.fns[qual].loop_cb = True
                continue
            if callee_name == "start_server":
                for qual in self._ref_targets(fn, args):
                    self.fns[qual].server_cb = True
                continue
            qual = self.resolve(fn, f)
            if qual is not None:
                fn.edges.add(qual)

    def _propagate(self):
        # coroutine reachability: async defs taint their synchronous
        # direct callees (executor hops already cut the edge)
        work = [q for q, f in self.fns.items() if f.coro]
        while work:
            fn = self.fns[work.pop()]
            for q in fn.edges:
                tgt = self.fns[q]
                if not tgt.coro:
                    tgt.coro = True
                    work.append(q)
        # thread reachability: registrar targets taint their sync
        # callees; never propagates into async defs
        work = [q for q, f in self.fns.items() if f.thread]
        while work:
            fn = self.fns[work.pop()]
            for q in fn.edges:
                tgt = self.fns[q]
                if not tgt.is_async and not tgt.thread:
                    tgt.thread = True
                    work.append(q)


# ---------------------------------------------------------------------------
# rule passes
# ---------------------------------------------------------------------------
def _blocking_pass(prog: _Program):
    for qual in sorted(prog.fns):
        fn = prog.fns[qual]
        if not fn.coro:
            continue
        where = "coroutine" if fn.is_async else \
            "function reachable from a coroutine"
        for n in _own_nodes(fn.node):
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            d = _dotted(f)
            prim = None
            if d == "time.sleep" or d.endswith(".time.sleep"):
                prim = "time.sleep() blocks the loop"
            elif isinstance(f, ast.Name) and f.id == "open":
                prim = "open() is synchronous file I/O"
            elif isinstance(f, ast.Attribute):
                t = prog.recv_type(fn, f.value)
                if f.attr in ("get", "put") and t == "thread_queue":
                    prim = ("queue.Queue.%s() parks the loop thread "
                            "on a threading condition" % f.attr)
                elif f.attr == "acquire" and t == "thread_lock":
                    prim = ("threading lock .acquire() blocks the "
                            "loop thread")
                elif f.attr == "result" and (
                        t == "cfuture"
                        or (isinstance(f.value, ast.Call)
                            and isinstance(f.value.func,
                                           ast.Attribute)
                            and f.value.func.attr == "submit")):
                    prim = ("Future.result() blocks until the "
                            "executor finishes")
                elif f.attr in ("recv", "recv_into", "sendall",
                                "connect", "accept") and \
                        t == "socket":
                    prim = "blocking socket %s()" % f.attr
                elif f.attr == "submit" and \
                        "cluster" in _dotted(f.value):
                    prim = ("sync cluster RPC %s() holds the loop "
                            "for the full round trip" % d)
            if prim is None:
                continue
            prog._add(
                "async-blocking-call", fn.mod, n.lineno, fn.display,
                "%s in %s %s — %s; hop it through "
                "run_in_executor (or mark the intended-sync site "
                "with a pragma)" % (d, where, fn.display, prim))


def _unawaited_pass(prog: _Program):
    for qual in sorted(prog.fns):
        fn = prog.fns[qual]
        for n in _own_nodes(fn.node):
            if not (isinstance(n, ast.Expr)
                    and isinstance(n.value, ast.Call)):
                continue
            callee = prog.resolve(fn, n.value.func)
            if callee is not None and prog.fns[callee].is_async:
                prog._add(
                    "async-unawaited-coroutine", fn.mod, n.lineno,
                    fn.display,
                    "%s(...) is a coroutine call whose value is "
                    "dropped — the body never runs; await it, "
                    "gather it, or wrap it in a task"
                    % prog.fns[callee].display)


def _threadsafe_pass(prog: _Program):
    for qual in sorted(prog.fns):
        fn = prog.fns[qual]
        if not fn.thread:
            continue
        for n in _own_nodes(fn.node):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)):
                continue
            f = n.func
            t = prog.recv_type(fn, f.value)
            bad = None
            if f.attr == "put_nowait" and t == "aio_queue":
                bad = "asyncio.Queue.put_nowait"
            elif f.attr == "set" and t == "aio_event":
                bad = "asyncio.Event.set"
            elif f.attr == "call_soon":
                bad = "loop.call_soon"
            if bad is None:
                continue
            prog._add(
                "async-threadsafe-boundary", fn.mod, n.lineno,
                fn.display,
                "%s runs on a non-loop thread but mutates "
                "loop-owned state via %s — asyncio structures are "
                "not thread-safe; marshal it through "
                "loop.call_soon_threadsafe" % (fn.display, bad))


def _lock_across_await_pass(prog: _Program):
    for qual in sorted(prog.fns):
        fn = prog.fns[qual]
        if not fn.is_async:
            continue
        own = set(map(id, _own_nodes(fn.node)))
        for n in _own_nodes(fn.node):
            if not isinstance(n, (ast.With, ast.AsyncWith)):
                continue
            lock = None
            for item in n.items:
                if prog.recv_type(fn, item.context_expr) == \
                        "thread_lock":
                    lock = _dotted(item.context_expr)
            if lock is None:
                continue
            if any(isinstance(c, ast.Await) and id(c) in own
                   for s in n.body for c in ast.walk(s)):
                prog._add(
                    "async-lock-across-await", fn.mod, n.lineno,
                    fn.display,
                    "threading lock %s is held across an await "
                    "point in %s — the loop can interleave another "
                    "coroutine that blocks on it, deadlocking the "
                    "loop thread against itself" % (lock, fn.display))


# ---------------------------------------------------------------------------
# exit-edge obligations: tasks and stream writers
# ---------------------------------------------------------------------------
def _settles_task(stmt: ast.AST, name: str) -> bool:
    for n in ast.walk(stmt):
        if isinstance(n, ast.Await) and _mentions(n.value, name):
            return True
        if isinstance(n, ast.Call) and \
                isinstance(n.func, ast.Attribute) and \
                n.func.attr in ("cancel", "add_done_callback") and \
                _mentions(n.func.value, name):
            return True
    return False


def _escapes_task(stmt: ast.AST, name: str) -> bool:
    for n in ast.walk(stmt):
        if isinstance(n, ast.Return) and n.value is not None and \
                _mentions(n.value, name):
            return True
        if isinstance(n, ast.Assign) and _mentions(n.value, name):
            return True
        if isinstance(n, (ast.Yield, ast.YieldFrom)) and \
                n.value is not None and _mentions(n.value, name):
            return True
        if isinstance(n, ast.Call):
            if any(_mentions(a, name) for a in n.args) or \
                    any(_mentions(k.value, name) for k in n.keywords):
                return True
    return False


def _settles_writer(stmt: ast.AST, name: str) -> bool:
    """Only ``await name.wait_closed()`` settles — ``close()`` alone
    merely schedules the close and the reset path never drains."""
    for n in ast.walk(stmt):
        if isinstance(n, ast.Await) and isinstance(n.value, ast.Call) \
                and isinstance(n.value.func, ast.Attribute) \
                and n.value.func.attr == "wait_closed" \
                and _mentions(n.value.func.value, name):
            return True
    return False


def _escapes_writer(stmt: ast.AST, name: str) -> bool:
    """Returning or storing the writer transfers ownership; passing
    it to a helper call is a BORROW and does not settle."""
    for n in ast.walk(stmt):
        if isinstance(n, ast.Return) and n.value is not None and \
                _mentions(n.value, name):
            return True
        if isinstance(n, ast.Assign) and _mentions(n.value, name):
            return True
    return False


class _Obligation:
    __slots__ = ("name", "kind", "line", "settles", "escapes")

    def __init__(self, name, kind, line):
        self.name = name
        self.kind = kind                # "task" | "writer"
        self.line = line
        if kind == "task":
            self.settles, self.escapes = _settles_task, _escapes_task
        else:
            self.settles, self.escapes = (_settles_writer,
                                          _escapes_writer)


class _ExitScanner:
    """Protolint's resource exit-edge walk, generalized: every path
    from the obligation's origin — returns, raises, unprotected
    may-raise calls, and the fall-through — must settle or escape
    it."""

    RULES = {"task": "async-task-exception",
             "writer": "async-writer-lifecycle"}

    def __init__(self, prog: _Program, fn: _Fn):
        self.prog = prog
        self.fn = fn
        self._reported = False

    def _add(self, line, ob: _Obligation, msg):
        self._reported = True
        self.prog._add(self.RULES[ob.kind], self.fn.mod, line,
                       "%s.%s" % (self.fn.display, ob.name), msg)

    def scan(self):
        fn = self.fn
        # writer params of the start_server callback: the obligation
        # exists from the first statement on
        if fn.server_cb:
            params = [a.arg for a in fn.node.args.args
                      if a.arg != "self"]
            if len(params) >= 2:
                ob = _Obligation(params[1], "writer",
                                 fn.node.lineno)
                self._run(ob, fn.node.body, [])
        # bare create_task/ensure_future expression statements
        for n in _own_nodes(fn.node):
            if isinstance(n, ast.Expr) and \
                    isinstance(n.value, ast.Call) and \
                    _is_task_ctor(n.value):
                ob = _Obligation("<dropped>", "task", n.lineno)
                self._add(n.lineno, ob,
                          "task created and immediately dropped — "
                          "its exception is silently lost; store "
                          "and await/cancel it or add a "
                          "done-callback")
        self._scan_block(fn.node.body, [])

    def _acquire(self, stmt) -> Optional[_Obligation]:
        if not isinstance(stmt, ast.Assign) or \
                len(stmt.targets) != 1:
            return None
        v = stmt.value
        tgt = stmt.targets[0]
        if isinstance(v, ast.Call) and _is_task_ctor(v) and \
                isinstance(tgt, ast.Name):
            return _Obligation(tgt.id, "task", stmt.lineno)
        # reader, writer = await asyncio.open_connection(...)
        if isinstance(v, ast.Await) and \
                isinstance(v.value, ast.Call) and \
                _dotted(v.value.func).endswith("open_connection") and \
                isinstance(tgt, ast.Tuple) and \
                len(tgt.elts) == 2 and \
                isinstance(tgt.elts[1], ast.Name):
            return _Obligation(tgt.elts[1].id, "writer",
                               stmt.lineno)
        return None

    def _run(self, ob: _Obligation, stmts, conts):
        self._reported = False
        settled = self._track(stmts, ob, protected=False)
        for cont in conts:
            if settled:
                break
            settled = self._track(cont, ob, protected=False)
        if not settled and not self._reported:
            if ob.kind == "task":
                self._add(ob.line, ob,
                          "the task bound to %r at line %d is "
                          "never awaited, cancelled, or given a "
                          "done-callback on the fall-through path "
                          "— its exception is silently lost"
                          % (ob.name, ob.line))
            else:
                self._add(ob.line, ob,
                          "the StreamWriter %r never reaches "
                          "close() + await wait_closed() (nor "
                          "escapes) on the fall-through path — the "
                          "transport is left half-closed"
                          % ob.name)

    def _scan_block(self, body, conts):
        for i, stmt in enumerate(body):
            ob = self._acquire(stmt)
            if ob is not None:
                self._run(ob, body[i + 1:], conts)
            sub_conts = [body[i + 1:]] + conts
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if sub:
                    self._scan_block(sub, sub_conts)
            for h in getattr(stmt, "handlers", ()):
                self._scan_block(h.body, sub_conts)

    def _track(self, stmts, ob: _Obligation, protected) -> bool:
        for stmt in stmts:
            # settle-by-containment applies to LEAF statements only:
            # a compound statement settling in one branch must still
            # have its other branches walked (protolint's reply-walk
            # refinement — an `if: settle()` must not cover the else)
            if not isinstance(stmt, (ast.If, ast.Try, ast.For,
                                     ast.AsyncFor, ast.While,
                                     ast.With, ast.AsyncWith)) and \
                    (ob.settles(stmt, ob.name)
                     or ob.escapes(stmt, ob.name)):
                return True
            if isinstance(stmt, ast.Try):
                if any(ob.settles(s, ob.name)
                       for s in stmt.finalbody):
                    return True           # every path runs finally
                prot = protected or _try_protects(stmt)
                if self._track(stmt.body, ob, prot):
                    return True
                continue
            if isinstance(stmt, ast.If):
                t = self._track(stmt.body, ob, protected)
                e = self._track(stmt.orelse, ob, protected)
                if t and (stmt.orelse and e):
                    return True
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While,
                                 ast.With, ast.AsyncWith)):
                if self._track(stmt.body, ob, protected):
                    return True
                continue
            if isinstance(stmt, (ast.Return, ast.Continue, ast.Break,
                                 ast.Raise)):
                if ob.kind == "task":
                    self._add(stmt.lineno, ob,
                              "exit before the task bound to %r at "
                              "line %d is awaited/cancelled — its "
                              "exception is silently lost on this "
                              "path" % (ob.name, ob.line))
                else:
                    self._add(stmt.lineno, ob,
                              "exit leaves the StreamWriter %r "
                              "without close() + await "
                              "wait_closed() — close() alone only "
                              "schedules the close; the transport "
                              "never drains on this path" % ob.name)
                return True
            if not protected:
                line = _may_raise(stmt)
                if line is not None:
                    if ob.kind == "task":
                        self._add(line, ob,
                                  "call may raise before the task "
                                  "bound to %r (line %d) is "
                                  "awaited/cancelled — the "
                                  "exception edge drops it"
                                  % (ob.name, ob.line))
                    else:
                        self._add(line, ob,
                                  "call may raise before the "
                                  "StreamWriter %r reaches close() "
                                  "+ await wait_closed() — the "
                                  "exception edge leaks the "
                                  "half-closed transport" % ob.name)
                    return True
        return False


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------
def build_model(modules: Dict[str, str]) -> _Program:
    return _Program(modules)


def analyze(modules: Dict[str, str]) -> List[Finding]:
    """Analyze ``{rel_path: source}`` as one program; findings are
    pragma-filtered per module."""
    prog = build_model(modules)
    _blocking_pass(prog)
    _unawaited_pass(prog)
    _threadsafe_pass(prog)
    _lock_across_await_pass(prog)
    for qual in sorted(prog.fns):
        _ExitScanner(prog, prog.fns[qual]).scan()
    out: List[Finding] = []
    for rel, mod in prog.modules.items():
        fs = [f for f in prog.findings if f.path == rel]
        out.extend(apply_pragmas(fs, mod.source))
    return sorted(out, key=lambda f: (f.path, f.line, f.rule))


def lint_source(source: str, rel_path: str) -> List[Finding]:
    """Single-module entry (fixtures drive this directly)."""
    return analyze({rel_path: source})


def _load_modules(root: str) -> Dict[str, str]:
    modules: Dict[str, str] = {}
    for pkg in PACKAGES:
        d = os.path.join(root, pkg)
        if not os.path.isdir(d):
            continue
        for name in sorted(os.listdir(d)):
            if not name.endswith(".py"):
                continue
            rel = "%s/%s" % (pkg, name)
            with open(os.path.join(root, rel)) as f:
                modules[rel] = f.read()
    return modules


def triggered(only: Optional[Set[str]]) -> bool:
    """Does the change set intersect the loop's trigger scope?"""
    if only is None:
        return True
    return any(p.startswith(TRIGGER_PREFIXES) for p in only)


def run(root: str, only: Optional[Set[str]] = None) -> List[Finding]:
    """Lint the live event-loop code.  ``only`` (--changed-only):
    skipped unless serving/, obs/, or tools/analysis/ changed; when
    it runs, reporting is restricted to changed files (pylocklint's
    convention — tier-1 always runs full scope)."""
    if not triggered(only):
        return []
    findings = analyze(_load_modules(root))
    if only is not None:
        findings = [f for f in findings if f.path in only]
    return findings
