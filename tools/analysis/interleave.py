"""Deterministic interleaving explorer for the serving cluster
(ISSUE 7 tentpole, dynamic half — a loom-lite).

The slow-tier cluster tests run real threads under the OS scheduler:
one interleaving per run, usually the same one.  This module replaces
the OS scheduler with a **cooperative, seeded, deterministic** one and
explores MANY interleavings:

* Every thread the cluster creates becomes a managed task parked on
  its own semaphore; exactly one task runs at a time (serialized, so
  every "race" is a *chosen order*, reproducible from the seed).
* **Yield points** — where the scheduler may switch tasks — are the
  cluster's synchronization operations (lock acquire/release, event
  set/clear/wait, thread spawn, clock reads, sleeps) plus, under the
  ``random`` strategy, every traced source line of ``cluster.py``
  (``sys.settrace``; ``sys.monitoring`` would serve on 3.12+).
* **Time is modeled**: ``perf_counter`` returns scheduler time, which
  advances a tick per yield and *jumps* to the earliest timed-wait
  deadline when every task is blocked — so TTL expiry, watchdog
  periods, and idle-loop timeouts execute in microseconds of real time
  and identically on every run.
* **Blocking primitives are scheduler-aware**: a managed task never
  blocks the real OS thread; it marks itself blocked on a predicate
  and hands the token over.  If no task is runnable and no deadline is
  pending, that is a **real deadlock** of the code under test —
  reported as :class:`DeadlockError` with a per-task dump (and proven
  detectable by ``tests/test_interleave.py``'s seeded-deadlock toy).

Injection is scoped, not global: :func:`patch` swaps the ``threading``
and ``time`` module objects *of* ``mxnet_tpu.serving.cluster`` AND
``mxnet_tpu.serving.engine`` for scheduler-aware shims, so jax /
numpy internals keep their real primitives.  (The engine joined the
sweep in round 21: its overlap mode runs a planner thread against
the engine lock, so planner-vs-step-vs-cancel interleavings are now
part of the subject — ``wl_overlap_plan``.)

Strategies
----------
``random``   pick uniformly among runnable tasks at every sync point;
             additionally preempt at traced ``cluster.py`` lines with
             probability ``line_preempt`` (default 0.1).
``preempt``  force a context switch at every lock acquire/release
             (the targeted mode: maximum contention reordering).

Seed protocol (``docs/static_analysis.md``): a schedule is fully
identified by ``(workload, strategy, seed)``; ``Stats.trace_hash`` is
the sha1 of the (task, kind) yield sequence and must be bit-identical
across runs of the same triple — ``test_deterministic_per_seed`` pins
exactly that.
"""
from __future__ import annotations

import hashlib
import random
import sys
import threading as _real_threading
import time as _real_time
from typing import Callable, Dict, List, Optional

__all__ = ["DeadlockError", "SchedulerShutdown", "Scheduler",
           "Stats", "patch", "run_schedule"]

_RUNNABLE, _BLOCKED, _FINISHED = "runnable", "blocked", "finished"


class DeadlockError(BaseException):
    """Every managed task is blocked and no timed wait can fire.
    Derives BaseException so the cluster's ``except Exception``
    failover path cannot swallow the verdict."""


class SchedulerShutdown(BaseException):
    """Teardown signal for leftover managed tasks."""


class Stats:
    __slots__ = ("yields", "switches", "tasks", "trace_hash",
                 "model_time")

    def __init__(self, yields, switches, tasks, trace_hash,
                 model_time):
        self.yields = yields
        self.switches = switches
        self.tasks = tasks
        self.trace_hash = trace_hash
        self.model_time = model_time

    def __repr__(self):
        return ("Stats(yields=%d, switches=%d, tasks=%d, "
                "trace=%s, t=%.4f)" % (self.yields, self.switches,
                                       self.tasks, self.trace_hash[:12],
                                       self.model_time))


class _Task:
    __slots__ = ("tid", "name", "sem", "state", "pred", "deadline",
                 "reason", "thread", "timed_out")

    def __init__(self, tid, name):
        self.tid = tid
        self.name = name
        self.sem = _real_threading.Semaphore(0)
        self.state = _RUNNABLE
        self.pred: Optional[Callable[[], bool]] = None
        self.deadline: Optional[float] = None
        self.reason = ""
        self.thread: Optional[_real_threading.Thread] = None
        self.timed_out = False


class Scheduler:
    """The cooperative scheduler.  One instance per schedule run."""

    def __init__(self, seed: int, mode: str = "random",
                 line_preempt: float = 0.1):
        if mode not in ("random", "preempt"):
            raise ValueError("mode must be 'random' or 'preempt'")
        self.rng = random.Random(seed)
        self.mode = mode
        self.line_preempt = line_preempt
        self.now = 0.0
        self._mu = _real_threading.Lock()
        self._tasks: Dict[int, _Task] = {}
        self._next_tid = 0
        self._local = _real_threading.local()
        self.abort: Optional[BaseException] = None
        self.yields = 0
        self.switches = 0
        self._sha = hashlib.sha1()
        self.root_done = _real_threading.Event()
        self.root_error: Optional[BaseException] = None
        from mxnet_tpu.serving import cluster as _cluster_mod
        self._traced_file = _cluster_mod.__file__

    # ------------------------------------------------------ plumbing --
    def _me(self) -> Optional[_Task]:
        return getattr(self._local, "task", None)

    def _new_task(self, name) -> _Task:
        task = _Task(self._next_tid, name)
        self._next_tid += 1
        self._tasks[task.tid] = task
        return task

    def _mark(self, tid: int, kind: str):
        self._sha.update(("%d:%s;" % (tid, kind)).encode())

    def _check_abort(self):
        if self.abort is not None:
            raise self.abort

    # the per-thread trace functions (sys.settrace): 'line' events in
    # cluster.py are extra yield points under the random strategy
    def _global_trace(self, frame, event, arg):
        if event == "call" and \
                frame.f_code.co_filename == self._traced_file:
            return self._local_trace
        return None

    def _local_trace(self, frame, event, arg):
        if event == "line":
            self.yield_point("line")
        return self._local_trace

    # ----------------------------------------------------- the core --
    def _promote_locked(self):
        """BLOCKED tasks whose predicate turned true become runnable;
        expired deadlines fire."""
        for t in self._tasks.values():
            if t.state != _BLOCKED:
                continue
            if t.pred is not None and t.pred():
                t.state = _RUNNABLE
                t.pred = None
                t.deadline = None
            elif t.deadline is not None and self.now >= t.deadline:
                t.state = _RUNNABLE
                t.pred = None
                t.deadline = None
                t.timed_out = True

    def _runnable_locked(self) -> List[_Task]:
        self._promote_locked()
        return [t for t in self._tasks.values()
                if t.state == _RUNNABLE]

    def _advance_or_deadlock_locked(self) -> List[_Task]:
        """No runnable task: jump model time to the earliest deadline,
        or declare deadlock."""
        deadlines = [t.deadline for t in self._tasks.values()
                     if t.state == _BLOCKED and t.deadline is not None]
        if deadlines:
            self.now = max(self.now, min(deadlines))
            return self._runnable_locked()
        live = [t for t in self._tasks.values()
                if t.state != _FINISHED]
        if not live:
            return []
        dump = "; ".join(
            "task %d (%s): blocked on %s" % (t.tid, t.name, t.reason)
            for t in sorted(live, key=lambda t: t.tid))
        err = DeadlockError(
            "all %d live task(s) blocked with no timed wait — "
            "deadlock: %s" % (len(live), dump))
        self.abort = err
        for t in self._tasks.values():
            if t.state != _FINISHED:
                t.sem.release()
        raise err

    def _choose_locked(self, candidates: List[_Task], cur: _Task,
                       kind: str) -> _Task:
        candidates = sorted(candidates, key=lambda t: t.tid)
        if self.mode == "preempt":
            if kind in ("acquire", "release"):
                others = [t for t in candidates if t is not cur]
                pool = others or candidates
            else:
                pool = [cur] if cur in candidates else candidates
            return self.rng.choice(pool)
        # random strategy
        if kind == "line":
            if self.rng.random() >= self.line_preempt:
                return cur if cur in candidates else \
                    self.rng.choice(candidates)
        return self.rng.choice(candidates)

    def yield_point(self, kind: str):
        task = self._me()
        if task is None:
            return                      # unmanaged thread: no-op
        self._check_abort()
        nxt = None
        with self._mu:
            self.yields += 1
            self.now += 1e-7
            self._mark(task.tid, kind)
            candidates = self._runnable_locked()
            chosen = self._choose_locked(candidates, task, kind)
            if chosen is not task:
                self.switches += 1
                self._mark(chosen.tid, "run")
                nxt = chosen
                nxt.sem.release()
        if nxt is not None:
            task.sem.acquire()
            self._check_abort()

    def block_until(self, pred: Callable[[], bool],
                    timeout: Optional[float], reason: str) -> bool:
        """Park the current task until ``pred()`` holds or the model
        deadline passes.  Returns what ``Event.wait`` would."""
        task = self._me()
        if task is None:
            raise RuntimeError(
                "block_until from an unmanaged thread (reason=%s) — "
                "run the workload inside run_schedule()" % reason)
        deadline = None if timeout is None else self.now + timeout
        while True:
            with self._mu:
                self._check_abort()
                if pred():
                    return True
                if deadline is not None and self.now >= deadline:
                    return False
                task.state = _BLOCKED
                task.pred = pred
                task.deadline = deadline
                task.reason = reason
                task.timed_out = False
                self._mark(task.tid, "block:" + reason)
                candidates = [t for t in self._runnable_locked()
                              if t is not task]
                if not candidates:
                    candidates = [t for t in
                                  self._advance_or_deadlock_locked()
                                  if t is not task]
                if task.state == _RUNNABLE:
                    # our own deadline fired during the jump
                    if task.timed_out:
                        return pred()
                    continue
                nxt = self.rng.choice(sorted(candidates,
                                             key=lambda t: t.tid))
                self.switches += 1
                self._mark(nxt.tid, "run")
                nxt.sem.release()
            task.sem.acquire()
            self._check_abort()

    def task_finished(self):
        task = self._me()
        with self._mu:
            task.state = _FINISHED
            self._mark(task.tid, "finish")
            if self.abort is not None:
                return
            candidates = self._runnable_locked()
            if not candidates:
                live = [t for t in self._tasks.values()
                        if t.state != _FINISHED]
                if not live:
                    return
                try:
                    candidates = self._advance_or_deadlock_locked()
                except DeadlockError:
                    return          # abort propagated to woken tasks
                if not candidates:
                    return
            nxt = self.rng.choice(sorted(candidates,
                                         key=lambda t: t.tid))
            self._mark(nxt.tid, "run")
            nxt.sem.release()

    # ------------------------------------------------------- spawning --
    def _boot(self, task: _Task, target, args, kwargs):
        self._local.task = task
        if self.mode == "random" and self.line_preempt > 0:
            sys.settrace(self._global_trace)
        task.sem.acquire()              # wait for the first grant
        try:
            self._check_abort()
            target(*args, **kwargs)
        except BaseException as e:      # noqa: BLE001
            if task.name == "<root>":
                self.root_error = e
            elif self.abort is None and not isinstance(
                    e, SchedulerShutdown):
                # a non-root task target raised PAST the cluster's own
                # exception handling — a harness or model bug, never a
                # legal schedule outcome (replica failure is caught
                # inside _worker): abort the schedule loudly
                with self._mu:
                    if self.abort is None:
                        self.abort = e
                        for t in self._tasks.values():
                            if t.state != _FINISHED:
                                t.sem.release()
        finally:
            if task.name == "<root>":
                self.root_done.set()
            self.task_finished()

    def spawn(self, name, target, args=(), kwargs=None) -> _Task:
        with self._mu:
            task = self._new_task(name)
            self._mark(task.tid, "spawn")
        th = _real_threading.Thread(
            target=self._boot, args=(task, target, args, kwargs or {}),
            daemon=True, name="ilv-%s" % name)
        task.thread = th
        th.start()
        return task

    def start_root(self, target):
        root = self.spawn("<root>", target)
        with self._mu:
            root.sem.release()          # root runs first
        return root

    def shutdown(self):
        with self._mu:
            if self.abort is None:
                self.abort = SchedulerShutdown("schedule over")
            for t in self._tasks.values():
                if t.state != _FINISHED:
                    t.sem.release()
        for t in self._tasks.values():
            if t.thread is not None:
                t.thread.join(timeout=5)

    def stats(self) -> Stats:
        return Stats(self.yields, self.switches, len(self._tasks),
                     self._sha.hexdigest(), self.now)


# ---------------------------------------------------------------------------
# scheduler-aware primitives (what the cluster sees as `threading`/`time`)
# ---------------------------------------------------------------------------
class SchedLock:
    _reentrant = False

    def __init__(self, sched: Scheduler):
        self._sched = sched
        self._owner: Optional[int] = None
        self._count = 0

    def acquire(self, blocking=True, timeout=-1):
        sched = self._sched
        task = sched._me()
        if task is None:
            raise RuntimeError("SchedLock from unmanaged thread")
        sched.yield_point("acquire")
        if self._owner == task.tid and self._reentrant:
            self._count += 1
            return True
        if self._owner is None:
            self._owner = task.tid
            self._count = 1
            return True
        if not blocking:
            return False
        ok = sched.block_until(
            lambda: self._owner is None,
            None if timeout in (-1, None) else timeout, "lock")
        if not ok:
            return False
        self._owner = task.tid
        self._count = 1
        return True

    def release(self):
        task = self._sched._me()
        if self._owner != (task.tid if task else None):
            raise RuntimeError("release of un-owned SchedLock")
        self._count -= 1
        if self._count == 0:
            self._owner = None
        self._sched.yield_point("release")

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *a):
        self.release()
        return False


class SchedRLock(SchedLock):
    _reentrant = True


class SchedEvent:
    """Model event with a REAL mirror so unmanaged threads (none in
    the explorer's own runs, but belt-and-braces) still wake."""

    def __init__(self, sched: Scheduler):
        self._sched = sched
        self._flag = False
        self._real = _real_threading.Event()

    def is_set(self):
        return self._flag

    def set(self):
        self._flag = True
        self._real.set()
        self._sched.yield_point("event-set")

    def clear(self):
        self._flag = False
        self._real.clear()
        self._sched.yield_point("event-clear")

    def wait(self, timeout=None):
        if self._sched._me() is None:
            return self._real.wait(timeout)
        if self._flag:
            self._sched.yield_point("event-wait")
            return True
        return self._sched.block_until(lambda: self._flag, timeout,
                                       "event")


class SchedThread:
    """threading.Thread stand-in: start() registers a managed task."""

    def __init__(self, sched=None, target=None, args=(), kwargs=None,
                 daemon=None, name=None):
        self._sched = sched
        self._target = target
        self._args = args
        self._kwargs = kwargs or {}
        self.daemon = daemon
        self.name = name or "sched-thread"
        self._task: Optional[_Task] = None

    def start(self):
        self._task = self._sched.spawn(self.name, self._target,
                                       self._args, self._kwargs)
        self._sched.yield_point("spawn")

    def is_alive(self):
        return self._task is not None and \
            self._task.state != _FINISHED

    def join(self, timeout=None):
        task = self._task
        if task is None:
            return
        if self._sched._me() is None:
            if task.thread is not None:
                task.thread.join(timeout)
            return
        self._sched.block_until(lambda: task.state == _FINISHED,
                                timeout, "join:%s" % self.name)


class _ThreadingShim:
    def __init__(self, sched: Scheduler):
        self._sched = sched

    def Thread(self, target=None, args=(), kwargs=None, daemon=None,
               name=None):
        return SchedThread(self._sched, target, args, kwargs, daemon,
                           name)

    def Event(self):
        return SchedEvent(self._sched)

    def Lock(self):
        return SchedLock(self._sched)

    def RLock(self):
        return SchedRLock(self._sched)


class _TimeShim:
    def __init__(self, sched: Scheduler):
        self._sched = sched

    def perf_counter(self):
        self._sched.yield_point("clock")
        return self._sched.now

    def sleep(self, t):
        if self._sched._me() is None:
            _real_time.sleep(t)
            return
        self._sched.block_until(lambda: False, max(0.0, float(t)),
                                "sleep")


class patch:
    """Context manager: swap ``mxnet_tpu.serving.cluster``'s and
    ``mxnet_tpu.serving.engine``'s module references to ``threading``
    / ``time`` for scheduler shims (the engine's overlap planner
    thread is under sweep since round 21)."""

    def __init__(self, sched: Scheduler):
        self.sched = sched

    def __enter__(self):
        from mxnet_tpu.serving import cluster, engine
        self._mods = (cluster, engine)
        self._saved = [(m.threading, m.time) for m in self._mods]
        shims = (_ThreadingShim(self.sched), _TimeShim(self.sched))
        for m in self._mods:
            m.threading, m.time = shims
        return self.sched

    def __exit__(self, *a):
        for m, (th, tm) in zip(self._mods, self._saved):
            m.threading, m.time = th, tm
        return False


def run_schedule(workload: Callable[[], None], seed: int,
                 mode: str = "random", line_preempt: float = 0.1,
                 real_timeout: float = 300.0) -> Stats:
    """Run ``workload()`` (which builds, drives, and closes a
    ``ServingCluster``) under one deterministic schedule.

    Raises whatever the workload raises (assertion failures surface
    with the seed in the pytest parameterization), ``DeadlockError``
    on a model deadlock, and ``RuntimeError`` if the schedule exceeds
    ``real_timeout`` real seconds (a hang the model cannot see —
    e.g. a real primitive smuggled past the shims)."""
    sched = Scheduler(seed, mode=mode, line_preempt=line_preempt)
    with patch(sched):
        sched.start_root(workload)
        finished = sched.root_done.wait(real_timeout)
        if not finished:
            sched.shutdown()
            raise RuntimeError(
                "interleave: schedule (seed=%d, mode=%s) still "
                "running after %.0fs real time — %r"
                % (seed, mode, real_timeout, sched.stats()))
        # let the cluster's own threads wind down (workloads close()
        # before returning, so normally everything is finished here)
        sched.shutdown()
    if sched.root_error is not None:
        raise sched.root_error
    return sched.stats()
