"""Native concurrency pass (mxlint analyzer 3 of 3).

A lightweight lexical/structural checker over ``native/src/*.cc`` —
not a compiler, but enough structure (comment/string stripping, brace
scoping, lock_guard/unique_lock lifetimes, a per-file call graph with
transitive lock sets) to machine-check the locking discipline the
sources document in prose.

Rules
-----
``cv-wait-predicate``  every ``cv.wait(lk)`` must use the predicate
    overload (``wait(lk, pred)``; ``wait_for``/``wait_until`` need the
    3-arg form) — bare waits are spurious-wakeup bugs.

``cv-pred-unlocked``  a store to a condition-variable predicate
    variable (config: ``cv_preds``) outside the cv's mutex.  The
    classic missed-wakeup: a waiter that evaluated the predicate false
    still holds the mutex until it blocks, so a store+notify in that
    window is lost (this exact bug lived in ``Engine::~Engine`` and
    ``ImageRecordLoader::StopWorkers`` until this pass caught it).

``guarded-field``  a shared field (config: ``guarded``) accessed
    outside its documented mutex.  Fields guarded per-object
    (``EngineVar::mu``) are checked object-insensitively — any held
    ``->mu`` satisfies the guard; the engine never holds two vars at
    once, and TSan (``make tsan``) backstops what this approximation
    misses.  ``std::atomic`` fields are exempt by not being configured.

``lock-order``  acquiring a ranked mutex while holding a higher-ranked
    one (config: ``order``, lower rank = acquire first), directly or
    through a same-file call chain; also re-acquiring a held mutex.

Annotations (in the comment block directly above a function)::

    // mxlint: requires(EngineVar::mu)   -- caller holds it (precondition)
    // mxlint: allow(<rule>)             -- suppress on the next line

Documented non-rules: ``Opr`` fields are single-owner (the ``wait``
countdown is the hand-off); ``outstanding_`` uses the safe
decrement-then-lock-then-notify pattern (the *notify* is under the
mutex, so the waiter cannot sleep through it).
"""
from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding, PRAGMA_RE, apply_pragmas

__all__ = ["CONFIG", "lint_file", "run"]

# per-file locking discipline — the machine-readable version of the
# design comments in engine.h / image_loader.h / storage.h
CONFIG = {
    "engine.cc": {
        "order": {"sched_mu_": 0, "EngineVar::mu": 1, "pool_mu_": 2,
                  "err_mu_": 3},
        "guarded": {
            "member": {"version": "EngineVar::mu",
                       "active_reads": "EngineVar::mu",
                       "active_write": "EngineVar::mu",
                       "exception": "EngineVar::mu",
                       "queue": "EngineVar::mu"},
            "self": {"ready_": "pool_mu_", "global_err_": "err_mu_"},
        },
        "cv_preds": {"stop_": "pool_mu_"},
    },
    "image_loader.cc": {
        "order": {"mu_": 0},
        "guarded": {
            "member": {"ready": "mu_", "pad": "mu_"},
            "self": {"has_error_": "mu_", "error_": "mu_"},
        },
        "cv_preds": {"stop_": "mu_"},
    },
    "storage.cc": {
        "order": {"mu_": 0},
        "guarded": {
            "member": {},
            "self": {"live_": "mu_", "free_pool_": "mu_",
                     "bytes_live_": "mu_", "bytes_pooled_": "mu_",
                     "num_allocs_": "mu_"},
        },
        "cv_preds": {},
    },
    "c_api.cc": {
        "order": {"EngineVar::mu": 0, "g_engine_mu": 0},
        "guarded": {
            "member": {"version": "EngineVar::mu"},
            "self": {"g_engine": "g_engine_mu"},
        },
        "cv_preds": {},
    },
}

_KEYWORDS = {"if", "for", "while", "switch", "catch", "return", "throw",
             "sizeof", "new", "delete", "else", "do", "case"}

_LOCK_RE = re.compile(
    r"\b(?:std::)?(?:lock_guard|unique_lock|scoped_lock)\s*"
    r"<[^>]*>\s*\w+\s*\(([^)]*)\)")
_WAIT_RE = re.compile(r"\.\s*wait(_for|_until)?\s*\(")
_FN_NAME_RE = re.compile(r"\b([A-Za-z_][\w]*(?:::~?[A-Za-z_]\w*)*)\s*\(")


def _strip_code(text: str) -> str:
    """Blank out comments, string and char literals, preserving
    newlines (line numbers survive)."""
    def blank(m):
        return re.sub(r"[^\n]", " ", m.group())
    text = re.sub(r"/\*.*?\*/", blank, text, flags=re.S)
    text = re.sub(r"//[^\n]*", blank, text)
    text = re.sub(r'"(?:\\.|[^"\\\n])*"', blank, text)
    text = re.sub(r"'(?:\\.|[^'\\\n])'", blank, text)
    return text


def _norm_mutex(expr: str) -> Optional[str]:
    """Normalize a lock_guard constructor argument to a discipline
    name; None = unranked local/unknown (ignored)."""
    expr = expr.split(",")[0].strip()
    if re.search(r"(?:->|\.)\s*mu$", expr):
        return "EngineVar::mu"
    m = re.match(r"^\w+$", expr)
    if m:
        name = expr
        if name.endswith("_mu") or name.endswith("mu_") or \
                name.endswith("_mu_"):
            return name
    return None


def _arg_count(code: str, open_idx: int) -> int:
    """Count top-level comma-separated args of the paren group opening
    at ``open_idx`` (index of '(')."""
    depth = 0
    commas = 0
    empty = True
    i = open_idx
    while i < len(code):
        ch = code[i]
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
            if depth == 0:
                break
        elif ch == "," and depth == 1:
            commas += 1
        elif depth >= 1 and not ch.isspace():
            empty = False
        i += 1
    return 0 if empty else commas + 1


class _Scanner:
    def __init__(self, rel_path: str, text: str, config: dict):
        self.rel = rel_path
        self.raw_lines = text.splitlines()
        self.code = _strip_code(text)
        self.cfg = config
        self.order: Dict[str, int] = config.get("order", {})
        self.findings: List[Finding] = []
        # events for the transitive pass: (line, fn, callee, held)
        self.calls: List[Tuple[int, str, str, Tuple[str, ...]]] = []
        # fn -> set of mutexes it directly acquires
        self.direct: Dict[str, Set[str]] = {}

    def _add(self, rule, line, symbol, msg):
        self.findings.append(Finding("native", rule, self.rel, line,
                                     symbol, msg))

    def _requires_for(self, fn_line: int) -> Set[str]:
        """``mxlint: requires(M)`` pragmas in the comment block above
        the function starting at ``fn_line``."""
        out: Set[str] = set()
        ln = fn_line - 1
        while ln >= 1:
            s = self.raw_lines[ln - 1].strip()
            if not s or s.startswith("//") or s.startswith("*") or \
                    s.startswith("/*"):
                for kind, val in PRAGMA_RE.findall(s):
                    if kind == "requires":
                        out.update(v.strip() for v in val.split(","))
                ln -= 1
            else:
                break
        return out

    # ------------------------------------------------------------------
    def scan(self) -> List[Finding]:
        code = self.code
        lines = code.splitlines(keepends=True)
        offsets = []
        pos = 0
        for ln in lines:
            offsets.append(pos)
            pos += len(ln)

        depth = 0
        fn: Optional[str] = None
        fn_depth = 0
        held: List[Tuple[str, int]] = []   # (mutex, acquired-at depth)
        requires: Set[str] = set()
        chunk_start = 0
        fn_names = self._collect_fn_names()

        def line_of(idx: int) -> int:
            lo, hi = 0, len(offsets) - 1
            while lo < hi:
                mid = (lo + hi + 1) // 2
                if offsets[mid] <= idx:
                    lo = mid
                else:
                    hi = mid - 1
            return lo + 1

        i = 0
        while i < len(code):
            ch = code[i]
            if ch == "{":
                chunk = code[chunk_start:i]
                if fn is None:
                    name = self._fn_header_name(chunk)
                    if name is not None:
                        fn = name
                        fn_depth = depth
                        requires = self._requires_for(line_of(i))
                        self.direct.setdefault(fn, set())
                else:
                    # statements headed by this brace (if/for/while
                    # conditions, wait(lk, [&] {...) calls) carry field
                    # and wait accesses of their own
                    self._scan_stmt(chunk, chunk_start, fn, held,
                                    requires, fn_names, line_of, depth)
                depth += 1
                chunk_start = i + 1
            elif ch == "}":
                if fn is not None:
                    self._scan_stmt(code[chunk_start:i], chunk_start,
                                    fn, held, requires, fn_names,
                                    line_of, depth)
                depth -= 1
                held[:] = [h for h in held if h[1] <= depth]
                if fn is not None and depth <= fn_depth:
                    fn = None
                    requires = set()
                chunk_start = i + 1
            elif ch == ";":
                self._scan_stmt(code[chunk_start:i + 1], chunk_start,
                                fn, held, requires, fn_names, line_of,
                                depth)
                chunk_start = i + 1
            i += 1
        self._transitive_pass()
        return self.findings

    def _collect_fn_names(self) -> Set[str]:
        names = set()
        for m in _FN_NAME_RE.finditer(self.code):
            base = m.group(1).split("::")[-1]
            if base not in _KEYWORDS:
                names.add(base)
        return names

    def _fn_header_name(self, chunk: str) -> Optional[str]:
        """Function name if ``chunk`` (text between the previous
        ``;{}`` and this ``{``) reads like a function header."""
        m = _FN_NAME_RE.search(chunk)
        if not m:
            return None
        base = m.group(1).split("::")[-1]
        if base in _KEYWORDS:
            return None
        return base

    # ------------------------------------------------------------------
    def _scan_stmt(self, stmt: str, start: int, fn, held, requires,
                   fn_names, line_of, depth):
        if fn is None:
            # namespace-scope declarations (e.g. the g_engine definition
            # itself) are not accesses
            return
        cfg = self.cfg
        held_names = {h[0] for h in held} | requires

        # lock acquisition
        for m in _LOCK_RE.finditer(stmt):
            norm = _norm_mutex(m.group(1))
            line = line_of(start + m.start())
            if norm is None:
                continue
            if fn is not None:
                self.direct.setdefault(fn, set()).add(norm)
            rank = self.order.get(norm)
            if norm in held_names:
                self._add("lock-order", line, norm,
                          "re-acquiring %s already held "
                          "(self-deadlock)" % norm)
            elif rank is not None:
                for h, _ in held:
                    hr = self.order.get(h)
                    if hr is not None and hr > rank:
                        self._add("lock-order", line, norm,
                                  "acquiring %s (rank %d) while "
                                  "holding %s (rank %d) — documented "
                                  "order violated" % (norm, rank, h,
                                                      hr))
            held.append((norm, depth))
            held_names.add(norm)

        # condvar waits need the predicate overload
        for m in _WAIT_RE.finditer(stmt):
            suffix = m.group(1) or ""
            open_idx = start + m.end() - 1
            n = _arg_count(self.code, open_idx)
            need = 1 if suffix == "" else 2
            if n <= need:
                self._add("cv-wait-predicate", line_of(open_idx),
                          "wait" + suffix,
                          "condition_variable %s without a predicate "
                          "— spurious wakeups break the protocol"
                          % ("wait" + suffix))

        # predicate stores outside the cv mutex
        for var, mu in cfg.get("cv_preds", {}).items():
            for m in re.finditer(
                    r"\b%s\s*(?:\.\s*(?:store|fetch_\w+)\s*\(|=[^=]|"
                    r"\+\+|--)" % re.escape(var), stmt):
                if mu not in held_names:
                    self._add("cv-pred-unlocked",
                              line_of(start + m.start()), var,
                              "store to cv predicate %r outside %s — "
                              "missed-wakeup window (waiter holds the "
                              "mutex between predicate check and "
                              "block)" % (var, mu))

        # guarded fields
        guarded = cfg.get("guarded", {})
        for field, mu in guarded.get("member", {}).items():
            for m in re.finditer(r"(?:->|\.)\s*%s\b(?!\s*\()"
                                 % re.escape(field), stmt):
                if mu not in held_names:
                    self._add("guarded-field",
                              line_of(start + m.start()), field,
                              "%r accessed outside its documented "
                              "mutex %s" % (field, mu))
        for field, mu in guarded.get("self", {}).items():
            for m in re.finditer(r"(?<![\w>.])%s\b" % re.escape(field),
                                 stmt):
                if mu not in held_names:
                    self._add("guarded-field",
                              line_of(start + m.start()), field,
                              "%r accessed outside its documented "
                              "mutex %s" % (field, mu))

        # call sites — ALL edges feed the transitive closure; only the
        # ones made while holding a lock are checked in the report pass
        for m in _FN_NAME_RE.finditer(stmt):
            base = m.group(1).split("::")[-1]
            if base in fn_names and base != fn and \
                    base not in _KEYWORDS:
                self.calls.append((line_of(start + m.start()), fn,
                                   base, tuple(sorted(held_names))))

    # ------------------------------------------------------------------
    def _transitive_pass(self):
        trans: Dict[str, Set[str]] = {f: set(s)
                                      for f, s in self.direct.items()}
        changed = True
        while changed:
            changed = False
            for line, caller, callee, _ in self.calls:
                if callee in trans and caller in trans:
                    before = len(trans[caller])
                    trans[caller] |= trans[callee]
                    if len(trans[caller]) != before:
                        changed = True
        for line, caller, callee, held in self.calls:
            if not held:
                continue
            for m in trans.get(callee, ()):
                rank = self.order.get(m)
                if rank is None:
                    continue
                if m in held:
                    self._add("lock-order", line, m,
                              "call to %s() may re-acquire held %s"
                              % (callee, m))
                    continue
                for h in held:
                    hr = self.order.get(h)
                    if hr is not None and hr > rank:
                        self._add("lock-order", line, m,
                                  "call to %s() may acquire %s (rank "
                                  "%d) while %s (rank %d) is held"
                                  % (callee, m, rank, h, hr))


def lint_file(path: str, rel_path: str,
              config: Optional[dict] = None) -> List[Finding]:
    if config is None:
        config = CONFIG.get(os.path.basename(rel_path))
        if config is None:
            config = {"order": {}, "guarded": {}, "cv_preds": {}}
    with open(path) as f:
        text = f.read()
    findings = _Scanner(rel_path, text, config).scan()
    return apply_pragmas(findings, text)


def run(root: str, only=None) -> List[Finding]:
    """``only``: optional set of repo-relative paths (--changed-only)."""
    src = os.path.join(root, "native", "src")
    findings: List[Finding] = []
    if not os.path.isdir(src):
        return findings
    for name in sorted(os.listdir(src)):
        rel = "native/src/" + name
        if name.endswith(".cc") and (only is None or rel in only):
            findings.extend(lint_file(os.path.join(src, name), rel))
    return findings
