"""Python concurrency pass (mxlint analyzer 4 — ISSUE 7 tentpole).

The serving layer is ~1.5k lines of threaded Python (cluster router /
watchdog / failover, prefix-cache refcounts, prefetch workers) whose
shared-state discipline was previously enforced only by prose comments
and the slow-tier tests not hanging.  This pass machine-checks it the
way ``native_lint`` checks the C++ engine: AST + a cross-module call
graph over ``mxnet_tpu/serving/``, ``mxnet_tpu/obs/`` and
``mxnet_tpu/io/``.

Rules
-----
``py-guarded-field``  **Inferred** guarded-by: a field written under
    ``with self._mu:`` in at least one site must be written under that
    same lock at every site.  No configuration table — the guard set is
    inferred per field from the code itself (writes in ``__init__`` are
    exempt: the object is not yet published).  Mutating container
    calls (``x.items.append(...)`` etc.) count as writes.  Reads are
    deliberately NOT checked: the repo leans on GIL-atomic advisory
    reads (e.g. ``_Replica.waiting``) and flagging them would drown
    the signal.

``py-lock-order``  Lock-order cycles across cluster ↔ engine ↔
    prefix_cache ↔ obs: every ``with lock:`` nesting — direct or
    through the transitive call graph — contributes an ordered edge
    (A held → B acquired); a cycle in that digraph is a deadlock two
    threads can reach by arriving from opposite ends.  Also flags
    re-acquiring a held non-reentrant ``threading.Lock`` (RLocks are
    reentrant and exempt from self-reacquisition).

``py-cv-wait-predicate``  ``cv.wait()`` on a ``threading.Condition``
    without the predicate overload — spurious wakeups break the
    protocol; use ``wait_for(pred)``.

``py-notify-unlocked``  ``cv.notify()`` / ``cv.notify_all()`` outside
    the condition's ``with cv:`` block.  At runtime this raises
    RuntimeError only if the lock is genuinely unheld at that instant;
    statically it is a missed-wakeup (or crash) waiting to happen.

``py-blocking-under-lock``  A blocking call while holding a lock,
    directly or through the call graph: ``queue.Queue`` get/put,
    ``Event.wait`` / ``Condition.wait``, ``Future.result()`` (names
    bound from ``.submit(...)``), ``time.sleep``, and jitted-step
    dispatch (``*step_fn(...)``, ``.step()`` / ``.run()`` methods,
    ``block_until_ready``) — a device dispatch inside a critical
    section serializes every other thread behind the compiled program.

``py-ref-leak``  PrefixCache refcount balance: entries returned by
    ``prefix.match(...)`` hold one ref each, so on **every** exit of
    the acquiring function they must either be released
    (``prefix.release(entries)``) or escape into owned state
    (``req.prefix_entries = entries`` — released later by
    ``_release``).  Exception edges count: a call that can raise
    between the ``match`` and the release/escape leaks the refs unless
    a surrounding ``try`` releases them in a handler or ``finally``.
    Direct ``.refs`` mutation outside ``prefix_cache.py`` also flags —
    the count is the cache's private invariant.

Conventions honored (mirroring the native pass):

* ``# mxlint: allow(<rule>)`` on the line or the comment block above —
  the shared pragma machinery in ``findings.py``.
* ``# mxlint: requires(<Class._lock>)`` in the comment block above a
  ``def`` — the caller holds that lock (precondition).
* A method whose name ends in ``_locked`` implicitly requires its
  class's lock when the class defines exactly one — the
  ``ServingCluster._route_locked`` naming convention, machine-checked.

Approximations (documented, TSan-free Python edition): method calls
resolve through ``self`` exactly, through typed attributes
(``self.prefix = PrefixCache(...)``) exactly, and otherwise only when
the method name is **unique** across the analyzed modules — ambiguous
names contribute no call edge rather than false ones.  Locks on
non-``self`` receivers are identified by (module, attribute) — good
enough while each module spells its locks distinctly.  Guarded-field
groups for non-``self`` receivers are scoped by the WRITING class as
well as the attribute (round 15): two router classes in one module
each mutating their own request records under their own lock must
not alias into one group and flag the minority lock's sites.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding, PRAGMA_RE, apply_pragmas

__all__ = ["PACKAGES", "lint_source", "analyze", "run"]

# repo-relative package roots the pass analyzes as ONE program (the
# cross-module call graph spans all of them).  mxnet_tpu/kvstore joined
# in round 19: the ICI-allreduce store's telemetry counters are written
# from data-loader threads while the main thread pulls — the same
# shared-state discipline the serving layer needs.
PACKAGES = ["mxnet_tpu/serving", "mxnet_tpu/obs", "mxnet_tpu/io",
            "mxnet_tpu/kvstore"]

_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "cond"}
_BLOCKING_QUEUE = {"get", "put"}
# container mutators that count as writes to the attribute they live on
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
             "pop", "popleft", "popitem", "remove", "discard", "clear",
             "update", "setdefault", "add"}
# calls treated as non-raising for the ref-leak exception-edge check
_SAFE_CALLS = {"len", "min", "max", "int", "float", "bool", "list",
               "tuple", "set", "dict", "isinstance", "range", "id",
               "repr", "str", "sorted", "enumerate", "zip", "abs"}


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


class _Lock:
    """One lock identity in the analyzed program."""
    __slots__ = ("key", "kind", "cls")

    def __init__(self, key: str, kind: str, cls: Optional[str]):
        self.key = key          # "Class.attr" | "module::attr"
        self.kind = kind        # "lock" | "rlock" | "cond"
        self.cls = cls


class _Fn:
    """Per-function facts for the cross-module passes."""
    __slots__ = ("qual", "mod", "cls", "name", "node", "acquires",
                 "calls", "blocks", "requires")

    def __init__(self, qual, mod, cls, name, node):
        self.qual = qual
        self.mod = mod
        self.cls = cls
        self.name = name
        self.node = node
        # direct acquisitions: set of lock keys
        self.acquires: Set[str] = set()
        # (line, callee_key_or_name, resolved: bool, held locks)
        self.calls: List[Tuple[int, str, bool, Tuple[str, ...]]] = []
        # blocking ops performed directly: (line, kind-label)
        self.blocks: List[Tuple[int, str]] = []
        self.requires: Set[str] = set()


class _Module:
    def __init__(self, rel: str, source: str):
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, rel)


class _Program:
    """Whole-program model over every analyzed module."""

    def __init__(self, modules: Dict[str, str]):
        self.modules = {rel: _Module(rel, src)
                        for rel, src in sorted(modules.items())}
        self.locks: Dict[str, _Lock] = {}
        # (module, attr) -> lock key, for non-self receivers
        self.attr_locks: Dict[Tuple[str, str], str] = {}
        # Class -> [lock keys]
        self.class_locks: Dict[str, List[str]] = {}
        self.fns: Dict[str, _Fn] = {}          # qualname -> _Fn
        self.by_name: Dict[str, List[str]] = {}  # bare name -> quals
        self.findings: List[Finding] = []
        # write sites: (mod, group) -> [(line, held, in_init, fnqual)]
        self.writes: Dict[Tuple[str, str], List] = {}
        # lock-order edges: (held, acquired, fn qual, line)
        self.order_edges: List[Tuple[str, str, str, int]] = []
        self._collect_locks()
        self._collect_fns()

    # ---------------------------------------------------- discovery --
    def _register_lock(self, mod: str, cls: Optional[str], attr: str,
                       kind: str):
        key = "%s.%s" % (cls, attr) if cls else "%s::%s" % (
            os.path.basename(mod), attr)
        if key not in self.locks:
            self.locks[key] = _Lock(key, kind, cls)
        self.attr_locks.setdefault((mod, attr), key)
        if cls:
            self.class_locks.setdefault(cls, []).append(key)

    def _collect_locks(self):
        for mod in self.modules.values():
            for node in ast.walk(mod.tree):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                value = node.value
                if not (isinstance(value, ast.Call)
                        and isinstance(value.func, ast.Attribute)
                        and value.func.attr in _LOCK_CTORS
                        and isinstance(value.func.value, ast.Name)
                        and value.func.value.id == "threading"):
                    continue
                kind = _LOCK_CTORS[value.func.attr]
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    if isinstance(tgt, ast.Attribute) and isinstance(
                            tgt.value, ast.Name):
                        cls = self._enclosing_class(mod, node) \
                            if tgt.value.id == "self" else None
                        self._register_lock(mod.rel, cls, tgt.attr,
                                            kind)
                    elif isinstance(tgt, ast.Name):
                        # module-level lock global
                        self._register_lock(mod.rel, None, tgt.id,
                                            kind)

    def _enclosing_class(self, mod: _Module,
                         target: ast.AST) -> Optional[str]:
        hit = [None]

        def walk(node, cls):
            for child in ast.iter_child_nodes(node):
                if child is target:
                    hit[0] = cls
                if isinstance(child, ast.ClassDef):
                    walk(child, child.name)
                else:
                    walk(child, cls)
        walk(mod.tree, None)
        return hit[0]

    def _collect_fns(self):
        for mod in self.modules.values():
            def walk(node, cls, outer):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        qual = "%s::%s%s" % (
                            mod.rel, cls + "." if cls else "",
                            child.name)
                        if outer is not None:
                            # nested defs are analyzed inline as part
                            # of their parent (closures share state)
                            continue
                        fn = _Fn(qual, mod.rel, cls, child.name, child)
                        fn.requires = self._requires_for(mod, child)
                        self.fns[qual] = fn
                        self.by_name.setdefault(child.name,
                                                []).append(qual)
                        walk(child, cls, qual)
                    elif isinstance(child, ast.ClassDef):
                        walk(child, child.name, outer)
                    else:
                        walk(child, cls, outer)
            walk(mod.tree, None, None)

    def _requires_for(self, mod: _Module, fndef) -> Set[str]:
        """requires() pragmas above the def + the ``*_locked`` naming
        convention (implicit requires of the class's sole lock)."""
        out: Set[str] = set()
        ln = fndef.lineno - 1
        # skip decorators upward
        while ln >= 1 and mod.lines[ln - 1].strip().startswith("@"):
            ln -= 1
        while ln >= 1:
            s = mod.lines[ln - 1].strip()
            if s.startswith("#"):
                for kind, val in PRAGMA_RE.findall(s):
                    if kind == "requires":
                        out.update(v.strip() for v in val.split(","))
                ln -= 1
            elif not s:
                ln -= 1
            else:
                break
        return out

    # ------------------------------------------------------ helpers --
    def lock_for_expr(self, mod: str, cls: Optional[str],
                      expr: ast.AST) -> Optional[str]:
        """Resolve a with-context / receiver expression to a lock key."""
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name):
            if expr.value.id == "self" and cls:
                key = "%s.%s" % (cls, expr.attr)
                if key in self.locks:
                    return key
            return self.attr_locks.get((mod, expr.attr))
        if isinstance(expr, ast.Name):
            return self.attr_locks.get((mod, expr.id))
        return None

    def implicit_requires(self, fn: _Fn) -> Set[str]:
        out = set(fn.requires)
        if fn.name.endswith("_locked") and fn.cls:
            keys = self.class_locks.get(fn.cls, [])
            if len(keys) == 1:
                out.add(keys[0])
        return out

    def resolve_call(self, fn: _Fn, call: ast.Call) -> Tuple[
            Optional[str], str]:
        """Return (qualname or None, bare name) for a call site."""
        func = call.func
        if isinstance(func, ast.Name):
            name = func.id
        elif isinstance(func, ast.Attribute):
            name = func.attr
            recv = func.value
            if isinstance(recv, ast.Name) and recv.id == "self" \
                    and fn.cls:
                qual = "%s::%s.%s" % (fn.mod, fn.cls, name)
                if qual in self.fns:
                    return qual, name
                return None, name
        else:
            return None, ""
        quals = self.by_name.get(name, [])
        if len(quals) == 1:
            return quals[0], name
        return None, name


# ---------------------------------------------------------------------------
# per-function scan
# ---------------------------------------------------------------------------
class _TypeEnv:
    """Names/attrs known to be Events, Conditions, Queues (for the
    blocking + cv rules).  Collected program-wide: ``self.q =
    queue.Queue()`` in one method types ``self.q`` everywhere."""

    def __init__(self, prog: _Program):
        self.events: Set[Tuple[str, str]] = set()   # (mod-or-*, attr)
        self.queues: Set[Tuple[str, str]] = set()
        self.futures: Set[str] = set()              # local fut names
        for mod in prog.modules.values():
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Assign):
                    continue
                v = node.value
                if not (isinstance(v, ast.Call)
                        and isinstance(v.func, ast.Attribute)):
                    continue
                base = v.func.value
                ctor = v.func.attr
                is_thr = isinstance(base, ast.Name) and \
                    base.id == "threading"
                is_q = isinstance(base, ast.Name) and base.id == "queue"
                for tgt in node.targets:
                    attr = None
                    if isinstance(tgt, ast.Attribute):
                        attr = tgt.attr
                    elif isinstance(tgt, ast.Name):
                        attr = tgt.id
                    if attr is None:
                        continue
                    if is_thr and ctor == "Event":
                        self.events.add((mod.rel, attr))
                    elif is_q and ctor == "Queue":
                        self.queues.add((mod.rel, attr))

    def is_event(self, mod: str, expr: ast.AST) -> bool:
        attr = expr.attr if isinstance(expr, ast.Attribute) else (
            expr.id if isinstance(expr, ast.Name) else None)
        return attr is not None and (mod, attr) in self.events

    def is_queue(self, mod: str, expr: ast.AST) -> bool:
        attr = expr.attr if isinstance(expr, ast.Attribute) else (
            expr.id if isinstance(expr, ast.Name) else None)
        return attr is not None and (mod, attr) in self.queues


class _FnScanner:
    """Walks one function body tracking held locks statement-wise."""

    def __init__(self, prog: _Program, types: _TypeEnv, fn: _Fn):
        self.prog = prog
        self.types = types
        self.fn = fn
        self.held_init = tuple(sorted(prog.implicit_requires(fn)))
        self.futures: Set[str] = set()
        self.in_init = fn.name in ("__init__", "__new__")

    def _add(self, rule, line, symbol, msg):
        self.prog.findings.append(Finding(
            "pylock", rule, self.fn.mod, line, symbol, msg))

    # -- entry ---------------------------------------------------------
    def scan(self):
        self.walk(self.fn.node.body, set(self.held_init),
                  nested=False)

    def walk(self, stmts, held: Set[str], nested: bool):
        """``nested`` marks code inside a def nested in this function
        (e.g. a worker closure): it runs later, on another thread, so
        ``__init__``'s publication exemption does not apply there."""
        for stmt in stmts:
            self.stmt(stmt, held, nested)

    def stmt(self, stmt, held: Set[str], nested: bool):
        fn = self.fn
        if isinstance(stmt, ast.With):
            add = []
            for item in stmt.items:
                key = self.prog.lock_for_expr(fn.mod, fn.cls,
                                              item.context_expr)
                if key is not None:
                    self.on_acquire(key, stmt.lineno, held)
                    add.append(key)
                else:
                    self.scan_expr(item.context_expr, held, nested)
            inner = set(held) | set(add)
            self.walk(stmt.body, inner, nested)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def (closure): body executes later — scan it with
            # no inherited locks, and without the __init__ exemption
            self.walk(stmt.body, set(self.held_init), nested=True)
            return
        if isinstance(stmt, ast.ClassDef):
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self.scan_expr(stmt.test, held, nested)
            self.walk(stmt.body, set(held), nested)
            self.walk(stmt.orelse, set(held), nested)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.scan_expr(stmt.iter, held, nested)
            self.walk(stmt.body, set(held), nested)
            self.walk(stmt.orelse, set(held), nested)
            return
        if isinstance(stmt, ast.Try):
            self.walk(stmt.body, set(held), nested)
            for h in stmt.handlers:
                self.walk(h.body, set(held), nested)
            self.walk(stmt.orelse, set(held), nested)
            self.walk(stmt.finalbody, set(held), nested)
            return
        # leaf statements: track future bindings, record writes, then
        # scan expressions
        if isinstance(stmt, ast.Assign) and isinstance(
                stmt.value, ast.Call) and isinstance(
                stmt.value.func, ast.Attribute) and \
                stmt.value.func.attr == "submit":
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    self.futures.add(tgt.id)
        self.record_writes(stmt, held, nested)
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                self.on_call(node, held)

    # -- lock events ---------------------------------------------------
    def on_acquire(self, key: str, line: int, held: Set[str]):
        self.fn.acquires.add(key)
        lock = self.prog.locks[key]
        if key in held and lock.kind == "lock":
            self._add("py-lock-order", line, key,
                      "re-acquiring non-reentrant %s already held "
                      "(self-deadlock)" % key)
        # ordered edges are collected program-wide (the cycle check
        # runs after every function is scanned)
        for h in held:
            if h != key:
                self.prog.order_edges.append((h, key, self.fn.qual,
                                              line))

    # -- calls ---------------------------------------------------------
    def on_call(self, call: ast.Call, held: Set[str]):
        fn = self.fn
        func = call.func
        dotted = _dotted(func)
        line = call.lineno

        # cv rules -----------------------------------------------------
        if isinstance(func, ast.Attribute):
            recv = func.value
            recv_lock = self.prog.lock_for_expr(fn.mod, fn.cls, recv)
            is_cond = recv_lock is not None and \
                self.prog.locks[recv_lock].kind == "cond"
            if is_cond:
                if func.attr == "wait":
                    self._add("py-cv-wait-predicate", line, recv_lock,
                              "Condition.wait() without a predicate — "
                              "use wait_for(pred); spurious wakeups "
                              "break the protocol")
                elif func.attr in ("notify", "notify_all") and \
                        recv_lock not in held:
                    self._add("py-notify-unlocked", line, recv_lock,
                              "%s() outside `with %s:` — notify must "
                              "run under the condition's lock"
                              % (func.attr, recv_lock))

        # blocking ops -------------------------------------------------
        blocked = None
        if isinstance(func, ast.Attribute):
            recv = func.value
            if func.attr in _BLOCKING_QUEUE and \
                    self.types.is_queue(fn.mod, recv):
                blocked = "queue.%s" % func.attr
            elif func.attr == "wait" and (
                    self.types.is_event(fn.mod, recv)
                    or (self.prog.lock_for_expr(fn.mod, fn.cls, recv)
                        is not None)):
                blocked = "wait"
            elif func.attr == "result" and isinstance(
                    recv, ast.Name) and recv.id in self.futures:
                blocked = "Future.result"
            elif func.attr == "block_until_ready":
                blocked = "block_until_ready"
            elif func.attr in ("step", "run") and not call.args \
                    and not call.keywords and isinstance(
                        recv, (ast.Name, ast.Attribute)):
                # jitted-step dispatch through an engine handle
                blocked = ".%s()" % func.attr
            elif dotted == "time.sleep":
                blocked = "time.sleep"
        elif isinstance(func, ast.Name) and func.id.endswith(
                "step_fn"):
            blocked = func.id
        if isinstance(func, ast.Attribute) and \
                func.attr.endswith("step_fn"):
            blocked = func.attr
        if blocked is not None:
            held_eff = set(held)
            if blocked == "wait" and isinstance(func, ast.Attribute):
                # Condition.wait releases ITS OWN lock while waiting —
                # only OTHER held locks make the wait a stall
                rl = self.prog.lock_for_expr(fn.mod, fn.cls,
                                             func.value)
                if rl is not None and \
                        self.prog.locks[rl].kind == "cond":
                    held_eff.discard(rl)
            self.fn.blocks.append((line, blocked))
            if held_eff:
                self._add("py-blocking-under-lock", line, blocked,
                          "blocking %s while holding %s — the "
                          "critical section stalls every waiter"
                          % (blocked, "+".join(sorted(held_eff))))

        # future-producing submits ------------------------------------
        # (tracked so fut.result() under a lock is recognizable)

        # call-graph edge ---------------------------------------------
        qual, name = self.prog.resolve_call(fn, call)
        if qual is not None and qual != fn.qual:
            fn.calls.append((line, qual, True, tuple(sorted(held))))

    # -- writes --------------------------------------------------------
    def record_writes(self, stmt, held: Set[str], nested: bool):
        fn = self.fn
        in_init = self.in_init and not nested
        sites: List[Tuple[str, str, int]] = []  # (recv, attr, line)

        def target_site(tgt):
            # recv.attr = ... | recv.attr[i] = ... | del recv.attr[i]
            node = tgt
            if isinstance(node, ast.Subscript):
                node = node.value
            if isinstance(node, ast.Attribute) and isinstance(
                    node.value, ast.Name):
                sites.append((node.value.id, node.attr, tgt.lineno))
            elif isinstance(node, ast.Name) and fn.cls is None:
                # module-level global written inside a function
                g = [n for n in ast.walk(fn.node)
                     if isinstance(n, ast.Global)
                     and node.id in n.names]
                if g:
                    sites.append(("<module>", node.id, tgt.lineno))

        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                if isinstance(tgt, (ast.Tuple, ast.List)):
                    for e in tgt.elts:
                        target_site(e)
                else:
                    target_site(tgt)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            target_site(stmt.target)
        elif isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                target_site(tgt)
        elif isinstance(stmt, ast.Expr) and isinstance(
                stmt.value, ast.Call):
            func = stmt.value.func
            if isinstance(func, ast.Attribute) and \
                    func.attr in _MUTATORS:
                node = func.value
                if isinstance(node, ast.Attribute) and isinstance(
                        node.value, ast.Name):
                    sites.append((node.value.id, node.attr,
                                  stmt.lineno))

        for recv, attr, line in sites:
            if attr.endswith("_mu") or attr.endswith("lock"):
                continue
            if recv == "self" and fn.cls:
                group = "%s.%s" % (fn.cls, attr)
            else:
                # non-self receivers scope to the WRITING class as
                # well as the attribute (round 15): ServingCluster
                # and DisaggServingCluster both mutate request
                # records with `state`/`error`/... fields, each
                # consistently under its OWN router lock — keying by
                # bare attribute aliased the two classes' disciplines
                # and flagged every site under the minority lock
                group = "%s::%s" % (fn.cls or "", attr)
            self.prog.writes.setdefault((fn.mod, group), []).append(
                (line, tuple(sorted(held)), in_init, fn.qual, attr))

    # -- expressions reached from non-leaf statements ------------------
    def scan_expr(self, expr, held, nested):
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self.on_call(node, held)


# ---------------------------------------------------------------------------
# ref-leak rule (separate focused walker)
# ---------------------------------------------------------------------------
def _is_prefix_match(call: ast.Call) -> bool:
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "match"
            and "prefix" in _dotted(f.value).lower())


def _find_match_call(node: ast.AST) -> Optional[ast.Call]:
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and _is_prefix_match(n):
            return n
    return None


def _name_in(node: ast.AST, name: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == name
               for n in ast.walk(node))


def _releases(stmt: ast.AST, name: str) -> bool:
    for n in ast.walk(stmt):
        if isinstance(n, ast.Call) and isinstance(
                n.func, ast.Attribute) and n.func.attr == "release" \
                and any(_name_in(a, name) for a in n.args):
            return True
    return False


def _escapes(stmt: ast.AST, name: str) -> bool:
    """entries stored into object state (an attribute/subscript) or
    returned — ownership transferred, the later release path owns it."""
    if isinstance(stmt, ast.Return) and stmt.value is not None \
            and _name_in(stmt.value, name):
        return True
    if isinstance(stmt, ast.Assign) and _name_in(stmt.value, name):
        for tgt in stmt.targets:
            if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                return True
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        f = stmt.value.func
        if isinstance(f, ast.Attribute) and f.attr in ("append",
                                                       "extend"):
            if any(_name_in(a, name) for a in stmt.value.args):
                return True
    return False


def _may_raise(stmt: ast.AST, name: str) -> Optional[int]:
    """Line of the first call in ``stmt`` that can raise (excluding the
    release itself and whitelisted builtins)."""
    for n in ast.walk(stmt):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        if isinstance(f, ast.Name) and f.id in _SAFE_CALLS:
            continue
        if isinstance(f, ast.Attribute) and f.attr in (
                "release", "get", "items", "values", "keys",
                "tobytes", "reshape", "discard", "add"):
            continue
        return n.lineno
    return None


class _RefLeakScanner:
    def __init__(self, prog: _Program, fn: _Fn):
        self.prog = prog
        self.fn = fn

    def _add(self, line, msg):
        self.prog.findings.append(Finding(
            "pylock", "py-ref-leak", self.fn.mod, line, "match",
            msg))

    def scan(self):
        # one acquisition tracked per function covers the repo idiom
        # (an _admit-style loop re-matches per iteration, but every
        # iteration has the same shape)
        self._scan_block(self.fn.node.body)

    def _scan_block(self, body) -> bool:
        for i, stmt in enumerate(body):
            name = self.acquire_name(stmt)
            if name is not None:
                self.track(body[i + 1:], name, stmt.lineno,
                           protected=False)
                return True
            for sub in (getattr(stmt, "body", None),
                        getattr(stmt, "orelse", None),
                        getattr(stmt, "finalbody", None)):
                if sub and self._scan_block(sub):
                    return True
            for h in getattr(stmt, "handlers", ()):
                if self._scan_block(h.body):
                    return True
        return False

    def acquire_name(self, stmt) -> Optional[str]:
        if not isinstance(stmt, ast.Assign):
            return None
        if _find_match_call(stmt.value) is None:
            return None
        tgt = stmt.targets[0]
        if isinstance(tgt, (ast.Tuple, ast.List)) and tgt.elts and \
                isinstance(tgt.elts[0], ast.Name):
            return tgt.elts[0].id
        if isinstance(tgt, ast.Name):
            return tgt.id
        return None

    def try_protects(self, stmt: ast.Try, name: str) -> bool:
        return any(_releases(s, name)
                   for h in stmt.handlers for s in h.body) or \
            any(_releases(s, name) for s in stmt.finalbody)

    def track(self, stmts, name: str, acq_line: int,
              protected: bool) -> bool:
        """Walk forward; returns True once the refs are settled
        (released or escaped) on this path."""
        for stmt in stmts:
            if _releases(stmt, name) or _escapes(stmt, name):
                return True
            if isinstance(stmt, ast.Try):
                prot = protected or self.try_protects(stmt, name)
                if self.track(stmt.body, name, acq_line, prot):
                    return True
                continue
            if isinstance(stmt, ast.If):
                then_done = self.track(stmt.body, name, acq_line,
                                       protected)
                else_done = self.track(stmt.orelse, name, acq_line,
                                       protected)
                # a branch that ends in return/continue without
                # settling already reported inside track(); if both
                # branches settled, we are done
                if then_done and (stmt.orelse and else_done):
                    return True
                continue
            if isinstance(stmt, (ast.For, ast.While)):
                if self.track(stmt.body, name, acq_line, protected):
                    return True
                continue
            if isinstance(stmt, (ast.Return, ast.Continue, ast.Break,
                                 ast.Raise)):
                self._add(stmt.lineno,
                          "exit without releasing the refs taken by "
                          "match() at line %d (entries %r neither "
                          "released nor stored)" % (acq_line, name))
                return True     # reported; stop tracking this path
            if not protected:
                line = _may_raise(stmt, name)
                if line is not None:
                    self._add(line,
                              "call may raise between match() (line "
                              "%d) and release/escape of %r — the "
                              "exception edge leaks the refs; wrap in "
                              "try/except that releases"
                              % (acq_line, name))
                    return True
        return False


def _scan_refs_attr(prog: _Program):
    """Direct ``.refs`` mutation outside prefix_cache.py."""
    for mod in prog.modules.values():
        if mod.rel.endswith("prefix_cache.py"):
            continue
        for node in ast.walk(mod.tree):
            tgt = None
            if isinstance(node, ast.AugAssign):
                tgt = node.target
            elif isinstance(node, ast.Assign):
                tgt = node.targets[0]
            if tgt is not None and isinstance(tgt, ast.Attribute) \
                    and tgt.attr == "refs":
                prog.findings.append(Finding(
                    "pylock", "py-ref-leak", mod.rel, node.lineno,
                    "refs", "PrefixCache refcounts mutated outside "
                    "prefix_cache.py — use match()/release()"))


# ---------------------------------------------------------------------------
# program-level passes
# ---------------------------------------------------------------------------
def _guarded_pass(prog: _Program):
    for (mod, group), sites in sorted(prog.writes.items()):
        guards: Dict[str, int] = {}
        for line, held, in_init, fnqual, attr in sites:
            if in_init:
                continue
            for h in held:
                guards[h] = guards.get(h, 0) + 1
        if not guards:
            continue
        guard = sorted(guards.items(), key=lambda kv: (-kv[1],
                                                       kv[0]))[0][0]
        for line, held, in_init, fnqual, attr in sites:
            if in_init or guard in held:
                continue
            prog.findings.append(Finding(
                "pylock", "py-guarded-field", mod, line, attr,
                "%r written under %s elsewhere but not here — "
                "guarded-by inference says every write site needs "
                "the lock (writes in __init__ are exempt)"
                % (attr, guard)))


def _transitive_pass(prog: _Program):
    """Propagate acquired-lock sets through the call graph, then (a)
    emit transitive blocking/ordering findings and (b) detect cycles
    in the lock-order digraph."""
    trans: Dict[str, Set[str]] = {q: set(f.acquires)
                                  for q, f in prog.fns.items()}
    tblocks: Dict[str, List[Tuple[int, str]]] = {
        q: list(f.blocks) for q, f in prog.fns.items()}
    changed = True
    while changed:
        changed = False
        for q, f in prog.fns.items():
            for line, callee, _, _ in f.calls:
                if callee not in trans:
                    continue
                before = len(trans[q])
                trans[q] |= trans[callee]
                if len(trans[q]) != before:
                    changed = True
                if tblocks[callee] and not tblocks[q]:
                    tblocks[q] = [(line, "%s (via %s)" % (
                        tblocks[callee][0][1],
                        callee.split("::")[-1]))]
                    changed = True

    for q, f in sorted(prog.fns.items()):
        for line, callee, _, held in f.calls:
            if not held or callee not in trans:
                continue
            cfn = prog.fns[callee]
            # transitive blocking
            for bline, kind in tblocks.get(callee, []):
                prog.findings.append(Finding(
                    "pylock", "py-blocking-under-lock", f.mod, line,
                    callee.split("::")[-1],
                    "call to %s() may block on %s while holding %s"
                    % (cfn.name, kind, "+".join(sorted(held)))))
                break
            # transitive ordering edges + re-acquisition
            callee_requires = prog.implicit_requires(cfn)
            for m in sorted(trans[callee]):
                if m in callee_requires:
                    continue
                if m in held and prog.locks[m].kind == "lock":
                    prog.findings.append(Finding(
                        "pylock", "py-lock-order", f.mod, line, m,
                        "call to %s() may re-acquire held "
                        "non-reentrant %s" % (cfn.name, m)))
                    continue
                for h in held:
                    if h != m:
                        prog.order_edges.append((h, m, q, line))

    # cycle detection over the order digraph
    edges: Dict[str, Dict[str, Tuple[str, int]]] = {}
    reported: Set[Tuple[str, str]] = set()
    for a, b, qual, line in prog.order_edges:
        fwd = edges.setdefault(a, {})
        if b not in fwd:
            fwd[b] = (qual, line)

    def reachable(src, dst, seen):
        if src == dst:
            return True
        for nxt in edges.get(src, {}):
            if nxt not in seen:
                seen.add(nxt)
                if reachable(nxt, dst, seen):
                    return True
        return False

    for a, b, qual, line in prog.order_edges:
        if (b, a) in reported or (a, b) in reported:
            continue
        if reachable(b, a, {b}) and a != b:
            # report at the LATER edge in scan order (the one closing
            # the cycle), once per lock pair
            reported.add((a, b))
            fn = prog.fns[qual]
            prog.findings.append(Finding(
                "pylock", "py-lock-order", fn.mod, line, b,
                "acquiring %s while holding %s closes a lock-order "
                "cycle (%s -> %s also exists) — two threads arriving "
                "from opposite ends deadlock" % (b, a, b, a)))


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------
def analyze(modules: Dict[str, str]) -> List[Finding]:
    """Analyze ``{rel_path: source}`` as one program; findings are
    pragma-filtered per module."""
    prog = _Program(modules)
    types = _TypeEnv(prog)
    for qual in sorted(prog.fns):
        fn = prog.fns[qual]
        _FnScanner(prog, types, fn).scan()
        _RefLeakScanner(prog, fn).scan()
    _scan_refs_attr(prog)
    _guarded_pass(prog)
    _transitive_pass(prog)
    out: List[Finding] = []
    for rel, mod in prog.modules.items():
        fs = [f for f in prog.findings if f.path == rel]
        out.extend(apply_pragmas(fs, mod.source))
    return sorted(out, key=lambda f: (f.path, f.line, f.rule))


def lint_source(source: str, rel_path: str) -> List[Finding]:
    """Single-module entry (fixtures drive this directly)."""
    return analyze({rel_path: source})


def run(root: str, only: Optional[Set[str]] = None) -> List[Finding]:
    """Lint every Python module under :data:`PACKAGES`.  ``only``
    restricts the *reported* modules (``--changed-only``) — the whole
    program is still parsed so cross-module lock-order stays sound."""
    modules: Dict[str, str] = {}
    for pkg in PACKAGES:
        d = os.path.join(root, pkg)
        if not os.path.isdir(d):
            continue
        for name in sorted(os.listdir(d)):
            if not name.endswith(".py"):
                continue
            rel = "%s/%s" % (pkg, name)
            with open(os.path.join(root, rel)) as f:
                modules[rel] = f.read()
    findings = analyze(modules)
    if only is not None:
        findings = [f for f in findings if f.path in only]
    return findings
