"""C-ABI consistency checker (mxlint analyzer 1 of 3).

Cross-checks three sources of truth that historically drifted apart:

1. the C prototypes in ``native/include/mxnet_tpu/c_api.h`` (parsed
   here with a small declaration grammar — comments stripped, handle
   typedefs resolved);
2. the ctypes ``_PROTOTYPES`` table in ``mxnet_tpu/native.py``
   (extracted by evaluating the module's simple top-level assignments —
   no package import, no native build);
3. every ``lib().MX*`` / ``lib.MX*`` call site in ``native.py`` (AST).

Rules
-----
``abi-unbound``          header function with no ``_PROTOTYPES`` entry
``abi-unknown-symbol``   table entry or call site naming no header fn
``abi-missing-argtypes`` call site whose symbol has no table entry
``abi-restype``          table restype disagrees with the header return
``abi-argcount``         table argtypes length disagrees with the header
``abi-argtypes``         an argtype disagrees with the header parameter

The C→ctypes correspondence is the table below.  Two deliberate
wideings: ``const uint8_t*`` accepts ``c_char_p`` (Python ``bytes``
buffers) and ``const char**`` maps to ``POINTER(c_void_p)`` — records
are binary, and a ``c_char_p`` out-param would NUL-truncate on read.
"""
from __future__ import annotations

import ast
import ctypes
import re
from typing import Dict, List, Tuple

from .findings import Finding

__all__ = ["parse_header", "load_prototypes", "call_sites", "check"]

# void* handle typedefs in c_api.h — resolved before type mapping
HANDLE_TYPEDEFS = {
    "RecordIOReaderHandle", "RecordIOWriterHandle", "ImageLoaderHandle",
    "EngineVarHandle", "ShmHandle",
}

# normalized C type -> acceptable ctypes types.  Identity comparison,
# not name comparison: on LP64 Linux c_uint64 IS c_ulong, c_size_t IS
# c_ulong, c_int64 IS c_long, c_uint8 IS c_ubyte — the platform alias
# resolution is exactly what makes the table 64-bit-correct, so the
# checker must honor it.  "CFUNCTYPE" is a wildcard for any ctypes
# function-pointer class.
_PTR = ctypes.POINTER
C_TO_CTYPES: Dict[str, Tuple[object, ...]] = {
    "void": (None,),
    "int": (ctypes.c_int,),
    "float": (ctypes.c_float,),
    "uint64_t": (ctypes.c_uint64,),
    "size_t": (ctypes.c_size_t,),
    "int*": (_PTR(ctypes.c_int),),
    "int64_t*": (_PTR(ctypes.c_int64),),
    "uint64_t*": (_PTR(ctypes.c_uint64),),
    "size_t*": (_PTR(ctypes.c_size_t),),
    "double*": (_PTR(ctypes.c_double),),
    "const float*": (_PTR(ctypes.c_float),),
    "const float**": (_PTR(_PTR(ctypes.c_float)),),
    "const char*": (ctypes.c_char_p,),
    # binary-safe out-param: c_char_p would truncate at the first NUL
    "const char**": (_PTR(ctypes.c_void_p),),
    "const uint8_t*": (ctypes.c_char_p, _PTR(ctypes.c_uint8)),
    "uint8_t*": (_PTR(ctypes.c_uint8),),
    "uint8_t**": (_PTR(_PTR(ctypes.c_uint8)),),
    "void*": (ctypes.c_void_p,),
    "void**": (_PTR(ctypes.c_void_p),),
    "MXEngineFn": ("CFUNCTYPE",),
    "MXEngineDeleter": ("CFUNCTYPE",),
}


def _matches(got, accepted) -> bool:
    if got in accepted:
        return True
    return "CFUNCTYPE" in accepted and isinstance(got, type) \
        and issubclass(got, ctypes._CFuncPtr)  # noqa: SLF001


def _expect_name(accepted) -> str:
    first = accepted[0] if accepted else None
    return first if isinstance(first, str) else _ctype_name(first)

_DECL_RE = re.compile(
    r"(?:^|\n)\s*(const\s+char\s*\*|int|void)\s+(MX\w+)\s*\(([^)]*)\)\s*;")


def _strip_comments(text: str) -> str:
    text = re.sub(r"/\*.*?\*/", lambda m: re.sub(r"[^\n]", " ", m.group()),
                  text, flags=re.S)
    return re.sub(r"//[^\n]*", "", text)


def _norm_arg(arg: str) -> str:
    """'const char* path' -> 'const char*'; 'uint64_t seed' ->
    'uint64_t'; 'ImageLoaderHandle* out' -> 'void**'."""
    arg = arg.strip()
    if arg in ("", "void"):
        return ""
    stars = arg.count("*")
    toks = [t for t in re.split(r"[\s*]+", arg) if t]
    # trailing identifier is the parameter name iff >1 type-ish token,
    # or the single token is not itself a known type/typedef
    known = set(HANDLE_TYPEDEFS) | {"MXEngineFn", "MXEngineDeleter"}
    base = toks[:-1] if (len(toks) > 1 and toks[-1] not in ("char", "int"))\
        else toks
    if len(base) == 1 and base[0] in known and base[0] in HANDLE_TYPEDEFS:
        base = ["void"]
        stars += 1
    elif len(base) >= 2 and base[-1] in HANDLE_TYPEDEFS:
        base = base[:-1] + ["void"]
        stars += 1
    t = " ".join(base) + "*" * stars
    # normalize 'std_'-style float params: 'const float' handled above
    return t


def parse_header(path: str) -> Dict[str, Tuple[str, List[str]]]:
    """Return ``{name: (return_ctype_str_set_key, [arg keys])}`` where
    keys index into C_TO_CTYPES."""
    with open(path) as f:
        text = _strip_comments(f.read())
    out: Dict[str, Tuple[str, List[str]]] = {}
    for ret, name, args in _DECL_RE.findall(text):
        ret = "const char*" if "char" in ret else ret.strip()
        arglist = []
        for a in args.split(","):
            n = _norm_arg(a)
            if n:
                arglist.append(n)
        out[name] = (ret, arglist)
    return out


def _ctype_name(obj) -> str:
    """Canonical spelling for a ctypes type object."""
    if obj is None:
        return "None"
    if isinstance(obj, type):
        if issubclass(obj, ctypes._Pointer):  # noqa: SLF001
            return "POINTER(%s)" % _ctype_name(obj._type_)
        if issubclass(obj, ctypes._CFuncPtr):  # noqa: SLF001
            return "CFUNCTYPE"
        return obj.__name__
    return repr(obj)


def load_prototypes(py_path: str) -> Dict[str, Tuple[object, list]]:
    """Extract ``_PROTOTYPES`` from a bindings module WITHOUT importing
    it as a package (no jax, no native build): evaluate the module's
    simple ``NAME = <expr>`` top-level assignments in a namespace
    seeded with ``ctypes``, skipping any that do not evaluate."""
    with open(py_path) as f:
        tree = ast.parse(f.read(), py_path)
    ns: dict = {"ctypes": ctypes}
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        try:
            val = eval(compile(ast.Expression(node.value), py_path,
                               "eval"), ns)
        except Exception:
            continue
        ns[node.targets[0].id] = val
    protos = ns.get("_PROTOTYPES")
    if not isinstance(protos, dict):
        raise ValueError("%s: no evaluable _PROTOTYPES table" % py_path)
    return protos


def call_sites(py_path: str) -> List[Tuple[str, int]]:
    """(symbol, line) for every ``lib().MX*`` / ``lib.MX*`` attribute
    reference in the bindings module."""
    with open(py_path) as f:
        tree = ast.parse(f.read(), py_path)
    sites: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Attribute)
                and node.attr.startswith("MX")):
            continue
        base = node.value
        is_lib_call = (isinstance(base, ast.Call)
                       and isinstance(base.func, ast.Name)
                       and base.func.id in ("lib", "_load"))
        is_lib_name = (isinstance(base, ast.Name)
                       and base.id in ("lib", "l", "_lib"))
        if is_lib_call or is_lib_name:
            sites.append((node.attr, node.lineno))
    return sites


def check(header_path: str, bindings_path: str, rel_header: str,
          rel_bindings: str, prototypes: dict = None) -> List[Finding]:
    """Run every ABI rule; ``prototypes`` overrides table extraction
    (fixture tests pass a dict directly)."""
    header = parse_header(header_path)
    protos = prototypes if prototypes is not None \
        else load_prototypes(bindings_path)
    findings: List[Finding] = []

    def add(rule, symbol, msg, path=rel_bindings, line=0):
        findings.append(Finding("abi", rule, path, line, symbol, msg))

    for name in sorted(header):
        if name not in protos:
            add("abi-unbound", name,
                "header function has no _PROTOTYPES entry",
                path=rel_header)
    for name in sorted(protos):
        if name not in header:
            add("abi-unknown-symbol", name,
                "_PROTOTYPES entry names no header function")
            continue
        want_ret, want_args = header[name]
        got_ret, got_args = protos[name]
        if not _matches(got_ret, C_TO_CTYPES[want_ret]):
            add("abi-restype", name,
                "restype %s != header %r (expect %s)"
                % (_ctype_name(got_ret), want_ret,
                   _expect_name(C_TO_CTYPES[want_ret])))
        if len(got_args) != len(want_args):
            add("abi-argcount", name,
                "argtypes has %d entries, header has %d"
                % (len(got_args), len(want_args)))
            continue
        for i, (got, want) in enumerate(zip(got_args, want_args)):
            accepted = C_TO_CTYPES.get(want, ())
            if not _matches(got, accepted):
                add("abi-argtypes", name,
                    "arg %d: %s != header %r (expect %s)"
                    % (i, _ctype_name(got), want,
                       _expect_name(accepted)))

    seen_missing = set()
    for symbol, line in call_sites(bindings_path):
        if symbol not in header:
            add("abi-unknown-symbol", symbol,
                "call site names no header function", line=line)
        elif symbol not in protos and symbol not in seen_missing:
            seen_missing.add(symbol)
            add("abi-missing-argtypes", symbol,
                "call site has no _PROTOTYPES entry "
                "(no argtypes/restype applied)", line=line)
    return findings
