#!/usr/bin/env python
"""Distributed job launcher.

Reference: ``tools/launch.py`` + the dmlc tracker (SURVEY.md §2.3
"launch.py", §4.5: ``launch.py -n 3 -s 1 --launcher local python
script.py`` forks scheduler/servers/workers as local processes with
``DMLC_*`` env — real transport, fake topology).

Supported launchers: ``local`` (fork all roles on this host — the test
topology), ``ssh`` (one worker per host from a hostfile; each host gets
the same DMLC_* rendezvous env), ``mpi`` (delegate process placement to
``mpirun``; ranks derive their DMLC role from ``OMPI_COMM_WORLD_RANK``),
and ``slurm`` (same via ``srun``/``SLURM_PROCID``).  On TPU pods the
heavy data path is XLA collectives over ICI/DCN inside each worker; this
launcher only provides role/rendezvous plumbing, like the reference's
tracker (``dmlc_tracker/{local,ssh,mpi,slurm}.py``).
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def launch_local(args, command):
    port = args.port or _free_port()
    base_env = dict(os.environ)
    base_env.update({
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_NUM_SERVER": str(args.num_servers),
    })
    procs = []

    for i in range(args.num_servers):
        env = dict(base_env)
        env["DMLC_ROLE"] = "server"
        env["DMLC_SERVER_ID"] = str(i)
        procs.append(subprocess.Popen(
            [sys.executable, "-c",
             "from mxnet_tpu.parallel.dist import run_server; run_server()"],
            env=env))

    for i in range(args.num_workers):
        env = dict(base_env)
        env["DMLC_ROLE"] = "worker"
        env["DMLC_WORKER_ID"] = str(i)
        procs.append(subprocess.Popen(command, env=env))

    workers = procs[args.num_servers:]
    code = 0
    try:
        for p in workers:
            p.wait()
            code = code or p.returncode
    finally:
        for p in procs[:args.num_servers]:
            p.send_signal(signal.SIGTERM)
    return code


def launch_ssh(args, command):
    with open(args.hostfile) as f:
        hosts = [h.strip() for h in f if h.strip()]
    port = args.port or 9091
    root = hosts[0]
    base = [
        "DMLC_PS_ROOT_URI=%s" % root,
        "DMLC_PS_ROOT_PORT=%d" % port,
        "DMLC_NUM_WORKER=%d" % args.num_workers,
        "DMLC_NUM_SERVER=%d" % args.num_servers,
    ]
    server_cmd = (sys.executable + " -c \"from mxnet_tpu.parallel.dist "
                  "import run_server; run_server()\"")
    server_procs = []
    # All servers co-locate on the root host, server i on ROOT_PORT + i —
    # workers key-shard their connections across those ports (run_server).
    for i in range(args.num_servers):
        env_fwd = " ".join(base + ["DMLC_ROLE=server",
                                   "DMLC_SERVER_ID=%d" % i])
        server_procs.append(subprocess.Popen(
            ["ssh", root, env_fwd + " " + server_cmd]))
    worker_procs = []
    for i in range(args.num_workers):
        host = hosts[i % len(hosts)]
        env_fwd = " ".join(base + ["DMLC_ROLE=worker",
                                   "DMLC_WORKER_ID=%d" % i])
        worker_procs.append(subprocess.Popen(
            ["ssh", host, env_fwd + " " + " ".join(command)]))
    code = 0
    try:
        for p in worker_procs:
            p.wait()
            code = code or p.returncode
    finally:
        for p in server_procs:
            p.send_signal(signal.SIGTERM)
    return code


_ROLE_SHIM = (
    "import os,sys,subprocess;"
    "r=int(os.environ.get('OMPI_COMM_WORLD_RANK',"
    "os.environ.get('PMI_RANK',os.environ.get('SLURM_PROCID','0'))));"
    "ns=int(os.environ['DMLC_NUM_SERVER']);"
    "os.environ.update({'DMLC_ROLE':'server','DMLC_SERVER_ID':str(r)}"
    " if r<ns else"
    " {'DMLC_ROLE':'worker','DMLC_WORKER_ID':str(r-ns)});"
    "sys.exit(subprocess.call(sys.argv[1:])"
    " if r>=ns else"
    " __import__('mxnet_tpu.parallel.dist',fromlist=['run_server'])"
    ".run_server())"
)


def _role_shim(env):
    """Bake the rendezvous env into the -c program itself: OpenMPI's
    orted spawns remote ranks with the login-shell environment, NOT
    mpirun's, so env-var forwarding cannot be relied on across nodes."""
    baked = "".join("os.environ[%r]=%r;" % (k, str(v))
                    for k, v in env.items())
    head, rest = _ROLE_SHIM.split(";", 1)
    return head + ";" + baked + rest


def launch_mpi(args, command, runner=None):
    """mpirun/srun launcher (reference: ``dmlc_tracker/mpi.py`` /
    ``slurm.py``).  Spawns num_servers + num_workers ranks; each rank
    derives its DMLC role from its MPI/slurm rank via a tiny shim —
    ranks [0, ns) are servers, the rest workers.  Caveats for multi-node
    allocations: server ranks bind 0.0.0.0 (any node), but
    DMLC_PS_ROOT_URI must name the node where the scheduler places ranks
    [0, ns) — export it before launching (the default, this node's
    hostname, is only right when servers land here).  ``-H/--hostfile``
    is not consulted; placement belongs to mpirun/srun."""
    nproc = args.num_workers + args.num_servers
    port = args.port or 9091
    root = os.environ.get("DMLC_PS_ROOT_URI", socket.gethostname())
    env = {
        "DMLC_PS_ROOT_URI": root,
        "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_NUM_SERVER": str(args.num_servers),
    }
    if runner is None:
        runner = "srun" if args.launcher == "slurm" else "mpirun"
    # rendezvous env is baked into the shim program (see _role_shim) —
    # launcher-specific -x/--export flags are both insufficient
    # (OpenMPI doesn't forward arbitrary env to remote orted-spawned
    # ranks) and non-portable (MPICH rejects -x)
    cmd = [runner, "-n", str(nproc), sys.executable, "-c",
           _role_shim(env)] + list(command)
    try:
        return subprocess.call(cmd, env={**os.environ, **env})
    except FileNotFoundError:
        sys.stderr.write(
            "%s not found on PATH; the equivalent command is:\n  %s\n"
            % (runner, " ".join(cmd)))
        return 127


def launch_serve(args, command):
    """Role-aware disaggregated-serving launcher (round 15): spawn
    ``--prefill`` + ``--decode`` worker processes running
    ``mxnet_tpu.serving.run_worker`` and the given command as the
    ROUTER process, all wired through ``MXNET_SERVE_*`` env.  The
    router script must build ``DisaggServingCluster(...,
    spawn=False, prefill=<n>, decode=<m>,
    port=int(os.environ["MXNET_SERVE_ROUTER_PORT"]))`` — worker
    processes connect to it exactly like locally-spawned ones, so
    the same protocol scales from this single-host topology to one
    worker per host (run ``run_worker()`` remotely with the env
    pointing at the router)."""
    if args.workers_only and not args.port:
        sys.stderr.write(
            "--workers-only: -p/--port must name the LIVE router's "
            "control port (the workers have nothing to rendezvous "
            "with otherwise)\n")
        return 2
    port = args.port or _free_port()
    base_env = dict(os.environ)
    base_env.update({
        "MXNET_SERVE_ROUTER_HOST": args.router_host,
        "MXNET_SERVE_ROUTER_PORT": str(port),
        "MXNET_SERVE_PREFILL": str(args.prefill),
        "MXNET_SERVE_DECODE": str(args.decode),
    })
    router = None
    if not args.workers_only:
        router = subprocess.Popen(command, env=base_env)
    workers = []
    for role, n in (("prefill", args.prefill),
                    ("decode", args.decode)):
        for i in range(n):
            env = dict(base_env)
            env["MXNET_SERVE_ROLE"] = role
            # --workers-only joins a LIVE cluster (round 16: the
            # autoscaler's off-host scale-up path — the router's
            # add_worker(role, spawn=False) is waiting for exactly
            # this name): name from --worker-start so the operator
            # matches what the router expects; the default topology
            # numbers workers from 0 as before
            env["MXNET_SERVE_WORKER"] = "%s%d" % (
                role, args.worker_start + i)
            workers.append(subprocess.Popen(
                [sys.executable, "-c",
                 "from mxnet_tpu.serving import run_worker; "
                 "run_worker()"], env=env))
    try:
        if router is not None:
            code = router.wait()
        else:
            code = 0
            for p in workers:
                p.wait()
                code = code or p.returncode
    finally:
        # reap workers in BOTH modes: after the router exits, and on
        # an abnormal exit (Ctrl-C mid-wait) of a --workers-only
        # launcher — otherwise the workers run on unsupervised
        for p in workers:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
    return code


def launch_http(args, command):
    """HTTP front-door launcher (round 20): run the streaming
    HTTP/SSE server ``mxnet_tpu.serving.http_frontend`` as the
    foreground process.  Any extra command tokens are passed through
    to the frontend CLI (``--disagg``, ``--replicas N``, ``--keys
    FILE|JSON``, model geometry flags …); ``-p/--port`` maps onto the
    listening port (default: MXNET_SERVE_HTTP_PORT or OS-assigned,
    printed as JSON at startup).  The demo server builds a
    random-weights model — production embeds
    :class:`mxnet_tpu.serving.HttpFrontend` over its own cluster and
    params (see docs/http_api.md)."""
    command = list(command)
    if command[:1] == ["--"]:              # argparse.REMAINDER keeps it
        command = command[1:]
    # -c entry (not -m): the serving package imports http_frontend at
    # import time, so runpy would warn about the double module object
    cmd = [sys.executable, "-c",
           "import sys; from mxnet_tpu.serving.http_frontend import "
           "main; sys.exit(main(sys.argv[1:]))"]
    if args.port:
        cmd += ["--port", str(args.port)]
    cmd += command
    env = dict(os.environ)
    # the server must import mxnet_tpu wherever the launcher was
    # invoked from — put the repo root on the child's path
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "") \
        if env.get("PYTHONPATH") else repo
    proc = subprocess.Popen(cmd, env=env)
    try:
        return proc.wait()
    except KeyboardInterrupt:
        proc.send_signal(signal.SIGTERM)
        return proc.wait()


def launch_sge(args, command):
    """SGE launcher (reference: ``dmlc_tracker/sge.py``): submit a job
    ARRAY of num_servers + num_workers tasks via ``qsub``; each task
    derives its DMLC role from ``$SGE_TASK_ID`` through the same shim
    the mpi/slurm path uses (task ids [1, ns] are servers, the rest
    workers).  The scheduler host must be reachable from the compute
    nodes via DMLC_PS_ROOT_URI (export before launching, as with mpi)."""
    import tempfile
    nproc = args.num_workers + args.num_servers
    port = args.port or 9091
    root = os.environ.get("DMLC_PS_ROOT_URI", socket.gethostname())
    env = {
        "DMLC_PS_ROOT_URI": root,
        "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_NUM_SERVER": str(args.num_servers),
    }
    # SGE_TASK_ID is 1-based; translate to the 0-based rank the shim
    # expects (OMPI_COMM_WORLD_RANK is the first var it consults)
    script = "\n".join([
        "#!/bin/sh",
        "#$ -t 1-%d" % nproc,
        "#$ -cwd",
        "#$ -S /bin/sh",
        "export OMPI_COMM_WORLD_RANK=$(($SGE_TASK_ID - 1))",
        " ".join("export %s=%s;" % kv for kv in env.items()),
        "exec %s -c '%s' %s" % (
            sys.executable, _role_shim(env).replace("'", "'\\''"),
            " ".join(command)),
        "",
    ])
    with tempfile.NamedTemporaryFile("w", suffix=".sge.sh",
                                     delete=False) as f:
        f.write(script)
        path = f.name
    cmd = ["qsub", "-sync", "y", path]
    try:
        return subprocess.call(cmd, env={**os.environ, **env})
    except FileNotFoundError:
        sys.stderr.write(
            "qsub not found on PATH; submit the generated job script "
            "yourself:\n  %s\n" % path)
        return 127


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--num-workers", type=int, default=None)
    ap.add_argument("-s", "--num-servers", type=int, default=1)
    ap.add_argument("--launcher", choices=["local", "ssh", "mpi",
                                           "slurm", "sge", "yarn",
                                           "serve", "http"],
                    default="local")
    ap.add_argument("--prefill", type=int, default=1,
                    help="serve launcher: prefill worker processes")
    ap.add_argument("--decode", type=int, default=1,
                    help="serve launcher: decode worker processes")
    ap.add_argument("--workers-only", action="store_true",
                    help="serve launcher: spawn ONLY workers against "
                         "a LIVE router at --router-host:-p (round-16 "
                         "scale-up path: the router must be waiting "
                         "in add_worker(role, spawn=False)); no "
                         "router command is run")
    ap.add_argument("--router-host", default="127.0.0.1",
                    help="serve launcher: router control host the "
                         "workers connect to")
    ap.add_argument("--worker-start", type=int, default=0,
                    help="serve launcher: first worker INDEX per "
                         "role (--workers-only joining a cluster "
                         "that already has prefill0..N-1)")
    ap.add_argument("-H", "--hostfile", default=None)
    ap.add_argument("-p", "--port", type=int, default=None)
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if not args.command and args.launcher != "http" \
            and not (args.launcher == "serve" and args.workers_only):
        ap.error("no command given")
    if args.launcher == "http":
        sys.exit(launch_http(args, args.command))
    if args.launcher == "serve":
        sys.exit(launch_serve(args, args.command))
    if args.num_workers is None:
        ap.error("-n/--num-workers is required for this launcher")
    if args.launcher == "local":
        sys.exit(launch_local(args, args.command))
    if args.launcher in ("mpi", "slurm"):
        sys.exit(launch_mpi(args, args.command))
    if args.launcher == "sge":
        sys.exit(launch_sge(args, args.command))
    if args.launcher == "yarn":
        # reference dmlc_tracker/yarn.py drives a Hadoop YARN client jar;
        # there is no YARN runtime in scope to build or test against —
        # deliberate absence, documented rather than stubbed wrong.
        sys.stderr.write(
            "yarn launcher: not supported in this build (needs a Hadoop "
            "cluster + the dmlc-yarn client jar; use ssh/mpi/slurm/sge "
            "against the same DMLC_* contract instead)\n")
        sys.exit(2)
    sys.exit(launch_ssh(args, args.command))


if __name__ == "__main__":
    main()
