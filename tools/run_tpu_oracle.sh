#!/bin/sh
# Drive the cpu-vs-tpu oracle to completion across tunnel wedges: the
# PjRt client cannot recover once the axon relay errors, so the tool
# exits with code 3 and a resume index; this wrapper restarts it in a
# fresh process until every case has run.
set -u
cd "$(dirname "$0")/.."
RECORD=${1:-docs/tpu_consistency_record.json}
START=0
while :; do
    python tools/check_tpu_consistency.py --record "$RECORD" \
        --start "$START" > /tmp/oracle_chunk.log 2>&1
    rc=$?
    cat /tmp/oracle_chunk.log
    if [ "$rc" != 3 ]; then
        exit "$rc"
    fi
    NEXT=$(grep -o "resume with --start [0-9]*" /tmp/oracle_chunk.log \
           | tail -1 | grep -o "[0-9]*$")
    if [ -z "$NEXT" ] || [ "$NEXT" = "$START" ]; then
        # same case wedges a fresh process twice -> skip it; the record
        # will show completed < cases for it (no pass/fail/skip bucket)
        echo "WARNING: case $START wedged two fresh processes —" \
             "permanently skipped; record is one case short" >&2
        NEXT=$((START + 1))
    fi
    START=$NEXT
    sleep 10
done
