"""Regenerate the family-by-family presence check in op_coverage.md.

Run: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python docs/gen_op_coverage.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from mxnet_tpu.ops import registry            # noqa: E402
import mxnet_tpu.numpy as mnp                 # noqa: E402

FAMILIES = {
 "nn core": ["Activation", "BatchNorm", "Convolution", "Deconvolution",
             "Dropout", "Embedding", "FullyConnected", "LRN",
             "LayerNorm", "GroupNorm", "InstanceNorm", "L2Normalization",
             "Pooling", "RNN", "SoftmaxOutput", "softmax", "log_softmax",
             "masked_softmax", "masked_log_softmax", "SequenceLast",
             "SequenceMask", "SequenceReverse", "SliceChannel",
             "UpSampling", "Pad", "Concat", "Flatten", "LeakyReLU",
             "CTCLoss", "SpatialTransformer", "GridGenerator",
             "BilinearSampler", "SwapAxis", "Cast", "BlockGrad",
             "MakeLoss", "Crop", "softmax_activation", "hard_sigmoid",
             "softsign", "relu", "sigmoid", "mish", "log_sigmoid"],
 "contrib detection": [
     "_contrib_DeformableConvolution",
     "_contrib_ModulatedDeformableConvolution",
     "_contrib_DeformablePSROIPooling", "_contrib_PSROIPooling",
     "_contrib_Proposal", "_contrib_MultiProposal", "_contrib_ROIAlign",
     "ROIPooling", "_contrib_RROIAlign", "_contrib_box_iou",
     "_contrib_box_nms", "_contrib_box_encode", "_contrib_box_decode",
     "_contrib_bipartite_matching", "MultiBoxPrior", "MultiBoxTarget",
     "MultiBoxDetection", "_contrib_BilinearResize2D",
     "_contrib_AdaptiveAvgPooling2D", "Correlation",
     "_contrib_SyncBatchNorm"],
 "contrib transformer": [
     "_contrib_interleaved_matmul_selfatt_qk",
     "_contrib_interleaved_matmul_selfatt_valatt",
     "_contrib_interleaved_matmul_encdec_qk",
     "_contrib_interleaved_matmul_encdec_valatt",
     "_contrib_div_sqrt_dim", "_contrib_arange_like"],
 "contrib misc": ["_contrib_quadratic", "_contrib_gradientmultiplier",
                  "_contrib_allclose", "_contrib_getnnz",
                  "_contrib_count_sketch", "_contrib_group_adagrad_update",
                  "_contrib_index_array", "_contrib_index_copy",
                  "_contrib_boolean_mask", "_contrib_fft", "_contrib_ifft"],
 "optimizer": ["sgd_update", "sgd_mom_update", "mp_sgd_update",
               "mp_sgd_mom_update", "nag_mom_update", "mp_nag_mom_update",
               "adam_update", "mp_adam_update", "adamw_update",
               "ftrl_update", "rmsprop_update", "rmspropalex_update",
               "signsgd_update", "signum_update", "lamb_update_phase1",
               "lamb_update_phase2", "mp_lamb_update_phase1",
               "mp_lamb_update_phase2", "multi_sgd_update",
               "multi_sgd_mom_update", "multi_mp_sgd_update",
               "multi_mp_sgd_mom_update", "multi_lars", "multi_sum_sq",
               "multi_all_finite", "preloaded_multi_sgd_update",
               "preloaded_multi_sgd_mom_update", "all_finite",
               "reset_arrays", "_contrib_group_adagrad_update"],
 "random": ["_random_uniform", "_random_normal", "_random_gamma",
            "_random_exponential", "_random_poisson",
            "_random_negative_binomial",
            "_random_generalized_negative_binomial", "_random_randint",
            "_sample_uniform", "_sample_normal", "_sample_gamma",
            "_sample_exponential", "_sample_poisson",
            "_sample_negative_binomial",
            "_sample_generalized_negative_binomial",
            "_sample_multinomial", "_sample_unique_zipfian", "_shuffle"],
 "linalg": ["linalg_gemm", "linalg_gemm2", "linalg_potrf", "linalg_potri",
            "linalg_trmm", "linalg_trsm", "linalg_sumlogdiag",
            "linalg_syrk", "linalg_gelqf", "linalg_syevd", "linalg_det",
            "linalg_slogdet", "linalg_inverse", "linalg_extractdiag",
            "linalg_makediag", "linalg_extracttrian", "khatri_rao"],
 "quantization": ["quantize", "quantize_v2", "dequantize", "requantize",
                  "quantized_conv", "quantized_fully_connected",
                  "quantized_pooling", "quantized_act",
                  "quantized_flatten"],
}


def main():
    have = set(registry.list_ops())
    np_fns = [n for n in dir(mnp)
              if not n.startswith("_") and callable(getattr(mnp, n))]
    print("registry ops:", len(have))
    print("mx.np callables:", len(np_fns))
    bad = []
    for fam, names in FAMILIES.items():
        missing = [n for n in names if n not in have]
        print("%-22s %d/%d present; missing: %s"
              % (fam, len(names) - len(missing), len(names),
                 missing or "none"))
        bad += missing
    if bad:
        raise SystemExit("MISSING: %r" % bad)
    print("all enumerated families fully present")


if __name__ == "__main__":
    main()
