"""Reduced repro: XLA:CPU AllReducePromotion crash on the gradient of a
partial-manual shard_map containing a bf16 ppermute (round-3 verdict
#9; upstream-issue quality).

Observed on jax 0.9.0 / CPU backend with 8 virtual devices::

    F hlo_instruction.cc:1585] Invalid binary instruction opcode copy
    ... xla::(anonymous namespace)::CloneAllReduce()
    ... xla::ChangeOpDataType::RunImpl()
    ... xla::AllReducePromotion::RunImpl()

Mechanism: the transpose of a shard_map whose manual axes are a strict
subset of the mesh ({"pp"} of a pp×dp mesh) emits an all-reduce over
``pp`` for the replicated-parameter gradient whose ``to_apply``
reduction computation is rooted in a ``copy`` instruction;
AllReducePromotion (which promotes bf16 all-reduces to f32 on CPU)
clones that reducer via ``HloInstruction::CreateBinary``, which
CHECK-fails on the non-binary ``copy`` opcode.  TPU does not run this
pass, and an f32 parameter at the shard_map boundary (cast to bf16
inside the manual region — the workaround in ``parallel/pipeline.py``)
avoids the bf16 all-reduce entirely.

Run:  JAX_PLATFORMS=cpu python docs/xla_cpu_bf16_pp_repro.py
      (crashes the process with the CHECK failure above;
       pass --workaround to see the f32-boundary version succeed)
"""
import sys

import numpy as np

import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def main():
    workaround = "--workaround" in sys.argv
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("pp", "dp"))

    def pp_body(x, w):
        wb = w.astype(jnp.bfloat16) if workaround else w
        y = jnp.dot(x, wb,
                    preferred_element_type=jnp.float32)
        y = y.astype(jnp.bfloat16)
        y = jax.lax.ppermute(y, "pp", [(0, 1), (1, 0)])
        return y

    f = jax.shard_map(pp_body, mesh=mesh, in_specs=(P("pp"), P()),
                      out_specs=P("pp"), axis_names={"pp"},
                      check_vma=False)

    def loss(w, x):
        return f(x, w).astype(jnp.float32).sum()

    w = jnp.ones((16, 16),
                 jnp.float32 if workaround else jnp.bfloat16)
    x = jnp.ones((4, 16), jnp.bfloat16)
    g = jax.jit(jax.grad(loss))(w, x)
    print("grad ok:", g.dtype)        # only reached with --workaround


if __name__ == "__main__":
    main()
