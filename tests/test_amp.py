"""AMP tests (reference: tests/python/gpu/test_amp.py — SURVEY.md §4.3)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import amp
from mxnet_tpu import test_utils as tu


@pytest.fixture
def amp_on():
    amp.init(target_dtype="bfloat16")
    yield
    amp.disable()


def test_amp_casts_matmul_to_bf16(amp_on):
    a = mx.nd.ones((4, 8))
    b = mx.nd.ones((8, 4))
    out = mx.nd.dot(a, b)
    assert str(out.dtype) == "bfloat16"
    # fp32-forced op comes back to float32
    s = mx.nd.softmax(out)
    assert str(s.dtype) == "float32"


def test_amp_widest_cast(amp_on):
    a = mx.nd.ones((2, 2))                        # f32
    b = mx.nd.ones((2, 2)).astype("bfloat16")
    out = mx.nd.broadcast_add(a, b)
    assert str(out.dtype) == "float32"


@pytest.mark.slow
def test_amp_gluon_training_converges(amp_on):
    np.random.seed(0)
    net = mx.gluon.nn.HybridSequential()
    net.add(mx.gluon.nn.Dense(32, activation="relu"))
    net.add(mx.gluon.nn.Dense(2))
    net.initialize(mx.initializer.Xavier())
    net.hybridize()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1})
    amp.init_trainer(trainer)
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()

    x = np.random.randn(64, 8).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.float32)
    losses = []
    for _ in range(30):
        data, label = mx.nd.array(x), mx.nd.array(y)
        with mx.autograd.record():
            out = net(data)
            L = loss_fn(out, label)
            with amp.scale_loss(L, trainer) as scaled:
                mx.autograd.backward(scaled)
        trainer.step(64)
        losses.append(float(L.mean().asnumpy()))
    assert losses[-1] < losses[0] * 0.7, losses[::10]


def test_loss_scaler_dynamics():
    s = amp.LossScaler(init_scale=1024, scale_factor=2, scale_window=3)
    s.update_scale(True)
    assert s.loss_scale == 512
    for _ in range(3):
        s.update_scale(False)
    assert s.loss_scale == 1024


def test_overflow_skips_update():
    net = mx.gluon.nn.Dense(2)
    net.initialize()
    x = mx.nd.ones((2, 4))
    with mx.autograd.record():
        out = net(x)
    out.backward()
    amp.init(target_dtype="float16")
    try:
        trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                                   {"learning_rate": 0.1})
        amp.init_trainer(trainer)
        w0 = net.weight.data().asnumpy().copy()
        # poison the gradient
        g = net.weight.grad()
        g._set_data(np.full(g.shape, np.inf, np.float32))
        scale0 = trainer._amp_loss_scaler.loss_scale
        trainer.step(2)
        assert trainer._amp_loss_scaler.loss_scale < scale0
        tu.assert_almost_equal(net.weight.data(), w0)
    finally:
        amp.disable()


def test_convert_symbol_inserts_casts():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    out = mx.sym.softmax(fc)
    conv = amp.convert_symbol(out, target_dtype="bfloat16")
    js = conv.tojson()
    assert "amp_cast" in js
    # converted graph still evaluates and matches fp32 within bf16 tol
    x = np.random.randn(2, 8).astype(np.float32)
    w = np.random.randn(4, 8).astype(np.float32)
    args = {"data": mx.nd.array(x), "fc_weight": mx.nd.array(w),
            "fc_bias": mx.nd.zeros((4,))}
    o1 = out._bind(mx.cpu(), dict(args), grad_req="null").forward()
    o2 = conv._bind(mx.cpu(), dict(args), grad_req="null").forward()
    tu.assert_almost_equal(o1[0], o2[0], rtol=3e-2, atol=3e-2)
