"""AMP tests (reference: tests/python/gpu/test_amp.py — SURVEY.md §4.3)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import amp
from mxnet_tpu import test_utils as tu


@pytest.fixture
def amp_on():
    amp.init(target_dtype="bfloat16")
    yield
    amp.disable()


def test_amp_casts_matmul_to_bf16(amp_on):
    a = mx.nd.ones((4, 8))
    b = mx.nd.ones((8, 4))
    out = mx.nd.dot(a, b)
    assert str(out.dtype) == "bfloat16"
    # fp32-forced op comes back to float32
    s = mx.nd.softmax(out)
    assert str(s.dtype) == "float32"


def test_amp_widest_cast(amp_on):
    a = mx.nd.ones((2, 2))                        # f32
    b = mx.nd.ones((2, 2)).astype("bfloat16")
    out = mx.nd.broadcast_add(a, b)
    assert str(out.dtype) == "float32"


@pytest.mark.slow
def test_amp_gluon_training_converges(amp_on):
    np.random.seed(0)
    net = mx.gluon.nn.HybridSequential()
    net.add(mx.gluon.nn.Dense(32, activation="relu"))
    net.add(mx.gluon.nn.Dense(2))
    net.initialize(mx.initializer.Xavier())
    net.hybridize()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1})
    amp.init_trainer(trainer)
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()

    x = np.random.randn(64, 8).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.float32)
    losses = []
    for _ in range(30):
        data, label = mx.nd.array(x), mx.nd.array(y)
        with mx.autograd.record():
            out = net(data)
            L = loss_fn(out, label)
            with amp.scale_loss(L, trainer) as scaled:
                mx.autograd.backward(scaled)
        trainer.step(64)
        losses.append(float(L.mean().asnumpy()))
    assert losses[-1] < losses[0] * 0.7, losses[::10]


def test_loss_scaler_dynamics():
    s = amp.LossScaler(init_scale=1024, scale_factor=2, scale_window=3)
    s.update_scale(True)
    assert s.loss_scale == 512
    for _ in range(3):
        s.update_scale(False)
    assert s.loss_scale == 1024


def test_overflow_skips_update():
    net = mx.gluon.nn.Dense(2)
    net.initialize()
    x = mx.nd.ones((2, 4))
    with mx.autograd.record():
        out = net(x)
    out.backward()
    amp.init(target_dtype="float16")
    try:
        trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                                   {"learning_rate": 0.1})
        amp.init_trainer(trainer)
        w0 = net.weight.data().asnumpy().copy()
        # poison the gradient
        g = net.weight.grad()
        g._set_data(np.full(g.shape, np.inf, np.float32))
        scale0 = trainer._amp_loss_scaler.loss_scale
        trainer.step(2)
        assert trainer._amp_loss_scaler.loss_scale < scale0
        tu.assert_almost_equal(net.weight.data(), w0)
    finally:
        amp.disable()


def test_amp_registry_classification_complete():
    """Round-6 sweep (verdict weak #5): every canonical registry op
    must carry an explicit AMP class — target / fp32 / widest /
    passthrough-safe.  A new op landing unclassified fails here instead
    of silently riding the hook's implicit else-branch; MXU-family ops
    (dot/conv/rnn/gemm/matmul) additionally may NOT hide in the
    passthrough list — they must be an explicit target (or a justified
    fp32/widest) entry."""
    import re
    from mxnet_tpu.contrib.amp import lists
    from mxnet_tpu.ops import registry

    canon = sorted({registry.get_op(n).name for n in registry.list_ops()})

    unclassified = [n for n in canon if lists.classify(n) is None]
    assert not unclassified, (
        "%d registry ops have no AMP classification — add each to "
        "TARGET_DTYPE_OPS / FP32_OPS / WIDEST_TYPE_CASTS / "
        "PASSTHROUGH_SAFE_OPS in contrib/amp/lists.py: %s"
        % (len(unclassified), unclassified))

    # no op may sit in two classes (first-match in the hook would
    # silently shadow the second)
    from collections import Counter
    seen = Counter(lists.TARGET_DTYPE_OPS + lists.FP32_OPS +
                   lists.WIDEST_TYPE_CASTS + lists.PASSTHROUGH_SAFE_OPS)
    dupes = [n for n, c in seen.items() if c > 1]
    assert not dupes, "ops in more than one AMP list: %s" % dupes

    # the MXU families must be deliberately placed, never passthrough.
    # quantized int8 conv/fc are exempt: their matmuls are already int8
    # with explicit scales (see the PASSTHROUGH_SAFE_OPS note).
    mxu = re.compile(r"(?i)(dot|conv|rnn|gemm|matmul|correlation|"
                     r"interleaved|einsum|tensordot)")
    for n in canon:
        if not mxu.search(n) or n.startswith("_contrib_quantized_"):
            continue
        cls = lists.classify(n)
        assert cls in ("target", "fp32"), (
            "MXU-family op %r classified %r — must be an explicit "
            "'target' (or justified 'fp32') entry" % (n, cls))

    # stale entries: every listed name must still exist in the registry
    # (aliases allowed) so the lists cannot rot as ops get renamed
    for n in seen:
        assert registry.op_exists(n), "AMP list entry %r is not a " \
            "registered op" % n


def test_amp_classify_helper():
    from mxnet_tpu.contrib.amp import lists
    assert lists.classify("dot") == "target"
    assert lists.classify("softmax") == "fp32"
    assert lists.classify("Concat") == "widest"
    assert lists.classify("relu") == "passthrough"
    assert lists.classify("no_such_op_xyz") is None


def test_convert_symbol_inserts_casts():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    out = mx.sym.softmax(fc)
    conv = amp.convert_symbol(out, target_dtype="bfloat16")
    js = conv.tojson()
    assert "amp_cast" in js
    # converted graph still evaluates and matches fp32 within bf16 tol
    x = np.random.randn(2, 8).astype(np.float32)
    w = np.random.randn(4, 8).astype(np.float32)
    args = {"data": mx.nd.array(x), "fc_weight": mx.nd.array(w),
            "fc_bias": mx.nd.zeros((4,))}
    o1 = out._bind(mx.cpu(), dict(args), grad_req="null").forward()
    o2 = conv._bind(mx.cpu(), dict(args), grad_req="null").forward()
    tu.assert_almost_equal(o1[0], o2[0], rtol=3e-2, atol=3e-2)
