"""Eager fast path — per-op compiled-callable cache (round-3 verdict
#3; reference: the Cython/FFI fast path, SURVEY.md §2.1 last row).
Unit coverage for the cache's semantic edges: identity-keyed safety,
dynamic lr, tracer bypass, blacklist fallback, LRU behavior, kill
switch."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.ops import registry as R


def setup_function(_fn):
    R._EAGER_CACHE.clear()
    R._EAGER_BLACKLIST.clear()


def test_cache_hit_is_single_entry_and_correct():
    a = nd.array(np.arange(12, dtype="float32").reshape(3, 4))
    r1 = nd.relu(a - 5).asnumpy()
    n0 = len(R._EAGER_CACHE)
    for _ in range(5):
        r2 = nd.relu(a - 5).asnumpy()
    assert len(R._EAGER_CACHE) == n0       # no growth on repeat calls
    np.testing.assert_array_equal(
        r1, np.maximum(np.arange(12).reshape(3, 4) - 5, 0))
    np.testing.assert_array_equal(r1, r2)


def test_distinct_attrs_get_distinct_entries():
    a = nd.array(np.random.rand(4, 6).astype("float32"))
    s1 = nd.sum(a, axis=0).asnumpy()
    s2 = nd.sum(a, axis=1).asnumpy()
    assert s1.shape == (6,) and s2.shape == (4,)
    np.testing.assert_allclose(s1, a.asnumpy().sum(0), rtol=1e-6)
    np.testing.assert_allclose(s2, a.asnumpy().sum(1), rtol=1e-6)


def test_lr_is_dynamic_not_a_cache_key():
    """Changing lr must not add cache entries (it is passed as a traced
    argument), and each call must use ITS lr value."""
    w = nd.ones((8,))
    g = nd.ones((8,))
    out = nd.sgd_update(w, g, lr=0.5)
    n0 = len(R._EAGER_CACHE)
    np.testing.assert_allclose(out.asnumpy(), 0.5)
    out2 = nd.sgd_update(w, g, lr=0.25)
    np.testing.assert_allclose(out2.asnumpy(), 0.75)
    assert len(R._EAGER_CACHE) == n0


def test_ephemeral_opdefs_are_not_cacheable():
    """Per-call OpDefs (getitem closures, autograd replay) must bypass
    the id-keyed cache — CPython reuses freed ids (round-3 bug class)."""
    from mxnet_tpu.ops.registry import OpDef
    op1 = OpDef("eph", lambda x: x * 2.0)
    assert not op1.cacheable
    handled, _ = R._eager_jit_call(op1, [nd.ones((2,))._data], (), {})
    assert not handled
    # registered ops ARE cacheable
    assert R.get_op("relu").cacheable


def test_slicing_values_are_not_cross_contaminated():
    """Regression: two different slice bounds through the stable
    _getitem op must not share a compiled callable."""
    x = nd.array(np.arange(64, dtype="float32").reshape(8, 8))
    a = x[0:2, 0:2]
    b = x[0:5, 0:3]
    assert a.shape == (2, 2) and b.shape == (5, 3)
    np.testing.assert_array_equal(b.asnumpy(),
                                  x.asnumpy()[0:5, 0:3])


def test_tracer_inputs_bypass_cache():
    """hybridize/vjp re-entry (tracer inputs) must not populate the
    eager cache."""
    import jax

    def f(v):
        op = R.get_op("relu")
        handled, _ = R._eager_jit_call(op, [v], (), {})
        assert not handled        # tracers are not concrete ArrayImpls
        return v

    jax.jit(f)(np.ones(3, "float32"))


def test_blacklist_falls_back_to_direct_path(caplog):
    """An impl that cannot trace gets blacklisted on first use and keeps
    working through the retracing path — announced by EXACTLY one log
    line (round-10 satellite: silent eager-path slowdowns were
    undiagnosable), repeat calls stay quiet."""
    import logging
    from mxnet_tpu.ops.registry import register, get_op, invoke

    name = "_test_untraceable_op"
    if not R.op_exists(name):
        @register(name, no_grad=True)
        def _untraceable(x):  # noqa: ANN001
            import numpy as _o
            return _o.asarray(x) * 2.0     # concretizes → untraceable

    # fresh state so the single-shot property is observable even when
    # another test already tripped this op
    R._EAGER_BLACKLIST.discard(name)
    R._EAGER_LOGGED.discard((name, "blacklisted"))
    op = get_op(name)
    with caplog.at_level(logging.WARNING,
                         logger="mxnet_tpu.ops.registry"):
        out = invoke(op, [nd.ones((3,))])
        np.testing.assert_allclose(np.asarray(out._data), 2.0)
        assert name in R._EAGER_BLACKLIST
        out2 = invoke(op, [nd.ones((3,))])     # stays on direct path
        np.testing.assert_allclose(np.asarray(out2._data), 2.0)
        out3 = invoke(op, [nd.ones((3,))])
        np.testing.assert_allclose(np.asarray(out3._data), 2.0)
    recs = [r for r in caplog.records
            if name in r.getMessage() and "pinned" in r.getMessage()]
    assert len(recs) == 1, \
        "blacklist must log exactly once, got %d" % len(recs)
    assert (name, "blacklisted") in R._EAGER_LOGGED


def test_autograd_and_cache_agree():
    """Recording mode replays through tracers; results must match the
    cached eager forward."""
    a = nd.array(np.random.RandomState(0).rand(4, 4).astype("float32"))
    eager = nd.sigmoid(a).asnumpy()
    a.attach_grad()
    with autograd.record():
        out = nd.sigmoid(a)
    out.backward()
    np.testing.assert_allclose(out.asnumpy(), eager, rtol=1e-6)
    s = eager * (1 - eager)
    np.testing.assert_allclose(a.grad.asnumpy(), s, rtol=1e-5)


def test_cache_lru_bound(monkeypatch):
    monkeypatch.setattr(R, "_EAGER_CACHE_MAX", 4)
    a = nd.ones((2, 2))
    for axis_pair in [(0,), (1,), (0, 1)]:
        nd.sum(a, axis=axis_pair)
    for k in range(2, 7):
        nd.reshape(nd.ones((4,)), shape=(2, 2))
        nd.sum(nd.ones((k, 2)), axis=1)
    assert len(R._EAGER_CACHE) <= 4


def test_kill_switch(monkeypatch):
    monkeypatch.setattr(R, "_EAGER_JIT", False)
    a = nd.ones((3, 3))
    out = nd.relu(a).asnumpy()
    np.testing.assert_array_equal(out, 1.0)
    assert len(R._EAGER_CACHE) == 0


def test_user_error_does_not_poison_blacklist():
    """Round-4 dispatch-tail fix: a caller error (wrong arity) on the
    FIRST call of an op must not blacklist it — only genuinely
    untraceable impls go to the retrace-per-call path."""
    from mxnet_tpu.ops.registry import get_op, invoke

    name = "_np_outer"
    R._EAGER_BLACKLIST.discard(name)
    op = get_op(name)
    with pytest.raises(Exception):
        invoke(op, [nd.ones((4,))])        # outer needs two operands
    assert name not in R._EAGER_BLACKLIST, \
        "user arity error poisoned the blacklist"
    out = invoke(op, [nd.ones((4,)), nd.ones((3,))])
    assert out.shape == (4, 3)
    # and the correct call landed in the compiled-callable cache
    assert any(k[0] == id(op) for k in R._EAGER_CACHE)
