"""Pallas implicit-GEMM fused conv kernel — interpreter-mode oracle.

The kernel is the committed artifact of the round-3 conv-ceiling
resolution (docs/conv_ceiling_experiment.md §6: it loses to the XLA
emitter per-shape and is NOT wired into the model path); this test
keeps it correct so the negative result stays reproducible."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu.kernels.fused_conv as fc


def _ref(x, w, scale=None, shift=None, relu=False):
    if scale is not None:
        x = x * scale + shift
        if relu:
            x = jnp.maximum(x, 0)
    return jax.lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))


@pytest.mark.slow
@pytest.mark.parametrize("case", [
    # (B, H, W, C, K, th, bk, prologue, relu, stats)
    (2, 8, 8, 8, 16, 4, 16, False, False, False),
    (2, 8, 8, 8, 16, 4, 16, True, True, True),
    (1, 12, 12, 16, 32, 6, 32, True, False, True),
])
def test_fused_conv_interpret(case):
    B, H, W, C, K, th, bk, prologue, relu, stats = case
    old = fc._INTERPRET
    fc._INTERPRET = True
    try:
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(B, H, W, C), jnp.float32)
        w = jnp.asarray(rng.randn(3, 3, C, K) * 0.1, jnp.float32)
        scale = jnp.asarray(rng.rand(C) + 0.5, jnp.float32) \
            if prologue else None
        shift = jnp.asarray(rng.randn(C) * 0.1, jnp.float32) \
            if prologue else None
        out = fc.conv3x3_fused(x, w, scale=scale, shift=shift,
                               relu=relu, stats=stats, th=th, bk=bk)
        r = _ref(x, w, scale, shift, relu)
        if stats:
            y, s, ss = out
            np.testing.assert_allclose(s, r.sum((0, 1, 2)), rtol=1e-4)
            np.testing.assert_allclose(ss, (r * r).sum((0, 1, 2)),
                                       rtol=1e-4)
        else:
            y = out
        np.testing.assert_allclose(y, r, rtol=1e-5, atol=1e-5)
    finally:
        fc._INTERPRET = old
