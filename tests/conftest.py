"""Test harness config: run everything on a virtual 8-device CPU mesh so
multi-chip code paths are exercised without TPU hardware (SURVEY.md §4 /
task brief).  Must run before jax is imported anywhere."""
import os

# Tests run on CPU; unsetting the axon pool IP makes the TPU sitecustomize
# skip tunnel registration entirely (robust against a busy/wedged tunnel).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# The environment's sitecustomize imports jax at interpreter startup with
# JAX_PLATFORMS=axon, so env vars alone are too late; jax>=0.9 also ignores
# xla_force_host_platform_device_count in favor of jax_num_cpu_devices.
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import numpy as _np
import pytest


@pytest.fixture(autouse=True)
def _seed_all():
    """Seeded-reproducible tests (reference: @with_seed decorator in
    tests/python/unittest/common.py)."""
    seed = int(os.environ.get("MXNET_TEST_SEED", "42"))
    _np.random.seed(seed)
    import mxnet_tpu as mx
    mx.random.seed(seed)
    yield
