"""Test harness config: run everything on a virtual 8-device CPU mesh so
multi-chip code paths are exercised without TPU hardware (SURVEY.md §4 /
task brief).  Must run before jax is imported anywhere."""
import os

# Tests run on CPU; unsetting the axon pool IP makes the TPU sitecustomize
# skip tunnel registration entirely (robust against a busy/wedged tunnel).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# The environment's sitecustomize imports jax at interpreter startup with
# JAX_PLATFORMS=axon, so env vars alone are too late; jax>=0.9 also ignores
# xla_force_host_platform_device_count in favor of jax_num_cpu_devices.
import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # jax < 0.5 has no jax_num_cpu_devices; it still honors the
    # XLA_FLAGS --xla_force_host_platform_device_count set above as
    # long as the backend has not initialized yet
    pass

import numpy as _np
import pytest

# -- slow-tier split (round-3 verdict #8) -----------------------------------
# The slow tier totals ~15 min on a 1-vCPU host — too long for one sitting.
# Each slow-marked MODULE is assigned to one of four balanced groups, each
# ≤~4.5 min, so CI/judges can run `pytest -m slow_a` … `-m slow_d` inside
# standard timeouts (tools/run_slow_tier.sh runs all four).  Measured
# per-file times: 2026-07-31 (this conftest).  Unlisted new slow modules
# land in slow_d by default.
_SLOW_GROUPS = {
    # group a: ~207s
    "test_train_convergence": "a", "test_vision_ops": "a",
    "test_test_utils": "a",
    # group b: ~219s
    "test_registry_sweep": "b", "test_dtype_matrix": "b",
    "test_operator_grad_sweep": "b", "test_operator": "b",
    "test_numpy": "b", "test_sparse": "b", "test_longtail_ops": "b",
    # group c: ~250s
    "test_pipeline_moe": "c", "test_parallel": "c",
    "test_ring_attention": "c",
    # group d: ~220s (everything else, incl. test_serving — the
    # continuous-batching engine, round 7)
    "test_serving": "d",
    # group e: ~4min — the collective-matrix pins compile 6 parallel
    # configs' steady-state train steps; too heavy to share a group
    "test_collective_matrix": "e",
    # group f: ~1min — the round-10 serving cluster (multi-replica
    # worker threads + watchdog timing); its own group so thread-
    # scheduling jitter never stretches group d past its budget
    "test_serving_cluster": "f",
    # group g: ~2min — round-11 in-engine speculation + paged-
    # attention kernel combos (every (kernel, spec_K) pair compiles a
    # fresh step program; isolated for the same budget reason as f)
    "test_serving_spec": "g",
    # group h: ~2min — round-12 interleaving explorer (>=200 seeded
    # schedules through the cluster; its own group so the sweep's
    # schedule count can grow without squeezing group f's budget)
    "test_interleave": "h",
    # group i: ~2.5min — round-14 tensor-parallel serving (every tp
    # config compiles a mesh-lowered step program on the virtual
    # 8-device mesh; isolated for the same compile-budget reason as g)
    "test_serving_tp": "i",
    # group j: ~4min — round-15 disaggregated prefill/decode serving
    # (each test spawns 2-3 worker OS processes that each import jax
    # and compile a step program; isolated so the per-test process
    # spawn cost never squeezes another group's budget)
    "test_serving_disagg": "j",
    # group k: ~3min — round-16 traffic realism (seeded trace replay,
    # autoscaler up/down with the zero-leak drain contract, chaos
    # kill/stall under burst vs the generate oracle; own group
    # because the scenarios pace themselves on the wall clock and
    # replica-thread scheduling jitter must not squeeze f/h)
    "test_serving_traffic": "k",
    # group l: ~2min — round-18 KV tiering (scripted pressure/spill
    # scenarios over tight pools; own group so the per-test engine
    # compiles never squeeze d/f)
    "test_serving_tier": "l",
    # group m: ~2min — round-19 training scale-out (FSDP/ICI-kvstore
    # exactness + byte-accounting; every config compiles its own
    # sharded train step on the virtual mesh, so the group is
    # isolated for the same compile-budget reason as e/g/i)
    "test_train_scale": "m",
    # group n: ~3min — round-20 HTTP/SSE front door (each scenario
    # runs a live asyncio server thread over a real cluster and paces
    # on the wall clock; own group so socket/scheduling jitter never
    # squeezes f/k)
    "test_http_frontend": "n",
    # group o: ~2min — round-21 latency-hiding overlap (every
    # scenario compiles the tok_src step variant on top of the
    # serial program, and the disagg case spawns worker processes;
    # own group so the double compile bill never squeezes d/f/j)
    "test_serving_overlap": "o",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.get_closest_marker("slow") is not None:
            mod = item.module.__name__.rsplit(".", 1)[-1]
            group = _SLOW_GROUPS.get(mod, "d")
            item.add_marker(getattr(pytest.mark, "slow_" + group))


@pytest.fixture(autouse=True)
def _seed_all():
    """Seeded-reproducible tests (reference: @with_seed decorator in
    tests/python/unittest/common.py)."""
    seed = int(os.environ.get("MXNET_TEST_SEED", "42"))
    _np.random.seed(seed)
    import mxnet_tpu as mx
    mx.random.seed(seed)
    yield
