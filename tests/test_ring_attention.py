"""Ring / Ulysses sequence-parallel attention vs dense reference
(8-device CPU mesh; SURVEY.md §5.7 — first-class extension)."""
import numpy as np
import pytest

import mxnet_tpu  # noqa: F401  (jax config via conftest)

pytestmark = pytest.mark.slow


def _ref_attention(q, k, v, mask, causal=False):
    import jax
    import jax.numpy as jnp
    dh = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / np.sqrt(dh)
    valid = mask[:, None, None, :] != 0
    if causal:
        T = q.shape[1]
        pos = jnp.arange(T)
        valid = valid & (pos[None, None, None, :] <= pos[None, None, :, None])
    s = jnp.where(valid, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(
        q.dtype)


def _inputs(B=2, T=32, H=4, dh=8, seed=0):
    import jax.numpy as jnp
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(B, T, H, dh).astype(np.float32))
    k = jnp.asarray(rng.randn(B, T, H, dh).astype(np.float32))
    v = jnp.asarray(rng.randn(B, T, H, dh).astype(np.float32))
    mask = np.ones((B, T), np.int8)
    mask[:, T - 5:] = 0          # padding at the end
    return q, k, v, jnp.asarray(mask)


@pytest.mark.parametrize("method", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_seq_parallel_matches_dense(method, causal):
    from mxnet_tpu.parallel import make_mesh, sequence_parallel_attention
    mesh = make_mesh({"dp": 2, "sp": 4})
    q, k, v, mask = _inputs()
    out = sequence_parallel_attention(q, k, v, mask, mesh=mesh,
                                      causal=causal, method=method)
    ref = _ref_attention(q, k, v, mask, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_attention_gradients_match():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import make_mesh, ring_attention
    mesh = make_mesh({"sp": 8})
    q, k, v, mask = _inputs(B=1, T=64)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mask, mesh=mesh,
                                      causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref_attention(q, k, v, mask, causal=True) ** 2)

    g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_transformer_forward_with_sp_mesh():
    """Full transformer forward under jit with dp×sp mesh + ring attn."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.models import transformer as T

    mesh = make_mesh({"dp": 2, "sp": 4})
    cfg = T.bert_tiny(use_flash=False, remat=False, dropout=0.0,
                      dtype="float32", seq_parallel="ring")
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((2, 128), dtype=jnp.int32)

    fn = jax.jit(lambda p, t: T.forward(p, t, cfg, mesh=mesh))
    out_sp = fn(params, tokens)

    cfg0 = T.bert_tiny(use_flash=False, remat=False, dropout=0.0,
                       dtype="float32")
    out_dense = T.forward(params, tokens, cfg0)
    np.testing.assert_allclose(np.asarray(out_sp), np.asarray(out_dense),
                               rtol=2e-4, atol=2e-4)


def test_train_step_with_sp_mesh():
    """One MLM train step over dp×sp — the long-context training config."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.models import transformer as T

    mesh = make_mesh({"dp": 2, "sp": 4})
    cfg = T.bert_tiny(use_flash=False, remat=True, dropout=0.1,
                      seq_parallel="ring")
    init_state, step = T.make_train_step(cfg, mesh=mesh)
    state = init_state(jax.random.PRNGKey(0))
    B, L = 2, 128
    tokens = jnp.zeros((B, L), dtype=jnp.int32)
    labels = jnp.where(jnp.arange(L)[None, :] % 7 == 0, tokens, -100)
    batch = {"tokens": tokens, "labels": labels,
             "mask": jnp.ones((B, L), dtype=jnp.int8)}
    state, loss = step(state, batch, jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))
