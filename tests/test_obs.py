"""Observability layer tests (round 8, ``mxnet_tpu/obs``):

* histogram percentile math pinned against numpy on known samples;
* counters reconciling EXACTLY against a deterministic scripted
  serving workload (N submits, forced preemption, full drain);
* one chrome-trace dump from a metrics-enabled serving run containing
  BOTH op events and request lifecycle spans on the shared clock;
* Prometheus exposition format; native decode-counter reset;
  MXEngineStats; training MetricsCallback / Monitor integration.

Round 23 — cluster-wide distributed tracing + flight recorder:

* crash-durable flight-recorder ring mechanics (wraparound,
  truncation, disabled path) plus real-SIGKILL forensics: a child
  process records and dies by signal 9; the parent recovers the tail;
* worker span shipping folded onto the router timeline — trace-merge
  reconciliation on a live cross-process cluster (spans stored
  per-rid, clock offsets measured, merged chrome dump with
  per-worker + transport swimlanes next to the router's lanes);
* the ops surfaces: ``debug_status`` / ``request_trace`` behind the
  HTTP front door's ``/debug/statusz`` + ``/debug/trace/<rid>``.

Pure-python instrument tests run in the fast tier; tests that step the
serving engine are slow (group d, with the rest of serving)."""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import native, obs, profiler
from mxnet_tpu.obs import (Counter, Gauge, Histogram, MetricsRegistry,
                           REQ_TID_BASE)

REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# instruments (fast tier)
# ---------------------------------------------------------------------------

def test_histogram_percentiles_vs_numpy():
    """Estimator pin: with bucket width w, the histogram percentile
    must land within w of numpy's exact percentile."""
    w = 5.0
    bounds = tuple(np.arange(w, 1000.0 + w, w))
    rng = np.random.RandomState(0)
    for dist in (rng.gamma(2.0, 80.0, 5000),
                 rng.uniform(0, 900, 2000),
                 np.concatenate([rng.normal(30, 5, 1000),
                                 rng.normal(700, 40, 50)])):
        dist = np.clip(dist, 0.01, 999.0)
        h = Histogram("t", bounds=bounds)
        for v in dist:
            h.observe(v)
        for q in (50, 90, 95, 99):
            est = h.percentile(q)
            exact = float(np.percentile(dist, q))
            assert abs(est - exact) <= w + 1e-9, (q, est, exact)
        assert h.count == len(dist)
        np.testing.assert_allclose(h.sum, dist.sum(), rtol=1e-9)


def test_histogram_edges_and_validation():
    h = Histogram("t", bounds=(1.0, 2.0, 4.0))
    assert h.percentile(50) == 0.0          # empty
    h.observe(100.0)                        # overflow bucket
    assert h.percentile(99) == 4.0          # clamps to last finite edge
    assert h.counts[-1] == 1
    with pytest.raises(ValueError):
        Histogram("bad", bounds=())
    with pytest.raises(ValueError):
        Histogram("bad", bounds=(2.0, 1.0))
    with pytest.raises(ValueError):
        Histogram("bad", bounds=(1.0, 1.0))


def test_registry_mechanics():
    reg = MetricsRegistry(labels={"engine": "7"})
    c = reg.counter("a_total", "help text")
    c.inc()
    c.inc(4)
    assert c.value == 5
    g = reg.gauge("b")
    g.set(2.5)
    g.inc()
    g.dec(0.5)
    assert g.value == 3.0
    # get-or-create returns the SAME instrument
    assert reg.counter("a_total") is c
    # kind conflicts are an error, not silent shadowing
    with pytest.raises(TypeError):
        reg.gauge("a_total")
    h = reg.histogram("h_ms")
    h.observe(3.0)
    snap = reg.snapshot()
    assert snap["labels"] == {"engine": "7"}
    assert snap["counters"]["a_total"] == 5
    assert snap["gauges"]["b"] == 3.0
    assert snap["histograms"]["h_ms"]["count"] == 1
    # reset_values zeroes in place: bound handles stay live
    reg.reset_values()
    assert c.value == 0 and h.count == 0 and sum(h.counts) == 0
    c.inc()
    assert reg.snapshot()["counters"]["a_total"] == 1


def test_sanitize_name():
    assert obs.sanitize_name("fc1.weight/grad") == "fc1_weight_grad"
    assert obs.sanitize_name("0abc")[0] == "_"


def test_prometheus_text_format():
    reg = MetricsRegistry(labels={"engine": "3"})
    reg.counter("x_total", "things").inc(2)
    reg.gauge("y").set(1.5)
    h = reg.histogram("lat_ms", bounds=(1.0, 10.0))
    for v in (0.5, 0.7, 5.0, 99.0):
        h.observe(v)
    text = obs.prometheus_text(registries=[reg], include_native=False)
    lines = text.splitlines()
    assert "# HELP x_total things" in lines
    assert "# TYPE x_total counter" in lines
    assert 'x_total{engine="3"} 2' in lines
    assert 'y{engine="3"} 1.5' in lines
    # cumulative buckets + +Inf tail + sum/count
    assert 'lat_ms_bucket{engine="3",le="1.0"} 2' in lines
    assert 'lat_ms_bucket{engine="3",le="10.0"} 3' in lines
    assert 'lat_ms_bucket{engine="3",le="+Inf"} 4' in lines
    assert 'lat_ms_count{engine="3"} 4' in lines
    assert any(l.startswith('lat_ms_sum{engine="3"}') for l in lines)


def test_prometheus_families_grouped_across_registries():
    """Text-format rule: every line of a metric family forms ONE group
    with a single TYPE header — two registries sharing names (two
    engines) must interleave as labeled series, not repeat families."""
    r0 = MetricsRegistry(labels={"engine": "0"})
    r1 = MetricsRegistry(labels={"engine": "1"})
    for r in (r0, r1):
        r.counter("steps_total").inc(1)
        r.histogram("lat_ms", bounds=(1.0,)).observe(0.5)
    text = obs.prometheus_text(registries=[r0, r1],
                               include_native=False)
    lines = text.splitlines()
    assert lines.count("# TYPE steps_total counter") == 1
    assert lines.count("# TYPE lat_ms histogram") == 1
    i0 = lines.index('steps_total{engine="0"} 1')
    i1 = lines.index('steps_total{engine="1"} 1')
    assert i1 == i0 + 1                     # adjacent: one family block


def test_prometheus_default_surface_includes_native():
    """The one-surface property: a scrape of the default surface
    carries native decode/engine/storage series when the library is
    loaded."""
    text = obs.prometheus_text()
    assert text.endswith("\n")
    if native.available():
        assert "mxnet_native_engine_ops_dispatched_total" in text
        assert "mxnet_native_decode_jpeg_total" in text


def test_profiler_record_events_gating(tmp_path):
    ev = {"name": "n", "ph": "i", "ts": profiler.now_us(),
          "pid": 1, "tid": 1, "s": "t"}
    assert profiler.is_recording() is False
    assert profiler.record_events([ev]) is False   # dropped, not queued
    profiler.set_config(filename=str(tmp_path / "t.json"))
    profiler.set_state("run")
    try:
        assert profiler.record_events([dict(ev, name="in_run")]) is True
    finally:
        profiler.set_state("stop")
    with open(profiler.dump()) as f:
        names = [e["name"] for e in json.load(f)["traceEvents"]]
    assert "in_run" in names and "n" not in names


# ---------------------------------------------------------------------------
# training-loop integration (fast tier)
# ---------------------------------------------------------------------------

class _FakeMetric:
    def get_name_value(self):
        return [("accuracy", 0.75), ("top k", 0.9)]


def test_metrics_callback_and_speedometer_gauge():
    from mxnet_tpu.callback import (BatchEndParam, MetricsCallback,
                                    Speedometer)
    reg = MetricsRegistry()
    cb = MetricsCallback(registry=reg, frequent=2, log=False)
    for nb in range(1, 5):
        cb(BatchEndParam(epoch=0, nbatch=nb, eval_metric=_FakeMetric()))
    snap = reg.snapshot()
    assert snap["counters"]["training_batches_total"] == 4
    assert snap["gauges"]["training_nbatch"] == 4
    assert snap["gauges"]["training_metric_accuracy"] == 0.75
    assert snap["gauges"]["training_metric_top_k"] == 0.9
    # 3 inter-batch intervals observed
    assert snap["histograms"]["training_batch_interval_ms"]["count"] == 3

    sp = Speedometer(batch_size=8, frequent=2, registry=reg)
    for nb in range(0, 5):
        sp(BatchEndParam(epoch=0, nbatch=nb, eval_metric=None))
    assert reg.snapshot()["gauges"]["training_samples_per_sec"] > 0


def test_monitor_publishes_gauges():
    reg = MetricsRegistry()
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    out = mx.sym.SoftmaxOutput(fc, name="softmax")
    mod = mx.mod.Module(out, context=mx.cpu())
    mod.bind([("data", (8, 16))], [("softmax_label", (8,))])
    mod.init_params()
    mon = mx.Monitor(interval=1, pattern=".*weight.*", registry=reg)
    mod.install_monitor(mon)
    from mxnet_tpu.io import DataBatch
    batch = DataBatch(data=[mx.nd.ones((8, 16))],
                      label=[mx.nd.zeros((8,))])
    mon.tic()
    mod.forward(batch, is_train=False)
    mon.toc()
    gauges = reg.snapshot()["gauges"]
    assert "monitor_fc_weight" in gauges
    assert gauges["monitor_fc_weight"] > 0


# ---------------------------------------------------------------------------
# native counters (fast tier, skipped without the library)
# ---------------------------------------------------------------------------

needs_native = pytest.mark.skipif(not native.available(),
                                  reason="native library not built")


@needs_native
def test_native_decode_counters_resettable():
    import cv2
    rng = np.random.RandomState(0)
    img = rng.randint(0, 255, size=(32, 40, 3), dtype=np.uint8)
    ok, buf = cv2.imencode(".jpg", img)
    assert ok
    native.decode_profile_reset()
    base = native.decode_profile_stats()
    assert base == {"jpeg": 0, "png": 0, "dct_scaled": 0, "errors": 0}
    native.imdecode(buf.tobytes())
    native.imdecode(buf.tobytes())
    st = native.decode_profile_stats()
    assert st["jpeg"] == 2
    with pytest.raises(mx.MXNetError):
        native.imdecode(b"definitely not an image")
    assert native.decode_profile_stats()["errors"] == 1
    native.decode_profile_reset()
    assert native.decode_profile_stats()["jpeg"] == 0
    # counters surface on the shared Prometheus exposition
    native.imdecode(buf.tobytes())
    assert "mxnet_native_decode_jpeg_total 1" in obs.prometheus_text()


@needs_native
def test_native_engine_stats():
    # explicit threaded reset: an earlier test may have left the
    # process-global engine in naive mode (workers == 0, no wakeups)
    eng = native.NativeEngine(engine_type="threaded")
    before = native.engine_stats()
    v = eng.new_var()
    done = []
    for _ in range(5):
        eng.push(lambda: done.append(1), mutate_vars=(v,))
    eng.wait_for_all()
    after = eng.stats()
    assert len(done) == 5
    assert after["ops_dispatched"] >= before["ops_dispatched"] + 5
    assert after["ops_executed"] >= before["ops_executed"] + 5
    assert after["outstanding"] == 0
    assert after["queue_depth"] == 0
    assert after["workers"] >= 1          # threaded default
    assert after["worker_wakeups"] >= 5
    eng.delete_var(v)
    eng.wait_for_all()


# ---------------------------------------------------------------------------
# serving-engine integration (slow tier, group d)
# ---------------------------------------------------------------------------

def _tiny(**kw):
    from mxnet_tpu.models import gpt
    base = dict(use_flash=False, remat=False, dropout=0.0,
                dtype="float32", vocab_size=128, max_len=64)
    base.update(kw)
    return gpt.gpt_tiny(**base)


def _mk_engine(metrics=True, **kw):
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.serving import ServingEngine
    cfg = _tiny()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    reg = MetricsRegistry(labels={"engine": "test"}) if metrics else None
    eng = ServingEngine(params, cfg, metrics=metrics, registry=reg,
                        **kw)
    return eng


def test_engine_metrics_env_and_disabled_path(monkeypatch):
    """Disabled = no obs object at all; env var arms the default."""
    eng = _mk_engine(metrics=False, num_slots=1, page_size=4)
    assert eng.metrics_enabled is False
    assert eng.registry is None
    assert eng.metrics() == {"enabled": False}
    monkeypatch.setenv("MXNET_SERVING_METRICS", "1")
    eng2 = _mk_engine(metrics=None, num_slots=1, page_size=4)
    assert eng2.metrics_enabled is True
    monkeypatch.setenv("MXNET_SERVING_METRICS", "0")
    eng3 = _mk_engine(metrics=None, num_slots=1, page_size=4)
    assert eng3.metrics_enabled is False


@pytest.mark.slow
def test_serving_counters_reconcile_scripted():
    """The reconciliation pin: a deterministic workload (3 submits, one
    cancel, full drain) must produce EXACTLY predictable counters —
    token/row counters equal the engine's own stats dict, TTFT count
    equals finished requests, TBT count equals tokens minus
    first-tokens."""
    rng = np.random.RandomState(0)
    eng = _mk_engine(num_slots=3, page_size=4, prefill_chunk=6)
    shapes = [(5, 8), (3, 12), (9, 4)]
    rids = [eng.submit(rng.randint(1, 90, P).astype(np.int32), N)
            for P, N in shapes]
    snap0 = eng.registry.snapshot()
    assert snap0["counters"]["serving_requests_submitted_total"] == 3
    assert snap0["gauges"]["serving_queued"] == 3
    eng.step()                              # admission happens here
    snap1 = eng.registry.snapshot()
    assert snap1["counters"]["serving_requests_admitted_total"] == \
        eng.stats["admitted"]
    assert snap1["gauges"]["serving_running"] == \
        sum(r is not None for r in eng._slots)
    outs = eng.run()
    m = eng.metrics()
    assert m["enabled"] is True
    c, g, h = m["counters"], m["gauges"], m["histograms"]
    n_tokens = sum(len(eng.requests[r].generated) for r in rids)
    assert outs and n_tokens == sum(n for _, n in shapes)
    # exact reconciliation against the engine's own accounting
    assert c["serving_steps_total"] == eng.stats["steps"]
    assert c["serving_decode_rows_total"] == eng.stats["decode_rows"]
    assert c["serving_prefill_rows_total"] == eng.stats["prefill_rows"]
    assert c["serving_dead_rows_total"] == eng.stats["dead_rows"]
    assert c["serving_requests_admitted_total"] == eng.stats["admitted"]
    assert c["serving_tokens_total"] == n_tokens
    assert c["serving_requests_finished_total"] == 3
    assert c["serving_preemptions_total"] == 0
    # page allocator mirror
    assert c["serving_pages_allocated_total"] == \
        eng.cache.alloc_pages_total
    assert c["serving_pages_freed_total"] == eng.cache.freed_pages_total
    assert c["serving_pages_allocated_total"] == \
        c["serving_pages_freed_total"]      # drained: all recycled
    # histograms: one TTFT per finished request, TBT for the rest,
    # one admission wait per admission, one step sample per step
    assert h["serving_ttft_ms"]["count"] == 3
    assert h["serving_tbt_ms"]["count"] == n_tokens - 3
    assert h["serving_admission_wait_ms"]["count"] == \
        eng.stats["admitted"]
    assert h["serving_step_ms"]["count"] == eng.stats["steps"]
    assert h["serving_ttft_ms"]["p99"] >= h["serving_tbt_ms"]["p50"]
    # terminal gauges
    assert g["serving_running"] == 0
    assert g["serving_queued"] == 0
    assert g["serving_pages_in_use"] == 0
    assert g["serving_page_free"] == eng.cache.num_pages - 1
    assert g["serving_hbm_held_bytes"] == 0
    # full telemetry reset (the bench warmup-exclusion path): registry
    # values, allocator ints, and the delta tracker reset TOGETHER, so
    # post-reset counters equal post-reset activity exactly
    eng.reset_metrics()
    eng.submit(rng.randint(1, 90, 5).astype(np.int32), 4)
    eng.run()
    c2 = eng.metrics()["counters"]
    assert eng.cache.alloc_pages_total > 0
    assert c2["serving_pages_allocated_total"] == \
        eng.cache.alloc_pages_total
    assert c2["serving_tokens_total"] == 4


@pytest.mark.slow
def test_serving_counters_forced_preemption():
    """Preemption path: an over-committed pool must count preemptions
    (== engine stats), re-admissions (admitted > submitted), and keep
    the token ledger exact through recompute."""
    rng = np.random.RandomState(3)
    eng = _mk_engine(num_slots=4, page_size=4, pages_per_slot=8,
                     num_pages=12, prefill_chunk=4)
    shapes = [(6, 20), (4, 24), (8, 16), (3, 22), (5, 18)]
    rids = [eng.submit(rng.randint(1, 90, P).astype(np.int32), N)
            for P, N in shapes]
    eng.run()
    m = eng.metrics()
    c = m["counters"]
    assert eng.stats["preemptions"] > 0
    assert c["serving_preemptions_total"] == eng.stats["preemptions"]
    assert c["serving_requests_admitted_total"] == \
        eng.stats["admitted"]
    # every preemption forces a re-admission
    assert eng.stats["admitted"] == \
        len(shapes) + eng.stats["preemptions"]
    assert c["serving_tokens_total"] == \
        sum(len(eng.requests[r].generated) for r in rids)
    assert c["serving_page_alloc_failures_total"] > 0
    assert m["histograms"]["serving_admission_wait_ms"]["count"] == \
        eng.stats["admitted"]


@pytest.mark.slow
def test_serving_spec_counters_reconcile():
    """Round-11 speculation ledger, reconciled exactly: drafted =
    accepted + rejected, counters equal the engine's own stats dict,
    the accept-rate gauge equals their ratio, and tokens_total still
    equals the tokens actually delivered (multi-commit steps change
    the per-step count, never the ledger)."""
    rng = np.random.RandomState(0)
    eng = _mk_engine(num_slots=2, page_size=4, prefill_chunk=6,
                     spec_K=3)
    rids = [eng.submit(rng.randint(1, 90, P).astype(np.int32), N)
            for P, N in [(5, 8), (3, 12), (9, 4)]]
    eng.run()
    m = eng.metrics()
    c, g, h = m["counters"], m["gauges"], m["histograms"]
    drafted = c["serving_spec_drafted_tokens_total"]
    accepted = c["serving_spec_accepted_tokens_total"]
    rejected = c["serving_spec_rejected_tokens_total"]
    assert drafted == eng.stats["spec_drafted"] > 0
    assert accepted == eng.stats["spec_accepted"]
    assert drafted == accepted + rejected
    assert g["serving_spec_accept_rate"] == accepted / drafted
    n_tokens = sum(len(eng.requests[r].generated) for r in rids)
    assert c["serving_tokens_total"] == n_tokens == 8 + 12 + 4
    # TBT records once per STEP per request (a verify step delivers
    # its commits as one burst), so ttft+tbt counts the sampling
    # steps, bounded by tokens when speculation commits multiples
    assert h["serving_ttft_ms"]["count"] == 3
    assert h["serving_tbt_ms"]["count"] <= n_tokens - 3
    # a spec engine with nothing accepted still reconciles: the
    # oracle-free drafter on random prompts may accept ~0 — the
    # ledger, not the rate, is the invariant here
    assert 0 <= accepted <= drafted


@pytest.mark.slow
def test_serving_spec_verify_trace_span(tmp_path):
    """The ``spec_verify`` span on the round-8 trace surface: emitted
    per speculating request per step while the profiler records, with
    drafted/accepted args, on the request's swimlane."""
    fname = str(tmp_path / "spec_trace.json")
    profiler.set_config(filename=fname)
    profiler.set_state("run")
    try:
        rng = np.random.RandomState(1)
        eng = _mk_engine(num_slots=2, page_size=4, prefill_chunk=4,
                         spec_K=2)
        eng.submit(rng.randint(1, 90, 5).astype(np.int32), 6)
        eng.run()
    finally:
        profiler.set_state("stop")
    with open(profiler.dump()) as f:
        trace = json.load(f)
    spans = [e for e in trace["traceEvents"]
             if e.get("cat") == "serving" and e["ph"] == "X"
             and e["name"] == "spec_verify"]
    assert spans, "no spec_verify spans in the dump"
    for e in spans:
        assert e["tid"] >= REQ_TID_BASE
        assert e["args"]["drafted"] >= 1
        assert 0 <= e["args"]["accepted"] <= e["args"]["drafted"]
    # exactly one span per draft-feeding decode step: the prefill-
    # finish step samples the first token with no drafts (TTFT), every
    # later sampling step is a decode step with drafts (TBT) — so
    # spans == TBT observations
    assert len(spans) == eng.registry.snapshot()["histograms"][
        "serving_tbt_ms"]["count"]


@pytest.mark.slow
def test_serving_cancel_counts():
    eng = _mk_engine(num_slots=1, page_size=4)
    r1 = eng.submit(np.arange(1, 6, dtype=np.int32), 6)
    r2 = eng.submit(np.arange(1, 4, dtype=np.int32), 6)
    eng.step()
    eng.cancel(r2)                          # still queued
    eng.cancel(r1)                          # running
    c = eng.metrics()["counters"]
    assert c["serving_requests_cancelled_total"] == 2
    assert c["serving_requests_finished_total"] == 0
    assert eng.metrics()["gauges"]["serving_running"] == 0


@pytest.mark.slow
def test_trace_dump_interleaves_ops_and_request_spans(tmp_path):
    """THE acceptance pin: one dump, op events AND lifecycle spans,
    shared clock."""
    fname = str(tmp_path / "serve_trace.json")
    profiler.set_config(filename=fname)
    profiler.set_state("run")
    try:
        rng = np.random.RandomState(0)
        eng = _mk_engine(num_slots=2, page_size=4, prefill_chunk=4)
        for P, N in [(5, 6), (3, 8)]:
            eng.submit(rng.randint(1, 90, P).astype(np.int32), N)
        eng.run()
        b = mx.nd.dot(mx.nd.ones((8, 8)), mx.nd.ones((8, 8)))
        b.wait_to_read()
    finally:
        profiler.set_state("stop")
    with open(profiler.dump()) as f:
        trace = json.load(f)                # validates as JSON
    evs = trace["traceEvents"]
    ops = [e for e in evs if e.get("cat") == "operator"]
    spans = [e for e in evs if e.get("cat") == "serving"
             and e["ph"] == "X"]
    metas = [e for e in evs if e["ph"] == "M"]
    op_names = {e["name"] for e in ops}
    span_names = {e["name"] for e in spans}
    assert "serving_step" in op_names and "dot" in op_names
    assert "admission_wait" in span_names
    assert "decode" in span_names
    assert any(n.startswith("prefill[") for n in span_names)
    instants = {e["name"] for e in evs if e.get("cat") == "serving"
                and e["ph"] == "i"}
    assert {"first_token", "retire"} <= instants
    # request swimlanes: tids in the reserved range, named via metadata
    req_tids = {e["tid"] for e in spans}
    assert all(t >= REQ_TID_BASE for t in req_tids)
    named = {e["tid"] for e in metas
             if e["args"]["name"].startswith("req ")}
    assert req_tids <= named
    # shared clock: serving spans and op events overlap in time
    t_ops = [e["ts"] for e in ops]
    t_spans = [e["ts"] for e in spans]
    assert min(t_spans) <= max(t_ops) and min(t_ops) <= max(t_spans)
    # op events and spans use the same pid group
    assert {e["pid"] for e in ops} == {e["pid"] for e in spans}


@pytest.mark.slow
def test_trace_metadata_reemitted_after_dump(tmp_path):
    """Every dump() starts a new trace file; each must carry its own
    swimlane thread_name metadata or post-first dumps show raw tids."""
    profiler.set_config(filename=str(tmp_path / "a.json"))
    profiler.set_state("run")
    try:
        rng = np.random.RandomState(0)
        eng = _mk_engine(num_slots=1, page_size=4)
        eng.submit(rng.randint(1, 90, 4).astype(np.int32), 4)
        eng.run()
        first = profiler.dump(filename=str(tmp_path / "a.json"))
        eng.submit(rng.randint(1, 90, 4).astype(np.int32), 4)
        eng.run()
    finally:
        profiler.set_state("stop")
    second = profiler.dump(filename=str(tmp_path / "b.json"))
    for fname in (first, second):
        evs = json.load(open(fname))["traceEvents"]
        span_tids = {e["tid"] for e in evs
                     if e.get("cat") == "serving"}
        named = {e["tid"] for e in evs if e["ph"] == "M"
                 and e["args"]["name"].startswith("req ")}
        assert span_tids and span_tids <= named, fname


@pytest.mark.slow
def test_registry_implies_metrics():
    """registry= must not be silently dropped."""
    from mxnet_tpu.serving import ServingEngine
    import jax
    from mxnet_tpu.models import transformer as T
    cfg = _tiny()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    reg = MetricsRegistry()
    eng = ServingEngine(params, cfg, num_slots=1, page_size=4,
                        registry=reg)     # no metrics= → implied True
    assert eng.metrics_enabled and eng.registry is reg
    with pytest.raises(ValueError):
        ServingEngine(params, cfg, num_slots=1, page_size=4,
                      metrics=False, registry=reg)


@pytest.mark.slow
def test_shared_registry_counters_stay_monotonic():
    """Two engines on one registry: allocator counters must aggregate
    by delta, never flip backwards between the engines' totals."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.serving import ServingEngine
    rng = np.random.RandomState(0)
    reg = MetricsRegistry()
    cfg = _tiny()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    engines = [ServingEngine(params, cfg, num_slots=1, page_size=4,
                             registry=reg) for _ in range(2)]
    ctr = reg.counter("serving_pages_allocated_total")
    last = 0
    for step_round in range(6):
        for e in engines:
            if step_round == 0:
                e.submit(rng.randint(1, 90, 4).astype(np.int32), 5)
            e.step()
            assert ctr.value >= last, (step_round, ctr.value, last)
            last = ctr.value
    for e in engines:
        e.run()
    assert ctr.value == sum(e.cache.alloc_pages_total for e in engines)


@pytest.mark.slow
def test_no_trace_events_without_profiler():
    """Metrics without a profiler session must not accumulate trace
    memory (the emitter drops batches while not recording)."""
    rng = np.random.RandomState(0)
    eng = _mk_engine(num_slots=2, page_size=4)
    eng.submit(rng.randint(1, 90, 5).astype(np.int32), 6)
    eng.run()
    assert eng._obs.trace._pending == []
    assert eng.metrics()["counters"]["serving_tokens_total"] == 6


@pytest.mark.slow
def test_serve_bench_telemetry_smoke(tmp_path):
    """serve_bench's source of truth is now the engine histogram; the
    telemetry row must carry the percentile set, the external
    cross-check, and sub-10% divergence (enforced inside run_engine —
    reaching this assert means it passed)."""
    import os
    import sys
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmark"))
    import serve_bench
    out = str(tmp_path / "serve.json")
    rc = serve_bench.main(["--quick", "--json", out])
    assert rc == 0
    rows = json.load(open(out))
    tel = [r for r in rows if r["section"] == "telemetry"]
    assert len(tel) == 1
    t = tel[0]
    for k in ("ttft_p50_ms", "ttft_p95_ms", "ttft_p99_ms",
              "tbt_p50_ms", "tbt_p95_ms", "tbt_p99_ms",
              "ext_tbt_p99_ms", "ext_ttft_p99_ms", "tbt_mean_ms",
              "ext_tbt_mean_ms", "tbt_p99_divergence",
              "overhead_incl_harness_pct"):
        assert k in t, k
    # rc == 0 means the in-bench divergence guards passed; re-assert
    # the mean agreement (exact arithmetic, no bucket quantization)
    assert abs(t["tbt_mean_ms"] - t["ext_tbt_mean_ms"]) <= \
        max(0.10 * t["ext_tbt_mean_ms"], 0.2)
    assert t["tbt_p50_ms"] <= t["tbt_p95_ms"] <= t["tbt_p99_ms"]
    assert t["ttft_p99_ms"] > 0


@pytest.mark.slow
def test_cluster_router_prefix_metrics_scrape_and_trace(tmp_path):
    """Round-10 surface pin: router counters, prefix-cache hit
    counters/gauges, and failover events all land on the EXISTING
    observability surface — cluster + per-replica prefix families in
    one Prometheus scrape, failover/resubmit instants in the chrome
    trace on the request's swimlane."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.serving import ServingCluster

    cfg = _tiny()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.RandomState(0)
    shared = rng.randint(1, 90, 8).astype(np.int32)
    fname = str(tmp_path / "cluster_trace.json")
    profiler.set_config(filename=fname)
    profiler.set_state("run")
    try:
        cl = ServingCluster(params, cfg, replicas=2, num_slots=2,
                            page_size=4, prefill_chunk=6,
                            metrics=True, watchdog_s=10.0)
        eng0 = cl.replicas[0].engine
        orig_step = eng0.step
        calls = [0]

        def bomb():
            calls[0] += 1
            if calls[0] == 4:
                raise RuntimeError("injected failure")
            return orig_step()

        eng0.step = bomb
        rids = []
        for i in range(6):
            p = np.concatenate([shared, rng.randint(1, 90, 2 + i)
                                .astype(np.int32)])
            rids.append(cl.submit(p, 6))
        for rid in rids:
            cl.result(rid, timeout=300)
        scrape = obs.prometheus_text()
        # numeric checks below are scoped to THIS cluster's
        # registries: the default scrape aggregates every live
        # registry in the process, so earlier tests' engines/clusters
        # (alive until GC) would skew the summed values
        scoped = obs.prometheus_text(
            registries=[cl.registry]
            + [r.engine.registry for r in cl.replicas],
            include_native=False)
        cl.close(timeout=60)
    finally:
        profiler.set_state("stop")

    # router families, labeled per cluster, on the shared scrape
    assert "# TYPE cluster_requests_submitted_total counter" in scrape
    assert 'cluster_requests_submitted_total{cluster="' in scrape
    for fam in ("cluster_failovers_total",
                "cluster_requests_resubmitted_total",
                "cluster_routed_affinity_total",
                "cluster_replicas_healthy", "cluster_ttft_ms_count"):
        assert fam in scrape, fam
    # prefix-cache families from the replica engines
    for fam in ("serving_prefix_hit_tokens_total",
                "serving_prefix_pages_inserted_total",
                "serving_prefix_cached_pages",
                "serving_prefix_hit_ratio"):
        assert fam in scrape, fam

    def _fam_value(name):
        tot = 0.0
        for line in scoped.splitlines():
            if line.startswith(name + "{") or \
                    line.startswith(name + " "):
                tot += float(line.rsplit(" ", 1)[1])
        return tot

    assert _fam_value("cluster_failovers_total") == 1
    assert _fam_value("cluster_requests_completed_total") == 6
    assert _fam_value("serving_prefix_hit_tokens_total") > 0

    # failover + resubmit instants on the request swimlanes, same
    # trace/clock as everything else
    with open(profiler.dump()) as f:
        trace = json.load(f)
    evs = trace["traceEvents"]
    inst = {e["name"] for e in evs
            if e.get("cat") == "serving" and e["ph"] == "i"}
    assert "failover" in inst and "resubmit" in inst
    fo = [e for e in evs if e.get("name") == "failover"]
    assert all(e["tid"] >= REQ_TID_BASE for e in fo)


# ---------------------------------------------------------------------------
# round 23 — flight recorder (fast tier)
# ---------------------------------------------------------------------------

def test_flight_ring_roundtrip_wraparound_truncation(tmp_path):
    """Ring mechanics: seq-ordered readback, wraparound keeping only
    the LAST n_slots events, oversized payloads truncated to a stub
    (never a torn slot), orderly close unlinking the file."""
    from mxnet_tpu.obs import flight
    rec = flight.FlightRecorder(slots=8, dir=str(tmp_path), pid=11)
    assert rec.enabled and os.path.exists(rec.path)
    for i in range(12):
        assert rec.record("ev", i=i, rid=100 + i) == i + 1
    evs = flight.read_flight(rec.path)
    # 12 records through 8 slots: seqs 5..12 survive, in order
    assert [e["seq"] for e in evs] == list(range(5, 13))
    assert [e["i"] for e in evs] == list(range(4, 12))
    assert all(e["kind"] == "ev" and e["rid"] == 100 + e["i"]
               for e in evs)
    ts = [e["t"] for e in evs]
    assert ts == sorted(ts) and all(t > 0 for t in ts)
    # oversized payload: replaced by a {"kind", "trunc"} stub that
    # still parses (the reader must never see half a JSON document)
    rec.record("big", blob="x" * 4096)
    assert rec.dropped == 1
    last = flight.read_flight(rec.path)[-1]
    assert last["kind"] == "big" and last["trunc"] > 4096
    path = rec.path
    rec.close(unlink=True)
    assert not os.path.exists(path)
    # recover-by-pid on a missing file: None, not a raise
    assert flight.flight_recover(11, dir=str(tmp_path)) is None


def test_flight_disabled_is_inert(tmp_path, monkeypatch):
    """slots=0 (arg or env) creates no file and record() is a no-op —
    the tracing-off path must do no I/O at all."""
    from mxnet_tpu.obs import flight
    rec = flight.FlightRecorder(slots=0, dir=str(tmp_path))
    assert not rec.enabled and rec.path is None
    assert rec.record("ev", x=1) is None
    assert os.listdir(str(tmp_path)) == []
    monkeypatch.setenv("MXNET_SERVE_FLIGHT_SLOTS", "0")
    rec2 = flight.FlightRecorder(dir=str(tmp_path))
    assert not rec2.enabled
    assert os.listdir(str(tmp_path)) == []
    rec.close()
    rec2.close()


def test_flight_recover_after_real_sigkill(tmp_path):
    """THE forensics pin: a child process records lifecycle events and
    dies by SIGKILL mid-flight — no atexit, no flush, no finally.  The
    parent recovers the tail by pid: mmap stores into the page cache
    are the durability mechanism."""
    from mxnet_tpu.obs import flight
    # the child loads flight.py by path (stdlib-only module): the test
    # exercises the crash path, not the package import
    src = os.path.join(REPO_DIR, "mxnet_tpu", "obs", "flight.py")
    child = (
        "import importlib.util, os, signal\n"
        "spec = importlib.util.spec_from_file_location('f', %r)\n"
        "f = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(f)\n"
        "rec = f.FlightRecorder(slots=16, dir=%r)\n"
        "for i in range(40):\n"
        "    rec.record('tick', i=i)\n"
        "rec.record('about_to_die', rid=7)\n"
        "os.kill(os.getpid(), signal.SIGKILL)\n"
        % (src, str(tmp_path)))
    proc = subprocess.Popen([sys.executable, "-c", child])
    proc.wait(timeout=60)
    assert proc.returncode == -signal.SIGKILL
    evs = flight.flight_recover(proc.pid, dir=str(tmp_path),
                                unlink=True)
    assert evs, "SIGKILLed child left no recoverable ring"
    # the last 16 of 41 records survive, tail intact and ordered
    assert len(evs) == 16
    assert [e["seq"] for e in evs] == list(range(26, 42))
    assert evs[-1]["kind"] == "about_to_die" and evs[-1]["rid"] == 7
    assert all(e["kind"] == "tick" for e in evs[:-1])
    # unlink=True consumed the file
    assert flight.flight_recover(proc.pid, dir=str(tmp_path)) is None


# ---------------------------------------------------------------------------
# round 23 — span shipping + merged trace (fast tier)
# ---------------------------------------------------------------------------

def test_span_buffer_wire_shape_cap_and_disable():
    from mxnet_tpu.obs.trace import SpanBuffer
    sb = SpanBuffer(cap=3)
    sb.span(1, "prefill", 1.0, 2.0, trace_id="req-a",
            args={"toks": 4})
    sb.instant(1, "submit_recv", 0.5, cat="transport")
    assert sb.drain() == [
        {"rid": 1, "name": "prefill", "ph": "X", "t0": 1.0,
         "t1": 2.0, "cat": "serving", "trace_id": "req-a",
         "args": {"toks": 4}},
        {"rid": 1, "name": "submit_recv", "ph": "i", "t": 0.5,
         "cat": "transport"}]
    assert sb.drain() == []                 # drained
    # over cap: new entries dropped and counted, never grown
    for i in range(5):
        sb.instant(i, "x", float(i))
    assert len(sb.drain()) == 3 and sb.dropped == 2
    off = SpanBuffer(cap=0)
    assert not off.enabled
    off.span(1, "a", 0.0, 1.0)
    off.instant(1, "b", 0.0)
    assert off.drain() == []


def test_merged_trace_lanes_offsets_and_flight_fold(tmp_path):
    """Router-side merge: wire spans from two 'workers' and a
    transport span land under synthetic chrome pids with
    process_name metadata; timestamps are corrected by each lane's
    clock offset; a recovered flight event folds in as an instant on
    the victim's lane."""
    from mxnet_tpu.obs.trace import (LANE_PID_BASE,
                                     MergedTraceEmitter)
    m = MergedTraceEmitter()
    # while NOT recording: batches are dropped, never retained
    m.add("w0", {"rid": 1, "name": "prefill", "ph": "X",
                 "t0": 1.0, "t1": 2.0})
    assert m.flush() is False and m._pending == []
    profiler.set_config(filename=str(tmp_path / "m.json"))
    profiler.set_state("run")
    try:
        m.add("w0", {"rid": 1, "name": "prefill", "ph": "X",
                     "t0": 1.0, "t1": 2.0, "trace_id": "e-1"},
              offset_s=0.25)
        m.add("w1", {"rid": 1, "name": "decode", "ph": "X",
                     "t0": 3.0, "t1": 4.5}, offset_s=-0.5)
        m.add("transport", {"rid": 1, "name": "transfer", "ph": "X",
                            "t0": 2.0, "t1": 2.1,
                            "cat": "transport"})
        m.add_flight("w0", {"kind": "step", "t": 5.0, "seq": 9,
                            "rid": 1, "active": 2})
        m.add("w0", {"rid": "garbage"})     # malformed: dropped
        assert m.flush() is True
    finally:
        profiler.set_state("stop")
    evs = json.load(open(profiler.dump()))["traceEvents"]
    names = {e["args"]["name"]: e["pid"] for e in evs
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert set(names) == {"w0", "w1", "transport"}
    assert all(pid >= LANE_PID_BASE for pid in names.values())
    by_name = {e["name"]: e for e in evs if e.get("ph") != "M"}
    # clock correction: ts = (t - offset) * 1e6 on the router clock
    assert by_name["prefill"]["ts"] == pytest.approx(0.75e6)
    assert by_name["prefill"]["dur"] == pytest.approx(1.0e6)
    assert by_name["prefill"]["args"]["trace_id"] == "e-1"
    assert by_name["decode"]["ts"] == pytest.approx(3.5e6)
    assert by_name["transfer"]["cat"] == "transport"
    fl = by_name["flight:step"]
    assert fl["ph"] == "i" and fl["cat"] == "flight"
    assert fl["pid"] == names["w0"]
    assert fl["args"]["seq"] == 9 and fl["args"]["active"] == 2
    assert by_name["prefill"]["pid"] == names["w0"]
    assert by_name["decode"]["pid"] == names["w1"]


# ---------------------------------------------------------------------------
# round 23 — cross-process trace merge + ops surface (slow tier)
# ---------------------------------------------------------------------------

def _tiny_disagg():
    import jax
    from mxnet_tpu.models import gpt as G
    cfg = G.gpt_tiny(dtype="float32")
    params = G.init_params(jax.random.PRNGKey(0), cfg)
    return params, cfg


def _disagg(params, cfg, **kw):
    from mxnet_tpu.serving import DisaggServingCluster
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("metrics", True)
    kw.setdefault("watchdog_s", 60.0)
    return DisaggServingCluster(params, cfg, **kw)


def _wait_spans(cl, rid, timeout=30.0):
    deadline = time.perf_counter() + timeout
    while True:
        try:
            spans = cl.request_trace(rid)["spans"]
        except KeyError:
            spans = []
        if spans or time.perf_counter() > deadline:
            return spans
        time.sleep(0.05)


@pytest.mark.slow
def test_disagg_trace_merge_reconciles(tmp_path):
    """Trace-merge reconciliation on a LIVE cluster: worker spans
    shipped on stats ticks land in the router's per-rid store stamped
    with worker name + clock offset and the edge-minted trace_id; the
    merged chrome dump holds router, per-worker, and transport
    swimlanes in ONE file; statusz reports measured clock offsets."""
    params, cfg = _tiny_disagg()
    rng = np.random.RandomState(0)
    ps = 4
    shared = rng.randint(1, cfg.vocab_size, 2 * ps).astype(np.int32)
    prompts = [np.concatenate(
        [shared, rng.randint(1, cfg.vocab_size, 3).astype(np.int32)])
        for _ in range(4)]
    profiler.set_config(filename=str(tmp_path / "merged.json"))
    profiler.set_state("run")
    try:
        cl = _disagg(params, cfg, prefill=2, decode=1, page_size=ps)
        try:
            rids = [cl.submit(p, 4, trace_id="edge-%d" % i)
                    for i, p in enumerate(prompts)]
            for rid in rids:
                cl.result(rid, timeout=180)
            # reconciliation: every request's DECODE span closes with
            # a token count equal to the committed stream the router
            # returned (spans ride the 0.25 s stats tick — poll)
            deadline = time.perf_counter() + 30
            decode_spans = {}
            while len(decode_spans) < len(rids) \
                    and time.perf_counter() < deadline:
                for rid in rids:
                    for s in _wait_spans(cl, rid, timeout=0):
                        if s["name"] == "decode" and "args" in s:
                            decode_spans[rid] = s
                time.sleep(0.05)
            assert len(decode_spans) == len(rids), decode_spans
            for rid, s in decode_spans.items():
                assert s["args"]["toks"] == 4
                assert s["t1"] >= s["t0"]
            spans = cl.request_trace(rids[-1])["spans"]
            # every span is stamped with its shipping worker and that
            # worker's measured clock offset, and carries the
            # edge-minted trace context
            workers = {s["worker"] for s in spans}
            assert workers <= set(cl.workers)
            assert all(np.isfinite(s["offset_s"]) for s in spans)
            assert any(s.get("trace_id") == "edge-%d" % (len(rids) - 1)
                       for s in spans)
            names = {s["name"] for s in spans}
            assert "submit_recv" in names
            # statusz: topology + per-worker clock model + flight ring
            ds = cl.debug_status()
            assert ds["kind"] == "disagg" and not ds["closed"]
            assert len(ds["workers"]) == 3
            for w in ds["workers"]:
                assert w["alive"] and not w["dead"]
                assert w["clock_offset_us"] is not None
                assert w["clock_rtt_us"] > 0
            assert ds["flight"]["path"]
            assert "windows" in ds["slo"]
            # request_trace on an unknown rid: KeyError, not a row
            with pytest.raises(KeyError):
                cl.request_trace(10 ** 9)
        finally:
            cl.close()
    finally:
        profiler.set_state("stop")
    evs = json.load(open(profiler.dump()))["traceEvents"]
    from mxnet_tpu.obs.trace import LANE_PID_BASE
    lanes = {e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e["name"] == "process_name"
             and e["pid"] >= LANE_PID_BASE}
    # all three workers shipped spans into the ONE dump; the shared
    # prefix crossed processes, so the transport lane is present too
    assert {"prefill0", "prefill1", "decode0"} <= lanes, lanes
    assert "transport" in lanes, lanes
    router_evs = [e for e in evs if e.get("pid", 0) < LANE_PID_BASE
                  and e.get("cat") == "serving"]
    assert router_evs, "router's own lanes missing from the dump"
    # reconciled clock: the corrected worker lanes overlap the
    # router's own span window (submit → ttft-span end).  Router
    # instants all sit at submit time — first-request compile puts
    # worker activity well after them — so the comparison must use
    # span ENDS (ts + dur): the router's ttft span stretches to the
    # first commit, past the worker's prefill start.  A broken offset
    # sign would shove the lanes a whole 2*offset outside the window.
    t_router0 = min(e["ts"] for e in router_evs if "ts" in e)
    t_router1 = max(e["ts"] + e.get("dur", 0.0)
                    for e in router_evs if "ts" in e)
    t_lanes = [e["ts"] for e in evs
               if e.get("pid", 0) >= LANE_PID_BASE and "ts" in e]
    assert min(t_lanes) <= t_router1 and t_router0 <= max(t_lanes)


@pytest.mark.slow
def test_disagg_sigkill_flight_tail_recovered():
    """Chaos forensics pin: SIGKILL a decode worker mid-decode; the
    router recovers the victim's flight-recorder tail (the black box
    an os._exit/SIGKILL leaves in /dev/shm), folds it into the trace
    surfaces, and every request still completes on the survivor."""
    params, cfg = _tiny_disagg()
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, cfg.vocab_size,
                           int(P)).astype(np.int32)
               for P in (5, 9, 14, 21)]
    nnew = [32] * 4
    cl = _disagg(params, cfg, prefill=1, decode=2, watchdog_s=30.0)
    try:
        rids = [cl.submit(p, n, trace_id="chaos-%d" % i)
                for i, (p, n) in enumerate(zip(prompts, nnew))]
        deadline = time.perf_counter() + 90
        while time.perf_counter() < deadline:
            with cl._lock:
                if any(r.state == "running" and r.phase == "decode"
                       and 0 < len(r.committed) < r.max_new_tokens
                       for r in cl.requests.values()):
                    break
            time.sleep(0.005)
        cl.kill_worker("decode0")
        for rid in rids:
            cl.result(rid, timeout=180)
        snap = cl.registry.snapshot()["counters"]
        assert snap["cluster_failovers_total"] >= 1
        ds = cl.debug_status()
        assert "decode0" in ds["flight"]["recovered"]
        victim = next(w for w in ds["workers"]
                      if w["worker"] == "decode0")
        assert victim["dead"]
        assert victim["flight_tail_events"] > 0
        # the recovered tail is the victim's own totally-ordered
        # event stream: per-step records with monotone seqs
        tail = cl._flight_tails["decode0"]
        seqs = [e["seq"] for e in tail]
        assert seqs == sorted(seqs)
        kinds = {e["kind"] for e in tail}
        assert "step" in kinds, kinds
        # the victim's ring file was consumed by the recovery sweep
        from mxnet_tpu.obs import flight
        pid = victim["pid"]
        assert pid is not None
        assert flight.flight_recover(pid) in (None, [])
    finally:
        cl.close()
