"""Deterministic interleaving explorer over the serving cluster
(ISSUE 7, dynamic half).  Slow tier, group h.

The sweep runs >= 200 seeded schedules (7 scripted workloads x 2
strategies x 20 seeds = 280; round 18 added the tier workload — spill
racing match racing preemption; round 21 added the overlap workload —
planner thread racing steps, submits, and a mid-pipeline cancel)
through
``tools.analysis.interleave``: every
schedule serializes the cluster's threads onto one runnable-at-a-time
order chosen by the seed, and asserts the same invariants the static
pass reasons about —

* **f32 greedy exactness**: every completed request is token-identical
  to single-engine ``generate`` whatever the interleaving;
* **refcount balance**: after drain, every replica's prefix-cache
  refcounts are zero and no page leaks (pages_in_use == cache-owned);
* **no deadlock**: the scheduler proves it by construction (all-blocked
  with no timed wait raises ``DeadlockError``), and the seeded-deadlock
  toy proves the detector actually fires.

Determinism pin: identical (workload, strategy, seed) triples produce
bit-identical yield-trace hashes.
"""
import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401  (conftest device setup)

from tools.analysis.interleave import DeadlockError, run_schedule

SEEDS = 20          # per (workload, strategy) cell; 7 * 2 * 20 = 280
MODES = ("random", "preempt")


def _cfg(**kw):
    from mxnet_tpu.models import gpt
    base = dict(use_flash=False, remat=False, dropout=0.0,
                dtype="float32", vocab_size=128, max_len=64)
    base.update(kw)
    return gpt.gpt_tiny(**base)


@pytest.fixture(scope="module")
def env():
    """Params/cfg + memoized single-engine references, with every
    compile warmed OUTSIDE the scheduler (the step/copy caches are
    config-keyed, so the schedules themselves never compile)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.serving import ServingCluster

    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    cl = ServingCluster(params, cfg, replicas=1, num_slots=2,
                        page_size=4, prefill_chunk=6)
    rid = cl.submit(np.arange(1, 7, dtype=np.int32), 4)
    cl.result(rid, timeout=300)
    cl.close(timeout=60)
    # the overlap (tok_src) step program is a DIFFERENT compiled
    # variant — warm it too, same engine geometry as the workloads
    # (wl_overlap_plan must never compile under the scheduler)
    from mxnet_tpu.serving import ServingEngine
    eng = ServingEngine(params, cfg, num_slots=2, page_size=4,
                        prefill_chunk=6, prefix_cache=True,
                        overlap=True)
    eng.submit(np.arange(1, 7, dtype=np.int32), 4)
    eng.run()
    eng.close()

    refs = {}

    def ref(prompt, n):
        key = (prompt.tobytes(), n)
        if key not in refs:
            refs[key] = np.asarray(gpt.generate(
                params, cfg, jnp.asarray(prompt)[None], n))[0]
        return refs[key]

    return params, cfg, ref


# ---------------------------------------------------------------------------
# scripted workloads — each builds, drives, verifies, and closes one
# cluster; prompts are fixed (same work under every schedule)
# ---------------------------------------------------------------------------
def _prompts_mixed(n):
    rng = np.random.RandomState(0)
    shared = rng.randint(1, 90, 8).astype(np.int32)
    out = []
    for i in range(n):
        if i % 2 == 0:
            p = np.concatenate([shared, rng.randint(1, 90, 2 + i)
                                .astype(np.int32)])
        else:
            p = rng.randint(1, 90, 4 + i).astype(np.int32)
        out.append((p, 3 + (i % 3)))
    return out


def _check_refcounts(cl):
    for rep in cl.replicas:
        prefix = rep.engine.prefix
        if prefix is None or rep.dead:
            continue
        assert prefix.refs_total == 0, \
            "replica %d leaked prefix refs" % rep.idx
        assert rep.engine.cache.pages_in_use == prefix.cached_pages, \
            "replica %d leaked pages" % rep.idx


def wl_submit_burst(params, cfg, ref):
    from mxnet_tpu.serving import ServingCluster
    wl = _prompts_mixed(5)
    cl = ServingCluster(params, cfg, replicas=2, num_slots=2,
                        page_size=4, prefill_chunk=6)
    try:
        rids = [cl.submit(p, n) for p, n in wl]
        for rid, (p, n) in zip(rids, wl):
            np.testing.assert_array_equal(cl.result(rid, timeout=300),
                                          ref(p, n))
        _check_refcounts(cl)
    finally:
        cl.close(timeout=60)


def wl_failover(params, cfg, ref):
    """Replica 0's engine raises on its 3rd step: waiting + in-flight
    requests must resubmit to the survivor recompute-exact."""
    from mxnet_tpu.serving import ServingCluster
    wl = _prompts_mixed(4)
    cl = ServingCluster(params, cfg, replicas=2, num_slots=2,
                        page_size=4, prefill_chunk=6)
    try:
        eng0 = cl.replicas[0].engine
        orig_step = eng0.step
        calls = [0]

        def bomb():
            calls[0] += 1
            if calls[0] == 3:
                raise RuntimeError("injected replica failure")
            return orig_step()

        eng0.step = bomb
        rids = [cl.submit(p, n) for p, n in wl]
        for rid, (p, n) in zip(rids, wl):
            np.testing.assert_array_equal(cl.result(rid, timeout=300),
                                          ref(p, n))
        _check_refcounts(cl)
    finally:
        cl.close(timeout=60)


def wl_drain_while_submitting(params, cfg, ref):
    """drain_replica(0) racing a burst of submit(): every request —
    rerouted stray or post-drain submit — completes exactly."""
    from mxnet_tpu.serving import ServingCluster
    from mxnet_tpu.serving import cluster as cluster_mod
    wl = _prompts_mixed(6)
    cl = ServingCluster(params, cfg, replicas=2, num_slots=2,
                        page_size=4, prefill_chunk=6)
    try:
        rids = []

        def submitter():
            for p, n in wl:
                rids.append(cl.submit(p, n))

        # cluster_mod.threading is the scheduler shim inside a
        # schedule (and the real module outside one)
        th = cluster_mod.threading.Thread(target=submitter,
                                          name="submitter")
        th.start()
        assert cl.drain_replica(0, timeout=300)
        th.join(300)
        assert len(rids) == len(wl)
        for rid, (p, n) in zip(rids, wl):
            np.testing.assert_array_equal(cl.result(rid, timeout=300),
                                          ref(p, n))
        for cr in (cl.requests[r] for r in rids):
            assert cr.state == "done"
        _check_refcounts(cl)
    finally:
        cl.close(timeout=60)


def wl_ttl_expiry(params, cfg, ref):
    """A ttl_s=0 request expires while waiting; traffic around it is
    unaffected."""
    from mxnet_tpu.serving import (RequestExpired, ServingCluster)
    cl = ServingCluster(params, cfg, replicas=1, num_slots=1,
                        page_size=4, prefill_chunk=4)
    try:
        rng = np.random.RandomState(7)
        p_ok = rng.randint(1, 90, 4).astype(np.int32)
        r_ok = cl.submit(p_ok, 8)
        r_ttl = cl.submit(rng.randint(1, 90, 4).astype(np.int32), 4,
                          ttl_s=0.0)
        with pytest.raises(RequestExpired):
            cl.result(r_ttl, timeout=300)
        np.testing.assert_array_equal(cl.result(r_ok, timeout=300),
                                      ref(p_ok, 8))
        _check_refcounts(cl)
    finally:
        cl.close(timeout=60)


def wl_prefix_cow(params, cfg, ref):
    """Prefix-COW under scheduling: a cached chain is re-hit by a
    whole-input duplicate and a mid-page divergence — both exact, both
    COW, refcounts drain to zero."""
    from mxnet_tpu.serving import ServingCluster
    rng = np.random.RandomState(1)
    pa = rng.randint(1, 90, 16).astype(np.int32)     # 4 full pages
    pc = np.concatenate([pa[:14],
                         rng.randint(90, 120, 4).astype(np.int32)])
    cl = ServingCluster(params, cfg, replicas=1, num_slots=2,
                        page_size=4, prefill_chunk=8)
    try:
        ra = cl.submit(pa, 6)
        np.testing.assert_array_equal(cl.result(ra, timeout=300),
                                      ref(pa, 6))
        rb = cl.submit(pa, 6)          # whole-input match -> COW
        rc = cl.submit(pc, 6)          # diverges inside page 3 -> COW
        np.testing.assert_array_equal(cl.result(rb, timeout=300),
                                      ref(pa, 6))
        np.testing.assert_array_equal(cl.result(rc, timeout=300),
                                      ref(pc, 6))
        assert cl.replicas[0].engine.stats["cow_copies"] == 2
        assert cl.replicas[0].engine.stats["prefix_hit_tokens"] > 0
        _check_refcounts(cl)
    finally:
        cl.close(timeout=60)


def wl_tier_spill(params, cfg, ref):
    """Round 18: spill racing match racing preemption.  One replica,
    a pool tight enough that concurrent fillers force pressure spills
    of the cached chain to the host tier WHILE a duplicate prompt
    re-matches it (warm restore) and slot contention preempts
    (swap-out → install-exact resume).  Whatever the schedule
    interleaves — spill-then-match, match-then-spill, preempt in
    between — every output is exact and nothing leaks (pages, refs,
    or tier bytes for retired swaps)."""
    from mxnet_tpu.serving import ServingCluster
    from mxnet_tpu.serving import cluster as cluster_mod
    rng = np.random.RandomState(2)
    pa = rng.randint(1, 90, 16).astype(np.int32)     # 4 full pages
    fills = [rng.randint(1, 90, 12).astype(np.int32)
             for _ in range(3)]
    cl = ServingCluster(params, cfg, replicas=1, num_slots=2,
                        page_size=4, prefill_chunk=6,
                        pages_per_slot=6, num_pages=11,
                        tier_bytes=1 << 20)
    try:
        assert cl.replicas[0].engine.tier is not None
        ra = cl.submit(pa, 4)
        np.testing.assert_array_equal(cl.result(ra, timeout=300),
                                      ref(pa, 4))
        rids = []

        def filler():
            # pressure: each filler wants 4 pages of the 10-usable
            # pool while pa's 4-page chain sits cached refcount-0 —
            # the spills race the warm re-match below
            for f in fills:
                rids.append((cl.submit(f, 4), f, 4))

        th = cluster_mod.threading.Thread(target=filler,
                                          name="tier-filler")
        th.start()
        rb = cl.submit(pa, 4)            # re-match: hot, warm, or cold
        np.testing.assert_array_equal(cl.result(rb, timeout=300),
                                      ref(pa, 4))
        th.join(300)
        for rid, f, n in rids:
            np.testing.assert_array_equal(cl.result(rid, timeout=300),
                                          ref(f, n))
        _check_refcounts(cl)
        eng = cl.replicas[0].engine
        # retired/cancelled requests must not squat swap entries
        assert not any(isinstance(k, tuple) and k[0] == "swap"
                       for k in eng.tier._entries), \
            "stale swap entries after drain"
    finally:
        cl.close(timeout=60)


def wl_overlap_plan(params, cfg, ref):
    """Round 21: the overlap pipeline's planner thread racing steps,
    submits, and cancels.  One overlap=True replica — every step's
    plan is built by the planner under the engine lock while the
    previous step executes — with a submit burst arriving through a
    second thread and a cancel landing at whatever pipeline depth the
    schedule picks.  Every completed request must be exact (the
    carried-token reconciliation may never leak a speculatively
    dispatched token into a commit), the cancelled request must
    retire without leaking its pages, and the drain must leave zero
    refs — under EVERY schedule."""
    from mxnet_tpu.serving import ServingCluster
    from mxnet_tpu.serving import cluster as cluster_mod
    wl = _prompts_mixed(5)
    cl = ServingCluster(params, cfg, replicas=1, num_slots=2,
                        page_size=4, prefill_chunk=6, overlap=True)
    try:
        assert cl.replicas[0].engine.overlap
        first = [cl.submit(p, n) for p, n in wl[:2]]
        rids = []

        def submitter():
            for p, n in wl[2:]:
                rids.append(cl.submit(p, n))
            # cancel the second request at whatever point this
            # schedule has the pipeline: queued, planned, dispatched
            # speculatively, or already done — all must be clean
            cl.cancel(first[1])

        th = cluster_mod.threading.Thread(target=submitter,
                                          name="overlap-submitter")
        th.start()
        np.testing.assert_array_equal(
            cl.result(first[0], timeout=300), ref(*wl[0]))
        th.join(300)
        for rid, (p, n) in zip(rids, wl[2:]):
            np.testing.assert_array_equal(cl.result(rid, timeout=300),
                                          ref(p, n))
        cr = cl.requests[first[1]]
        if cr.state == "done":            # finish beat the cancel
            np.testing.assert_array_equal(
                cl.result(first[1], timeout=300), ref(*wl[1]))
        else:
            assert cr.state == "cancelled"
            # whatever the pipeline committed before the cancel must
            # prefix the oracle (a bogus carried token would show up
            # exactly here)
            exp = ref(*wl[1])[wl[1][0].size:]
            got = list(cr.committed)
            assert got == list(exp[:len(got)])
        eng = cl.replicas[0].engine
        assert eng.stats["overlap_steps"] > 0
        _check_refcounts(cl)
    finally:
        cl.close(timeout=60)


WORKLOADS = {
    "burst": wl_submit_burst,
    "failover": wl_failover,
    "drain": wl_drain_while_submitting,
    "ttl": wl_ttl_expiry,
    "cow": wl_prefix_cow,
    "tier": wl_tier_spill,
    "overlap": wl_overlap_plan,
}


# ---------------------------------------------------------------------------
# the >= 200-schedule sweep (acceptance criterion)
# ---------------------------------------------------------------------------
@pytest.mark.slow
@pytest.mark.parametrize("mode", MODES)
@pytest.mark.parametrize("name", sorted(WORKLOADS))
def test_schedule_sweep(env, name, mode):
    """20 seeds per (workload, strategy) cell — 200 schedules total
    across the parameterized matrix, every one clean."""
    params, cfg, ref = env
    wl = WORKLOADS[name]
    for seed in range(SEEDS):
        try:
            stats = run_schedule(lambda: wl(params, cfg, ref), seed,
                                 mode=mode)
        except BaseException as e:
            raise AssertionError(
                "schedule (workload=%s, mode=%s, seed=%d) failed: %r"
                % (name, mode, seed, e)) from e
        assert stats.yields > 0


# ---------------------------------------------------------------------------
# explorer properties
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_deterministic_per_seed(env):
    """Same (workload, strategy, seed) -> bit-identical trace hash;
    different seeds genuinely explore different interleavings."""
    params, cfg, ref = env
    hashes = {}
    for seed in range(6):
        a = run_schedule(lambda: wl_submit_burst(params, cfg, ref),
                         seed, mode="random")
        b = run_schedule(lambda: wl_submit_burst(params, cfg, ref),
                         seed, mode="random")
        assert a.trace_hash == b.trace_hash, "seed %d" % seed
        assert a.yields == b.yields and a.switches == b.switches
        hashes[seed] = a.trace_hash
    assert len(set(hashes.values())) >= 4, \
        "seeds barely explored: %r" % hashes
    assert a.switches > 0


@pytest.mark.slow
def test_preempt_mode_switches_more(env):
    """The targeted strategy forces a switch at every lock
    acquire/release — its switch/yield ratio must dominate random's."""
    params, cfg, ref = env
    r = run_schedule(lambda: wl_submit_burst(params, cfg, ref), 0,
                     mode="random")
    p = run_schedule(lambda: wl_submit_burst(params, cfg, ref), 0,
                     mode="preempt")
    assert p.switches / max(1, p.yields) > \
        r.switches / max(1, r.yields)


@pytest.mark.slow
def test_deadlock_detection_fires(env):
    """The explorer's verdict is trustworthy only if the detector
    provably fires: a two-lock opposite-order toy (forced across via
    events) must raise DeadlockError under EVERY seed."""
    def wl():
        from mxnet_tpu.serving import cluster as cm
        la, lb = cm.threading.Lock(), cm.threading.Lock()
        ea, eb = cm.threading.Event(), cm.threading.Event()

        def t1():
            with la:
                ea.set()
                eb.wait()
                with lb:
                    pass

        def t2():
            with lb:
                eb.set()
                ea.wait()
                with la:
                    pass

        th1 = cm.threading.Thread(target=t1, name="t1")
        th2 = cm.threading.Thread(target=t2, name="t2")
        th1.start()
        th2.start()
        th1.join()
        th2.join()

    for seed in range(3):
        with pytest.raises(DeadlockError):
            run_schedule(wl, seed, mode="random")


@pytest.mark.slow
def test_model_time_jumps(env):
    """Timed waits execute in model time: a full TTL workload (0.02 s
    idle waits, 0.25 s monitor periods) finishes in well under a
    second of wall clock, proving waits jump rather than sleep."""
    import time
    params, cfg, ref = env
    t0 = time.perf_counter()
    stats = run_schedule(lambda: wl_ttl_expiry(params, cfg, ref), 0,
                         mode="random")
    assert stats.model_time > 0
    assert time.perf_counter() - t0 < 30.0
