"""mx.operator.CustomOp tests (reference model:
``tests/python/unittest/test_operator.py::test_custom_op``)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


@mx.operator.register("sq")
class SquareProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return Square()


class Square(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        self.assign(out_data[0], req[0], in_data[0] * in_data[0])

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0], 2 * in_data[0] * out_grad[0])


@mx.operator.register("split2")
class Split2Prop(mx.operator.CustomOpProp):
    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["top", "bottom"]

    def infer_shape(self, in_shape):
        n = in_shape[0][0] // 2
        rest = list(in_shape[0][1:])
        return in_shape, [[n] + rest, [n] + rest], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return Split2()


class Split2(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        n = in_data[0].shape[0] // 2
        self.assign(out_data[0], req[0], in_data[0][:n])
        self.assign(out_data[1], req[1], in_data[0][n:])

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0],
                    nd.concat(out_grad[0], out_grad[1], dim=0))


def test_custom_forward():
    x = np.array([[1.0, -2.0], [3.0, 0.5]], dtype="float32")
    y = nd.Custom(nd.array(x), op_type="sq").asnumpy()
    assert np.allclose(y, x * x)


def test_custom_backward_is_custom():
    x = np.array([[1.0, -2.0], [3.0, 0.5]], dtype="float32")
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.Custom(a, op_type="sq")
        L = y.sum()
    L.backward()
    assert np.allclose(a.grad.asnumpy(), 2 * x)


def test_custom_multi_output():
    x = np.arange(8, dtype="float32").reshape(4, 2)
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        top, bot = nd.Custom(a, op_type="split2")
        L = (top * 2).sum() + (bot * 3).sum()
    assert top.shape == (2, 2)
    L.backward()
    expect = np.concatenate([np.full((2, 2), 2.0), np.full((2, 2), 3.0)])
    assert np.allclose(a.grad.asnumpy(), expect)


def test_custom_inside_hybridize():
    from mxnet_tpu.gluon import nn, HybridBlock

    class Net(HybridBlock):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.dense = nn.Dense(4)

        def hybrid_forward(self, F, x):
            return F.Custom(self.dense(x), op_type="sq")

    net = Net()
    net.initialize(mx.initializer.Xavier())
    x = nd.array(np.random.RandomState(0).rand(3, 5).astype("float32"))
    ref = net(x).asnumpy()
    net.hybridize()
    out = net(x).asnumpy()
    out2 = net(x).asnumpy()  # cached path
    assert np.allclose(ref, out, rtol=1e-5, atol=1e-6)
    assert np.allclose(ref, out2, rtol=1e-5, atol=1e-6)


def test_custom_registry_listing():
    names = mx.operator.get_all_registered_operators()
    assert "sq" in names and "split2" in names


def test_custom_unknown_type_errors():
    try:
        nd.Custom(nd.zeros((2, 2)), op_type="definitely_missing")
        raise SystemExit("should have raised")
    except mx.base.MXNetError as e:
        assert "not registered" in str(e)
