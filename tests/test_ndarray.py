"""NDArray tests (reference model: ``tests/python/unittest/test_ndarray.py``)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_creation():
    a = nd.zeros((2, 3))
    assert a.shape == (2, 3)
    assert a.dtype == np.float32
    b = nd.ones((4,), dtype="float16")
    assert b.dtype == np.float16
    c = nd.full((2, 2), 7.0)
    assert np.all(c.asnumpy() == 7.0)
    d = nd.arange(0, 10, 2)
    assert d.shape == (5,)
    e = nd.array([[1, 2], [3, 4]])
    assert e.shape == (2, 2)
    # float64 input downcast to float32 (MXNet default behavior)
    f = nd.array(np.ones((2, 2), dtype=np.float64))
    assert f.dtype == np.float32


def test_arith_operators():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[5.0, 6.0], [7.0, 8.0]])
    assert np.allclose((a + b).asnumpy(), [[6, 8], [10, 12]])
    assert np.allclose((a - b).asnumpy(), [[-4, -4], [-4, -4]])
    assert np.allclose((a * b).asnumpy(), [[5, 12], [21, 32]])
    assert np.allclose((b / a).asnumpy(), [[5, 3], [7 / 3, 2]])
    assert np.allclose((a + 1).asnumpy(), [[2, 3], [4, 5]])
    assert np.allclose((1 + a).asnumpy(), [[2, 3], [4, 5]])
    assert np.allclose((2 - a).asnumpy(), [[1, 0], [-1, -2]])
    assert np.allclose((2 / a).asnumpy(), [[2, 1], [2 / 3, 0.5]])
    assert np.allclose((a ** 2).asnumpy(), [[1, 4], [9, 16]])
    assert np.allclose((-a).asnumpy(), [[-1, -2], [-3, -4]])
    assert np.allclose(abs(-a).asnumpy(), a.asnumpy())


def test_scalar_dtype_preserved():
    a = nd.ones((2, 2), dtype="float16")
    assert (a + 1).dtype == np.float16
    assert (a * 0.5).dtype == np.float16


def test_inplace():
    a = nd.ones((2, 2))
    v0 = a.version
    a += 1
    assert np.all(a.asnumpy() == 2)
    assert a.version > v0
    a *= 3
    assert np.all(a.asnumpy() == 6)


def test_comparison():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([2.0, 2.0, 2.0])
    assert np.allclose((a > b).asnumpy(), [0, 0, 1])
    assert np.allclose((a == b).asnumpy(), [0, 1, 0])
    assert np.allclose((a <= 2).asnumpy(), [1, 1, 0])


def test_indexing():
    a = nd.array(np.arange(24).reshape(2, 3, 4).astype("float32"))
    npy = a.asnumpy()
    assert np.allclose(a[0].asnumpy(), npy[0])
    assert np.allclose(a[1, 2].asnumpy(), npy[1, 2])
    assert np.allclose(a[:, 1].asnumpy(), npy[:, 1])
    assert np.allclose(a[0, 1:3].asnumpy(), npy[0, 1:3])
    idx = nd.array([0, 1])
    assert np.allclose(a[idx].asnumpy(), npy[[0, 1]])


def test_setitem():
    a = nd.zeros((3, 3))
    a[1] = 5.0
    assert np.allclose(a.asnumpy()[1], 5.0)
    a[0, 0:2] = nd.array([1.0, 2.0])
    assert np.allclose(a.asnumpy()[0], [1, 2, 0])
    a[:] = 9.0
    assert np.all(a.asnumpy() == 9.0)


def test_reshape_special_codes():
    a = nd.zeros((2, 3, 4))
    assert a.reshape((-1,)).shape == (24,)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.reshape((-2,)).shape == (2, 3, 4)
    assert a.reshape((-3, 4)).shape == (6, 4)
    assert a.reshape((2, -4, 1, 3, 4)).shape == (2, 1, 3, 4)
    assert a.reshape(6, 4).shape == (6, 4)


def test_methods():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    assert float(a.sum().asscalar()) == 10.0
    assert float(a.mean().asscalar()) == 2.5
    assert float(a.max().asscalar()) == 4.0
    assert a.sum(axis=1).shape == (2,)
    assert a.T.shape == (2, 2)
    assert np.allclose(a.T.asnumpy(), a.asnumpy().T)
    assert a.expand_dims(0).shape == (1, 2, 2)
    assert a.flatten().shape == (2, 2)
    assert a.astype("int32").dtype == np.int32


def test_copy_context():
    a = nd.ones((2, 2), ctx=mx.cpu())
    b = a.copy()
    b += 1
    assert np.all(a.asnumpy() == 1)
    c = a.as_in_context(mx.cpu())
    assert c is a
    d = a.copyto(mx.cpu(0))
    assert np.all(d.asnumpy() == 1)


def test_save_load(tmp_path):
    fname = str(tmp_path / "test.params")
    d = {"w": nd.array([[1.0, 2.0]]), "b": nd.array([3.0])}
    nd.save(fname, d)
    loaded = nd.load(fname)
    assert set(loaded) == {"w", "b"}
    assert np.allclose(loaded["w"].asnumpy(), [[1, 2]])
    lst = [nd.ones((2,)), nd.zeros((3,))]
    nd.save(fname, lst)
    loaded = nd.load(fname)
    assert isinstance(loaded, list) and len(loaded) == 2


def test_concat_stack_split():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    c = nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)
    parts = nd.split(nd.arange(0, 12).reshape((2, 6)), num_outputs=3,
                     axis=1)
    assert len(parts) == 3 and parts[0].shape == (2, 2)


def test_wait_and_scalar():
    a = nd.ones((1,))
    a.wait_to_read()
    assert a.asscalar() == 1.0
    assert float(a) == 1.0
    assert int(a) == 1
    nd.waitall()


def test_iter_len():
    a = nd.array(np.arange(6).reshape(3, 2))
    assert len(a) == 3
    rows = list(a)
    assert len(rows) == 3 and rows[0].shape == (2,)
