"""API long-tail tests: CSVIter/LibSVMIter, SDMLLoss, modifier RNN
cells, Identity/Concatenate layers, metric aliases."""
import os
import tempfile

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
import mxnet_tpu.io as mio
from mxnet_tpu.gluon import loss as gloss, nn, rnn


def test_csv_iter():
    with tempfile.TemporaryDirectory() as d:
        dpath = os.path.join(d, "x.csv")
        lpath = os.path.join(d, "y.csv")
        X = np.arange(12).reshape(6, 2)
        np.savetxt(dpath, X, delimiter=",")
        np.savetxt(lpath, np.arange(6), delimiter=",")
        it = mio.CSVIter(data_csv=dpath, data_shape=(2,),
                         label_csv=lpath, batch_size=3)
        b = it.next()
        assert b.data[0].shape == (3, 2)
        assert np.allclose(b.data[0].asnumpy(), X[:3])
        it.reset()
        assert np.allclose(it.next().data[0].asnumpy(), X[:3])


def test_csv_iter_no_label():
    """label_csv=None → all-zero dummy label (reference iter_csv.cc:
    'if label_csv is not available, all labels will be returned as
    0'), so scripts doing batch.label[0] keep working."""
    with tempfile.TemporaryDirectory() as d:
        dpath = os.path.join(d, "x.csv")
        X = np.arange(12).reshape(6, 2)
        np.savetxt(dpath, X, delimiter=",")
        it = mio.CSVIter(data_csv=dpath, data_shape=(2,), batch_size=3)
        b = it.next()
        assert b.data[0].shape == (3, 2)
        assert b.label[0].shape == (3, 1)
        assert np.allclose(b.label[0].asnumpy(), 0)


def test_eager_jit_unhashable_pos_attr_falls_back():
    """A raw numpy array in positional attrs must fall back to the
    direct eager path, not crash the cache-key lookup (review
    regression, round 3)."""
    from mxnet_tpu import nd
    a = nd.array(np.array([[1., 2.], [3., 4.]], "float32"))
    out = nd.take(a, np.array([0, 1]))
    assert out.shape[0] == 2


def test_bleu_metric():
    """metric.BLEU vs the hand-computed Papineni example: hyp 'the cat
    is on the mat' / ref 'the cat sat on the mat' → smoothed BLEU-4 =
    (5/6 · 4/6 · 2/5 · 1/4)^(1/4) ≈ 0.48549, BP=1 (equal lengths);
    unsmoothed is 0 (no 4-gram match)."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    vocab = {w: i for i, w in enumerate(
        "the cat is sat on mat PAD".split())}

    def ids(s, pad_to=None):
        t = [vocab[w] for w in s.split()]
        if pad_to:
            t += [vocab["PAD"]] * (pad_to - len(t))
        return t

    hyp = ids("the cat is on the mat")
    ref = ids("the cat sat on the mat")
    m = mx.metric.create("bleu", smooth=True)
    m.update(nd.array([ref]), nd.array([hyp]))
    assert abs(m.get()[1] - 0.485498) < 1e-4, m.get()
    m0 = mx.metric.BLEU(smooth=False)
    m0.update(nd.array([ref]), nd.array([hyp]))
    assert m0.get()[1] == 0.0
    # perfect hypothesis → 1.0; pad stripping must not change it
    m1 = mx.metric.BLEU(pad_token=vocab["PAD"])
    m1.update(nd.array([ids("the cat sat on the mat", pad_to=9)]),
              nd.array([ids("the cat sat on the mat", pad_to=9)]))
    assert abs(m1.get()[1] - 1.0) < 1e-9
    # brevity penalty: hyp strictly shorter than ref is penalized below
    # its raw precision (here all n-gram precisions are 1)
    m2 = mx.metric.BLEU(max_n=2)
    m2.update(nd.array([ids("the cat sat on")]),
              nd.array([ids("the cat sat")]))
    import math
    assert abs(m2.get()[1] - math.exp(1 - 4 / 3)) < 1e-6
    # scores (batch, len, vocab) are argmax-decoded
    import numpy as _np
    sc = _np.zeros((1, len(hyp), len(vocab)), "float32")
    for i, t in enumerate(hyp):
        sc[0, i, t] = 1.0
    m3 = mx.metric.BLEU(smooth=True)
    m3.update(nd.array([ref]), nd.array(sc))
    assert abs(m3.get()[1] - 0.485498) < 1e-4


def test_libsvm_iter():
    with tempfile.TemporaryDirectory() as d:
        sv = os.path.join(d, "t.svm")
        with open(sv, "w") as f:
            f.write("1 0:1.5 3:2.0\n0 1:1.0\n1 2:0.5\n")
        it = mio.LibSVMIter(data_libsvm=sv, data_shape=(4,),
                            batch_size=2)
        b = it.next()
        assert b.data[0].stype == "csr"
        dense = b.data[0].tostype("default").asnumpy()
        assert dense[0, 0] == 1.5 and dense[0, 3] == 2.0
        assert dense[1, 1] == 1.0
        assert np.allclose(b.label[0].asnumpy().ravel(), [1, 0])
        b2 = it.next()           # padded final batch
        assert b2.pad == 1


def test_sdml_loss_matches_numpy_oracle():
    rng = np.random.RandomState(0)
    N, D = 4, 5
    a = rng.randn(N, D).astype("float32")
    b = rng.randn(N, D).astype("float32")
    sp = 0.3
    sd = gloss.SDMLLoss(smoothing_parameter=sp)
    got = sd(nd.array(a), nd.array(b)).asnumpy()

    # reference formula: KL(smoothed eye || log_softmax(-pairwise_l2^2))
    dist = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
    z = -dist
    logp = z - z.max(1, keepdims=True)
    logp = logp - np.log(np.exp(logp).sum(1, keepdims=True))
    gold = np.eye(N, dtype="float32")
    labels = gold * (1 - sp) + (1 - gold) * sp / (N - 1)
    kl = labels * (np.log(labels + 1e-12) - logp)
    expect = kl.mean(axis=1)
    assert np.allclose(got, expect, rtol=1e-4, atol=1e-5)

    # differentiable
    x1 = nd.array(a)
    x1.attach_grad()
    with autograd.record():
        L = sd(x1, nd.array(b)).mean()
    L.backward()
    assert np.isfinite(x1.grad.asnumpy()).all()
    # training signal: a gradient step on x1 toward b's pairing lowers
    # the loss
    x1b = nd.array(a - 0.05 * x1.grad.asnumpy())
    assert float(sd(x1b, nd.array(b)).mean().asnumpy()) <         float(L.asnumpy())


def test_variational_dropout_cell_mask_is_fixed_per_sequence():
    cell = rnn.VariationalDropoutCell(rnn.RNNCell(8), drop_outputs=0.5)
    cell.initialize()
    x = nd.array(np.ones((4, 6), "float32"))
    st = cell.begin_state(batch_size=4)
    with autograd.record():
        o1, st = cell(x, st)
        o2, st = cell(x, st)
    z1 = o1.asnumpy() == 0
    z2 = o2.asnumpy() == 0
    assert z1.any()              # dropout active in train mode
    assert (z1 == z2).all()      # same mask at every step
    cell.reset()
    # eval mode: no dropout
    o3, _ = cell(x, cell.begin_state(batch_size=4))
    assert not (o3.asnumpy() == 0).all()


def test_identity_concatenate_layers():
    net = nn.HybridConcatenate(axis=1)
    net.add(nn.Dense(3), nn.Identity(), nn.Dense(2))
    net.initialize(mx.initializer.Xavier())
    x = nd.array(np.ones((2, 4), "float32"))
    ref = net(x)
    assert ref.shape == (2, 9)
    net.hybridize()
    assert np.allclose(net(x).asnumpy(), ref.asnumpy(), rtol=1e-6)
    assert isinstance(nn.Block, type) and isinstance(nn.SymbolBlock, type)


def test_metric_legacy_aliases():
    m = mx.metric.create("torch")
    m.update([nd.array([0.0])], [nd.array([2.0, 4.0])])
    name, val = m.get()
    assert name == "torch" and np.isclose(val, 3.0)
    m2 = mx.metric.create("caffe")
    assert m2.get()[0] == "caffe"
