"""Convergence ("train") tests — small real trainings asserting final
accuracy (reference: tests/python/train/, SURVEY.md §4.4: catches
silent numeric bugs unit tests miss)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _blob_data(n, dim, classes, seed=0, scale=2.0):
    # class centers fixed across splits; `seed` varies only the noise
    centers = np.random.RandomState(1234).randn(
        classes, dim).astype("float32") * scale
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, n)
    x = centers[y] + rng.randn(n, dim).astype("float32")
    return x, y.astype("float32")


def _train(net, X, Y, epochs, batch, lr, hybridize=True):
    net.initialize(mx.initializer.Xavier())
    if hybridize:
        net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": lr, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    n = X.shape[0]
    for _ in range(epochs):
        for i in range(0, n, batch):
            data = nd.array(X[i:i + batch])
            label = nd.array(Y[i:i + batch])
            with autograd.record():
                loss = loss_fn(net(data), label)
            loss.backward()
            trainer.step(data.shape[0])
    return net


def _accuracy(net, X, Y):
    out = net(nd.array(X)).asnumpy()
    return (out.argmax(1) == Y).mean()


def test_mlp_convergence():
    """MLP on separable blobs must exceed 95% val accuracy
    (reference analog: train/test_mlp)."""
    X, Y = _blob_data(2048, 64, 10)
    Xv, Yv = _blob_data(512, 64, 10, seed=1)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(64, activation="relu"), nn.Dense(10))
    net = _train(net, X, Y, epochs=4, batch=64, lr=0.05)
    acc = _accuracy(net, Xv, Yv)
    assert acc > 0.95, acc


def test_conv_convergence():
    """Small CNN with BatchNorm on image-shaped blobs (reference
    analog: tests/python/train/test_conv.py)."""
    rng = np.random.RandomState(0)
    n, classes = 1024, 4
    y = rng.randint(0, classes, n)
    # class-dependent spatial frequency pattern
    base = np.zeros((n, 1, 16, 16), dtype="float32")
    xs = np.arange(16, dtype="float32")
    for c in range(classes):
        pat = np.outer(np.sin(xs * (c + 1) / 3), np.cos(xs * (c + 1) / 3))
        base[y == c, 0] = pat.astype("float32")
    X = base + rng.randn(n, 1, 16, 16).astype("float32") * 0.3
    Y = y.astype("float32")

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(8, 3, padding=1), nn.BatchNorm(),
                nn.Activation("relu"), nn.MaxPool2D(2),
                nn.Conv2D(16, 3, padding=1), nn.Activation("relu"),
                nn.GlobalAvgPool2D(), nn.Dense(classes))
    net = _train(net, X, Y, epochs=4, batch=64, lr=0.05)
    acc = _accuracy(net, X, Y)
    assert acc > 0.9, acc


def test_lm_perplexity_improves():
    """Tiny GPT perplexity on a periodic stream must approach 1
    (the Sockeye/NMT-style language-model convergence check)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt

    cfg = gpt.gpt_tiny(vocab_size=16, max_len=64, dropout=0.0,
                       use_flash=False, dtype="float32")
    init_state, step = gpt.make_train_step(cfg, learning_rate=1e-2)
    state = init_state(jax.random.PRNGKey(0))
    seq = jnp.tile(jnp.arange(1, 9, dtype=jnp.int32), 8)[None, :48]
    batch = {"tokens": jnp.tile(seq, (8, 1))}
    for i in range(60):
        state, loss = step(state, batch, jax.random.PRNGKey(i))
    ppl = float(np.exp(float(loss)))
    assert ppl < 1.1, ppl


# ---------------------------------------------------------------------------
# examples smoke (the runnable documentation must stay runnable)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("script,extra", [
    ("mnist_mlp.py", ["--epochs", "1"]),
    ("resnet_data_parallel.py", ["--iters", "2", "--image-size", "32",
                                 "--batch-size", "8"]),
    ("bert_pretrain.py", ["--steps", "2", "--seq-len", "64",
                          "--batch-size", "4", "--dp", "4", "--tp", "2"]),
    ("gpt_generate.py", ["--steps", "10"]),
    ("nmt_bucketing.py", ["--batches", "12", "--batch-size", "16"]),
    ("int8_quantization.py", ["--epochs", "3", "--calib-mode", "naive"]),
    ("ssd_detection.py", ["--epochs", "3", "--batch-size", "8"]),
])
def test_example_runs(script, extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script)] + extra,
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])


_RESNET_CACHE = {}


def _resnet_synthetic_data():
    rng = np.random.RandomState(0)
    n, classes = 256, 4
    y = rng.randint(0, classes, n)
    X = rng.randn(n, 3, 32, 32).astype("float32") * 0.3
    # class-dependent channel mean + quadrant pattern
    for c in range(classes):
        X[y == c, c % 3] += 2.0
        X[y == c, :, (c // 2) * 16:(c // 2) * 16 + 16,
          (c % 2) * 16:(c % 2) * 16 + 16] += 1.0
    return X, y, classes


def _trained_resnet18():
    """Train model-zoo resnet18 on the synthetic set once per session;
    the convergence gate and the INT8 accuracy gate share it."""
    if "net" in _RESNET_CACHE:
        return _RESNET_CACHE["net"], _RESNET_CACHE["traj"]
    from mxnet_tpu.parallel import DataParallelTrainer, make_mesh
    from mxnet_tpu.gluon.model_zoo import vision

    X, y, classes = _resnet_synthetic_data()
    Y = y.astype("float32")
    net = vision.resnet18_v1(classes=classes)
    net.initialize(mx.initializer.Xavier())
    import jax
    mesh = make_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = DataParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                  "sgd", {"learning_rate": 0.1,
                                          "momentum": 0.9}, mesh=mesh)
    batch = 32
    first = last = None
    for epoch in range(8):
        for i in range(0, len(X), batch):
            loss = trainer.step(nd.array(X[i:i + batch]),
                                nd.array(Y[i:i + batch]))
        v = float(loss.asnumpy())
        first = v if first is None else first
        last = v
    trainer.sync_back()
    _RESNET_CACHE["net"] = net
    _RESNET_CACHE["traj"] = (first, last)
    return net, (first, last)


def test_resnet_model_zoo_convergence():
    """The FLAGSHIP config's training path end-to-end: model-zoo
    resnet18 through DataParallelTrainer on synthetic structured
    images, fixed seed, accuracy threshold (verdict weak #6 — a proxy
    for the BASELINE.md ImageNet run, which has no dataset here)."""
    net, (first, last) = _trained_resnet18()
    assert last < first * 0.5, (first, last)
    X, y, _ = _resnet_synthetic_data()
    out = net(nd.array(X[:128])).asnumpy()
    acc = float((out.argmax(1) == y[:128]).mean())
    assert acc > 0.85, acc


def test_resnet18_int8_accuracy_within_1pct(tmp_path):
    """INT8 accuracy gate (round-3 verdict #7): PTQ-quantize the
    convergence tier's trained resnet18 and assert held-out top-1
    within 1 percentage point of fp32.

    Calibration is minmax ('naive'): the synthetic set's class signal
    lives in near-binary activation spikes, which KL/entropy calibration
    clips by design (measured: thresholds at 3-10% of range, top-1
    63%) — entropy mode trades tail fidelity for dense-region
    resolution and is only appropriate for smooth natural-image
    activation distributions.  quantized_dtype='auto' also exercises
    the uint8 activation path on the post-ReLU layers."""
    from mxnet_tpu.contrib.quantization import quantize_model
    from mxnet_tpu import model as model_mod

    net, _ = _trained_resnet18()
    X, y, classes = _resnet_synthetic_data()
    train_sl, held_sl = slice(0, 128), slice(128, 256)

    # export the served form (symbol + params), as a deployment would
    prefix = str(tmp_path / "resnet18")
    net(nd.array(X[:2]))          # ensure initialized/traced
    net.export(prefix)
    sym, arg_params, aux_params = model_mod.load_checkpoint(prefix, 0)

    def top1(s, args, aux, sl):
        arg = dict(args)
        arg["data"] = nd.array(X[sl])
        ex = s.bind(ctx=mx.cpu(), args=arg, aux_states=dict(aux))
        out = ex.forward(is_train=False)[0].asnumpy()
        return float((out.argmax(1) == y[sl]).mean())

    fp32_acc = top1(sym, arg_params, aux_params, held_sl)
    assert fp32_acc > 0.85, fp32_acc

    calib = mx.io.NDArrayIter(X[train_sl][:64], label=None,
                              batch_size=32)
    qsym, qarg, qaux = quantize_model(
        sym, arg_params, aux_params, calib_mode="naive",
        calib_data=calib, quantized_dtype="auto")
    int8_acc = top1(qsym, qarg, qaux, held_sl)
    assert int8_acc >= fp32_acc - 0.01, (fp32_acc, int8_acc)


def test_nmt_bucketing_convergence():
    """The Sockeye/NMT flagship config: BucketingModule over variable
    sequence lengths must exceed 80% token accuracy AND 0.8 corpus
    BLEU on the token-shift translation task with a fixed seed
    (BASELINE.md Sockeye row: BLEU parity metric; round-3 verdict
    #10)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "nmt_bucketing", os.path.join(REPO, "examples",
                                      "nmt_bucketing.py"))
    ex = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ex)

    # the example's own train() so the test gates the exact config the
    # runnable documentation uses
    acc, bleu, bm = ex.train(batches=90, batch_size=32, seed=7,
                             score_after=60)
    assert acc > 0.8, acc
    assert bleu > 0.8, bleu
    # all three buckets were actually exercised (shape-keyed jit cache)
    assert sorted(bm._buckets) == sorted(ex.BUCKETS)
