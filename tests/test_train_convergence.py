"""Convergence ("train") tests — small real trainings asserting final
accuracy (reference: tests/python/train/, SURVEY.md §4.4: catches
silent numeric bugs unit tests miss)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _blob_data(n, dim, classes, seed=0, scale=2.0):
    # class centers fixed across splits; `seed` varies only the noise
    centers = np.random.RandomState(1234).randn(
        classes, dim).astype("float32") * scale
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, n)
    x = centers[y] + rng.randn(n, dim).astype("float32")
    return x, y.astype("float32")


def _train(net, X, Y, epochs, batch, lr, hybridize=True):
    net.initialize(mx.initializer.Xavier())
    if hybridize:
        net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": lr, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    n = X.shape[0]
    for _ in range(epochs):
        for i in range(0, n, batch):
            data = nd.array(X[i:i + batch])
            label = nd.array(Y[i:i + batch])
            with autograd.record():
                loss = loss_fn(net(data), label)
            loss.backward()
            trainer.step(data.shape[0])
    return net


def _accuracy(net, X, Y):
    out = net(nd.array(X)).asnumpy()
    return (out.argmax(1) == Y).mean()


def test_mlp_convergence():
    """MLP on separable blobs must exceed 95% val accuracy
    (reference analog: train/test_mlp)."""
    X, Y = _blob_data(2048, 64, 10)
    Xv, Yv = _blob_data(512, 64, 10, seed=1)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(64, activation="relu"), nn.Dense(10))
    net = _train(net, X, Y, epochs=4, batch=64, lr=0.05)
    acc = _accuracy(net, Xv, Yv)
    assert acc > 0.95, acc


def test_conv_convergence():
    """Small CNN with BatchNorm on image-shaped blobs (reference
    analog: tests/python/train/test_conv.py)."""
    rng = np.random.RandomState(0)
    n, classes = 1024, 4
    y = rng.randint(0, classes, n)
    # class-dependent spatial frequency pattern
    base = np.zeros((n, 1, 16, 16), dtype="float32")
    xs = np.arange(16, dtype="float32")
    for c in range(classes):
        pat = np.outer(np.sin(xs * (c + 1) / 3), np.cos(xs * (c + 1) / 3))
        base[y == c, 0] = pat.astype("float32")
    X = base + rng.randn(n, 1, 16, 16).astype("float32") * 0.3
    Y = y.astype("float32")

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(8, 3, padding=1), nn.BatchNorm(),
                nn.Activation("relu"), nn.MaxPool2D(2),
                nn.Conv2D(16, 3, padding=1), nn.Activation("relu"),
                nn.GlobalAvgPool2D(), nn.Dense(classes))
    net = _train(net, X, Y, epochs=4, batch=64, lr=0.05)
    acc = _accuracy(net, X, Y)
    assert acc > 0.9, acc


def test_lm_perplexity_improves():
    """Tiny GPT perplexity on a periodic stream must approach 1
    (the Sockeye/NMT-style language-model convergence check)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt

    cfg = gpt.gpt_tiny(vocab_size=16, max_len=64, dropout=0.0,
                       use_flash=False, dtype="float32")
    init_state, step = gpt.make_train_step(cfg, learning_rate=1e-2)
    state = init_state(jax.random.PRNGKey(0))
    seq = jnp.tile(jnp.arange(1, 9, dtype=jnp.int32), 8)[None, :48]
    batch = {"tokens": jnp.tile(seq, (8, 1))}
    for i in range(60):
        state, loss = step(state, batch, jax.random.PRNGKey(i))
    ppl = float(np.exp(float(loss)))
    assert ppl < 1.1, ppl


# ---------------------------------------------------------------------------
# examples smoke (the runnable documentation must stay runnable)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("script,extra", [
    ("mnist_mlp.py", ["--epochs", "1"]),
    ("resnet_data_parallel.py", ["--iters", "2", "--image-size", "32",
                                 "--batch-size", "8"]),
    ("bert_pretrain.py", ["--steps", "2", "--seq-len", "64",
                          "--batch-size", "4", "--dp", "4", "--tp", "2"]),
    ("gpt_generate.py", ["--steps", "10"]),
    ("nmt_bucketing.py", ["--batches", "12", "--batch-size", "16"]),
    ("int8_quantization.py", ["--epochs", "3", "--calib-mode", "naive"]),
    ("ssd_detection.py", ["--epochs", "3", "--batch-size", "8"]),
])
def test_example_runs(script, extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script)] + extra,
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
