"""Tier-1 gate for the mxlint static-analysis suite (ISSUE 4).

Three layers of assertion:

1. **Live repo is clean** — every analyzer runs over the working tree
   and reports ZERO new violations (pragma- and baseline-filtered).
   This is the gate that keeps ABI drift, hot-loop host syncs, and
   locking-discipline regressions out of future PRs.
2. **Rules actually fire** — seeded-violation fixtures under
   ``tests/fixtures/mxlint/`` prove each rule detects its target
   exactly as often as seeded, and that the pragma / requires() /
   baseline suppression paths work.
3. **Coverage invariants** — every ``MX*`` function in ``c_api.h`` has
   an explicit argtypes/restype entry (zero baselined ABI findings —
   acceptance criterion), and the runner end-to-end stays under the
   tier-1 time budget (pure parsing, no native build, no jax tracing).
"""
import collections
import os
import time

import pytest

from tools.analysis import abi, jaxlint, native_lint
from tools.analysis.findings import (Finding, apply_pragmas,
                                     load_baseline, split_new)
from tools.analysis.runner import BINDINGS, HEADER, REPO_ROOT, run_all

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "mxlint")


def _rules(findings):
    return collections.Counter(f.rule for f in findings)


# ---------------------------------------------------------------------------
# 1. live repo
# ---------------------------------------------------------------------------
class TestLiveRepo:
    def test_runner_clean_and_fast(self):
        t0 = time.perf_counter()
        report = run_all()
        dt = time.perf_counter() - t0
        assert report["new"] == [], \
            "new static-analysis violations:\n" + "\n".join(
                "  %s" % f for f in report["new"])
        assert dt < 20.0, "analyzers must stay tier-1 cheap (%.1fs)" % dt

    def test_abi_zero_findings_even_baselined(self):
        """Acceptance criterion: zero *baselined* ABI findings — the
        argtypes table is complete and exact, not grandfathered."""
        fs = abi.check(os.path.join(REPO_ROOT, HEADER),
                       os.path.join(REPO_ROOT, BINDINGS),
                       HEADER, BINDINGS)
        assert fs == [], "\n".join(str(f) for f in fs)

    def test_abi_header_fully_covered(self):
        """Every header function bound; every binding in the header."""
        header = abi.parse_header(os.path.join(REPO_ROOT, HEADER))
        protos = abi.load_prototypes(os.path.join(REPO_ROOT, BINDINGS))
        assert set(header) == set(protos)
        # the header is the real one, not a stub
        assert len(header) >= 40
        for name in ("MXEnginePushAsync", "MXImageRecordLoaderCreateEx",
                     "MXShmData", "MXEngineStats"):
            assert name in header

    def test_prototypes_match_loaded_library(self):
        """The table applies cleanly to the shipped binary: every entry
        resolves to an exported symbol (catches header/table symbols
        the .so does not actually export)."""
        from mxnet_tpu import native
        if not native.available():
            pytest.skip("native library unavailable")
        missing = native._apply_prototypes(native.lib())
        assert missing == []

    def test_known_intentional_sync_is_pragmad(self):
        """The serving step's one intended device sync stays auditable:
        the pragma is present AND the linter honors it (removing the
        pragma makes the finding reappear)."""
        path = os.path.join(REPO_ROOT, "mxnet_tpu/serving/engine.py")
        src = open(path).read()
        assert "mxlint: allow(host-sync)" in src
        stripped = src.replace("# mxlint: allow(host-sync)", "#")
        fs = jaxlint.lint_source(stripped, "mxnet_tpu/serving/engine.py")
        assert _rules(fs)["host-sync"] >= 1


# ---------------------------------------------------------------------------
# 2. seeded fixtures — each rule fires, suppression works
# ---------------------------------------------------------------------------
class TestAbiFixtures:
    @pytest.fixture(scope="class")
    def findings(self):
        return abi.check(os.path.join(FIXTURES, "abi_fixture.h"),
                         os.path.join(FIXTURES,
                                      "abi_fixture_bindings.py"),
                         "abi_fixture.h", "abi_fixture_bindings.py")

    def test_each_rule_fires_exactly_once(self, findings):
        assert _rules(findings) == {
            "abi-argtypes": 1,      # MXFixDrift: POINTER(c_int)
            "abi-restype": 1,       # MXFixRet: c_int vs const char*
            "abi-argcount": 1,      # MXFixCount: 1 vs 2
            "abi-unbound": 1,       # MXFixUnbound
            "abi-missing-argtypes": 1,   # MXFixUnbound call site
            "abi-unknown-symbol": 2,     # MXFixPhantom + MXFixNowhere
        }

    def test_drift_details(self, findings):
        by_sym = {(f.rule, f.symbol) for f in findings}
        assert ("abi-argtypes", "MXFixDrift") in by_sym
        assert ("abi-restype", "MXFixRet") in by_sym
        assert ("abi-unbound", "MXFixUnbound") in by_sym

    def test_baseline_suppresses(self, findings):
        baseline = {f.key for f in findings if f.rule == "abi-argtypes"}
        new, old = split_new(findings, baseline)
        assert _rules(old) == {"abi-argtypes": 1}
        assert "abi-argtypes" not in _rules(new)

    def test_good_binding_clean(self):
        header = abi.parse_header(os.path.join(FIXTURES,
                                               "abi_fixture.h"))
        assert header["MXFixGood"] == ("int",
                                       ["const char*", "uint64_t*"])


class TestJaxFixtures:
    @pytest.fixture(scope="class")
    def findings(self):
        src = open(os.path.join(FIXTURES, "jax_fixture.py")).read()
        return jaxlint.lint_source(src, "jax_fixture.py",
                                   region_re=".*", clock=True)

    def test_counts(self, findings):
        assert _rules(findings) == {"host-sync": 2, "retrace": 2,
                                    "clock-mix": 1}

    def test_pragma_suppressed_twins(self, findings):
        # each rule seeded one extra pragma'd violation — none surface
        lines = {(f.rule, f.line) for f in findings}
        src = open(os.path.join(FIXTURES, "jax_fixture.py")).read()
        for i, text in enumerate(src.splitlines(), 1):
            if "suppressed twin" in text:
                assert not any(ln in (i, i + 1) for _, ln in lines)

    def test_jnp_asarray_rebind_keeps_taint(self):
        """jnp.asarray is host->device — rebinding through it must NOT
        launder the taint (code-review regression): the later float()
        is still a real device sync and must flag."""
        src = ("import jax.numpy as jnp\n"
               "def step(self, x):\n"
               "    out = self._step_fn(x)\n"
               "    y = jnp.asarray(out)\n"
               "    return float(y)\n")
        fs = jaxlint.lint_source(src, "m.py", region_re=".*",
                                 clock=False)
        assert _rules(fs) == {"host-sync": 1}
        # while a genuine host materialization DOES clear it
        src_np = src.replace("jnp.asarray", "np.asarray")
        fs_np = jaxlint.lint_source(src_np, "m.py", region_re=".*",
                                    clock=False)
        assert _rules(fs_np) == {"host-sync": 1}  # the np.asarray line
        assert fs_np[0].line == 4

    def test_taint_not_overbroad(self, findings):
        # np.asarray of an untainted arg and perf_counter never flag
        msgs = [f for f in findings if f.line == 0]
        assert msgs == []
        src_lines = open(os.path.join(FIXTURES,
                                      "jax_fixture.py")).read().splitlines()
        for f in findings:
            assert "must NOT fire" not in src_lines[f.line - 1]


class TestNativeFixtures:
    CFG = {
        "order": {"alpha_mu_": 0, "beta_mu_": 1},
        "guarded": {"member": {"count": "alpha_mu_"},
                    "self": {"shared_": "alpha_mu_"}},
        "cv_preds": {"quit_": "beta_mu_"},
    }

    @pytest.fixture(scope="class")
    def findings(self):
        return native_lint.lint_file(
            os.path.join(FIXTURES, "native_fixture.cc"),
            "native_fixture.cc", config=self.CFG)

    def test_counts(self, findings):
        assert _rules(findings) == {
            "lock-order": 2,          # direct + transitive
            "guarded-field": 2,       # box->count + shared_ (one
                                      # pragma'd twin suppressed)
            "cv-wait-predicate": 1,
            "cv-pred-unlocked": 1,
        }

    def test_direct_and_transitive_lock_order(self, findings):
        msgs = [f.message for f in findings if f.rule == "lock-order"]
        assert any("holding beta_mu_" in m for m in msgs)
        assert any("call to AlphaOnly()" in m for m in msgs)

    def test_requires_annotation_honored(self, findings):
        # GuardedPrecondition's body would fire without requires()
        src = open(os.path.join(FIXTURES, "native_fixture.cc")).read()
        bad = src.replace("mxlint: requires(alpha_mu_)", "fixture:")
        import tempfile
        with tempfile.NamedTemporaryFile("w", suffix=".cc",
                                         delete=False) as tf:
            tf.write(bad)
        try:
            fs = native_lint.lint_file(tf.name, "native_fixture.cc",
                                       config=self.CFG)
            assert _rules(fs)["guarded-field"] == \
                _rules(findings)["guarded-field"] + 1
        finally:
            os.unlink(tf.name)

    def test_live_engine_discipline_is_machine_checked(self):
        """Deleting the engine.cc ~Engine lock reintroduces the
        missed-wakeup finding — the pass genuinely guards the fix
        shipped in this PR."""
        path = os.path.join(REPO_ROOT, "native/src/engine.cc")
        src = open(path).read()
        assert "std::lock_guard<std::mutex> lk(pool_mu_);\n" \
               "    stop_.store(true);" in src
        broken = src.replace(
            "    std::lock_guard<std::mutex> lk(pool_mu_);\n"
            "    stop_.store(true);", "    stop_.store(true);", 1)
        import tempfile
        with tempfile.NamedTemporaryFile("w", suffix=".cc",
                                         delete=False) as tf:
            tf.write(broken)
        try:
            fs = native_lint.lint_file(
                tf.name, "engine.cc",
                config=native_lint.CONFIG["engine.cc"])
            assert _rules(fs)["cv-pred-unlocked"] >= 1
        finally:
            os.unlink(tf.name)


# ---------------------------------------------------------------------------
# 3. infra behaviors
# ---------------------------------------------------------------------------
class TestInfra:
    def test_pragma_comment_block_above(self):
        src = ("x = 1\n"
               "# mxlint: allow(host-sync) -- reason\n"
               "# second comment line\n"
               "y = np.asarray(out)\n")
        f = Finding("jax", "host-sync", "m.py", 4, "np.asarray", "m")
        assert apply_pragmas([f], src) == []

    def test_pragma_wrong_rule_does_not_suppress(self):
        src = "# mxlint: allow(retrace)\ny = np.asarray(out)\n"
        f = Finding("jax", "host-sync", "m.py", 2, "np.asarray", "m")
        assert apply_pragmas([f], src) == [f]

    def test_baseline_roundtrip(self, tmp_path):
        p = tmp_path / "baseline.json"
        p.write_text('{"version": 1, "allow": [{"rule": "r", '
                     '"path": "p.py", "symbol": "s"}, "a:b:c"]}')
        keys = load_baseline(str(p))
        assert keys == {"r:p.py:s", "a:b:c"}

    def test_checked_in_baseline_is_empty(self):
        """The suite ships with zero accepted debt — anything new must
        be fixed or explicitly pragma'd with a justification."""
        keys = load_baseline(os.path.join(
            REPO_ROOT, "tools", "analysis", "baseline.json"))
        assert keys == set()
