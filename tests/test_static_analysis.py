"""Tier-1 gate for the mxlint static-analysis suite (ISSUE 4/7/8).

Three layers of assertion:

1. **Live repo is clean** — every analyzer runs over the working tree
   and reports ZERO new violations (pragma- and baseline-filtered).
   This is the gate that keeps ABI drift, hot-loop host syncs,
   locking-discipline regressions, dropped step-program donation, and
   HBM-footprint creep out of future PRs.
2. **Rules actually fire** — seeded-violation fixtures under
   ``tests/fixtures/mxlint/`` prove each rule detects its target
   exactly as often as seeded, and that the pragma / requires() /
   baseline suppression paths work.
3. **Coverage invariants** — every ``MX*`` function in ``c_api.h`` has
   an explicit argtypes/restype entry (zero baselined ABI findings —
   acceptance criterion), graphlint's budget manifest and sharding
   audit stay current, and the runner end-to-end stays under the
   tier-1 time budget (parsing + abstract tracing only: no native
   build, no compilation, no program execution).
"""
import collections
import importlib.util
import json
import os
import time

import pytest

from tools.analysis import (abi, asynclint, envlint, graphlint,
                            jaxlint, native_lint, protolint,
                            pylocklint)
from tools.analysis.findings import (Finding, apply_pragmas,
                                     load_baseline, split_new)
from tools.analysis.runner import (BINDINGS, HEADER, REPO_ROOT,
                                   changed_files, findings_json,
                                   run_all)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "mxlint")


def _rules(findings):
    return collections.Counter(f.rule for f in findings)


def _load_graph_fixture():
    path = os.path.join(FIXTURES, "graph_fixture.py")
    spec = importlib.util.spec_from_file_location("graph_fixture", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# 1. live repo
# ---------------------------------------------------------------------------
class TestLiveRepo:
    def test_runner_clean_and_fast(self):
        t0 = time.perf_counter()
        report = run_all()
        dt = time.perf_counter() - t0
        assert report["new"] == [], \
            "new static-analysis violations:\n" + "\n".join(
                "  %s" % f for f in report["new"])
        assert dt < 20.0, "analyzers must stay tier-1 cheap (%.1fs)" % dt

    def test_abi_zero_findings_even_baselined(self):
        """Acceptance criterion: zero *baselined* ABI findings — the
        argtypes table is complete and exact, not grandfathered."""
        fs = abi.check(os.path.join(REPO_ROOT, HEADER),
                       os.path.join(REPO_ROOT, BINDINGS),
                       HEADER, BINDINGS)
        assert fs == [], "\n".join(str(f) for f in fs)

    def test_abi_header_fully_covered(self):
        """Every header function bound; every binding in the header."""
        header = abi.parse_header(os.path.join(REPO_ROOT, HEADER))
        protos = abi.load_prototypes(os.path.join(REPO_ROOT, BINDINGS))
        assert set(header) == set(protos)
        # the header is the real one, not a stub
        assert len(header) >= 40
        for name in ("MXEnginePushAsync", "MXImageRecordLoaderCreateEx",
                     "MXShmData", "MXEngineStats"):
            assert name in header

    def test_prototypes_match_loaded_library(self):
        """The table applies cleanly to the shipped binary: every entry
        resolves to an exported symbol (catches header/table symbols
        the .so does not actually export)."""
        from mxnet_tpu import native
        if not native.available():
            pytest.skip("native library unavailable")
        missing = native._apply_prototypes(native.lib())
        assert missing == []

    def test_pylocklint_zero_findings_even_baselined(self):
        """ISSUE 7 acceptance criterion: pylocklint reports ZERO
        findings with an EMPTY baseline over serving/, obs/, io/ —
        nothing grandfathered."""
        fs = pylocklint.run(REPO_ROOT)
        assert fs == [], "\n".join(str(f) for f in fs)

    def test_pylocklint_guards_the_admit_ref_leak_fix(self):
        """Deleting the round-12 try/except in ServingEngine._admit
        reintroduces the py-ref-leak finding — the pass genuinely
        guards the fix shipped in this PR (PR-4 pattern)."""
        path = os.path.join(REPO_ROOT, "mxnet_tpu/serving/engine.py")
        src = open(path).read()
        guarded = ("            except BaseException:\n")
        assert guarded in src
        # strip the handler body's release (keep it syntactically
        # valid: the handler just re-raises)
        broken = src.replace(
            "                if entries:\n"
            "                    self.prefix.release(entries)\n"
            "                raise\n",
            "                raise\n", 1)
        assert broken != src
        fs = pylocklint.lint_source(broken,
                                    "mxnet_tpu/serving/engine.py")
        assert collections.Counter(
            f.rule for f in fs)["py-ref-leak"] >= 1

    def test_changed_only_scopes_the_run(self):
        """--changed-only reports only changed files (the full parse
        still happens, so this is a reporting scope, not a soundness
        hole in tier-1 — which always runs full)."""
        cf = changed_files(REPO_ROOT)
        if cf is None:
            pytest.skip("git unavailable")
        report = run_all(changed_only=True)
        assert report["changed"] is not None
        allowed = set(report["changed"])
        for f in report["findings"]:
            assert f.path in allowed or f.path in (HEADER, BINDINGS)

    def test_known_intentional_sync_is_pragmad(self):
        """The serving engine's intended device syncs stay auditable.
        Round 21 split the step into dispatch + drain, so there are
        now TWO pragma'd readback sites — the serial step's inline
        ``np.asarray`` and the overlap path's deferred ``_drain`` —
        and the linter honors both (stripping the pragmas makes BOTH
        findings reappear)."""
        path = os.path.join(REPO_ROOT, "mxnet_tpu/serving/engine.py")
        src = open(path).read()
        assert src.count("mxlint: allow(host-sync)") >= 2
        stripped = src.replace("# mxlint: allow(host-sync)", "#")
        fs = jaxlint.lint_source(stripped, "mxnet_tpu/serving/engine.py")
        assert _rules(fs)["host-sync"] >= 2


# ---------------------------------------------------------------------------
# 2. seeded fixtures — each rule fires, suppression works
# ---------------------------------------------------------------------------
class TestAbiFixtures:
    @pytest.fixture(scope="class")
    def findings(self):
        return abi.check(os.path.join(FIXTURES, "abi_fixture.h"),
                         os.path.join(FIXTURES,
                                      "abi_fixture_bindings.py"),
                         "abi_fixture.h", "abi_fixture_bindings.py")

    def test_each_rule_fires_exactly_once(self, findings):
        assert _rules(findings) == {
            "abi-argtypes": 1,      # MXFixDrift: POINTER(c_int)
            "abi-restype": 1,       # MXFixRet: c_int vs const char*
            "abi-argcount": 1,      # MXFixCount: 1 vs 2
            "abi-unbound": 1,       # MXFixUnbound
            "abi-missing-argtypes": 1,   # MXFixUnbound call site
            "abi-unknown-symbol": 2,     # MXFixPhantom + MXFixNowhere
        }

    def test_drift_details(self, findings):
        by_sym = {(f.rule, f.symbol) for f in findings}
        assert ("abi-argtypes", "MXFixDrift") in by_sym
        assert ("abi-restype", "MXFixRet") in by_sym
        assert ("abi-unbound", "MXFixUnbound") in by_sym

    def test_baseline_suppresses(self, findings):
        baseline = {f.key for f in findings if f.rule == "abi-argtypes"}
        new, old = split_new(findings, baseline)
        assert _rules(old) == {"abi-argtypes": 1}
        assert "abi-argtypes" not in _rules(new)

    def test_good_binding_clean(self):
        header = abi.parse_header(os.path.join(FIXTURES,
                                               "abi_fixture.h"))
        assert header["MXFixGood"] == ("int",
                                       ["const char*", "uint64_t*"])


class TestJaxFixtures:
    @pytest.fixture(scope="class")
    def findings(self):
        src = open(os.path.join(FIXTURES, "jax_fixture.py")).read()
        return jaxlint.lint_source(src, "jax_fixture.py",
                                   region_re=".*", clock=True)

    def test_counts(self, findings):
        assert _rules(findings) == {"host-sync": 2, "retrace": 2,
                                    "clock-mix": 1}

    def test_pragma_suppressed_twins(self, findings):
        # each rule seeded one extra pragma'd violation — none surface
        lines = {(f.rule, f.line) for f in findings}
        src = open(os.path.join(FIXTURES, "jax_fixture.py")).read()
        for i, text in enumerate(src.splitlines(), 1):
            if "suppressed twin" in text:
                assert not any(ln in (i, i + 1) for _, ln in lines)

    def test_jnp_asarray_rebind_keeps_taint(self):
        """jnp.asarray is host->device — rebinding through it must NOT
        launder the taint (code-review regression): the later float()
        is still a real device sync and must flag."""
        src = ("import jax.numpy as jnp\n"
               "def step(self, x):\n"
               "    out = self._step_fn(x)\n"
               "    y = jnp.asarray(out)\n"
               "    return float(y)\n")
        fs = jaxlint.lint_source(src, "m.py", region_re=".*",
                                 clock=False)
        assert _rules(fs) == {"host-sync": 1}
        # while a genuine host materialization DOES clear it
        src_np = src.replace("jnp.asarray", "np.asarray")
        fs_np = jaxlint.lint_source(src_np, "m.py", region_re=".*",
                                    clock=False)
        assert _rules(fs_np) == {"host-sync": 1}  # the np.asarray line
        assert fs_np[0].line == 4

    def test_taint_not_overbroad(self, findings):
        # np.asarray of an untainted arg and perf_counter never flag
        msgs = [f for f in findings if f.line == 0]
        assert msgs == []
        src_lines = open(os.path.join(FIXTURES,
                                      "jax_fixture.py")).read().splitlines()
        for f in findings:
            assert "must NOT fire" not in src_lines[f.line - 1]


class TestNativeFixtures:
    CFG = {
        "order": {"alpha_mu_": 0, "beta_mu_": 1},
        "guarded": {"member": {"count": "alpha_mu_"},
                    "self": {"shared_": "alpha_mu_"}},
        "cv_preds": {"quit_": "beta_mu_"},
    }

    @pytest.fixture(scope="class")
    def findings(self):
        return native_lint.lint_file(
            os.path.join(FIXTURES, "native_fixture.cc"),
            "native_fixture.cc", config=self.CFG)

    def test_counts(self, findings):
        assert _rules(findings) == {
            "lock-order": 2,          # direct + transitive
            "guarded-field": 2,       # box->count + shared_ (one
                                      # pragma'd twin suppressed)
            "cv-wait-predicate": 1,
            "cv-pred-unlocked": 1,
        }

    def test_direct_and_transitive_lock_order(self, findings):
        msgs = [f.message for f in findings if f.rule == "lock-order"]
        assert any("holding beta_mu_" in m for m in msgs)
        assert any("call to AlphaOnly()" in m for m in msgs)

    def test_requires_annotation_honored(self, findings):
        # GuardedPrecondition's body would fire without requires()
        src = open(os.path.join(FIXTURES, "native_fixture.cc")).read()
        bad = src.replace("mxlint: requires(alpha_mu_)", "fixture:")
        import tempfile
        with tempfile.NamedTemporaryFile("w", suffix=".cc",
                                         delete=False) as tf:
            tf.write(bad)
        try:
            fs = native_lint.lint_file(tf.name, "native_fixture.cc",
                                       config=self.CFG)
            assert _rules(fs)["guarded-field"] == \
                _rules(findings)["guarded-field"] + 1
        finally:
            os.unlink(tf.name)

    def test_live_engine_discipline_is_machine_checked(self):
        """Deleting the engine.cc ~Engine lock reintroduces the
        missed-wakeup finding — the pass genuinely guards the fix
        shipped in this PR."""
        path = os.path.join(REPO_ROOT, "native/src/engine.cc")
        src = open(path).read()
        assert "std::lock_guard<std::mutex> lk(pool_mu_);\n" \
               "    stop_.store(true);" in src
        broken = src.replace(
            "    std::lock_guard<std::mutex> lk(pool_mu_);\n"
            "    stop_.store(true);", "    stop_.store(true);", 1)
        import tempfile
        with tempfile.NamedTemporaryFile("w", suffix=".cc",
                                         delete=False) as tf:
            tf.write(broken)
        try:
            fs = native_lint.lint_file(
                tf.name, "engine.cc",
                config=native_lint.CONFIG["engine.cc"])
            assert _rules(fs)["cv-pred-unlocked"] >= 1
        finally:
            os.unlink(tf.name)


class TestPylockFixtures:
    """Every pylocklint rule fires exactly as seeded in
    fixtures/mxlint/pylock_fixture.py, pragma twins stay suppressed,
    and the baseline suppresses by key (ISSUE 7 satellite)."""

    @pytest.fixture(scope="class")
    def findings(self):
        src = open(os.path.join(FIXTURES, "pylock_fixture.py")).read()
        return pylocklint.lint_source(src, "pylock_fixture.py")

    def test_counts(self, findings):
        assert _rules(findings) == {
            "py-guarded-field": 1,        # Guarded.bad
            "py-lock-order": 2,           # cycle + transitive re-acq
            "py-cv-wait-predicate": 1,    # CV.bare_wait
            "py-notify-unlocked": 1,      # CV.bad_notify
            "py-blocking-under-lock": 2,  # direct q.get + transitive
            "py-ref-leak": 3,             # return + exception + .refs
        }

    def test_lock_order_variants(self, findings):
        msgs = [f.message for f in findings
                if f.rule == "py-lock-order"]
        assert any("closes a lock-order cycle" in m for m in msgs)
        assert any("may re-acquire held non-reentrant" in m
                   for m in msgs)

    def test_blocking_variants(self, findings):
        msgs = [f.message for f in findings
                if f.rule == "py-blocking-under-lock"]
        assert any("queue.get" in m for m in msgs)
        assert any("call to _slow()" in m for m in msgs)

    def test_ref_leak_variants(self, findings):
        msgs = [f.message for f in findings if f.rule == "py-ref-leak"]
        assert any("exit without releasing" in m for m in msgs)
        assert any("exception edge leaks" in m for m in msgs)
        assert any("outside" in m for m in msgs)

    def test_pragma_suppressed_twins(self, findings):
        src = open(os.path.join(FIXTURES, "pylock_fixture.py")).read()
        lines = {(f.rule, f.line) for f in findings}
        for i, text in enumerate(src.splitlines(), 1):
            if "suppressed twin" in text:
                assert not any(ln in (i, i + 1, i + 2)
                               for _, ln in lines), \
                    "twin at line %d surfaced" % i

    def test_locked_convention_and_clean_shapes(self, findings):
        """helper_locked / guarded_exception / ok_escape / good_wait /
        good_notify / fine seeded NO findings."""
        import ast
        src = open(os.path.join(FIXTURES, "pylock_fixture.py")).read()
        spans = {}
        for node in ast.walk(ast.parse(src)):
            if isinstance(node, ast.FunctionDef):
                spans[node.name] = (node.lineno, node.end_lineno)
        clean = {"helper_locked", "guarded_exception", "ok_escape",
                 "good_wait", "good_notify", "fine"}
        for f in findings:
            for name in clean:
                lo, hi = spans[name]
                assert not (lo <= f.line <= hi), \
                    "%s seeded clean but got %s" % (name, f)

    def test_baseline_suppresses(self, findings):
        baseline = {f.key for f in findings
                    if f.rule == "py-guarded-field"}
        new, old = split_new(findings, baseline)
        assert _rules(old) == {"py-guarded-field": 1}
        assert "py-guarded-field" not in _rules(new)


class TestPylockAutoscalerCoverage:
    """ISSUE 11 satellite: pylocklint's guarded-field / lock-order
    inference reaches the round-16 ``serving/autoscaler.py`` (the
    live module's cleanliness is pinned by
    ``test_pylocklint_zero_findings_even_baselined``, which now scans
    it — these prove a violation planted THERE would fire, i.e. the
    coverage is real, not vacuous)."""

    def test_planted_guarded_field_fires(self):
        src = ("import threading\n"
               "class Autoscaler:\n"
               "    def __init__(self):\n"
               "        self._mu = threading.Lock()\n"
               "        self.target = 0\n"
               "    def tick(self):\n"
               "        with self._mu:\n"
               "            self.target = 1\n"
               "    def _loop(self):\n"
               "        self.target = 2\n")
        fs = pylocklint.lint_source(
            src, "mxnet_tpu/serving/autoscaler.py")
        assert _rules(fs) == {"py-guarded-field": 1}

    def test_planted_lock_order_cycle_fires(self):
        src = ("import threading\n"
               "class Autoscaler:\n"
               "    def __init__(self):\n"
               "        self._mu = threading.Lock()\n"
               "        self._scale_mu = threading.Lock()\n"
               "    def tick(self):\n"
               "        with self._mu:\n"
               "            with self._scale_mu:\n"
               "                pass\n"
               "    def _loop(self):\n"
               "        with self._scale_mu:\n"
               "            with self._mu:\n"
               "                pass\n")
        fs = pylocklint.lint_source(
            src, "mxnet_tpu/serving/autoscaler.py")
        assert "py-lock-order" in _rules(fs)

    def test_planted_blocking_under_lock_fires(self):
        # the autoscaler's real hazard shape: actuation (a blocking
        # drain) while holding a lock
        src = ("import threading, time\n"
               "class Autoscaler:\n"
               "    def __init__(self):\n"
               "        self._mu = threading.Lock()\n"
               "    def tick(self):\n"
               "        with self._mu:\n"
               "            time.sleep(1.0)\n")
        fs = pylocklint.lint_source(
            src, "mxnet_tpu/serving/autoscaler.py")
        assert _rules(fs) == {"py-blocking-under-lock": 1}


class TestPylockTierCoverage:
    """ISSUE 13 satellite: pylocklint's auto-scope reaches the
    round-18 ``serving/tier_store.py`` (zero findings on the live
    module is pinned by the repo-wide scan; these prove a violation
    planted THERE would fire — the coverage is real, not vacuous.
    The live store is deliberately lock-free on the owning engine's
    thread, so the plants are the shapes a future 'make it shared'
    edit would introduce)."""

    def test_planted_guarded_field_fires(self):
        src = ("import threading\n"
               "class HostTierStore:\n"
               "    def __init__(self):\n"
               "        self._mu = threading.Lock()\n"
               "        self.bytes_held = 0\n"
               "    def put(self, n):\n"
               "        with self._mu:\n"
               "            self.bytes_held = n\n"
               "    def pop(self):\n"
               "        self.bytes_held = 0\n")
        fs = pylocklint.lint_source(
            src, "mxnet_tpu/serving/tier_store.py")
        assert _rules(fs) == {"py-guarded-field": 1}

    def test_planted_blocking_under_lock_fires(self):
        # the tier's real future hazard shape: a device transfer
        # (blocking) while holding a store lock would serialize every
        # spill behind every restore
        src = ("import threading, time\n"
               "class HostTierStore:\n"
               "    def __init__(self):\n"
               "        self._mu = threading.Lock()\n"
               "    def put(self, key):\n"
               "        with self._mu:\n"
               "            time.sleep(0.1)\n")
        fs = pylocklint.lint_source(
            src, "mxnet_tpu/serving/tier_store.py")
        assert _rules(fs) == {"py-blocking-under-lock": 1}


class TestPylockOverlapCoverage:
    """Round 21: pylocklint genuinely covers the double-buffered
    planner handoff in ``serving/engine.py`` (the live module's
    cleanliness is pinned by the repo-wide zero-findings scan; these
    prove the violations the overlap pipeline COULD regress into
    would fire there — coverage is real, not vacuous)."""

    def test_planted_plan_state_unguarded_write_fires(self):
        # the handoff hazard: the planner publishes plan state under
        # the engine lock, so a step-side write that skips the lock
        # is exactly the torn-handoff bug the discipline prevents
        src = ("import threading\n"
               "class ServingEngine:\n"
               "    def __init__(self):\n"
               "        self._mu = threading.Lock()\n"
               "        self._buf_idx = 0\n"
               "    def _build_plan(self):\n"
               "        with self._mu:\n"
               "            self._buf_idx ^= 1\n"
               "    def _reset(self):\n"
               "        with self._mu:\n"
               "            self._buf_idx = 0\n"
               "    def step(self):\n"
               "        self._buf_idx ^= 1\n")
        fs = pylocklint.lint_source(
            src, "mxnet_tpu/serving/engine.py")
        assert _rules(fs) == {"py-guarded-field": 1}

    def test_planted_ready_wait_under_lock_fires(self):
        # the deadlock shape the handoff must never regress into:
        # step() waiting for the planner's ready event WHILE holding
        # the lock the planner needs to build the plan
        src = ("import threading\n"
               "class ServingEngine:\n"
               "    def __init__(self):\n"
               "        self._mu = threading.Lock()\n"
               "        self._plan_ready = threading.Event()\n"
               "    def _take_plan(self):\n"
               "        with self._mu:\n"
               "            self._plan_ready.wait()\n")
        fs = pylocklint.lint_source(
            src, "mxnet_tpu/serving/engine.py")
        assert _rules(fs) == {"py-blocking-under-lock": 1}

    def test_planted_dispatch_under_lock_fires(self):
        # dispatching the jitted step while holding the engine lock
        # would stall submit/cancel behind device time — the exact
        # latency the overlap exists to hide
        src = ("import threading\n"
               "class ServingEngine:\n"
               "    def __init__(self):\n"
               "        self._mu = threading.Lock()\n"
               "    def _dispatch(self, plan):\n"
               "        with self._mu:\n"
               "            self._step_fn(plan)\n")
        fs = pylocklint.lint_source(
            src, "mxnet_tpu/serving/engine.py")
        assert _rules(fs) == {"py-blocking-under-lock": 1}

    def test_live_requires_pragmas_are_load_bearing(self):
        """Stripping the ``requires(ServingEngine._mu)`` pragmas from
        the live engine makes guarded-field findings appear: the
        planner/commit helpers really do touch lock-guarded state,
        and the pragmas are the proof obligation, not decoration."""
        path = os.path.join(REPO_ROOT, "mxnet_tpu/serving/engine.py")
        src = open(path).read()
        assert src.count("mxlint: requires(ServingEngine._mu)") >= 4
        stripped = src.replace(
            "# mxlint: requires(ServingEngine._mu)", "#")
        fs = pylocklint.lint_source(
            stripped, "mxnet_tpu/serving/engine.py")
        assert _rules(fs).get("py-guarded-field", 0) >= 1

    def test_planted_lock_order_cycle_fires(self):
        src = ("import threading\n"
               "class HostTierStore:\n"
               "    def __init__(self):\n"
               "        self._mu = threading.Lock()\n"
               "        self._lru_mu = threading.Lock()\n"
               "    def put(self):\n"
               "        with self._mu:\n"
               "            with self._lru_mu:\n"
               "                pass\n"
               "    def evict(self):\n"
               "        with self._lru_mu:\n"
               "            with self._mu:\n"
               "                pass\n")
        fs = pylocklint.lint_source(
            src, "mxnet_tpu/serving/tier_store.py")
        assert "py-lock-order" in _rules(fs)


class TestPylockKVStoreCoverage:
    """ISSUE 14 satellite: pylocklint's auto-scope reaches the
    round-19 ``mxnet_tpu/kvstore`` package (the ICI-allreduce store's
    telemetry counters are written under ``self._mu`` from whatever
    thread pushes; zero findings on the live package is pinned by
    ``test_pylocklint_zero_findings_even_baselined``, which now scans
    it — these prove a violation planted THERE would fire, i.e. the
    coverage is real, not vacuous)."""

    def test_planted_guarded_field_fires(self):
        src = ("import threading\n"
               "class ICIKVStore:\n"
               "    def __init__(self):\n"
               "        self._mu = threading.Lock()\n"
               "        self._collectives = 0\n"
               "    def push(self, key, value):\n"
               "        with self._mu:\n"
               "            self._collectives += 1\n"
               "    def reset(self):\n"
               "        self._collectives = 0\n")
        fs = pylocklint.lint_source(src, "mxnet_tpu/kvstore/ici.py")
        assert _rules(fs) == {"py-guarded-field": 1}

    def test_planted_blocking_under_lock_fires(self):
        # the store's real hazard shape: dispatching the collective
        # (a device step) while holding the telemetry lock would
        # serialize every pushing thread behind the compiled program
        src = ("import threading, time\n"
               "class ICIKVStore:\n"
               "    def __init__(self):\n"
               "        self._mu = threading.Lock()\n"
               "    def push(self, key, value):\n"
               "        with self._mu:\n"
               "            time.sleep(0.5)\n")
        fs = pylocklint.lint_source(src, "mxnet_tpu/kvstore/ici.py")
        assert _rules(fs) == {"py-blocking-under-lock": 1}

    def test_live_store_holds_no_lock_across_the_collective(self):
        """The live push() dispatches the collective OUTSIDE _mu (the
        lock guards only the counters) — pinned here so a refactor
        that hoists the lock around _reduce_flat re-fires the planted
        shape above on the real file."""
        src = open(os.path.join(
            REPO_ROOT, "mxnet_tpu/kvstore/ici.py")).read()
        fs = pylocklint.lint_source(src, "mxnet_tpu/kvstore/ici.py")
        assert fs == [], [str(f) for f in fs]


class TestPylockHttpFrontendCoverage:
    """ISSUE 15 satellite: pylocklint's auto-scope (the
    ``mxnet_tpu/serving`` package glob) reaches the round-20
    ``http_frontend.py`` — the thread↔asyncio bridge is exactly its
    beat: cluster threads feed the event loop via
    ``call_soon_threadsafe`` while the loop thread owns quota state.
    Zero findings on the live module is pinned below; the planted
    shapes prove a violation THERE would fire — coverage is real, not
    vacuous."""

    def test_planted_guarded_field_fires(self):
        src = ("import threading\n"
               "class HttpFrontend:\n"
               "    def __init__(self):\n"
               "        self._mu = threading.Lock()\n"
               "        self._active = 0\n"
               "    def _serve_conn(self, reader, writer):\n"
               "        with self._mu:\n"
               "            self._active += 1\n"
               "    def close(self):\n"
               "        self._active = 0\n")
        fs = pylocklint.lint_source(
            src, "mxnet_tpu/serving/http_frontend.py")
        assert _rules(fs) == {"py-guarded-field": 1}

    def test_planted_blocking_under_lock_fires(self):
        # the front door's real hazard shape: waiting on the cluster
        # (a blocking result()/submit()) while holding a lock the
        # completion callback needs would deadlock every stream —
        # the live module routes ALL cluster calls through the
        # executor and keeps quota state loop-thread-only
        src = ("import threading, time\n"
               "class HttpFrontend:\n"
               "    def __init__(self):\n"
               "        self._mu = threading.Lock()\n"
               "    def _run_request(self, rid):\n"
               "        with self._mu:\n"
               "            time.sleep(0.5)\n")
        fs = pylocklint.lint_source(
            src, "mxnet_tpu/serving/http_frontend.py")
        assert _rules(fs) == {"py-blocking-under-lock": 1}

    def test_live_frontend_is_clean(self):
        """The live module holds no lock across any blocking call
        (the bridge is one ``call_soon_threadsafe`` per event batch;
        cluster calls ride the executor) — pinned so a refactor that
        adds a lock around the bridge re-fires the planted shapes
        above on the real file."""
        src = open(os.path.join(
            REPO_ROOT, "mxnet_tpu/serving/http_frontend.py")).read()
        fs = pylocklint.lint_source(
            src, "mxnet_tpu/serving/http_frontend.py")
        assert fs == [], [str(f) for f in fs]


class TestPylockObsFlightCoverage:
    """Round 23 satellite: pylocklint covers the crash-durable flight
    ring and the worker span buffer — both emit from HOT paths (wire
    recv threads, the engine step loop), so their locks must stay
    memory-only.  Zero findings on the live ``mxnet_tpu/obs`` package
    is pinned by the repo-wide scan; the plants prove the violations
    the observability layer COULD regress into would fire there."""

    def test_planted_flight_sync_under_lock_fires(self):
        # THE tempting flight-ring bug: "make it durable" by msync
        # (or any syscall) inside record()'s lock — every wire recv
        # and engine step would then serialize behind a disk flush.
        # Page-cache durability is the design; a sync is a regression.
        src = ("import threading, time\n"
               "class FlightRecorder:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "    def record(self, kind):\n"
               "        with self._lock:\n"
               "            time.sleep(0)\n")
        fs = pylocklint.lint_source(src, "mxnet_tpu/obs/flight.py")
        assert _rules(fs) == {"py-blocking-under-lock": 1}

    def test_planted_span_ship_under_lock_fires(self):
        # the span-shipping hazard: draining the buffer is fine, but
        # waiting for the router's ship ack while still holding the
        # buffer lock would stall every concurrent span/instant emit
        # behind the socket round-trip — the live worker drains under
        # the lock, ships outside
        src = ("import threading\n"
               "class SpanBuffer:\n"
               "    def __init__(self):\n"
               "        self._mu = threading.Lock()\n"
               "        self._acked = threading.Event()\n"
               "    def ship(self):\n"
               "        with self._mu:\n"
               "            self._acked.wait()\n")
        fs = pylocklint.lint_source(src, "mxnet_tpu/obs/trace.py")
        assert _rules(fs) == {"py-blocking-under-lock": 1}

    def test_planted_guarded_seq_fires(self):
        # the ring's seq counter is lock-guarded (slot index and slot
        # head derive from it); an unguarded fast-path increment is a
        # torn-slot generator under concurrent recorders
        src = ("import threading\n"
               "class FlightRecorder:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self._seq = 0\n"
               "    def record(self, kind):\n"
               "        with self._lock:\n"
               "            self._seq += 1\n"
               "    def reset(self):\n"
               "        self._seq = 0\n")
        fs = pylocklint.lint_source(src, "mxnet_tpu/obs/flight.py")
        assert _rules(fs) == {"py-guarded-field": 1}

    def test_live_obs_emit_paths_are_clean(self):
        """The live recorder/buffer/merger hold their locks over
        memory-only work (json.dumps + buffer stores; the profiler
        hand-off is a locked list append) — pinned so a refactor that
        adds a flush or a send under either lock re-fires the planted
        shapes on the real files."""
        for rel in ("mxnet_tpu/obs/flight.py",
                    "mxnet_tpu/obs/trace.py"):
            src = open(os.path.join(REPO_ROOT, rel)).read()
            fs = pylocklint.lint_source(src, rel)
            assert fs == [], (rel, [str(f) for f in fs])


class TestBenchSyncFixtures:
    """jaxlint bench-no-sync (ISSUE 7 satellite): the timed-region /
    unsynced-jit pattern fires once, the pragma'd twin is suppressed,
    proper syncs (direct or via a local hard_sync-style helper) stay
    clean."""

    SRC = (
        "import time\n"
        "import jax\n"
        "import numpy as np\n"
        "\n"
        "\n"
        "def hard_sync(r):\n"
        "    jax.block_until_ready(r)\n"
        "\n"
        "\n"
        "def bad(f, x):\n"
        "    g = jax.jit(f)\n"
        "    t0 = time.perf_counter()\n"
        "    r = g(x)\n"
        "    dt = time.perf_counter() - t0\n"
        "    return r, dt\n"
        "\n"
        "\n"
        "def bad_bare_close(f, x):\n"
        "    g = jax.jit(f)\n"
        "    t0 = time.perf_counter()\n"
        "    r = g(x)\n"
        "    t1 = time.perf_counter()\n"
        "    return r, t1 - t0\n"
        "\n"
        "\n"
        "def bad_twin(f, x):\n"
        "    g = jax.jit(f)\n"
        "    t0 = time.perf_counter()\n"
        "    r = g(x)\n"
        "    # mxlint: allow(bench-no-sync) -- suppressed twin\n"
        "    dt = time.perf_counter() - t0\n"
        "    return r, dt\n"
        "\n"
        "\n"
        "def good_direct(f, x):\n"
        "    g = jax.jit(f)\n"
        "    t0 = time.perf_counter()\n"
        "    r = g(x)\n"
        "    jax.block_until_ready(r)\n"
        "    dt = time.perf_counter() - t0\n"
        "    return dt\n"
        "\n"
        "\n"
        "def good_helper(f, x):\n"
        "    g = jax.jit(f)\n"
        "    t0 = time.perf_counter()\n"
        "    hard_sync(g(x))\n"
        "    dt = time.perf_counter() - t0\n"
        "    return dt\n"
        "\n"
        "\n"
        "def good_loop(f, x):\n"
        "    g = jax.jit(f)\n"
        "    best = 1e9\n"
        "    for _ in range(3):\n"
        "        t0 = time.perf_counter()\n"
        "        r = g(x)\n"
        "        r = np.asarray(r)\n"
        "        best = min(best, time.perf_counter() - t0)\n"
        "    return best\n"
        "\n"
        "\n"
        "def untimed(f, x):\n"
        "    g = jax.jit(f)\n"
        "    return g(x)\n")

    @pytest.fixture(scope="class")
    def findings(self):
        return jaxlint.lint_source(self.SRC, "bench_fixture.py",
                                   region_re="$^", clock=False,
                                   bench=True)

    def test_fires_exactly_once_per_seed(self, findings):
        """One finding per seeded region: the subtraction close (bad)
        and the bare `t1 = perf_counter()` close (bad_bare_close —
        the canonical two-read idiom, a review-pass fix)."""
        assert _rules(findings) == {"bench-no-sync": 2}
        assert "line 13" in findings[0].message

    def test_engine_methods_do_not_alias_jitted_names(self):
        """`eng.run()` must not match a local `@jax.jit def run` —
        the spec_decode_probe false positive fixed in this PR."""
        src = ("import time\nimport jax\n"
               "@jax.jit\n"
               "def run(x):\n"
               "    return x\n"
               "def bench(eng, x):\n"
               "    t0 = time.perf_counter()\n"
               "    outs = eng.run()\n"
               "    return time.perf_counter() - t0\n")
        fs = jaxlint.lint_source(src, "b.py", region_re="$^",
                                 clock=False, bench=True)
        assert fs == []

    def test_live_benchmarks_clean(self):
        """Every benchmark driver syncs what it times (or pragmas the
        dispatch measurement) — zero live findings."""
        bench_dir = os.path.join(REPO_ROOT, "benchmark")
        bad = []
        for name in sorted(os.listdir(bench_dir)):
            if not name.endswith(".py"):
                continue
            src = open(os.path.join(bench_dir, name)).read()
            bad += [f for f in jaxlint.lint_source(
                src, "benchmark/" + name)
                if f.rule == "bench-no-sync"]
        assert bad == [], "\n".join(str(f) for f in bad)


class TestHotRegionAdditions:
    """ISSUE 7 satellite: the round-12 hot regions — cluster
    watchdog/failover, prefix-cache eviction/COW leaf, metrics
    registry mutation — each trip on a planted violation exactly once,
    and a violation OUTSIDE the region stays silent."""

    PLANT = ("    import jax\n"
             "    for _ in range(2):\n"
             "        f = jax.jit(lambda x: x)\n")

    CASES = [
        ("mxnet_tpu/serving/cluster.py",
         "class ServingCluster:\n"
         " def _fail_replica(self, rep, error):\n%s"),
        ("mxnet_tpu/serving/cluster.py",
         "class ServingCluster:\n"
         " def _monitor_loop(self):\n%s"),
        ("mxnet_tpu/serving/cluster.py",
         "class ServingCluster:\n"
         " def drain_replica(self, idx):\n%s"),
        ("mxnet_tpu/serving/prefix_cache.py",
         "class PrefixCache:\n"
         " def _drop(self, e):\n%s"),
        ("mxnet_tpu/obs/metrics.py",
         "class MetricsRegistry:\n"
         " def _get(self, cls, name):\n%s"),
        # round 16: the autoscaler control loop, the chaos driver's
        # replay-time apply path, and the trace generator
        ("mxnet_tpu/serving/autoscaler.py",
         "class Autoscaler:\n"
         " def tick(self, now=None):\n%s"),
        ("mxnet_tpu/serving/chaos.py",
         "class ChaosDriver:\n"
         " def poll(self, now_rel):\n%s"),
        ("benchmark/traffic_trace.py",
         "def generate_trace(spec):\n%s"),
        # round 17: the disagg scale-actuation paths protolint's
        # call-graph walks also cover — add_worker/drain_worker and
        # the late-join handshake helper run while the cluster serves
        ("mxnet_tpu/serving/cluster.py",
         "class DisaggServingCluster:\n"
         " def add_worker(self, role):\n%s"),
        ("mxnet_tpu/serving/cluster.py",
         "class DisaggServingCluster:\n"
         " def drain_worker(self, name):\n%s"),
        ("mxnet_tpu/serving/cluster.py",
         "class DisaggServingCluster:\n"
         " def _handshake_one(self, wh, timeout):\n%s"),
        # round 18: the KV-tiering hot paths — the whole tier store,
        # the prefix-cache spill/restore leaves (they run inside the
        # allocator's pressure callback), and the engine's swap
        # paths; an in-loop jit or stray sync there prices every
        # pressure event and every preemption resume
        ("mxnet_tpu/serving/tier_store.py",
         "class HostTierStore:\n"
         " def put(self, key, content, n_pages):\n%s"),
        ("mxnet_tpu/serving/prefix_cache.py",
         "class PrefixCache:\n"
         " def _spill_entry(self, e):\n%s"),
        ("mxnet_tpu/serving/prefix_cache.py",
         "class PrefixCache:\n"
         " def _restore_run(self, tokens, m, parent):\n%s"),
        ("mxnet_tpu/serving/engine.py",
         "class ServingEngine:\n"
         " def _preempt_victim(self, victim):\n%s"),
        ("mxnet_tpu/serving/engine.py",
         "class ServingEngine:\n"
         " def _swap_in(self, req, inp, slot):\n%s"),
        # round 19: the training scale-out hot paths — the ICI
        # KVStore's per-gradient-sync push/bucketing and the FSDP
        # composition helpers traced inside the sharded train step;
        # an in-loop jit there recompiles the collective every sync
        ("mxnet_tpu/kvstore/ici.py",
         "class ICIKVStore:\n"
         " def push(self, key, value, priority=0):\n%s"),
        ("mxnet_tpu/kvstore/ici.py",
         "class ICIKVStore:\n"
         " def _reduce_flat(self, devs, bucket):\n%s"),
        ("mxnet_tpu/parallel/fsdp.py",
         "def fsdp_param_specs(cfg, dp='dp', tp=None):\n%s"),
        # round 20: the HTTP front door's streaming/cancel paths run
        # on the ONE asyncio event loop thread — an in-loop jit or
        # stray sync in the SSE pump or the disconnect→cancel path
        # stalls every open stream at once
        ("mxnet_tpu/serving/http_frontend.py",
         "class HttpFrontend:\n"
         " async def _stream_sse(self, writer, reader, q, rid, "
         "prompt, req_id):\n%s"),
        ("mxnet_tpu/serving/http_frontend.py",
         "class HttpFrontend:\n"
         " async def _cancel_disconnected(self, rid):\n%s"),
        ("benchmark/http_bench.py",
         "def run_load(args):\n%s"),
        # round 24: the round-23 debug endpoints run on the same
        # event-loop thread as every SSE stream — an in-loop jit in
        # statusz/trace handling stalls all of them at once
        ("mxnet_tpu/serving/http_frontend.py",
         "class HttpFrontend:\n"
         " async def _handle_statusz(self, writer, req_id):\n%s"),
        ("mxnet_tpu/serving/http_frontend.py",
         "class HttpFrontend:\n"
         " async def _handle_trace(self, writer, path, req_id):\n%s"),
    ]

    @pytest.mark.parametrize("rel,template", CASES)
    def test_planted_violation_fires_once(self, rel, template):
        src = template % self.PLANT.replace("    ", "  ")
        fs = jaxlint.lint_source(src, rel, clock=False)
        assert _rules(fs) == {"retrace": 1}, \
            "%s: %r" % (rel, [str(f) for f in fs])

    def test_outside_region_is_silent(self):
        src = ("class ServingCluster:\n"
               " def some_cold_path(self):\n"
               "  import jax\n"
               "  for _ in range(2):\n"
               "   f = jax.jit(lambda x: x)\n")
        fs = jaxlint.lint_source(src, "mxnet_tpu/serving/cluster.py",
                                 clock=False)
        assert fs == []


# ---------------------------------------------------------------------------
# protolint (ISSUE 12): live repo, fixtures, protocol audit workflow
# ---------------------------------------------------------------------------
def _serving_modules():
    return protolint._load_modules(REPO_ROOT)


def _with_cluster(src):
    mods = _serving_modules()
    mods["mxnet_tpu/serving/cluster.py"] = src
    return mods


class TestProtolintLiveRepo:
    def test_protolint_zero_findings_even_baselined(self):
        """ISSUE 12 acceptance criterion: the wire-protocol &
        process-lifecycle audit reports ZERO findings with an EMPTY
        baseline over mxnet_tpu/serving/ — nothing grandfathered."""
        fs = protolint.run(REPO_ROOT)
        assert fs == [], "\n".join(str(f) for f in fs)

    def test_protocol_audit_checked_in_and_current(self):
        """docs/protocol.md is committed (acceptance criterion) and
        regenerates identically; every conn.send kind in serving/ has
        a handler row (no UNCOVERED), and the gen-fenced kinds are
        marked."""
        path = os.path.join(REPO_ROOT, protolint.AUDIT_PATH)
        committed = open(path).read()
        assert committed == protolint.protocol_audit_md(REPO_ROOT)
        assert "UNCOVERED" not in committed
        for kind in ("submit", "pages", "handoff", "fetch",
                     "fetch_reply", "stats_req", "stats", "abort",
                     "tokens", "done", "hello", "ready", "config",
                     "peers", "shutdown", "cancel"):
            assert "| `%s` |" % kind in committed, kind
        # the gen-fence column is verified, not decorative
        assert "| NO |" not in committed

    def test_cancel_kind_is_gen_fenced(self):
        """ISSUE 15: the round-20 client-disconnect ``cancel`` wire
        kind is audited — router → worker, carrying ``below_gen`` —
        and the fence column says yes, so a late cancel for a gen
        that already died is a no-op by checked invariant, not by
        convention."""
        committed = open(os.path.join(REPO_ROOT,
                                      protolint.AUDIT_PATH)).read()
        row = next(ln for ln in committed.splitlines()
                   if ln.startswith("| `cancel` |"))
        assert "router → worker" in row
        assert "below_gen" in row
        assert row.rstrip().endswith("| yes |")
        # synthetic in-process kinds never reach the wire table
        assert "| `_wake` |" not in committed
        assert "| `_lost` |" not in committed

    def test_audit_covers_every_send_kind(self):
        """The table covers exactly the literal-kind send sites the
        model sees — a new conn.send kind cannot ship without a row
        (and, via tier-1, without a handler)."""
        committed = open(os.path.join(
            REPO_ROOT, protolint.AUDIT_PATH)).read()
        prog = protolint.build_model(_serving_modules())
        kinds = {s.kind for s in prog.sends
                 if not s.kind.startswith("_")}
        assert kinds, "protocol model saw no send sites"
        for kind in kinds:
            assert "| `%s` |" % kind in committed, kind

    def test_protolint_guards_the_submit_gen_fence(self):
        """Deleting the round-17 fence in the worker's submit arm
        re-fires proto-gen-fence — the pass genuinely guards the fix
        shipped in this PR (PR-4/7/8 convention)."""
        src = _serving_modules()["mxnet_tpu/serving/cluster.py"]
        fence = (
            '            if meta["gen"] < self._fenced.get('
            'meta["rid"], -1):\n'
            "                # a late dispatch racing an abort for a "
            "NEWER\n"
            "                # incarnation of the same rid: the "
            "router no longer\n"
            "                # wants this gen — admitting it would "
            "resurrect a\n"
            "                # fenced zombie (proto-gen-fence checked "
            "invariant)\n"
            "                return\n")
        assert fence in src
        fs = protolint.analyze(_with_cluster(src.replace(fence, "",
                                                         1)))
        got = [f for f in fs if f.rule == "proto-gen-fence"
               and f.symbol == "submit"]
        assert len(got) == 1, [str(f) for f in fs]

    def test_protolint_guards_the_fetch_reply_degrade(self):
        """The fetch server's degrade-to-miss handler is what makes
        the fetch/fetch_reply pairing hold on exception edges —
        replacing it with a re-raise re-fires proto-reply-pairing."""
        src = _serving_modules()["mxnet_tpu/serving/cluster.py"]
        handler = (
            "            except Exception:\n"
            "                # degrade to a miss: the requester falls "
            "back to a\n"
            "                # cold prefill instead of eating its "
            "fetch timeout\n"
            "                n_full, reply_bufs = 0, []\n")
        assert handler in src
        broken = src.replace(
            handler, "            except Exception:\n"
                     "                raise\n", 1)
        fs = protolint.analyze(_with_cluster(broken))
        got = [f for f in fs if f.rule == "proto-reply-pairing"
               and f.symbol == "fetch"]
        assert len(got) == 1, [str(f) for f in fs]

    def test_protolint_guards_the_stats_reply_path(self):
        """_send_stats is the stats_req reply path: reintroducing the
        pre-round-17 rate-limit early-return re-fires
        proto-reply-pairing (a rate-limited reply DROPS solicited
        replies and stalls cluster_stats() to its timeout)."""
        src = _serving_modules()["mxnet_tpu/serving/cluster.py"]
        entry = ("        self._last_stats = time.perf_counter()\n"
                 "        eng = self.eng\n")
        assert entry in src
        broken = src.replace(entry, (
            "        if sid is None:\n"
            "            return\n" + entry), 1)
        fs = protolint.analyze(_with_cluster(broken))
        got = [f for f in fs if f.rule == "proto-reply-pairing"
               and f.symbol == "stats_req"]
        assert len(got) == 1, [str(f) for f in fs]

    def test_protolint_guards_the_terminate_reap_fixes(self):
        """Dropping any of the round-17 post-terminate joins re-fires
        py-resource-lifecycle: a SIGTERMed worker process stays a
        zombie pid until the router exits."""
        src = _serving_modules()["mxnet_tpu/serving/cluster.py"]
        reap = "                wh.proc.join(timeout=5)   " \
               "# reap the zombie pid\n"
        assert reap in src
        fs = protolint.analyze(_with_cluster(src.replace(reap, "",
                                                         1)))
        got = [f for f in fs if f.rule == "py-resource-lifecycle"
               and f.symbol == "terminate"]
        assert len(got) == 1, [str(f) for f in fs]

    def test_protolint_catches_meta_schema_drift(self):
        """Dropping a meta key one side still reads fires
        proto-meta-schema at the drifted SEND site — the cross-process
        KeyError class the rule exists for."""
        src = _serving_modules()["mxnet_tpu/serving/cluster.py"]
        whole = ('self.router.send("lost", {"rid": st["rid"],\n'
                 '                                      '
                 '"gen": st["gen"]})')
        assert whole in src
        broken = src.replace(
            whole, 'self.router.send("lost", {"rid": st["rid"]})', 1)
        fs = protolint.analyze(_with_cluster(broken))
        got = [f for f in fs if f.rule == "proto-meta-schema"]
        assert len(got) == 1 and got[0].symbol == "lost" \
            and "'gen'" in got[0].message, [str(f) for f in fs]

    def test_protolint_catches_dropped_dispatch_arm(self):
        """Deleting a dispatch arm fires proto-unhandled-kind at the
        send site — the silent-drop class."""
        src = _serving_modules()["mxnet_tpu/serving/cluster.py"]
        arm = ('            elif kind == "handed":\n'
               "                self._on_handed(wh, meta)\n")
        assert arm in src
        fs = protolint.analyze(_with_cluster(src.replace(arm, "", 1)))
        got = [f for f in fs if f.rule == "proto-unhandled-kind"]
        assert len(got) == 1 and got[0].symbol == "handed", \
            [str(f) for f in fs]

    def test_changed_only_trigger_gating(self, monkeypatch):
        """--changed-only: protolint re-analyzes only when serving/,
        parallel/dist.py, or tools/analysis/ change; any other change
        set skips the pass entirely (and a triggered run reports only
        changed files, pylocklint's convention)."""
        assert protolint.triggered(None)
        assert protolint.triggered({"mxnet_tpu/serving/cluster.py"})
        assert protolint.triggered({"mxnet_tpu/parallel/dist.py"})
        assert protolint.triggered({"tools/analysis/protolint.py"})
        assert not protolint.triggered({"README.md",
                                        "mxnet_tpu/models/gpt.py"})

        def boom(*a, **kw):
            raise AssertionError("analyzed despite no trigger")

        monkeypatch.setattr(protolint, "analyze", boom)
        assert protolint.run(REPO_ROOT, only={"README.md"}) == []


class TestProtoFixtures:
    """Every protolint rule fires exactly once as seeded in
    fixtures/mxlint/proto_fixture.py, pragma twins stay suppressed,
    clean shapes stay silent, and the baseline suppresses by key
    (ISSUE 12 satellite, mirroring pylock_fixture.py)."""

    ROLES = {"FixRouter": "router", "FixWorker": "worker"}

    @pytest.fixture(scope="class")
    def findings(self):
        src = open(os.path.join(FIXTURES, "proto_fixture.py")).read()
        return protolint.lint_source(src, "proto_fixture.py",
                                     roles=self.ROLES)

    def test_each_rule_fires_exactly_once(self, findings):
        assert _rules(findings) == {
            "proto-unhandled-kind": 1,    # orphan send site
            "proto-unknown-kind": 1,      # ghost arm
            "proto-meta-schema": 1,       # job missing payload
            "proto-gen-fence": 1,         # cancel arm unfenced
            "proto-reply-pairing": 1,     # ping_req exception edge
            "py-resource-lifecycle": 1,   # leaked Listener
        }, [str(f) for f in findings]

    def test_findings_name_their_kinds(self, findings):
        by_rule = {f.rule: f for f in findings}
        assert by_rule["proto-unhandled-kind"].symbol == "orphan"
        assert by_rule["proto-unknown-kind"].symbol == "ghost"
        assert by_rule["proto-meta-schema"].symbol == "job"
        assert "'payload'" in by_rule["proto-meta-schema"].message
        assert by_rule["proto-gen-fence"].symbol == "cancel"
        assert by_rule["proto-reply-pairing"].symbol == "ping_req"
        assert by_rule["py-resource-lifecycle"].symbol == "lst"

    def test_pragma_suppressed_twins(self, findings):
        src = open(os.path.join(FIXTURES, "proto_fixture.py")).read()
        lines = {(f.rule, f.line) for f in findings}
        hit = 0
        for i, text in enumerate(src.splitlines(), 1):
            if "suppressed twin" in text:
                hit += 1
                assert not any(ln in (i, i + 1, i + 2)
                               for _, ln in lines), \
                    "twin at line %d surfaced" % i
        assert hit >= 6                   # one twin per rule (+ the
        #                                   docstring's mentions)

    def test_clean_shapes_silent(self, findings):
        """The fenced arm (fine), the replying pair twin (echo_req),
        the escaping connection, the daemon thread, and the
        terminate+join pair seeded NO findings."""
        import ast
        src = open(os.path.join(FIXTURES, "proto_fixture.py")).read()
        spans = {}
        for node in ast.walk(ast.parse(src)):
            if isinstance(node, ast.FunctionDef):
                spans[node.name] = (node.lineno, node.end_lineno)
        for f in findings:
            for name in ("send_fine", "recv_loop", "clean_escape",
                         "clean_daemon_thread", "clean_reaped"):
                lo, hi = spans[name]
                assert not (lo <= f.line <= hi), \
                    "%s seeded clean but got %s" % (name, f)

    def test_baseline_suppresses(self, findings):
        baseline = {f.key for f in findings
                    if f.rule == "proto-gen-fence"}
        new, old = split_new(findings, baseline)
        assert _rules(old) == {"proto-gen-fence": 1}
        assert "proto-gen-fence" not in _rules(new)


class TestProtolintWalkerEdges:
    """Review-pass regressions: walker edge cases that would each be
    a silent false negative (the zero-findings bar leans on the
    analyzer actually looking)."""

    PROBE = (
        "class W:\n"
        "    def __init__(self, router):\n"
        "        self.router = router\n"
        "    def handle(self, kind, meta, bufs):\n"
        "        if kind == 'ping_req':\n"
        "%s"
        "class R:\n"
        "    def __init__(self, conn):\n"
        "        self.conn = conn\n"
        "    def go(self):\n"
        "        self.conn.send('ping_req', {'q': 1})\n"
        "    def recv_loop(self):\n"
        "        kind, meta, bufs = self.conn.recv()\n"
        "        if kind == 'ping':\n"
        "            pass\n")
    ROLES = {"R": "router", "W": "worker"}

    def _lint(self, arm_body):
        return protolint.lint_source(self.PROBE % arm_body, "m.py",
                                     roles=self.ROLES)

    def test_last_arm_in_chain_is_exit_edge_checked(self):
        """An arm whose whole If fits the arm span (the LAST arm of
        an elif chain) must still get branch analysis — reordering
        _handle must never silently disable the reply check."""
        fs = self._lint(
            "            data = self.compute(meta['q'])\n"
            "            self.router.send('ping', {'rid': data})\n")
        assert _rules(fs) == {"proto-reply-pairing": 1}

    def test_reply_in_one_branch_does_not_cover_the_other(self):
        """`if ok: send_reply()` / `else: return` drops the reply on
        the else edge — containment alone must not settle it."""
        fs = self._lint(
            "            if meta.get('ok', 0):\n"
            "                self.router.send('ping', {'rid': 1})\n"
            "            else:\n"
            "                return\n")
        assert _rules(fs) == {"proto-reply-pairing": 1}

    def test_bare_try_finally_does_not_protect(self):
        """try/finally without a handler does not stop the exception
        — the reply is still dropped on that edge."""
        fs = self._lint(
            "            try:\n"
            "                data = self.compute(meta['q'])\n"
            "            finally:\n"
            "                self.cleanup()\n"
            "            self.router.send('ping', {'rid': data})\n")
        assert _rules(fs) == {"proto-reply-pairing": 1}

    def test_fall_through_exit_leaks_resource(self):
        """The implicit function-end exit is an exit path too: an
        acquired resource that is never settled must flag even with
        no explicit return."""
        fs = protolint.lint_source(
            "class C:\n"
            "    def f(self):\n"
            "        lst = Listener()\n", "m.py", roles={})
        assert _rules(fs) == {"py-resource-lifecycle": 1}

    def test_settle_in_block_continuation_is_clean(self):
        """A resource acquired inside an `if` and settled after it
        (the _peer_conn shape) must NOT flag on the if-body's end."""
        fs = protolint.lint_source(
            "class C:\n"
            "    def f(self, cached):\n"
            "        conn = cached\n"
            "        if conn is None:\n"
            "            conn = connect('h', 1)\n"
            "        self.conns[0] = conn\n"
            "        return conn\n", "m.py", roles={})
        assert fs == [], [str(f) for f in fs]


# ---------------------------------------------------------------------------
# asynclint (ISSUE 19): live repo, forced-fix guards, fixtures
# ---------------------------------------------------------------------------
HTTP_FRONTEND = "mxnet_tpu/serving/http_frontend.py"


class TestAsynclintLiveRepo:
    def test_asynclint_zero_findings_even_baselined(self):
        """ISSUE 19 acceptance criterion: the asyncio event-loop
        audit reports ZERO findings with an EMPTY baseline over
        serving/ + obs/ — nothing grandfathered."""
        fs = asynclint.run(REPO_ROOT)
        assert fs == [], "\n".join(str(f) for f in fs)

    def test_asynclint_guards_the_503_wait_closed_fix(self):
        """The forced fix, edge 1: the 503 connection-cap path must
        drain the refused transport (close() only schedules the
        close).  Reverting it to the bare close()+return re-fires
        async-writer-lifecycle on that exit edge."""
        src = open(os.path.join(REPO_ROOT, HTTP_FRONTEND)).read()
        fix = (
            "            writer.close()\n"
            "            try:\n"
            "                # close() only schedules the close — "
            "wait for the\n"
            "                # transport to drain so refused "
            "connections can't\n"
            "                # pile up half-closed under an overload "
            "burst\n"
            "                await writer.wait_closed()\n"
            "            except OSError:\n"
            "                pass\n"
            "            return")
        assert fix in src
        broken = src.replace(
            fix, "            writer.close()\n            return", 1)
        fs = [f for f in asynclint.lint_source(broken, HTTP_FRONTEND)
              if f.rule == "async-writer-lifecycle"]
        assert len(fs) == 1 and fs[0].symbol.endswith(
            "_serve_conn.writer"), [str(f) for f in fs]

    def test_asynclint_guards_the_finally_wait_closed_fix(self):
        """The forced fix, edge 2: _serve_conn's finally settles the
        writer for every normal and exception edge of the connection
        loop.  Dropping the wait_closed there re-fires the rule on
        the fall-through path."""
        src = open(os.path.join(REPO_ROOT, HTTP_FRONTEND)).read()
        fix = ("            writer.close()\n"
               "            try:\n"
               "                await writer.wait_closed()\n"
               "            except OSError:\n"
               "                pass")
        assert src.count(fix) == 1
        broken = src.replace(fix, "            writer.close()", 1)
        fs = [f for f in asynclint.lint_source(broken, HTTP_FRONTEND)
              if f.rule == "async-writer-lifecycle"]
        assert len(fs) == 1 and fs[0].symbol.endswith(
            "_serve_conn.writer"), [str(f) for f in fs]

    def test_changed_only_trigger_gating(self, monkeypatch):
        """--changed-only: asynclint re-analyzes only when serving/,
        obs/, or tools/analysis/ change; any other change set skips
        the pass entirely."""
        assert asynclint.triggered(None)
        assert asynclint.triggered({HTTP_FRONTEND})
        assert asynclint.triggered({"mxnet_tpu/obs/trace.py"})
        assert asynclint.triggered({"tools/analysis/asynclint.py"})
        assert not asynclint.triggered({"README.md",
                                        "mxnet_tpu/models/gpt.py"})

        def boom(*a, **kw):
            raise AssertionError("analyzed despite no trigger")

        monkeypatch.setattr(asynclint, "analyze", boom)
        assert asynclint.run(REPO_ROOT, only={"README.md"}) == []


class TestAsyncFixtures:
    """Every asynclint rule fires exactly once as seeded in
    fixtures/mxlint/async_fixture.py, pragma twins stay suppressed,
    the blessed clean shapes (executor hop, threadsafe reference
    bridge, awaited/cancelled/escaping tasks, try/finally writer
    settle) stay silent, and the baseline suppresses by key."""

    CLEAN = ("clean_executor_hop", "_pull", "clean_boundary_bridge",
             "clean_task_awaited", "clean_task_cancelled",
             "clean_task_escapes", "clean_writer_settled",
             "clean_lock_released_before_await")

    @pytest.fixture(scope="class")
    def findings(self):
        src = open(os.path.join(FIXTURES, "async_fixture.py")).read()
        return asynclint.lint_source(src, "async_fixture.py")

    def test_each_rule_fires_exactly_once(self, findings):
        assert _rules(findings) == {
            "async-blocking-call": 1,        # time.sleep in a coro
            "async-unawaited-coroutine": 1,  # dropped coroutine call
            "async-task-exception": 1,       # never-settled task
            "async-threadsafe-boundary": 1,  # engine-thread put_nowait
            "async-writer-lifecycle": 1,     # close() w/o wait_closed
            "async-lock-across-await": 1,    # threading lock + await
        }, [str(f) for f in findings]

    def test_findings_name_their_sites(self, findings):
        by_rule = {f.rule: f for f in findings}
        assert by_rule["async-blocking-call"].symbol == \
            "FixAsync.plant_blocking"
        assert "time.sleep" in by_rule["async-blocking-call"].message
        assert by_rule["async-unawaited-coroutine"].symbol == \
            "FixAsync.plant_unawaited"
        assert by_rule["async-task-exception"].symbol == \
            "FixAsync.plant_task.t"
        assert by_rule["async-threadsafe-boundary"].symbol == \
            "FixAsync.plant_boundary.feed"
        assert "call_soon_threadsafe" in \
            by_rule["async-threadsafe-boundary"].message
        assert by_rule["async-writer-lifecycle"].symbol == \
            "FixAsync.plant_writer.writer"
        assert "wait_closed" in \
            by_rule["async-writer-lifecycle"].message
        assert by_rule["async-lock-across-await"].symbol == \
            "FixAsync.plant_lock"

    def test_pragma_suppressed_twins(self, findings):
        src = open(os.path.join(FIXTURES, "async_fixture.py")).read()
        lines = {(f.rule, f.line) for f in findings}
        hit = 0
        for i, text in enumerate(src.splitlines(), 1):
            if "suppressed twin" in text:
                hit += 1
                assert not any(ln in (i, i + 1, i + 2, i + 3)
                               for _, ln in lines), \
                    "twin at line %d surfaced" % i
        assert hit >= 6                   # one twin per rule

    def test_clean_shapes_silent(self, findings):
        import ast
        src = open(os.path.join(FIXTURES, "async_fixture.py")).read()
        spans = {}
        for node in ast.walk(ast.parse(src)):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                spans[node.name] = (node.lineno, node.end_lineno)
        for name in self.CLEAN:
            assert name in spans, "fixture lost clean shape %s" % name
        for f in findings:
            for name in self.CLEAN:
                lo, hi = spans[name]
                assert not (lo <= f.line <= hi), \
                    "%s seeded clean but got %s" % (name, f)

    def test_baseline_suppresses(self, findings):
        baseline = {f.key for f in findings
                    if f.rule == "async-blocking-call"}
        new, old = split_new(findings, baseline)
        assert _rules(old) == {"async-blocking-call": 1}
        assert "async-blocking-call" not in _rules(new)


# ---------------------------------------------------------------------------
# envlint (ISSUE 19 satellite): env-var documentation drift
# ---------------------------------------------------------------------------
class TestEnvlint:
    def test_every_env_read_documented(self):
        """Every literal MXNET_* key read anywhere in mxnet_tpu/ has
        a row in docs/env_vars.md — zero drift, nothing baselined."""
        fs = envlint.run(REPO_ROOT)
        assert fs == [], "\n".join(str(f) for f in fs)

    def test_doc_key_parse_sees_the_table(self):
        doc = open(os.path.join(REPO_ROOT, envlint.DOC)).read()
        keys = envlint.documented_keys(doc)
        # spot-check rows from four different table sections
        for k in ("MXNET_EAGER_JIT", "MXNET_SERVE_OVERLAP",
                  "MXNET_SERVE_FLIGHT_SLOTS", "MXNET_TEST_SEED"):
            assert k in keys, k

    def test_planted_undocumented_read_fires(self):
        """The drift proof: an env read with no doc row fires
        env-doc-drift once, at the read site, naming the key — for
        every read shape the scanner models."""
        doc = envlint.documented_keys(
            open(os.path.join(REPO_ROOT, envlint.DOC)).read())
        shapes = [
            'import os\nV = os.environ.get("MXNET_NEW_KNOB", "0")\n',
            'import os\nV = os.environ["MXNET_NEW_KNOB"]\n',
            'import os\nV = "MXNET_NEW_KNOB" in os.environ\n',
            'from mxnet_tpu.base import env_int\n'
            'V = env_int("MXNET_NEW_KNOB", 3)\n',
        ]
        for src in shapes:
            fs = envlint.lint_source(src, "mxnet_tpu/serving/x.py",
                                     doc)
            assert _rules(fs) == {"env-doc-drift": 1}, (src, fs)
            assert fs[0].symbol == "MXNET_NEW_KNOB"
        # ...and a documented read of the same shape stays silent
        ok = envlint.lint_source(
            'import os\nV = os.environ.get("MXNET_NEW_KNOB")\n',
            "mxnet_tpu/serving/x.py", doc | {"MXNET_NEW_KNOB"})
        assert ok == []

    def test_pragma_suppresses_intended_undocumented(self):
        fs = envlint.lint_source(
            "import os\n"
            "# mxlint: allow(env-doc-drift) -- internal-only knob\n"
            'V = os.environ.get("MXNET_SECRET_KNOB")\n',
            "mxnet_tpu/serving/x.py", set())
        assert fs == []

    def test_changed_only_trigger_gating(self, monkeypatch):
        assert envlint.triggered(None)
        assert envlint.triggered({"mxnet_tpu/base.py"})
        assert envlint.triggered({"docs/env_vars.md"})
        assert envlint.triggered({"tools/analysis/envlint.py"})
        assert not envlint.triggered({"README.md", "docs/perf.md"})

        def boom(*a, **kw):
            raise AssertionError("analyzed despite no trigger")

        monkeypatch.setattr(envlint, "analyze", boom)
        assert envlint.run(REPO_ROOT, only={"README.md"}) == []


# ---------------------------------------------------------------------------
# graphlint (ISSUE 8): live repo, fixtures, manifest + audit workflow
# ---------------------------------------------------------------------------
class TestGraphlintLiveRepo:
    def test_graphlint_zero_findings_even_baselined(self):
        """Acceptance criterion: the compiled-program audit reports
        ZERO findings with an EMPTY baseline — donation verified,
        budgets met, no undeclared f32 upcasts, no host callbacks."""
        fs = graphlint.run(REPO_ROOT)
        assert fs == [], "\n".join(str(f) for f in fs)

    def test_budget_manifest_covers_required_programs(self):
        """The committed hbm_budgets.json covers the serving step (all
        three kernels/meshes), GPT generate, and the train steps
        (acceptance criterion), agrees exactly with the registry, and
        records a trace closure for every program (the --changed-only
        scope)."""
        budgets = graphlint.load_budgets()
        progs = set(budgets["programs"])
        assert {"serving_step", "serving_step_pallas",
                "serving_step_tp", "cow_page_copy", "gpt_generate",
                "gpt_spec_block", "transformer_train_step",
                "gpt_train_step", "paged_attention_kernel",
                "tier_page_restore"} <= progs
        assert progs == {sp.name for sp in graphlint.live_programs()}
        for name, e in budgets["programs"].items():
            assert e["budget_bytes"] >= e["peak_bytes"], name
            assert e["closure"], name
        ss = budgets["programs"]["serving_step"]["closure"]
        assert "mxnet_tpu/serving/engine.py" in ss
        assert "mxnet_tpu/models/gpt.py" in ss

    def test_per_device_expected_peaks_recorded(self):
        """Round-14 acceptance: the serving step entries carry
        per-device (÷tp) expected peaks — the sharded inputs (pools +
        tp-sharded params) divide by tp, replicated inputs and the
        (conservatively replicated) intermediates do not, so the
        per-device number sits strictly between peak/tp and peak and
        decreases with tp."""
        budgets = graphlint.load_budgets()
        # the pallas step is tp=1-only this round — no ÷tp row for an
        # unreachable configuration
        assert "per_device_expected_peak_bytes" not in \
            budgets["programs"]["serving_step_pallas"]
        for name in ("serving_step", "serving_step_tp"):
            e = budgets["programs"][name]
            pd = e["per_device_expected_peak_bytes"]
            assert set(pd) == {"tp%d" % t
                               for t in graphlint._PER_DEVICE_TPS}
            peak = e["peak_bytes"]
            assert peak / 4 < pd["tp4"] < pd["tp2"] < peak, (name, pd)
        # and it regenerates identically from the live spec table
        sp = {s.name: s for s in graphlint.live_programs()}[
            "serving_step"]
        assert graphlint._per_device_expected_peaks(
            sp, budgets["programs"]["serving_step"]["peak_bytes"]) \
            == budgets["programs"]["serving_step"][
                "per_device_expected_peak_bytes"]

    def test_sharding_audit_checked_in_and_current(self):
        """The ServingEngine step-program sharding audit is committed
        (acceptance criterion) and regenerates identically.  Round 14:
        the table now verifies the ENGINE'S DECLARED shardings
        (serving/engine.py step_input_specs) against the megatron
        rules — UNCOVERED count must be 0 and nothing may mismatch."""
        path = os.path.join(REPO_ROOT, graphlint.AUDIT_PATH)
        committed = open(path).read()
        assert committed == graphlint.sharding_audit_md(REPO_ROOT)
        assert "pools[*]['kv']" in committed
        assert "UNCOVERED count: 0, mismatched: 0" in committed
        assert "P(None, None, 'tp', None)" in committed   # heads axis
        assert "covered: P(None, 'tp')" in committed      # megatron
        assert "MISMATCH — " not in committed

    def test_sharding_readiness_verifies_engine_declaration(
            self, monkeypatch):
        """The graph-sharding-readiness rule genuinely audits the LIVE
        declaration: a drifted step_input_specs — pools sharded on the
        wrong axis, a host row vector suddenly tp-sharded — fires, and
        the live declaration is clean."""
        from jax.sharding import PartitionSpec as P
        from mxnet_tpu.serving import engine as E
        assert graphlint.sharding_readiness_findings(REPO_ROOT) == []
        real = E.step_input_specs

        def drifted(params, cfg, kv_int8, tp="tp"):
            specs = list(real(params, cfg, kv_int8, tp=tp))
            # pools sharded on the PAGE axis instead of heads, and the
            # token rows tp-sharded (two distinct mismatch classes)
            specs[1] = [{"kv": P(None, tp, None, None),
                         "s": P(None, tp, None, None)}
                        for _ in range(cfg.n_layers)]
            specs[2] = P(tp)
            return tuple(specs)

        monkeypatch.setattr(E, "step_input_specs", drifted)
        fs = graphlint.sharding_readiness_findings(REPO_ROOT)
        assert _rules(fs) == {"graph-sharding-readiness": 1}
        assert "mismatch" in fs[0].symbol
        # anchored at the declaration, not at graphlint
        assert fs[0].path == "mxnet_tpu/serving/engine.py"

    def test_graphlint_guards_the_kv_quantize_fix(self, monkeypatch):
        """Reverting _kv_quantize to the round-4 bf16-accumulation
        version (bf16 max/divide, cosmetic f32 upcast of the stacked
        scales) re-fires graph-dtype-drift on the serving step — the
        pass genuinely guards the fix shipped in this PR (PR-4/7
        convention)."""
        import jax.numpy as jnp
        from mxnet_tpu.models import gpt as G
        src = open(os.path.join(REPO_ROOT,
                                "mxnet_tpu/models/gpt.py")).read()
        assert "kf = k.astype(jnp.float32)" in src   # the fix is live

        def old_kv_quantize(k, v):
            sk = jnp.maximum(jnp.max(jnp.abs(k), axis=-1) / 127.0,
                             1e-8)
            sv = jnp.maximum(jnp.max(jnp.abs(v), axis=-1) / 127.0,
                             1e-8)
            kq = jnp.clip(jnp.round(k / sk[..., None]), -127, 127
                          ).astype(jnp.int8)
            vq = jnp.clip(jnp.round(v / sv[..., None]), -127, 127
                          ).astype(jnp.int8)
            return (jnp.concatenate([kq, vq], axis=-1),
                    jnp.stack([sk, sv], axis=-1).astype(jnp.float32))

        monkeypatch.setattr(G, "_kv_quantize", old_kv_quantize)
        # pjit caches the traced jaxpr per (fn, avals) — drop it so
        # the re-trace actually sees the monkeypatched quantizer, and
        # drop it AGAIN on the way out so later tests re-tracing the
        # _step_cache'd fn do not read the poisoned bf16 jaxpr back
        import jax
        from mxnet_tpu.serving import engine as E
        jax.clear_caches()
        try:
            sp = {s.name: s for s in graphlint.live_programs()}[
                "serving_step"]
            fs = graphlint.check_program(
                sp, REPO_ROOT, budgets=graphlint.load_budgets())
        finally:
            E._step_cache.clear()
            jax.clear_caches()
        assert _rules(fs)["graph-dtype-drift"] >= 1, \
            [str(f) for f in fs]

    def test_dropping_donation_refires(self, monkeypatch):
        """Rebuilding the serving step with donate_argnums stripped
        (what a careless _make_step refactor would do) fires
        graph-donation — the registry audits the LIVE builder."""
        import jax
        from mxnet_tpu.serving import engine as E
        real_jit = jax.jit

        def nodonate_jit(*a, **kw):
            kw.pop("donate_argnums", None)
            return real_jit(*a, **kw)

        monkeypatch.setattr(jax, "jit", nodonate_jit)
        E._step_cache.clear()
        try:
            sp = {s.name: s for s in graphlint.live_programs()}[
                "serving_step"]
            fs = graphlint.check_program(
                sp, REPO_ROOT, budgets=graphlint.load_budgets())
        finally:
            E._step_cache.clear()    # never leak the undonated step
        assert _rules(fs)["graph-donation"] == 1, [str(f) for f in fs]

    def test_dropping_donation_refires_under_shardings(self,
                                                       monkeypatch):
        """Round-14 acceptance: pool donation is verified on the
        SHARDED step too — stripping donate_argnums from the
        tp-lowered build (in/out shardings intact) fires
        graph-donation, i.e. the gate did not silently stop applying
        when the program gained a mesh."""
        import jax
        from mxnet_tpu.serving import engine as E
        real_jit = jax.jit

        def nodonate_jit(*a, **kw):
            kw.pop("donate_argnums", None)
            return real_jit(*a, **kw)

        monkeypatch.setattr(jax, "jit", nodonate_jit)
        E._step_cache.clear()
        try:
            sp = {s.name: s for s in graphlint.live_programs()}[
                "serving_step_tp"]
            fs = graphlint.check_program(
                sp, REPO_ROOT, budgets=graphlint.load_budgets())
        finally:
            E._step_cache.clear()
        assert _rules(fs)["graph-donation"] == 1, [str(f) for f in fs]

    def test_changed_only_traces_by_closure(self, monkeypatch):
        """--changed-only re-traces a program iff a file in its
        recorded trace closure changed (analysis-infra changes always
        re-trace; --all / tier-1 ignores the scope entirely)."""
        budgets = graphlint.load_budgets()
        sp = {s.name: s for s in graphlint.live_programs()}[
            "serving_step"]
        assert graphlint._needs_trace(
            sp, budgets, {"mxnet_tpu/serving/engine.py"})
        assert graphlint._needs_trace(
            sp, budgets, {"tools/analysis/graphlint.py"})
        assert not graphlint._needs_trace(sp, budgets, {"README.md"})

        # nothing changed -> NO program traced at all
        def no_trace(*a, **kw):
            raise AssertionError("traced despite empty change set")

        monkeypatch.setattr(graphlint, "check_program", no_trace)
        assert graphlint.run(REPO_ROOT, only=set()) == []

    def test_update_budgets_never_relaxes(self, tmp_path):
        """--update-budgets re-records peak_bytes and closures but a
        committed budget only ever ratchets DOWN (perf-gate
        semantics); a program over its budget stays a finding until
        the budget is hand-edited with justification."""
        gf = _load_graph_fixture()
        sp = {s.name: s for s in gf.PROGRAMS}["fix_over_budget"]
        p = tmp_path / "budgets.json"
        p.write_text(json.dumps({"version": 1, "programs": {
            "fix_over_budget": {"peak_bytes": 5, "budget_bytes": 5,
                                "closure": []}}}))
        data = graphlint.update_budgets(REPO_ROOT, path=str(p),
                                        specs=[sp])
        e = data["programs"]["fix_over_budget"]
        assert e["peak_bytes"] > 5          # measurement re-recorded
        assert e["budget_bytes"] == 5       # budget NOT relaxed
        # ...and a generous budget tightens to ceil(peak * HEADROOM)
        p.write_text(json.dumps({"version": 1, "programs": {
            "fix_over_budget": {"peak_bytes": 10 ** 9,
                                "budget_bytes": 10 ** 9,
                                "closure": []}}}))
        data = graphlint.update_budgets(REPO_ROOT, path=str(p),
                                        specs=[sp])
        e = data["programs"]["fix_over_budget"]
        import math
        assert e["budget_bytes"] == int(math.ceil(
            e["peak_bytes"] * graphlint.HEADROOM))

    def test_estimator_is_deterministic_and_scales(self):
        """peak_live_bytes: bit-stable across runs, and a program that
        materializes an extra full-size temporary estimates strictly
        higher (the property the budget gate rides on)."""
        import jax
        import jax.numpy as jnp
        s = jax.ShapeDtypeStruct((64, 64), jnp.float32)

        def lean(x):
            return (x * 2.0).sum()

        def fat(x):
            a = x * 2.0
            b = x * 3.0
            c = x * 4.0
            return (a + b + c).sum()

        j1 = jax.make_jaxpr(lean)(s)
        p1 = graphlint.peak_live_bytes(j1)
        assert p1 == graphlint.peak_live_bytes(jax.make_jaxpr(lean)(s))
        assert graphlint.peak_live_bytes(jax.make_jaxpr(fat)(s)) > p1


class TestGraphFixtures:
    """Every graphlint rule fires exactly once over the seeded toy
    registry in fixtures/mxlint/graph_fixture.py, pragma twins stay
    suppressed, clean programs stay silent, and the baseline
    suppresses by key (ISSUE 8 satellite)."""

    @pytest.fixture(scope="class")
    def fixture(self):
        return _load_graph_fixture()

    @pytest.fixture(scope="class")
    def findings(self, fixture):
        return graphlint.run(REPO_ROOT, specs=fixture.PROGRAMS,
                             budgets=fixture.BUDGETS)

    def test_each_rule_fires_exactly_once(self, findings):
        assert _rules(findings) == {
            "graph-donation": 1,      # fix_dropped_donation
            "graph-dtype-drift": 1,   # fix_f32_upcast
            "graph-hbm-budget": 1,    # fix_over_budget
            "graph-host-sync": 1,     # fix_host_callback
        }, [str(f) for f in findings]

    def test_findings_name_their_programs(self, findings):
        by_rule = {f.rule: f for f in findings}
        assert "fix_dropped_donation" in \
            by_rule["graph-donation"].symbol
        assert "fix_f32_upcast" in by_rule["graph-dtype-drift"].symbol
        assert by_rule["graph-hbm-budget"].symbol == "fix_over_budget"
        assert "debug_callback" in by_rule["graph-host-sync"].symbol

    def test_dtype_finding_anchors_at_the_upcast_line(self, findings):
        f = [x for x in findings if x.rule == "graph-dtype-drift"][0]
        src = open(os.path.join(FIXTURES,
                                "graph_fixture.py")).read()
        line = src.splitlines()[f.line - 1]
        assert "astype(jnp.float32)" in line

    def test_pragma_suppressed_twins(self, findings):
        for f in findings:
            assert "twin" not in f.symbol, str(f)

    def test_clean_programs_silent(self, findings):
        for f in findings:
            assert "fine_" not in f.symbol, str(f)

    def test_baseline_suppresses(self, findings):
        baseline = {f.key for f in findings
                    if f.rule == "graph-donation"}
        new, old = split_new(findings, baseline)
        assert _rules(old) == {"graph-donation": 1}
        assert "graph-donation" not in _rules(new)

    def test_missing_budget_entry_is_a_finding(self, fixture):
        sp = {s.name: s for s in fixture.PROGRAMS}["fix_over_budget"]
        fs = graphlint.check_program(sp, REPO_ROOT,
                                     budgets={"programs": {}})
        assert _rules(fs)["graph-hbm-budget"] == 1
        assert "--update-budgets" in fs[0].message

    def test_growth_over_manifest_is_a_finding(self, fixture):
        """Within budget but >10% over the recorded peak still fires
        (the trajectory half of the gate)."""
        sp = {s.name: s for s in fixture.PROGRAMS}["fix_over_budget"]
        fs = graphlint.check_program(
            sp, REPO_ROOT,
            budgets={"programs": {"fix_over_budget": {
                "peak_bytes": 100, "budget_bytes": 10 ** 9}}})
        assert _rules(fs) == {"graph-hbm-budget": 1}
        assert "grew" in fs[0].message


# ---------------------------------------------------------------------------
# 3. infra behaviors
# ---------------------------------------------------------------------------
class TestInfra:
    def test_pragma_comment_block_above(self):
        src = ("x = 1\n"
               "# mxlint: allow(host-sync) -- reason\n"
               "# second comment line\n"
               "y = np.asarray(out)\n")
        f = Finding("jax", "host-sync", "m.py", 4, "np.asarray", "m")
        assert apply_pragmas([f], src) == []

    def test_pragma_wrong_rule_does_not_suppress(self):
        src = "# mxlint: allow(retrace)\ny = np.asarray(out)\n"
        f = Finding("jax", "host-sync", "m.py", 2, "np.asarray", "m")
        assert apply_pragmas([f], src) == [f]

    def test_baseline_roundtrip(self, tmp_path):
        p = tmp_path / "baseline.json"
        p.write_text('{"version": 1, "allow": [{"rule": "r", '
                     '"path": "p.py", "symbol": "s"}, "a:b:c"]}')
        keys = load_baseline(str(p))
        assert keys == {"r:p.py:s", "a:b:c"}

    def test_checked_in_baseline_is_empty(self):
        """The suite ships with zero accepted debt — anything new must
        be fixed or explicitly pragma'd with a justification."""
        keys = load_baseline(os.path.join(
            REPO_ROOT, "tools", "analysis", "baseline.json"))
        assert keys == set()

    def test_findings_json_schema(self):
        """--format json (ISSUE 8 satellite): the stable CI schema —
        every finding carries rule/file/line/message/fingerprint, the
        fingerprint is the sha1 of the line-independent baseline key
        (stable under unrelated edits), statuses partition
        new/baselined."""
        f1 = Finding("jax", "host-sync", "m.py", 7, "np.asarray", "m1")
        f2 = Finding("jax", "host-sync", "m.py", 9, "np.asarray", "m2")
        old = Finding("abi", "abi-argtypes", "n.py", 0, "MXFoo", "m3")
        data = findings_json({"new": [f1], "baselined": [old]})
        assert data["version"] == 1
        assert data["new"] == 1 and data["baselined"] == 1
        entry = data["findings"][0]
        assert set(entry) == {"rule", "file", "line", "message",
                              "fingerprint", "analyzer", "symbol",
                              "status"}
        assert entry == {"rule": "host-sync", "file": "m.py",
                         "line": 7, "message": "m1",
                         "analyzer": "jax", "symbol": "np.asarray",
                         "status": "new",
                         "fingerprint": entry["fingerprint"]}
        # line-independent: same key -> same fingerprint; 12 hex chars
        fp1 = findings_json({"new": [f1], "baselined": []})
        fp2 = findings_json({"new": [f2], "baselined": []})
        assert fp1["findings"][0]["fingerprint"] == \
            fp2["findings"][0]["fingerprint"]
        assert len(entry["fingerprint"]) == 12
        int(entry["fingerprint"], 16)
        assert data["findings"][1]["status"] == "baselined"

    def test_cli_format_json_round_trips(self, capsys):
        """`python -m tools.analysis --format json` emits parseable
        JSON with zero new findings on the live repo (what
        tools/run_static_analysis.sh passes through for CI)."""
        from tools.analysis import runner
        rc = runner.main(["--format", "json", "--changed-only"])
        out = capsys.readouterr().out
        data = json.loads(out)
        assert rc == 0
        assert data["version"] == 1
        assert data["new"] == 0
