"""Autograd tests (reference model: ``tests/python/unittest/test_autograd.py``)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_basic_backward():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x + 2 * x
    y.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * x.asnumpy() + 2)


def test_head_grads():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward(nd.array([10.0, 100.0]))
    assert np.allclose(x.grad.asnumpy(), [30.0, 300.0])


def test_grad_req_add():
    x = nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = 2 * x
        y.backward()
    assert np.allclose(x.grad.asnumpy(), [6.0, 6.0])


def test_grad_req_null():
    x = nd.array([1.0])
    x.attach_grad(grad_req="null")
    with autograd.record():
        y = x * 2
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [0.0])


def test_chain_and_branches():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        a = x * 3
        b = x * x
        y = a + b * a  # 3x + 3x^3
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [3 + 9 * 4.0])


def test_detach_and_stop_gradient():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    assert np.allclose(x.grad.asnumpy(), [9.0])
    with autograd.record():
        y2 = nd.BlockGrad(x * x) * x
    y2.backward()
    assert np.allclose(x.grad.asnumpy(), [9.0])


def test_pause_and_modes():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
        with autograd.predict_mode():
            assert not autograd.is_training()
    assert not autograd.is_recording()


def test_grad_function():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    g = autograd.grad(y, [x])
    assert np.allclose(g[0].asnumpy(), 2 * x.asnumpy())
    # .grad buffer untouched by grad()
    assert np.allclose(x.grad.asnumpy(), 0.0)


def test_higher_order():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x  # y = x^3
        dy = autograd.grad(y, [x], create_graph=True)[0]  # 3x^2
        assert np.allclose(dy.asnumpy(), [12.0])
        d2y = autograd.grad(dy, [x])[0]  # 6x
    assert np.allclose(d2y.asnumpy(), [12.0])


def test_multiple_variables():
    a = nd.array([1.0])
    b = nd.array([2.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        y = a * b + a
    y.backward()
    assert np.allclose(a.grad.asnumpy(), [3.0])
    assert np.allclose(b.grad.asnumpy(), [1.0])


def test_mark_variables():
    x = nd.array([5.0])
    g = nd.zeros((1,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = x * 4
    y.backward()
    assert np.allclose(g.asnumpy(), [4.0])


def test_random_replay_consistency():
    """Dropout backward must see the same mask as forward (keys are tape
    constants)."""
    mx.random.seed(7)
    x = nd.ones((1000,))
    x.attach_grad()
    with autograd.record():
        with autograd.train_mode():
            y = nd.Dropout(x, p=0.5)
        s = y.sum()
    s.backward()
    # gradient equals the forward mask scaling exactly
    yv = y.asnumpy()
    gv = x.grad.asnumpy()
    assert np.allclose(gv, yv)  # since x==1, y = mask*2 = grad


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            y, = self.saved_tensors
            return dy * y * (1 - y)

    x = nd.array([0.0, 1.0])
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    sig = 1 / (1 + np.exp(-x.asnumpy()))
    assert np.allclose(x.grad.asnumpy(), sig * (1 - sig), rtol=1e-5)


def test_exception_on_untracked_backward():
    x = nd.array([1.0])
    y = x * 2  # not recorded
    with pytest.raises(mx.MXNetError):
        y.backward()


def test_inplace_rejected_under_recording():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        with pytest.raises(mx.MXNetError):
            y += 1
