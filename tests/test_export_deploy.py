"""The deployment chain (reference workflow): HybridBlock.export writes
a REAL symbol graph + params, SymbolBlock.imports serves it, graph
passes optimize it, and the C predict API embeds it."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.gluon import nn


def _net():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(4, 3, padding=1), nn.BatchNorm(),
                nn.Activation("relu"), nn.GlobalAvgPool2D(),
                nn.Dense(3))
    net.initialize(mx.initializer.Xavier())
    return net


def test_export_writes_real_symbol(tmp_path):
    net = _net()
    x = nd.array(np.random.RandomState(0).randn(2, 2, 8, 8)
                 .astype("float32"))
    net(x)  # materialize params
    prefix = str(tmp_path / "model")
    net.export(prefix)

    s = sym.load(prefix + "-symbol.json")
    ops = [n.op.name for n in s._nodes() if not n.is_var]
    assert "Convolution" in ops and "BatchNorm" in ops
    assert "FullyConnected" in ops
    loaded = nd.load(prefix + "-0000.params")
    assert any(k.startswith("arg:") for k in loaded)
    assert any(k.startswith("aux:") for k in loaded)  # BN running stats


def test_symbolblock_imports_matches_block(tmp_path):
    net = _net()
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(2, 2, 8, 8).astype("float32"))
    # train-mode forwards to move BN stats off init values
    from mxnet_tpu import autograd
    with autograd.record():
        net(x)
    ref = net(x).asnumpy()

    prefix = str(tmp_path / "model")
    net.export(prefix)
    from mxnet_tpu.gluon.block import SymbolBlock
    served = SymbolBlock.imports(prefix + "-symbol.json", "data",
                                 prefix + "-0000.params")
    got = served(x).asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    # new batch size → executor rebinds transparently
    x2 = nd.array(rng.randn(5, 2, 8, 8).astype("float32"))
    assert served(x2).shape == (5, 3)


def test_exported_graph_optimizes(tmp_path):
    """conv+BN folding applies to gluon-exported graphs."""
    net = _net()
    x = nd.array(np.random.RandomState(1).randn(2, 2, 8, 8)
                 .astype("float32"))
    net(x)
    ref = net(x).asnumpy()
    prefix = str(tmp_path / "model")
    net.export(prefix)

    s = sym.load(prefix + "-symbol.json")
    loaded = nd.load(prefix + "-0000.params")
    args = {k[4:]: v for k, v in loaded.items() if k.startswith("arg:")}
    aux = {k[4:]: v for k, v in loaded.items() if k.startswith("aux:")}
    s2, args2, aux2 = s.optimize_for("fold_conv_bn", args, aux)
    ops = [n.op.name for n in s2._nodes() if not n.is_var]
    assert "BatchNorm" not in ops

    ex = s2.bind(ctx=mx.cpu(), args=dict(args2, data=x), aux_states=aux2)
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), ref,
                               rtol=1e-4, atol=1e-5)


def test_symbolblock_in_hybrid_parent(tmp_path):
    """A SymbolBlock composes inside another block's symbolic trace."""
    net = _net()
    x = nd.array(np.random.RandomState(2).randn(2, 2, 8, 8)
                 .astype("float32"))
    net(x)
    prefix = str(tmp_path / "model")
    net.export(prefix)
    from mxnet_tpu.gluon.block import SymbolBlock
    served = SymbolBlock.imports(prefix + "-symbol.json", "data",
                                 prefix + "-0000.params")
    out_sym = served(sym.Variable("data"))
    assert "FullyConnected" in [n.op.name for n in out_sym._nodes()
                                if not n.is_var]


def test_symbolblock_inputs_not_mutated(tmp_path):
    """Serving must never write into the caller's input arrays."""
    net = _net()
    rng = np.random.RandomState(3)
    x1 = nd.array(rng.randn(2, 2, 8, 8).astype("float32"))
    x2 = nd.array(rng.randn(2, 2, 8, 8).astype("float32"))
    net(x1)
    prefix = str(tmp_path / "m")
    net.export(prefix)
    from mxnet_tpu.gluon.block import SymbolBlock
    served = SymbolBlock.imports(prefix + "-symbol.json", "data",
                                 prefix + "-0000.params")
    x1_copy = x1.asnumpy().copy()
    served(x1)
    served(x2)
    np.testing.assert_array_equal(x1.asnumpy(), x1_copy)


@pytest.mark.slow
def test_symbolblock_fine_tunes(tmp_path):
    """Gradients flow through a loaded SymbolBlock (reference parity)."""
    from mxnet_tpu import autograd, gluon
    net = _net()
    rng = np.random.RandomState(4)
    x = nd.array(rng.randn(8, 2, 8, 8).astype("float32"))
    y = nd.array(rng.randint(0, 3, (8,)).astype("float32"))
    net(x)
    prefix = str(tmp_path / "m")
    net.export(prefix)
    from mxnet_tpu.gluon.block import SymbolBlock
    served = SymbolBlock.imports(prefix + "-symbol.json", "data",
                                 prefix + "-0000.params")
    trainer = gluon.Trainer(served.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for _ in range(5):
        with autograd.record():
            L = loss_fn(served(x), y)
        L.backward()
        trainer.step(8)
        losses.append(float(L.asnumpy().mean()))
    assert losses[-1] < losses[0], losses
