"""Disaggregated prefill/decode serving (round 15).

FAST tier: the wire layer (raw frames, bounded/garbage length
prefixes, peer killed mid-frame), page export/install roundtrips, the
cluster prefix index, and an in-process prefill→install→adopt
simulation of the cross-process handoff (``admit_prefilled``).

SLOW tier (group j): whole-OS-process clusters — f32-greedy
bit-identity to the single-engine ``generate`` oracle across the
prefill/decode split, cluster-level prefilled-exactly-once
reconciliation via the remote-hit counters, SIGKILL of a prefill
process mid-stream and of a decode process mid-decode with
recompute-exact completion and zero leaked pages/refs on survivors,
preemption/resume on the decode side, and int8-KV page transfer.
"""
import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tiny(dtype="float32"):
    import jax
    from mxnet_tpu.models import gpt as G
    cfg = G.gpt_tiny(dtype=dtype)
    params = G.init_params(jax.random.PRNGKey(0), cfg)
    return params, cfg


def _gen_ref(params, cfg, prompt, n):
    from mxnet_tpu.models import gpt as G
    return np.asarray(G.generate(params, cfg, prompt[None, :], n))[0]


# ===========================================================================
# FAST tier — wire layer
# ===========================================================================

def test_raw_frame_roundtrip():
    from mxnet_tpu.parallel.dist import send_frame, recv_frame
    a, b = socket.socketpair()
    try:
        payload = [np.arange(100, dtype=np.int8).data,
                   np.arange(7, dtype=np.float32).data]
        send_frame(a, {"kind": "pages", "n": 2}, payload)
        meta, bufs = recv_frame(b)
        assert meta == {"kind": "pages", "n": 2}
        assert bytes(bufs[0]) == np.arange(100, dtype=np.int8).tobytes()
        assert bytes(bufs[1]) == \
            np.arange(7, dtype=np.float32).tobytes()
        # legacy pickled frames travel the same wire
        from mxnet_tpu.parallel.dist import _send
        _send(a, ("push", "k", 1))
        obj, none = recv_frame(b)
        assert obj == ("push", "k", 1) and none is None
    finally:
        a.close()
        b.close()


def test_recv_bounds_garbage_length_prefix():
    """A garbage/oversized length prefix (peer killed mid-frame, or a
    foreign protocol) must raise, not allocate gigabytes."""
    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.parallel.dist import _recv, recv_frame, \
        MAX_FRAME_BYTES
    for reader in (_recv, recv_frame):
        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack("<Q", MAX_FRAME_BYTES + 1))
            with pytest.raises(MXNetError, match="length"):
                reader(b)
        finally:
            a.close()
            b.close()
    # a raw frame on the kvstore's pickled-only path is also an error
    a, b = socket.socketpair()
    try:
        from mxnet_tpu.parallel.dist import send_frame
        send_frame(a, {"kind": "x"}, [])
        with pytest.raises(MXNetError, match="raw frame"):
            _recv(b)
    finally:
        a.close()
        b.close()


def test_recv_peer_closed_mid_frame_reads_as_eof():
    """Half a frame then an abortive close (the SIGKILL shape) must
    read as EOF (None), not an exception racing __del__."""
    from mxnet_tpu.parallel.dist import recv_frame
    a, b = socket.socketpair()
    try:
        a.sendall(struct.pack("<Q", 1000) + b"x" * 10)  # 990 short
        # abortive close: RST instead of FIN, like a killed process
        a.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                     struct.pack("ii", 1, 0))
        a.close()
        assert recv_frame(b) is None
    finally:
        b.close()


@pytest.mark.slow
def test_dist_kvstore_survives_server_sigkill_mid_frame():
    """Satellite regression: a kvstore worker whose server process is
    SIGKILLed mid-traffic must surface the failure at a sync point
    (deferred-error contract), and close()/__del__ must be safe —
    no hang, no exception out of the destructor."""
    import mxnet_tpu as mx
    from mxnet_tpu.parallel.dist import DistKVStore
    port_probe = socket.socket()
    port_probe.bind(("127.0.0.1", 0))
    port = port_probe.getsockname()[1]
    port_probe.close()
    server = subprocess.Popen(
        [sys.executable, "-c",
         "from mxnet_tpu.parallel.dist import DistServer;"
         "s = DistServer(port=%d, num_workers=1, sync_mode=True);"
         "s.serve_forever()" % port],
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": REPO},
        cwd=REPO)
    old = dict(os.environ)
    os.environ.update({"DMLC_PS_ROOT_URI": "127.0.0.1",
                       "DMLC_PS_ROOT_PORT": str(port),
                       "DMLC_NUM_WORKER": "1", "DMLC_WORKER_ID": "0"})
    try:
        kv = DistKVStore("dist_sync")
        kv.init("w", mx.nd.zeros((4,)))
        os.kill(server.pid, signal.SIGKILL)
        server.wait(timeout=30)
        # pushes after the kill die on the wire; the error must
        # surface at the next sync op, not crash the sender thread
        with pytest.raises(mx.MXNetError):
            for _ in range(50):
                kv.push("w", mx.nd.ones((4,)))
                kv.barrier()
        t0 = time.perf_counter()
        kv.close()                        # bounded, no hang
        assert time.perf_counter() - t0 < 15
        kv.__del__()                      # destructor must not raise
    finally:
        os.environ.clear()
        os.environ.update(old)
        if server.poll() is None:
            server.kill()


# ===========================================================================
# FAST tier — page transfer + index
# ===========================================================================

def _fill_pages(cache, ids, seed=0):
    import jax
    import jax.numpy as jnp
    rng = np.random.RandomState(seed)
    pools = []
    for pool in cache.pools:
        lay = {}
        for k, v in pool.items():
            a = np.asarray(jax.device_get(v)).copy()
            a[ids] = rng.randint(-100, 100,
                                 a[ids].shape).astype(a.dtype)
            lay[k] = jnp.asarray(a)
        pools.append(lay)
    cache.pools = pools


@pytest.mark.parametrize("kv_int8", [False, True])
def test_page_export_install_roundtrip(kv_int8):
    from mxnet_tpu.models import gpt as G
    from mxnet_tpu.serving.paged_kv import PagedKVCache
    from mxnet_tpu.serving.page_streamer import pages_to_bufs, \
        bufs_to_pages, page_wire_bytes
    cfg = G.gpt_tiny()
    src = PagedKVCache(cfg, 9, 4, kv_int8=kv_int8)
    _fill_pages(src, [1, 2, 5])
    content = src.export_pages([1, 2, 5])
    # the wire layout: raw buffers, byte count == pool bytes
    bufs = pages_to_bufs(content)
    assert sum(memoryview(b).nbytes for b in bufs) == \
        page_wire_bytes(src, 3)
    dst = PagedKVCache(cfg, 9, 4, kv_int8=kv_int8)
    ids = dst.alloc(3)
    dst.install_pages(ids, bufs_to_pages(dst, 3, bufs))
    back = dst.export_pages(ids)
    for l1, l2 in zip(content, back):
        for k in l1:
            assert np.array_equal(np.asarray(l1[k]),
                                  np.asarray(l2[k]))


def test_install_pages_validates_shape():
    from mxnet_tpu.models import gpt as G
    from mxnet_tpu.serving.paged_kv import PagedKVCache
    cfg = G.gpt_tiny()
    c = PagedKVCache(cfg, 5, 4)
    content = c.export_pages([1, 2])
    with pytest.raises(ValueError, match="does not match"):
        c.install_pages([1], content)     # 2 pages of content, 1 id
    with pytest.raises(ValueError, match="layers"):
        c.install_pages([1, 2], content[:-1])


def test_transport_tree_roundtrip():
    from mxnet_tpu.serving.transport import tree_to_frames, \
        frames_to_tree
    tree = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
            "layers": [{"w": np.ones((2, 2), np.int8)},
                       {"w": np.zeros((1,), np.float64)}]}
    meta, bufs = tree_to_frames(tree)
    back = frames_to_tree(meta, [bytearray(b) for b in bufs])
    assert np.array_equal(back["a"], tree["a"])
    assert back["layers"][0]["w"].dtype == np.int8
    assert np.array_equal(back["layers"][1]["w"],
                          tree["layers"][1]["w"])


def test_put_transport_segment_roundtrip():
    """Round 22 put-path primitives: ``put_write`` lands buffers in
    one pid-prefixed shm segment, ``put_read`` maps them back
    byte-identical AND unlinks at open (on-disk segments exist only
    in flight), ``release`` is idempotent and balances the open
    counter, and ``put_sweep`` reclaims an unreceived segment by its
    writer's pid."""
    import glob
    from mxnet_tpu.serving.transport import (
        PUT_DIR, PUT_STATS, put_read, put_sweep, put_write)

    bufs = [np.arange(64, dtype=np.float32).tobytes(),
            np.arange(5, dtype=np.int8).tobytes()]
    path, sizes = put_write(bufs)
    assert os.path.exists(path) and sizes == [256, 5]
    assert str(os.getpid()) in os.path.basename(path)
    got = put_read(path, sizes)
    assert not os.path.exists(path)       # unlinked AT open
    assert bytes(got[0]) == bufs[0] and bytes(got[1]) == bufs[1]
    opens, rels = PUT_STATS["opens"], PUT_STATS["releases"]
    got.release()
    got.release()                         # idempotent
    assert PUT_STATS["releases"] == rels + 1
    assert PUT_STATS["opens"] == opens
    # a never-received segment sweeps by pid (the SIGKILL-recovery
    # path the router runs for a killed worker)
    path2, _ = put_write(bufs)
    assert put_sweep(os.getpid()) >= 1
    assert not os.path.exists(path2)
    assert not glob.glob(os.path.join(
        PUT_DIR, "mxserve-put-%d-*" % os.getpid()))


def test_put_capability_negotiation():
    """Eligibility is strictly both-sides-advertised + same shm
    domain; MXNET_SERVE_TRANSPORT=socket kills the advertisement
    entirely (the negotiated fallback every mismatch takes)."""
    from mxnet_tpu.serving.transport import (put_capability,
                                             put_eligible)
    mine = put_capability()
    assert mine is not None and mine["put_pages"]
    assert put_eligible(mine, dict(mine))
    assert not put_eligible(mine, None)
    assert not put_eligible(None, dict(mine))
    assert not put_eligible(mine, dict(mine, host="elsewhere"))
    assert not put_eligible(mine, dict(mine, put_pages=False))
    old = os.environ.get("MXNET_SERVE_TRANSPORT")
    os.environ["MXNET_SERVE_TRANSPORT"] = "socket"
    try:
        assert put_capability() is None
    finally:
        if old is None:
            del os.environ["MXNET_SERVE_TRANSPORT"]
        else:
            os.environ["MXNET_SERVE_TRANSPORT"] = old


def test_put_transport_conn_handshake_and_frames():
    """A live socket pair: caps frames record the peer capability on
    the connection, a put-carrying frame materializes as zero-copy
    views (body bytes NOT on the socket), and the receiver's recv
    unlinked the segment."""
    from mxnet_tpu.serving.transport import (Connection, Listener,
                                             connect, put_write)
    accepted, frames = [], []
    evt = threading.Event()

    def handler(conn):
        conn.send_caps()
        accepted.append(conn)
        evt.set()
        while True:
            got = conn.recv()
            if got is None:
                return
            frames.append(got)

    lis = Listener().start(handler)
    try:
        c = connect(lis.host, lis.port)
        c.send_caps()
        caps = c.wait_caps(timeout=5.0)
        assert caps is not None and caps["put_pages"]
        assert evt.wait(5.0)
        payload = [b"x" * 4096, b"y" * 128]
        path, sizes = put_write(payload)
        before = c.bytes_sent
        c.send("pages", {"srid": (1, 0), "start": 0, "n": 1,
                         "put": {"path": path, "sizes": sizes}}, ())
        # body did NOT ride the socket: only the header went out
        assert c.bytes_sent == before
        srv = accepted[0]
        deadline = time.time() + 5
        while len(frames) < 2 and time.time() < deadline:
            time.sleep(0.01)
        assert srv.peer_put is not None   # our caps recorded over there
        kind, meta, bufs = frames[-1]
        assert kind == "pages" and meta["n"] == 1
        assert bytes(bufs[0]) == payload[0]
        assert bytes(bufs[1]) == payload[1]
        assert not os.path.exists(path)   # receiver unlinked at open
        bufs.release()
    finally:
        lis.close()


def test_page_receiver_releases_held_put_segments():
    """The unified hold representation: a pool-dry hold keeps the
    transport's buffers AS DELIVERED (no downgrade copy), and abort
    releases put-backed holds — segment lifetime is bounded by
    staging lifetime."""
    from mxnet_tpu.serving.page_streamer import PageReceiver

    class _Bufs(list):
        def __init__(self, it):
            super().__init__(it)
            self.released = False

        def release(self):
            self.released = True

    class _Cache:
        def alloc(self, n):
            return None                   # pool permanently dry

        def free(self, ids):
            pass

    class _Eng:
        cache = _Cache()

    rec = PageReceiver(_Eng())
    held = _Bufs([b"a", b"b"])
    rec.on_pages((7, 0), 0, 1, held)
    assert rec._staged[(7, 0)].held[0][1] is held   # no copy
    assert not held.released
    rec.abort((7, 0))
    assert held.released


def test_cluster_prefix_index_semantics():
    from mxnet_tpu.serving import ClusterPrefixIndex
    idx = ClusterPrefixIndex()
    k = [b"a", b"ab", b"abc"]
    assert idx.match(k) == (None, 0, None)
    idx.report_insert("p0", k[:2])
    assert idx.match(k) == ("p0", 2, "hbm")
    # first-inserter-wins: p1's duplicate insert does not steal keys
    idx.report_insert("p1", k)
    assert idx.match(k) == ("p0", 2, "hbm")  # k[2] now p1's, but chain
    # eviction only by the owner
    idx.report_evict("p1", [k[0]])
    assert idx.match(k) == ("p0", 2, "hbm")
    idx.report_evict("p0", [k[0]])
    assert idx.match(k) == (None, 0, None)   # chain head gone
    # a dead replica's keys drop wholesale
    idx.report_insert("p0", k)
    idx.drop_owner("p0")
    owner, d, _ = idx.match(k)
    assert owner in (None, "p1")          # p1 still owns k[2] only
    assert idx.match([k[2]]) == ("p1", 1, "hbm")


def test_cluster_prefix_index_tier_tags():
    """Round 18: per-key tier tags — only the owner may re-tag, a
    chain with any host-tier page summarizes as 'host', eviction and
    owner death clear the tags."""
    from mxnet_tpu.serving import ClusterPrefixIndex
    idx = ClusterPrefixIndex()
    k = [b"a", b"ab", b"abc"]
    idx.report_insert("p0", k)
    assert idx.match(k) == ("p0", 3, "hbm")
    # leaf spilled: the chain summary flips to host
    idx.report_tier("p0", [k[2]], "host")
    assert idx.match(k) == ("p0", 3, "host")
    assert idx.match(k[:2]) == ("p0", 2, "hbm")
    # a non-owner's re-tag is ignored
    idx.report_tier("p1", [k[0]], "host")
    assert idx.match(k[:1]) == ("p0", 1, "hbm")
    # warm restore re-tags back
    idx.report_tier("p0", [k[2]], "hbm")
    assert idx.match(k) == ("p0", 3, "hbm")
    assert idx.keys_retagged_total == 2
    # a real eviction clears key AND tag; a later insert is hbm again
    idx.report_tier("p0", [k[2]], "host")
    idx.report_evict("p0", [k[2]])
    idx.report_insert("p0", [k[2]])
    assert idx.match(k) == ("p0", 3, "hbm")
    import pytest
    with pytest.raises(ValueError):
        idx.report_tier("p0", [k[0]], "warm")


def test_admit_prefilled_adopts_handoff_exactly():
    """In-process simulation of the cross-process handoff: engine A
    prefills (1-token budget), its retire-snapshot pages export;
    engine B installs them and adopts the request mid-decode —
    output must be bit-identical to the ``generate`` oracle."""
    from mxnet_tpu.serving import ServingEngine
    from mxnet_tpu.serving.page_streamer import pages_to_bufs, \
        bufs_to_pages
    params, cfg = _tiny()
    rng = np.random.RandomState(0)
    prompt = rng.randint(1, cfg.vocab_size, 13).astype(np.int32)
    n_new = 7

    snap = {}
    A = ServingEngine(params, cfg, num_slots=2, page_size=4,
                      prefix_cache=True)
    A.retire_cb = lambda req: snap.update(
        pages=list(req.pages), n_cached=req.n_cached)
    rid = A.submit(prompt, 1)
    A.run()
    t0 = int(A.requests[rid].generated[0])
    n_pages = -(-snap["n_cached"] // A.page_size)
    bufs = pages_to_bufs(A.cache.export_pages(
        snap["pages"][:n_pages]))

    B = ServingEngine(params, cfg, num_slots=2, page_size=4)
    ids = B.cache.alloc(n_pages)
    B.cache.install_pages(ids, bufs_to_pages(B.cache, n_pages, bufs))
    erid = B.admit_prefilled(prompt, [t0], ids,
                             max_new_tokens=n_new)
    B.run()
    out = B.requests[erid].output
    assert np.array_equal(out, _gen_ref(params, cfg, prompt, n_new))
    # no leaks: the adopted request retired and recycled its pages
    assert B.cache.pages_in_use == 0


def test_admit_prefilled_validation():
    from mxnet_tpu.serving import ServingEngine
    params, cfg = _tiny()
    eng = ServingEngine(params, cfg, num_slots=1, page_size=4)
    with pytest.raises(ValueError, match="committed token"):
        eng.admit_prefilled(np.ones(4, np.int32), [], [1],
                            max_new_tokens=2)
    with pytest.raises(ValueError, match="cannot cover"):
        eng.admit_prefilled(np.ones(9, np.int32), [5], [1],
                            max_new_tokens=2)


# ===========================================================================
# SLOW tier (group j) — whole-process disaggregated clusters
# ===========================================================================

def _cluster(params, cfg, **kw):
    from mxnet_tpu.serving import DisaggServingCluster
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("metrics", True)
    kw.setdefault("watchdog_s", 60.0)
    return DisaggServingCluster(params, cfg, **kw)


def _leak_check(cl):
    """Zero leaked pages/refs on every surviving worker: allocated
    pages are exactly the prefix trie's cached pages (prefill) or
    nothing (decode), no dangling refs, no staged streams."""
    for name, st in cl.cluster_stats().items():
        assert st["pages_in_use"] - st["prefix_cached_pages"] == 0, \
            (name, st)
        assert st["prefix_refs"] == 0, (name, st)
        assert st["staged_rids"] == 0, (name, st)
        assert st["active_requests"] == 0, (name, st)


@pytest.mark.slow
def test_disagg_identity_mixed_lengths():
    """Two OS processes (1 prefill + 1 decode) exchanging KV pages:
    f32-greedy outputs bit-identical to single-engine ``generate``
    across mixed prompt/output lengths."""
    params, cfg = _tiny()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size, int(P)).astype(np.int32)
               for P in (5, 9, 17, 3, 21, 12)]
    nnew = [6, 4, 8, 5, 11, 1]            # incl. a 1-token request
    cl = _cluster(params, cfg, prefill=1, decode=1)
    try:
        assert len({w.proc.pid for w in cl.workers.values()}) == 2
        rids = [cl.submit(p, n) for p, n in zip(prompts, nnew)]
        for rid, p, n in zip(rids, prompts, nnew):
            out = cl.result(rid, timeout=180)
            assert np.array_equal(out, _gen_ref(params, cfg, p, n))
        st = cl.cluster_stats()
        assert st["prefill0"]["pages_streamed"] > 0
        assert st["decode0"]["pages_installed"] > 0
        assert st["decode0"]["decode_rows"] > 0
        # the decode side never prefilled anything (no preemption in
        # this sizing): the split is real, not a fallback
        assert st["decode0"]["prefill_rows"] == 0
        _leak_check(cl)
    finally:
        cl.close()


@pytest.mark.slow
def test_disagg_remote_prefix_prefilled_once_per_cluster():
    """K requests sharing a prefix, spread across 2 prefill
    processes: the prefix is COLD-prefilled exactly once cluster-wide
    — the other replica fetches the pages (remote hit), every later
    request hits locally.  Reconciled via the
    serving_prefix_remote_hits_total counter AND per-worker prefill
    row counts; outputs stay exact."""
    params, cfg = _tiny()
    rng = np.random.RandomState(0)
    ps = 4
    shared = rng.randint(1, cfg.vocab_size, 2 * ps).astype(np.int32)
    tails = [rng.randint(1, cfg.vocab_size, 3).astype(np.int32)
             for _ in range(6)]
    prompts = [np.concatenate([shared, t]) for t in tails]
    cl = _cluster(params, cfg, prefill=2, decode=1, page_size=ps)
    try:
        # sequential submits: round-robin alternates the two prefill
        # workers, so the shared prefix MUST cross the process
        # boundary by request 2
        for p in prompts:
            out = cl.result(cl.submit(p, 4), timeout=180)
            assert np.array_equal(out, _gen_ref(params, cfg, p, 4))
        st = cl.cluster_stats()
        hits = sum(v.get("remote_hits", 0) for v in st.values())
        hit_toks = sum(v.get("remote_hit_tokens", 0)
                       for v in st.values())
        assert hits == 1, st              # fetched exactly once
        assert hit_toks == shared.size
        # prefill-row reconciliation: the shared prefix's rows were
        # paid once cluster-wide.  Every request = prefix (8) + tail
        # (3) + 0 extra rows; each worker pays the prefix rows at
        # most... exactly once would be 8; the remote-hit worker pays
        # zero.  Total rows = sum(prompts) - (K-1)*prefix_len -
        # (whatever partial-page tail reuse matched, >= 0).
        total_rows = sum(v["prefill_rows"] for v in st.values()
                         if v["role"] == "prefill")
        cold_total = sum(p.size for p in prompts)
        saved = cold_total - total_rows
        assert saved >= (len(prompts) - 1) * shared.size, st
        # router counters agree with the worker-side totals
        snap = cl.registry.snapshot()["counters"]
        assert snap["serving_prefix_remote_hits_total"] == 1
        assert snap["serving_prefix_remote_hit_tokens_total"] == \
            shared.size
        assert snap["cluster_page_bytes_streamed_total"] > 0
        _leak_check(cl)
    finally:
        cl.close()


def _wait_mid_decode(cl, timeout=90):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        with cl._lock:
            if any(r.state == "running" and r.phase == "decode"
                   and 0 < len(r.committed) < r.max_new_tokens
                   for r in cl.requests.values()):
                return True
        time.sleep(0.005)
    return False


def _wait_mid_prefill(cl, timeout=90):
    """True once some request is still in the prefill phase with
    pages already streamed (the mid-stream kill window)."""
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        with cl._lock:
            streaming = any(r.state == "running"
                            and r.phase == "prefill"
                            for r in cl.requests.values())
        if streaming:
            return True
        time.sleep(0.002)
    return False


@pytest.mark.slow
def test_disagg_sigkill_prefill_mid_stream():
    """SIGKILL (not a raised exception) of a whole prefill process
    mid-stream: every in-flight request completes recompute-exact on
    the survivors, zero leaked pages/refs."""
    params, cfg = _tiny()
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, cfg.vocab_size,
                           int(P)).astype(np.int32)
               for P in rng.choice([9, 14, 21, 30], 10)]
    nnew = [int(n) for n in rng.choice([6, 10, 16], 10)]
    cl = _cluster(params, cfg, prefill=2, decode=1, watchdog_s=30.0)
    try:
        rids = [cl.submit(p, n) for p, n in zip(prompts, nnew)]
        assert _wait_mid_prefill(cl), "no prefill in flight to kill"
        cl.kill_worker("prefill0")
        for rid, p, n in zip(rids, prompts, nnew):
            out = cl.result(rid, timeout=180)
            assert np.array_equal(out, _gen_ref(params, cfg, p, n))
        snap = cl.registry.snapshot()["counters"]
        assert snap["cluster_failovers_total"] >= 1
        assert not cl.workers["prefill0"].proc.is_alive()
        _leak_check(cl)
    finally:
        cl.close()


@pytest.mark.slow
def test_disagg_sigkill_decode_mid_decode():
    """SIGKILL of a whole decode process while requests are actively
    decoding: the router's streamed committed tokens resubmit as
    prompt extension (recompute-exact) and every output stays
    bit-identical to the oracle."""
    params, cfg = _tiny()
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, cfg.vocab_size,
                           int(P)).astype(np.int32)
               for P in rng.choice([5, 9, 14, 21], 8)]
    nnew = [48] * 8                       # long decodes: a real window
    cl = _cluster(params, cfg, prefill=1, decode=2, watchdog_s=30.0)
    try:
        rids = [cl.submit(p, n) for p, n in zip(prompts, nnew)]
        assert _wait_mid_decode(cl), "no request caught mid-decode"
        cl.kill_worker("decode0")
        for rid, p, n in zip(rids, prompts, nnew):
            out = cl.result(rid, timeout=180)
            assert np.array_equal(out, _gen_ref(params, cfg, p, n))
        snap = cl.registry.snapshot()["counters"]
        assert snap["cluster_failovers_total"] >= 1
        assert snap["cluster_requests_resubmitted_total"] >= 1
        _leak_check(cl)
    finally:
        cl.close()


@pytest.mark.slow
def test_disagg_preemption_resume_exact():
    """A decode pool too small for the whole batch forces
    preemption + recompute-exact resume ON THE DECODE SIDE (its local
    re-prefill path) — outputs stay bit-identical."""
    params, cfg = _tiny()
    rng = np.random.RandomState(2)
    prompts = [rng.randint(1, cfg.vocab_size, 17).astype(np.int32)
               for _ in range(6)]
    n_new = 24
    # 4 slots x ceil((17+24)/4)=11 pages would want 44; give 25 so
    # concurrent decodes exhaust the pool and preempt
    cl = _cluster(params, cfg, prefill=1, decode=1, num_slots=4,
                  pages_per_slot=11, num_pages=25)
    try:
        rids = [cl.submit(p, n_new) for p in prompts]
        for rid, p in zip(rids, prompts):
            out = cl.result(rid, timeout=240)
            assert np.array_equal(out,
                                  _gen_ref(params, cfg, p, n_new))
        st = cl.cluster_stats()
        assert st["decode0"]["preemptions"] > 0, \
            "pool sizing failed to force a preemption"
        # the decode side re-prefilled its preemption victims locally
        assert st["decode0"]["prefill_rows"] > 0
        _leak_check(cl)
    finally:
        cl.close()


@pytest.mark.slow
def test_disagg_put_vs_socket_transport_bit_identical():
    """Round 22 tentpole pin: the same workload forced over the
    /dev/shm put transport and over plain socket frames produces
    BIT-IDENTICAL outputs (both equal to ``generate``), the put run
    really put (pages_put == pages_streamed on the prefill side, 0 on
    the socket run), zero page/ref leaks on both ends, and zero put
    segments left on disk after either run."""
    import glob
    from mxnet_tpu.serving.transport import PUT_DIR

    params, cfg = _tiny()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size, int(P)).astype(np.int32)
               for P in (5, 9, 17, 3)]
    nnew = [6, 4, 8, 5]
    outs = {}
    old = os.environ.get("MXNET_SERVE_TRANSPORT")
    try:
        for mode in ("put", "socket"):
            os.environ["MXNET_SERVE_TRANSPORT"] = mode
            cl = _cluster(params, cfg, prefill=1, decode=1)
            try:
                rids = [cl.submit(p, n)
                        for p, n in zip(prompts, nnew)]
                outs[mode] = [cl.result(r, timeout=180)
                              for r in rids]
                st = cl.cluster_stats()
                if mode == "put":
                    assert st["prefill0"]["pages_put"] == \
                        st["prefill0"]["pages_streamed"] > 0
                    assert st["prefill0"]["put_bytes"] > 0
                else:
                    assert st["prefill0"]["pages_put"] == 0
                assert st["decode0"]["pages_installed"] > 0
                _leak_check(cl)
            finally:
                cl.close()
            assert not glob.glob(
                os.path.join(PUT_DIR, "mxserve-put-*")), \
                "put segments left on disk after %s run" % mode
    finally:
        if old is None:
            os.environ.pop("MXNET_SERVE_TRANSPORT", None)
        else:
            os.environ["MXNET_SERVE_TRANSPORT"] = old
    for a, b, p, n in zip(outs["put"], outs["socket"], prompts, nnew):
        assert np.array_equal(a, b)       # transport-invariant bytes
        assert np.array_equal(a, _gen_ref(params, cfg, p, n))


@pytest.mark.slow
def test_disagg_int8_kv_pages_transfer_exactly():
    """int8-KV mode: quantized pages + f32 scale pages stream in the
    int8 page-pool wire layout, and the disaggregated output is
    BIT-identical to a single engine in the same int8 mode (the
    transfer is lossless; int8-vs-f32 is the engine's own caveat,
    not the wire's)."""
    from mxnet_tpu.serving import ServingEngine
    params, cfg = _tiny()
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, cfg.vocab_size, int(P)).astype(np.int32)
               for P in (7, 13, 18)]
    n_new = 9
    ref_eng = ServingEngine(params, cfg, num_slots=4, page_size=4,
                            kv_int8=True)
    refs = {}
    for p in prompts:
        rid = ref_eng.submit(p, n_new)
        refs[rid] = p
    ref_out = ref_eng.run()
    ref_by_prompt = {refs[rid].tobytes(): out
                     for rid, out in ref_out.items()}
    cl = _cluster(params, cfg, prefill=1, decode=1, kv_int8=True)
    try:
        rids = [cl.submit(p, n_new) for p in prompts]
        for rid, p in zip(rids, prompts):
            out = cl.result(rid, timeout=180)
            assert np.array_equal(out, ref_by_prompt[p.tobytes()])
        st = cl.cluster_stats()
        # int8 pages are ~4x smaller than f32 (+ scale pages): wire
        # bytes must match the int8 pool layout exactly
        from mxnet_tpu.serving.paged_kv import PagedKVCache
        probe = PagedKVCache(cfg, 2, 4, kv_int8=True)
        assert st["prefill0"]["bytes_streamed"] == \
            st["prefill0"]["pages_streamed"] * probe.bytes_per_page
        _leak_check(cl)
    finally:
        cl.close()
