"""Training scale-out tests (round 19, ROADMAP item 5): FSDP through
the mesh, the ICI-allreduce KVStore as the gradient-sync substrate, and
the exactness protocols the train-scale bench gates on.

Fast tier: mesh-free spec declarations, rule-table coverage, error
surfaces, optimizer sharded-state init, the DataParallelTrainer
zero-host-transfer regression.  Slow tier (group m): multi-device FSDP
byte accounting against live ``addressable_shards``, FSDP-vs-unsharded
trajectory equivalence, FSDP×tp composition, and the dp=2 BERT-grad
bit-identity protocol through the ICI store.
"""
import numpy as np
import pytest

import mxnet_tpu as mx


def _tiny_cfg(**kw):
    from mxnet_tpu.models import transformer as T
    base = dict(use_flash=False, remat=False, dropout=0.0)
    base.update(kw)
    return T.bert_tiny(**base)


def _mlm_batch(cfg, B=16, T_len=32, seed=2):
    import jax
    import jax.numpy as jnp
    tokens = jax.random.randint(jax.random.PRNGKey(seed), (B, T_len), 0,
                                cfg.vocab_size)
    labels = jnp.where(jnp.arange(T_len)[None] % 5 == 0, tokens, -100)
    return {"tokens": tokens, "labels": labels,
            "mask": jnp.ones((B, T_len), bool)}


# ---------------------------------------------------------------------------
# fast tier
# ---------------------------------------------------------------------------

def test_fsdp_rules_cover_every_param():
    """The SNIPPETS [3] contract: every param leaf matches a rule, an
    invented leaf raises (silent replication is how FSDP quietly stops
    being FSDP), and MoE configs are refused loudly."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.parallel.fsdp import (fsdp_rules,
                                         match_partition_rules,
                                         fsdp_param_specs)
    cfg = _tiny_cfg()
    shapes = jax.eval_shape(
        lambda: T.init_params(jax.random.PRNGKey(0), cfg))
    triples = match_partition_rules(fsdp_rules(), shapes)
    assert len(triples) == len(jax.tree_util.tree_leaves(shapes))
    with pytest.raises(mx.MXNetError, match="no partition rule"):
        match_partition_rules(fsdp_rules(), {"brand_new_table": shapes[
            "tok_emb"]})
    with pytest.raises(mx.MXNetError, match="MoE"):
        fsdp_param_specs(_tiny_cfg(n_experts=2, moe_every=1))


def test_fsdp_specs_compose_with_megatron_table():
    """dp lands on the dim the tp rule leaves free; with tp live the
    two stack (tp partitions first, dp subdivides)."""
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel.fsdp import fsdp_param_specs
    cfg = _tiny_cfg()
    sp = fsdp_param_specs(cfg)
    assert sp["layers"][0]["wq"] == P("dp", None)
    assert sp["layers"][0]["wo"] == P(None, "dp")
    assert sp["type_emb"] == P(None, "dp")
    assert sp["layers"][0]["ln1"]["g"] == P("dp")
    sp_tp = fsdp_param_specs(cfg, tp="tp")
    assert sp_tp["layers"][0]["wq"] == P("dp", "tp")
    assert sp_tp["layers"][0]["wo"] == P("tp", "dp")
    assert sp_tp["layers"][0]["bq"] == P(("tp", "dp"))
    assert sp_tp["type_emb"] == P(None, ("tp", "dp"))


def test_train_step_specs_declared_and_audited():
    """The declared train-step in/out specs exist mesh-free (the
    serving ``step_input_specs`` convention) and graphlint's
    independent derivation agrees — the tier-1 wiring of the
    ROADMAP-5 closing criterion."""
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.models import transformer as T
    from tools.analysis import graphlint
    cfg = _tiny_cfg()
    pspecs, batch, rng = T.train_step_input_specs(cfg, tp="tp")
    assert batch["tokens"] == P("dp", None)
    assert rng == P()
    out_p, out_loss = T.train_step_output_specs(cfg, tp="tp")
    assert out_p == pspecs and out_loss == P()
    assert graphlint.train_sharding_readiness_findings(".") == []
    _, counts = graphlint._train_sharding_rows(cfg)
    assert counts["uncovered"] == 0 and counts["mismatched"] == 0
    assert counts["covered"] > 20


def test_train_audit_catches_drifted_declaration(monkeypatch):
    """A drifted declaration (params suddenly replicated) fires the
    train half of graph-sharding-readiness — the rule genuinely
    guards the declaration, PR-4/7/8 convention."""
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.models import transformer as T
    from tools.analysis import graphlint
    real = T.train_step_input_specs

    def drifted(cfg, dp="dp", tp=None, fsdp=True):
        pspecs, batch, rng = real(cfg, dp=dp, tp=tp, fsdp=fsdp)
        pspecs = jax.tree_util.tree_map(
            lambda s: P(), pspecs, is_leaf=lambda x: isinstance(x, P))
        return pspecs, batch, rng

    monkeypatch.setattr(T, "train_step_input_specs", drifted)
    fs = graphlint.train_sharding_readiness_findings(".")
    assert any(f.symbol == "train_step_input_specs.mismatch"
               for f in fs), [str(f) for f in fs]
    assert all(f.path == "mxnet_tpu/models/transformer.py"
               for f in fs)


def test_fsdp_requires_live_dp_axis():
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.fsdp import fsdp_param_shardings
    cfg = _tiny_cfg()
    with pytest.raises(mx.MXNetError, match="live 'dp' axis"):
        T.make_train_step(cfg, mesh=None, fsdp=True)
    with pytest.raises(mx.MXNetError, match="live"):
        fsdp_param_shardings(cfg, make_mesh({"tp": 8}))


def test_bucket_overlap_validation():
    """Round 21: bucket_overlap is fenced to the configs where the
    homogeneous layer scan is sound — requires fsdp, refuses bogus
    values, MoE stacks, and seq-parallel configs."""
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.parallel import make_mesh
    cfg = _tiny_cfg()
    mesh = make_mesh({"dp": 8})
    with pytest.raises(mx.MXNetError, match="must be False, True"):
        T.make_train_step(cfg, mesh=mesh, fsdp=True,
                          bucket_overlap="yes")
    with pytest.raises(mx.MXNetError, match="requires fsdp=True"):
        T.make_train_step(cfg, mesh=mesh, bucket_overlap=True)
    with pytest.raises(mx.MXNetError, match="homogeneous"):
        T.make_train_step(_tiny_cfg(n_experts=2), mesh=mesh,
                          fsdp=True, bucket_overlap=True)
    with pytest.raises(mx.MXNetError, match="homogeneous"):
        T.make_train_step(_tiny_cfg(seq_parallel=True), mesh=mesh,
                          fsdp=True, bucket_overlap=True)


def test_optimizer_state_zeros_matches_weight_sharding():
    """optimizer.state_zeros: a mesh-sharded weight gets its moments
    allocated directly INTO the same sharding (no init-then-reshard
    peak, no per-update reshard); single-device weights keep the
    reference ctx behavior."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxnet_tpu.ndarray.ndarray import NDArray
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.optimizer.optimizer import state_zeros
    mesh = make_mesh({"dp": 8})
    w = jax.device_put(jnp.ones((64, 16)),
                       NamedSharding(mesh, P("dp", None)))
    s = state_zeros(NDArray(w))
    assert s._data.sharding == w.sharding
    assert s._data.addressable_shards[0].data.shape == (8, 16)
    # and the Adam updater path creates sharded moments from it
    opt = mx.optimizer.Adam(learning_rate=0.1)
    mu, nu = opt.create_state(0, NDArray(w))
    assert mu._data.sharding == w.sharding
    s2 = state_zeros(mx.nd.ones((4,), ctx=mx.tpu(1)))
    assert s2.context == mx.tpu(1)


def test_dpt_steady_state_step_is_host_transfer_free():
    """Round-19 DataParallelTrainer audit regression pin: with a live
    mesh and device-resident batches, the steady-state step performs
    ZERO host transfers (no param round-trip through host numpy, no
    hidden device_get) — enforced with jax's transfer guard."""
    import jax
    from mxnet_tpu import nd, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import DataParallelTrainer, make_mesh
    from mxnet_tpu.parallel import multihost

    calls = []
    real = multihost.host_staged_put

    def spy(value, sharding):
        calls.append(type(value).__name__)
        return real(value, sharding)

    multihost.host_staged_put = spy
    try:
        np.random.seed(0)
        X = np.random.randn(16, 6).astype("float32")
        Y = X @ np.random.randn(6, 1).astype("float32")
        net = nn.Dense(1, use_bias=False)
        net.initialize(mx.initializer.Zero())
        tr = DataParallelTrainer(net, gluon.loss.L2Loss(), "sgd",
                                 {"learning_rate": 0.05},
                                 mesh=make_mesh({"dp": 8}))
        tr.step(nd.array(X), nd.array(Y))      # build + first step
    finally:
        multihost.host_staged_put = real
    # single-process staging must not have gone through host numpy
    assert "ndarray" not in calls, calls
    dd = jax.device_put(X, tr._batch_sharding)
    ll = jax.device_put(Y, tr._batch_sharding)
    with jax.transfer_guard("disallow"):
        tr.step(dd, ll)
        loss = tr.step(dd, ll)
    assert np.isfinite(float(loss.asnumpy()))


# ---------------------------------------------------------------------------
# slow tier (group m)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fsdp_per_device_bytes_exactly_div_dp():
    """The PR-9 protocol for the train half: per-device param bytes
    and every param-shaped optimizer moment are EXACTLY total/dp,
    asserted against live ``addressable_shards`` (the only replicated
    opt leaf is adamw's scalar step count)."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.fsdp import shard_bytes
    dp = 8
    cfg = _tiny_cfg()
    init_state, _ = T.make_train_step(cfg, mesh=make_mesh({"dp": dp}),
                                      fsdp=True)
    params, opt = init_state(jax.random.PRNGKey(0))
    tot, per = shard_bytes(params)
    assert tot == per * dp, (tot, per)
    for leaf in jax.tree_util.tree_leaves(params):
        n_sh = len({str(sh.index) for sh in leaf.addressable_shards})
        assert n_sh == dp, (leaf.shape, n_sh)
    tot_o, per_o = shard_bytes(opt)
    # everything but the 4-byte scalar count divides exactly
    count_bytes = 4
    assert tot_o - count_bytes == (per_o - count_bytes) * dp, \
        (tot_o, per_o)


@pytest.mark.slow
def test_fsdp_trains_like_unsharded():
    """FSDP changes the placement, not the math: the loss trajectory
    matches the plain replicated-dp step to float tolerance and
    decreases."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.parallel import make_mesh
    cfg = _tiny_cfg()
    batch = _mlm_batch(cfg)
    mesh = make_mesh({"dp": 8})

    def run(fsdp):
        init_state, step = T.make_train_step(cfg, mesh=mesh, fsdp=fsdp,
                                             learning_rate=1e-3)
        state = init_state(jax.random.PRNGKey(0))
        out = []
        for i in range(6):
            state, loss = step(state, batch,
                               jax.random.fold_in(jax.random.PRNGKey(1),
                                                  i))
            out.append(float(loss))
        return out

    fsdp_losses = run(True)
    ref_losses = run(False)
    np.testing.assert_allclose(fsdp_losses, ref_losses, rtol=2e-3,
                               atol=2e-3)
    assert fsdp_losses[-1] < fsdp_losses[0]


@pytest.mark.slow
def test_bucket_overlap_bitwise_vs_fused_and_tracks_legacy():
    """Round 21 HARD GATE: the layer-bucketed reduce-scatter step
    (``bucket_overlap=True``) must be BITWISE identical — losses and
    every updated weight — to its ``"fused"`` comparator (the same
    scan graph with the grad constraint deferred to one post-backward
    sync).  Identical graphs up to collective PLACEMENT is the whole
    claim: overlap moves the reduce-scatters, it may not change a
    single bit.  Against the round-20 autodiff path the scan backward
    is a different (valid) graph, so that comparison is tolerance-
    based, and training must still descend."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.parallel import make_mesh
    cfg = _tiny_cfg()
    batch = _mlm_batch(cfg)
    mesh = make_mesh({"dp": 8})

    def run(bucket_overlap):
        init_state, step = T.make_train_step(cfg, mesh=mesh,
                                             fsdp=True,
                                             learning_rate=1e-3,
                                             bucket_overlap=
                                             bucket_overlap)
        state = init_state(jax.random.PRNGKey(0))
        losses = []
        for i in range(4):
            state, loss = step(state, batch,
                               jax.random.fold_in(
                                   jax.random.PRNGKey(1), i))
            losses.append(float(loss))
        return losses, jax.device_get(state[0])

    bk_losses, bk_params = run(True)
    fu_losses, fu_params = run("fused")
    assert bk_losses == fu_losses, (bk_losses, fu_losses)
    flat_b, _ = jax.tree_util.tree_flatten_with_path(bk_params)
    flat_f = jax.tree_util.tree_leaves(fu_params)
    for (path, leaf_b), leaf_f in zip(flat_b, flat_f):
        assert np.array_equal(np.asarray(leaf_b),
                              np.asarray(leaf_f)), \
            "bucketed != fused at %s" % jax.tree_util.keystr(path)

    legacy_losses, _ = run(False)
    np.testing.assert_allclose(bk_losses, legacy_losses, rtol=2e-3,
                               atol=2e-3)
    assert bk_losses[-1] < bk_losses[0], bk_losses


@pytest.mark.slow
def test_fsdp_composes_with_tensor_parallelism():
    """dp×tp mesh: the same step lowers with stacked (tp, dp) /
    split-dim shardings, trains, and divides the dominant bytes by the
    full mesh size — every 2-D weight splits into tp×dp distinct
    shards; the 1-D vectors the megatron table replicates w.r.t. tp
    shard ÷dp, so the tree total sits strictly below the dp-only
    bound."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.fsdp import shard_bytes
    cfg = _tiny_cfg()
    mesh = make_mesh({"dp": 4, "tp": 2})
    init_state, step = T.make_train_step(cfg, mesh=mesh, fsdp=True,
                                         learning_rate=1e-3)
    state = init_state(jax.random.PRNGKey(0))
    for leaf in jax.tree_util.tree_leaves(state[0]):
        if leaf.ndim >= 2:
            n_sh = len({str(sh.index)
                        for sh in leaf.addressable_shards})
            assert n_sh == 8, (leaf.shape, n_sh)
    tot, per = shard_bytes(state[0])
    assert per < tot / 4, (tot, per)
    batch = _mlm_batch(cfg, B=8)
    losses = []
    for i in range(5):
        state, loss = step(state, batch,
                           jax.random.fold_in(jax.random.PRNGKey(1), i))
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


@pytest.mark.slow
def test_ici_dp2_bert_grad_sync_bit_identical_vs_accumulation():
    """The model-level exactness protocol the bench gates on: per-
    device BERT microbatch grads (the SAME jitted ``mlm_loss`` grad
    program on each device) synced through the ICI store must produce
    a loss trajectory BIT-identical to single-device accumulation of
    the same two microbatches — the dp=2 collective is one order-free
    f32 add per element."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.ndarray.ndarray import NDArray
    cfg = _tiny_cfg(dtype="float32")
    batch = _mlm_batch(cfg, B=8)
    devs = jax.devices()[:2]
    key = jax.random.PRNGKey(3)

    gfn = jax.jit(jax.value_and_grad(
        lambda p, b, r: T.mlm_loss(p, b, r, cfg)))
    upd = jax.jit(lambda p, g, lr: jax.tree_util.tree_map(
        lambda pv, gv: pv - lr * gv, p, g))

    def halves(dev):
        return [jax.tree_util.tree_map(
            lambda x: jax.device_put(x[sl], dev), batch)
            for sl, dev in zip((slice(0, 4), slice(4, 8)), dev)]

    def run_kv():
        kv = mx.kv.create("ici")
        params = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, devs[0]),
            T.init_params(jax.random.PRNGKey(0), cfg))
        flat, treedef = jax.tree_util.tree_flatten(params)
        for i, leaf in enumerate(flat):
            kv.init(i, NDArray(leaf) * 0)
        b0, b1 = halves(devs)
        losses = []
        for step_i in range(3):
            p1 = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, devs[1]), params)
            l0, g0 = gfn(params, b0, key)
            l1, g1 = gfn(p1, b1, key)
            f0 = jax.tree_util.tree_leaves(g0)
            f1 = jax.tree_util.tree_leaves(g1)
            keys = list(range(len(f0)))
            kv.push(keys, [[NDArray(a), NDArray(b)]
                           for a, b in zip(f0, f1)])
            outs = []
            for i in keys:
                o = NDArray(jnp.zeros(f0[i].shape, f0[i].dtype))
                kv.pull(i, out=o)
                outs.append(jax.device_put(o._data, devs[0]))
            gsum = jax.tree_util.tree_unflatten(treedef, outs)
            params = upd(params, gsum, 1e-2)
            losses.append((np.asarray(l0), np.asarray(l1)))
        assert kv.stats()["collectives"] >= 3
        return losses, params

    def run_accum():
        params = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, devs[0]),
            T.init_params(jax.random.PRNGKey(0), cfg))
        b0, b1 = halves((devs[0], devs[0]))
        losses = []
        for step_i in range(3):
            l0, g0 = gfn(params, b0, key)
            l1, g1 = gfn(params, b1, key)
            gsum = jax.tree_util.tree_map(lambda a, b: a + b, g0, g1)
            params = upd(params, gsum, 1e-2)
            losses.append((np.asarray(l0), np.asarray(l1)))
        return losses, params

    kv_losses, kv_params = run_kv()
    acc_losses, acc_params = run_accum()
    for (a0, a1), (b0_, b1_) in zip(kv_losses, acc_losses):
        assert a0.tobytes() == b0_.tobytes()
        assert a1.tobytes() == b1_.tobytes()
    for a, b in zip(jax.tree_util.tree_leaves(kv_params),
                    jax.tree_util.tree_leaves(acc_params)):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


@pytest.mark.slow
def test_ici_bucketed_training_sync_bit_identical():
    """Bucketed vs unbucketed sync of a full bert_tiny gradient set is
    bitwise identical while fusing the per-key collectives into a
    handful of flat ones."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.ndarray.ndarray import NDArray
    cfg = _tiny_cfg(dtype="float32")
    batch = _mlm_batch(cfg, B=8)
    devs = jax.devices()[:2]
    key = jax.random.PRNGKey(3)
    gfn = jax.jit(jax.value_and_grad(
        lambda p, b, r: T.mlm_loss(p, b, r, cfg)))
    params = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, devs[0]),
        T.init_params(jax.random.PRNGKey(0), cfg))
    p1 = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, devs[1]), params)
    b0 = jax.tree_util.tree_map(
        lambda x: jax.device_put(x[:4], devs[0]), batch)
    b1 = jax.tree_util.tree_map(
        lambda x: jax.device_put(x[4:], devs[1]), batch)
    _, g0 = gfn(params, b0, key)
    _, g1 = gfn(p1, b1, key)
    f0 = jax.tree_util.tree_leaves(g0)
    f1 = jax.tree_util.tree_leaves(g1)

    def sync(bucket_bytes):
        kv = mx.kv.create("ici")
        kv.bucket_bytes = bucket_bytes
        keys = list(range(len(f0)))
        for i in keys:
            kv.init(i, NDArray(f0[i]) * 0)
        kv.push(keys, [[NDArray(a), NDArray(b)]
                       for a, b in zip(f0, f1)])
        outs = []
        import jax.numpy as jnp
        for i in keys:
            o = NDArray(jnp.zeros(f0[i].shape, f0[i].dtype))
            kv.pull(i, out=o)
            outs.append(np.asarray(o._data))
        return outs, kv.stats()

    fused, s_fused = sync(4 << 20)
    perkey, s_perkey = sync(0)
    assert s_fused["collectives"] < s_perkey["collectives"], \
        (s_fused, s_perkey)
    assert s_perkey["collectives"] == len(f0)
    for a, b in zip(fused, perkey):
        assert a.tobytes() == b.tobytes()
