"""Continuous-batching serving engine (mxnet_tpu/serving/): paged-KV
greedy decode must be token-identical to ``models/gpt.py generate``
under f32, page recycling must not leak across requests, and
preemption-recompute must stay exact.  Slow tier, group d."""
import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401  (conftest device setup)


def _cfg(**kw):
    from mxnet_tpu.models import gpt
    base = dict(use_flash=False, remat=False, dropout=0.0,
                dtype="float32", vocab_size=128, max_len=64)
    base.update(kw)
    return gpt.gpt_tiny(**base)


def _ref(params, cfg, prompt, n, **kw):
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt
    return np.asarray(
        gpt.generate(params, cfg, jnp.asarray(prompt)[None], n,
                     **kw))[0]


@pytest.mark.slow
def test_paged_greedy_token_identical_mixed_lengths():
    """The exactness pin: every request in a mixed prompt/output-length
    batch decodes token-identically to plain ``generate`` (f32 greedy),
    through admission waves, chunked prefill, and page recycling —
    for float and weight-only-int8 params."""
    import jax
    from mxnet_tpu.models import gpt, transformer as T
    from mxnet_tpu.serving import ServingEngine

    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.RandomState(0)
    shapes = [(5, 8), (3, 12), (9, 4), (2, 6), (7, 10), (4, 9)]
    for p in (params, gpt.quantize_decode_params(params)):
        eng = ServingEngine(p, cfg, num_slots=3, page_size=4,
                            prefill_chunk=6)
        reqs = [(eng.submit(rng.randint(1, 90, P).astype(np.int32), N),
                 N) for P, N in shapes]
        outs = eng.run()
        assert eng.stats["admitted"] == len(shapes)
        for rid, N in reqs:
            req = eng.requests[rid]
            ref = _ref(p, cfg, req.prompt, N)
            np.testing.assert_array_equal(outs[rid], ref)
        # every page returned to the pool after the drain
        assert eng.cache.pages_in_use == 0


@pytest.mark.slow
def test_requests_join_in_flight():
    """Iteration-level batching: a request submitted while others are
    mid-decode joins the running batch and still decodes exactly."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.serving import ServingEngine

    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(5), cfg)
    rng = np.random.RandomState(1)
    eng = ServingEngine(params, cfg, num_slots=3, page_size=4,
                        prefill_chunk=8)
    r1 = eng.submit(rng.randint(1, 90, 6).astype(np.int32), 14)
    r2 = eng.submit(rng.randint(1, 90, 4).astype(np.int32), 10)
    for _ in range(4):
        eng.step()
    # r1/r2 are mid-decode now; r3 joins in flight
    r3 = eng.submit(rng.randint(1, 90, 5).astype(np.int32), 8)
    outs = eng.run()
    for rid, n in ((r1, 14), (r2, 10), (r3, 8)):
        np.testing.assert_array_equal(
            outs[rid], _ref(params, cfg, eng.requests[rid].prompt, n))


@pytest.mark.slow
def test_forced_retire_page_reuse_no_leakage():
    """Page recycling: force-retire a mid-flight request, then admit a
    new one into a single-request-sized pool so it MUST reuse the
    freed pages (no zero-fill on recycle) — its output must equal the
    isolated reference, i.e. no cross-request leakage through stale
    page contents."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.serving import ServingEngine

    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(7), cfg)
    rng = np.random.RandomState(2)
    # pool = exactly one max-length request (+ scratch): a second
    # request's lifetime footprint (5 pages of 5) cannot be served
    # without consuming recycled pages
    eng = ServingEngine(params, cfg, num_slots=2, page_size=4,
                        pages_per_slot=5, num_pages=6, prefill_chunk=8)
    ra = eng.submit(rng.randint(1, 90, 8).astype(np.int32), 12)
    for _ in range(5):
        eng.step()
    req_a = eng.requests[ra]
    assert req_a.state == "running" and len(req_a.generated) > 0
    pages_a = set(req_a.pages)
    assert pages_a
    eng.cancel(ra)                        # forced retire mid-flight
    assert req_a.state == "cancelled"
    assert eng.cache.pages_in_use == 0

    rb = eng.submit(rng.randint(1, 90, 7).astype(np.int32), 12)
    req_b = eng.requests[rb]
    seen_b = set()
    while eng.step() is not False:
        seen_b |= set(req_b.pages)
    # the new request really did sit on recycled pages
    assert seen_b & pages_a, (seen_b, pages_a)
    assert req_b.state == "done"
    np.testing.assert_array_equal(
        req_b.output, _ref(params, cfg, req_b.prompt, 12))


@pytest.mark.slow
def test_preemption_recompute_exact():
    """An over-committed pool preempts the youngest running request
    (pages freed, requeued, committed tokens re-prefilled on
    re-admission) — greedy outputs must stay token-identical for every
    request, preempted or not."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.serving import ServingEngine

    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(9), cfg)
    rng = np.random.RandomState(3)
    eng = ServingEngine(params, cfg, num_slots=4, page_size=4,
                        pages_per_slot=8, num_pages=12,
                        prefill_chunk=4)
    reqs = []
    for P, N in [(6, 20), (4, 24), (8, 16), (3, 22), (5, 18)]:
        rid = eng.submit(rng.randint(1, 90, P).astype(np.int32), N)
        reqs.append((rid, N))
    outs = eng.run()
    assert eng.stats["preemptions"] > 0, \
        "pool was sized to force preemption"
    for rid, N in reqs:
        np.testing.assert_array_equal(
            outs[rid], _ref(params, cfg, eng.requests[rid].prompt, N))
    assert eng.cache.pages_in_use == 0


@pytest.mark.slow
def test_pallas_kernel_token_identical():
    """Round-11 acceptance pin, engine level: the fused Pallas
    paged-attention step (``kernel="pallas"``, interpreter mode on
    CPU) decodes token-identically to plain ``generate`` through a
    mixed-length batch with admission waves — the 1–2 ulp
    online-softmax difference (kernels/paged_attention.py docstring)
    never flips an argmax on this pinned workload.  The broader
    kernel-vs-reference sweep is tier-1
    (tests/test_paged_attention.py); speculation × kernel combos are
    group g (tests/test_serving_spec.py)."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.serving import ServingEngine

    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.RandomState(0)
    shapes = [(5, 8), (3, 12), (9, 4), (2, 6)]
    eng = ServingEngine(params, cfg, num_slots=3, page_size=4,
                        prefill_chunk=6, kernel="pallas")
    reqs = [(eng.submit(rng.randint(1, 90, P).astype(np.int32), N), N)
            for P, N in shapes]
    outs = eng.run()
    for rid, N in reqs:
        np.testing.assert_array_equal(
            outs[rid], _ref(params, cfg, eng.requests[rid].prompt, N))
    assert eng.cache.pages_in_use == 0
    with pytest.raises(ValueError):
        ServingEngine(params, cfg, num_slots=1, page_size=4,
                      kernel="mosaic")


@pytest.mark.slow
def test_paged_int8_kv_agreement():
    """Paged int8-KV (per-(row, token) s8 pages + f32 scale pages)
    tracks contiguous ``generate(kv_int8=True)`` the same way the
    contiguous int8 path tracks fp — greedy agreement, not bit
    equality (page-view gathers reduce in a different order)."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.serving import ServingEngine

    cfg = _cfg(vocab_size=512, d_model=128, n_heads=4, n_layers=3,
               d_ff=256)
    params = T.init_params(jax.random.PRNGKey(11), cfg)
    rng = np.random.RandomState(4)
    eng = ServingEngine(params, cfg, num_slots=2, page_size=4,
                        kv_int8=True, prefill_chunk=8)
    reqs = [eng.submit(rng.randint(1, 500, P).astype(np.int32), 12)
            for P in (5, 7)]
    outs = eng.run()
    for rid in reqs:
        ref = _ref(params, cfg, eng.requests[rid].prompt, 12,
                   kv_int8=True)
        assert (outs[rid] == ref).mean() >= 0.9, (outs[rid], ref)


@pytest.mark.slow
def test_serving_eos_stops_early():
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.serving import ServingEngine

    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(13), cfg)
    prompt = np.arange(1, 6, dtype=np.int32)
    ref = _ref(params, cfg, prompt, 12)
    eos = int(ref[8])                     # a token greedy WILL emit
    eng = ServingEngine(params, cfg, num_slots=1, page_size=4)
    rid = eng.submit(prompt, 12, eos_id=eos)
    outs = eng.run()
    assert outs[rid].size <= ref.size
    assert outs[rid][-1] == eos
    np.testing.assert_array_equal(outs[rid], ref[:outs[rid].size])


@pytest.mark.slow
def test_serve_bench_smoke():
    """CI smoke of the serving bench harness (--quick preset): the e2e
    section must carry both the engine and fixed-batch rows with the
    accounting the gate and docs rely on."""
    import json
    import os
    import sys
    import tempfile
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmark"))
    import serve_bench

    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "serve.json")
        rc = serve_bench.main(["--quick", "--kernel-ablation",
                               "--spec-sweep", "--json", out])
        assert rc == 0
        rows = json.load(open(out))
    e2e = {r["config"].split("_")[0]: r for r in rows
           if r["section"] == "e2e"}
    assert set(e2e) == {"engine", "fixed"}
    eng, base = e2e["engine"], e2e["fixed"]
    assert eng["tok_s"] > 0 and base["tok_s"] > 0
    assert 0.0 <= eng["occupancy"] <= 1.0
    assert eng["hbm_peak_held"] <= eng["hbm_pool"]
    # equal-HBM comparison: the page pool must not exceed the
    # baseline's contiguous allocation
    assert eng["hbm_pool"] <= base["hbm_held"]
    # round-11 sections: the kernel ablation carries a step-time pair
    # (xla + pallas) and the spec sweep carries accept accounting
    kern = {r["config"]: r for r in rows if r["section"] == "kernel"}
    assert set(kern) == {"kernel_xla", "kernel_pallas"}
    assert all(r["step_p50_ms"] > 0 for r in kern.values())
    spec = {r["config"]: r for r in rows if r["section"] == "spec"}
    assert set(spec) == {"spec_K0", "spec_K2", "spec_K4"}
    for name, r in spec.items():
        if r["config"] != "spec_K0":
            assert r["spec_drafted"] > 0
            assert 0.0 <= r["spec_accept_rate"] <= 1.0
            assert r["tokens_per_step"] >= 1.0


@pytest.mark.slow
def test_serving_validation():
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.serving import ServingEngine, PagedKVCache

    cfg = _cfg(max_len=16)
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    eng = ServingEngine(params, cfg, num_slots=1, page_size=4)
    with pytest.raises(ValueError):
        eng.submit(np.ones(10, np.int32), 10)    # 20 > max_len 16
    with pytest.raises(ValueError):
        eng.submit(np.ones(0, np.int32), 4)
    with pytest.raises(ValueError):
        eng.submit(np.ones(4, np.int32), 0)
    with pytest.raises(ValueError):
        ServingEngine(params, cfg, num_slots=1, page_size=4,
                      num_pages=3)               # < one request
    with pytest.raises(ValueError):
        PagedKVCache(cfg, num_pages=1, page_size=4)
    assert eng.step() is False                   # idle engine
    # indivisible page_size: the view rounds up past max_len (masked
    # tail), construction succeeds, submit stays max_len-gated
    eng7 = ServingEngine(params, cfg, num_slots=1, page_size=7)
    assert eng7.max_seq == 21
    with pytest.raises(ValueError):
        eng7.submit(np.ones(8, np.int32), 9)     # 17 > max_len 16


@pytest.mark.slow
def test_cancel_after_done_is_noop():
    """A cancel landing after completion (the inherent client race)
    must not drop the finished output."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.serving import ServingEngine

    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(1), cfg)
    eng = ServingEngine(params, cfg, num_slots=1, page_size=4)
    rid = eng.submit(np.arange(1, 6, dtype=np.int32), 6)
    outs = eng.run()
    eng.cancel(rid)
    assert eng.requests[rid].state == "done"
    np.testing.assert_array_equal(eng.requests[rid].output, outs[rid])
