"""Long-tail op tests: LRN, im2col/col2im, masked_softmax, fft,
LARS/mp-LAMB multi-tensor ops, legacy Crop (reference model:
``tests/python/unittest/test_operator.py`` sections)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


@pytest.mark.slow
def test_lrn_matches_torch():
    import torch
    x = np.random.RandomState(0).rand(2, 8, 5, 5).astype("float32")
    out = nd.LRN(nd.array(x), alpha=1e-3, beta=0.75, knorm=2.0,
                 nsize=5).asnumpy()
    ref = torch.nn.functional.local_response_norm(
        torch.from_numpy(x), size=5, alpha=1e-3, beta=0.75, k=2.0).numpy()
    assert np.allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_im2col_col2im_roundtrip():
    x = np.random.RandomState(1).rand(2, 3, 6, 6).astype("float32")
    cols = nd.im2col(nd.array(x), kernel=(3, 3), stride=(1, 1),
                     pad=(1, 1))
    assert cols.shape == (2, 27, 36)
    # conv via im2col == Convolution op
    w = np.random.RandomState(2).rand(4, 3, 3, 3).astype("float32")
    ref = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                         pad=(1, 1), num_filter=4, no_bias=True).asnumpy()
    via = (w.reshape(4, -1) @ cols.asnumpy()).reshape(2, 4, 6, 6)
    # note: im2col feature order is C-major k-minor, matching OIHW flatten
    assert np.allclose(via, ref, rtol=1e-4, atol=1e-4)
    # col2im is the adjoint: <im2col(x), y> == <x, col2im(y)>
    y = np.random.RandomState(3).rand(*cols.shape).astype("float32")
    back = nd.col2im(nd.array(y), output_size=(6, 6), kernel=(3, 3),
                     stride=(1, 1), pad=(1, 1)).asnumpy()
    lhs = (cols.asnumpy() * y).sum()
    rhs = (x * back).sum()
    assert np.isclose(lhs, rhs, rtol=1e-4)


def test_masked_softmax():
    x = np.random.RandomState(4).randn(2, 5).astype("float32")
    m = np.array([[1, 1, 0, 1, 0], [1, 1, 1, 1, 1]], dtype="float32")
    out = nd.masked_softmax(nd.array(x), nd.array(m)).asnumpy()
    assert np.allclose(out[0, [2, 4]], 0)
    assert np.isclose(out[0].sum(), 1.0, atol=1e-6)
    e = np.exp(x[0, [0, 1, 3]] - x[0, [0, 1, 3]].max())
    assert np.allclose(out[0, [0, 1, 3]], e / e.sum(), rtol=1e-5)


def test_fft_ifft_roundtrip():
    x = np.random.RandomState(5).rand(3, 8).astype("float32")
    f = nd.contrib.fft(nd.array(x))
    assert f.shape == (3, 16)
    back = nd.contrib.ifft(f).asnumpy() / 8  # reference scales by N
    assert np.allclose(back, x, rtol=1e-4, atol=1e-5)
    ref = np.fft.fft(x, axis=-1)
    packed = f.asnumpy().reshape(3, 8, 2)
    assert np.allclose(packed[..., 0], ref.real, rtol=1e-4, atol=1e-4)
    assert np.allclose(packed[..., 1], ref.imag, rtol=1e-4, atol=1e-4)


def test_multi_lars_and_preloaded_sgd():
    w = [np.random.RandomState(i).rand(4, 3).astype("float32")
         for i in range(2)]
    g = [np.random.RandomState(10 + i).rand(4, 3).astype("float32")
         for i in range(2)]
    lrs = np.array([0.1, 0.2], dtype="float32")
    wds = np.array([0.01, 0.0], dtype="float32")
    wss = np.array([(x * x).sum() for x in w], dtype="float32")
    gss = np.array([(x * x).sum() for x in g], dtype="float32")
    new_lrs = nd.multi_lars(nd.array(lrs), nd.array(wss), nd.array(gss),
                            nd.array(wds), eta=0.01).asnumpy()
    wn, gn = np.sqrt(wss), np.sqrt(gss)
    expect = lrs * 0.01 * wn / (gn + wds * wn + 1e-8)
    assert np.allclose(new_lrs, expect, rtol=1e-5)

    arrs = [nd.array(w[0]), nd.array(g[0]), nd.array(w[1]), nd.array(g[1]),
            nd.array(new_lrs), nd.array(wds)]
    o = nd.preloaded_multi_sgd_update(*arrs, num_weights=2)
    for i in range(2):
        expect_w = w[i] - new_lrs[i] * (g[i] + wds[i] * w[i])
        assert np.allclose(o[i].asnumpy(), expect_w, rtol=1e-5, atol=1e-6)


def test_mp_lamb_phases():
    w32 = np.random.RandomState(6).rand(5).astype("float32")
    w16 = w32.astype("float16")
    g = np.random.RandomState(7).rand(5).astype("float16")
    mean = nd.array(np.zeros(5, "float32"))
    var = nd.array(np.zeros(5, "float32"))
    upd = nd.mp_lamb_update_phase1(
        nd.array(w16), nd.array(g), mean, var,
        nd.array(w32), t=1, wd=0.01)
    # moments are mutated in place (FMutateInputs contract)
    assert np.allclose(mean.asnumpy(), 0.1 * g.astype("float32"),
                       rtol=1e-3)
    assert (var.asnumpy() > 0).all()
    r1 = np.linalg.norm(w32)
    r2 = np.linalg.norm(upd.asnumpy())
    out = nd.mp_lamb_update_phase2(
        nd.array(w16), upd, nd.array(np.array(r1, "float32")),
        nd.array(np.array(r2, "float32")), nd.array(w32), lr=0.1)
    assert out.dtype == np.float16
    expect32 = w32 - 0.1 * (r1 / r2) * upd.asnumpy()
    assert np.allclose(out.asnumpy(), expect32.astype("float16"),
                       rtol=1e-3, atol=1e-3)


def test_crop_legacy():
    x = np.arange(36, dtype="float32").reshape(1, 1, 6, 6)
    like = np.zeros((1, 1, 2, 2), dtype="float32")
    out = nd.Crop(nd.array(x), nd.array(like), center_crop=True).asnumpy()
    assert np.allclose(out[0, 0], x[0, 0, 2:4, 2:4])
    out2 = nd.Crop(nd.array(x), h_w=(3, 2), offset=(1, 4)).asnumpy()
    assert out2.shape == (1, 1, 3, 2)
    assert np.allclose(out2[0, 0], x[0, 0, 1:4, 4:6])


def test_log_sigmoid_mish_grads():
    x = np.random.RandomState(8).randn(4, 3).astype("float32")
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.log_sigmoid(a)
    y.backward()
    assert np.allclose(a.grad.asnumpy(), 1 / (1 + np.exp(x)), rtol=1e-4)
    out = nd.mish(nd.array(x)).asnumpy()
    sp = np.log1p(np.exp(x))
    assert np.allclose(out, x * np.tanh(sp), rtol=1e-4, atol=1e-5)


def test_kl_sparse_reg_identity_forward():
    x = np.random.RandomState(9).randn(3, 4).astype("float32")
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.IdentityAttachKLSparseReg(a, sparseness_target=0.2,
                                         penalty=0.1)
        L = y.sum()
    assert np.allclose(y.asnumpy(), x)
    L.backward()
    g = a.grad.asnumpy()
    assert g.shape == x.shape and not np.allclose(g, 1.0)  # reg added


def test_multi_lars_zero_grad_passthrough():
    """Regression: a zero-gradient layer keeps its lr unchanged instead
    of exploding to eta*||w||/eps."""
    lrs = np.array([0.1, 0.1], dtype="float32")
    wss = np.array([4.0, 4.0], dtype="float32")
    gss = np.array([0.0, 1.0], dtype="float32")
    wds = np.array([0.0, 0.0], dtype="float32")
    out = nd.multi_lars(nd.array(lrs), nd.array(wss), nd.array(gss),
                        nd.array(wds), eta=0.01).asnumpy()
    assert np.isclose(out[0], 0.1)
    assert np.isclose(out[1], 0.1 * 0.01 * 2.0 / 1.0, rtol=1e-4)


def test_multi_sum_sq_and_reset_arrays():
    ws = [np.random.RandomState(i).rand(3, 4).astype("float32")
          for i in range(3)]
    out = nd.multi_sum_sq(*[nd.array(x) for x in ws],
                          num_arrays=3).asnumpy()
    assert np.allclose(out, [(x * x).sum() for x in ws], rtol=1e-5)
    arrs = [nd.array(x) for x in ws]
    nd.reset_arrays(*arrs, num_arrays=3)
    assert all((a.asnumpy() == 0).all() for a in arrs)


def test_legacy_0index_ops():
    d = nd.array(np.arange(12, dtype="float32").reshape(4, 3))
    i = nd.array(np.array([0, 2, 1, 0], "float32"))
    assert np.allclose(nd.choose_element_0index(d, i).asnumpy(),
                       [0, 5, 7, 9])
    f = nd.fill_element_0index(
        d, nd.array(np.full(4, -1.0, "float32")), i).asnumpy()
    assert f[0, 0] == -1 and f[1, 2] == -1 and f[2, 1] == -1
    assert f[0, 1] == 1  # untouched
