"""Tensor-parallel serving (round 14, ROADMAP item 1).

The tentpole contract: ``ServingEngine(tp=N)`` lowers the ONE compiled
step program through a ``parallel/mesh.py`` mesh — params sharded by
the megatron rules (int8 q/s specs derived), paged KV pools sharded on
the HEADS axis, host state replicated — and under f32 greedy the
outputs stay TOKEN-IDENTICAL to ``tp=1`` and to ``models/gpt.py
generate`` through everything the engine can do: mixed-length batches,
in-flight joins, preemption/resume, prefix-cache hits with COW,
int8-KV pages, and in-engine speculation.  The per-device half of the
claim — KV-pool and weight bytes ~1/tp, so a model ~tp× too big for
one chip serves — is asserted against the actual device shards.

Runs on the conftest's virtual 8-device CPU mesh.  Slow tier, group i
(each tp config compiles its own mesh-lowered step program); the
mesh-free shardings-spec test at the bottom is FAST tier.
"""
import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401  (conftest device setup)


def _cfg(**kw):
    from mxnet_tpu.models import gpt
    base = dict(use_flash=False, remat=False, dropout=0.0,
                dtype="float32", vocab_size=128, max_len=64)
    base.update(kw)
    return gpt.gpt_tiny(**base)


def _ref(params, cfg, prompt, n, **kw):
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt
    return np.asarray(
        gpt.generate(params, cfg, jnp.asarray(prompt)[None], n,
                     **kw))[0]


def _setup(seed=3, **kw):
    import jax
    from mxnet_tpu.models import transformer as T
    cfg = _cfg(**kw)
    params = T.init_params(jax.random.PRNGKey(seed), cfg)
    return params, cfg


# ------------------------------------------------------------ identity ---

@pytest.mark.slow
def test_tp2_token_identical_mixed_lengths_and_joins():
    """The acceptance pin: a mixed prompt/output-length batch with an
    in-flight join decodes token-identically at tp=2 — bit-equal to
    the tp=1 engine on the same schedule AND to plain ``generate`` —
    for float and weight-only-int8 params."""
    from mxnet_tpu.models import gpt
    from mxnet_tpu.serving import ServingEngine

    params, cfg = _setup()
    shapes = [(5, 8), (3, 12), (9, 4), (2, 6)]
    for p in (params, gpt.quantize_decode_params(params)):
        outs = {}
        for tp in (1, 2):
            rng = np.random.RandomState(0)
            eng = ServingEngine(p, cfg, num_slots=3, page_size=4,
                                prefill_chunk=6, tp=tp)
            reqs = [(eng.submit(rng.randint(1, 90, P).astype(np.int32),
                                N), N) for P, N in shapes[:3]]
            for _ in range(3):
                eng.step()
            # the join lands mid-decode, same step on both engines
            P, N = shapes[3]
            reqs.append((eng.submit(
                rng.randint(1, 90, P).astype(np.int32), N), N))
            got = eng.run()
            outs[tp] = [(got[rid], eng.requests[rid].prompt, N)
                        for rid, N in reqs]
            assert eng.cache.pages_in_use == 0
        for (o2, prompt, N), (o1, _, _) in zip(outs[2], outs[1]):
            np.testing.assert_array_equal(o2, o1)          # tp2 == tp1
            np.testing.assert_array_equal(
                o2, _ref(p, cfg, prompt, N))               # == generate


@pytest.mark.slow
def test_tp2_preemption_recompute_exact():
    """An over-committed pool under tp=2: the youngest victim is
    preempted, re-prefills its committed tokens on re-admission, and
    every output — preempted or not — stays identical to generate."""
    from mxnet_tpu.serving import ServingEngine

    params, cfg = _setup(seed=9)
    rng = np.random.RandomState(3)
    eng = ServingEngine(params, cfg, num_slots=4, page_size=4,
                        pages_per_slot=8, num_pages=12,
                        prefill_chunk=4, tp=2)
    reqs = []
    for P, N in [(6, 20), (4, 24), (8, 16), (3, 22), (5, 18)]:
        rid = eng.submit(rng.randint(1, 90, P).astype(np.int32), N)
        reqs.append((rid, N))
    outs = eng.run()
    assert eng.stats["preemptions"] > 0, \
        "pool was sized to force preemption"
    for rid, N in reqs:
        np.testing.assert_array_equal(
            outs[rid], _ref(params, cfg, eng.requests[rid].prompt, N))
    assert eng.cache.pages_in_use == 0


@pytest.mark.slow
def test_tp2_prefix_cache_cow_hit_exact():
    """Shared-prefix reuse under tp=2: a replayed full-page prompt
    maps cached pages read-only, COWs the final-token page (each
    device copies its 1/tp slice through the sharded donated copy
    program), and decodes identically to generate."""
    from mxnet_tpu.serving import ServingEngine

    params, cfg = _setup(seed=5)
    rng = np.random.RandomState(7)
    eng = ServingEngine(params, cfg, num_slots=2, page_size=4,
                        prefill_chunk=8, tp=2, prefix_cache=True)
    # 8 tokens = two full pages -> both donated on completion; the
    # replay whole-input-matches, re-feeds the final token, and must
    # COW the page that token lands in
    pr = rng.randint(1, 90, 8).astype(np.int32)
    r1 = eng.submit(pr, 6)
    eng.run()
    r2 = eng.submit(pr, 6)
    eng.run()
    assert eng.stats["prefix_hit_tokens"] > 0
    assert eng.stats["cow_copies"] >= 1
    ref = _ref(params, cfg, pr, 6)
    np.testing.assert_array_equal(eng.requests[r1].output, ref)
    np.testing.assert_array_equal(eng.requests[r2].output, ref)
    # divergent-tail request: partial-page match, COW mid-page
    pr2 = pr.copy()
    pr2[6:] = (pr2[6:] + 1) % 90 + 1
    r3 = eng.submit(pr2, 6)
    eng.run()
    np.testing.assert_array_equal(eng.requests[r3].output,
                                  _ref(params, cfg, pr2, 6))


@pytest.mark.slow
def test_tp2_int8_kv_agreement():
    """Paged int8-KV under tp=2 tracks contiguous
    ``generate(kv_int8=True)`` the same way the tp=1 paged path does —
    greedy agreement (page gathers and sharded reductions reorder the
    sums), not bit equality."""
    from mxnet_tpu.serving import ServingEngine

    params, cfg = _setup(seed=11, vocab_size=512, d_model=128,
                         n_heads=4, n_layers=3, d_ff=256)
    rng = np.random.RandomState(4)
    eng = ServingEngine(params, cfg, num_slots=2, page_size=4,
                        kv_int8=True, prefill_chunk=8, tp=2)
    reqs = [eng.submit(rng.randint(1, 500, P).astype(np.int32), 12)
            for P in (5, 7)]
    outs = eng.run()
    for rid in reqs:
        ref = _ref(params, cfg, eng.requests[rid].prompt, 12,
                   kv_int8=True)
        assert (outs[rid] == ref).mean() >= 0.9, (outs[rid], ref)
    # the f32 scale pool shards its heads axis alongside the int8 pool
    assert len(eng.cache.pools[0]["s"].addressable_shards) == 2


@pytest.mark.slow
def test_pallas_tp_token_identical_vs_xla_and_generate():
    """Round 22 chip-ready pin: the PALLAS-kernel engine at tp∈{2,4}
    — ``paged_attention`` shard_map-lowered over the serving mesh,
    each device walking its 1/tp heads slice of the sharded pool,
    attention collective-free per head — decodes TOKEN-IDENTICALLY
    (f32 greedy) to the tp=1 XLA engine and to ``generate`` through
    mixed lengths and an in-flight join.  Interpreter-mode pallas on
    the virtual mesh: the lowering is the thing under test, the
    kernel body is the tier-1-pinned one."""
    from mxnet_tpu.serving import ServingEngine

    params, cfg = _setup()
    shapes = [(5, 8), (3, 12), (9, 4), (2, 6)]

    def run(tp, kernel):
        rng = np.random.RandomState(0)
        eng = ServingEngine(params, cfg, num_slots=3, page_size=4,
                            prefill_chunk=6, tp=tp, kernel=kernel)
        reqs = [(eng.submit(rng.randint(1, 90, P).astype(np.int32),
                            N), N) for P, N in shapes[:3]]
        for _ in range(3):
            eng.step()
        P, N = shapes[3]
        reqs.append((eng.submit(
            rng.randint(1, 90, P).astype(np.int32), N), N))
        got = eng.run()
        outs = [(got[rid], eng.requests[rid].prompt, N)
                for rid, N in reqs]
        assert eng.cache.pages_in_use == 0
        return outs

    base = run(1, "xla")
    for tp in (2, 4):
        for (op, prompt, N), (ox, _, _) in zip(run(tp, "pallas"),
                                               base):
            np.testing.assert_array_equal(op, ox)   # pallas tpN == xla tp1
            np.testing.assert_array_equal(
                op, _ref(params, cfg, prompt, N))   # == generate


@pytest.mark.slow
def test_pallas_tp2_speculation_and_int8():
    """The pallas×tp capability COMPOSES: spec_K=1 draft rows ride the
    shard_map-lowered kernel token-identically to generate (no gate —
    draft rows are just extra T rows in the same grid), and int8-KV
    pages with the retiled (pages, 2, ps, H) scale planes dequantize
    inside the sharded walk with the same greedy agreement the XLA
    tp=2 path pins."""
    from mxnet_tpu.serving import ServingEngine

    params, cfg = _setup(seed=3)
    rng = np.random.RandomState(1)
    eng = ServingEngine(params, cfg, num_slots=2, page_size=4,
                        prefill_chunk=6, tp=2, spec_K=1,
                        kernel="pallas")
    reqs = [(eng.submit(rng.randint(1, 90, P).astype(np.int32), N), N)
            for P, N in [(5, 10), (3, 12)]]
    outs = eng.run()
    assert eng.stats["spec_drafted"] > 0
    for rid, N in reqs:
        np.testing.assert_array_equal(
            outs[rid], _ref(params, cfg, eng.requests[rid].prompt, N))

    params8, cfg8 = _setup(seed=11, vocab_size=512, d_model=128,
                           n_heads=4, n_layers=3, d_ff=256)
    rng = np.random.RandomState(4)
    eng = ServingEngine(params8, cfg8, num_slots=2, page_size=4,
                        kv_int8=True, prefill_chunk=8, tp=2,
                        kernel="pallas")
    reqs = [eng.submit(rng.randint(1, 500, P).astype(np.int32), 12)
            for P in (5, 7)]
    outs = eng.run()
    for rid in reqs:
        ref = _ref(params8, cfg8, eng.requests[rid].prompt, 12,
                   kv_int8=True)
        assert (outs[rid] == ref).mean() >= 0.9, (outs[rid], ref)
    assert len(eng.cache.pools[0]["s"].addressable_shards) == 2


@pytest.mark.slow
def test_tp2_speculation_token_identical():
    """In-engine speculation rides the sharded step unchanged: draft
    rows feed the same mesh-lowered program, per-row verify/commit and
    pointer rollback stay host-side — tp=2 + spec_K=1 output is
    token-identical to generate whatever the drafter proposes."""
    from mxnet_tpu.serving import ServingEngine

    params, cfg = _setup(seed=3)
    rng = np.random.RandomState(1)
    shapes = [(5, 10), (3, 12), (7, 8)]
    for drafter in ("ngram", lambda toks, K: toks[-K:]):
        eng = ServingEngine(params, cfg, num_slots=2, page_size=4,
                            prefill_chunk=6, tp=2, spec_K=1,
                            spec_drafter=drafter)
        reqs = [(eng.submit(rng.randint(1, 90, P).astype(np.int32),
                            N), N) for P, N in shapes]
        outs = eng.run()
        assert eng.stats["spec_drafted"] > 0
        for rid, N in reqs:
            np.testing.assert_array_equal(
                outs[rid],
                _ref(params, cfg, eng.requests[rid].prompt, N))


# ----------------------------------------------------- per-device bytes ---

@pytest.mark.slow
def test_tp2_per_device_bytes_halve():
    """The perf claim, measured: pool buffers and the tp-sharded
    weights really live as 1/tp-size shards per device — the
    accounting properties agree with the ACTUAL device placement, so
    a model ~tp× too big for one chip's HBM serves at tp chips."""
    from mxnet_tpu.models import gpt
    from mxnet_tpu.serving import ServingEngine

    params, cfg = _setup()
    engs = {tp: ServingEngine(
        gpt.quantize_decode_params(params), cfg, num_slots=2,
        page_size=4, tp=tp) for tp in (1, 2)}
    e1, e2 = engs[1], engs[2]
    assert e2.hbm_pool_per_device * 2 == e2.hbm_pool == e1.hbm_pool
    # actual shards: every pool buffer splits exactly in half
    for pool in e2.cache.pools:
        shards = pool["kv"].addressable_shards
        assert len(shards) == 2
        assert all(s.data.nbytes == pool["kv"].nbytes // 2
                   for s in shards)
    # tp-sharded weights halve per device too (wq is P(None, 'tp'))
    wq = e2.params["layers"][0]["wq"]["q"]
    assert all(s.data.nbytes == wq.nbytes // 2
               for s in wq.addressable_shards)
    # replicated leaves (layer norms) do not
    g = e2.params["layers"][0]["ln1"]["g"]
    assert all(s.data.nbytes == g.nbytes
               for s in g.addressable_shards)
    # held bytes track allocation, per-device = 1/tp exactly
    rid = e2.submit(np.arange(1, 9, dtype=np.int32), 4)
    e2.step()
    assert e2.hbm_held > 0
    assert e2.hbm_held_per_device * 2 == e2.hbm_held
    e2.cancel(rid)


# ----------------------------------------------------------- validation ---

@pytest.mark.slow
def test_tp_validation():
    """Clear errors at the boundary: a tp that does not divide the
    heads, a mesh without a 'tp' axis, tp/mesh disagreement, and a tp
    past the visible devices.  Round 22: the old blanket
    pallas×tp>1 error is GONE — the capability check is mesh present
    + heads divisible, and a pallas tp=2 engine constructs (the
    identity pins below prove it decodes)."""
    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.parallel.mesh import make_mesh, serving_mesh
    from mxnet_tpu.serving import ServingEngine

    params, cfg = _setup()
    with pytest.raises(ValueError, match="n_heads"):
        ServingEngine(params, cfg, num_slots=1, page_size=4, tp=3)
    # heads-divisibility is kernel-independent (the pallas shard_map
    # walks H/tp heads per device; 4 heads over tp=3 has no whole
    # slice either way)
    with pytest.raises(ValueError, match="n_heads"):
        ServingEngine(params, cfg, num_slots=1, page_size=4, tp=3,
                      kernel="pallas")
    # mesh-lowered pallas is a supported combination now
    eng_p = ServingEngine(params, cfg, num_slots=1, page_size=4,
                          tp=2, kernel="pallas")
    assert eng_p.tp == 2 and eng_p.mesh is not None
    with pytest.raises(ValueError, match="no 'tp' axis"):
        ServingEngine(params, cfg, num_slots=1, page_size=4,
                      mesh=make_mesh({"dp": -1}))
    with pytest.raises(ValueError, match="disagrees"):
        ServingEngine(params, cfg, num_slots=1, page_size=4, tp=4,
                      mesh=serving_mesh(2))
    with pytest.raises(MXNetError, match="devices"):
        serving_mesh(1024)
    # MoE decode params are tp=1-only this round (clear error, like
    # the pallas kernel path)
    import jax
    from mxnet_tpu.models import gpt
    mcfg = _cfg(n_experts=2, moe_every=2)
    mparams = jax.eval_shape(
        lambda: gpt.init_params(jax.random.PRNGKey(0), mcfg))
    with pytest.raises(ValueError, match="MoE.*tp=1-only"):
        ServingEngine(mparams, mcfg, num_slots=1, page_size=4, tp=2)
    # a trivial tp=1 mesh falls back to the unsharded path
    eng = ServingEngine(params, cfg, num_slots=1, page_size=4,
                        mesh=serving_mesh(1))
    assert eng.tp == 1 and eng.mesh is None


# ------------------------------------------------------ cluster failover ---

@pytest.mark.slow
def test_cluster_failover_under_tp_preserves_config():
    """Round-14 satellite fix: the cluster captures the WHOLE engine
    config once (``_engine_kwargs``), so a request resubmitted to a
    survivor after a replica failure lands on an engine with the same
    tp/mesh setup — and the recompute-exact resume stays
    token-identical to generate under tp=2."""
    from mxnet_tpu.serving import ServingCluster

    params, cfg = _setup(seed=5)
    rng = np.random.RandomState(5)
    cl = ServingCluster(params, cfg, replicas=2, num_slots=2,
                        page_size=4, prefill_chunk=6, metrics=True,
                        watchdog_s=10.0, tp=2)
    try:
        # the config is captured once and applied to every replica
        assert cl._engine_kwargs["tp"] == 2
        assert all(r.engine.tp == 2 for r in cl.replicas)
        # params are sharded ONCE cluster-wide: every replica holds
        # the SAME committed buffers (the engine's device_put is a
        # no-op on already-placed arrays), not an independent sharded
        # copy per replica — R copies would multiply the per-device
        # weight bytes tp exists to divide
        assert cl.replicas[0].engine.params["tok_emb"] \
            is cl.replicas[1].engine.params["tok_emb"]
        eng0 = cl.replicas[0].engine
        orig_step = eng0.step
        calls = [0]

        def bomb():
            calls[0] += 1
            if calls[0] == 4:
                raise RuntimeError("injected replica failure")
            return orig_step()

        eng0.step = bomb
        wl = [(rng.randint(1, 90, P).astype(np.int32), N)
              for P, N in [(5, 10), (3, 12), (7, 8), (4, 9), (6, 7),
                           (2, 11)]]
        rids = [cl.submit(p, n) for p, n in wl]
        for rid, (p, n) in zip(rids, wl):
            np.testing.assert_array_equal(
                cl.result(rid, timeout=300), _ref(params, cfg, p, n))
        c = cl.metrics()["counters"]
        assert c["cluster_failovers_total"] == 1
        assert c["cluster_requests_completed_total"] == len(wl)
        # the survivor that re-ran the work is itself tp=2
        health = {h["replica"]: h for h in cl.health()}
        assert health[0]["dead"] and health[1]["alive"]
        assert cl.replicas[1].engine.tp == 2
        assert any(cl.requests[r].failovers > 0 for r in rids)
    finally:
        cl.close(timeout=60)


# ------------------------------------------------- FAST: mesh-free specs ---

def test_step_input_specs_mesh_free():
    """FAST tier: the engine's declared sharding table is pure spec —
    no mesh, no devices, no arrays.  Pools shard exactly the heads
    axis, int8 q/s derive from the float megatron rules (per-column
    scales follow the sharded out-dim, per-row embedding scales
    replicate), host-built rows replicate, and the tree aligns
    leaf-for-leaf with the real program inputs."""
    import jax
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.models import gpt
    from mxnet_tpu.serving.engine import (step_input_specs,
                                          step_output_specs)

    cfg = _cfg()
    params = jax.eval_shape(
        lambda: gpt.quantize_decode_params(
            gpt.init_params(jax.random.PRNGKey(0), cfg)))
    specs = step_input_specs(params, cfg, kv_int8=True)
    pspec, pools = specs[0], specs[1]
    assert len(specs) == 8
    # pools: heads axis over tp, nothing else — index 2 on the kv
    # layout, index 3 on the retiled (pages, 2, ps, H) scale planes
    assert all(pool["kv"] == P(None, None, "tp", None)
               and pool["s"] == P(None, None, None, "tp")
               for pool in pools)
    assert len(pools) == cfg.n_layers
    # host-built rows replicate
    assert all(s == P() for s in specs[2:])
    # megatron rules + q/s derivation
    layer = pspec["layers"][0]
    assert layer["wq"]["q"] == P(None, "tp")
    assert layer["wq"]["s"] == P("tp")      # per-column, sharded out
    assert layer["wo"]["q"] == P("tp", None)
    assert layer["wo"]["s"] == P(None)      # per-column, unsharded out
    assert pspec["tok_emb"]["q"] == P(None, "tp")
    assert pspec["tok_emb"]["s"] == P(None)  # per-ROW (vocab) scales
    assert pspec["emb_ln"]["g"] == P()
    # float params take the rules verbatim
    fparams = jax.eval_shape(
        lambda: gpt.init_params(jax.random.PRNGKey(0), cfg))
    fspecs = step_input_specs(fparams, cfg, kv_int8=False)
    assert fspecs[0]["layers"][0]["wq"] == P(None, "tp")
    assert "s" not in fspecs[1][0]
    # spec tree structurally matches the params tree (binding to a
    # mesh is a plain tree_map — what _make_step does)
    assert jax.tree_util.tree_structure(
        jax.tree_util.tree_map(lambda s: 0, pspec,
                               is_leaf=lambda x: isinstance(x, P))) \
        == jax.tree_util.tree_structure(params)
    # output twin: replicated argmaxes, pool sharding preserved
    # (shape/dtype/sharding match is what keeps donation aliasing)
    out = step_output_specs(cfg, kv_int8=True)
    assert out[0] == P() and out[1] == pools
