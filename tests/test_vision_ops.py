"""Detection / spatial-transform op tests with numpy oracles
(reference model: ``tests/python/unittest/test_operator.py`` sections for
box_nms, MultiBox*, ROIPooling, SpatialTransformer, Correlation)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd

pytestmark = pytest.mark.slow


def np_iou(a, b):
    lt = np.maximum(a[:, None, :2], b[None, :, :2])
    rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    aa = np.maximum(a[:, 2] - a[:, 0], 0) * np.maximum(a[:, 3] - a[:, 1], 0)
    ab = np.maximum(b[:, 2] - b[:, 0], 0) * np.maximum(b[:, 3] - b[:, 1], 0)
    union = aa[:, None] + ab[None, :] - inter
    return np.where(union > 0, inter / union, 0)


def test_box_iou():
    rng = np.random.RandomState(0)
    a = rng.uniform(0, 1, (5, 4)).astype("float32")
    b = rng.uniform(0, 1, (7, 4)).astype("float32")
    a[:, 2:] += a[:, :2]
    b[:, 2:] += b[:, :2]
    out = nd.contrib.box_iou(nd.array(a), nd.array(b)).asnumpy()
    assert out.shape == (5, 7)
    assert np.allclose(out, np_iou(a, b), rtol=1e-5, atol=1e-6)


def test_box_nms_suppresses_overlaps():
    # three boxes: 2nd overlaps 1st heavily (lower score -> suppressed),
    # 3rd is disjoint (kept)
    data = np.array([[
        [0, 0.9, 0.0, 0.0, 1.0, 1.0],
        [0, 0.8, 0.05, 0.05, 1.0, 1.0],
        [0, 0.7, 2.0, 2.0, 3.0, 3.0],
    ]], dtype="float32")
    out = nd.contrib.box_nms(nd.array(data), overlap_thresh=0.5,
                             coord_start=2, score_index=1,
                             id_index=0).asnumpy()[0]
    scores = out[:, 1]
    assert (scores > 0).sum() == 2           # one suppressed
    assert np.isclose(scores[0], 0.9)        # sorted desc
    kept_boxes = out[scores > 0][:, 2:]
    assert any(np.allclose(b, [2, 2, 3, 3]) for b in kept_boxes)


def test_box_nms_per_class_vs_force():
    # overlapping boxes of DIFFERENT classes survive per-class nms but
    # not force_suppress
    data = np.array([[
        [0, 0.9, 0.0, 0.0, 1.0, 1.0],
        [1, 0.8, 0.0, 0.0, 1.0, 1.0],
    ]], dtype="float32")
    keep = nd.contrib.box_nms(nd.array(data), overlap_thresh=0.5,
                              coord_start=2, score_index=1,
                              id_index=0).asnumpy()[0]
    assert (keep[:, 1] > 0).sum() == 2
    sup = nd.contrib.box_nms(nd.array(data), overlap_thresh=0.5,
                             coord_start=2, score_index=1, id_index=0,
                             force_suppress=True).asnumpy()[0]
    assert (sup[:, 1] > 0).sum() == 1


def test_multibox_prior():
    x = nd.zeros((1, 3, 4, 6))
    anchors = nd.MultiBoxPrior(x, sizes=(0.5, 0.25),
                               ratios=(1.0, 2.0)).asnumpy()
    # (S + R - 1) anchors per cell
    assert anchors.shape == (1, 4 * 6 * 3, 4)
    # first cell center is ((0.5/6), (0.5/4)); first anchor is size .5
    a0 = anchors[0, 0]
    cx, cy = (a0[0] + a0[2]) / 2, (a0[1] + a0[3]) / 2
    assert np.isclose(cx, 0.5 / 6, atol=1e-6)
    assert np.isclose(cy, 0.5 / 4, atol=1e-6)
    assert np.isclose(a0[2] - a0[0], 0.5, atol=1e-6)


@pytest.mark.slow
def test_multibox_target_assigns():
    anchors = np.array([[[0.0, 0.0, 0.5, 0.5],
                         [0.5, 0.5, 1.0, 1.0],
                         [0.0, 0.5, 0.5, 1.0]]], dtype="float32")
    # one gt box matching anchor 0 exactly; class 2
    label = np.array([[[2, 0.0, 0.0, 0.5, 0.5],
                       [-1, 0, 0, 0, 0]]], dtype="float32")
    cls_pred = np.zeros((1, 4, 3), dtype="float32")
    loc_t, loc_m, cls_t = nd.MultiBoxTarget(
        nd.array(anchors), nd.array(label), nd.array(cls_pred))
    cls_t = cls_t.asnumpy()[0]
    assert cls_t[0] == 3          # gt class 2 -> target 3 (0 = bg)
    assert cls_t[1] == 0
    loc_m = loc_m.asnumpy()[0].reshape(3, 4)
    assert loc_m[0].all() and not loc_m[1].any()
    # exact match -> zero regression target
    assert np.allclose(loc_t.asnumpy()[0].reshape(3, 4)[0], 0, atol=1e-5)


@pytest.mark.slow
def test_multibox_detection_roundtrip():
    anchors = np.array([[[0.1, 0.1, 0.4, 0.4],
                         [0.6, 0.6, 0.9, 0.9]]], dtype="float32")
    # class 1 at anchor 0, class 2 at anchor 1, zero loc offsets
    cls_prob = np.array([[[0.1, 0.2],      # background
                          [0.8, 0.1],      # class 1
                          [0.1, 0.7]]],    # class 2
                        dtype="float32")
    loc_pred = np.zeros((1, 8), dtype="float32")
    out = nd.MultiBoxDetection(nd.array(cls_prob), nd.array(loc_pred),
                               nd.array(anchors)).asnumpy()[0]
    kept = out[out[:, 0] >= 0]
    assert len(kept) == 2
    # detection ids are original 0-based gt classes (channel - 1):
    # channel 1 -> class 0 at anchor 0, channel 2 -> class 1 at anchor 1
    ids = sorted(kept[:, 0])
    assert ids == [0.0, 1.0]
    row0 = kept[kept[:, 0] == 0][0]
    assert np.allclose(row0[2:], [0.1, 0.1, 0.4, 0.4], atol=1e-5)


def test_roi_pooling_matches_manual():
    x = np.arange(64, dtype="float32").reshape(1, 1, 8, 8)
    rois = np.array([[0, 0, 0, 3, 3]], dtype="float32")  # 4x4 region
    out = nd.ROIPooling(nd.array(x), nd.array(rois), pooled_size=(2, 2),
                        spatial_scale=1.0).asnumpy()
    # region rows 0..3 cols 0..3, 2x2 max pool
    region = x[0, 0, :4, :4]
    expect = np.array([[region[:2, :2].max(), region[:2, 2:].max()],
                       [region[2:, :2].max(), region[2:, 2:].max()]])
    assert np.allclose(out[0, 0], expect)


def test_roi_align_constant_field():
    # on a constant image every bilinear sample returns the constant
    x = np.full((1, 2, 10, 10), 3.5, dtype="float32")
    rois = np.array([[0, 1.0, 1.0, 7.0, 5.0]], dtype="float32")
    out = nd.contrib.ROIAlign(nd.array(x), nd.array(rois),
                              pooled_size=(3, 3),
                              spatial_scale=1.0).asnumpy()
    assert out.shape == (1, 2, 3, 3)
    assert np.allclose(out, 3.5, atol=1e-6)


def test_roi_align_gradient_flows():
    x = np.random.RandomState(0).rand(1, 1, 6, 6).astype("float32")
    rois = np.array([[0, 0.5, 0.5, 4.5, 4.5]], dtype="float32")
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.contrib.ROIAlign(a, nd.array(rois), pooled_size=(2, 2),
                                spatial_scale=1.0)
    y.backward()
    g = a.grad.asnumpy()
    assert g.sum() > 0            # bilinear weights sum to out count
    assert np.isclose(g.sum(), 4.0, atol=1e-4)


def test_bilinear_sampler_identity_grid():
    x = np.random.RandomState(1).rand(2, 3, 5, 7).astype("float32")
    ys = np.linspace(-1, 1, 5)
    xs = np.linspace(-1, 1, 7)
    xg, yg = np.meshgrid(xs, ys)
    grid = np.stack([xg, yg])[None].repeat(2, 0).astype("float32")
    out = nd.BilinearSampler(nd.array(x), nd.array(grid)).asnumpy()
    assert np.allclose(out, x, atol=1e-5)


def test_spatial_transformer_identity_affine():
    x = np.random.RandomState(2).rand(1, 2, 6, 6).astype("float32")
    theta = np.array([[1, 0, 0, 0, 1, 0]], dtype="float32")
    out = nd.SpatialTransformer(nd.array(x), nd.array(theta),
                                target_shape=(6, 6)).asnumpy()
    assert np.allclose(out, x, atol=1e-5)
    # shifted affine moves content
    theta2 = np.array([[1, 0, 0.5, 0, 1, 0]], dtype="float32")
    out2 = nd.SpatialTransformer(nd.array(x), nd.array(theta2),
                                 target_shape=(6, 6)).asnumpy()
    assert not np.allclose(out2, x)


def test_grid_generator_warp_zero_flow():
    flow = np.zeros((1, 2, 4, 5), dtype="float32")
    grid = nd.GridGenerator(nd.array(flow), transform_type="warp").asnumpy()
    xs = np.linspace(-1, 1, 5)
    assert np.allclose(grid[0, 0, 0], xs, atol=1e-6)


def test_bilinear_resize_2d():
    x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
    out = nd.contrib.BilinearResize2D(nd.array(x), height=7,
                                      width=7).asnumpy()
    assert out.shape == (1, 1, 7, 7)
    # align_corners: corners preserved
    assert np.isclose(out[0, 0, 0, 0], 0.0)
    assert np.isclose(out[0, 0, -1, -1], 15.0)
    assert np.isclose(out[0, 0, 3, 3], 7.5)  # center bilinear


def test_adaptive_avg_pooling():
    x = np.random.RandomState(3).rand(2, 3, 7, 5).astype("float32")
    out = nd.contrib.AdaptiveAvgPooling2D(nd.array(x),
                                          output_size=(3, 2)).asnumpy()
    assert out.shape == (2, 3, 3, 2)
    import torch
    ref = torch.nn.functional.adaptive_avg_pool2d(
        torch.from_numpy(x), (3, 2)).numpy()
    assert np.allclose(out, ref, rtol=1e-5, atol=1e-6)
    # divisible case equals reshape-mean
    x2 = np.random.RandomState(4).rand(1, 1, 6, 6).astype("float32")
    out2 = nd.contrib.AdaptiveAvgPooling2D(nd.array(x2),
                                           output_size=(3, 3)).asnumpy()
    expect = x2.reshape(1, 1, 3, 2, 3, 2).mean((3, 5))
    assert np.allclose(out2, expect, rtol=1e-5, atol=1e-6)


def test_correlation_self_is_mean_square():
    x = np.random.RandomState(5).rand(1, 4, 6, 6).astype("float32")
    out = nd.Correlation(nd.array(x), nd.array(x), kernel_size=1,
                         max_displacement=1, stride1=1, stride2=1,
                         pad_size=1).asnumpy()
    assert out.shape[1] == 9      # 3x3 displacement grid
    # zero-displacement channel (index 4) == mean over C of x*x
    center = out[0, 4]
    expect = (x[0] ** 2).mean(0)
    assert np.allclose(center, expect, rtol=1e-5, atol=1e-6)


def test_svm_output_grad():
    x = np.array([[0.5, -0.2, 0.1]], dtype="float32")
    lab = np.array([0], dtype="float32")
    a = nd.array(x)
    a.attach_grad()
    with autograd.record():
        y = nd.SVMOutput(a, nd.array(lab), margin=1.0, use_linear=True)
    assert np.allclose(y.asnumpy(), x)
    y.backward()
    g = a.grad.asnumpy()[0]
    # target class 0: margin violated (0.5 < 1) -> grad -1;
    # others: -x > -1 -> margin violated -> grad +1
    assert np.allclose(g, [-1.0, 1.0, 1.0])


def test_batch_take_and_ravel():
    a = np.arange(12, dtype="float32").reshape(4, 3)
    idx = np.array([0, 2, 1, 0], dtype="float32")
    out = nd.batch_take(nd.array(a), nd.array(idx)).asnumpy()
    assert np.allclose(out, a[np.arange(4), idx.astype(int)])

    flat = np.array([0, 5, 11], dtype="float32")
    coords = nd.unravel_index(nd.array(flat), shape=(4, 3)).asnumpy()
    assert np.allclose(coords, np.stack(np.unravel_index([0, 5, 11],
                                                         (4, 3))))
    back = nd.ravel_multi_index(nd.array(coords.astype("float32")),
                                shape=(4, 3)).asnumpy()
    assert np.allclose(back, [0, 5, 11])


def test_index_ops_and_boolean_mask():
    old = np.zeros((4, 3), dtype="float32")
    new = np.ones((2, 3), dtype="float32")
    out = nd.contrib.index_copy(nd.array(old),
                                nd.array(np.array([1, 3], "float32")),
                                nd.array(new)).asnumpy()
    assert out[1].all() and out[3].all() and not out[0].any()

    data = np.arange(12, dtype="float32").reshape(4, 3)
    mask = np.array([1, 0, 1, 0], dtype="float32")
    got = nd.contrib.boolean_mask(nd.array(data), nd.array(mask)).asnumpy()
    assert np.allclose(got, data[[0, 2]])

    x = nd.array(np.zeros((2, 3), "float32"))
    ia = nd.contrib.index_array(x).asnumpy()
    assert ia.shape == (2, 3, 2)
    assert ia[1, 2, 0] == 1 and ia[1, 2, 1] == 2

    al = nd.contrib.arange_like(x, axis=1).asnumpy()
    assert np.allclose(al, [0, 1, 2])


def test_detection_ops_in_symbol_graph():
    """Detection ops compose in the symbolic path too."""
    import mxnet_tpu.symbol as sym
    data = sym.Variable("data")
    anchors = sym.MultiBoxPrior(data, sizes=(0.3,), ratios=(1.0,))
    ex = anchors.bind(mx.cpu(), {"data": nd.zeros((1, 3, 2, 2))})
    out = ex.forward()[0].asnumpy()
    assert out.shape == (1, 4, 4)


def test_multibox_target_padded_rows_dont_clobber():
    """Regression: a padded (cls=-1) label row argmaxes to anchor 0 and
    must not clobber a valid gt's forced bipartite match there."""
    anchors = np.array([[[0.0, 0.0, 0.4, 0.4],
                         [0.6, 0.6, 1.0, 1.0]]], dtype="float32")
    # gt IoU with anchor0 = 0.25 < threshold -> only the forced
    # bipartite stage assigns it
    label = np.array([[[1, 0.0, 0.0, 0.2, 0.2],
                       [-1, 0, 0, 0, 0]]], dtype="float32")
    cls_pred = np.zeros((1, 3, 2), dtype="float32")
    _, _, cls_t = nd.MultiBoxTarget(nd.array(anchors), nd.array(label),
                                    nd.array(cls_pred),
                                    overlap_threshold=0.5)
    assert cls_t.asnumpy()[0, 0] == 2  # gt class 1 -> target 2


def test_box_nms_topk_counts_valid_only():
    """Regression: background rows must not consume topk slots."""
    data = np.array([[
        [0, 0.9, 0.0, 0.0, 1.0, 1.0],     # background (id 0)
        [1, 0.8, 2.0, 2.0, 3.0, 3.0],     # valid class-1 box
    ]], dtype="float32")
    out = nd.contrib.box_nms(nd.array(data), overlap_thresh=0.5, topk=1,
                             coord_start=2, score_index=1, id_index=0,
                             background_id=0).asnumpy()[0]
    assert (out[:, 1] > 0).sum() == 1
    assert out[out[:, 1] > 0][0][0] == 1  # the class-1 box survived


def test_mrcnn_mask_target_basic():
    """Round-4: _contrib_mrcnn_mask_target crops each roi's MATCHED gt
    mask and one-hot-scatters it to the class channel."""
    import numpy as np
    from mxnet_tpu import nd

    B, N, M, H, W, C, MS = 1, 3, 2, 8, 8, 4, 4
    gt = np.zeros((B, M, H, W), np.float32)
    gt[0, 0, :4, :] = 1.0          # instance 0: top half
    gt[0, 1, :, :4] = 1.0          # instance 1: left half
    rois = np.array([[[0, 0, 7, 7],          # whole image
                      [0, 0, 7, 7],
                      [0, 0, 7, 7]]], np.float32)
    matches = np.array([[0, 1, 0]], np.int32)
    cls_t = np.array([[2, 1, 0]], np.int32)   # roi2 = background

    t, w = nd.contrib.mrcnn_mask_target(
        nd.array(rois), nd.array(gt), nd.array(matches),
        nd.array(cls_t), num_rois=N, num_classes=C, mask_size=(MS, MS),
        aligned=True)
    t, w = t.asnumpy(), w.asnumpy()
    assert t.shape == (B, N, C, MS, MS) and w.shape == t.shape

    # weights: one-hot at cls-1 for positives, all-zero for background
    assert w[0, 0, 1].min() == 1.0 and w[0, 0].sum() == MS * MS
    assert w[0, 1, 0].min() == 1.0 and w[0, 1].sum() == MS * MS
    assert w[0, 2].sum() == 0.0

    # targets: roi 0 matched the top-half mask -> top rows ~1, bottom ~0
    m0 = t[0, 0, 1]
    assert m0[0].mean() > 0.9 and m0[-1].mean() < 0.1
    # roi 1 matched the left-half mask -> left cols ~1, right ~0
    m1 = t[0, 1, 0]
    assert m1[:, 0].mean() > 0.9 and m1[:, -1].mean() < 0.1
    # background roi contributes nothing
    assert np.abs(t[0, 2]).sum() == 0.0
    # non-target channels are zero
    assert np.abs(t[0, 0, [0, 2, 3]]).sum() == 0.0


def test_mrcnn_mask_target_roi_crop_region():
    """A roi covering only a quadrant crops that quadrant of the mask."""
    import numpy as np
    from mxnet_tpu import nd

    gt = np.zeros((1, 1, 16, 16), np.float32)
    gt[0, 0, :8, :8] = 1.0                       # top-left quadrant on
    rois = np.array([[[0, 0, 7.0, 7.0],          # inside the quadrant
                      [8.0, 8.0, 15.0, 15.0]]], np.float32)  # outside
    matches = np.zeros((1, 2), np.int32)
    cls_t = np.ones((1, 2), np.int32)

    t, w = nd.contrib.mrcnn_mask_target(
        nd.array(rois), nd.array(gt), nd.array(matches),
        nd.array(cls_t), num_rois=2, num_classes=2, mask_size=(4, 4),
        aligned=True)
    t = t.asnumpy()
    assert t[0, 0, 0].mean() > 0.9               # fully inside the mask
    assert t[0, 1, 0].mean() < 0.1               # fully outside


def test_mrcnn_mask_target_data_path():
    """End-to-end instance-mask data path: the synthetic instance-seg
    dataset feeds _contrib_mrcnn_mask_target and the generated targets
    reconstruct the gt masks for positive rois (round-4 item #8)."""
    import numpy as np
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.data.vision.datasets import \
        SyntheticInstanceSegDataset

    ds = SyntheticInstanceSegDataset(num_samples=2, size=32,
                                     max_instances=2, seed=3)
    img, lab = ds[0]
    boxes = lab["boxes"].asnumpy()[None]          # (1, M, 4) as rois
    masks = lab["masks"].asnumpy()[None]          # (1, M, 32, 32)
    classes = lab["classes"].asnumpy().astype("int32")[None]
    M = boxes.shape[1]
    matches = np.arange(M, dtype=np.int32)[None]  # roi i <- gt i

    t, w = nd.contrib.mrcnn_mask_target(
        nd.array(boxes), nd.array(masks), nd.array(matches),
        nd.array(classes), num_rois=M, num_classes=3,
        mask_size=(14, 14), aligned=True)
    t, w = t.asnumpy(), w.asnumpy()
    for i in range(M):
        c = int(classes[0, i])
        if c == 0:
            assert w[0, i].sum() == 0
            continue
        # the roi is the instance's own box, so the aligned crop of its
        # mask must be mostly ones (boundary bins may interpolate)
        assert t[0, i, c - 1].mean() > 0.7, (i, c, t[0, i, c - 1].mean())
        assert w[0, i, c - 1].min() == 1.0
