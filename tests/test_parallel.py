"""Parallelism tests on the virtual 8-device CPU mesh (SURVEY.md §4.5:
distributed tests without a real cluster)."""
import numpy as np
import os
import pytest

import mxnet_tpu as mx


def test_make_mesh():
    import jax
    from mxnet_tpu.parallel import make_mesh
    n = len(jax.devices())
    assert n == 8, "conftest should provide 8 virtual devices"
    mesh = make_mesh({"dp": -1})
    assert mesh.shape["dp"] == 8
    mesh = make_mesh({"dp": 4, "tp": 2})
    assert mesh.shape == {"dp": 4, "tp": 2}
    with pytest.raises(mx.MXNetError):
        make_mesh({"dp": 3})


@pytest.mark.slow
def test_data_parallel_trainer_matches_single_device():
    """Sharded dp training must match the math of plain training."""
    import jax
    from mxnet_tpu import nd, gluon, autograd
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import DataParallelTrainer, make_mesh

    np.random.seed(0)
    X = np.random.randn(16, 6).astype("float32")
    Y = (X @ np.random.randn(6, 1).astype("float32"))

    def build():
        net = nn.Dense(1, use_bias=False)
        net.initialize(mx.initializer.Zero())
        return net

    # plain eager reference
    net_ref = build()
    tr = gluon.Trainer(net_ref.collect_params(), "sgd",
                       {"learning_rate": 0.05})
    loss_fn = gluon.loss.L2Loss()
    for _ in range(5):
        with autograd.record():
            L = loss_fn(net_ref(nd.array(X)), nd.array(Y))
        L.backward()
        tr.step(16)
    w_ref = net_ref.weight.data().asnumpy()

    # sharded dp over 8 devices
    net_dp = build()
    mesh = make_mesh({"dp": 8})
    dpt = DataParallelTrainer(net_dp, loss_fn, "sgd",
                              {"learning_rate": 0.05}, mesh=mesh)
    for _ in range(5):
        loss = dpt.step(nd.array(X), nd.array(Y))
    dpt.sync_back()
    w_dp = net_dp.weight.data().asnumpy()
    assert np.allclose(w_ref, w_dp, rtol=1e-4, atol=1e-5), \
        (w_ref, w_dp)


@pytest.mark.slow
def test_transformer_train_step_dp_tp():
    """Full transformer step over dp x tp mesh compiles and decreases
    loss."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.models import transformer as T

    mesh = make_mesh({"dp": 4, "tp": 2})
    cfg = T.bert_tiny(use_flash=False, remat=False, dropout=0.0)
    init_state, step = T.make_train_step(cfg, mesh=mesh,
                                         learning_rate=1e-3)
    state = init_state(jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(1)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (8, 128), 0,
                                cfg.vocab_size)
    labels = jnp.where(jnp.arange(128)[None] % 5 == 0, tokens, -100)
    batch = {"tokens": tokens, "labels": labels,
             "mask": jnp.ones((8, 128), dtype=bool)}
    losses = []
    for i in range(8):
        state, loss = step(state, batch, jax.random.fold_in(rng, i))
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_param_shardings_layout():
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.models import transformer as T
    from jax.sharding import PartitionSpec as P
    mesh = make_mesh({"dp": 4, "tp": 2})
    cfg = T.bert_tiny()
    sh = T.param_shardings(cfg, mesh)
    assert sh["layers"][0]["w1"].spec == P(None, "tp")
    assert sh["layers"][0]["w2"].spec == P("tp", None)
    assert sh["emb_ln"]["g"].spec == P()


def test_kvstore_multi_device_contexts():
    """Reference-style per-device replicas reduce correctly (the legacy
    Trainer path) on virtual devices."""
    from mxnet_tpu import nd
    kv = mx.kvstore.create("device")
    vals = [nd.ones((4,), ctx=mx.tpu(i)) * (i + 1) for i in range(4)]
    kv.init("w", nd.zeros((4,)))
    kv.push("w", vals)
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    assert np.allclose(out.asnumpy(), 1 + 2 + 3 + 4)


@pytest.mark.slow
def test_data_parallel_amp_learns():
    """amp=True (bf16 compute, f32 master) still converges."""
    import numpy as np
    from mxnet_tpu import nd, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import DataParallelTrainer, make_mesh

    np.random.seed(0)
    X = np.random.randn(32, 10).astype("float32")
    W = np.random.randn(10, 3).astype("float32")
    Y = (X @ W).argmax(1)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"), nn.Dense(3))
    net.initialize(mx.initializer.Xavier())
    tr = DataParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             "sgd", {"learning_rate": 0.5},
                             mesh=make_mesh({"dp": 8}), amp=True)
    losses = [float(tr.step(nd.array(X), nd.array(Y)).asnumpy())
              for _ in range(12)]
    assert losses[-1] < losses[0] * 0.5, losses


@pytest.mark.slow
def test_data_parallel_bn_stats_update():
    """BatchNorm running stats must survive the jitted train step (the
    mutate=(3,4) contract carries through to the trainer state)."""
    import numpy as np
    from mxnet_tpu import nd, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import DataParallelTrainer, make_mesh

    np.random.seed(0)
    X = np.random.randn(16, 4, 5, 5).astype("float32") * 2 + 1
    Y = np.random.randint(0, 2, (16,))
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(4, 3, padding=1), nn.BatchNorm(),
                nn.Activation("relu"), nn.GlobalAvgPool2D(),
                nn.Dense(2))
    net.initialize(mx.initializer.Xavier())
    net(nd.array(X))  # materialize deferred shapes
    bn = [b for b in net._children.values()
          if isinstance(b, nn.BatchNorm)][0]
    before = bn.running_mean.data().asnumpy().copy()
    tr = DataParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             "sgd", {"learning_rate": 0.1},
                             mesh=make_mesh({"dp": 8}))
    for _ in range(4):
        tr.step(nd.array(X), nd.array(Y))
    tr.sync_back()
    after = bn.running_mean.data().asnumpy()
    assert np.abs(after - before).max() > 1e-4


def test_multihost_single_process():
    """Single-process initialize is a no-op that still exposes the
    rank/num_hosts/global_mesh surface (reference: kvstore rank/size)."""
    from mxnet_tpu.parallel import multihost
    multihost.initialize()
    assert multihost.is_initialized()
    assert multihost.rank() == 0
    assert multihost.num_hosts() == 1
    assert len(multihost.local_devices()) == 8
    mesh = multihost.global_mesh({"dp": -1})
    assert mesh.shape["dp"] == 8
    multihost.shutdown()
    assert not multihost.is_initialized()


@pytest.mark.slow
def test_data_parallel_zero1_matches():
    """DataParallelTrainer(shard_optimizer=True) trains identically."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding
    from mxnet_tpu import nd, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import DataParallelTrainer, make_mesh

    np.random.seed(0)
    X = np.random.randn(16, 8).astype("float32")
    Y = np.random.randint(0, 3, (16,))

    def run(shard):
        mx.random.seed(7)
        np.random.seed(7)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(16, activation="relu"), nn.Dense(3))
        net.initialize(mx.initializer.Xavier())
        tr = DataParallelTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
            {"learning_rate": 0.05}, mesh=make_mesh({"dp": 8}),
            shard_optimizer=shard)
        losses = [float(tr.step(nd.array(X), nd.array(Y)).asnumpy())
                  for _ in range(5)]
        if shard:
            specs = [str(l.sharding.spec) for l in
                     jax.tree_util.tree_leaves(tr._state[1])
                     if isinstance(l.sharding, NamedSharding)]
            assert any("dp" in s for s in specs), specs
        return losses

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5)


def test_run_steps_matches_python_loop():
    """The device-side multi-step loop (one jitted lax.scan dispatch)
    must produce the same trajectory as K individual step() calls, in
    both data modes (batch reuse and (K, batch, ...) superbatch)."""
    from mxnet_tpu import nd, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import DataParallelTrainer, make_mesh

    np.random.seed(1)
    K, B = 4, 16
    Xs = np.random.randn(K, B, 6).astype("float32")
    Ys = np.einsum("kbi,io->kbo", Xs,
                   np.random.randn(6, 1).astype("float32"))

    def build():
        net = nn.Dense(1, use_bias=False)
        net.initialize(mx.initializer.Zero())
        return net

    def make(net):
        return DataParallelTrainer(net, gluon.loss.L2Loss(), "sgd",
                                   {"learning_rate": 0.05},
                                   mesh=make_mesh({"dp": 8}))

    # reference: python loop over the superbatch
    net_ref = build()
    tr_ref = make(net_ref)
    ref_losses = [float(tr_ref.step(nd.array(Xs[k]),
                                    nd.array(Ys[k])).asnumpy())
                  for k in range(K)]
    tr_ref.sync_back()
    w_ref = net_ref.weight.data().asnumpy()

    # superbatch mode: one dispatch
    net_sb = build()
    tr_sb = make(net_sb)
    losses = tr_sb.run_steps(nd.array(Xs), nd.array(Ys)).asnumpy()
    tr_sb.sync_back()
    assert losses.shape == (K,)
    assert np.allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)
    assert np.allclose(net_sb.weight.data().asnumpy(), w_ref,
                       rtol=1e-5, atol=1e-6)

    # reuse mode: same batch every step == python loop on that batch
    net_r1, net_r2 = build(), build()
    tr1, tr2 = make(net_r1), make(net_r2)
    for _ in range(3):
        tr1.step(nd.array(Xs[0]), nd.array(Ys[0]))
    losses2 = tr2.run_steps(nd.array(Xs[0]), nd.array(Ys[0]),
                            steps=3).asnumpy()
    tr1.sync_back(); tr2.sync_back()
    assert losses2.shape == (3,)
    assert np.allclose(net_r1.weight.data().asnumpy(),
                       net_r2.weight.data().asnumpy(),
                       rtol=1e-5, atol=1e-6)
    tr2.sync()  # exercises the hard sync path


@pytest.mark.slow
def test_multichip_dryrun_no_involuntary_remat():
    """The full multi-chip dryrun (dp/sp/tp, pp/dp, dp/ep/tp meshes with
    ZeRO-1) must compile without SPMD 'Involuntary full
    rematerialization' — those replicate-then-reshard transitions are
    what kills scaling on real hardware (round-1 verdict item #2).
    Subprocess because the warning is emitted by XLA C++ on stderr."""
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "__graft_entry__.py"),
         "multichip", "8"],
        capture_output=True, text=True, timeout=500,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PALLAS_AXON_POOL_IPS": ""})
    assert r.returncode == 0, r.stderr[-2000:]
    # 3 transformer mesh configs + the conv+BN dp config (round 4)
    assert r.stdout.count("loss") == 4, r.stdout
    assert "Involuntary full rematerialization" not in r.stderr, \
        r.stderr[-3000:]


@pytest.mark.slow
def test_data_parallel_bn_is_global_stats():
    """Pin BatchNorm semantics under GSPMD dp (round-4 verdict item #2).

    GSPMD is semantics-preserving: ``jnp.mean`` over the batch axis of a
    dp-sharded array is the GLOBAL batch mean (XLA inserts the
    cross-replica reduce), so a dp-sharded ``nn.BatchNorm`` computes
    SyncBatchNorm statistics — unlike reference MXNet's data-parallel
    BN, which normalizes each device's shard with per-device stats
    (upstream SyncBatchNorm was the separate opt-in:
    ``src/operator/contrib/sync_batch_norm-inl.h``).  This test builds a
    batch whose two dp shards have wildly different means, so the two
    semantics produce far-apart losses, and asserts the dp loss equals
    the global-stats loss.  docs/architecture.md "BatchNorm under
    GSPMD" documents the contract.
    """
    import numpy as np
    from mxnet_tpu import nd, gluon, autograd
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import DataParallelTrainer, make_mesh

    np.random.seed(0)
    N, D = 4, 8                             # per-shard batch, dp degree
    shards = [np.random.randn(N, 4, 6, 6).astype("float32")
              + 10.0 * (i - D / 2) for i in range(D)]
    X = np.concatenate(shards)              # shard means far apart
    Y = np.tile(np.arange(2), N * D // 2).astype("int64")

    def build():
        np.random.seed(42)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Conv2D(4, 3, padding=1), nn.BatchNorm(),
                    nn.Activation("relu"), nn.GlobalAvgPool2D(),
                    nn.Dense(2))
        net.initialize(mx.initializer.Xavier(rnd_type="uniform",
                                             magnitude=2.0))
        net(nd.array(X[:2]))
        return net

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    # dp=8: first step's loss, before any update
    tr = DataParallelTrainer(build(), loss_fn, "sgd",
                             {"learning_rate": 0.0},
                             mesh=make_mesh({"dp": D}))
    loss_dp = float(tr.step(nd.array(X), nd.array(Y)).asnumpy())

    # global-stats single-device run (train mode => batch stats)
    net = build()
    with autograd.record():
        l_global = loss_fn(net(nd.array(X)), nd.array(Y))
    loss_global = float(l_global.mean().asnumpy())

    # per-device-stats run: each shard normalized with its own stats
    net = build()
    with autograd.record():
        ls = [loss_fn(net(nd.array(s)),
                      nd.array(Y[i * N:(i + 1) * N])).mean()
              for i, s in enumerate(shards)]
    loss_perdev = float(sum(l.asnumpy() for l in ls)) / D

    # the two semantics must actually be distinguishable on this data
    assert abs(loss_global - loss_perdev) > 1e-2, \
        (loss_global, loss_perdev)
    # and the dp run must match the GLOBAL (SyncBatchNorm) semantics
    assert abs(loss_dp - loss_global) < 1e-3, \
        ("dp loss %.5f, global %.5f, perdev %.5f"
         % (loss_dp, loss_global, loss_perdev))
