"""Tests for the testing toolkit itself (SURVEY.md §4 oracles)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import test_utils as tu


def test_assert_almost_equal_pass_and_fail():
    a = np.ones((3, 3), np.float32)
    tu.assert_almost_equal(a, a + 1e-7)
    with pytest.raises(AssertionError):
        tu.assert_almost_equal(a, a + 1.0)
    with pytest.raises(AssertionError):
        tu.assert_almost_equal(a, np.ones((3, 2), np.float32))


def test_assert_almost_equal_ndarray():
    a = mx.nd.ones((2, 2))
    tu.assert_almost_equal(a, np.ones((2, 2)))


def test_check_numeric_gradient_elemwise():
    tu.check_numeric_gradient(
        lambda x: (x * x).sum(),
        [np.random.randn(3, 4)])


def test_check_numeric_gradient_dot():
    tu.check_numeric_gradient(
        lambda a, b: mx.nd.dot(a, b),
        [np.random.randn(3, 4), np.random.randn(4, 2)])


@pytest.mark.slow
def test_check_numeric_gradient_catches_wrong_grad():
    # exp's gradient is exp(x); sqrt(x)'s is not — a deliberately wrong
    # pairing must FAIL the oracle.
    with pytest.raises(AssertionError):
        tu.check_numeric_gradient(
            lambda x: mx.nd.sqrt(mx.nd.abs(x) + 2.0) + x.detach() * 0 +
            mx.nd.exp(x * 0) * 0 + _wrong(x),
            [np.random.rand(3) + 0.5])


def _wrong(x):
    # a custom Function with an intentionally wrong backward
    class Bad(mx.autograd.Function):
        def forward(self, a):
            return a * 2

        def backward(self, g):
            return g * 3.0  # wrong: should be 2.0

    return Bad()(x)


def test_check_consistency_dtypes():
    # same ctx, two dtypes — exercises the tolerance machinery end to end
    tu.check_consistency(
        lambda x: mx.nd.exp(x),
        [np.random.randn(4, 4)],
        ctx_list=[mx.cpu(), mx.cpu()],
        dtypes=[np.float32, np.float16])


def test_rand_ndarray_shape():
    a = tu.rand_ndarray((2, 5))
    assert a.shape == (2, 5)
