"""In-engine speculative decode + fused-kernel combos (round 11).

The acceptance bar is the round-7 one, extended: whatever the drafter
proposes, whichever attention kernel runs, and however many tokens a
verify step commits, f32 greedy engine outputs are TOKEN-IDENTICAL to
``models/gpt.py generate`` — through admission waves, preemption/
recompute, eos-mid-commit, and forced rejections.  Slow tier, group g
(its own group so the extra step-program compiles never stretch group
d past its budget — the round-10 group-f precedent).
"""
import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401  (conftest device setup)


def _cfg(**kw):
    from mxnet_tpu.models import gpt
    base = dict(use_flash=False, remat=False, dropout=0.0,
                dtype="float32", vocab_size=128, max_len=64)
    base.update(kw)
    return gpt.gpt_tiny(**base)


def _ref(params, cfg, prompt, n, **kw):
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt
    return np.asarray(
        gpt.generate(params, cfg, jnp.asarray(prompt)[None], n,
                     **kw))[0]


@pytest.mark.slow
@pytest.mark.parametrize("kernel", ["xla", "pallas"])
def test_spec_token_identical_mixed_lengths(kernel):
    """Speculation on (ngram drafter) × both attention kernels: a
    mixed prompt/output-length batch with admission waves decodes
    token-identically to plain generate, rejected drafts roll back by
    pointer, and the drafted/accepted ledger stays consistent."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.serving import ServingEngine

    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.RandomState(0)
    shapes = [(5, 8), (3, 12), (9, 4), (2, 6)]
    eng = ServingEngine(params, cfg, num_slots=3, page_size=4,
                        prefill_chunk=6, spec_K=3, kernel=kernel)
    reqs = [(eng.submit(rng.randint(1, 90, P).astype(np.int32), N), N)
            for P, N in shapes]
    outs = eng.run()
    for rid, N in reqs:
        np.testing.assert_array_equal(
            outs[rid], _ref(params, cfg, eng.requests[rid].prompt, N))
    assert eng.stats["spec_drafted"] > 0
    assert 0 <= eng.stats["spec_accepted"] <= eng.stats["spec_drafted"]
    assert eng.cache.pages_in_use == 0


@pytest.mark.slow
def test_spec_forced_rejection_rollback():
    """An ADVERSARIAL drafter (constant proposals) must degrade to
    plain decode, never corrupt it: rejected draft k/v sits in cache
    slots past the committed pointer and is overwritten before any
    mask exposes it.  An ORACLE drafter (replays the reference
    continuation) must accept everything and cut the step count —
    proving the accept path actually commits multiple tokens."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.serving import ServingEngine

    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(7), cfg)
    rng = np.random.RandomState(2)
    prompt = rng.randint(1, 90, 6).astype(np.int32)
    N = 20
    ref = _ref(params, cfg, prompt, N)

    # adversarial: always propose token 1 — (essentially) always wrong
    eng = ServingEngine(params, cfg, num_slots=2, page_size=4,
                        spec_K=4,
                        spec_drafter=lambda toks, K: np.ones(K,
                                                             np.int32))
    rid = eng.submit(prompt, N)
    outs = eng.run()
    np.testing.assert_array_equal(outs[rid], ref)
    assert eng.stats["spec_drafted"] > 0
    assert eng.stats["spec_accepted"] < eng.stats["spec_drafted"]

    # oracle: replay the reference continuation — all drafts accepted,
    # steps shrink accordingly (the batched-verify commit machinery)
    full = ref

    def oracle(tokens, K):
        n = tokens.size
        out = np.ones(K, np.int32)
        avail = full[n:n + K]
        out[:avail.size] = avail
        return out

    eng2 = ServingEngine(params, cfg, num_slots=2, page_size=4,
                         spec_K=4, spec_drafter=oracle)
    rid2 = eng2.submit(prompt, N)
    outs2 = eng2.run()
    np.testing.assert_array_equal(outs2[rid2], ref)
    assert eng2.stats["spec_accepted"] == eng2.stats["spec_drafted"]
    # N tokens in ceil(N / (K+1)) decode steps + prefill
    assert eng2.stats["steps"] < eng.stats["steps"]


@pytest.mark.slow
def test_spec_preemption_recompute_exact():
    """The acceptance criterion's preemption/resume path with
    speculation armed: an over-committed pool (draft rows deepen page
    demand, so preemptions fire) still yields token-identical outputs
    for every request after recompute."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.serving import ServingEngine

    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(9), cfg)
    rng = np.random.RandomState(3)
    eng = ServingEngine(params, cfg, num_slots=4, page_size=4,
                        pages_per_slot=8, num_pages=12,
                        prefill_chunk=4, spec_K=2)
    reqs = []
    for P, N in [(6, 20), (4, 24), (8, 16), (3, 22), (5, 18)]:
        rid = eng.submit(rng.randint(1, 90, P).astype(np.int32), N)
        reqs.append((rid, N))
    outs = eng.run()
    assert eng.stats["preemptions"] > 0, \
        "pool was sized to force preemption"
    for rid, N in reqs:
        np.testing.assert_array_equal(
            outs[rid], _ref(params, cfg, eng.requests[rid].prompt, N))
    assert eng.cache.pages_in_use == 0


@pytest.mark.slow
def test_spec_eos_mid_commit():
    """eos inside an accepted draft run truncates the commit exactly
    where plain decode would have stopped — tokens past the eos in
    the same verify step are dropped, not delivered."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.serving import ServingEngine

    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(13), cfg)
    prompt = np.arange(1, 6, dtype=np.int32)
    ref = _ref(params, cfg, prompt, 12)
    eos = int(ref[8])                     # a token greedy WILL emit
    full = ref

    def oracle(tokens, K):
        n = tokens.size
        out = np.ones(K, np.int32)
        avail = full[n:n + K]
        out[:avail.size] = avail
        return out

    eng = ServingEngine(params, cfg, num_slots=1, page_size=4,
                        spec_K=4, spec_drafter=oracle)
    rid = eng.submit(prompt, 12, eos_id=eos)
    outs = eng.run()
    assert outs[rid].size <= ref.size
    assert outs[rid][-1] == eos
    np.testing.assert_array_equal(outs[rid], ref[:outs[rid].size])


@pytest.mark.slow
def test_spec_int8_kv_agreement():
    """Speculation over the paged int8-KV cache: greedy agreement with
    contiguous ``generate(kv_int8=True)`` at the round-7 tolerance
    (page-view gathers reduce in a different order — bit equality is
    not the int8 contract)."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.serving import ServingEngine

    cfg = _cfg(vocab_size=512, d_model=128, n_heads=4, n_layers=3,
               d_ff=256)
    params = T.init_params(jax.random.PRNGKey(11), cfg)
    rng = np.random.RandomState(4)
    eng = ServingEngine(params, cfg, num_slots=2, page_size=4,
                        kv_int8=True, prefill_chunk=8, spec_K=2)
    reqs = [eng.submit(rng.randint(1, 500, P).astype(np.int32), 12)
            for P in (5, 7)]
    outs = eng.run()
    for rid in reqs:
        ref = _ref(params, cfg, eng.requests[rid].prompt, 12,
                   kv_int8=True)
        assert (outs[rid] == ref).mean() >= 0.9, (outs[rid], ref)


@pytest.mark.slow
def test_spec_counters_and_validation():
    """spec_K=0 must be byte-for-byte the round-7 engine (no draft
    rows, zero spec counters); bad spec args raise; a drafter
    returning the wrong shape raises at plan time."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.serving import ServingEngine

    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(1), cfg)
    eng = ServingEngine(params, cfg, num_slots=2, page_size=4)
    assert eng.n_rows == 2 + 8            # num_slots + prefill_chunk
    rid = eng.submit(np.arange(1, 6, dtype=np.int32), 6)
    eng.run()
    assert eng.stats["spec_drafted"] == 0
    assert eng.requests[rid].state == "done"
    with pytest.raises(ValueError):
        ServingEngine(params, cfg, num_slots=1, page_size=4, spec_K=-1)
    with pytest.raises(ValueError):
        ServingEngine(params, cfg, num_slots=1, page_size=4,
                      spec_drafter=3)
    bad = ServingEngine(params, cfg, num_slots=1, page_size=4,
                        spec_K=2,
                        spec_drafter=lambda t, K: np.ones(K + 1,
                                                          np.int32))
    bad.submit(np.arange(1, 6, dtype=np.int32), 6)
    with pytest.raises(ValueError):
        bad.run()
