"""INT8 quantization tests (reference: tests/python/quantization/
test_quantization.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.contrib import quantization as qz


def test_quantize_dequantize_int8_roundtrip():
    np.random.seed(0)
    x = np.random.uniform(-3, 3, (4, 7)).astype(np.float32)
    a = nd.array(x)
    q, mn, mx_ = nd.quantize_v2(a, out_type="int8")
    assert q.dtype == np.int8
    back = nd.dequantize(q, mn, mx_)
    # max quantization error is half a level: range/127/2
    r = np.max(np.abs(x))
    assert np.max(np.abs(back.asnumpy() - x)) <= r / 127.0 + 1e-6


def test_quantize_uint8():
    x = np.random.uniform(0, 5, (3, 5)).astype(np.float32)
    a = nd.array(x)
    q, mn, mx_ = nd.quantize(a, nd.array(0.0), nd.array(5.0),
                             out_type="uint8")
    assert q.dtype == np.uint8
    back = nd.dequantize(q, mn, mx_)
    assert np.max(np.abs(back.asnumpy() - x)) <= 5.0 / 255.0 + 1e-6


def test_quantized_fully_connected_matches_fp32():
    np.random.seed(1)
    x = np.random.uniform(-1, 1, (8, 16)).astype(np.float32)
    w = np.random.uniform(-1, 1, (4, 16)).astype(np.float32)
    b = np.random.uniform(-1, 1, (4,)).astype(np.float32)

    ref = x @ w.T + b

    qx, mnx, mxx = nd.quantize_v2(nd.array(x), out_type="int8")
    qw, mnw, mxw = nd.quantize_v2(nd.array(w), out_type="int8")
    qb, mnb, mxb = nd.quantize_v2(nd.array(b), out_type="int8")
    out32, mno, mxo = nd.quantized_fully_connected(
        qx, qw, qb, mnx, mxx, mnw, mxw, mnb, mxb, num_hidden=4)
    assert out32.dtype == np.int32
    out = nd.dequantize(out32, mno, mxo).asnumpy()
    # int8 quantization of both operands: ~1% relative error on this scale
    assert np.max(np.abs(out - ref)) < 0.1


def test_quantized_conv_matches_fp32():
    np.random.seed(2)
    x = np.random.uniform(-1, 1, (2, 3, 8, 8)).astype(np.float32)
    w = np.random.uniform(-1, 1, (5, 3, 3, 3)).astype(np.float32)

    ref = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                         num_filter=5, no_bias=True).asnumpy()

    qx, mnx, mxx = nd.quantize_v2(nd.array(x), out_type="int8")
    qw, mnw, mxw = nd.quantize_v2(nd.array(w), out_type="int8")
    out32, mno, mxo = nd.quantized_conv(
        qx, qw, mnx, mxx, mnw, mxw, kernel=(3, 3), num_filter=5,
        no_bias=True)
    out = nd.dequantize(out32, mno, mxo).asnumpy()
    assert np.max(np.abs(out - ref)) < 0.2


def test_requantize_int32_to_int8():
    x = np.random.uniform(-2, 2, (6, 6)).astype(np.float32)
    q, mn, mx_ = nd.quantize_v2(nd.array(x), out_type="int8")
    # promote to an int32 "accumulator" with the int32 range convention
    q32 = q.astype("int32") * (2 ** 24)
    r = float(mx_.asnumpy())
    mn32 = nd.array(-r * (2 ** 31 - 1) / (127.0 * 2 ** 24))
    mx32 = nd.array(r * (2 ** 31 - 1) / (127.0 * 2 ** 24))
    q8, mn8, mx8 = nd.requantize(q32, mn32, mx32)
    back = nd.dequantize(q8, mn8, mx8).asnumpy()
    assert np.max(np.abs(back - x)) < r / 127.0 * 2 + 1e-5


def test_optimal_threshold_kl():
    # a gaussian with a lone outlier: KL threshold should clip the outlier
    np.random.seed(3)
    arr = np.random.normal(0, 1, 20000)
    arr = np.concatenate([arr, [40.0]])
    coll = qz.LayerHistogramCollector()
    coll.collect("x", arr)
    (lo, hi), = [coll.thresholds()["x"]]
    assert hi < 20.0  # outlier clipped
    assert hi > 2.0   # bulk preserved


def test_minmax_collector():
    coll = qz.LayerOutputMinMaxCollector()
    coll.collect("x", np.array([-1.0, 2.0]))
    coll.collect("x", np.array([-3.0, 1.0]))
    assert coll.thresholds()["x"] == (-3.0, 2.0)


def _small_mlp_symbol():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    return fc2


@pytest.mark.parametrize("calib_mode", ["none", "naive", "entropy"])
@pytest.mark.slow
def test_quantize_model_end_to_end(calib_mode):
    np.random.seed(4)
    sym = _small_mlp_symbol()
    args = {
        "fc1_weight": nd.array(np.random.uniform(-1, 1, (16, 8))
                               .astype(np.float32)),
        "fc1_bias": nd.array(np.zeros(16, np.float32)),
        "fc2_weight": nd.array(np.random.uniform(-1, 1, (4, 16))
                               .astype(np.float32)),
        "fc2_bias": nd.array(np.zeros(4, np.float32)),
    }
    x = np.random.uniform(-1, 1, (32, 8)).astype(np.float32)

    fp_exe = sym.bind(ctx=mx.cpu(), args={**args, "data": nd.array(x)},
                      grad_req="null")
    ref = fp_exe.forward(is_train=False)[0].asnumpy()

    qsym, qargs, qaux = qz.quantize_model(
        sym, args, {}, data_names=("data",), ctx=mx.cpu(),
        calib_mode=calib_mode, calib_data=nd.array(x),
        quantized_dtype="int8")

    # offline weights became int8 params
    assert any(k.endswith("_quantize") for k in qargs)
    qexe = qsym.bind(ctx=mx.cpu(), args={**qargs, "data": nd.array(x)},
                     grad_req="null")
    out = qexe.forward(is_train=False)[0].asnumpy()
    assert out.shape == ref.shape
    # int8 end-to-end: loose tolerance, but must track fp32 closely
    err = np.max(np.abs(out - ref)) / (np.max(np.abs(ref)) + 1e-6)
    assert err < 0.1, "relative error %.3f too high (mode=%s)" \
        % (err, calib_mode)


def test_quantize_model_excluded_layer():
    sym = _small_mlp_symbol()
    args = {
        "fc1_weight": nd.array(np.random.uniform(-1, 1, (16, 8))
                               .astype(np.float32)),
        "fc1_bias": nd.array(np.zeros(16, np.float32)),
        "fc2_weight": nd.array(np.random.uniform(-1, 1, (4, 16))
                               .astype(np.float32)),
        "fc2_bias": nd.array(np.zeros(4, np.float32)),
    }
    qsym, qargs, _ = qz.quantize_model(
        sym, args, {}, calib_mode="none",
        excluded_sym_names=("fc2",), ctx=mx.cpu())
    # fc2 stays fp32: its weight must NOT be quantized
    assert "fc2_weight" in qargs
    assert not any(k.startswith("fc2_weight_quantize") for k in qargs)
    assert any(k.startswith("fc1_weight_quantize") for k in qargs)


def test_quantized_max_pooling_int8():
    """reduce_window init value must carry the int8 operand dtype."""
    import numpy as np
    from mxnet_tpu import nd
    data = nd.array(np.arange(-8, 8, dtype=np.int8).reshape(1, 1, 4, 4)
                    .astype("int8"))
    out, mn, mx_ = nd.quantized_pooling(
        data, nd.array([-1.0]), nd.array([1.0]),
        kernel=(2, 2), stride=(2, 2), pool_type="max")
    ref = np.array([[[[ -3, -1], [5, 7]]]], dtype=np.int8)
    np.testing.assert_array_equal(out.asnumpy(), ref)


def test_quantized_conv_uint8_activations():
    """u8 activations (zero-point-0 affine, the reference quantized-conv
    default for post-ReLU data) x s8 weights match fp32 (round-3
    missing #7)."""
    np.random.seed(4)
    x = np.random.uniform(0, 1, (2, 3, 8, 8)).astype(np.float32)  # >= 0
    w = np.random.uniform(-1, 1, (5, 3, 3, 3)).astype(np.float32)
    ref = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                         num_filter=5, no_bias=True).asnumpy()
    qx, mnx, mxx = nd.quantize_v2(nd.array(x), out_type="auto",
                                  min_calib_range=0.0,
                                  max_calib_range=float(x.max()))
    assert qx.dtype == np.uint8              # auto + min>=0 -> u8
    qw, mnw, mxw = nd.quantize_v2(nd.array(w), out_type="int8")
    out32, mno, mxo = nd.quantized_conv(
        qx, qw, mnx, mxx, mnw, mxw, kernel=(3, 3), num_filter=5,
        no_bias=True)
    out = nd.dequantize(out32, mno, mxo).asnumpy()
    assert np.max(np.abs(out - ref)) < 0.15, np.max(np.abs(out - ref))
    # auto with a negative min stays int8
    qn, _, _ = nd.quantize_v2(nd.array(x - 0.5), out_type="auto",
                              min_calib_range=-0.5,
                              max_calib_range=0.5)
    assert qn.dtype == np.int8


def test_quantized_fc_uint8_activations():
    np.random.seed(5)
    x = np.random.uniform(0, 1, (8, 16)).astype(np.float32)
    w = np.random.uniform(-1, 1, (4, 16)).astype(np.float32)
    ref = x @ w.T
    qx, mnx, mxx = nd.quantize_v2(nd.array(x), out_type="uint8",
                                  min_calib_range=0.0,
                                  max_calib_range=float(x.max()))
    qw, mnw, mxw = nd.quantize_v2(nd.array(w), out_type="int8")
    out32, mno, mxo = nd.quantized_fully_connected(
        qx, qw, mnx, mxx, mnw, mxw, num_hidden=4, no_bias=True)
    out = nd.dequantize(out32, mno, mxo).asnumpy()
    assert np.max(np.abs(out - ref)) < 0.1, np.max(np.abs(out - ref))


def test_quantized_uint8_positive_min_zero_point_correct():
    """Review regression (round 3): 'auto'-selected u8 with a POSITIVE
    calibrated min must still compute correctly — the calibrated u8
    quantization is forced to zero-point-0 (range [0, max]), because
    the compute ops assume q = x*255/max."""
    np.random.seed(6)
    x = np.random.uniform(0.5, 1.0, (8, 16)).astype(np.float32)
    w = np.random.uniform(-1, 1, (4, 16)).astype(np.float32)
    ref = x @ w.T
    qx, mnx, mxx = nd.quantize_v2(nd.array(x), out_type="auto",
                                  min_calib_range=0.5,
                                  max_calib_range=1.0)
    assert qx.dtype == np.uint8
    assert float(mnx.asnumpy()) == 0.0       # zero-point-0 range
    qw, mnw, mxw = nd.quantize_v2(nd.array(w), out_type="int8")
    out32, mno, mxo = nd.quantized_fully_connected(
        qx, qw, mnx, mxx, mnw, mxw, num_hidden=4, no_bias=True)
    out = nd.dequantize(out32, mno, mxo).asnumpy()
    assert np.max(np.abs(out - ref)) < 0.1, np.max(np.abs(out - ref))
    # explicitly-negative calibrated min cannot be u8
    with pytest.raises(mx.MXNetError):
        nd.quantize_v2(nd.array(w), out_type="uint8",
                       min_calib_range=-1.0, max_calib_range=1.0)


def test_uint8_mode_params_stay_s8():
    """Advisor regression (round 3): with quantized_dtype='uint8', the
    quantize_v2 inserted for a NON-offline weight/bias edge must be s8 —
    a u8 quantize clips the negative half of a bias to zero and the
    quantized op's rb/127 rescale then silently mis-scales it."""
    np.random.seed(7)
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("w")
    b = mx.sym.Variable("b")
    fc = mx.sym.FullyConnected(data, weight=w, bias=b, num_hidden=4,
                               name="fc")
    # offline_params EMPTY: weight and bias edges get inserted quantize_v2
    qsym = qz.quantize_symbol(fc, offline_params=(),
                              quantized_dtype="uint8")
    x = np.random.uniform(0, 1, (8, 16)).astype(np.float32)
    wv = np.random.uniform(-1, 1, (4, 16)).astype(np.float32)
    bv = np.array([-3.0, -1.0, 1.0, 3.0], np.float32)   # negative halves
    ref = x @ wv.T + bv
    exe = qsym.bind(ctx=mx.cpu(),
                    args={"data": nd.array(x), "w": nd.array(wv),
                          "b": nd.array(bv)},
                    grad_req="null")
    out = exe.forward(is_train=False)[0].asnumpy()
    err = np.max(np.abs(out - ref)) / (np.max(np.abs(ref)) + 1e-6)
    assert err < 0.1, "uint8-mode bias mis-quantized: rel err %.3f" % err
    # and the param quantizes really are s8 in the rewritten graph
    found = {}
    for node in qsym._nodes():
        if node.name in ("w_quantize", "b_quantize"):
            found[node.name] = node.attrs.get("out_type")
    assert set(found) == {"w_quantize", "b_quantize"}, \
        "param quantize nodes missing/renamed: %r" % (found,)
    assert all(t == "int8" for t in found.values()), found
