"""Hierarchical KV tiering (round 18, ISSUE 13): spill instead of
drop, install instead of recompute.  Slow tier, group l (the fast
``HostTierStore`` unit tests carry no marker).

Pins:

* spill → warm-hit reinstall is BIT-identical to ``generate`` (f32),
  including int8-KV scale pages;
* swap-out preemption resume is install-exact and bit-identical, for
  decode-phase and mid-prefill victims, f32 and int8-KV;
* the host tier's byte-budget LRU actually enforces (evicted spills
  degrade to cold — exact either way) and tier eviction of a chain
  page drops exactly its unreachable spilled descendants;
* zero leaked pages/refs/tier entries across
  spill → tier-evict → reinstall cycles;
* the ``_drop`` ordering fix: a mid-pressure spill captures page
  bytes BEFORE the free list recycles the page, so the tier copy
  never reads pages the triggering allocation already overwrote;
* the peer-fetch serving path: a spilled chain ships from host DRAM
  (``spilled_content`` + ``merge_page_content``) and grafts into a
  sibling engine bit-exactly — the in-process twin of the disagg
  FETCH path.
"""
import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401  (conftest device setup)


def _cfg(**kw):
    from mxnet_tpu.models import gpt
    base = dict(use_flash=False, remat=False, dropout=0.0,
                dtype="float32", vocab_size=128, max_len=64)
    base.update(kw)
    return gpt.gpt_tiny(**base)


def _ref(params, cfg, prompt, n, **kw):
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt
    return np.asarray(
        gpt.generate(params, cfg, jnp.asarray(prompt)[None], n,
                     **kw))[0]


def _engine(params, cfg, tier_bytes=1 << 22, **kw):
    from mxnet_tpu.serving import ServingEngine
    base = dict(num_slots=2, page_size=4, prefill_chunk=6,
                pages_per_slot=8, prefix_cache=True,
                tier_bytes=tier_bytes)
    base.update(kw)
    return ServingEngine(params, cfg, **base)


# ---------------------------------------------------------------------------
# HostTierStore unit tests (host-only: FAST tier)
# ---------------------------------------------------------------------------
def _content(n_pages=1, fill=0, nbytes_per_page=64):
    return [{"kv": np.full((n_pages, nbytes_per_page), fill,
                           np.int8)}]


def test_tier_store_lru_budget_enforced():
    from mxnet_tpu.serving import HostTierStore
    st = HostTierStore(budget_bytes=3 * 64)
    assert st.put("a", _content(fill=1), 1)
    assert st.put("b", _content(fill=2), 1)
    assert st.put("c", _content(fill=3), 1)
    assert st.bytes_held == 3 * 64 and len(st) == 3
    # d evicts the LRU (a)
    assert st.put("d", _content(fill=4), 1)
    assert "a" not in st and st.bytes_held == 3 * 64
    assert st.evictions_total == 1 and st.evicted_pages_total == 1
    # touching b protects it: e evicts c, not b
    assert st.peek("b") is not None
    assert st.put("e", _content(fill=5), 1)
    assert "b" in st and "c" not in st
    # a single entry over the whole budget is refused outright
    assert not st.put("big", _content(n_pages=4), 4)
    assert "big" not in st and len(st) == 3
    # pop accounts installs; drop does not
    e = st.pop("b")
    assert e.content[0]["kv"][0, 0] == 2
    assert st.installed_pages_total == 1
    held = st.bytes_held
    assert st.drop("d") and st.bytes_held == held - 64
    assert st.installed_pages_total == 1


def test_tier_store_evict_cb_reentrant():
    """The eviction callback may pop OTHER keys (the prefix cache
    drops unreachable spilled descendants this way) — the LRU loop
    must survive the reentrant mutation."""
    from mxnet_tpu.serving import HostTierStore
    st = HostTierStore(budget_bytes=4 * 64)
    dropped = []

    def cb(key):
        dropped.append(key)
        st.pop("child-of-%s" % key)       # reentrant removal

    st.evict_cb = cb
    st.put("r", _content(), 1)
    st.put("child-of-r", _content(), 1)
    st.put("x", _content(), 1)
    st.put("y", _content(), 1)
    # over budget: evicts "r"; its callback pops "child-of-r" too
    st.put("z", _content(n_pages=2), 2)
    assert dropped == ["r"]
    assert "child-of-r" not in st
    assert st.bytes_held == sum(e.nbytes
                                for e in st._entries.values())


def test_tier_store_replace_and_meta():
    from mxnet_tpu.serving import HostTierStore
    st = HostTierStore(budget_bytes=1 << 12)
    st.put(("swap", 7), _content(fill=1), 1, meta={"n_cached": 5})
    st.put(("swap", 7), _content(n_pages=2, fill=2), 2,
           meta={"n_cached": 9})
    assert len(st) == 1
    e = st.pop(("swap", 7))
    assert e.meta["n_cached"] == 9 and e.n_pages == 2
    assert st.bytes_held == 0


# ---------------------------------------------------------------------------
# engine-level tiering (slow)
# ---------------------------------------------------------------------------
@pytest.mark.slow
@pytest.mark.parametrize("kv_int8", [False, True],
                         ids=["f32", "int8kv"])
def test_spill_reinstall_bit_identity(kv_int8):
    """A cached chain spilled to the host tier and warm-restored
    serves the duplicate prompt bit-identically to ``generate`` —
    int8-KV moves its f32 scale pages losslessly too."""
    import jax
    from mxnet_tpu.models import transformer as T
    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.RandomState(0)
    eng = _engine(params, cfg, kv_int8=kv_int8)
    prompt = rng.randint(1, 90, 16).astype(np.int32)  # 4 full pages
    r1 = eng.submit(prompt, 5)
    out1 = eng.run()[r1]
    hot_pages = eng.prefix.cached_pages
    assert hot_pages == 4
    assert eng.prefix.spill() == 4
    assert eng.prefix.cached_pages == 0
    assert eng.prefix.spilled_pages == 4
    assert eng.cache.pages_in_use == 0                # pool drained
    assert eng.tier.pages_held == 4
    r2 = eng.submit(prompt, 5)
    out2 = eng.run()[r2]
    np.testing.assert_array_equal(out1, out2)
    if not kv_int8:
        np.testing.assert_array_equal(out2, _ref(params, cfg,
                                                 prompt, 5))
    # the warm hit restored through the tier, not a recompute
    assert eng.prefix.pages_restored_total >= 3
    assert eng.prefix.warm_hits_total == 1
    assert eng.stats["prefix_hit_tokens"] > 0
    # nothing leaked: pool pages are exactly the re-cached chain
    assert eng.prefix.refs_total == 0
    assert eng.cache.pages_in_use == eng.prefix.cached_pages


@pytest.mark.slow
@pytest.mark.parametrize("kv_int8", [False, True],
                         ids=["f32", "int8kv"])
def test_swap_resume_exact(kv_int8):
    """Preemption with the tier on: the victim's pages (and int8
    scale pages) swap out, resume installs them back, and the final
    output is bit-identical to the undisturbed oracle — for a
    decode-phase victim and a mid-prefill victim."""
    import jax
    from mxnet_tpu.models import transformer as T
    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.RandomState(1)
    oracle = {}

    def check(eng, rid, prompt, n):
        out = eng.requests[rid].output
        key = (prompt.tobytes(), n)
        if key not in oracle:
            oracle[key] = _ref(params, cfg, prompt, n)
        if not kv_int8:
            np.testing.assert_array_equal(out, oracle[key])

    # decode-phase victim
    eng = _engine(params, cfg, kv_int8=kv_int8, prefix_cache=False)
    prompt = rng.randint(1, 90, 13).astype(np.int32)
    rid = eng.submit(prompt, 8)
    req = eng.requests[rid]
    while len(req.generated) < 3:
        eng.step()
    pre_preempt = list(req.generated)
    assert eng.preempt(rid) is True       # swapped
    assert eng.stats["swap_outs"] == 1
    eng.run()
    assert eng.stats["swap_ins"] == 1
    # install-exact resume: the pre-preemption tokens were not
    # recomputed, they were already committed; the continuation
    # matches the oracle bit for bit
    assert req.generated[:len(pre_preempt)] == pre_preempt
    check(eng, rid, prompt, 8)
    assert eng.cache.pages_in_use == 0
    assert len(eng.tier._entries) == 0    # swap entry consumed

    # mid-prefill victim (pending is None at preemption)
    eng2 = _engine(params, cfg, kv_int8=kv_int8, prefix_cache=False,
                   prefill_chunk=4)
    long_p = rng.randint(1, 90, 17).astype(np.int32)
    rid2 = eng2.submit(long_p, 4)
    req2 = eng2.requests[rid2]
    eng2.step()                           # partial prefill only
    assert req2.pending is None and 0 < req2.n_cached < long_p.size
    swapped = eng2.preempt(rid2)
    assert swapped is True
    eng2.run()
    check(eng2, rid2, long_p, 4)
    assert eng2.stats["swap_ins"] == 1
    assert eng2.cache.pages_in_use == 0


@pytest.mark.slow
def test_swap_entry_evicted_degrades_to_recompute():
    """A swap entry LRU-aged out of the tier before resume: the
    request falls back to the round-7 recompute path and stays
    exact — the tier is a latency tier, never a correctness
    dependency."""
    import jax
    from mxnet_tpu.models import transformer as T
    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.RandomState(2)
    eng = _engine(params, cfg, prefix_cache=False)
    prompt = rng.randint(1, 90, 9).astype(np.int32)
    rid = eng.submit(prompt, 6)
    req = eng.requests[rid]
    while len(req.generated) < 2:
        eng.step()
    assert eng.preempt(rid) is True
    # age the swap entry out behind the engine's back
    assert eng.tier.pop(("swap", rid)) is not None
    eng.run()
    assert eng.stats["swap_ins"] == 0     # recompute path taken
    np.testing.assert_array_equal(req.output,
                                  _ref(params, cfg, prompt, 6))
    assert eng.cache.pages_in_use == 0


@pytest.mark.slow
def test_tier_budget_partial_warm_hit_and_descendant_drop():
    """A tier too small for the whole chain: the LRU keeps only the
    newest spills, ``_on_tier_evict`` drops each evicted page's
    now-unreachable spilled descendants, and the duplicate prompt
    still completes exactly (partially warm or fully cold)."""
    import jax
    from mxnet_tpu.models import transformer as T
    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.RandomState(3)
    eng = _engine(params, cfg)
    prompt = rng.randint(1, 90, 16).astype(np.int32)  # 4 pages
    r1 = eng.submit(prompt, 4)
    out1 = eng.run()[r1]
    page_bytes = eng.cache.bytes_per_page
    # shrink the budget to TWO pages, then spill the 4-page chain:
    # spills run leaf-first, so the two oldest spills (the deepest
    # pages) are evicted as the shallower ones arrive — and because
    # a chain restores root-first, every surviving key whose parent
    # was evicted must be dropped as unreachable
    eng.tier.budget_bytes = 2 * page_bytes
    eng.prefix.spill()
    # reachability invariant: every surviving spilled record's parent
    # is reachable — root, itself spilled, or still hot in the trie
    for key in eng.prefix._spilled:
        parent = key[:-4 * eng.page_size]
        if parent and parent not in eng.prefix._spilled:
            hot, _ = eng.prefix.probe_depth(
                np.frombuffer(key, np.int32))
            assert hot * eng.page_size * 4 >= len(parent), \
                "unreachable spilled key survived tier eviction"
    assert eng.tier.bytes_held <= 2 * page_bytes
    r2 = eng.submit(prompt, 4)
    out2 = eng.run()[r2]
    np.testing.assert_array_equal(out1, out2)
    assert eng.prefix.refs_total == 0
    assert eng.cache.pages_in_use == eng.prefix.cached_pages


@pytest.mark.slow
def test_spill_evict_reinstall_cycles_leak_nothing():
    """Many spill → (tier-evict) → reinstall cycles across several
    chains: refs, pool pages, spilled records, and tier bytes all
    reconcile after every drain."""
    import jax
    from mxnet_tpu.models import transformer as T
    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.RandomState(4)
    eng = _engine(params, cfg, num_pages=13, tier_bytes=1 << 20)
    prompts = [rng.randint(1, 90, 8 + 4 * i).astype(np.int32)
               for i in range(3)]
    for cycle in range(4):
        rids = [eng.submit(p, 3) for p in prompts]
        eng.run()
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(
                eng.requests[rid].output, _ref(params, cfg, p, 3))
            del eng.requests[rid]
        if cycle % 2 == 0:
            eng.prefix.spill()
        # invariants after every cycle
        assert eng.prefix.refs_total == 0
        assert eng.cache.pages_in_use == eng.prefix.cached_pages
        assert eng.tier.pages_held == eng.prefix.spilled_pages
        assert eng.tier.bytes_held == sum(
            e.nbytes for e in eng.tier._entries.values())
    # teardown path: clear() drops hot AND spilled without spilling
    eng.prefix.clear()
    assert eng.cache.pages_in_use == 0
    assert eng.prefix.spilled_pages == 0
    assert eng.tier.pages_held == 0


@pytest.mark.slow
def test_mid_pressure_spill_never_reads_recycled_pages():
    """The ``_drop`` ordering fix (ISSUE 13 small fix): the spill
    export happens BEFORE ``cache.free`` — the very allocation whose
    pressure triggered the spill immediately recycles the freed page
    and overwrites it, so an export-after-free would capture the NEW
    request's bytes.  Pin: bytes in the tier after a mid-pressure
    spill equal the chain's pre-spill export, and the later warm hit
    is bit-exact."""
    import jax
    from mxnet_tpu.models import transformer as T
    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.RandomState(5)
    # pool: 8 usable pages; chain A = 4 pages; request B needs all
    # 8 — pressure must spill A's WHOLE chain and hand its recycled
    # pages straight to B, whose prefill overwrites them this step
    eng = _engine(params, cfg, num_pages=9, num_slots=1)
    pa = rng.randint(1, 90, 16).astype(np.int32)
    ra = eng.submit(pa, 3)
    out_a = eng.run()[ra]
    assert eng.prefix.cached_pages == 4
    chain_pages = [e.page for e in eng.prefix._by_key.values()]
    golden = eng.cache.export_pages(sorted(chain_pages))
    pb = rng.randint(1, 90, 29).astype(np.int32)      # needs 8 pages
    rb = eng.submit(pb, 3)
    out_b = eng.run()[rb]
    np.testing.assert_array_equal(out_b, _ref(params, cfg, pb, 3))
    # the pressure spilled (not dropped) A's chain...
    assert eng.prefix.pages_spilled_total == 4
    assert eng.prefix.spilled_pages == 4
    # ...and the tier copy carries the PRE-recycle bytes: walk the
    # chain's content out of the tier and compare each page to the
    # pre-spill export (golden rows are in sorted-page-id order;
    # chain_pages[j] is chain position j's page id — _by_key keeps
    # insertion order, which is root-to-leaf)
    tier_run = eng.prefix.spilled_content(pa, 0)
    assert len(tier_run) == 4
    pos_of_page = {pg: i for i, pg in enumerate(sorted(chain_pages))}
    for j, content in enumerate(tier_run):
        gi = pos_of_page[chain_pages[j]]
        for layer_t, layer_g in zip(content, golden):
            for k in layer_t:
                np.testing.assert_array_equal(
                    layer_t[k][0], layer_g[k][gi],
                    err_msg="spilled page %d captured recycled "
                            "bytes" % j)
    # and the warm hit replays exactly
    r2 = eng.submit(pa, 3)
    np.testing.assert_array_equal(eng.run()[r2], out_a)


@pytest.mark.slow
def test_spilled_chain_serves_peer_fetch_exactly():
    """In-process twin of the disagg FETCH path for spilled chains:
    engine A spills its chain, ``spilled_content`` ships the host
    bytes (no pool allocation on A), engine B installs + grafts them
    and serves the prompt bit-identically — while A's pool stays
    untouched."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.serving.page_streamer import (
        bufs_to_pages, merge_page_content, pages_to_bufs)
    from mxnet_tpu.serving.prefix_cache import chain_keys
    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.RandomState(6)
    a = _engine(params, cfg)
    b = _engine(params, cfg)
    prompt = rng.randint(1, 90, 16).astype(np.int32)
    ra = a.submit(prompt, 4)
    out_a = a.run()[ra]
    a.prefix.spill()
    in_use_before = a.cache.pages_in_use
    # serve the fetch: hot head (none) + spilled tail, straight from
    # host DRAM, through the same bufs codec the wire uses
    entries, pages, m = a.prefix.match(prompt, restore=False)
    assert m == 0 and not pages           # everything spilled
    a.prefix.release(entries)
    tail = a.prefix.spilled_content(prompt, 0)
    assert len(tail) == 4
    assert a.cache.pages_in_use == in_use_before  # no A-side alloc
    bufs = pages_to_bufs(merge_page_content(tail))
    # requester side: install + graft (the _fetch_remote body)
    n = len(tail)
    ids = b.cache.alloc(n)
    b.cache.install_pages(ids, bufs_to_pages(b.cache, n, bufs))
    created = b.prefix.insert_chain(prompt[:n * b.page_size], ids,
                                    upto_page=n)
    b.prefix.release([e for _, e in created])
    rb = b.submit(prompt, 4)
    out_b = b.run()[rb]
    np.testing.assert_array_equal(out_a, out_b)
    np.testing.assert_array_equal(out_b, _ref(params, cfg, prompt, 4))
    assert b.stats["prefix_hit_tokens"] > 0
    assert b.prefix.refs_total == 0


@pytest.mark.slow
def test_match_restore_exception_releases_refs():
    """The warm-restore path allocates inside match(): an exception
    through that alloc/install (the pressure callback can raise — the
    same edge round 12's py-ref-leak fix guards in _admit) must
    release every ref the walk already took and give back any pages
    the restore allocated, or the chain pins unevictable forever."""
    import jax
    from mxnet_tpu.models import transformer as T
    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.RandomState(11)
    eng = _engine(params, cfg)
    # two chained prompts: a hot head + a spilled tail, so the match
    # holds refs on the head when the tail restore blows up
    pa = rng.randint(1, 90, 16).astype(np.int32)
    r1 = eng.submit(pa, 4)
    eng.run()
    eng.prefix.spill()
    r2 = eng.submit(pa[:8], 3)            # re-heat the chain head
    eng.run()
    h, w = eng.prefix.probe_depth(pa)
    assert h >= 1 and w >= 1              # mixed hot+spilled chain
    in_use = eng.cache.pages_in_use
    orig = eng.cache.install_pages

    def boom(*a, **k):
        raise RuntimeError("injected install failure")

    eng.cache.install_pages = boom
    try:
        with pytest.raises(RuntimeError, match="injected"):
            eng.prefix.match(pa)
    finally:
        eng.cache.install_pages = orig
    assert eng.prefix.refs_total == 0, "match leaked refs on the " \
        "restore exception edge"
    assert eng.cache.pages_in_use == in_use, \
        "restore leaked its allocated pages"
    # the popped keys' records retired with their bytes: a re-match
    # now serves the hot head and recomputes the tail — still exact
    r3 = eng.submit(pa, 4)
    np.testing.assert_array_equal(eng.run()[r3],
                                  _ref(params, cfg, pa, 4))


@pytest.mark.slow
def test_shadowed_spill_retags_hbm():
    """insert_chain dropping a spilled twin (the chain was recomputed
    hot while its bytes sat in the tier) must fire tier_cb('hbm') —
    otherwise the router's index tag stays 'host' forever, because
    report_insert ignores keys it already owns."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu.serving.prefix_cache import chain_keys
    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.RandomState(12)
    eng = _engine(params, cfg)
    moves = []
    eng.prefix.tier_cb = lambda k, t: moves.append((k, t))
    prompt = rng.randint(1, 90, 8).astype(np.int32)   # 2 pages
    r1 = eng.submit(prompt, 3)
    eng.run()
    eng.prefix.spill()
    keys = chain_keys(prompt, eng.page_size)
    # spills run leaf-first, so the host re-tags arrive deepest-first
    assert moves == [(k, "host") for k in reversed(keys)]
    # recompute the chain hot via direct donation (the shadow branch:
    # the spilled twins exist while the fresh pages insert)
    pages = eng.cache.alloc(2)
    created = eng.prefix.insert_chain(prompt, pages, upto_page=2)
    assert len(created) == 2
    assert moves[2:] == [(k, "hbm") for k in keys]
    assert eng.prefix.spilled_pages == 0
    assert eng.tier.pages_held == 0       # twins' bytes released
    eng.prefix.release([e for _, e in created])


@pytest.mark.slow
def test_swap_over_budget_skips_export():
    """A victim the tier must refuse (chain bytes > whole budget)
    pays NO device export — the budget pre-check runs before the
    gather — and the preemption degrades to recompute-exact."""
    import jax
    from mxnet_tpu.models import transformer as T
    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.RandomState(13)
    eng = _engine(params, cfg, prefix_cache=False,
                  tier_bytes=1)           # refuses everything
    prompt = rng.randint(1, 90, 12).astype(np.int32)
    rid = eng.submit(prompt, 6)
    req = eng.requests[rid]
    while len(req.generated) < 2:
        eng.step()
    calls = []
    orig = eng.cache.export_pages
    eng.cache.export_pages = lambda ids: calls.append(ids) or orig(ids)
    try:
        assert eng.preempt(rid) is False
    finally:
        eng.cache.export_pages = orig
    assert calls == [], "over-budget swap still paid the export"
    eng.run()
    np.testing.assert_array_equal(req.output,
                                  _ref(params, cfg, prompt, 6))


@pytest.mark.slow
def test_tier_metrics_reconcile():
    """The round-8 surface: serving_tier_* counters/gauges reconcile
    exactly against the store's own accounting after a scripted
    spill/restore/swap sequence."""
    import jax
    from mxnet_tpu.models import transformer as T
    from mxnet_tpu import obs as O
    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.RandomState(7)
    reg = O.MetricsRegistry()
    eng = _engine(params, cfg, metrics=True, registry=reg)
    prompt = rng.randint(1, 90, 12).astype(np.int32)
    r1 = eng.submit(prompt, 4)
    eng.run()
    eng.prefix.spill()
    r2 = eng.submit(prompt, 4)            # warm restore
    eng.run()
    rid = eng.submit(rng.randint(1, 90, 9).astype(np.int32), 6)
    req = eng.requests[rid]
    while len(req.generated) < 2:
        eng.step()
    eng.preempt(rid)                      # swap out
    eng.run()                             # swap in + finish
    snap = reg.snapshot()["counters"]
    t = eng.tier
    assert snap["serving_tier_spills_total"] == t.spilled_pages_total
    assert snap["serving_tier_installs_total"] == \
        t.installed_pages_total
    assert snap["serving_tier_bytes_total"] == t.bytes_moved_total
    assert snap["serving_swap_outs_total"] == 1
    assert snap["serving_swap_ins_total"] == 1
    assert snap["serving_prefix_warm_hit_tokens_total"] == \
        eng.prefix.warm_hit_tokens_total > 0
    g = reg.snapshot()["gauges"]
    assert g["serving_tier_pages"] == t.pages_held
    assert g["serving_tier_bytes_held"] == t.bytes_held
    assert g["serving_tier_budget_bytes"] == t.budget_bytes


@pytest.mark.slow
def test_tier_off_is_bit_identical_round17_behavior():
    """tier_bytes=0 (the default): no tier object exists, pressure
    drops, preemption recomputes — and outputs match the tiered
    engine's bit for bit (the tier moves latency, never tokens)."""
    import jax
    from mxnet_tpu.models import transformer as T
    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.RandomState(8)
    prompts = [rng.randint(1, 90, 8 + 2 * i).astype(np.int32)
               for i in range(4)]
    outs = {}
    for tb in (0, 1 << 20):
        eng = _engine(params, cfg, tier_bytes=tb, num_pages=11,
                      pages_per_slot=5)
        assert (eng.tier is None) == (tb == 0)
        rids = [eng.submit(p, 4) for p in prompts]
        got = eng.run()
        outs[tb] = [got[r] for r in rids]
        assert eng.cache.pages_in_use == eng.prefix.cached_pages
    for a, b in zip(outs[0], outs[1 << 20]):
        np.testing.assert_array_equal(a, b)
