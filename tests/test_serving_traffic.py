"""Traffic realism (round 16): trace replay, autoscaler, chaos,
goodput.

Fast tier: trace-format determinism (same seed ⇒ same hash), the
autoscaler POLICY driven synchronously through a fake metrics-only
cluster (hysteresis, cooldown, min/max budget), the histogram window,
and the chaos schedule's seed protocol.

Slow tier, group k: live scenarios on the tiny GPT — the autoscaler
scaling a real cluster up under a burst and back down with the
CHECKED zero-leak drain, chaos kill/stall under burst with bit-exact
completions vs the ``generate`` oracle, the ``serve_bench --trace``
smoke (seed + trace_sha in the JSON row), env-var-configurable
cluster limits, and disagg worker add/drain."""
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401  (conftest device setup)


def _cfg(**kw):
    from mxnet_tpu.models import gpt
    base = dict(use_flash=False, remat=False, dropout=0.0,
                dtype="float32", vocab_size=128, max_len=64)
    base.update(kw)
    return gpt.gpt_tiny(**base)


def _ref(params, cfg, prompt, n):
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt
    return np.asarray(
        gpt.generate(params, cfg, jnp.asarray(prompt)[None], n))[0]


def _setup(seed=3):
    import jax
    from mxnet_tpu.models import transformer as T
    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(seed), cfg)
    return params, cfg


def _tiny_trace(seed=0, base_rate=24.0, duration_s=1.0):
    import benchmark.traffic_trace as TT
    spec = TT.burst10x_spec(seed=seed, vocab=90, max_total=28,
                            base_rate=base_rate,
                            duration_s=duration_s,
                            prompt_max=12, out_max=10)
    return TT.generate_trace(spec)


def _assert_no_leaks(cl):
    for rep in cl.replicas:
        if rep.engine is None or rep.dead:
            continue
        eng = rep.engine
        refs = 0 if eng.prefix is None else eng.prefix.refs_total
        cached = 0 if eng.prefix is None else eng.prefix.cached_pages
        assert refs == 0, "replica %d leaks %d refs" % (rep.idx, refs)
        assert eng.cache.pages_in_use == cached, \
            "replica %d leaks pages (%d in use, %d cache-owned)" % (
                rep.idx, eng.cache.pages_in_use, cached)


# ---------------------------------------------------------------------------
# fast tier: trace format + policy + seed protocols
# ---------------------------------------------------------------------------

def test_trace_determinism_same_seed_same_hash():
    """The reproducibility contract MULTICHIP_r08 rests on: the trace
    is a pure function of its spec (seed included)."""
    import benchmark.traffic_trace as TT
    a, b = _tiny_trace(seed=11), _tiny_trace(seed=11)
    assert TT.trace_hash(a) == TT.trace_hash(b)
    assert a["events"] == b["events"]
    c = _tiny_trace(seed=12)
    assert TT.trace_hash(c) != TT.trace_hash(a)


def test_trace_shape_burst_and_clamps():
    """Arrivals sorted; lengths inside the clamps and on the prompt
    grid; the burst window's arrival density is a large multiple of
    the baseline's (the 10x claim, measured on the events)."""
    import benchmark.traffic_trace as TT
    tr = _tiny_trace(seed=4, base_rate=40.0, duration_s=2.0)
    spec = tr["spec"]
    times = [t for t, _, _ in tr["events"]]
    assert times == sorted(times)
    for _, prompt, n in tr["events"]:
        assert spec["prompt_min"] <= len(prompt) <= spec["prompt_max"]
        assert len(prompt) in spec["prompt_grid"]
        assert 1 <= n <= spec["out_max"]
        assert len(prompt) + n <= spec["max_total"]
    b0, b1 = spec["burst_at_s"], spec["burst_at_s"] + spec["burst_dur_s"]
    in_burst = sum(b0 <= t < b1 for t in times)
    outside = len(times) - in_burst
    dens_burst = in_burst / spec["burst_dur_s"]
    dens_out = outside / (spec["duration_s"] - spec["burst_dur_s"])
    assert dens_burst > 4 * dens_out, \
        "burst density %.1f/s vs baseline %.1f/s" % (dens_burst,
                                                     dens_out)


def test_goodput_classification():
    import benchmark.traffic_trace as TT
    slo = TT.SLO(ttft_ms=100.0, tbt_ms=50.0)
    # in SLO: ttft 50ms, gaps 10ms
    ok, ttft, tbt = TT.classify_request(
        0.0, [0.05, 0.06, 0.07], 3, slo)
    assert ok and ttft == pytest.approx(50.0) \
        and tbt == pytest.approx(10.0)
    # TTFT blown
    assert not TT.classify_request(0.0, [0.2, 0.21], 2, slo)[0]
    # one mid-stream stall blows the worst-gap budget
    assert not TT.classify_request(
        0.0, [0.05, 0.06, 0.2], 3, slo)[0]
    # incomplete (fewer tokens than requested) never counts
    assert not TT.classify_request(0.0, [0.05], 3, slo)[0]
    # no tokens at all (rejected/dropped)
    assert not TT.classify_request(0.0, [], 1, slo)[0]


def test_chaos_schedule_seed_protocol():
    from mxnet_tpu.serving import chaos_schedule
    a = chaos_schedule(7, 10.0, n_events=3, kinds=("kill", "stall"))
    b = chaos_schedule(7, 10.0, n_events=3, kinds=("kill", "stall"))
    assert [(e.t, e.kind) for e in a] == [(e.t, e.kind) for e in b]
    assert [e.t for e in a] == sorted(e.t for e in a)
    assert all(2.5 <= e.t <= 7.5 for e in a)
    c = chaos_schedule(8, 10.0, n_events=3, kinds=("kill", "stall"))
    assert [(e.t, e.kind) for e in a] != [(e.t, e.kind) for e in c]


def test_histogram_window_percentile():
    from mxnet_tpu.obs import Histogram
    from mxnet_tpu.serving import HistogramWindow
    h = Histogram("w")
    for v in (1.0, 2.0, 3.0):
        h.observe(v)
    win = HistogramWindow(h)
    assert win.percentile(95) is None      # cumulative history hidden
    h.observe(1000.0)
    p = win.percentile(95)
    assert p is not None and p > 500.0     # only the window's sample
    assert win.percentile(95) is None      # window consumed


class _FakeScalableCluster:
    """Metrics-only stand-in: the policy must be drivable from the
    registry alone (that is the design claim), so the fake only
    implements the actuation protocol + a registry."""

    def __init__(self, registry, slots=4):
        self.registry = registry
        self.slots_per_replica = slots
        self.ups = 0
        self.downs = 0

    def scale_up(self):
        self.ups += 1
        g = self.registry.gauge("cluster_replicas_healthy")
        g.set(g.value + 1)
        return True

    def scale_down(self, timeout=None):
        self.downs += 1
        g = self.registry.gauge("cluster_replicas_healthy")
        g.set(g.value - 1)
        return True


def test_autoscaler_policy_hysteresis_cooldown_budget():
    """The policy pinned synchronously: scale-up only after
    ``up_ticks`` sustained overload, cooldown suppresses back-to-back
    actions, scale-down only after ``down_ticks`` sustained
    underload, and the min/max budget is never crossed."""
    from mxnet_tpu.obs import MetricsRegistry
    from mxnet_tpu.serving import Autoscaler
    reg = MetricsRegistry()
    cl = _FakeScalableCluster(reg, slots=4)
    g_q = reg.gauge("cluster_queue_depth")
    g_if = reg.gauge("cluster_in_flight")
    g_h = reg.gauge("cluster_replicas_healthy")
    g_h.set(1)
    sc = Autoscaler(cl, min_size=1, max_size=2, interval_s=0.01,
                    cooldown_s=10.0, up_ticks=2, down_ticks=3,
                    up_queue_factor=1.0, down_queue_factor=0.5)
    t = 100.0
    g_q.set(50)                            # overloaded
    assert sc.tick(t) is None              # tick 1 of 2: hysteresis
    assert sc.tick(t + 1) == "up" and cl.ups == 1
    assert sc.tick(t + 2) is None          # cooldown, though overloaded
    assert sc.tick(t + 3) is None
    t += 20                                # past cooldown
    assert sc.tick(t) is None              # streak was reset by action
    assert sc.tick(t + 1) is None          # at max_size=2: budget holds
    assert cl.ups == 1
    g_q.set(0)
    g_if.set(0)                            # idle: underload streak
    t += 20
    assert sc.tick(t) is None
    assert sc.tick(t + 1) is None
    assert sc.tick(t + 2) == "down" and cl.downs == 1
    t += 40                                # past cooldown, at min_size
    for i in range(5):
        assert sc.tick(t + i) is None      # never below min_size
    assert cl.downs == 1
    assert [e["action"] for e in sc.events] == ["up", "down"]


def test_autoscaler_requires_metrics():
    from mxnet_tpu.serving import Autoscaler

    class NoMetrics:
        registry = None

    with pytest.raises(ValueError):
        Autoscaler(NoMetrics())


# ---------------------------------------------------------------------------
# slow tier (group k): live scenarios
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_autoscaler_scale_up_and_down_drains_cleanly():
    """The acceptance path minus chaos: a burst drives a real
    ServingCluster from 1 replica to >1; idling drives it back down
    to 1 via the graceful drain; NOTHING is dropped (every output
    bit-exact), the drained replica's engine held zero refs/pages at
    release (remove_replica raises otherwise), and the survivors leak
    nothing."""
    from mxnet_tpu.serving import Autoscaler, ServingCluster

    params, cfg = _setup()
    rng = np.random.RandomState(5)
    cl = ServingCluster(params, cfg, replicas=1, num_slots=2,
                        page_size=4, prefill_chunk=6, metrics=True,
                        max_queue=10 ** 6)
    sc = Autoscaler(cl, min_size=1, max_size=3, interval_s=0.02,
                    cooldown_s=0.1, up_ticks=1, down_ticks=5,
                    up_queue_factor=0.5, down_queue_factor=0.5)
    sc.start()
    try:
        wl = [(rng.randint(1, 90, 4 + (i % 5)).astype(np.int32),
               6 + (i % 4)) for i in range(24)]
        rids = [cl.submit(p, n) for p, n in wl]
        for rid, (p, n) in zip(rids, wl):
            np.testing.assert_array_equal(
                cl.result(rid, timeout=300), _ref(params, cfg, p, n))
        assert sum(e["action"] == "up" for e in sc.events) >= 1
        # idle: the scaler must come back down to min_size via the
        # leak-checked drain
        deadline = time.perf_counter() + 60
        while time.perf_counter() < deadline:
            if len(cl._healthy()) == 1:
                break
            time.sleep(0.05)
        assert len(cl._healthy()) == 1
        assert sum(e["action"] == "down" for e in sc.events) >= 1
        c = cl.metrics()["counters"]
        assert c["cluster_scale_ups_total"] >= 1
        assert c["cluster_scale_downs_total"] >= 1
        assert c["cluster_requests_completed_total"] >= len(wl)
        _assert_no_leaks(cl)
        # removed replicas really released their engines
        assert any(r.engine is None for r in cl.replicas)
    finally:
        sc.close()
        cl.close(timeout=60)


@pytest.mark.slow
def test_chaos_kill_and_stall_under_burst_exact():
    """Chaos under burst, the in-process arm: a seeded schedule kills
    one replica and stalls another past the watchdog mid-replay.
    Every request still completes BIT-IDENTICAL to the generate
    oracle, both faults show up as failovers, and no pages/refs leak
    on the survivors."""
    import benchmark.traffic_trace as TT
    from mxnet_tpu.serving import (ChaosDriver, ChaosEvent,
                                   ServingCluster)

    params, cfg = _setup()
    trace = _tiny_trace(seed=2, base_rate=30.0, duration_s=1.2)
    wl = TT.workload(trace)
    cl = ServingCluster(params, cfg, replicas=3, num_slots=2,
                        page_size=4, prefill_chunk=6, metrics=True,
                        max_queue=10 ** 6, watchdog_s=0.5)
    spec = trace["spec"]
    mid = spec["burst_at_s"] + spec["burst_dur_s"] / 2.0
    drv = ChaosDriver(cl, [ChaosEvent(mid, "kill"),
                           ChaosEvent(mid + 0.2, "stall")], seed=3)
    try:
        t0 = time.perf_counter()
        rids = []
        for at, prompt, n in wl:
            while True:
                now = time.perf_counter() - t0
                drv.poll(now)
                if now >= at:
                    break
                time.sleep(min(at - now, 0.01))
            rids.append(cl.submit(prompt, n))
        while True:
            drv.poll(time.perf_counter() - t0)
            if cl.drain(timeout=0.25) and drv.done():
                break
            assert time.perf_counter() - t0 < 300
        assert len(drv.applied) == 2
        assert {a["kind"] for a in drv.applied} == {"kill", "stall"}
        for rid, (at, prompt, n) in zip(rids, wl):
            np.testing.assert_array_equal(
                cl.result(rid, timeout=60),
                _ref(params, cfg, prompt, n))
        c = cl.metrics()["counters"]
        assert c["cluster_failovers_total"] == 2
        _assert_no_leaks(cl)
    finally:
        drv.close()
        cl.close(timeout=60)


@pytest.mark.slow
def test_autoscaler_self_heals_total_replica_loss():
    """Replica death at the min-capacity floor: with a scaler
    attached, the LAST replica dying parks its requests instead of
    failing them, submit() refuses RETRYABLY (ClusterOverloaded with
    a retry_after_s hint, not ClusterClosed), the scaler's self-heal
    rule restores capacity bypassing hysteresis/cooldown, and every
    parked request completes bit-exact via recompute-exact resume."""
    from mxnet_tpu.serving import (Autoscaler, ChaosDriver,
                                   ChaosEvent, ClusterOverloaded,
                                   ServingCluster)

    params, cfg = _setup()
    rng = np.random.RandomState(6)
    cl = ServingCluster(params, cfg, replicas=1, num_slots=2,
                        page_size=4, prefill_chunk=6, metrics=True)
    sc = Autoscaler(cl, min_size=1, max_size=2, interval_s=0.02,
                    cooldown_s=5.0, up_ticks=100, down_ticks=10 ** 6)
    # NOT started: we drive tick() by hand so the healing window is
    # deterministic and observable
    drv = ChaosDriver(cl, [ChaosEvent(0.0, "kill")], seed=0)
    try:
        wl = [(rng.randint(1, 90, 6).astype(np.int32), 8)
              for _ in range(4)]
        rids = [cl.submit(p, n) for p, n in wl]
        drv.poll(0.0)                      # kill the ONLY replica
        deadline = time.perf_counter() + 60
        while len(cl._healthy()) and time.perf_counter() < deadline:
            time.sleep(0.01)
        assert not cl._healthy()
        # retryable refusal during the healing window
        with pytest.raises(ClusterOverloaded) as ei:
            cl.submit(np.ones(4, np.int32), 2)
        assert ei.value.retry_after_s > 0
        # in-flight requests parked, not failed
        assert all(not cl.requests[r].done_evt.is_set() for r in rids)
        assert sc.tick() == "up"           # self-heal: no hysteresis,
        assert sc.events[-1]["self_heal"]  # no cooldown wait
        for rid, (p, n) in zip(rids, wl):
            np.testing.assert_array_equal(
                cl.result(rid, timeout=300), _ref(params, cfg, p, n))
        r2 = cl.submit(np.ones(4, np.int32), 2)  # back in service
        cl.result(r2, timeout=300)
        _assert_no_leaks(cl)
    finally:
        drv.close()
        sc.close()
        cl.close(timeout=60)


@pytest.mark.slow
def test_serve_bench_trace_smoke():
    """CI smoke of the round-16 section: ``--quick --trace burst10x``
    must emit one trace row carrying the reproducing seed +
    trace_sha, a goodput fraction, a fired chaos event, and a clean
    oracle cross-check (run_trace_replay raises on any incomplete or
    divergent request — rc 0 IS the exactness assertion)."""
    import json as _json
    import sys
    import tempfile
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmark"))
    import serve_bench
    import traffic_trace as TT

    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "trace.json")
        rc = serve_bench.main(["--quick", "--trace", "burst10x",
                               "--seed", "5", "--json", out])
        assert rc == 0
        rows = _json.load(open(out))
    assert len(rows) == 1
    r = rows[0]
    assert r["section"] == "trace"
    assert r["seed"] == 5
    # the checked-in seed alone reproduces the workload
    p = serve_bench.PRESETS["quick"]
    expect = TT.trace_hash(
        TT.generate_trace(serve_bench._trace_spec(p, 5)))
    assert r["trace_sha"] == expect
    assert 0.0 < r["goodput_frac"] <= 1.0
    assert r["completed"] == r["submitted"]
    assert r["oracle_checked"] == r["submitted"]
    assert r["oracle_mismatches"] == 0
    assert len(r["chaos"]) == 1 and r["failovers"] >= 1
    assert r["slo_ttft_ms"] == p.slo_ttft_ms


@pytest.mark.slow
def test_cluster_limits_from_env(monkeypatch):
    """Satellite: the watchdog/TTL/admission limits read
    ``MXNET_SERVE_*`` env defaults (the autoscaler/chaos tests need
    tighter timeouts than production), and an explicit argument still
    wins."""
    from mxnet_tpu.serving import ServingCluster

    params, cfg = _setup()
    monkeypatch.setenv("MXNET_SERVE_MAX_QUEUE", "7")
    monkeypatch.setenv("MXNET_SERVE_WATCHDOG_S", "3.5")
    monkeypatch.setenv("MXNET_SERVE_TTL_S", "123.0")
    cl = ServingCluster(params, cfg, replicas=1, num_slots=2,
                        page_size=4, prefill_chunk=6)
    try:
        assert cl.max_queue == 7
        assert cl.watchdog_s == 3.5
        assert cl.default_ttl_s == 123.0
        rid = cl.submit(np.ones(4, np.int32), 2)
        assert cl.requests[rid].deadline is not None  # env TTL applied
        cl.result(rid, timeout=120)
    finally:
        cl.close(timeout=60)
    cl = ServingCluster(params, cfg, replicas=1, num_slots=2,
                        page_size=4, prefill_chunk=6,
                        max_queue=99, watchdog_s=9.0,
                        default_ttl_s=None)
    try:
        assert cl.max_queue == 99 and cl.watchdog_s == 9.0
        # NOTE: default_ttl_s=None means "use the env default" (None
        # is the sentinel), so the env TTL still applies here
        assert cl.default_ttl_s == 123.0
    finally:
        cl.close(timeout=60)
    monkeypatch.setenv("MXNET_SERVE_MAX_QUEUE", "not-a-number")
    with pytest.raises(ValueError):
        ServingCluster(params, cfg, replicas=1, num_slots=2,
                       page_size=4, prefill_chunk=6)


@pytest.mark.slow
def test_disagg_add_and_drain_worker():
    """Role-aware scale actuation on the cross-process cluster: a
    worker ADDED to a live cluster serves traffic (peer map refreshed
    everywhere), and draining a worker is graceful — outstanding
    requests finish, later traffic avoids it, outputs stay
    bit-exact."""
    from mxnet_tpu.serving import DisaggServingCluster

    params, cfg = _setup()
    rng = np.random.RandomState(9)
    cl = DisaggServingCluster(params, cfg, prefill=1, decode=1,
                              num_slots=2, page_size=4,
                              prefill_chunk=6, metrics=True,
                              watchdog_s=60.0)
    try:
        name = cl.add_worker("prefill")
        assert name == "prefill1"
        health = {h["worker"]: h for h in cl.health()}
        assert health["prefill1"]["alive"]
        wl = [(rng.randint(1, 90, 6).astype(np.int32), 5)
              for _ in range(6)]
        rids = [cl.submit(p, n) for p, n in wl]
        assert cl.drain_worker("prefill0", timeout=120)
        health = {h["worker"]: h for h in cl.health()}
        assert health["prefill0"]["dead"]
        # post-drain traffic rides the added worker
        p2 = rng.randint(1, 90, 8).astype(np.int32)
        r2 = cl.submit(p2, 4)
        for rid, (p, n) in zip(rids, wl):
            np.testing.assert_array_equal(
                cl.result(rid, timeout=300), _ref(params, cfg, p, n))
        np.testing.assert_array_equal(cl.result(r2, timeout=300),
                                      _ref(params, cfg, p2, 4))
        # the last worker of a role refuses to drain
        assert not cl.drain_worker("decode0", timeout=5)
    finally:
        cl.close()


@pytest.mark.slow
def test_disagg_standby_worker_adopted_by_scale_up():
    """Round 18 (ROADMAP item-2 remainder): a STANDBY worker is fully
    handshaken and pre-warmed but invisible — out of routing, out of
    the healthy gauge, out of chaos's victim set — until
    ``scale_up()`` adopts it in O(peer-map flip).  The adopted worker
    then serves traffic bit-exactly; with no standby left, scale_up
    falls back to spawning."""
    import time as _time
    from mxnet_tpu.serving import DisaggServingCluster

    params, cfg = _setup()
    rng = np.random.RandomState(10)
    cl = DisaggServingCluster(params, cfg, prefill=1, decode=1,
                              num_slots=2, page_size=4,
                              prefill_chunk=6, metrics=True,
                              watchdog_s=60.0)
    try:
        # one warm spare per role (the deployment shape serve_bench
        # --standby provisions: the role-aware scale_up grows
        # whichever role's load is higher at the tick, so a single-
        # role spare could leave the other spawn-priced)
        assert cl.add_worker("prefill", standby=True) == "prefill1"
        assert cl.add_worker("decode", standby=True) == "decode1"
        health = {h["worker"]: h for h in cl.health()}
        assert health["prefill1"]["alive"]
        assert health["prefill1"]["standby"]
        assert health["decode1"]["standby"]
        # invisible to the healthy-capacity gauge (the autoscaler
        # must still see only the pre-burst capacity, or it would
        # never fire the scale-up that adopts a standby)
        assert cl.registry.snapshot()["gauges"][
            "cluster_workers_healthy"] == 2
        # scale_up adopts a parked spare of whichever role it picks —
        # O(flag flip), not O(spawn+compile)
        t0 = _time.perf_counter()
        assert cl.scale_up() is True
        adopt_s = _time.perf_counter() - t0
        assert adopt_s < 1.0, \
            "standby adoption took %.2fs — it spawned instead" \
            % adopt_s
        health = {h["worker"]: h for h in cl.health()}
        adopted = [h for h in health.values()
                   if h["worker"] in ("prefill1", "decode1")
                   and not h["standby"]]
        assert len(adopted) == 1 and not adopted[0]["draining"]
        assert cl.registry.snapshot()["gauges"][
            "cluster_workers_healthy"] == 3
        # direct adoption of the other role's spare works too
        other_role = "prefill" if adopted[0]["worker"] == "decode1" \
            else "decode"
        assert cl.adopt_standby(other_role) == other_role + "1"
        assert cl.registry.snapshot()["gauges"][
            "cluster_workers_healthy"] == 4
        # the adopted workers serve bit-exactly (round-robin lands
        # every other request on each role's second worker)
        wl = [(rng.randint(1, 90, 6).astype(np.int32), 4)
              for _ in range(4)]
        for p, n in wl:
            rid = cl.submit(p, n)
            np.testing.assert_array_equal(
                cl.result(rid, timeout=300), _ref(params, cfg, p, n))
        st = cl.cluster_stats()
        assert st["prefill1"]["steps"] > 0, \
            "the adopted standby never stepped"
        # no spares parked anymore: the next adoption attempt misses
        assert cl.adopt_standby("prefill") is None
        assert cl.adopt_standby("decode") is None
    finally:
        cl.close()
