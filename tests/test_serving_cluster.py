"""Serving cluster + shared-prefix KV reuse (round 10).

Exactness pins: f32 greedy tokens through the ``ServingCluster`` —
any replica count, with prefix-cache hits, copy-on-write divergence,
and a forced mid-flight replica failure + resubmit — must be
token-identical to single-engine ``generate`` output.  Prefix-cache
correctness: refcounts return to zero after retire, COW never mutates
a shared page, eviction under pool pressure preserves exactness.

Slow tier, group f (the serving-cluster group wired into
``tools/run_slow_tier.sh``)."""
import time

import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401  (conftest device setup)


def _cfg(**kw):
    from mxnet_tpu.models import gpt
    base = dict(use_flash=False, remat=False, dropout=0.0,
                dtype="float32", vocab_size=128, max_len=64)
    base.update(kw)
    return gpt.gpt_tiny(**base)


def _ref(params, cfg, prompt, n):
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt
    return np.asarray(
        gpt.generate(params, cfg, jnp.asarray(prompt)[None], n))[0]


def _setup(seed=3):
    import jax
    from mxnet_tpu.models import transformer as T
    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(seed), cfg)
    return params, cfg


# ---------------------------------------------------------------------------
# prefix cache (engine level)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_prefix_hits_exact_and_refcounts_zero():
    """Shared-prefix requests skip prefill rows via cached pages yet
    decode token-identically; after every retire all entry refcounts
    are zero and pages_in_use equals exactly the cache-owned pages."""
    from mxnet_tpu.serving import ServingEngine

    params, cfg = _setup()
    rng = np.random.RandomState(0)
    shared = rng.randint(1, 90, 12).astype(np.int32)
    eng = ServingEngine(params, cfg, num_slots=3, page_size=4,
                        prefill_chunk=6, prefix_cache=True)
    cases = []
    for i in range(5):
        tail = rng.randint(1, 90, 2 + i).astype(np.int32)
        cases.append((np.concatenate([shared, tail]), 6 + i))
    rids = [eng.submit(p, n) for p, n in cases]
    outs = eng.run()
    for rid, (p, n) in zip(rids, cases):
        np.testing.assert_array_equal(outs[rid], _ref(params, cfg, p, n))
    assert eng.stats["prefix_hit_tokens"] > 0
    assert eng.prefix.refs_total == 0
    assert eng.cache.pages_in_use == eng.prefix.cached_pages
    assert eng.prefix.cached_pages > 0


@pytest.mark.slow
def test_cow_divergence_exact_and_shared_page_untouched():
    """COW pin: a request diverging inside a cached page (and one
    re-submitting the whole cached input) decodes exactly, and the
    SHARED page's device contents are bit-unchanged afterwards — the
    write went to the private copy."""
    from mxnet_tpu.serving import ServingEngine

    params, cfg = _setup()
    rng = np.random.RandomState(1)
    eng = ServingEngine(params, cfg, num_slots=2, page_size=4,
                        prefill_chunk=8, prefix_cache=True)
    pa = rng.randint(1, 90, 16).astype(np.int32)   # 4 full pages
    ra = eng.submit(pa, 8)
    outs = eng.run()
    np.testing.assert_array_equal(outs[ra], _ref(params, cfg, pa, 8))
    assert eng.prefix.cached_pages == 4

    # identify the cached chain's last page and snapshot its contents
    entries, pages, m = eng.prefix.match(pa)
    eng.prefix.release(entries)
    assert m == 16 and len(pages) == 4
    last_pg = pages[-1]
    snap = [np.asarray(pool["kv"][last_pg])
            for pool in eng.cache.pools]

    # whole-input match: page 3 is COW'd to re-feed the final token
    rb = eng.submit(pa, 8)
    # partial-page divergence: shares 14 tokens, diverges inside page 3
    pc = np.concatenate([pa[:14], rng.randint(90, 120, 4)
                         .astype(np.int32)])
    rc = eng.submit(pc, 8)
    outs = eng.run()
    np.testing.assert_array_equal(outs[rb], _ref(params, cfg, pa, 8))
    np.testing.assert_array_equal(outs[rc], _ref(params, cfg, pc, 8))
    assert eng.stats["cow_copies"] == 2
    for layer, pool in enumerate(eng.cache.pools):
        np.testing.assert_array_equal(np.asarray(pool["kv"][last_pg]),
                                      snap[layer])
    assert eng.prefix.refs_total == 0


@pytest.mark.slow
def test_prefix_refcounts_after_forced_retire():
    """The forced-retire leak pattern with the prefix cache armed: a
    mid-flight cancel drops the request's refs; the cached chain
    survives with refcount 0 and a follow-up identical prompt HITS it
    while any recycled private pages are reused without leakage."""
    from mxnet_tpu.serving import ServingEngine

    params, cfg = _setup(seed=7)
    rng = np.random.RandomState(2)
    eng = ServingEngine(params, cfg, num_slots=2, page_size=4,
                        pages_per_slot=5, num_pages=8,
                        prefill_chunk=8, prefix_cache=True)
    pa = rng.randint(1, 90, 8).astype(np.int32)
    ra = eng.submit(pa, 12)
    for _ in range(5):
        eng.step()
    req_a = eng.requests[ra]
    assert req_a.state == "running" and len(req_a.generated) > 0
    assert req_a.shared_pages, "prompt pages should be donated by now"
    eng.cancel(ra)                        # forced retire mid-flight
    assert eng.prefix.refs_total == 0
    assert eng.cache.pages_in_use == eng.prefix.cached_pages

    rb = eng.submit(pa, 12)               # same prompt → cache hit
    outs = eng.run()
    assert eng.requests[rb].prefix_hit_tokens > 0
    np.testing.assert_array_equal(outs[rb], _ref(params, cfg, pa, 12))
    assert eng.prefix.refs_total == 0


@pytest.mark.slow
def test_prefix_eviction_under_pressure_exact():
    """A pool too small for live traffic + cached chains must evict
    refcount-0 chains (never referenced ones) and stay exact."""
    from mxnet_tpu.serving import ServingEngine

    params, cfg = _setup(seed=7)
    rng = np.random.RandomState(2)
    eng = ServingEngine(params, cfg, num_slots=2, page_size=4,
                        pages_per_slot=5, num_pages=6,
                        prefill_chunk=8, prefix_cache=True)
    pa = rng.randint(1, 90, 8).astype(np.int32)
    ra = eng.submit(pa, 12)
    outs = eng.run()
    np.testing.assert_array_equal(outs[ra], _ref(params, cfg, pa, 12))
    assert eng.prefix.cached_pages > 0

    # unrelated request needing the whole pool: the cached chain must
    # be evicted to admit it
    pb = rng.randint(90, 120, 7).astype(np.int32)
    rb = eng.submit(pb, 12)
    outs = eng.run()
    np.testing.assert_array_equal(outs[rb], _ref(params, cfg, pb, 12))
    assert eng.prefix.pages_evicted_total > 0
    assert eng.prefix.refs_total == 0


@pytest.mark.slow
def test_prefix_with_preemption_exact():
    """Prefix cache + youngest-preempt recompute: over-committed pool,
    shared prefixes — every output exact, refs drained."""
    from mxnet_tpu.serving import ServingEngine

    params, cfg = _setup(seed=9)
    rng = np.random.RandomState(3)
    shared = rng.randint(1, 90, 8).astype(np.int32)
    eng = ServingEngine(params, cfg, num_slots=4, page_size=4,
                        pages_per_slot=8, num_pages=12,
                        prefill_chunk=4, prefix_cache=True)
    reqs = []
    for i, n in enumerate((20, 24, 16, 22, 18)):
        p = np.concatenate([shared[:4 + i],
                            rng.randint(1, 90, 2).astype(np.int32)])
        reqs.append((eng.submit(p, n), p, n))
    outs = eng.run()
    assert eng.stats["preemptions"] > 0
    for rid, p, n in reqs:
        np.testing.assert_array_equal(outs[rid],
                                      _ref(params, cfg, p, n))
    assert eng.prefix.refs_total == 0
    assert eng.cache.pages_in_use == eng.prefix.cached_pages


# ---------------------------------------------------------------------------
# cluster
# ---------------------------------------------------------------------------

def _mixed_workload(rng, shared, n):
    out = []
    for i in range(n):
        if i % 2 == 0:
            p = np.concatenate([shared, rng.randint(1, 90, 2 + i)
                                .astype(np.int32)])
        else:
            p = rng.randint(1, 90, 4 + i).astype(np.int32)
        out.append((p, 5 + (i % 5)))
    return out


@pytest.mark.slow
@pytest.mark.parametrize("replicas", [1, 2, 3])
def test_cluster_exactness_any_replica_count(replicas):
    """THE exactness pin: mixed shared-prefix traffic through 1/2/3
    replicas (prefix hits and COW included) is token-identical to
    single-engine ``generate``."""
    from mxnet_tpu.serving import ServingCluster

    params, cfg = _setup()
    rng = np.random.RandomState(replicas)
    shared = rng.randint(1, 90, 8).astype(np.int32)
    wl = _mixed_workload(rng, shared, 8)
    # one exact duplicate → whole-input match → COW path
    wl.append((wl[0][0], wl[0][1]))
    with ServingCluster(params, cfg, replicas=replicas, num_slots=2,
                        page_size=4, prefill_chunk=6,
                        metrics=True) as cl:
        rids = [cl.submit(p, n) for p, n in wl]
        for rid, (p, n) in zip(rids, wl):
            np.testing.assert_array_equal(cl.result(rid, timeout=300),
                                          _ref(params, cfg, p, n))
        hits = sum(r.engine.stats["prefix_hit_tokens"]
                   for r in cl.replicas)
        assert hits > 0
        c = cl.metrics()["counters"]
        assert c["cluster_requests_completed_total"] == len(wl)


@pytest.mark.slow
def test_cluster_failover_resubmit_exact():
    """Forced mid-flight replica failure: the dead replica's waiting
    and in-flight requests are resubmitted to the survivor via the
    recompute-exact resume path — every output stays identical to an
    undisturbed single-engine run."""
    from mxnet_tpu.serving import ServingCluster

    params, cfg = _setup()
    rng = np.random.RandomState(5)
    shared = rng.randint(1, 90, 8).astype(np.int32)
    cl = ServingCluster(params, cfg, replicas=2, num_slots=2,
                        page_size=4, prefill_chunk=6, metrics=True,
                        watchdog_s=10.0)
    try:
        eng0 = cl.replicas[0].engine
        orig_step = eng0.step
        calls = [0]

        def bomb():
            calls[0] += 1
            if calls[0] == 4:
                raise RuntimeError("injected replica failure")
            return orig_step()

        eng0.step = bomb
        wl = _mixed_workload(rng, shared, 6)
        rids = [cl.submit(p, n) for p, n in wl]
        for rid, (p, n) in zip(rids, wl):
            np.testing.assert_array_equal(cl.result(rid, timeout=300),
                                          _ref(params, cfg, p, n))
        c = cl.metrics()["counters"]
        assert c["cluster_failovers_total"] == 1
        assert c["cluster_requests_completed_total"] == len(wl)
        health = {h["replica"]: h for h in cl.health()}
        assert health[0]["dead"] and not health[0]["alive"]
        assert health[1]["alive"]
        # mid-flight victims really did resume with committed tokens
        assert any(cl.requests[r].failovers > 0 for r in rids)
    finally:
        cl.close(timeout=60)


@pytest.mark.slow
def test_cluster_failover_with_speculation_exact():
    """Round-11 acceptance pin: mid-flight replica failure with
    in-engine speculation armed (spec_K on every replica).  A verify
    step may have committed SEVERAL tokens before the failure; the
    snapshot-and-resubmit path replays them as prompt extension and
    the resumed engine (also speculating) must still produce
    token-identical output — committed tokens are committed tokens
    regardless of how many a step produced."""
    from mxnet_tpu.serving import ServingCluster

    params, cfg = _setup()
    rng = np.random.RandomState(11)
    shared = rng.randint(1, 90, 8).astype(np.int32)
    cl = ServingCluster(params, cfg, replicas=2, num_slots=2,
                        page_size=4, prefill_chunk=6, metrics=True,
                        watchdog_s=10.0, spec_K=2)
    try:
        assert all(r.engine.spec_K == 2 for r in cl.replicas)
        eng0 = cl.replicas[0].engine
        orig_step = eng0.step
        calls = [0]

        def bomb():
            calls[0] += 1
            if calls[0] == 4:
                raise RuntimeError("injected replica failure")
            return orig_step()

        eng0.step = bomb
        wl = _mixed_workload(rng, shared, 6)
        rids = [cl.submit(p, n) for p, n in wl]
        for rid, (p, n) in zip(rids, wl):
            np.testing.assert_array_equal(cl.result(rid, timeout=300),
                                          _ref(params, cfg, p, n))
        c = cl.metrics()["counters"]
        assert c["cluster_failovers_total"] == 1
        assert c["cluster_requests_completed_total"] == len(wl)
        # speculation really ran on the replicas
        assert sum(r.engine.stats["spec_drafted"]
                   for r in cl.replicas) > 0
    finally:
        cl.close(timeout=60)


@pytest.mark.slow
def test_cluster_watchdog_stall_failover():
    """A replica that stalls past the watchdog (step blocked, no
    raise) is drained by the monitor; its requests complete exactly on
    the survivor and the zombie's late completion is fenced."""
    from mxnet_tpu.serving import ServingCluster

    params, cfg = _setup()
    rng = np.random.RandomState(6)
    cl = ServingCluster(params, cfg, replicas=2, num_slots=2,
                        page_size=4, prefill_chunk=6, metrics=True,
                        watchdog_s=0.4)
    try:
        eng0 = cl.replicas[0].engine
        orig_step = eng0.step
        calls = [0]

        def stall():
            calls[0] += 1
            if calls[0] == 3:
                time.sleep(1.5)           # > watchdog, then returns
            return orig_step()

        eng0.step = stall
        wl = _mixed_workload(rng, rng.randint(1, 90, 8)
                             .astype(np.int32), 6)
        rids = [cl.submit(p, n) for p, n in wl]
        for rid, (p, n) in zip(rids, wl):
            np.testing.assert_array_equal(cl.result(rid, timeout=300),
                                          _ref(params, cfg, p, n))
        c = cl.metrics()["counters"]
        assert c["cluster_failovers_total"] == 1
        assert c["cluster_requests_completed_total"] == len(wl)
    finally:
        cl.close(timeout=60)


@pytest.mark.slow
def test_cluster_backpressure_and_ttl():
    from mxnet_tpu.serving import (ServingCluster, ClusterOverloaded,
                                   RequestExpired)

    params, cfg = _setup()
    rng = np.random.RandomState(7)
    cl = ServingCluster(params, cfg, replicas=1, num_slots=1,
                        page_size=4, prefill_chunk=4, metrics=True,
                        max_queue=3)
    try:
        r_ok = cl.submit(rng.randint(1, 90, 4).astype(np.int32), 20)
        r_ttl = cl.submit(rng.randint(1, 90, 4).astype(np.int32), 4,
                          ttl_s=0.0)
        with pytest.raises(ClusterOverloaded) as ei:
            for _ in range(10):
                cl.submit(rng.randint(1, 90, 4).astype(np.int32), 4)
        # round-16 satellite: the rejection carries a structured
        # Retry-After hint (queue excess / recent drain rate — the
        # future HTTP 429 + Retry-After), mirrored on the gauge
        assert ei.value.retry_after_s is not None
        assert ei.value.retry_after_s > 0
        assert "retry after" in str(ei.value)
        assert cl.metrics()["gauges"]["cluster_retry_after_s"] == \
            ei.value.retry_after_s
        with pytest.raises(RequestExpired):
            cl.result(r_ttl, timeout=120)
        out = cl.result(r_ok, timeout=300)
        np.testing.assert_array_equal(
            out, _ref(params, cfg, cl.requests[r_ok].prompt, 20))
        assert cl.drain(timeout=300)
        c = cl.metrics()["counters"]
        assert c["cluster_requests_rejected_total"] >= 1
        assert c["cluster_requests_expired_total"] == 1
    finally:
        cl.close(timeout=60)


@pytest.mark.slow
def test_cluster_drain_replica_scale_down():
    """Graceful scale-down: draining a replica reroutes its waiting
    requests, finishes its in-flight ones, parks the worker; traffic
    continues on the survivor with exact outputs."""
    from mxnet_tpu.serving import ServingCluster

    params, cfg = _setup()
    rng = np.random.RandomState(8)
    cl = ServingCluster(params, cfg, replicas=2, num_slots=2,
                        page_size=4, prefill_chunk=6, metrics=True)
    try:
        wl = _mixed_workload(rng, rng.randint(1, 90, 8)
                             .astype(np.int32), 4)
        rids = [cl.submit(p, n) for p, n in wl]
        assert cl.drain_replica(0, timeout=300)
        health = {h["replica"]: h for h in cl.health()}
        assert health[0]["draining"] and not health[0]["alive"]
        assert health[0]["in_flight"] == 0
        # post-scale-down traffic lands on the survivor
        p2 = rng.randint(1, 90, 6).astype(np.int32)
        r2 = cl.submit(p2, 6)
        for rid, (p, n) in zip(rids, wl):
            np.testing.assert_array_equal(cl.result(rid, timeout=300),
                                          _ref(params, cfg, p, n))
        np.testing.assert_array_equal(cl.result(r2, timeout=300),
                                      _ref(params, cfg, p2, 6))
        assert cl.requests[r2].replica == 1
    finally:
        cl.close(timeout=60)


@pytest.mark.slow
def test_cluster_drain_while_submitting_race():
    """Round-12 race pin (normal OS scheduler): drain_replica(0)
    concurrent with a burst of submit().  Strays are rerouted, new
    submissions never land on the draining replica, and every output
    is exact."""
    import threading
    from mxnet_tpu.serving import ServingCluster

    params, cfg = _setup()
    rng = np.random.RandomState(12)
    shared = rng.randint(1, 90, 8).astype(np.int32)
    wl = _mixed_workload(rng, shared, 8)
    cl = ServingCluster(params, cfg, replicas=2, num_slots=2,
                        page_size=4, prefill_chunk=6, metrics=True)
    try:
        rids = []

        def submitter():
            for p, n in wl:
                rids.append(cl.submit(p, n))

        th = threading.Thread(target=submitter)
        th.start()
        assert cl.drain_replica(0, timeout=300)
        th.join(300)
        assert len(rids) == len(wl)
        for rid, (p, n) in zip(rids, wl):
            np.testing.assert_array_equal(cl.result(rid, timeout=300),
                                          _ref(params, cfg, p, n))
        # post-drain, every terminal home is the survivor or the
        # request finished on replica 0 BEFORE it drained — but no
        # request may still be assigned to a drained, parked worker
        health = {h["replica"]: h for h in cl.health()}
        assert health[0]["draining"] and health[0]["in_flight"] == 0
        assert health[0]["waiting"] == 0
    finally:
        cl.close(timeout=60)


@pytest.mark.slow
def test_cluster_drain_while_submitting_interleaved():
    """The same race under the deterministic interleaving explorer:
    10 seeded schedules x 2 strategies, every interleaving of the
    drain against the submit burst stays exact (the slow-tier sweep in
    test_interleave.py runs the full 200-schedule matrix)."""
    from tools.analysis.interleave import run_schedule
    from mxnet_tpu.serving import ServingCluster
    from mxnet_tpu.serving import cluster as cluster_mod

    params, cfg = _setup()
    rng = np.random.RandomState(13)
    wl = _mixed_workload(rng, rng.randint(1, 90, 8).astype(np.int32),
                         5)
    refs = [_ref(params, cfg, p, n) for p, n in wl]
    # warm the step/copy caches outside the scheduler
    warm = ServingCluster(params, cfg, replicas=1, num_slots=2,
                          page_size=4, prefill_chunk=6)
    warm.result(warm.submit(wl[0][0], 2), timeout=300)
    warm.close(timeout=60)

    def workload():
        cl = ServingCluster(params, cfg, replicas=2, num_slots=2,
                            page_size=4, prefill_chunk=6)
        try:
            rids = []

            def submitter():
                for p, n in wl:
                    rids.append(cl.submit(p, n))

            th = cluster_mod.threading.Thread(target=submitter)
            th.start()
            assert cl.drain_replica(0, timeout=300)
            th.join(300)
            for rid, ref in zip(rids, refs):
                np.testing.assert_array_equal(
                    cl.result(rid, timeout=300), ref)
        finally:
            cl.close(timeout=60)

    for mode in ("random", "preempt"):
        for seed in range(10):
            stats = run_schedule(workload, seed, mode=mode)
            assert stats.switches > 0, (mode, seed)


@pytest.mark.slow
def test_prefix_refs_released_when_alloc_raises():
    """Round-12 pylocklint regression (py-ref-leak): if the allocator
    raises mid-admission — the pressure callback can — the refs
    match() just took must be released, not leaked (a leaked ref pins
    its chain unevictable for the engine's lifetime)."""
    from mxnet_tpu.serving import ServingEngine

    params, cfg = _setup()
    rng = np.random.RandomState(4)
    pa = rng.randint(1, 90, 8).astype(np.int32)
    eng = ServingEngine(params, cfg, num_slots=2, page_size=4,
                        prefill_chunk=8, prefix_cache=True)
    ra = eng.submit(pa, 6)
    eng.run()
    assert eng.prefix.cached_pages > 0
    assert eng.prefix.refs_total == 0

    orig_alloc = eng.cache.alloc
    def bomb(n):
        raise RuntimeError("injected alloc failure")
    eng.cache.alloc = bomb
    rb = eng.submit(pa, 6)            # matches the cached chain
    with pytest.raises(RuntimeError, match="injected alloc"):
        eng.step()
    assert eng.prefix.refs_total == 0, \
        "alloc-raise admission leaked prefix refs"
    # engine recovers once the allocator does
    eng.cache.alloc = orig_alloc
    outs = eng.run()
    np.testing.assert_array_equal(outs[rb], _ref(params, cfg, pa, 6))
    assert eng.prefix.refs_total == 0


@pytest.mark.slow
def test_cluster_prefix_affinity_routing():
    """Requests sharing a prompt prefix stick to the replica that
    cached it (while load allows): the router's affinity counter moves
    and same-prefix requests co-locate."""
    from mxnet_tpu.serving import ServingCluster

    params, cfg = _setup()
    rng = np.random.RandomState(9)
    shared = rng.randint(1, 90, 8).astype(np.int32)   # 2 full pages
    # wide slack isolates the affinity signal: with the default slack
    # (= num_slots) a burst bigger than the slack correctly SPILLS to
    # the least-loaded replica — that is the SLO part of the router
    cl = ServingCluster(params, cfg, replicas=2, num_slots=4,
                        page_size=4, prefill_chunk=8, metrics=True,
                        affinity_slack=64)
    try:
        rids = []
        for i in range(6):
            p = np.concatenate([shared, rng.randint(1, 90, 2 + i)
                                .astype(np.int32)])
            rids.append(cl.submit(p, 4))
        assert cl.drain(timeout=300)
        homes = {cl.requests[r].replica for r in rids}
        assert len(homes) == 1, \
            "shared-prefix requests scattered: %s" % homes
        c = cl.metrics()["counters"]
        assert c["cluster_routed_affinity_total"] >= len(rids) - 1
    finally:
        cl.close(timeout=60)


@pytest.mark.slow
def test_cluster_validation_and_close_semantics():
    from mxnet_tpu.serving import ServingCluster, ClusterClosed

    params, cfg = _setup()
    with pytest.raises(ValueError):
        ServingCluster(params, cfg, replicas=0, num_slots=1)
    cl = ServingCluster(params, cfg, replicas=1, num_slots=1,
                        page_size=4)
    rid = cl.submit(np.arange(1, 6, dtype=np.int32), 4)
    out = cl.result(rid, timeout=300)
    np.testing.assert_array_equal(
        out, _ref(params, cfg, np.arange(1, 6, dtype=np.int32), 4))
    cl.close(timeout=60)
    with pytest.raises(ClusterClosed):
        cl.submit(np.arange(1, 6, dtype=np.int32), 4)


@pytest.mark.slow
def test_serve_bench_cluster_smoke():
    """CI smoke of the round-10 bench sections: ``--replicas 2
    --shared-prefix-frac 0.8`` must emit the prefix gate row (hit
    faster than cold), a prefix-on/off cluster pair, and a failover
    row in which every request completed (run_cluster raises
    otherwise — rc 0 IS the completion assertion)."""
    import json as _json
    import os
    import sys
    import tempfile
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmark"))
    import serve_bench

    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "serve.json")
        rc = serve_bench.main(["--quick", "--no-telemetry",
                               "--replicas", "2",
                               "--shared-prefix-frac", "0.8",
                               "--json", out])
        assert rc == 0
        rows = _json.load(open(out))
    prefix = [r for r in rows if r["section"] == "prefix"]
    assert len(prefix) == 1
    assert prefix[0]["ttft_hit_ms"] < prefix[0]["ttft_cold_ms"]
    assert prefix[0]["hit_tokens"] > 0
    cluster = {r["config"]: r for r in rows
               if r["section"] == "cluster"}
    assert set(cluster) == {"cluster_r2_prefix", "cluster_r2_cold",
                            "cluster_r2_failover"}
    assert cluster["cluster_r2_prefix"]["prefix_hit_tokens"] > 0
    assert cluster["cluster_r2_cold"]["prefix_hit_tokens"] == 0
    fo = cluster["cluster_r2_failover"]
    assert fo["failovers"] == 1
    assert fo["completed"] == fo["completed"] and fo["tok_s"] > 0


@pytest.mark.slow
def test_cluster_poison_request_and_result_retention():
    """Round-10 review fixes: an engine-invalid request fails the
    submit() call in the caller's thread (it must never reach and
    kill a replica worker), and terminal requests are purged past
    ``retain_results`` so the table stays bounded."""
    from mxnet_tpu.serving import ServingCluster

    params, cfg = _setup()
    rng = np.random.RandomState(11)
    cl = ServingCluster(params, cfg, replicas=1, num_slots=2,
                        page_size=4, prefill_chunk=6,
                        retain_results=3)
    try:
        with pytest.raises(ValueError):
            cl.submit(rng.randint(1, 90, 60).astype(np.int32), 60)
        with pytest.raises(ValueError):
            cl.submit(np.ones(0, np.int32), 4)
        with pytest.raises(ValueError):
            cl.submit(np.ones(4, np.int32), 0)
        rids = [cl.submit(rng.randint(1, 90, 4).astype(np.int32), 4)
                for _ in range(6)]
        for rid in rids:
            cl.result(rid, timeout=300)
        assert all(r.thread.is_alive() for r in cl.replicas)
        # only the newest retain_results terminal requests remain,
        # and the replica engine dropped its completed records too
        assert len(cl.requests) == 3
        assert set(cl.requests) == set(rids[-3:])
        assert cl.replicas[0].engine.requests == {}
    finally:
        cl.close(timeout=60)
