"""Perl binding over the C train ABI (round-3 verdict #5; reference:
perl-package/AI-MXNet — SURVEY.md §2.3 "Perl" row): a Perl program
trains the MNIST-style MLP through AI::MXNetTPU and its loss trajectory
must match the identical training loop run in Python (the same gate as
the C++ frontend's test_ctrain.py)."""
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu import optimizer as opt_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")
PERLPKG = os.path.join(REPO, "perl-package", "AI-MXNetTPU")

N, D, H, C = 64, 16, 16, 4
EPOCHS = 8
LR = 0.5


def _make_data():
    rng = np.random.RandomState(42)
    X = rng.randn(N, D).astype("float32")
    wt = rng.randn(D, C).astype("float32")
    Y = (X @ wt).argmax(axis=1).astype("float32")
    W1 = (rng.randn(H, D) * 0.3).astype("float32")
    B1 = np.zeros(H, "float32")
    W2 = (rng.randn(C, H) * 0.3).astype("float32")
    B2 = np.zeros(C, "float32")
    return X, Y, W1, B1, W2, B2


def _python_trajectory():
    X, Y, W1, B1, W2, B2 = _make_data()
    x, y = nd.array(X), nd.array(Y)
    params = [nd.array(a) for a in (W1, B1, W2, B2)]
    for p in params:
        p.attach_grad()
    updater = opt_mod.get_updater(opt_mod.create("sgd",
                                                 learning_rate=LR))
    losses = []
    for _ in range(EPOCHS):
        with autograd.record():
            h = nd.FullyConnected(x, params[0], params[1], num_hidden=H)
            a = nd.Activation(h, act_type="relu")
            o = nd.FullyConnected(a, params[2], params[3], num_hidden=C)
            loss = nd.negative(nd.mean(nd.pick(nd.log_softmax(o), y)))
        loss.backward()
        losses.append(float(loss.asnumpy()))
        for i, p in enumerate(params):
            updater(i, p.grad, p)
    return losses


@pytest.mark.slow
def test_perl_training_matches_python(tmp_path):
    if shutil.which("perl") is None:
        pytest.skip("no perl in this image")
    r = subprocess.run(["make", "-C", NATIVE, "train"],
                       capture_output=True, text=True, timeout=300)
    lib = os.path.join(NATIVE, "lib", "libmxnet_tpu_train.so")
    if r.returncode != 0 or not os.path.exists(lib):
        pytest.skip("train library build failed: %s" % r.stderr[-500:])
    r = subprocess.run(["make", "-C", PERLPKG],
                       capture_output=True, text=True, timeout=300)
    ffi = os.path.join(PERLPKG, "lib", "auto", "AI", "MXNetTPU", "FFI",
                       "FFI.so")
    if r.returncode != 0 or not os.path.exists(ffi):
        pytest.skip("perl XS build failed: %s" % (r.stdout + r.stderr)[-500:])

    data_file = tmp_path / "train_data.bin"
    with open(data_file, "wb") as f:
        for b in _make_data():
            f.write(np.ascontiguousarray(b, "<f4").tobytes())

    env = dict(os.environ)
    env["PYTHONPATH"] = ":".join(
        p for p in (env.get("PYTHONPATH", ""), REPO) if p) or REPO
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        ["perl", "-Ilib", os.path.join("examples", "train_mlp.pl"),
         str(data_file)],
        cwd=PERLPKG, env=env, capture_output=True, text=True,
        timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    perl_losses = [float(l.split()[1])
                   for l in r.stdout.splitlines() if l.startswith("loss")]
    assert len(perl_losses) == EPOCHS, r.stdout

    py_losses = _python_trajectory()
    np.testing.assert_allclose(perl_losses, py_losses, rtol=1e-5,
                               atol=1e-6)
    # and it actually learned
    assert perl_losses[-1] < perl_losses[0] * 0.5