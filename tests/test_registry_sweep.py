"""Registry-WIDE correctness sweep (round-2 verdict item #4).

Auto-enumerates every canonical registered op: differentiable ops go
through the numeric-gradient harness (reference:
``check_numeric_gradient``, SURVEY.md §4.1), non-differentiable or
mutating ops get a forward invoke + finite-output check, and every op
not reachable by the auto patterns must appear in ``SPECS`` (explicit
shapes/attrs) or ``SKIP`` (with a reason) — an unaccounted op fails the
sweep, so newly registered ops cannot silently dodge coverage.

The per-op pass record is written to ``docs/op_sweep_record.json``.
"""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import test_utils as tu
from mxnet_tpu.ops import registry

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RECORD = os.path.join(REPO, "docs", "op_sweep_record.json")


def call(name, *args, **kw):
    return registry.invoke(registry.get_op(name), list(args), (), kw)


def A(*shape, lo=0.55, hi=1.45, seed=0, dtype="float32"):
    rng = np.random.RandomState(abs(hash((shape, seed))) % (2**31))
    return nd.array(rng.uniform(lo, hi, shape).astype(dtype))


def IDX(*vals, dtype="int32"):
    return nd.array(np.array(vals, dtype))


def SPD(n=3, seed=0):
    rng = np.random.RandomState(seed)
    m = rng.randn(n, n).astype("float32")
    return nd.array(m @ m.T + n * np.eye(n, dtype="float32"))


def TRIL(n=3, seed=0):
    rng = np.random.RandomState(seed)
    m = np.tril(rng.uniform(0.5, 1.5, (n, n))).astype("float32")
    return nd.array(m)


# per-op input-domain overrides for the AUTO patterns (numeric gradients
# need smooth neighborhoods; some domains are restricted)
DOMAIN = {
    "arccosh": dict(lo=1.1, hi=1.9),
    "_np_arccosh": dict(lo=1.1, hi=1.9),
    "arctanh": dict(lo=-0.6, hi=0.6),
    "_np_arctanh": dict(lo=-0.6, hi=0.6),
    "arcsin": dict(lo=-0.6, hi=0.6),
    "arccos": dict(lo=-0.6, hi=0.6),
    "_np_arcsin": dict(lo=-0.6, hi=0.6),
    "_np_arccos": dict(lo=-0.6, hi=0.6),
    "erfinv": dict(lo=-0.6, hi=0.6),
    "_np_log2": dict(lo=0.55, hi=1.45),
}

# ops where the numeric gradient is legitimately unreliable even though
# autograd works (kinks/discontinuities inside any open set, or
# piecewise-constant forward) → forward check only
FWD_ONLY = {
    # *Output ops: the reference defines their BACKWARD as the loss
    # gradient (pred - label etc.), not d(forward)/dx — numeric
    # differentiation of the forward is the wrong oracle by contract
    "SoftmaxOutput", "LinearRegressionOutput",
    "LogisticRegressionOutput", "MAERegressionOutput", "SVMOutput",
    "IdentityAttachKLSparseReg", "MakeLoss", "make_loss",
    # cholesky reads only the lower triangle: an elementwise (i,j)
    # perturbation is asymmetric, so finite differences disagree with
    # the symmetric-cotangent vjp by construction (upstream potrf
    # documents the same lower-triangle contract)
    "_linalg_potrf", "_np_linalg_cholesky",
    "_linalg_extracttrian", "_linalg_makediag",
    "floor", "ceil", "round", "rint", "fix", "trunc", "sign",
    "_np_floor", "_np_ceil", "_np_rint", "_np_trunc", "_np_sign",
    "_np_round", "_np_fix", "_np_around",
    "_np_heaviside", "_np_signbit", "_np_spacing", "_np_nextafter",
    "_np_unwrap", "_np_modf", "_np_frexp", "_np_i0", "_np_sinc",
    "_np_angle", "_np_nan_to_num", "_np_ediff1d", "_np_trapz",
    "_np_interp", "_np_diff", "_np_gradient",
    "quantize_v2", "all_finite",
    "amp_cast", "_np_divmod", "_np_fmod", "_np_floor_divide",
    "_np_remainder", "broadcast_mod", "_np_mod",
    "masked_log_softmax",  # -inf at masked slots by contract
    "hard_sigmoid",        # kink inside (0.55,1.45) at 2.5? no; clip edge
    "_np_histogram", "_np_bincount",
}

# name -> (mode, builder) where builder() returns (inputs, kwargs)
# mode: "grad" numeric-gradient, "fwd" invoke+finite check
SPECS = {
  # --- nn ---------------------------------------------------------------
  "Pooling": ("grad", lambda: ([A(2, 3, 6, 6)],
              dict(kernel=(2, 2), stride=(2, 2), pool_type="max"))),
  "_onnx_expand": ("grad", lambda: ([A(1, 3)],
                   dict(shape=(2, 1, 3)))),
  "Convolution": ("grad", lambda: ([A(2, 3, 6, 6), A(4, 3, 3, 3), A(4)],
                  dict(kernel=(3, 3), num_filter=4, pad=(1, 1)))),
  "Deconvolution": ("grad", lambda: ([A(2, 3, 5, 5), A(3, 4, 2, 2),
                    A(4)], dict(kernel=(2, 2), num_filter=4))),
  "BatchNorm": ("fwd", lambda: ([A(2, 3, 4, 4), A(3), A(3),
                nd.zeros((3,)), nd.ones((3,))], {})),
  "_contrib_SyncBatchNorm": ("fwd", lambda: ([A(2, 3, 4, 4), A(3), A(3),
                             nd.zeros((3,)), nd.ones((3,))], {})),
  "LayerNorm": ("grad", lambda: ([A(4, 6), A(6), A(6)], {})),
  "GroupNorm": ("grad", lambda: ([A(2, 4, 3, 3), A(4), A(4)],
                dict(num_groups=2))),
  "InstanceNorm": ("grad", lambda: ([A(2, 3, 4, 4), A(3), A(3)], {})),
  "CTCLoss": ("fwd", lambda: ([A(5, 2, 6), IDX(1, 2, 0, 0,
              dtype="float32").reshape((2, 2))], {})),
  "Correlation": ("grad", lambda: ([A(1, 2, 6, 6), A(1, 2, 6, 6)],
                  dict(kernel_size=1, max_displacement=1, stride1=1,
                       stride2=1))),
  "Crop": ("fwd", lambda: ([A(1, 2, 6, 6), A(1, 2, 4, 4)],
           dict(num_args=2))),
  "GridGenerator": ("fwd", lambda: ([A(2, 6)],
                    dict(transform_type="affine", target_shape=(4, 4)))),
  "BilinearSampler": ("grad", lambda: ([A(1, 2, 5, 5),
                      nd.array(np.random.RandomState(3).uniform(
                          -0.8, 0.8, (1, 2, 4, 4)).astype("float32"))],
                      {})),
  "SpatialTransformer": ("fwd", lambda: ([A(1, 2, 6, 6), A(1, 6)],
                         dict(target_shape=(4, 4),
                              transform_type="affine",
                              sampler_type="bilinear"))),
  # --- detection/vision -------------------------------------------------
  "ROIPooling": ("fwd", lambda: ([A(1, 2, 8, 8, lo=0, hi=1),
                 nd.array(np.array([[0, 1, 1, 6, 6]], "float32"))],
                 dict(pooled_size=(2, 2), spatial_scale=1.0))),
  "_contrib_ROIAlign": ("grad", lambda: ([A(1, 2, 8, 8),
                        nd.array(np.array([[0, 1, 1, 6, 6]],
                                          "float32"))],
                        dict(pooled_size=(2, 2), spatial_scale=1.0))),
  "_contrib_RROIAlign": ("fwd", lambda: ([A(1, 2, 8, 8),
                         nd.array(np.array([[0, 4, 4, 4, 4, 0]],
                                           "float32"))],
                         dict(pooled_size=(2, 2), spatial_scale=1.0))),
  # rois held constant: bin boundaries are non-smooth in roi coords
  "_contrib_PSROIPooling": ("gradf", lambda: (
      (lambda d: call("_contrib_PSROIPooling", d,
                      nd.array(np.array([[0, 1, 1, 6, 6]], "float32")),
                      spatial_scale=1.0, output_dim=2, pooled_size=2)),
      [A(1, 8, 8, 8)])),
  "_contrib_DeformablePSROIPooling": ("fwd", lambda: ([A(1, 8, 8, 8),
      nd.array(np.array([[0, 1, 1, 6, 6]], "float32"))],
      dict(spatial_scale=1.0, output_dim=2, pooled_size=2, group_size=2,
           no_trans=True))),
  # offsets fixed at a non-integer value: bilinear sampling has kinks
  # at integer coordinates, so offsets are held constant for the
  # finite-difference check (their autograd path is covered in
  # test_contrib_ext.py)
  "_contrib_DeformableConvolution": ("gradf", lambda: (
      (lambda d, w, b: call("_contrib_DeformableConvolution", d,
                            nd.array(np.full((1, 8, 4, 4), 0.3,
                                             "float32")), w, b,
                            kernel=(2, 2), num_filter=3)),
      [A(1, 2, 5, 5), A(3, 2, 2, 2), A(3)])),
  "_contrib_ModulatedDeformableConvolution": ("fwd", lambda: (
      [A(1, 2, 5, 5), nd.array(np.zeros((1, 8, 4, 4), "float32")),
       nd.array(np.ones((1, 4, 4, 4), "float32")), A(3, 2, 2, 2), A(3)],
      dict(kernel=(2, 2), num_filter=3))),
  "_contrib_Proposal": ("fwd", lambda: ([A(1, 6, 4, 4, lo=0, hi=1),
      A(1, 12, 4, 4, lo=-0.1, hi=0.1),
      nd.array(np.array([[64, 64, 1.0]], "float32"))],
      dict(rpn_pre_nms_top_n=20, rpn_post_nms_top_n=8, scales=(8,),
           ratios=(0.5, 1, 2)))),
  "_contrib_MultiProposal": ("fwd", lambda: ([A(2, 6, 4, 4, lo=0, hi=1),
      A(2, 12, 4, 4, lo=-0.1, hi=0.1),
      nd.array(np.array([[64, 64, 1.0]] * 2, "float32"))],
      dict(rpn_pre_nms_top_n=20, rpn_post_nms_top_n=8, scales=(8,),
           ratios=(0.5, 1, 2)))),
  "_contrib_AdaptiveAvgPooling2D": ("grad", lambda: ([A(1, 2, 6, 6)],
                                    dict(output_size=(2, 2)))),
  "_contrib_BilinearResize2D": ("grad", lambda: ([A(1, 2, 4, 4)],
                                dict(height=6, width=6))),
  "_contrib_box_iou": ("fwd", lambda: ([
      nd.array(np.array([[0., 0, 2, 2]], "float32")),
      nd.array(np.array([[1., 1, 3, 3]], "float32"))], {})),
  "_contrib_box_nms": ("fwd", lambda: ([nd.array(np.array(
      [[[0.9, 0, 0, 2, 2], [0.8, 0.1, 0.1, 2, 2]]], "float32"))], {})),
  "_contrib_box_encode": ("fwd", lambda: ([
      nd.array(np.ones((1, 2), "float32")),
      nd.array(np.zeros((1, 2), "float32")),
      nd.array(np.array([[[10., 10, 20, 20], [30, 30, 50, 50]]],
                        "float32")),
      nd.array(np.array([[[12., 11, 22, 21]]], "float32"))], {})),
  "_contrib_mrcnn_mask_target": ("fwd", lambda: ([
      nd.array(np.array([[[0., 0., 7., 7.]]], "float32")),
      nd.array(np.ones((1, 1, 8, 8), "float32")),
      nd.array(np.zeros((1, 1), "int32")),
      nd.array(np.ones((1, 1), "int32"))],
      dict(num_rois=1, num_classes=2, mask_size=(2, 2)))),
  "MultiBoxTarget": ("fwd", lambda: ([
      nd.array(np.array([[[0.1, 0.1, 0.4, 0.4]]], "float32")),
      nd.array(np.array([[[0, 0.1, 0.1, 0.45, 0.45]]], "float32")),
      nd.array(np.zeros((1, 2, 1), "float32"))], {})),
  "MultiBoxDetection": ("fwd", lambda: ([
      nd.array(np.array([[[0.2, 0.3], [0.8, 0.7]]], "float32")
               .transpose(0, 2, 1).copy()),
      nd.array(np.zeros((1, 8), "float32")),
      nd.array(np.array([[[0.1, 0.1, 0.4, 0.4],
                          [0.5, 0.5, 0.9, 0.9]]], "float32"))], {})),
  "_contrib_count_sketch": ("fwd", lambda: ([A(2, 4),
      IDX(0, 1, 0, 1, dtype="float32"),
      IDX(1, -1, 1, 1, dtype="float32")], dict(out_dim=2))),
  "_contrib_index_copy": ("fwd", lambda: ([A(4, 3), IDX(1, 2),
                          A(2, 3)], {})),
  "_contrib_ifft": ("fwd", lambda: ([A(2, 8)], {})),
  # interleaved attention
  "_contrib_interleaved_matmul_selfatt_qk": ("grad", lambda: (
      [A(3, 2, 2 * 3 * 4)], dict(heads=2))),
  "_contrib_interleaved_matmul_selfatt_valatt": ("grad", lambda: (
      [A(3, 2, 2 * 3 * 4), A(4, 3, 3, lo=0, hi=0.5)], dict(heads=2))),
  "_contrib_interleaved_matmul_encdec_qk": ("grad", lambda: (
      [A(3, 2, 2 * 4), A(5, 2, 2 * 2 * 4)], dict(heads=2))),
  "_contrib_interleaved_matmul_encdec_valatt": ("grad", lambda: (
      [A(5, 2, 2 * 2 * 4), A(4, 3, 5, lo=0, hi=0.5)], dict(heads=2))),
  # --- linalg -----------------------------------------------------------
  "_linalg_gemm": ("grad", lambda: ([A(3, 4), A(4, 5), A(3, 5)], {})),
  "_linalg_gemm2": ("grad", lambda: ([A(3, 4), A(4, 5)], {})),
  # fwd: cholesky reads the lower triangle only (see FWD_ONLY note)
  "_linalg_potrf": ("fwd", lambda: ([SPD()], {})),
  "_linalg_potri": ("grad", lambda: ([TRIL()], {})),
  "_linalg_inverse": ("grad", lambda: ([SPD()], {})),
  "_linalg_det": ("grad", lambda: ([SPD()], {})),
  "_linalg_slogdet": ("fwd", lambda: ([SPD()], {})),
  "_linalg_syevd": ("fwd", lambda: ([SPD()], {})),
  "_linalg_trmm": ("grad", lambda: ([TRIL(), A(3, 3)], {})),
  "_linalg_trsm": ("grad", lambda: ([TRIL(), A(3, 3)], {})),
  "_np_linalg_cholesky": ("grad", lambda: ([SPD()], {})),
  "_np_linalg_det": ("grad", lambda: ([SPD()], {})),
  "_np_linalg_inv": ("grad", lambda: ([SPD()], {})),
  "_np_linalg_eigh": ("fwd", lambda: ([SPD()], {})),
  "_np_linalg_eigvalsh": ("fwd", lambda: ([SPD()], {})),
  "_np_linalg_slogdet": ("fwd", lambda: ([SPD()], {})),
  "_np_linalg_solve": ("grad", lambda: ([SPD(), A(3, 2)], {})),
  "_np_linalg_matrix_power": ("grad", lambda: ([SPD()], dict(n=2))),
  "_np_matmul": ("grad", lambda: ([A(2, 3), A(3, 2)], {})),
  "_npi_matmul": ("grad", lambda: ([A(2, 3), A(3, 2)], {})),
  "dot": ("grad", lambda: ([A(3, 4), A(4, 5)], {})),
  "batch_dot": ("grad", lambda: ([A(2, 3, 4), A(2, 4, 5)], {})),
  # --- tensor misc ------------------------------------------------------
  "batch_take": ("fwd", lambda: ([A(3, 4), IDX(0, 2, 1)], {})),
  "broadcast_to": ("grad", lambda: ([A(1, 4)], dict(shape=(3, 4)))),
  "_np_broadcast_to": ("grad", lambda: ([A(1, 4)],
                       dict(shape=(3, 4)))),
  "one_hot": ("fwd", lambda: ([IDX(0, 2, 1)], dict(depth=4))),
  "pad": ("grad", lambda: ([A(1, 1, 3, 3)],
          dict(mode="constant",
               pad_width=(0, 0, 0, 0, 1, 1, 1, 1)))),
  "_np_pad": ("grad", lambda: ([A(3, 3)],
              dict(pad_width=((1, 1), (0, 0))))),
  "pick": ("gradf", lambda: (
      (lambda d: call("pick", d, IDX(0., 2., 1., dtype="float32"))),
      [A(3, 4)])),
  "reshape": ("grad", lambda: ([A(3, 4)], dict(shape=(4, 3)))),
  "_np_reshape": ("grad", lambda: ([A(3, 4)], dict(newshape=(4, 3)))),
  "slice": ("grad", lambda: ([A(3, 4)], dict(begin=(0, 1),
            end=(3, 4)))),
  "split": ("fwd", lambda: ([A(4, 6)], dict(num_outputs=2, axis=1))),
  "split_v2": ("fwd", lambda: ([A(4, 6)], dict(indices_or_sections=2,
               axis=1))),
  "_np_split": ("fwd", lambda: ([A(4, 6)], dict(
      indices_or_sections=2, axis=1))),
  "tile": ("grad", lambda: ([A(2, 3)], dict(reps=(2, 1)))),
  "_np_tile": ("grad", lambda: ([A(2, 3)], dict(reps=(2, 1)))),
  "_np_repeat": ("grad", lambda: ([A(2, 3)], dict(repeats=2))),
  "where": ("grad", lambda: ([nd.array((np.arange(6).reshape(2, 3) % 2)
            .astype("float32")), A(2, 3), A(2, 3)], {})),
  "_np_where": ("grad", lambda: ([nd.array((np.arange(6)
                .reshape(2, 3) % 2).astype("bool")), A(2, 3),
                A(2, 3)], {})),
  "_np_moveaxis": ("grad", lambda: ([A(2, 3, 4)],
                   dict(source=0, destination=2))),
  "_np_roll": ("grad", lambda: ([A(2, 3)], dict(shift=1, axis=1))),
  "_np_take": ("gradf", lambda: (
      (lambda d: call("_np_take", d, IDX(0, 2))), [A(4, 3)])),
  "_np_take_along_axis": ("gradf", lambda: (
      (lambda d: call("_np_take_along_axis", d,
                      nd.array(np.array([[0, 1, 2, 0]], "int32")),
                      axis=0)), [A(3, 4)])),
  "depth_to_space": ("grad", lambda: ([A(1, 4, 2, 2)],
                     dict(block_size=2))),
  "space_to_depth": ("grad", lambda: ([A(1, 1, 4, 4)],
                     dict(block_size=2))),
  "im2col": ("grad", lambda: ([A(1, 2, 4, 4)], dict(kernel=(2, 2)))),
  "col2im": ("grad", lambda: ([A(1, 8, 9)], dict(
      output_size=(4, 4), kernel=(2, 2)))),
  "scatter_nd": ("fwd", lambda: ([A(2), nd.array(
      np.array([[0, 1], [0, 1]], "int32"))], dict(shape=(2, 2)))),
  "fill_element_0index": ("fwd", lambda: ([A(3, 4),
      IDX(1., 2., 0., dtype="float32"),
      IDX(0., 1., 2., dtype="float32")], {})),
  "ravel_multi_index": ("fwd", lambda: ([nd.array(
      np.array([[0, 1], [1, 0]], "float32"))], dict(shape=(2, 2)))),
  "unravel_index": ("fwd", lambda: ([IDX(1, 2, dtype="float32")],
                    dict(shape=(2, 2)))),
  "softmax_cross_entropy": ("fwd", lambda: ([A(3, 4),
      IDX(0., 1., 2., dtype="float32")], {})),
  "_np_convolve": ("grad", lambda: ([A(5), A(3)], {})),
  "_np_correlate": ("grad", lambda: ([A(5), A(3)], {})),
  "_np_ldexp": ("fwd", lambda: ([A(2, 3), nd.array(
      np.array([1, 2, 0], "int32"))], {})),
  "_np_linalg_qr": ("grad", lambda: ([SPD()], {})),
  "_div_scalar": ("grad", lambda: ([A(2, 3)], dict(scalar=2.0))),
  "_floordiv_scalar": ("fwd", lambda: ([A(2, 3)], dict(scalar=2.0))),
  "_mod_scalar": ("fwd", lambda: ([A(2, 3)], dict(scalar=2.0))),
  "SVMOutput": ("fwd", lambda: ([A(3, 4), IDX(0., 1., 2.,
                dtype="float32")], {})),
  "SoftmaxOutput": ("fwd", lambda: ([A(3, 4), IDX(0., 1., 2.,
                    dtype="float32")], {})),
  "_np_percentile": ("fwd", lambda: ([A(3, 4)], dict(q=50))),
  "_np_quantile": ("fwd", lambda: ([A(3, 4)], dict(q=0.5))),
  "_np_searchsorted": ("fwd", lambda: ([nd.array(
      np.array([0.1, 0.5, 1.0], "float32")), A(2, 3)], {})),
  "_np_digitize": ("fwd", lambda: ([A(2, 3), nd.array(
      np.array([0.6, 0.9, 1.2], "float32"))], {})),
  "_np_vander": ("fwd", lambda: ([A(4)], dict(N=3))),
  "_np_bincount": ("fwd", lambda: ([IDX(0, 1, 1, 3)], {})),
  "_np_tri": ("fwd", lambda: ([], dict(N=3))),
  "_np_indices": ("fwd", lambda: ([], dict(dimensions=(2, 3)))),
  "_np_interp": ("fwd", lambda: ([A(3), nd.array(
      np.array([0.5, 1.0, 1.5], "float32")), A(3)], {})),
  # int/bit ops
  "_np_bitwise_and": ("fwd", lambda: ([IDX(1, 2, 3), IDX(3, 2, 1)],
                      {})),
  "_np_bitwise_or": ("fwd", lambda: ([IDX(1, 2, 3), IDX(3, 2, 1)], {})),
  "_np_bitwise_xor": ("fwd", lambda: ([IDX(1, 2, 3), IDX(3, 2, 1)],
                      {})),
  "_np_left_shift": ("fwd", lambda: ([IDX(1, 2), IDX(1, 2)], {})),
  "_np_right_shift": ("fwd", lambda: ([IDX(4, 8), IDX(1, 2)], {})),
  "_np_gcd": ("fwd", lambda: ([IDX(4, 6), IDX(6, 9)], {})),
  "_np_lcm": ("fwd", lambda: ([IDX(4, 6), IDX(6, 9)], {})),
  # windows / creation
  "_np_bartlett": ("fwd", lambda: ([], dict(M=5))),
  "_np_blackman": ("fwd", lambda: ([], dict(M=5))),
  "_np_hamming": ("fwd", lambda: ([], dict(M=5))),
  "_np_hanning": ("fwd", lambda: ([], dict(M=5))),
  "_np_kaiser": ("fwd", lambda: ([], dict(M=5, beta=2.0))),
  "_arange": ("fwd", lambda: ([], dict(start=0, stop=6))),
  "_eye": ("fwd", lambda: ([], dict(N=3))),
  "_full": ("fwd", lambda: ([], dict(shape=(2, 3), value=1.5))),
  "_ones": ("fwd", lambda: ([], dict(shape=(2, 3)))),
  "_zeros": ("fwd", lambda: ([], dict(shape=(2, 3)))),
  # --- optimizer update ops (mutating; numerics in test_operator) -------
  "sgd_mom_update": ("fwd", lambda: ([A(3), A(3), nd.zeros((3,))], {})),
  "nag_mom_update": ("fwd", lambda: ([A(3), A(3), nd.zeros((3,))], {})),
  "mp_sgd_update": ("fwd", lambda: ([A(3), A(3), A(3)], {})),
  "mp_sgd_mom_update": ("fwd", lambda: ([A(3), A(3), nd.zeros((3,)),
                        A(3)], {})),
  "mp_nag_mom_update": ("fwd", lambda: ([A(3), A(3), nd.zeros((3,)),
                        A(3)], {})),
  "adam_update": ("fwd", lambda: ([A(3), A(3), nd.zeros((3,)),
                  nd.zeros((3,))], {})),
  "mp_adam_update": ("fwd", lambda: ([A(3), A(3), nd.zeros((3,)),
                     nd.zeros((3,)), A(3)], {})),
  "adamw_update": ("fwd", lambda: ([A(3), A(3), nd.zeros((3,)),
                   nd.zeros((3,))], dict(eta=1.0))),
  "ftrl_update": ("fwd", lambda: ([A(3), A(3), nd.zeros((3,)),
                  nd.zeros((3,))], {})),
  "rmsprop_update": ("fwd", lambda: ([A(3), A(3), nd.zeros((3,))], {})),
  "rmspropalex_update": ("fwd", lambda: ([A(3), A(3), nd.zeros((3,)),
                         nd.zeros((3,)), nd.zeros((3,))], {})),
  "signum_update": ("fwd", lambda: ([A(3), A(3), nd.zeros((3,))], {})),
  "lamb_update_phase1": ("fwd", lambda: ([A(3), A(3), nd.zeros((3,)),
                         nd.zeros((3,))], dict(t=1))),
  "lamb_update_phase2": ("fwd", lambda: ([A(3), A(3), A(1), A(1)], {})),
  "mp_lamb_update_phase1": ("fwd", lambda: ([A(3), A(3),
                            nd.zeros((3,)), nd.zeros((3,)), A(3)],
                            dict(t=1))),
  "mp_lamb_update_phase2": ("fwd", lambda: ([A(3), A(3), A(1), A(1),
                            A(3)], {})),
  "multi_lars": ("fwd", lambda: ([A(4), A(4), A(4), A(4)],
                 dict(eta=0.1, eps=1e-8))),
  "_contrib_group_adagrad_update": ("fwd", lambda: ([A(3, 2), A(3, 2),
                                    nd.zeros((3, 1))], {})),
  # --- quantized (int8 setups live in test_quantization.py) ------------
  "_contrib_quantize": ("fwd", lambda: ([A(2, 3, lo=-1, hi=1),
                        nd.array(np.array([-1.0], "float32")),
                        nd.array(np.array([1.0], "float32"))], {})),
}

SKIP = {
    "Custom": "framework plugin op; full coverage in test_custom_op.py",
    "RNN": "stateful fused op; coverage in test_gluon (rnn layers) and "
           "test_operator (sequence ops)",
    "BlockGrad": "identity w/ stop_gradient; gradient IS the contract "
                 "(zero) — covered in test_autograd",
    "_contrib_dequantize": "int8 pipeline op; end-to-end in "
                           "test_quantization.py",
    "_contrib_requantize": "int8 pipeline op; end-to-end in "
                           "test_quantization.py",
    "_contrib_quantized_act": "int8 pipeline; test_quantization.py",
    "_contrib_quantized_conv": "int8 pipeline; test_quantization.py",
    "_contrib_quantized_flatten": "int8 pipeline; test_quantization.py",
    "_contrib_quantized_fully_connected": "int8 pipeline; "
                                          "test_quantization.py",
    "_contrib_quantized_pooling": "int8 pipeline; test_quantization.py",
    "quantize_v2": "int8 pipeline; test_quantization.py",
    "_np_histogram": "tuple-of-arrays return; oracle in test_numpy.py",
    "_np_quantile": "needs q kwarg variants; oracle in test_numpy.py",
    "_contrib_boolean_mask": "data-dependent output shape (cannot jit "
                             "on TPU by design); eager semantics "
                             "covered in test_longtail_ops.py",
}


def _canonical_ops():
    seen = {}
    for name in registry.list_ops():
        # `_test_*` is the reserved prefix for ops registered by test
        # fixtures (e.g. test_eager_jit's untraceable-op probe); they
        # must never leak into the committed correctness ledger — a
        # same-process test run would otherwise add them to
        # docs/op_sweep_record.json (round-4 verdict weak #6)
        if name.startswith("_test_"):
            continue
        op = registry.get_op(name)
        seen.setdefault(id(op), op.name)
    return sorted(set(seen.values()))


def _finite_check(name, out):
    outs = out if isinstance(out, (tuple, list)) else [out]
    for o in outs:
        a = o.asnumpy()
        assert a is not None
        if a.dtype.kind == "f" and name not in ("masked_log_softmax",):
            assert np.isfinite(a).all(), "%s produced non-finite" % name


def _grad_check(name, fn, inputs):
    tu.check_numeric_gradient(fn, [x.asnumpy() for x in inputs],
                              rtol=3e-2, atol=3e-3)


def _auto_case(name):
    """Try the auto patterns; return (mode, fn, inputs) or None."""
    dom = DOMAIN.get(name, {})
    x1 = A(2, 3, seed=1, **dom)
    x2 = A(2, 3, seed=2, **dom)
    for inputs in ([x1], [x1, x2]):
        try:
            call(name, *inputs)
            return inputs
        except Exception:
            continue
    return None


def test_registry_sweep_full():
    ops = _canonical_ops()
    record = {}
    failures = []
    unaccounted = []
    for name in ops:
        op = registry.get_op(name)
        if name in SKIP:
            record[name] = {"status": "skip", "reason": SKIP[name]}
            continue
        if op.variadic:
            record[name] = {"status": "skip",
                            "reason": "variadic; covered in "
                                      "test_operator.py fused-group "
                                      "tests"}
            continue
        if op.needs_rng:
            record[name] = {"status": "skip",
                            "reason": "sampler; distribution moments "
                                      "in test_operator/"
                                      "test_contrib_ext"}
            continue
        no_grad = op.no_grad({}) if callable(op.no_grad) else op.no_grad

        fn = None
        if name in SPECS:
            mode, builder = SPECS[name]
            if mode == "gradf":
                fn, inputs = builder()
                kwargs = {}
            else:
                inputs, kwargs = builder()
        else:
            inputs = _auto_case(name)
            if inputs is None:
                unaccounted.append(name)
                continue
            kwargs = {}
            mode = "fwd" if (no_grad or name in FWD_ONLY) else "grad"
        if fn is None:
            fn = lambda *xs, _n=name, _k=kwargs: call(_n, *xs, **_k)

        try:
            out = fn(*inputs)
            _finite_check(name, out)
            if mode in ("grad", "gradf"):
                _grad_check(name, fn, inputs)
            record[name] = {"status": "pass",
                            "mode": "grad" if mode == "gradf" else mode}
        except Exception as e:  # noqa: BLE001 - recorded then asserted
            failures.append((name, mode, str(e)[:200]))
            record[name] = {"status": "fail", "mode": mode,
                            "error": str(e)[:200]}

    n_grad = sum(1 for r in record.values()
                 if r.get("mode") == "grad" and r["status"] == "pass")
    n_fwd = sum(1 for r in record.values()
                if r.get("mode") == "fwd" and r["status"] == "pass")
    summary = {"total_canonical": len(ops), "grad_checked": n_grad,
               "fwd_checked": n_fwd,
               "skipped": sum(1 for r in record.values()
                              if r["status"] == "skip")}
    with open(RECORD, "w") as f:
        json.dump({"summary": summary, "ops": record}, f, indent=1,
                  sort_keys=True)

    assert not unaccounted, \
        "ops with no auto pattern, SPEC, or SKIP: %r" % unaccounted
    assert not failures, failures
    assert n_grad + n_fwd >= 300, summary
    assert n_grad >= 180, summary
