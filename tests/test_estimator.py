"""Estimator + contrib layer tests (reference:
tests/python/unittest/test_gluon_estimator.py, test_gluon_contrib.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.contrib import nn as contrib_nn
from mxnet_tpu.gluon.contrib.estimator import (
    CheckpointHandler, EarlyStoppingHandler, EpochEnd, Estimator,
    LoggingHandler, StoppingHandler)


def _dataset(n=256, dim=8, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    W = rng.randn(dim, classes)
    X = rng.randn(n, dim).astype(np.float32)
    y = np.argmax(X @ W, axis=1).astype(np.float32)
    return X, y


def _loader(X, y, batch=64):
    for i in range(0, len(X), batch):
        yield nd.array(X[i:i + batch]), nd.array(y[i:i + batch])


class _ListLoader:
    """Re-iterable loader (generator exhausts after one epoch)."""

    def __init__(self, X, y, batch=64):
        self.batches = list(_loader(X, y, batch))

    def __iter__(self):
        return iter(self.batches)


def _net(classes=3):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(classes))
    net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    return net


@pytest.mark.slow
def test_estimator_fit_improves_accuracy():
    X, y = _dataset()
    net = _net()
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    train_metrics=mx.metric.Accuracy(),
                    trainer=gluon.Trainer(net.collect_params(), "adam",
                                          {"learning_rate": 0.01}),
                    context=mx.cpu())
    est.fit(_ListLoader(X, y), epochs=10)
    name, acc = est.train_metrics[0].get()
    assert acc > 0.8, (name, acc)
    # loss metric populated
    _, lv = est.train_loss_metric.get()
    assert np.isfinite(lv)


@pytest.mark.slow
def test_estimator_validation_and_early_stopping():
    X, y = _dataset()
    net = _net()
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    train_metrics=mx.metric.Accuracy(),
                    context=mx.cpu())
    early = EarlyStoppingHandler(monitor=est.val_metrics[0], patience=2)
    est.fit(_ListLoader(X, y), val_data=_ListLoader(X, y), epochs=50,
            event_handlers=[early])
    # must have stopped long before 50 epochs on a non-improving metric
    assert early.current_epoch < 50


def test_estimator_max_batches():
    X, y = _dataset()
    net = _net()
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    context=mx.cpu())
    stop = StoppingHandler(max_batch=3)
    est.fit(_ListLoader(X, y), batches=3, event_handlers=[stop])
    assert stop.current_batch == 3


def test_estimator_checkpoint(tmp_path):
    X, y = _dataset()
    net = _net()
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    train_metrics=mx.metric.Accuracy(),
                    context=mx.cpu())
    ckpt = CheckpointHandler(str(tmp_path), model_prefix="m",
                             monitor=est.train_metrics[0], save_best=True)
    est.fit(_ListLoader(X, y), epochs=2, event_handlers=[ckpt])
    files = os.listdir(tmp_path)
    assert any(f.startswith("m-epoch") and f.endswith(".params")
               for f in files), files
    assert "m-best.params" in files
    # roundtrip: load best params into a fresh net
    net2 = _net()
    net2.load_parameters(str(tmp_path / "m-best.params"), ctx=mx.cpu())
    xa = nd.array(X[:4])
    np.testing.assert_allclose(net(xa).asnumpy(), net2(xa).asnumpy(),
                               rtol=1e-6)


def test_custom_event_handler():
    X, y = _dataset()
    net = _net()
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    context=mx.cpu())

    class CountEpochs(EpochEnd):
        n = 0

        def epoch_end(self, estimator, *a, **kw):
            CountEpochs.n += 1

    est.fit(_ListLoader(X, y), epochs=3, event_handlers=[CountEpochs()])
    assert CountEpochs.n == 3


# ---------------------------------------------------------------------------
# contrib layers
# ---------------------------------------------------------------------------

def test_hybrid_concurrent_and_identity():
    blk = contrib_nn.HybridConcurrent(axis=1)
    with blk.name_scope():
        blk.add(nn.Dense(4))
        blk.add(nn.Dense(4))
        blk.add(contrib_nn.Identity())
    blk.initialize(ctx=mx.cpu())
    x = nd.ones((2, 4))
    out = blk(x)
    assert out.shape == (2, 12)
    np.testing.assert_allclose(out.asnumpy()[:, 8:], np.ones((2, 4)))


def test_concurrent():
    blk = contrib_nn.Concurrent(axis=1)
    with blk.name_scope():
        blk.add(nn.Dense(3), contrib_nn.Identity())
    blk.initialize(ctx=mx.cpu())
    out = blk(nd.ones((2, 5)))
    assert out.shape == (2, 8)


def test_pixelshuffle2d():
    x = nd.array(np.arange(2 * 8 * 3 * 3, dtype=np.float32)
                 .reshape(2, 8, 3, 3))
    out = contrib_nn.PixelShuffle2D(2)(x)
    assert out.shape == (2, 2, 6, 6)
    # torch-style check: block (0,0) of channel 0 comes from channels 0..3
    xn = x.asnumpy()
    on = out.asnumpy()
    assert on[0, 0, 0, 0] == xn[0, 0, 0, 0]
    assert on[0, 0, 0, 1] == xn[0, 1, 0, 0]
    assert on[0, 0, 1, 0] == xn[0, 2, 0, 0]
    assert on[0, 0, 1, 1] == xn[0, 3, 0, 0]


def test_sparse_embedding_lazy_update():
    """sparse_grad=True routes through the row-lazy optimizer update:
    with wd > 0 only rows seen in the batch change (reference
    lazy_update semantics); dense grads would decay every row."""
    from mxnet_tpu import autograd
    emb = contrib_nn.SparseEmbedding(10, 4)
    emb.initialize(mx.initializer.One(), ctx=mx.cpu())
    params = emb.collect_params()
    assert list(params.values())[0].grad_stype == "row_sparse"
    trainer = gluon.Trainer(params, "sgd",
                            {"learning_rate": 0.1, "wd": 0.5})
    x = nd.array(np.array([1, 3], np.float32))
    with autograd.record():
        loss = emb(x).sum()
    loss.backward()
    trainer.step(2)
    w = list(params.values())[0].data().asnumpy()
    assert not np.allclose(w[1], 1.0)  # touched rows updated
    np.testing.assert_allclose(w[0], 1.0)  # untouched: no decay (lazy)
    np.testing.assert_allclose(w[5], 1.0)


def test_estimator_fit_zero_epochs_returns():
    X, y = _dataset(n=64)
    net = _net()
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    context=mx.cpu())
    est.fit(_ListLoader(X, y), epochs=0)   # must not hang
    est.fit(_ListLoader(X, y), batches=0)  # must not hang


def test_checkpoint_resume(tmp_path):
    X, y = _dataset(n=64)
    net = _net()
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    context=mx.cpu())
    est.fit(_ListLoader(X, y), epochs=2, event_handlers=[
        CheckpointHandler(str(tmp_path), model_prefix="r")])
    # fresh net resumes from the newest epoch checkpoint
    net2 = _net()
    est2 = Estimator(net2, gluon.loss.SoftmaxCrossEntropyLoss(),
                     context=mx.cpu())
    ckpt = CheckpointHandler(str(tmp_path), model_prefix="r",
                             resume_from_checkpoint=True, verbose=1)
    est2.fit(_ListLoader(X, y), epochs=1, event_handlers=[ckpt])
    assert ckpt.current_epoch >= 2  # resumed past the saved epochs


def test_val_metrics_preserve_config():
    net = _net()
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    train_metrics=mx.metric.TopKAccuracy(top_k=2),
                    context=mx.cpu())
    assert est.val_metrics[0].top_k == 2


def test_syncbatchnorm_matches_batchnorm():
    sbn = contrib_nn.SyncBatchNorm(in_channels=4)
    bn = nn.BatchNorm(in_channels=4)
    sbn.initialize(ctx=mx.cpu())
    bn.initialize(ctx=mx.cpu())
    x = nd.array(np.random.RandomState(0)
                 .randn(2, 4, 3, 3).astype(np.float32))
    np.testing.assert_allclose(sbn(x).asnumpy(), bn(x).asnumpy(),
                               rtol=1e-5, atol=1e-5)
