"""Latency-hiding overlap (round 21): the double-buffered engine
pipeline with device-carried sampling must be BIT-IDENTICAL to the
serial schedule — and to ``models/gpt.py generate`` — under every
stop condition that can invalidate a speculatively dispatched step.

Exactness pins:

* overlap ON vs OFF, mixed prompt/output lengths, through eos stops,
  mid-pipeline preemption, and a cancel racing the planner thread —
  identical states and tokens for every non-cancelled request, zero
  leaked pages/refs either way;
* a cancelled request's committed tokens may legitimately differ by
  pipeline depth (the cancel lands one step earlier or later), but
  the shorter transcript must prefix the longer — a wrong carried
  token would break the prefix, not just the length;
* ``spec_K > 0`` engines fence the pipeline (carried argmaxes can't
  feed the draft matcher, which needs host tokens) and must degrade
  to exact serial behaviour;
* both cluster flavors (replicated ``ServingCluster`` and the
  process-split ``DisaggServingCluster``) stay generate-identical
  with ``overlap=True`` threaded through their engine kwargs.

Slow tier, group o (own group: every scenario pays a second compiled
step variant — the ``tok_src`` program — on top of the serial one).
"""
import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401  (conftest device setup)


def _cfg(**kw):
    from mxnet_tpu.models import gpt
    base = dict(use_flash=False, remat=False, dropout=0.0,
                dtype="float32", vocab_size=97, max_len=96)
    base.update(kw)
    return gpt.gpt_tiny(**base)


def _setup(seed=0):
    import jax
    from mxnet_tpu.models import transformer as T
    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(seed), cfg)
    return params, cfg


def _ref(params, cfg, prompt, n):
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt
    return np.asarray(
        gpt.generate(params, cfg, jnp.asarray(prompt)[None], n))[0]


def _mixed(rng, vocab, lens=(3, 11, 7, 19, 5, 13)):
    return [rng.randint(1, vocab, size=n).astype(np.int32)
            for n in lens]


def _drain_engine(eng, chaos=None, cancel_rid=None):
    """Step to completion, optionally injecting chaos at a fixed step
    index (same index whichever schedule the pipeline runs, so serial
    and overlapped runs face the same script)."""
    steps = 0
    while True:
        if eng.step() is False:
            break
        steps += 1
        if chaos == "preempt" and steps == 3:
            running = [r for r in eng._slots if r is not None]
            if running:
                eng.preempt(running[-1].rid)
        if chaos == "cancel" and steps == 4 and cancel_rid is not None:
            eng.cancel(cancel_rid)
    return steps


def _engine_run(params, cfg, overlap, eos=None, chaos=None,
                spec_K=0):
    from mxnet_tpu.serving import ServingEngine
    rng = np.random.RandomState(7)
    prompts = _mixed(rng, cfg.vocab_size)
    maxnew = [9, 4, 1, 7, 12, 6]
    eng = ServingEngine(params, cfg, num_slots=3, page_size=8,
                        prefill_chunk=6, prefix_cache=True,
                        spec_K=spec_K, overlap=overlap)
    rids = [eng.submit(p, m, eos_id=eos)
            for p, m in zip(prompts, maxnew)]
    _drain_engine(eng, chaos=chaos, cancel_rid=rids[1])
    res = {rid: (req.state, list(req.generated))
           for rid, req in eng.requests.items()}
    if eng.prefix is not None:
        eng.prefix.evict(10 ** 9)
    held = eng.cache.pages_in_use
    stats = dict(eng.stats)
    eng.close()
    return res, held, stats


def _assert_equiv(a, b, name):
    """Serial run ``a`` vs overlapped run ``b``: same states
    everywhere; exact tokens except for cancelled requests, whose
    transcripts must be prefix-consistent (pipeline-depth slack)."""
    assert set(a) == set(b)
    for rid in a:
        sa, ga = a[rid]
        sb, gb = b[rid]
        assert sa == sb, (name, rid, a, b)
        if sa == "cancelled":
            n = min(len(ga), len(gb))
            assert ga[:n] == gb[:n], (name, rid, ga, gb)
        else:
            assert ga == gb, (name, rid, ga, gb)


@pytest.mark.slow
@pytest.mark.parametrize("scenario", ["plain", "eos", "preempt",
                                      "cancel"])
def test_overlap_bit_identical_to_serial(scenario):
    """The core pin: overlapped engine vs serial engine on the same
    mixed-length burst, with the speculatively dispatched step
    invalidated by eos stops, a mid-pipeline preemption, or a cancel
    racing the planner — identical outcomes, zero leaks, and the
    overlapped run actually pipelined (overlap_steps > 0) while
    hiding host time (host_hidden_ms > 0)."""
    params, cfg = _setup()
    kw = {"plain": {}, "eos": {"eos": 5},
          "preempt": {"chaos": "preempt"},
          "cancel": {"chaos": "cancel"}}[scenario]
    a, held_a, _ = _engine_run(params, cfg, overlap=False, **kw)
    b, held_b, st = _engine_run(params, cfg, overlap=True, **kw)
    _assert_equiv(a, b, scenario)
    assert held_a == 0 and held_b == 0, (scenario, held_a, held_b)
    assert st["overlap_steps"] > 0
    assert st["host_hidden_ms"] > 0.0


@pytest.mark.slow
def test_overlap_matches_generate():
    """Single-request overlapped decode is token-identical to plain
    ``generate`` (the carried argmax is the same argmax the host
    would have fed back)."""
    from mxnet_tpu.serving import ServingEngine
    params, cfg = _setup()
    rng = np.random.RandomState(11)
    for p, m in zip(_mixed(rng, cfg.vocab_size, (3, 11, 7)),
                    (8, 5, 6)):
        ref = _ref(params, cfg, p, m)
        eng = ServingEngine(params, cfg, num_slots=2, page_size=8,
                            prefill_chunk=8, overlap=True)
        rid = eng.submit(p, m)
        out = eng.run()[rid]
        eng.close()
        assert np.array_equal(ref[:out.size], out), (ref, out)


@pytest.mark.slow
def test_overlap_spec_engine_fences_to_serial():
    """spec_K > 0: the draft matcher needs host-visible tokens, so
    every decode step with live samplers fences the pipeline — the
    overlapped engine must produce bit-identical output to the serial
    one, and the fence counter must prove the fencing actually
    happened (not that overlap silently disabled itself)."""
    from mxnet_tpu.serving import ServingEngine
    params, cfg = _setup()
    rng = np.random.RandomState(7)
    prompts = _mixed(rng, cfg.vocab_size)
    maxnew = [9, 4, 1, 7, 12, 6]

    def run(overlap):
        eng = ServingEngine(params, cfg, num_slots=3, page_size=8,
                            prefill_chunk=6, spec_K=2,
                            overlap=overlap)
        for p, m in zip(prompts, maxnew):
            eng.submit(p, m)
        out = {k: v.tolist() for k, v in eng.run().items()}
        st = dict(eng.stats)
        eng.close()
        return out, st

    sa, _ = run(False)
    sb, st = run(True)
    assert sa == sb
    assert st["overlap_fences"] > 0


@pytest.mark.slow
def test_overlap_eos_invalidates_speculative_step_no_leak():
    """An eos stop commits one step BEHIND an already-dispatched
    speculative step: the junk row the dead slot computed must never
    be committed, the slot's pages must come back, and a follow-up
    request reusing the slot must still be exact."""
    from mxnet_tpu.serving import ServingEngine
    params, cfg = _setup()
    rng = np.random.RandomState(3)
    p = rng.randint(1, cfg.vocab_size, 6).astype(np.int32)
    full = _ref(params, cfg, p, 12)[p.size:]
    eos = int(full[2])                     # stop after 3 tokens
    eng = ServingEngine(params, cfg, num_slots=2, page_size=8,
                        prefill_chunk=8, overlap=True)
    rid = eng.submit(p, 12, eos_id=eos)
    eng.run()
    got = list(eng.requests[rid].generated)
    assert got == [int(t) for t in full[:3]]
    assert eng.cache.pages_in_use == 0
    # slot reuse after the invalidated step: fresh request, exact
    q = rng.randint(1, cfg.vocab_size, 9).astype(np.int32)
    rid2 = eng.submit(q, 5)
    out = eng.run()[rid2]
    assert np.array_equal(out, _ref(params, cfg, q, 5))
    eng.close()


@pytest.mark.slow
def test_cluster_overlap_identity_and_cancel_race():
    """Replicated cluster with overlap=True: mixed-length burst is
    generate-identical, a cancel fired from another thread mid-flight
    retires cleanly, and the drain leaves zero refs/pages on every
    replica."""
    import threading
    from mxnet_tpu.serving import ServingCluster
    params, cfg = _setup()
    rng = np.random.RandomState(5)
    prompts = _mixed(rng, cfg.vocab_size)
    maxnew = [6, 4, 8, 5, 7, 3]
    cl = ServingCluster(params, cfg, replicas=2, num_slots=2,
                        page_size=8, prefill_chunk=6, overlap=True)
    try:
        rids = [cl.submit(p, n) for p, n in zip(prompts, maxnew)]
        victim = rids[2]
        th = threading.Thread(target=lambda: cl.cancel(victim))
        th.start()
        for rid, p, n in zip(rids, prompts, maxnew):
            if rid == victim:
                continue
            out = cl.result(rid, timeout=300)
            assert np.array_equal(out, _ref(params, cfg, p, n))
        th.join(60)
        cr = cl.requests[victim]
        assert cr.state in ("done", "cancelled")
        if cr.state == "done":
            assert np.array_equal(cl.result(victim, timeout=60),
                                  _ref(params, cfg, prompts[2],
                                       maxnew[2]))
        else:
            exp = _ref(params, cfg, prompts[2],
                       maxnew[2])[prompts[2].size:]
            got = list(cr.committed)
            assert got == [int(t) for t in exp[:len(got)]]
        for rep in cl.replicas:
            eng = rep.engine
            assert eng.overlap
            assert eng.stats["overlap_steps"] > 0
            if eng.prefix is not None:
                assert eng.prefix.refs_total == 0
                assert eng.cache.pages_in_use == \
                    eng.prefix.cached_pages
    finally:
        cl.close(timeout=60)


@pytest.mark.slow
def test_disagg_cluster_overlap_identity():
    """Process-split cluster (1 prefill + 1 decode OS process) with
    overlap=True threaded through the worker engine kwargs: outputs
    stay generate-identical, the decode worker actually pipelines
    (overlap_steps > 0 in its stats snapshot), and no worker leaks
    pages, refs, or staged streams."""
    from mxnet_tpu.serving import DisaggServingCluster
    params, cfg = _setup()
    rng = np.random.RandomState(9)
    prompts = _mixed(rng, cfg.vocab_size, (5, 9, 17, 3, 12))
    nnew = [6, 4, 8, 5, 7]
    cl = DisaggServingCluster(params, cfg, prefill=1, decode=1,
                              num_slots=4, page_size=4,
                              metrics=True, watchdog_s=60.0,
                              overlap=True)
    try:
        rids = [cl.submit(p, n) for p, n in zip(prompts, nnew)]
        for rid, p, n in zip(rids, prompts, nnew):
            out = cl.result(rid, timeout=180)
            assert np.array_equal(out, _ref(params, cfg, p, n))
        st = cl.cluster_stats()
        assert st["decode0"]["overlap_steps"] > 0
        for name, ws in st.items():
            assert ws["pages_in_use"] - ws["prefix_cached_pages"] \
                == 0, (name, ws)
            assert ws["prefix_refs"] == 0, (name, ws)
            assert ws["staged_rids"] == 0, (name, ws)
            assert ws["active_requests"] == 0, (name, ws)
    finally:
        cl.close()


def test_overlap_env_var_and_validation():
    """Fast tier: ``MXNET_SERVE_OVERLAP`` resolves the default, the
    explicit kwarg wins over the env, and close() is idempotent —
    all without compiling anything (no steps run)."""
    import os
    from mxnet_tpu.serving import ServingEngine
    params, cfg = _setup()

    def make(**kw):
        return ServingEngine(params, cfg, num_slots=2, page_size=8,
                             prefill_chunk=8, **kw)

    old = os.environ.get("MXNET_SERVE_OVERLAP")
    try:
        os.environ["MXNET_SERVE_OVERLAP"] = "1"
        eng = make()
        assert eng.overlap
        eng.close()
        eng = make(overlap=False)
        assert not eng.overlap
        eng.close()
        os.environ["MXNET_SERVE_OVERLAP"] = "0"
        eng = make()
        assert not eng.overlap
        eng.close()
        eng = make(overlap=True)
        assert eng.overlap
        eng.close()
        eng.close()                        # idempotent
    finally:
        if old is None:
            os.environ.pop("MXNET_SERVE_OVERLAP", None)
        else:
            os.environ["MXNET_SERVE_OVERLAP"] = old
