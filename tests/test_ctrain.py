"""Training-capable C++ frontend over the C train ABI (round-2 verdict
item #9; reference: cpp-package/include/mxnet-cpp/ — SURVEY.md §2.3
"C++ frontend" row): a standalone C++ program trains an MNIST-style MLP
through MXTrainOpInvoke/autograd/optimizer and its loss trajectory must
match the identical training loop run in Python."""
import os
import struct
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu import optimizer as opt_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")

N, D, H, C = 64, 16, 16, 4
EPOCHS = 8
LR = 0.5

CPP_MAIN = r"""
#include <cstdio>
#include <cstdlib>
#include <fstream>
#include <vector>
#include "mxnet_tpu/cpp/train.hpp"

namespace mxcpp = mxnet_tpu::cpp;

static std::vector<float> ReadFloats(std::ifstream& f, size_t n) {
  std::vector<float> v(n);
  f.read(reinterpret_cast<char*>(v.data()), n * sizeof(float));
  return v;
}

int main(int argc, char** argv) {
  const int N = 64, D = 16, H = 16, C = 4, EPOCHS = 8;
  std::ifstream f(argv[1], std::ios::binary);
  auto X = ReadFloats(f, N * D);
  auto Y = ReadFloats(f, N);
  auto W1 = ReadFloats(f, H * D);
  auto B1 = ReadFloats(f, H);
  auto W2 = ReadFloats(f, C * H);
  auto B2 = ReadFloats(f, C);

  mxcpp::NDArray x({N, D}, X), y({N}, Y);
  mxcpp::NDArray w1({H, D}, W1), b1({H}, B1);
  mxcpp::NDArray w2({C, H}, W2), b2({C}, B2);
  w1.AttachGrad();
  b1.AttachGrad();
  w2.AttachGrad();
  b2.AttachGrad();

  mxcpp::Optimizer sgd("sgd", "{\"learning_rate\": 0.5}");

  for (int e = 0; e < EPOCHS; ++e) {
    mxcpp::Autograd::RecordStart();
    auto h = mxcpp::Operator("FullyConnected")
                 .SetAttr("num_hidden", H)
                 .Invoke({x, w1, b1});
    auto a = mxcpp::Operator("Activation")
                 .SetAttr("act_type", "relu")
                 .Invoke({h});
    auto o = mxcpp::Operator("FullyConnected")
                 .SetAttr("num_hidden", C)
                 .Invoke({a, w2, b2});
    auto lp = mxcpp::Operator("log_softmax").Invoke({o});
    auto picked = mxcpp::Operator("pick").Invoke({lp, y});
    auto mean = mxcpp::Operator("mean").Invoke({picked});
    auto loss = mxcpp::Operator("negative").Invoke({mean});
    mxcpp::Autograd::RecordStop();
    loss.Backward();
    printf("loss %.6f\n", loss.Scalar());
    mxcpp::NDArray* params[4] = {&w1, &b1, &w2, &b2};
    for (int i = 0; i < 4; ++i) {
      auto g = params[i]->Grad();
      sgd.Update(i, params[i], g);
      g.Free();
    }
    for (mxcpp::NDArray* t : {&h, &a, &o, &lp, &picked, &mean, &loss}) {
      t->Free();
    }
  }
  return 0;
}
"""


def _make_data():
    rng = np.random.RandomState(42)
    X = rng.randn(N, D).astype("float32")
    wt = rng.randn(D, C).astype("float32")
    Y = (X @ wt).argmax(axis=1).astype("float32")
    W1 = (rng.randn(H, D) * 0.3).astype("float32")
    B1 = np.zeros(H, "float32")
    W2 = (rng.randn(C, H) * 0.3).astype("float32")
    B2 = np.zeros(C, "float32")
    return X, Y, W1, B1, W2, B2


def _python_trajectory():
    X, Y, W1, B1, W2, B2 = _make_data()
    x, y = nd.array(X), nd.array(Y)
    params = [nd.array(a) for a in (W1, B1, W2, B2)]
    for p in params:
        p.attach_grad()
    updater = opt_mod.get_updater(opt_mod.create("sgd",
                                                 learning_rate=LR))
    losses = []
    for _ in range(EPOCHS):
        with autograd.record():
            h = nd.FullyConnected(x, params[0], params[1], num_hidden=H)
            a = nd.Activation(h, act_type="relu")
            o = nd.FullyConnected(a, params[2], params[3], num_hidden=C)
            loss = nd.negative(nd.mean(nd.pick(nd.log_softmax(o), y)))
        loss.backward()
        losses.append(float(loss.asnumpy()))
        for i, p in enumerate(params):
            updater(i, p.grad, p)
    return losses


@pytest.mark.slow
def test_cpp_training_matches_python(tmp_path):
    r = subprocess.run(["make", "-C", NATIVE, "train"],
                       capture_output=True, text=True, timeout=300)
    lib = os.path.join(NATIVE, "lib", "libmxnet_tpu_train.so")
    if r.returncode != 0 or not os.path.exists(lib):
        pytest.skip("train library build failed: %s" % r.stderr[-500:])

    data_file = tmp_path / "train_data.bin"
    blobs = _make_data()
    with open(data_file, "wb") as f:
        for b in blobs:
            f.write(np.ascontiguousarray(b, "<f4").tobytes())

    src = tmp_path / "train_demo.cc"
    src.write_text(CPP_MAIN)
    binary = str(tmp_path / "train_demo")
    inc = subprocess.run(["python3-config", "--includes"],
                         capture_output=True, text=True).stdout.split()
    r = subprocess.run(
        ["g++", "-std=c++14", str(src), "-o", binary,
         "-I", os.path.join(NATIVE, "include"),
         "-L", os.path.join(NATIVE, "lib"), "-lmxnet_tpu_train",
         "-Wl,-rpath," + os.path.join(NATIVE, "lib")] + inc,
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.environ.get("PYTHONPATH", "") + ":" + REPO)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    run = subprocess.run([binary, str(data_file)], capture_output=True,
                         text=True, timeout=300, env=env)
    assert run.returncode == 0, run.stdout + run.stderr
    cpp_losses = [float(l.split()[1]) for l in
                  run.stdout.strip().splitlines() if l.startswith("loss")]
    assert len(cpp_losses) == EPOCHS, run.stdout

    py_losses = _python_trajectory()
    np.testing.assert_allclose(cpp_losses, py_losses, rtol=1e-5,
                               atol=1e-6)
    # and it actually learns
    assert cpp_losses[-1] < cpp_losses[0] * 0.7
