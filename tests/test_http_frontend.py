"""HTTP/SSE front door (round 20).

Fast tier (no cluster, no compile): the pure wire-format helpers
(request-head parser, SSE framing, chunked transfer encoding), the
edge admission pieces (token bucket, API key table), the
``retry_after_s`` watchdog clamp, and the LIVE server driven over a
real loopback socket against a scripted FAKE cluster — auth/quota/
body-size rejection paths, SSE frame exactness, and client-disconnect
→ ``cancel(rid)`` propagation, all without building an engine.

Slow tier, group n: the same server over real clusters on the tiny
GPT — stream bit-identity vs the ``generate`` oracle on both
endpoints' modes, client disconnect mid-decode freeing the request's
pages while a concurrent request is still generating (the round-20
acceptance criterion, both cluster flavors), the disagg gen-fenced
``cancel`` wire kind (late/duplicate cancel is a no-op), the
mass-disconnect leak reconciliation, and the ``http_bench`` load-proof
smoke."""
import itertools
import json
import os
import socket
import threading
import time
from collections import deque

import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401  (conftest device setup)

from mxnet_tpu.serving.http_frontend import (ApiKeyTable, HttpFrontend,
                                             TokenBucket, chunk,
                                             parse_request_head,
                                             sse_event)


# ---------------------------------------------------------------------------
# raw-socket client helpers (blocking: tests want determinism, not
# throughput)
# ---------------------------------------------------------------------------

def _request_bytes(path="/v1/generate", method="POST", body=b"",
                   key=None, extra=()):
    head = ["%s %s HTTP/1.1" % (method, path), "Host: test"]
    if key is not None:
        head.append("Authorization: Bearer %s" % key)
    if method == "POST":
        head.append("Content-Length: %d" % len(body))
    head.extend(extra)
    return ("\r\n".join(head) + "\r\n\r\n").encode() + body


def _recv_head(sock):
    buf = b""
    while b"\r\n\r\n" not in buf:
        data = sock.recv(65536)
        if not data:
            raise AssertionError("EOF before response head: %r" % buf)
        buf += data
    head, rest = buf.split(b"\r\n\r\n", 1)
    status = int(head.split(b" ", 2)[1])
    headers = {}
    for ln in head.split(b"\r\n")[1:]:
        k, v = ln.decode("latin-1").split(":", 1)
        headers[k.strip().lower()] = v.strip()
    return status, headers, rest


def _read_n(sock, rest, n):
    while len(rest) < n:
        data = sock.recv(65536)
        if not data:
            raise AssertionError("EOF mid-body")
        rest += data
    return rest[:n], rest[n:]


def _read_sse(sock, rest):
    """Read a chunked SSE body to the terminal chunk; returns the
    ordered (event, payload) list."""
    events = []
    buf = rest
    while True:
        while b"\r\n" not in buf:
            data = sock.recv(65536)
            if not data:
                return events          # peer closed (error paths)
            buf += data
        nl = buf.find(b"\r\n")
        n = int(buf[:nl], 16)
        body, buf = _read_n(sock, buf[nl + 2:], n + 2)
        if n == 0:
            return events
        for block in body[:-2].split(b"\n\n"):
            if not block.strip():
                continue
            ev = data_ = None
            for ln in block.split(b"\n"):
                if ln.startswith(b"event: "):
                    ev = ln[7:].decode()
                elif ln.startswith(b"data: "):
                    data_ = json.loads(ln[6:])
            events.append((ev, data_))


def _generate_body(prompt, n, stream=True, **kw):
    obj = {"prompt": [int(x) for x in prompt],
           "max_new_tokens": int(n), "stream": stream}
    obj.update(kw)
    return json.dumps(obj).encode()


def _connect(fe):
    s = socket.create_connection((fe.host, fe.port), timeout=60)
    s.settimeout(60)
    return s


def _sse_tokens(events):
    return [d["t"] for ev, d in events if ev == "token"]


# ---------------------------------------------------------------------------
# fast tier: pure wire-format units
# ---------------------------------------------------------------------------

def test_parse_request_head():
    m, p, h = parse_request_head(
        b"POST /v1/generate HTTP/1.1\r\nHost: x\r\n"
        b"Content-Length: 3\r\nX-Api-Key:  k1 \r\n\r\n")
    assert (m, p) == ("POST", "/v1/generate")
    assert h["content-length"] == "3"
    assert h["x-api-key"] == "k1"          # trimmed, lower-cased name
    # last-wins duplicate headers
    _, _, h = parse_request_head(
        b"GET / HTTP/1.1\r\nA: 1\r\nA: 2\r\n\r\n")
    assert h["a"] == "2"


@pytest.mark.parametrize("head", [
    b"GET /\r\n\r\n",                      # no HTTP version
    b"GET / HTTP/2\r\n\r\n",               # not HTTP/1.x
    b"GET  /  HTTP/1.1\r\n\r\n",           # extra spaces
    b"GET x HTTP/1.1\r\n\r\n",             # path not absolute
    b"GET / HTTP/1.1\r\nbad line\r\n\r\n"  # colon-free header
])
def test_parse_request_head_malformed(head):
    with pytest.raises(ValueError):
        parse_request_head(head)


def test_sse_event_and_chunk_framing():
    ev = sse_event("token", {"i": 0, "t": 7})
    assert ev == b'event: token\ndata: {"i":0,"t":7}\n\n'
    ck = chunk(ev)
    assert ck == (b"%x\r\n" % len(ev)) + ev + b"\r\n"
    assert chunk(b"") == b"0\r\n\r\n"      # terminal chunk


def test_token_bucket():
    # unlimited: always ok
    tb = TokenBucket(None, 1)
    assert all(tb.take()[0] for _ in range(100))
    # hard burst budget (rate=0): exactly `burst` takes, then never
    tb = TokenBucket(0, 3)
    got = [tb.take()[0] for _ in range(10)]
    assert got == [True] * 3 + [False] * 7
    ok, retry = tb.take()
    assert not ok and retry is None        # never refills
    # refilling bucket with an injected clock: deterministic
    tb = TokenBucket(2.0, 2)               # 2 tokens/s, burst 2
    t0 = tb.t
    assert tb.take(t0)[0] and tb.take(t0)[0]
    ok, retry = tb.take(t0)
    assert not ok and retry == pytest.approx(0.5)
    ok, _ = tb.take(t0 + 0.5)              # one token refilled
    assert ok
    ok, _ = tb.take(t0 + 10.0)             # refill caps at burst
    assert ok
    assert tb.tokens == pytest.approx(1.0)


def test_api_key_table_load_shapes(tmp_path):
    spec = {"sk-a": {"tenant": "a", "rate": 2.5,
                     "max_in_flight": 4},
            "sk-b": {}}
    for src in (spec, json.dumps(spec)):
        kt = ApiKeyTable.load(src)
        a = kt.lookup("sk-a")
        assert a.name == "a" and a.max_in_flight == 4
        assert a.bucket.rate == 2.5 and a.bucket.burst == 3
        b = kt.lookup("sk-b")
        assert b.name == "sk-b"            # display name defaults
        assert b.bucket.rate is None and b.max_in_flight is None
        assert kt.lookup("sk-zzz") is None
        assert kt.lookup(None) is None
    f = tmp_path / "keys.json"
    f.write_text(json.dumps(spec))
    assert ApiKeyTable.load(str(f)).lookup("sk-a").name == "a"
    # idempotent: load() of a table is the table
    kt = ApiKeyTable.load(spec)
    assert ApiKeyTable.load(kt) is kt


def test_retry_after_clamped_to_watchdog():
    """The round-20 small fix: the completion-rate hint is bounded
    ABOVE by the watchdog, so a stalled or barely-completing cluster
    can never advertise a multi-hour Retry-After."""
    from mxnet_tpu.serving.cluster import ServingCluster
    cl = object.__new__(ServingCluster)    # the method's state only
    cl.watchdog_s = 30.0
    cl.max_queue = 4
    cl._obs = None
    now = time.perf_counter()
    # one completion interval over ~10 s => rate ~0.1/s
    cl._completions = deque([now - 10.0, now - 1e-4])
    # small excess: unclamped arithmetic (2 excess / 0.1 per s ~ 20 s)
    hint = cl._retry_after_locked(waiting=cl.max_queue + 1)
    assert 10.0 < hint < 30.0
    # huge excess: would be ~10^6 s — must clamp to the watchdog
    assert cl._retry_after_locked(waiting=10 ** 5) == 30.0
    # no completions observed: the watchdog/4 floor (already bounded)
    cl._completions = deque()
    assert cl._retry_after_locked(waiting=10 ** 5) == \
        pytest.approx(7.5)


# ---------------------------------------------------------------------------
# fast tier: the live server over a scripted fake cluster
# ---------------------------------------------------------------------------

class _FakeCluster:
    """Duck-typed stand-in for ServingCluster: scripted token streams,
    recorded cancels — the edge and framing paths without an engine."""

    def __init__(self, script=(5, 6, 7), hold=False):
        from mxnet_tpu.obs import MetricsRegistry
        self.registry = MetricsRegistry({"cluster": "fake"})
        self.script = list(script)
        self.hold = threading.Event()      # set => block before done
        if hold:
            self.hold.clear()
        else:
            self.hold.set()
        self.cancelled = []
        self.submitted = []
        self._seq = itertools.count(100)
        self._lock = threading.Lock()
        self._cancel_evt = {}              # rid -> Event

    def submit(self, prompt, max_new_tokens, eos_id=None, ttl_s=None):
        rid = next(self._seq)
        self.submitted.append((rid, np.asarray(prompt),
                               max_new_tokens))
        self._cancel_evt[rid] = threading.Event()
        return rid

    def attach_stream(self, rid, cb):
        prompt = next(p for r, p, _ in self.submitted if r == rid)

        def run():
            for t in self.script:
                cb(("tokens", [t]))
                time.sleep(0.005)
            while not self.hold.wait(0.05):
                if self._cancel_evt[rid].is_set():
                    return                 # cancelled while held
            out = np.concatenate([prompt.astype(np.int64),
                                  np.asarray(self.script)])
            cb(("done", out))

        threading.Thread(target=run, daemon=True).start()

    def cancel(self, rid):
        self.cancelled.append(rid)
        self._cancel_evt[rid].set()
        return True

    def health(self):
        return [{"replica": 0, "alive": True}]


def test_http_edge_rejections_fast():
    """401/429/413/400/404/405/411 — each refused at the edge,
    BEFORE submit(), with X-Request-Id on every response and the
    rejection counters reconciling exactly."""
    fake = _FakeCluster()
    keys = {"sk-good": {"tenant": "t", "rate": 0, "burst": 2}}
    fe = HttpFrontend(fake, keys=keys, max_body=256).start()
    try:
        def roundtrip(raw):
            s = _connect(fe)
            try:
                s.sendall(raw)
                return _recv_head(s)
            finally:
                s.close()

        body = _generate_body([1, 2], 3)
        # no key / unknown key -> 401
        st, h, _ = roundtrip(_request_bytes(body=body))
        assert st == 401 and h["x-request-id"]
        st, _, _ = roundtrip(_request_bytes(body=body, key="sk-bad"))
        assert st == 401
        # burst budget 2: two accepted, third 429 with Retry-After
        for _ in range(2):
            st, _, _ = roundtrip(_request_bytes(body=body,
                                                key="sk-good"))
            assert st == 200
        st, h, _ = roundtrip(_request_bytes(body=body, key="sk-good"))
        assert st == 429 and "retry-after" in h
        # oversized body -> 413 (and the submit never happened)
        st, _, _ = roundtrip(_request_bytes(body=b"x" * 512,
                                            key="sk-good"))
        assert st == 413
        # undecodable body -> 400
        st, _, _ = roundtrip(_request_bytes(body=b"not json",
                                            key="sk-good"))
        assert st == 400
        # unknown path -> 404; bad method -> 405; no length -> 411
        st, _, _ = roundtrip(_request_bytes(path="/v2/zzz", body=body,
                                            key="sk-good"))
        assert st == 404
        st, _, _ = roundtrip(b"PUT /v1/generate HTTP/1.1\r\n"
                             b"Host: x\r\nContent-Length: 0\r\n\r\n")
        assert st == 405
        st, _, _ = roundtrip(
            b"POST /v1/generate HTTP/1.1\r\nHost: x\r\n"
            b"Authorization: Bearer sk-good\r\n\r\n")
        assert st == 411
        # the two 200s are the ONLY submits that reached the cluster
        assert len(fake.submitted) == 2
        snap = fake.registry.snapshot()["counters"]
        assert snap["http_rejected_auth_total"] == 2
        assert snap["http_rejected_quota_total"] == 1
        assert snap["http_rejected_body_total"] == 1
    finally:
        fe.close()


def test_http_sse_framing_and_json_mode_fast():
    """The SSE stream is exact: ordered token events with running
    indices, one done event carrying the count, clean terminal chunk.
    JSON mode returns the generated tokens on a keep-alive
    connection (two requests ride one socket)."""
    fake = _FakeCluster(script=[5, 6, 7])
    fe = HttpFrontend(fake, keys=None).start()
    try:
        s = _connect(fe)
        s.sendall(_request_bytes(body=_generate_body([9, 8], 3)))
        st, h, rest = _recv_head(s)
        assert st == 200
        assert h["content-type"] == "text/event-stream"
        assert h["transfer-encoding"] == "chunked"
        events = _read_sse(s, rest)
        s.close()
        assert _sse_tokens(events) == [5, 6, 7]
        assert [d["i"] for ev, d in events if ev == "token"] \
            == [0, 1, 2]
        assert events[-1][0] == "done" and events[-1][1]["n"] == 3
        # JSON mode, keep-alive: two requests on one connection
        s = _connect(fe)
        for _ in range(2):
            s.sendall(_request_bytes(
                body=_generate_body([9, 8], 3, stream=False)))
            st, h, rest = _recv_head(s)
            assert st == 200
            clen = int(h["content-length"])
            body, _ = _read_n(s, rest, clen)
            assert json.loads(body)["tokens"] == [5, 6, 7]
        s.close()
        snap = fake.registry.snapshot()["counters"]
        assert snap["http_streams_total"] == 1
        assert snap["http_requests_total"] == 3
    finally:
        fe.close()


def test_http_disconnect_propagates_cancel_fast():
    """Client disconnect mid-stream reaches ``cluster.cancel(rid)``:
    the scripted stream never completes (the fake holds the done
    event), the client reads one token and slams the socket."""
    fake = _FakeCluster(script=[4], hold=True)
    fe = HttpFrontend(fake, keys=None).start()
    try:
        s = _connect(fe)
        s.sendall(_request_bytes(body=_generate_body([1], 8)))
        st, _, rest = _recv_head(s)
        assert st == 200
        while b"event: token" not in rest:
            rest += s.recv(4096)
        # RST, not FIN: the rudest client disconnect
        s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                     b"\x01\x00\x00\x00\x00\x00\x00\x00")
        s.close()
        deadline = time.perf_counter() + 10
        while time.perf_counter() < deadline and not fake.cancelled:
            time.sleep(0.01)
        assert fake.cancelled, "disconnect never reached cancel()"
        snap = fake.registry.snapshot()["counters"]
        assert snap["http_client_disconnects_total"] == 1
    finally:
        fake.hold.set()
        fe.close()


def test_json_mode_disconnect_propagates_cancel_fast():
    """JSON mode watches the read side too: a client that drops the
    connection while its non-streamed request decodes reaches
    ``cluster.cancel(rid)`` exactly like an SSE disconnect — the
    engine must not decode to completion for nobody."""
    fake = _FakeCluster(script=[4], hold=True)
    fe = HttpFrontend(fake, keys=None).start()
    try:
        s = _connect(fe)
        s.sendall(_request_bytes(body=_generate_body([1], 8,
                                                     stream=False)))
        deadline = time.perf_counter() + 10
        while time.perf_counter() < deadline and not fake.submitted:
            time.sleep(0.01)
        assert fake.submitted
        s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                     b"\x01\x00\x00\x00\x00\x00\x00\x00")
        s.close()
        deadline = time.perf_counter() + 10
        while time.perf_counter() < deadline and not fake.cancelled:
            time.sleep(0.01)
        assert fake.cancelled, "JSON-mode disconnect never cancelled"
        snap = fake.registry.snapshot()["counters"]
        assert snap["http_client_disconnects_total"] == 1
    finally:
        fake.hold.set()
        fe.close()


def test_http_max_in_flight_quota_fast():
    """max_in_flight bounds CONCURRENT admitted requests per tenant:
    a held stream occupies the slot, the next request 429s, and the
    slot frees on completion."""
    fake = _FakeCluster(script=[4], hold=True)
    fe = HttpFrontend(fake,
                      keys={"sk-t": {"max_in_flight": 1}}).start()
    try:
        s1 = _connect(fe)
        s1.sendall(_request_bytes(body=_generate_body([1], 4),
                                  key="sk-t"))
        st, _, rest = _recv_head(s1)
        assert st == 200
        while b"event: token" not in rest:
            rest += s1.recv(4096)
        s2 = _connect(fe)
        s2.sendall(_request_bytes(body=_generate_body([1], 4),
                                  key="sk-t"))
        st, _, _ = _recv_head(s2)
        assert st == 429
        s2.close()
        fake.hold.set()                    # finish the held stream
        _read_sse(s1, rest)
        s1.close()
        deadline = time.perf_counter() + 10
        while time.perf_counter() < deadline:
            if fe.keys.lookup("sk-t").in_flight == 0:
                break
            time.sleep(0.01)
        s3 = _connect(fe)
        s3.sendall(_request_bytes(body=_generate_body([1], 4),
                                  key="sk-t"))
        st, _, rest = _recv_head(s3)
        assert st == 200
        _read_sse(s3, rest)
        s3.close()
    finally:
        fake.hold.set()
        fe.close()


def test_healthz_and_metrics_fast():
    fake = _FakeCluster()
    fe = HttpFrontend(fake, keys={"sk": {}}).start()
    try:
        s = _connect(fe)
        s.sendall(_request_bytes(path="/healthz", method="GET"))
        st, h, rest = _recv_head(s)
        assert st == 200
        body, _ = _read_n(s, rest, int(h["content-length"]))
        obj = json.loads(body)
        assert obj["ok"] and obj["tenants"][0]["tenant"] == "sk"
        # keep-alive: /metrics rides the same socket
        s.sendall(_request_bytes(path="/metrics", method="GET"))
        st, h, rest = _recv_head(s)
        assert st == 200
        assert h["content-type"].startswith("text/plain")
        s.close()
    finally:
        fe.close()


def test_debug_statusz_and_trace_fast():
    """Round-23 ops surface at the edge: ``GET /debug/statusz`` and
    ``GET /debug/trace/<rid>`` relay the cluster's snapshots with the
    response's own X-Request-Id stamped in, 404 an unknown rid (the
    cluster's KeyError), 400 a non-integer rid, 405 non-GET, and 404
    when the attached cluster has no debug surface at all."""
    fake = _FakeCluster()
    fake.debug_status = lambda: {
        "kind": "fake", "closed": False, "workers": [],
        "in_flight": [], "slo": {"windows": []},
        "flight": {"path": None, "recovered": []}}

    def request_trace(rid):
        if rid != 100:
            raise KeyError(rid)
        return {"rid": rid, "router": {"state": "running"},
                "spans": [{"name": "prefill", "worker": "w0"}]}
    fake.request_trace = request_trace
    fe = HttpFrontend(fake, keys={"sk": {}}).start()
    try:
        s = _connect(fe)
        s.sendall(_request_bytes(path="/debug/statusz", method="GET"))
        st, h, rest = _recv_head(s)
        assert st == 200
        body, rest = _read_n(s, rest, int(h["content-length"]))
        obj = json.loads(body)
        assert obj["kind"] == "fake" and obj["workers"] == []
        assert obj["request_id"] == h["x-request-id"]
        # keep-alive: the trace surface rides the same socket
        s.sendall(_request_bytes(path="/debug/trace/100",
                                 method="GET"))
        st, h, rest = _recv_head(s)
        assert st == 200
        body, rest = _read_n(s, rest, int(h["content-length"]))
        obj = json.loads(body)
        assert obj["rid"] == 100
        assert obj["spans"][0]["worker"] == "w0"
        assert obj["request_id"] == h["x-request-id"]
        s.close()

        def one(raw):
            c = _connect(fe)
            try:
                c.sendall(raw)
                return _recv_head(c)[0]
            finally:
                c.close()

        assert one(_request_bytes(path="/debug/trace/999",
                                  method="GET")) == 404
        assert one(_request_bytes(path="/debug/trace/xyz",
                                  method="GET")) == 400
        assert one(_request_bytes(path="/debug/statusz",
                                  body=b"{}")) == 405
    finally:
        fe.close()
    # a cluster flavor without the surface: a clean 404, not a 500
    bare = _FakeCluster()
    fe = HttpFrontend(bare, keys={"sk": {}}).start()
    try:
        s = _connect(fe)
        s.sendall(_request_bytes(path="/debug/statusz", method="GET"))
        assert _recv_head(s)[0] == 404
        s.close()
    finally:
        fe.close()


def test_oversized_head_answered_not_dropped():
    """A request head past the 256 KiB stream limit gets a 400, not a
    silent connection drop (every malformed input answers with a
    status code)."""
    fake = _FakeCluster()
    fe = HttpFrontend(fake, keys=None).start()
    try:
        s = _connect(fe)
        s.sendall(b"POST /v1/generate HTTP/1.1\r\n"
                  b"X-Junk: " + b"j" * 300 * 1024 + b"\r\n\r\n")
        st, h, _ = _recv_head(s)
        assert st == 400
        s.close()
    finally:
        fe.close()


def test_tenant_accounting_partitions_traffic():
    """accepted counts edge-ADMITTED requests only: a quota 429 or an
    auth miss is rejected, a cluster-side failure after admission
    still counts accepted — accepted + rejected partitions the
    tenant's well-formed traffic."""
    fake = _FakeCluster()
    keys = {"sk-t": {"tenant": "t", "rate": 0, "burst": 2}}
    fe = HttpFrontend(fake, keys=keys).start()
    try:
        body = _generate_body([1, 2], 2, stream=False)
        for _ in range(2):
            s = _connect(fe)
            s.sendall(_request_bytes(body=body, key="sk-t"))
            st, h, rest = _recv_head(s)
            assert st == 200
            _read_n(s, rest, int(h["content-length"]))
            s.close()
        s = _connect(fe)
        s.sendall(_request_bytes(body=body, key="sk-t"))
        assert _recv_head(s)[0] == 429
        s.close()
        snap = fe.keys.snapshot()[0]
        assert snap["accepted"] == 2 and snap["rejected"] == 1
        assert snap["in_flight"] == 0
    finally:
        fe.close()


def test_env_knob_validation():
    from mxnet_tpu.serving.http_frontend import _env_int
    os.environ["MXNET_SERVE_HTTP_MAX_BODY"] = "nope"
    try:
        with pytest.raises(ValueError):
            _env_int("MXNET_SERVE_HTTP_MAX_BODY", 1)
    finally:
        del os.environ["MXNET_SERVE_HTTP_MAX_BODY"]


def test_clusters_expose_registry():
    """``HttpFrontend.__init__`` reads ``cluster.registry``
    unconditionally — BOTH cluster flavors must expose it (round 24:
    the in-proc property had been dropped in a refactor, so the front
    door crashed at construction over a real ``ServingCluster``)."""
    import inspect
    from mxnet_tpu.serving import DisaggServingCluster, ServingCluster
    for cls in (ServingCluster, DisaggServingCluster):
        assert isinstance(
            inspect.getattr_static(cls, "registry", None), property), cls


# ---------------------------------------------------------------------------
# slow tier (group n): real clusters over real sockets
# ---------------------------------------------------------------------------

def _cfg(**kw):
    from mxnet_tpu.models import gpt
    base = dict(use_flash=False, remat=False, dropout=0.0,
                dtype="float32", vocab_size=128, max_len=64)
    base.update(kw)
    return gpt.gpt_tiny(**base)


def _ref(params, cfg, prompt, n):
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt
    return np.asarray(
        gpt.generate(params, cfg, jnp.asarray(prompt)[None], n))[0]


def _setup(seed=3):
    import jax
    from mxnet_tpu.models import transformer as T
    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(seed), cfg)
    return params, cfg


def _assert_no_leaks(cl):
    for rep in cl.replicas:
        if rep.engine is None or rep.dead:
            continue
        eng = rep.engine
        refs = 0 if eng.prefix is None else eng.prefix.refs_total
        cached = 0 if eng.prefix is None else eng.prefix.cached_pages
        assert refs == 0, "replica %d leaks %d refs" % (rep.idx, refs)
        assert eng.cache.pages_in_use == cached, \
            "replica %d leaks pages (%d in use, %d cache-owned)" % (
                rep.idx, eng.cache.pages_in_use, cached)


def _abort(sock):
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                    b"\x01\x00\x00\x00\x00\x00\x00\x00")
    sock.close()


@pytest.mark.slow
def test_stream_bit_identity_both_modes():
    """Every SSE stream and every JSON-mode response carries exactly
    the ``generate`` oracle's tokens, over real loopback sockets,
    across mixed lengths on a 2-replica cluster."""
    from mxnet_tpu.serving import HttpFrontend, ServingCluster
    params, cfg = _setup()
    rng = np.random.RandomState(7)
    cl = ServingCluster(params, cfg, replicas=2, num_slots=2,
                        page_size=4, prefill_chunk=6, metrics=True)
    fe = None
    try:
        fe = HttpFrontend(cl, keys=None).start()
        wl = [(rng.randint(1, 90, 3 + (i % 6)).astype(np.int32),
               4 + (i % 5)) for i in range(10)]
        for i, (p, n) in enumerate(wl):
            stream = i % 3 != 2
            s = _connect(fe)
            s.sendall(_request_bytes(
                body=_generate_body(p, n, stream=stream)))
            st, h, rest = _recv_head(s)
            assert st == 200, (st, rest)
            o_gen = [int(t) for t in _ref(params, cfg, p, n)[len(p):]]
            if stream:
                events = _read_sse(s, rest)
                assert _sse_tokens(events) == o_gen, "stream %d" % i
                assert events[-1][0] == "done"
            else:
                body, _ = _read_n(s, rest, int(h["content-length"]))
                assert json.loads(body)["tokens"] == o_gen
            s.close()
        _assert_no_leaks(cl)
    finally:
        if fe is not None:
            fe.close()
        cl.close()


@pytest.mark.slow
def test_disconnect_frees_pages_while_peer_still_decoding():
    """The acceptance criterion: a client disconnect mid-decode frees
    the victim's pages BEFORE the engine finishes its generation —
    observed via the pool gauge while a CONCURRENT request on the
    same replica is still decoding (so the free provably did not wait
    for the engine to go idle)."""
    from mxnet_tpu.serving import HttpFrontend, ServingCluster
    params, cfg = _setup()
    cl = ServingCluster(params, cfg, replicas=1, num_slots=2,
                        page_size=4, prefill_chunk=8, metrics=True)
    fe = None
    try:
        fe = HttpFrontend(cl, keys=None).start()
        pa = np.arange(1, 7, dtype=np.int32)
        pb = np.arange(40, 48, dtype=np.int32)   # disjoint prefixes
        n = 48                                   # long decode
        s = _connect(fe)
        s.sendall(_request_bytes(body=_generate_body(pa, n)))
        st, _, rest = _recv_head(s)
        assert st == 200
        while b"event: token" not in rest:
            rest += s.recv(4096)                 # A is decoding
        rid_b = cl.submit(pb, n)
        eng = cl.replicas[0].engine
        deadline = time.perf_counter() + 60
        while time.perf_counter() < deadline:
            with cl._lock:
                if sum(r.state == "running"
                       for r in cl.requests.values()) == 2:
                    break
            time.sleep(0.005)
        in_use_both = eng.cache.pages_in_use
        _abort(s)                                # A's client vanishes
        # the victim's pages must return to the pool while B is
        # STILL decoding — poll for the drop and record B's state at
        # the moment it is observed
        freed_at_state = None
        while time.perf_counter() < deadline:
            in_use = eng.cache.pages_in_use
            with cl._lock:
                b_state = cl.requests[rid_b].state
            if in_use < in_use_both:
                freed_at_state = b_state
                break
            time.sleep(0.002)
        assert freed_at_state is not None, \
            "disconnected request's pages never freed"
        assert freed_at_state == "running", \
            "pages freed only after the engine drained (B was %r)" \
            % freed_at_state
        # the cancel is the counted outcome, and B is exact
        np.testing.assert_array_equal(cl.result(rid_b, timeout=300),
                                      _ref(params, cfg, pb, n))
        snap = cl.registry.snapshot()["counters"]
        assert snap["cluster_cancelled_total"] == 1
        assert snap["http_client_disconnects_total"] == 1
        _assert_no_leaks(cl)
    finally:
        if fe is not None:
            fe.close()
        cl.close()


@pytest.mark.slow
def test_disagg_disconnect_cancel_gen_fenced():
    """Disagg flavor: the disconnect rides the new gen-fenced
    ``cancel`` wire kind — worker pages/slots recycle without
    waiting for the generation, a late or duplicate cancel is a
    no-op (the fence), and the cluster serves bit-exact traffic
    afterwards."""
    from mxnet_tpu.serving import DisaggServingCluster, HttpFrontend
    params, cfg = _setup()
    rng = np.random.RandomState(11)
    cl = DisaggServingCluster(params, cfg, prefill=1, decode=1,
                              num_slots=2, page_size=4,
                              prefill_chunk=6, metrics=True,
                              watchdog_s=60.0)
    fe = None
    try:
        fe = HttpFrontend(cl, keys=None).start()
        p = rng.randint(1, 90, 6).astype(np.int32)
        s = _connect(fe)
        s.sendall(_request_bytes(body=_generate_body(p, 40)))
        st, _, rest = _recv_head(s)
        assert st == 200
        while b"event: token" not in rest:
            rest += s.recv(4096)
        with cl._lock:
            (rid,) = [r for r, cr in cl.requests.items()
                      if cr.state == "running"]
        _abort(s)
        deadline = time.perf_counter() + 60
        while time.perf_counter() < deadline:
            with cl._lock:
                if cl.requests[rid].state == "cancelled":
                    break
            time.sleep(0.01)
        with cl._lock:
            assert cl.requests[rid].state == "cancelled"
        # worker pages/slots recycled NOW (not at generation end):
        # poll the per-worker stats until every staged page and
        # active request is gone
        clean = None
        while time.perf_counter() < deadline:
            st_ = cl.cluster_stats()
            if all(not s_.get("active_requests")
                   and not s_.get("staged_rids")
                   and not s_.get("prefix_refs")
                   and s_.get("pages_in_use", 0)
                   == s_.get("prefix_cached_pages", 0)
                   for s_ in st_.values()):
                clean = st_
                break
            time.sleep(0.02)
        assert clean is not None, "worker pages never recycled: %r" \
            % (cl.cluster_stats(),)
        # duplicate cancel: terminal state => False, and the worker-
        # side fence makes the (already-sent) kind a no-op
        assert cl.cancel(rid) is False
        # a COMPLETED request's late cancel is the same no-op
        p2 = rng.randint(1, 90, 5).astype(np.int32)
        r2 = cl.submit(p2, 5)
        np.testing.assert_array_equal(cl.result(r2, timeout=300),
                                      _ref(params, cfg, p2, 5))
        assert cl.cancel(r2) is False
        # and the cluster still serves exactly after all of it
        p3 = rng.randint(1, 90, 7).astype(np.int32)
        r3 = cl.submit(p3, 6)
        np.testing.assert_array_equal(cl.result(r3, timeout=300),
                                      _ref(params, cfg, p3, 6))
        snap = cl.registry.snapshot()["counters"]
        assert snap["cluster_cancelled_total"] == 1
    finally:
        if fe is not None:
            fe.close()
        cl.close()


@pytest.mark.slow
def test_mass_disconnect_storm_reconciles():
    """The storm shape from the load proof, in-process scale: many
    concurrent SSE streams, half aborted mid-flight in one burst —
    every survivor bit-identical, every victim cancelled or
    completed (the inherent race), zero pages/refs leaked, and the
    disconnect/cancel counters reconcile exactly."""
    from mxnet_tpu.serving import HttpFrontend, ServingCluster
    params, cfg = _setup()
    rng = np.random.RandomState(13)
    cl = ServingCluster(params, cfg, replicas=2, num_slots=2,
                        page_size=4, prefill_chunk=6, metrics=True,
                        max_queue=10 ** 6)
    fe = None
    N = 16
    try:
        fe = HttpFrontend(cl, keys=None).start()
        wl = [(rng.randint(1, 90, 4 + (i % 4)).astype(np.int32), 24)
              for i in range(N)]
        socks, rests = [], []
        for p, n in wl:
            s = _connect(fe)
            s.sendall(_request_bytes(body=_generate_body(p, n)))
            socks.append(s)
            rests.append(b"")
        for i, s in enumerate(socks):
            st, _, rest = _recv_head(s)
            assert st == 200
            rests[i] = rest
        # the storm: every odd stream aborted in one burst
        victims = set(range(1, N, 2))
        for i in sorted(victims):
            _abort(socks[i])
        # survivors read to completion and must be oracle-exact
        for i, (p, n) in enumerate(wl):
            if i in victims:
                continue
            events = _read_sse(socks[i], rests[i])
            o_gen = [int(t) for t in
                     _ref(params, cfg, p, n)[len(p):]]
            assert _sse_tokens(events) == o_gen, "stream %d" % i
            socks[i].close()
        # drain: every request terminal, nothing leaked
        deadline = time.perf_counter() + 120
        while time.perf_counter() < deadline:
            with cl._lock:
                live = sum(r.state in ("queued", "running")
                           for r in cl.requests.values())
            if not live:
                break
            time.sleep(0.05)
        assert not live, "%d requests never reached terminal" % live
        with cl._lock:
            states = [r.state for r in cl.requests.values()]
        n_done = states.count("done")
        n_cancelled = states.count("cancelled")
        assert n_done + n_cancelled == N
        assert n_done >= N - len(victims)  # survivors all done
        snap = cl.registry.snapshot()["counters"]
        # every abort was detected; every CANCELLED request came from
        # one of those disconnects (a victim that finished before the
        # cancel landed is the allowed race)
        assert snap["http_client_disconnects_total"] \
            == len(victims)
        assert snap["cluster_cancelled_total"] == n_cancelled \
            <= len(victims)
        # every HTTP-consumed request is DELIVERED (the terminal
        # stream event is the delivery) so the request table stays
        # bounded under pure HTTP traffic — without this a
        # long-running front door grows memory with total traffic
        with cl._lock:
            assert all(r.delivered for r in cl.requests.values())
        _assert_no_leaks(cl)
    finally:
        if fe is not None:
            fe.close()
        cl.close()


@pytest.mark.slow
def test_http_bench_quick_smoke():
    """The load proof's hard-fail protocol at CI scale: tiny floors,
    but the same checks (peak concurrency, 429 closed form, stream
    identity, leak reconciliation) all enforced by run_load itself —
    a RuntimeError here IS the failure."""
    import benchmark.http_bench as HB
    import benchmark.serve_bench as SB
    import benchmark.traffic_trace as TT
    p = SB.PRESETS["quick"]
    params, cfg = SB._model(p)
    trace = TT.generate_trace(HB._load_spec(p, 0, 16.0, 1.0))
    row = HB.run_load(params, cfg, p, trace, replicas=2,
                      min_concurrent=4, capped_burst=2,
                      capped_every=6, json_every=9)
    assert row["edge_429"] == row["expected_429"]
    assert row["peak_concurrent"] >= 4
    assert row["seed"] == 0 and row["trace_sha"] == \
        TT.trace_hash(trace)
    assert row["oracle_identical"] >= 1
