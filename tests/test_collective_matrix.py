"""Pin the compiled GSPMD collective pattern per parallel config
(round-4 verdict item #2).

Loss-parity tests prove the math; they cannot catch a GSPMD regression
that keeps the numbers right while wrecking the communication pattern —
e.g. a plain-dp step that suddenly all-gathers, or a ring-attention
chain lowered to all-to-alls.  Real multi-chip hardware does not exist
in this environment, so the optimized-HLO collective inventory on the
8-device CPU mesh is the strongest multi-chip perf proxy available
(template: ``test_multichip_dryrun_no_involuntary_remat``).

Each config's train step is lowered at STEADY STATE (after one executed
step, because ``donate_argnums`` feeds the output shardings back in:
under ZeRO-1 the returned params are dp-sharded, so the steady-state
executable — the one every step after the first runs — is the one that
matters) and its collective instruction counts are checked against an
expected window; any collective KIND not in the config's expected set
failing to be zero fails the test.

Measured inventory (jax 0.9 XLA:CPU, 2026-07-31) recorded in
``docs/architecture.md`` "Collective matrix"; the windows below leave
slack for XLA-version drift while still catching pattern regressions.
"""
import os

import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.slow

COLLS = ("all-reduce", "all-gather", "reduce-scatter",
         "collective-permute", "all-to-all")

# config -> {collective: (min, max)}; unlisted collectives must be 0
EXPECTED = {
    "dp": {"all-reduce": (1, 3)},
    "dp+zero1": {
        # grads still reduced; params+moments live SHARDED between
        # steps (the ZeRO-1 memory saving) and are gathered at their
        # use sites — one all-gather per parameter tensor
        "all-reduce": (1, 3), "all-gather": (30, 90),
        "reduce-scatter": (0, 90),   # legal alternative lowering
    },
    "tp": {
        # megatron: activation psums every layer, fwd + bwd
        "all-reduce": (8, 40), "all-gather": (0, 10),
    },
    "sp-ring": {
        "all-reduce": (8, 48), "all-gather": (0, 30),
        # THE signature: ring attention's kv rotation must stay a
        # ppermute chain (sp=2, 2 layers, fwd + remat'd bwd + dq/dkv)
        "collective-permute": (4, 24),
    },
    "pp": {
        "all-reduce": (1, 10), "all-gather": (0, 6),
        # GPipe stage handoffs
        "collective-permute": (8, 28),
        # stacked per-stage params reshard inside the microbatch scan
        "all-to-all": (0, 64),
    },
    "ep": {
        # einsum dispatch/combine (parallel/moe.py design): GSPMD
        # reshards the expert-sharded einsums with a bounded number of
        # gathers — an explosion here means expert weights replicated
        "all-reduce": (1, 10), "all-gather": (0, 6),
    },
}

CONFIGS = {
    "dp": ({"dp": 8}, {}, False),
    "dp+zero1": ({"dp": 8}, {}, True),
    "tp": ({"dp": 4, "tp": 2}, {}, False),
    "sp-ring": ({"dp": 2, "sp": 2, "tp": 2},
                dict(seq_parallel="ring"), False),
    "pp": ({"pp": 2, "dp": 4}, dict(pp_microbatches=2), False),
    "ep": ({"dp": 4, "ep": 2}, dict(n_experts=4, moe_every=2), False),
}


def _inventory(text):
    return {c: text.count(c + "(") + text.count(c + "-start(")
            for c in COLLS}


def _steady_state_hlo(axes, extra, shard_opt):
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.models import transformer as T

    mesh = make_mesh(axes)
    cfg = T.bert_tiny(use_flash=False, remat=True, dropout=0.0, **extra)
    init_state, step = T.make_train_step(cfg, mesh=mesh,
                                         learning_rate=1e-4,
                                         shard_optimizer=shard_opt)
    state = init_state(jax.random.PRNGKey(0))
    B = max(2, mesh.shape.get("dp", 1) *
            (cfg.pp_microbatches if "pp" in axes else 1))
    L = 128
    tokens = jnp.zeros((B, L), dtype=jnp.int32)
    labels = jnp.where(jnp.arange(L)[None, :] % 7 == 0, tokens, -100)
    batch = {"tokens": tokens, "labels": labels,
             "mask": jnp.ones((B, L), dtype=bool)}
    state, _ = step(state, batch, jax.random.PRNGKey(1))
    jax.block_until_ready(state)
    compiled = step.lower(state, batch, jax.random.PRNGKey(1)).compile()
    return compiled, state


@pytest.mark.parametrize("name", list(CONFIGS))
def test_collective_inventory(name):
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    axes, extra, shard_opt = CONFIGS[name]
    compiled, state = _steady_state_hlo(axes, extra, shard_opt)
    inv = _inventory(compiled.as_text())
    expected = EXPECTED[name]
    for coll, n in inv.items():
        if coll in expected:
            lo, hi = expected[coll]
            assert lo <= n <= hi, (
                "%s: %s count %d outside [%d, %d] — the compiled "
                "collective pattern changed; inspect before updating "
                "the window (docs/architecture.md Collective matrix)"
                % (name, coll, n, lo, hi))
        else:
            assert n == 0, (
                "%s: unexpected collective %s x%d in optimized HLO"
                % (name, coll, n))

    if name == "dp+zero1":
        # the memory claim behind ZeRO-1: optimizer state (and, with
        # donation, params) must be stored sharded between steps, not
        # replicated-with-sharded-updates
        params, opt_state = state
        big = [l for l in jax.tree_util.tree_leaves(opt_state)
               if hasattr(l, "sharding") and l.size > 1000]
        assert big and all(not l.sharding.is_fully_replicated
                           for l in big), \
            "ZeRO-1 moment buffers are not sharded at rest"


def test_dp_gradient_reduce_is_combined():
    """The dp gradient reduction must stay ONE combined (tupled)
    all-reduce over the gradient tensors — per-tensor reduces would
    serialize ICI transfers on real hardware."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    axes, extra, shard_opt = CONFIGS["dp"]
    compiled, _ = _steady_state_hlo(axes, extra, shard_opt)
    text = compiled.as_text()
    # a combined all-reduce has a TUPLE result type listing every
    # gradient tensor: "(f32[...], f32[...], ...) all-reduce("
    big_tuple = [ln for ln in text.splitlines()
                 if " all-reduce(" in ln and ln.count("f32[") > 10]
    assert big_tuple, \
        "gradient all-reduce is no longer a combined tuple reduce"
