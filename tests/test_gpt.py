"""Decoder-only LM family: causal masking, next-token training,
KV-cache generation (models/gpt.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _cfg(**kw):
    from mxnet_tpu.models import gpt
    base = dict(use_flash=False, remat=False, dropout=0.0,
                dtype="float32")
    base.update(kw)
    return gpt.gpt_tiny(**base)


@pytest.mark.slow
def test_causal_mask_blocks_future():
    """Changing a future token must not change past logits."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt, transformer as T

    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    B, L = 2, 16
    tokens = jnp.arange(B * L, dtype=jnp.int32).reshape(B, L) % 100
    logits1 = gpt.forward(params, tokens, cfg)
    tokens2 = tokens.at[:, -1].set(999)
    logits2 = gpt.forward(params, tokens2, cfg)
    np.testing.assert_allclose(np.asarray(logits1[:, :-1]),
                               np.asarray(logits2[:, :-1]),
                               rtol=1e-5, atol=1e-6)
    # non-causal config DOES leak
    cfg_nc = _cfg(causal=False)
    l1 = T.forward(params, tokens, cfg_nc)
    l2 = T.forward(params, tokens2, cfg_nc)
    assert np.abs(np.asarray(l1[:, 0]) - np.asarray(l2[:, 0])).max() > 1e-6


@pytest.mark.slow
def test_lm_training_learns():
    """Next-token loss must fall on a deterministic sequence."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt

    cfg = _cfg()
    init_state, step = gpt.make_train_step(cfg, learning_rate=5e-3)
    state = init_state(jax.random.PRNGKey(0))
    B, L = 4, 32
    base = (jnp.arange(L, dtype=jnp.int32)[None] +
            jnp.arange(B, dtype=jnp.int32)[:, None]) % 50
    batch = {"tokens": base}
    losses = []
    for i in range(10):
        state, loss = step(state, batch, jax.random.PRNGKey(i))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.6, losses


@pytest.mark.slow
def test_generate_matches_full_forward():
    """Greedy KV-cache decoding must pick the same tokens as greedy
    decoding via the full (re-run) forward pass."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt, transformer as T

    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    B, P, N = 2, 5, 6
    prompt = (jnp.arange(B * P, dtype=jnp.int32).reshape(B, P) % 90) + 1

    out = gpt.generate(params, cfg, prompt, N)
    assert out.shape == (B, P + N)
    np.testing.assert_array_equal(np.asarray(out[:, :P]),
                                  np.asarray(prompt))

    # reference greedy loop with full forward each step
    seq = prompt
    for _ in range(N):
        logits = gpt.forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


@pytest.mark.slow
def test_generate_respects_max_len():
    import jax
    from mxnet_tpu.models import gpt, transformer as T
    cfg = _cfg(max_len=8)
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    import jax.numpy as jnp
    prompt = jnp.ones((1, 5), jnp.int32)
    with pytest.raises(ValueError):
        gpt.generate(params, cfg, prompt, 10)


@pytest.mark.slow
def test_gpt_train_step_sharded():
    """LM train step over a dp x tp mesh."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt
    from mxnet_tpu.parallel import make_mesh

    mesh = make_mesh({"dp": 4, "tp": 2})
    cfg = _cfg()
    init_state, step = gpt.make_train_step(cfg, mesh=mesh)
    state = init_state(jax.random.PRNGKey(0))
    tokens = jnp.arange(4 * 32, dtype=jnp.int32).reshape(4, 32) % 100
    state, loss = step(state, {"tokens": tokens}, jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))

@pytest.mark.slow
def test_int8_weight_only_decode_parity():
    """Weight-only int8 decode (round 4): teacher-forced logits must
    track fp within quantization tolerance, and greedy generation must
    agree with fp on nearly every step."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt, transformer as T

    cfg = _cfg(vocab_size=512, d_model=128, n_heads=4, n_layers=3,
               d_ff=256)
    params = T.init_params(jax.random.PRNGKey(7), cfg)
    qparams = gpt.quantize_decode_params(params)

    # structure: 2-D matmul weights became {"q" s8, "s" f32}
    assert qparams["tok_emb"]["q"].dtype == jnp.int8
    for l in qparams["layers"]:
        assert l["wq"]["q"].dtype == jnp.int8
        assert l["ln1"]["g"].dtype != jnp.int8      # norms stay float

    B, L = 2, 24
    tokens = ((jnp.arange(B * L, dtype=jnp.int32).reshape(B, L) * 7)
              % cfg.vocab_size)

    def teacher_forced(p):
        H, dh = cfg.n_heads, cfg.d_model // cfg.n_heads
        caches = [{"kv": jnp.zeros((B * H, L, 2 * dh), jnp.float32)}
                  for _ in range(cfg.n_layers)]
        outs = []
        for t in range(L):
            logits, caches = gpt._decode_one(p, cfg, tokens[:, t], t,
                                             caches)
            outs.append(logits)
        return jnp.stack(outs, axis=1)              # (B, L, V)

    lf = np.asarray(teacher_forced(params))
    lq = np.asarray(teacher_forced(qparams))

    # cosine similarity per position and top-1 agreement
    num = (lf * lq).sum(-1)
    den = np.linalg.norm(lf, axis=-1) * np.linalg.norm(lq, axis=-1)
    cos = num / (den + 1e-9)
    assert cos.min() > 0.99, "logit cosine dropped to %.4f" % cos.min()
    agree = (lf.argmax(-1) == lq.argmax(-1)).mean()
    assert agree >= 0.95, "top-1 agreement %.3f" % agree

    # end-to-end greedy generate with quantized params runs and mostly
    # matches fp greedy
    prompt = tokens[:, :4]
    of = np.asarray(gpt.generate(params, cfg, prompt, 8))
    oq = np.asarray(gpt.generate(qparams, cfg, prompt, 8))
    assert of.shape == oq.shape
    assert (of == oq).mean() >= 0.8, (of, oq)


@pytest.mark.slow
def test_int8_kv_cache_decode_parity():
    """Round-4: the int8 KV-cache path (generate(kv_int8=True)) must
    track fp decode — per-token s8 quantization with scales folded into
    the attention dots."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt, transformer as T

    cfg = _cfg(vocab_size=512, d_model=128, n_heads=4, n_layers=3,
               d_ff=256)
    params = T.init_params(jax.random.PRNGKey(11), cfg)
    prompt = ((jnp.arange(2 * 5, dtype=jnp.int32).reshape(2, 5) * 13)
              % cfg.vocab_size)
    of = np.asarray(gpt.generate(params, cfg, prompt, 12))
    okv = np.asarray(gpt.generate(params, cfg, prompt, 12,
                                  kv_int8=True))
    assert of.shape == okv.shape
    # greedy decode should agree on (nearly) every token at these
    # scales; a k/v scale-column swap or mis-fold collapses agreement
    assert (of == okv).mean() >= 0.9, (of, okv)
    # combined with weight-only int8 it still decodes sanely
    oq = np.asarray(gpt.generate(gpt.quantize_decode_params(params),
                                 cfg, prompt, 12, kv_int8=True))
    assert (of == oq).mean() >= 0.7, (of, oq)
