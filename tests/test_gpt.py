"""Decoder-only LM family: causal masking, next-token training,
KV-cache generation (models/gpt.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _cfg(**kw):
    from mxnet_tpu.models import gpt
    base = dict(use_flash=False, remat=False, dropout=0.0,
                dtype="float32")
    base.update(kw)
    return gpt.gpt_tiny(**base)


@pytest.mark.slow
def test_causal_mask_blocks_future():
    """Changing a future token must not change past logits."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt, transformer as T

    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    B, L = 2, 16
    tokens = jnp.arange(B * L, dtype=jnp.int32).reshape(B, L) % 100
    logits1 = gpt.forward(params, tokens, cfg)
    tokens2 = tokens.at[:, -1].set(999)
    logits2 = gpt.forward(params, tokens2, cfg)
    np.testing.assert_allclose(np.asarray(logits1[:, :-1]),
                               np.asarray(logits2[:, :-1]),
                               rtol=1e-5, atol=1e-6)
    # non-causal config DOES leak
    cfg_nc = _cfg(causal=False)
    l1 = T.forward(params, tokens, cfg_nc)
    l2 = T.forward(params, tokens2, cfg_nc)
    assert np.abs(np.asarray(l1[:, 0]) - np.asarray(l2[:, 0])).max() > 1e-6


@pytest.mark.slow
def test_lm_training_learns():
    """Next-token loss must fall on a deterministic sequence."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt

    cfg = _cfg()
    init_state, step = gpt.make_train_step(cfg, learning_rate=5e-3)
    state = init_state(jax.random.PRNGKey(0))
    B, L = 4, 32
    base = (jnp.arange(L, dtype=jnp.int32)[None] +
            jnp.arange(B, dtype=jnp.int32)[:, None]) % 50
    batch = {"tokens": base}
    losses = []
    for i in range(10):
        state, loss = step(state, batch, jax.random.PRNGKey(i))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.6, losses


@pytest.mark.slow
def test_generate_matches_full_forward():
    """Greedy KV-cache decoding must pick the same tokens as greedy
    decoding via the full (re-run) forward pass."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt, transformer as T

    cfg = _cfg()
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    B, P, N = 2, 5, 6
    prompt = (jnp.arange(B * P, dtype=jnp.int32).reshape(B, P) % 90) + 1

    out = gpt.generate(params, cfg, prompt, N)
    assert out.shape == (B, P + N)
    np.testing.assert_array_equal(np.asarray(out[:, :P]),
                                  np.asarray(prompt))

    # reference greedy loop with full forward each step
    seq = prompt
    for _ in range(N):
        logits = gpt.forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


@pytest.mark.slow
def test_generate_respects_max_len():
    import jax
    from mxnet_tpu.models import gpt, transformer as T
    cfg = _cfg(max_len=8)
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    import jax.numpy as jnp
    prompt = jnp.ones((1, 5), jnp.int32)
    with pytest.raises(ValueError):
        gpt.generate(params, cfg, prompt, 10)


@pytest.mark.slow
def test_gpt_train_step_sharded():
    """LM train step over a dp x tp mesh."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt
    from mxnet_tpu.parallel import make_mesh

    mesh = make_mesh({"dp": 4, "tp": 2})
    cfg = _cfg()
    init_state, step = gpt.make_train_step(cfg, mesh=mesh)
    state = init_state(jax.random.PRNGKey(0))
    tokens = jnp.arange(4 * 32, dtype=jnp.int32).reshape(4, 32) % 100
    state, loss = step(state, {"tokens": tokens}, jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))

@pytest.mark.slow
def test_int8_weight_only_decode_parity():
    """Weight-only int8 decode (round 4): teacher-forced logits must
    track fp within quantization tolerance, and greedy generation must
    agree with fp on nearly every step."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt, transformer as T

    cfg = _cfg(vocab_size=512, d_model=128, n_heads=4, n_layers=3,
               d_ff=256)
    params = T.init_params(jax.random.PRNGKey(7), cfg)
    qparams = gpt.quantize_decode_params(params)

    # structure: 2-D matmul weights became {"q" s8, "s" f32}
    assert qparams["tok_emb"]["q"].dtype == jnp.int8
    for l in qparams["layers"]:
        assert l["wq"]["q"].dtype == jnp.int8
        assert l["ln1"]["g"].dtype != jnp.int8      # norms stay float

    B, L = 2, 24
    tokens = ((jnp.arange(B * L, dtype=jnp.int32).reshape(B, L) * 7)
              % cfg.vocab_size)

    def teacher_forced(p):
        H, dh = cfg.n_heads, cfg.d_model // cfg.n_heads
        caches = [{"kv": jnp.zeros((B * H, L, 2 * dh), jnp.float32)}
                  for _ in range(cfg.n_layers)]
        outs = []
        for t in range(L):
            logits, caches = gpt._decode_one(p, cfg, tokens[:, t], t,
                                             caches)
            outs.append(logits)
        return jnp.stack(outs, axis=1)              # (B, L, V)

    lf = np.asarray(teacher_forced(params))
    lq = np.asarray(teacher_forced(qparams))

    # cosine similarity per position and top-1 agreement
    num = (lf * lq).sum(-1)
    den = np.linalg.norm(lf, axis=-1) * np.linalg.norm(lq, axis=-1)
    cos = num / (den + 1e-9)
    assert cos.min() > 0.99, "logit cosine dropped to %.4f" % cos.min()
    agree = (lf.argmax(-1) == lq.argmax(-1)).mean()
    assert agree >= 0.95, "top-1 agreement %.3f" % agree

    # end-to-end greedy generate with quantized params runs and mostly
    # matches fp greedy
    prompt = tokens[:, :4]
    of = np.asarray(gpt.generate(params, cfg, prompt, 8))
    oq = np.asarray(gpt.generate(qparams, cfg, prompt, 8))
    assert of.shape == oq.shape
    assert (of == oq).mean() >= 0.8, (of, oq)


def test_kv_quantize_accumulates_in_f32():
    """Round 13 (graphlint graph-dtype-drift fix): ``_kv_quantize``
    upcasts k/v ONCE at entry and computes scale + quantization grid
    in f32 — the stored scales are exactly ``max|x| / 127`` in f32,
    not a bf16-rounded value cosmetically upcast (the old late
    ``.astype(f32)`` on the stacked scales), and the int8 round-trip
    error stays within half a (correct) quantization step."""
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt

    rng = np.random.RandomState(0)
    k = jnp.asarray(rng.randn(6, 4, 16), jnp.bfloat16)
    v = jnp.asarray(rng.randn(6, 4, 16), jnp.bfloat16)
    kv, s = gpt._kv_quantize(k, v)
    assert kv.dtype == jnp.int8 and s.dtype == jnp.float32

    kf = np.asarray(k, np.float32)
    vf = np.asarray(v, np.float32)
    exp_sk = np.maximum(
        np.abs(kf).max(-1) / np.float32(127.0), np.float32(1e-8))
    exp_sv = np.maximum(
        np.abs(vf).max(-1) / np.float32(127.0), np.float32(1e-8))
    np.testing.assert_array_equal(np.asarray(s[..., 0]), exp_sk)
    np.testing.assert_array_equal(np.asarray(s[..., 1]), exp_sv)

    deq_k = np.asarray(kv[..., :16], np.float32) * exp_sk[..., None]
    deq_v = np.asarray(kv[..., 16:], np.float32) * exp_sv[..., None]
    assert np.abs(deq_k - kf).max() <= exp_sk.max() * 0.5 + 1e-7
    assert np.abs(deq_v - vf).max() <= exp_sv.max() * 0.5 + 1e-7


# ---------------------------------------------------------------------------
# speculative decode (fast tier: the distribution-exactness gates)
# ---------------------------------------------------------------------------

def test_spec_decode_greedy_exact_ngram():
    """Greedy speculative decode must be TOKEN-IDENTICAL to plain
    ``generate`` — the distribution-exactness gate for the accept rule
    (longest matching prefix + the target's own token at the first
    mismatch), for every K."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt, transformer as T

    cfg = _cfg(vocab_size=128, max_len=64)
    params = T.init_params(jax.random.PRNGKey(3), cfg)
    B, P, N = 2, 6, 12
    # repetitive prompt so the ngram drafter actually gets accepts on
    # one row while the other stays adversarial
    prompt = jnp.asarray([[7, 9, 7, 9, 7, 9],
                          [3, 11, 5, 2, 17, 23]], jnp.int32)
    ref = gpt.generate(params, cfg, prompt, N)
    for K in (1, 2, 4):
        out, st = gpt.generate_speculative(
            params, cfg, prompt, N, K=K, drafter="ngram",
            return_stats=True)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
        assert int(st["tokens"]) >= N
        assert int(st["iters"]) >= 1
        assert 0 <= int(st["accepted"]) <= int(st["drafted"])
        # every iteration commits at least one token
        assert int(st["iters"]) <= N


def test_spec_decode_greedy_exact_self_drafter():
    """Self-drafting (layer-slice draft model, optionally w8) must also
    be token-identical under greedy — acceptance only ever compares
    against the TARGET's argmax, so a bad draft costs speed, never
    correctness."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt, transformer as T

    cfg = _cfg(vocab_size=128, max_len=64)
    params = T.init_params(jax.random.PRNGKey(4), cfg)
    B, P, N = 2, 5, 10
    prompt = ((jnp.arange(B * P, dtype=jnp.int32).reshape(B, P) * 13)
              % 100) + 1
    ref = gpt.generate(params, cfg, prompt, N)

    dparams, dcfg = gpt.draft_slice_params(params, cfg, n_layers=1)
    out, st = gpt.generate_speculative(
        params, cfg, prompt, N, K=3, drafter="self",
        draft_params=dparams, draft_cfg=dcfg, return_stats=True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    # w8 draft model: still exact (quantization changes the PROPOSALS,
    # never the accepted distribution)
    qd = gpt.quantize_decode_params(dparams)
    out = gpt.generate_speculative(
        params, cfg, prompt, N, K=3, drafter="self",
        draft_params=qd, draft_cfg=dcfg)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_spec_decode_quantized_target_paths():
    """Speculative decode over the quantized decode-path options (w8
    weights, int8 KV cache) stays token-identical to plain generate
    with the SAME options — exactness is relative to the target
    configuration, whatever its numerics."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt, transformer as T

    cfg = _cfg(vocab_size=128, max_len=64)
    params = T.init_params(jax.random.PRNGKey(7), cfg)
    prompt = ((jnp.arange(2 * 4, dtype=jnp.int32).reshape(2, 4) * 7)
              % 100) + 1
    qparams = gpt.quantize_decode_params(params)
    ref = gpt.generate(qparams, cfg, prompt, 8)
    out = gpt.generate_speculative(qparams, cfg, prompt, 8, K=3,
                                   drafter="ngram")
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    refk = gpt.generate(params, cfg, prompt, 8, kv_int8=True)
    outk = gpt.generate_speculative(params, cfg, prompt, 8, K=3,
                                    drafter="ngram", kv_int8=True)
    np.testing.assert_array_equal(np.asarray(outk), np.asarray(refk))


def test_spec_rollback_forced_rejections():
    """KV-cache rollback: force a draft rejection at EVERY position
    j = 0..K across iterations and assert (a) committed tokens equal
    the non-speculative greedy sequence exactly, (b) committed cache
    slots match the sequential ``_decode_one`` reference (bit-identical
    up to XLA's block-vs-single matmul reduction order, < 1e-6 here),
    and (c) the next step's logits from the speculative caches argmax-
    match the reference bitwise.  Rejected slots are rolled back by
    POINTER only — the next block write must overwrite them before any
    mask exposes them."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt, transformer as T

    cfg = _cfg(vocab_size=64, max_len=64)
    params = T.init_params(jax.random.PRNGKey(5), cfg)
    B, P, K, N = 2, 5, 3, 8
    prompt = ((jnp.arange(B * P, dtype=jnp.int32).reshape(B, P) * 7)
              % 60) + 1
    total = P + N + K

    # reference: prefill + N-1 sequential greedy decode steps
    logits, rcaches = gpt._prefill_full(params, cfg, prompt, total)
    tok = jnp.argmax(logits, -1).astype(jnp.int32)
    ref_toks = [tok]
    for i in range(N - 1):
        logits, rcaches = gpt._decode_one(params, cfg, tok, P + i,
                                          rcaches)
        tok = jnp.argmax(logits, -1).astype(jnp.int32)
        ref_toks.append(tok)
    ref_arr = np.stack([np.asarray(t) for t in ref_toks], 1)

    # speculative path with ADVERSARIAL drafts: correct up to position
    # j, deliberately wrong from j on — j cycles 0..K so every
    # rejection depth (including accept-all, j=K) is exercised
    logits, caches = gpt._prefill_full(params, cfg, prompt, total)
    pending = jnp.argmax(logits, -1).astype(jnp.int32)
    emitted, j, spec_toks = 1, 0, [pending]
    forced_depths = set()
    while emitted < N:
        correct = [ref_arr[:, emitted + i] if emitted + i < N
                   else np.zeros(B, np.int32) for i in range(K)]
        drafts = np.stack(correct, 1).astype(np.int32)
        jj = j % (K + 1)
        forced_depths.add(jj)
        if jj < K:
            drafts[:, jj:] = (drafts[:, jj:] + 1) % cfg.vocab_size
        drafts = jnp.asarray(drafts)
        n = P + emitted - 1
        block = jnp.concatenate([pending[:, None], drafts], 1)
        lb, caches = gpt._decode_block(params, cfg, block, n, caches)
        tgt = jnp.argmax(lb, -1).astype(jnp.int32)
        ok = drafts == tgt[:, :K]
        a = int(jnp.min(jnp.sum(
            jnp.cumprod(ok.astype(jnp.int32), 1), 1)))
        # the forced rejection must bite exactly where we planted it
        # (unless the reference sequence ran out first)
        assert a == min(jj, N - emitted), (a, jj, emitted)
        cont = tgt[:, a]
        for i in range(a):
            spec_toks.append(drafts[:, i])
        spec_toks.append(cont)
        pending, emitted, j = cont, emitted + a + 1, j + 1
    assert forced_depths == set(range(K + 1)), forced_depths

    spec_arr = np.stack([np.asarray(t) for t in spec_toks], 1)[:, :N]
    np.testing.assert_array_equal(spec_arr, ref_arr)

    # committed cache slots [0, P+N-1) must match the sequential
    # reference; stale rejected slots beyond them are irrelevant
    for rc, sc in zip(rcaches, caches):
        r = np.asarray(rc["kv"][:, :P + N - 1])
        s = np.asarray(sc["kv"][:, :P + N - 1])
        assert np.abs(r - s).max() < 1e-6
    l_ref, _ = gpt._decode_one(params, cfg,
                               jnp.asarray(ref_arr[:, -1]),
                               P + N - 1, rcaches)
    l_spec, _ = gpt._decode_one(params, cfg,
                                jnp.asarray(spec_arr[:, -1]),
                                P + N - 1, caches)
    np.testing.assert_allclose(np.asarray(l_spec), np.asarray(l_ref),
                               rtol=0, atol=1e-5)
    np.testing.assert_array_equal(
        np.asarray(jnp.argmax(l_spec, -1)),
        np.asarray(jnp.argmax(l_ref, -1)))


def test_spec_decode_sampled_distribution():
    """temperature>0: the rejection-sampling accept rule's MARGINALS
    must equal target sampling.  Exact enumeration gives the true
    marginal of the 2nd generated token; empirical distributions from
    plain generate (control) and both speculative drafters must all sit
    within the same sampling-noise band of it."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt, transformer as T

    cfg = _cfg(vocab_size=16, max_len=32)
    params = T.init_params(jax.random.PRNGKey(9), cfg)
    B, P, N = 4, 4, 2
    prompt = ((jnp.arange(B * P, dtype=jnp.int32).reshape(B, P) * 5)
              % 16)

    # exact marginal of token at position P+1 per row:
    #   p2(t) = sum_t1 p1(t1) * p(t | prompt + t1)
    logits1 = gpt.forward(params, prompt, cfg)[:, -1]
    p1 = np.asarray(jax.nn.softmax(logits1, -1), np.float64)
    p2 = np.zeros((B, cfg.vocab_size))
    for t1 in range(cfg.vocab_size):
        ext = jnp.concatenate(
            [prompt, jnp.full((B, 1), t1, jnp.int32)], 1)
        l2 = gpt.forward(params, ext, cfg)[:, -1]
        p2 += p1[:, t1:t1 + 1] * np.asarray(jax.nn.softmax(l2, -1),
                                            np.float64)

    dparams, dcfg = gpt.draft_slice_params(params, cfg, n_layers=1)
    M = 250

    def empirical(fn):
        cnt = np.zeros((B, cfg.vocab_size))
        for i in range(M):
            out = np.asarray(fn(jax.random.PRNGKey(10_000 + i)))
            for b in range(B):
                cnt[b, out[b, P + 1]] += 1
        return cnt / M

    runs = {
        "generate": lambda r: gpt.generate(
            params, cfg, prompt, N, temperature=1.0, rng=r),
        "spec-ngram": lambda r: gpt.generate_speculative(
            params, cfg, prompt, N, K=2, temperature=1.0,
            drafter="ngram", rng=r),
        "spec-self": lambda r: gpt.generate_speculative(
            params, cfg, prompt, N, K=2, temperature=1.0,
            drafter="self", draft_params=dparams, draft_cfg=dcfg,
            rng=r),
    }
    # TV noise floor for M samples over V cats ~ sqrt(V/(2*pi*M))/...;
    # empirically ~0.06 at M=250, V=16 — gate at 2.5x that
    for name, fn in runs.items():
        emp = empirical(fn)
        tv = 0.5 * np.abs(emp - p2).sum(-1).max()
        assert tv < 0.15, "%s marginal TV %.3f" % (name, tv)


def test_spec_decode_bf16_agreement():
    """Under bf16 compute, exactness is modulo 1-ulp argmax ties: the
    block-verify and single-step forwards may reduce in different
    orders, and the random-init checkpoint's near-flat logits make
    such ties common — the worst case.  Gates: (a) f32 at the same
    shapes stays token-exact (any bf16 divergence is ulp-ties, not
    indexing); (b) if the bf16 output diverges from plain ``generate``,
    the FIRST divergent position per row must sit on a near-tie of the
    sequential model's logits (top-2 gap within a few bf16 ulps) —
    after that the histories legitimately differ."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt, transformer as T

    B, P, N = 2, 8, 24
    prompt = jnp.asarray(
        np.tile([[5, 9, 5, 9, 5, 9, 5, 9]], (B, 1)), jnp.int32)

    cfg = _cfg(vocab_size=512, max_len=128, dtype="float32")
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    ref = np.asarray(gpt.generate(params, cfg, prompt, N))
    out = np.asarray(gpt.generate_speculative(
        params, cfg, prompt, N, K=4, drafter="ngram"))
    np.testing.assert_array_equal(out, ref)

    cfg = _cfg(vocab_size=512, max_len=128, dtype="bfloat16")
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    ref = np.asarray(gpt.generate(params, cfg, prompt, N))
    out = np.asarray(gpt.generate_speculative(
        params, cfg, prompt, N, K=4, drafter="ngram"))
    for b in range(B):
        div = np.nonzero(ref[b] != out[b])[0]
        if div.size == 0:
            continue
        i = int(div[0]) - P          # first divergent generated index
        assert i >= 0, "diverged inside the prompt"
        # sequential logits at the divergence: teacher-force ref up to
        # it and read the top-2 gap
        total = P + N + 4
        logits, caches = gpt._prefill_full(params, cfg, prompt[b:b + 1],
                                           total)
        for j in range(i):
            logits, caches = gpt._decode_one(
                params, cfg, jnp.asarray(ref[b:b + 1, P + j]), P + j,
                caches)
        top2 = np.sort(np.asarray(logits)[0])[-2:]
        gap, mag = top2[1] - top2[0], max(abs(top2[1]), 1.0)
        # bf16 ulp at |x| is ~2^-8 * |x|; allow a few ulps of slack
        assert gap <= 16.0 * mag * 2.0 ** -8, (
            "bf16 divergence at generated idx %d is not a near-tie: "
            "top-2 gap %.5f (mag %.2f)" % (i, gap, mag))


def test_spec_decode_validation():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt, transformer as T

    cfg = _cfg(max_len=16)
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    prompt = jnp.ones((1, 5), jnp.int32)
    with pytest.raises(ValueError):
        gpt.generate_speculative(params, cfg, prompt, 10, K=4)  # 5+10+4>16
    with pytest.raises(ValueError):
        gpt.generate_speculative(params, cfg, prompt, 4, K=0)
    with pytest.raises(ValueError):
        gpt.generate_speculative(params, cfg, prompt, 4, drafter="self")
    with pytest.raises(ValueError):
        gpt.generate_speculative(params, cfg, prompt, 4, drafter="huh")
    # max_new_tokens=0 short-circuits
    out = gpt.generate_speculative(params, cfg, prompt, 0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(prompt))


@pytest.mark.slow
def test_int8_kv_cache_decode_parity():
    """Round-4: the int8 KV-cache path (generate(kv_int8=True)) must
    track fp decode — per-token s8 quantization with scales folded into
    the attention dots."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.models import gpt, transformer as T

    cfg = _cfg(vocab_size=512, d_model=128, n_heads=4, n_layers=3,
               d_ff=256)
    params = T.init_params(jax.random.PRNGKey(11), cfg)
    prompt = ((jnp.arange(2 * 5, dtype=jnp.int32).reshape(2, 5) * 13)
              % cfg.vocab_size)
    of = np.asarray(gpt.generate(params, cfg, prompt, 12))
    okv = np.asarray(gpt.generate(params, cfg, prompt, 12,
                                  kv_int8=True))
    assert of.shape == okv.shape
    # greedy decode should agree on (nearly) every token at these
    # scales; a k/v scale-column swap or mis-fold collapses agreement
    assert (of == okv).mean() >= 0.9, (of, okv)
    # combined with weight-only int8 it still decodes sanely
    oq = np.asarray(gpt.generate(gpt.quantize_decode_params(params),
                                 cfg, prompt, 12, kv_int8=True))
    assert (of == oq).mean() >= 0.7, (of, oq)


@pytest.mark.slow
def test_spec_decode_probe_smoke():
    """CI smoke of the spec-decode bench harness (bounded: --quick tiny
    model, 16/64-token timings).  Runs all three probe sections through
    main() and checks the invariants the benchmark relies on: the
    calibration config (full target as its own drafter) commits > 1
    token/iter, every e2e row carries accept-rate accounting, and the
    micro section produced the c_S/c_1 ratios."""
    import json
    import os
    import sys
    import tempfile
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmark"))
    import spec_decode_probe

    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "probe.json")
        rc = spec_decode_probe.main(
            ["--quick", "--batches", "1", "--ks", "2,4",
             "--json", out])
        assert rc == 0
        rows = json.load(open(out))
    micro = [r for r in rows if r["section"] == "micro"]
    e2e = [r for r in rows if r["section"] == "e2e"]
    assert {r["S"] for r in micro} == {1, 3, 5}
    calib = [r for r in e2e if "calib" in r["config"]]
    assert calib and calib[0]["tokens_per_iter"] > 1.5, calib
    for r in e2e:
        assert 0.0 <= r["accept_rate"] <= 1.0
        assert r["tokens_per_iter"] >= 1.0 or r["K"] == 0
