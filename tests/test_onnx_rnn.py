"""Fused-RNN ONNX converters + wire-format golden/external validation.

Reference: the mx2onnx RNN/LSTM/GRU converter family (SURVEY.md §2.2
"ONNX" row).  The torch cross-checks validate our hand-rolled protobuf
reader AND the gate-order remapping against an independent ONNX
implementation (torch ships its own protobuf writer)."""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.contrib.onnx import export_model, import_model
from mxnet_tpu.contrib.onnx.mx2onnx import to_onnx_bytes
from mxnet_tpu.contrib.onnx.onnx_proto import decode_model, encode_model
from mxnet_tpu.ops.rnn_op import rnn_param_size

GOLDEN = os.path.join(os.path.dirname(__file__), "golden")
T, N, I, H = 5, 3, 4, 6


def _rnn_case(mode, L, bi, seed=0):
    rng = np.random.RandomState(seed)
    D = 2 if bi else 1
    psize = rnn_param_size(mode, L, I, H, bi)
    x = sym.Variable("data")
    p = sym.Variable("rnn_params")
    h0 = sym.Variable("state")
    args = [x, p, h0]
    if mode == "lstm":
        args.append(sym.Variable("state_cell"))
    out = sym.RNN(*args, state_size=H, num_layers=L, mode=mode,
                  bidirectional=bi, state_outputs=True, name="rnn0")
    y = out[0]
    params = {"rnn_params": nd.array(
        (rng.rand(psize).astype("float32") - 0.5) * 0.4)}
    data = rng.rand(T, N, I).astype("float32")
    state = np.zeros((L * D, N, H), dtype="float32")
    shapes = [(T, N, I), (L * D, N, H)] + \
        ([(L * D, N, H)] if mode == "lstm" else [])
    return y, params, data, state, shapes


def _forward_ref(y, params, data, state, mode):
    ex_args = {"data": nd.array(data), "state": nd.array(state),
               "rnn_params": params["rnn_params"]}
    if mode == "lstm":
        ex_args["state_cell"] = nd.array(state)
    ex = y.bind(ctx=mx.cpu(), args=ex_args)
    return ex.forward()[0].asnumpy()


def _forward_imported(s2, arg2, aux2, data, state):
    a2 = dict(arg2)
    for n in s2.list_arguments():
        if n in a2:
            continue
        a2[n] = nd.array(data) if n == "data" else nd.array(state)
    ex2 = s2.bind(ctx=mx.cpu(), args=a2, aux_states=aux2)
    return ex2.forward()[0].asnumpy()


@pytest.mark.parametrize("mode,L,bi", [
    ("lstm", 1, False), ("gru", 1, False), ("rnn_tanh", 1, False),
    ("rnn_relu", 1, False), ("lstm", 1, True), ("gru", 1, True),
    ("lstm", 2, False), ("lstm", 2, True)])
def test_rnn_onnx_byte_roundtrip(mode, L, bi):
    y, params, data, state, shapes = _rnn_case(mode, L, bi)
    model = export_model(y, params, shapes)
    s2, arg2, aux2 = import_model(decode_model(to_onnx_bytes(model)))
    ref = _forward_ref(y, params, data, state, mode)
    got = _forward_imported(s2, arg2, aux2, data, state)
    np.testing.assert_allclose(ref, got, rtol=2e-5, atol=2e-6)


def test_onnx_wire_bytes_handcomputed():
    """Anchor the encoder to the protobuf spec with a hand-computed
    message: field tags, varints, and length-delimited framing of a
    minimal ModelProto must match bytes derived by hand."""
    model = {"ir_version": 7, "opset": 13, "producer": "t",
             "producer_version": "1.0",
             "graph": {"name": "g", "nodes": [
                 {"op_type": "Relu", "name": "r", "inputs": ["x"],
                  "outputs": ["y"], "attrs": {}}],
                 "inputs": [{"name": "x", "dtype": "float32",
                             "shape": (2,)}],
                 "outputs": ["y"], "initializers": {}}}
    b = encode_model(model)
    # ModelProto field 1 (ir_version), varint 7 → tag 0x08, value 0x07
    assert b[0:2] == bytes([0x08, 0x07])
    # field 2 (producer_name) → tag 0x12, len 1, 't'
    assert b[2:5] == bytes([0x12, 0x01, ord("t")])
    # NodeProto for Relu: input 'x' (tag 0x0A), output 'y' (0x12),
    # name 'r' (0x1A), op_type 'Relu' (0x22)
    node = bytes([0x0A, 1, ord("x"), 0x12, 1, ord("y"),
                  0x1A, 1, ord("r"), 0x22, 4]) + b"Relu"
    assert node in b
    # graph (ModelProto field 7, wire 2) → tag 0x3A present
    assert bytes([0x3A]) in b
    # opset_import (field 8): domain "" (0x0A 0x00), version 13 (0x10 0x0D)
    assert bytes([0x42, 0x04, 0x0A, 0x00, 0x10, 0x0D]) in b
    # decode inverts encode exactly
    m2 = decode_model(b)
    assert m2["ir_version"] == 7 and m2["opset"] == 13
    assert m2["graph"]["nodes"][0]["op_type"] == "Relu"
    assert m2["graph"]["inputs"] == [
        {"name": "x", "dtype": "float32", "shape": (2,)}]


def test_onnx_golden_bytes_stable():
    """Exported bytes for fixed-seed models must equal the committed
    golden ``.onnx`` files — pins the wire format across rounds."""
    cases = {"onnx_lstm.onnx": ("lstm", 1, False),
             "onnx_gru_bi.onnx": ("gru", 1, True)}
    for fname, (mode, L, bi) in cases.items():
        y, params, data, state, shapes = _rnn_case(mode, L, bi)
        b = to_onnx_bytes(export_model(y, params, shapes))
        path = os.path.join(GOLDEN, fname)
        assert os.path.exists(path), \
            "golden %s missing — regenerate via tests/golden/README" % fname
        golden = open(path, "rb").read()
        assert b == golden, \
            "%s: exported bytes diverged from golden (%d vs %d bytes)" \
            % (fname, len(b), len(golden))
        # and the golden file itself imports + runs
        s2, arg2, aux2 = import_model(path)
        ref = _forward_ref(y, params, data, state, mode)
        got = _forward_imported(s2, arg2, aux2, data, state)
        np.testing.assert_allclose(ref, got, rtol=2e-5, atol=2e-6)


@pytest.mark.slow
@pytest.mark.parametrize("kind,default_state", [
    ("lstm", False), ("gru", False), ("lstm", True), ("gru", True)])
def test_rnn_onnx_torch_crosscheck(kind, default_state):
    """torch model → torch's own ONNX protobuf writer → our wire reader
    + importer → forward must match torch's forward.  External
    validation of both the byte codec and the gate-order mapping."""
    torch = pytest.importorskip("torch")
    from torch.onnx._internal.torchscript_exporter import onnx_proto_utils
    orig = onnx_proto_utils._add_onnxscript_fn
    # final re-serialization step needs the onnx package but only adds
    # onnxscript custom functions (none here) — pass bytes through
    onnx_proto_utils._add_onnxscript_fn = lambda b, co: b
    try:
        tm = (torch.nn.LSTM(I, H, 1) if kind == "lstm"
              else torch.nn.GRU(I, H, 1)).eval()
        xt = torch.randn(T, N, I)
        h0t = torch.randn(1, N, H) * 0.3
        state = (h0t, torch.randn(1, N, H) * 0.3) if kind == "lstm" \
            else h0t
        with torch.no_grad():
            y_ref = tm(xt, None if default_state else state)[0].numpy()
        with tempfile.TemporaryDirectory() as d:
            pth = os.path.join(d, "t.onnx")
            if default_state:
                # torch builds zero states via a Shape/Gather/Concat/
                # Expand chain — exercises the importer's constant
                # folding (round 3)
                in_names = ["data"]
                export_args = (xt,)
            else:
                in_names = ["data", "h0"] + (["c0"] if kind == "lstm"
                                             else [])
                export_args = (xt, state)
            torch.onnx.export(tm, export_args, pth, opset_version=13,
                              input_names=in_names, output_names=["out"],
                              dynamo=False)
            s2, arg2, aux2 = import_model(pth)
            a2 = dict(arg2)
            feeds = {"data": xt.numpy(), "h0": h0t.numpy()}
            if kind == "lstm":
                feeds["c0"] = state[1].numpy()
            for n in s2.list_arguments():
                if n not in a2:
                    a2[n] = nd.array(feeds[n])
            ex2 = s2.bind(ctx=mx.cpu(), args=a2, aux_states=aux2)
            got = ex2.forward()[0].asnumpy()
            if got.ndim == 4:
                got = got.transpose(0, 2, 1, 3).reshape(T, N, -1)
            np.testing.assert_allclose(y_ref, got, rtol=2e-4, atol=1e-5)
    finally:
        onnx_proto_utils._add_onnxscript_fn = orig


@pytest.mark.slow
def test_cnn_onnx_torch_crosscheck():
    """torch CNN → torch ONNX bytes → our reader/importer → numerics
    match torch (validates Conv/Gemm/Flatten/Softmax import against an
    external producer, not our own encodings)."""
    torch = pytest.importorskip("torch")
    from torch.onnx._internal.torchscript_exporter import onnx_proto_utils
    orig = onnx_proto_utils._add_onnxscript_fn
    onnx_proto_utils._add_onnxscript_fn = lambda b, co: b
    try:
        class M(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.c = torch.nn.Conv2d(3, 4, 3, padding=1)
                self.f = torch.nn.Linear(4 * 8 * 8, 3)

            def forward(self, x):
                return torch.softmax(
                    self.f(torch.relu(self.c(x)).flatten(1)), -1)
        m = M().eval()
        xt = torch.randn(2, 3, 8, 8)
        with torch.no_grad():
            y_ref = m(xt).numpy()
        with tempfile.TemporaryDirectory() as d:
            pth = os.path.join(d, "t.onnx")
            torch.onnx.export(m, (xt,), pth, opset_version=13,
                              input_names=["data"], output_names=["out"],
                              dynamo=False)
            s2, arg2, aux2 = import_model(pth)
            a2 = dict(arg2)
            a2["data"] = nd.array(xt.numpy())
            ex2 = s2.bind(ctx=mx.cpu(), args=a2, aux_states=aux2)
            got = ex2.forward()[0].asnumpy()
            np.testing.assert_allclose(y_ref, got, rtol=2e-4, atol=1e-5)
    finally:
        onnx_proto_utils._add_onnxscript_fn = orig
